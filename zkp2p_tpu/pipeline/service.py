"""The batched proving service: queue -> pad to batch -> prove -> verify
sample -> emit (the BASELINE.json north-star service shape).

Failure semantics mirror the reference UI's explicit state machine
(`SubmitOrderGenerateProofForm.tsx:45-56,171-220`), hardened for a
fleet (docs/ROBUSTNESS.md): each request ends in exactly one of
  done | error-bad-input | error-failed-to-prove |
  error-deadline-exceeded | error-shed
with the error recorded next to the request — no silent drops; plus the
verify-after-prove self-check the pipeline scripts do
(`5_gen_proof.sh:15-22` runs `snarkjs groth16 verify` right after prove).

Requests are JSON files in a spool directory (the S3/queue stand-in);
results and errors are written alongside.  Fault tolerance is layered
(docs/ROBUSTNESS.md has the full ladder):

  transient retries (bounded, exponential backoff)
    -> batch bisection (a poisoned request terminal-errors ALONE, its
       batchmates still ship `done`, <= log2(S) extra proves per mate)
      -> degradation ladder (precomp -> multi -> batch-affine ->
         sequential, reusing the existing knob gates)
        -> error-failed-to-prove

plus per-request deadlines (payload `deadline_s` or ZKP2P_DEADLINE_S,
checked at claim and again at batch assembly) and a spool backlog cap
(ZKP2P_SPOOL_CAP) that sheds load visibly instead of silently aging
requests.  Every layer is provable on demand via the fault-injection
sites (utils.faults, ZKP2P_FAULTS) and the chaos harness
(tools/chaos.py: N workers, SIGKILLs mid-prove, injected faults, one
global invariant).
"""

from __future__ import annotations

import contextlib
import errno
import json
import os
import queue
import re
import threading
import time
import traceback
from dataclasses import dataclass, field
from typing import Callable, Dict, List, Optional, Tuple

from ..formats.proof_json import dump
from ..utils.audit import execution_digest, preflight, sample_device_memory
from ..utils.faults import FaultInjected, fault_point
from ..utils.metrics import REGISTRY, JsonlSink, maybe_start_metrics_server, publish_native_stats, run_id, run_manifest
from ..utils.trace import drain as drain_trace, set_context, trace

# The terminal-state machine (docs/ROBUSTNESS.md): every request ends in
# EXACTLY ONE of these, recorded as a .proof.json/.error.json artifact
# plus a request record + requests_total{state} counter.
TERMINAL_STATES = (
    "done",
    "error-bad-input",
    "error-failed-to-prove",
    "error-deadline-exceeded",
    "error-shed",
)

# A torn .req.json younger than this is left alone for one more sweep —
# a non-atomic uploader may still be writing it — before the sweep
# judges it corrupt and terminals error-bad-input.
TORN_REQ_GRACE_S = 2.0

# Degradation ladder (last resort before error-failed-to-prove): each
# rung re-proves the isolated request with one more fast path gated off,
# reusing the existing knob gates — they are fresh-read per prove, so an
# env overlay flips them for exactly one attempt.  Proof BYTES are
# knob-invariant (the byte-parity oracles pin every arm), so a ladder
# rescue emits the same proof the fast path would have.  The overlay is
# process-global while it is applied; proves are serialized on the
# consumer thread, so no concurrent prove can observe a half-applied
# rung (the witness producer never proves).
_DEGRADATION_LADDER = (
    ("no-precomp", {"ZKP2P_MSM_PRECOMP": "0"}),
    ("no-multi", {"ZKP2P_MSM_PRECOMP": "0", "ZKP2P_MSM_MULTI": "0"}),
    ("no-batch-affine", {
        "ZKP2P_MSM_PRECOMP": "0", "ZKP2P_MSM_MULTI": "0",
        "ZKP2P_MSM_BATCH_AFFINE": "0",
    }),
    ("sequential", {
        "ZKP2P_MSM_PRECOMP": "0", "ZKP2P_MSM_MULTI": "0",
        "ZKP2P_MSM_BATCH_AFFINE": "0", "ZKP2P_MSM_OVERLAP": "0",
    }),
)

# Patterns that classify an exception as TRANSIENT (retry-worthy) when
# its type alone does not: allocator and pool exhaustion surface as
# RuntimeError text from the C/XLA layers.  Word-bounded: a bare
# substring scan classified any message merely CONTAINING "pool"
# ("spool", a path) or "resource" as transient, and a deterministic
# failure classified transient defer-livelocks in the witness path.
_TRANSIENT_RE = re.compile(
    r"\balloc\w*\b|\bpool\b|\bout of memory\b|\btemporarily unavailable\b|\bresource exhausted\b"
)

# OSError errnos that signal pressure that can clear (disk/fd/memory
# exhaustion, interruption) — retry-worthy.  Everything else in the
# class (ENOENT, EACCES, EISDIR, ...) is payload pathology: a request
# naming a missing file must terminal error-bad-input, not defer.
_TRANSIENT_ERRNOS = frozenset(
    getattr(errno, name)
    for name in (
        "ENOSPC", "EDQUOT", "EIO", "EAGAIN", "EWOULDBLOCK", "EINTR",
        "EMFILE", "ENFILE", "ENOMEM", "EBUSY", "ETIMEDOUT",
    )
    if hasattr(errno, name)
)


# Batch-fill histogram buckets: live requests per batch handed to the
# prover (upper bounds; +Inf implicit).  Fill vs batch_size is THE
# signal the ROADMAP-item-2 dynamic batch scheduler will size columns
# from, so it is recorded as a distribution, not a last-write gauge.
BATCH_FILL_BUCKETS: Tuple[float, ...] = (1, 2, 4, 8, 16, 32, 64)


@contextlib.contextmanager
def _lifespan(reqs, name: str, **attrs):
    """Per-request lifecycle span — the waterfall substrate: brackets
    `name` over every request in `reqs` (one Request or a batch list)
    with ONE shared wall-clock [t0, t0+ms] interval appended to each
    request's `spans` list, persisted on the request's next record and
    exported by `trace_report --chrome-trace` (one pid per worker, one
    tid per request).  Wall-clock (`time.time`), not perf_counter: the
    waterfall is cross-process, so spans must share the spool's arrival
    clock (req-file mtime).  Cost: one dict build + one append per
    request per span — microseconds against multi-second proves."""
    t0 = time.time()
    try:
        yield
    finally:
        rec = {"name": name, "t0": round(t0, 6), "ms": round((time.time() - t0) * 1e3, 3)}
        rec.update(attrs)
        for r in (reqs if isinstance(reqs, (list, tuple)) else (reqs,)):
            r.spans.append(dict(rec))


def scan_spool(spool: str, now: float, window_s: float, stale_claim_s: float) -> Dict:
    """One queue-state pass over a spool: arrivals inside the trailing
    `window_s` (counted BEFORE the terminal skip — a request that
    arrived and completed inside one window is still offered load),
    open backlog, the claimable/in-flight split by claim freshness.
    Shared by the worker TimeseriesSampler and the fleet supervisor's
    scrape loop (pipeline.fleet_obs) — one definition of "backlog", so
    the per-worker time-series and the fleet alert signals can never
    disagree about what the queue looks like.  An unreadable spool
    degrades to zeros (observation must never raise)."""
    arrivals = backlog = claimable = in_flight = 0
    try:
        names = set(os.listdir(spool))
    except OSError:
        return {"arrivals": 0, "backlog": 0, "claimable": 0, "in_flight": 0}
    for fn in names:
        if not fn.endswith(".req.json"):
            continue
        base = fn[: -len(".req.json")]
        try:
            if window_s > 0 and now - os.path.getmtime(os.path.join(spool, fn)) <= window_s:
                arrivals += 1
        except OSError:
            pass
        if base + ".proof.json" in names or base + ".error.json" in names:
            continue
        backlog += 1
        fresh = False
        if base + ".claim" in names:
            try:
                fresh = now - os.path.getmtime(os.path.join(spool, base + ".claim")) < stale_claim_s
            except OSError:
                pass
        if fresh:
            in_flight += 1
        else:
            claimable += 1
    return {
        "arrivals": arrivals, "backlog": backlog,
        "claimable": claimable, "in_flight": in_flight,
    }


def spool_terminal(spool: str) -> bool:
    """True when every request in `spool` has a terminal artifact —
    the exit condition chaos/fleet/loadgen workers share (an unreadable
    spool reads as not-terminal: keep sweeping, don't die)."""
    try:
        names = set(os.listdir(spool))
    except OSError:
        return False
    for fn in names:
        if not fn.endswith(".req.json"):
            continue
        base = fn[: -len(".req.json")]
        if base + ".proof.json" not in names and base + ".error.json" not in names:
            return False
    return True


def _is_transient(exc: BaseException) -> bool:
    """Transient = retry may genuinely succeed: injected faults (their
    whole point), allocation pressure, and the exhaustion slice of the
    OSError class.  Everything else — bad witnesses, payloads naming
    missing files, proof-count mismatches, failed sample verification —
    is permanent and goes straight to isolation: a permanent failure
    classified transient would defer-livelock, re-claimed and re-failed
    every sweep with no terminal state ever written."""
    if isinstance(exc, (FaultInjected, MemoryError)):
        return True
    if isinstance(exc, OSError) and exc.errno is not None:
        return exc.errno in _TRANSIENT_ERRNOS
    if isinstance(exc, (RuntimeError, OSError)):
        # C/XLA-layer exhaustion carries only text (and an errno-less
        # OSError only its message); other types never marker-match —
        # a ValueError mentioning "resource" is a bad payload, not load
        return _TRANSIENT_RE.search(str(exc).lower()) is not None
    return False


@dataclass
class Request:
    path: str
    payload: Dict
    witness: Optional[list] = None
    error: Optional[str] = None
    # observability: request_id (the spool base name — unique per
    # request, stable across worker takeovers) + claim timestamp, so the
    # terminal record carries true claim->terminal latency
    rid: str = ""
    t_claim: float = 0.0
    # deadline anchor: the request file's mtime (the spool's arrival
    # clock — survives worker crashes and takeovers, unlike any
    # in-process timestamp)
    t_submit: float = 0.0
    # terminal state assigned THIS sweep (None = still open), and the
    # deliberate non-terminal outcome: a deferred request released its
    # claim for a later sweep to retry (emit failure, transient witness
    # failure) — the safety net must not terminal it
    done: Optional[str] = None
    deferred: bool = False
    # which degradation rung rescued the prove (None = fast path)
    degraded_rung: Optional[str] = None
    # slot in the batch the request was CLAIMED into (records keep the
    # original batch attribution across bisection)
    batch_index: Optional[int] = None
    # the batch size the scheduler INTENDED when this request's batch
    # was assembled (off arm: the static batch_size cap; adaptive: the
    # controller's choice) — batch_n alone cannot distinguish "low
    # load" from "controller chose small", so records carry both
    batch_target: Optional[int] = None
    # priority lane (payload `priority` key, default from the
    # ZKP2P_SCHED_PRIORITY_DEFAULT knob): "interactive" | "bulk".  The
    # static arm ignores it; the adaptive arm batches interactive-first.
    priority: str = "bulk"
    # lifecycle spans THIS sweep (witness/prove attempts/rungs/verify/
    # emit, each {name, t0, ms, ...}) — persisted on every record the
    # sweep emits, terminal or deferred, so the full waterfall survives
    # defer→re-prove cycles as one sink line per attempt
    spans: List[Dict] = field(default_factory=list)


class TimeseriesSampler:
    """Periodic service time-series: one `{"type": "timeseries", ...}`
    line per interval (ZKP2P_TS_SAMPLE_S; 0 = off) appended to the
    service's JSONL sink, so post-hoc analysis can correlate a latency
    spike with the queue state that caused it (the signal SZKP-style
    scheduling presumes and nothing here recorded before).

    Line schema (docs/OBSERVABILITY.md §time-series):
      ts / run_id / pid      identity (joins the run manifest)
      window_s               actual seconds since the previous sample
      arrivals               req files whose mtime landed in the window
      arrival_rate_hz        arrivals / window_s
      backlog                open requests (no terminal artifact yet)
      claimable              backlog minus fresh-claimed peer work
      in_flight              open requests under a fresh claim
      batch_fill_last        live size of the newest batch handed to the prover
      counters               cumulative service counters (registry values)
      native_delta           nonzero native C stat deltas since the last sample
      slo                    rolling-window SLO snapshot (utils.slo)
      hbm_*                  device-memory point sample (absent on XLA:CPU)

    One listdir + one stat per spool entry per sample — bounded by the
    spool size the admission cap already bounds; measured ≪1 ms on
    hundred-request spools."""

    def __init__(self, interval_s: float, stale_claim_s: float = 300.0):
        self.interval_s = interval_s
        self.stale_claim_s = stale_claim_s
        self.batch_fill_last = 0
        # the scheduler's intended size for the newest batch (static
        # arm: the batch_size cap) — recorded NEXT to batch_fill_last
        # so the time-series can separate "low load" (target high,
        # fill low) from "controller chose small" (target == fill)
        self.batch_target_last = 0
        self._last_ts: Optional[float] = None
        self._last_native: Dict = {}
        # fleet attribution on every line (same contract as the request
        # records): resolved once — identity cannot change under a
        # running sampler
        try:
            from ..utils.config import load_config

            cfg = load_config()
            self._worker_id, self._fleet_id = cfg.worker_id, cfg.fleet_id
        except Exception:  # noqa: BLE001 — observation only
            self._worker_id = self._fleet_id = ""

    def _scan(self, spool: str, now: float, window_s: float) -> Dict:
        # delegates to the module-level scan_spool — the fleet plane's
        # supervisor scrape uses the same function, so "backlog" means
        # one thing whether a worker or the supervisor measured it
        return scan_spool(spool, now, window_s, self.stale_claim_s)

    def maybe_sample(self, spool: str, sink: JsonlSink, force: bool = False) -> Optional[Dict]:
        """Sample when the interval elapsed (or `force`); returns the
        record (also written to `sink`) or None when off/not due.
        Failures degrade to None — observation must never stop a sweep."""
        if self.interval_s <= 0 and not force:
            return None
        now = time.time()
        if not force and self._last_ts is not None and now - self._last_ts < self.interval_s:
            return None
        try:
            window_s = (now - self._last_ts) if self._last_ts is not None else self.interval_s
            self._last_ts = now
            scan = self._scan(spool, now, window_s)
            rec: Dict = {
                "type": "timeseries",
                "ts": round(now, 3),
                "run_id": run_id(),
                "pid": os.getpid(),
                "window_s": round(window_s, 3),
                "arrival_rate_hz": round(scan["arrivals"] / window_s, 4) if window_s > 0 else 0.0,
                "batch_fill_last": self.batch_fill_last,
                "batch_size_target": self.batch_target_last,
                **scan,
            }
            if self._worker_id:
                rec["worker"] = self._worker_id
            if self._fleet_id:
                rec["fleet"] = self._fleet_id
            # cumulative service counters out of the registry (post-hoc
            # analysis diffs consecutive lines for rates)
            counters: Dict[str, float] = {}
            for m in REGISTRY.snapshot():
                name = m["name"]
                if not name.startswith("zkp2p_service_") or m["kind"] != "counter":
                    continue
                key = name[len("zkp2p_service_"):]
                if key.endswith("_total"):
                    key = key[: -len("_total")]
                lab = m["labels"]
                if lab:
                    key += "_" + "_".join(str(v) for v in lab.values())
                counters[key] = counters.get(key, 0) + m["value"]
            rec["counters"] = counters
            # live backlog gauges for the scrape (same numbers as the line)
            REGISTRY.gauge("zkp2p_service_backlog").set(scan["backlog"])
            REGISTRY.gauge("zkp2p_service_in_flight").set(scan["in_flight"])
            # native C stat deltas since the last sample, nonzero only
            try:
                from ..native.lib import stats_snapshot

                snap = stats_snapshot()
            except Exception:  # noqa: BLE001 — numpy-less env, no .so
                snap = None
            if snap:
                delta = {
                    k: v - self._last_native.get(k, 0)
                    for k, v in snap.items()
                    if v != self._last_native.get(k, 0)
                }
                self._last_native = dict(snap)
                if delta:
                    rec["native_delta"] = delta
            try:
                from ..utils.slo import default_tracker

                rec["slo"] = default_tracker().snapshot()
            except Exception:  # noqa: BLE001 — observation only
                pass
            mem = sample_device_memory("service/timeseries")
            if mem is not None:
                rec["hbm_bytes_in_use"] = mem["bytes_in_use"]
                rec["hbm_peak_bytes"] = mem["peak_bytes_in_use"]
            sink.write(rec)
            return rec
        except Exception:  # noqa: BLE001 — the sweep must not die for a sample
            return None


class ProvingService:
    def __init__(
        self,
        cs,
        dpk,
        vk,
        witness_fn: Callable[[Dict], list],
        public_fn: Callable[[list], list],
        batch_size: int = 4,
        max_wait_s: float = 2.0,
        inputs_fn: Optional[Callable[[Dict], tuple]] = None,
        prover_fn: Optional[Callable] = None,
        prefetch: int = 1,
        stale_claim_s: float = 300.0,
        deadline_s: Optional[float] = None,
        spool_cap: Optional[int] = None,
        retries: Optional[int] = None,
        retry_backoff_s: Optional[float] = None,
        circuit: str = "",
    ):
        """witness_fn: request payload -> witness vector (raises on bad
        input); public_fn: witness -> public signals.

        inputs_fn (optional): payload -> (public_inputs, seed); when
        given, the producer runs the whole batch through the vectorized
        `witness_batch` tier (r1cs BlockHooks) and falls back to
        per-request scalar witnessing if the batch evaluation fails.
        prover_fn (optional): (dpk, [witness]) -> [Proof]; defaults to
        the vmapped device `prove_tpu_batch` — on chip-less hosts pass
        `prover.native_prove.prove_native_batch` (the multi-column fast
        path: whole claimed batches ride ONE base sweep per G1 MSM
        family; ZKP2P_MSM_MULTI=0 degrades it to sequential proves).
        prefetch: ready-batch queue depth (witness ∥ prove overlap
        window; 1 = classic double buffering).
        stale_claim_s: concurrent workers sweeping one spool partition
        requests via O_EXCL <name>.claim files; a claim older than this
        is treated as a crashed worker's and taken over.
        deadline_s: default per-request deadline (seconds since the
        request file's mtime; a payload `deadline_s` key overrides it
        per request; None = the ZKP2P_DEADLINE_S config default; 0 =
        no deadline).
        spool_cap: pending-backlog admission cap per sweep — requests
        beyond it are shed as error-shed (None = ZKP2P_SPOOL_CAP; 0 =
        unlimited).
        retries / retry_backoff_s: bounded transient-failure retries per
        batch prove and the exponential-backoff base (None = the
        ZKP2P_PROVE_RETRIES / ZKP2P_RETRY_BACKOFF_S defaults)."""
        self.cs = cs
        self.dpk = dpk
        self.vk = vk
        self.witness_fn = witness_fn
        self.public_fn = public_fn
        self.batch_size = batch_size
        self.max_wait_s = max_wait_s
        self.inputs_fn = inputs_fn
        self.prover_fn = prover_fn
        self.prefetch = max(1, prefetch)
        self.stale_claim_s = stale_claim_s
        self.deadline_s = deadline_s
        self.spool_cap = spool_cap
        self.retries = retries
        self.retry_backoff_s = retry_backoff_s
        # per-spool rotating JSONL sinks (lazy; see _sink).  Locked:
        # the witness producer thread and the proving thread both emit
        # records, and two racing JsonlSink instances for one path
        # would rotate against each other.
        self._sinks: Dict[str, JsonlSink] = {}
        self._sinks_lock = threading.Lock()
        # knob manifest + sink override for request records, resolved
        # once per process (env-derived; cannot change under a running
        # service — and _emit_record must not re-parse the config per
        # record).  None = not yet resolved.
        self._knobs: Optional[Dict] = None
        self._sink_override: Optional[str] = None
        self._resolved = False
        # time-series sampler (run() installs one when ZKP2P_TS_SAMPLE_S
        # > 0; process_dir works standalone without it)
        self._sampler: Optional["TimeseriesSampler"] = None
        # graceful drain (docs/ROBUSTNESS.md §fleet): once set, the
        # producer claims NO new requests — in-flight batches (already
        # claimed, possibly queued in ready_q) still prove, verify, and
        # emit to their terminal states under the sweep heartbeat, so a
        # SIGTERM'd worker finishes what it owns and strands nothing.
        # run() exits after the draining sweep completes.
        self._drain = threading.Event()
        # fleet identity (ZKP2P_WORKER_ID / ZKP2P_FLEET_ID, stamped by
        # the supervisor into the worker env) — resolved with the policy
        # knobs, stamped on every record + time-series line
        self._worker_id = ""
        self._fleet_id = ""
        # adaptive scheduler (pipeline.sched, ZKP2P_SCHED=adaptive):
        # controller built lazily on the first adaptive sweep (the gate
        # is fresh-read per sweep, so one process can A/B both arms),
        # and the per-sweep decision summary the fleet heartbeat carries
        # (the `sched` block in fleet /status and `zkp2p-tpu top`)
        self._sched_ctl = None
        self._sched_hb: Optional[Dict] = None
        # perf-regression sentry (utils.perfledger): the budget book
        # every terminal request's spans are checked against, loaded
        # lazily on the first terminal record (the gate and ledger are
        # env/disk-derived — stable under a running service), the
        # cumulative overrun/check counters the fleet heartbeat carries
        # (`perf` block in fleet /status and `zkp2p-tpu top`), and the
        # per-stage span samples the exit-time ledger stamp aggregates.
        # `circuit` labels this service's ledger entries and selects its
        # budget rows; "" = the generic "service" bucket.
        self.circuit = circuit or "service"
        self._perf_book = None
        self._perf_lock = threading.Lock()
        self._perf_hb: Optional[Dict] = None
        self._perf_agg: Dict[str, List[float]] = {}

    def request_drain(self) -> None:
        """Flip the drain flag: stop claiming, finish in-flight work,
        then exit run().  Idempotent; callable from signal handlers
        (Event.set is async-signal-safe enough for CPython)."""
        self._drain.set()

    @property
    def draining(self) -> bool:
        return self._drain.is_set()

    def _resolve_policy(self) -> None:
        """Fill constructor-None policy knobs from the typed config,
        once per process (env cannot change under a running service)."""
        if self._resolved:
            return
        from ..utils.config import load_config

        cfg = load_config()
        self._deadline_default = self.deadline_s if self.deadline_s is not None else cfg.deadline_s
        self._spool_cap = self.spool_cap if self.spool_cap is not None else cfg.spool_cap
        self._retries = self.retries if self.retries is not None else cfg.prove_retries
        self._retry_backoff_s = (
            self.retry_backoff_s if self.retry_backoff_s is not None else cfg.retry_backoff_s
        )
        self._worker_id = cfg.worker_id
        self._fleet_id = cfg.fleet_id
        self._fleet_dir = cfg.fleet_dir
        self._priority_default = (
            "interactive" if cfg.sched_priority_default == "interactive" else "bulk"
        )
        self._resolved = True

    # a heartbeat younger than this marks a LIVE fleet peer (the hb
    # thread beats every ~5 s; 3 beats of slack before a peer stops
    # counting toward the scheduler's parallelism)
    _PEER_HB_FRESH_S = 15.0

    def _live_peers(self) -> int:
        """Live workers sharing this spool (self included), from fresh
        heartbeat files in the fleet dir — the scheduler's parallelism:
        N workers pull ONE queue, so a worker predicting completion
        times as if it served the whole backlog alone would shed
        requests its peers could still serve.  Solo service (no fleet
        dir) = 1; an unreadable dir degrades to 1 (predictions turn
        conservative, never wrong-side)."""
        if not getattr(self, "_fleet_dir", ""):
            return 1
        n = 0
        now = time.time()
        try:
            for fn in os.listdir(self._fleet_dir):
                if not fn.endswith(".hb"):
                    continue
                try:
                    if now - os.path.getmtime(os.path.join(self._fleet_dir, fn)) < self._PEER_HB_FRESH_S:
                        n += 1
                except OSError:
                    pass
        except OSError:
            return 1
        return max(1, n)

    def _live_peer_tiers(self) -> List[str]:
        """Advertised tiers of live fleet peers (self EXCLUDED), from
        the `tier` field of fresh heartbeat JSON.  Feeds the scheduler's
        heterogeneous routing: a native worker seeing a live "sharded"
        peer defers its bulk lane to it (and vice versa for
        interactive).  Solo service or unreadable heartbeats = [] — the
        scheduler then serves both lanes itself, so a torn/legacy hb
        (no tier field) degrades to homogeneous routing, never to a
        starved lane."""
        if not getattr(self, "_fleet_dir", ""):
            return []
        my_wid = getattr(self, "_worker_id", "") or ""
        tiers: List[str] = []
        now = time.time()
        try:
            for fn in os.listdir(self._fleet_dir):
                if not fn.endswith(".hb") or fn == my_wid + ".hb":
                    continue
                path = os.path.join(self._fleet_dir, fn)
                try:
                    if now - os.path.getmtime(path) >= self._PEER_HB_FRESH_S:
                        continue
                    with open(path) as f:
                        hb = json.load(f)
                    tier = hb.get("tier")
                    if isinstance(tier, str) and tier:
                        tiers.append(tier)
                except (OSError, ValueError):
                    pass  # torn write / legacy hb: peer counts for parallelism, not routing
        except OSError:
            return []
        return tiers

    def _sched_controller(self):
        """The lazily-built BatchController (adaptive arm only).  The
        amortization model and objective are resolved once per process —
        calibration cannot change under a running service; the GATE
        stays fresh-read per sweep.  Resolution (sched.build_controller):
        explicit ZKP2P_SCHED_AMORT -> tuned host-profile points (the
        controller starts CALIBRATED — the points were measured on this
        hardware) -> built-in venmo curve with warm-up."""
        if self._sched_ctl is None:
            from ..utils.config import load_config
            from .sched import build_controller

            self._sched_ctl = build_controller(load_config())
        return self._sched_ctl

    # -------------------------------------------------------- observability
    #
    # Every request's terminal transition is RECORDED, not just counted:
    # one JSONL line per request (request_id, state, claim->terminal ms,
    # run_id/pid, the full knob manifest) in a rotating sink next to the
    # spool, aggregatable offline by tools/trace_report.py.  The env-level
    # ZKP2P_METRICS_SINK override redirects all spools to one path.

    def _sink(self, spool: str) -> JsonlSink:
        # keyed by the RESOLVED path, not the spool: a ZKP2P_METRICS_SINK
        # override funnels every spool into one file, which must mean one
        # JsonlSink instance (two would race each other's rotation)
        with self._sinks_lock:
            if self._sink_override is None:
                from ..utils.config import load_config

                self._sink_override = load_config().metrics_sink  # "" = per-spool
            path = self._sink_override or (spool.rstrip("/") + ".metrics.jsonl")
            s = self._sinks.get(path)
            if s is None:
                s = self._sinks[path] = JsonlSink(path)
            return s

    def _emit_record(
        self,
        spool: str,
        req: Request,
        state: str,
        knobs: Dict,
        batch_index: Optional[int] = None,
        batch_n: Optional[int] = None,
        **extra,
    ) -> None:
        try:
            fault_point("sink")
            rec = {
                "type": "request",
                "ts": round(time.time(), 3),
                "run_id": run_id(),
                "pid": os.getpid(),
                "request_id": req.rid,
                "state": state,
                "ms": round((time.time() - req.t_claim) * 1e3, 3) if req.t_claim else None,
                "knobs": knobs,
                # which code paths this process has exercised (the audit
                # gate→arm map hash): two requests are comparable only
                # when their digests match — see docs/OBSERVABILITY.md
                "execution_digest": execution_digest(),
            }
            # fleet attribution: which worker of which fleet produced
            # this record — pids recycle across restarts, worker ids
            # don't, so trace_report groups waterfall rows by worker
            if self._worker_id:
                rec["worker"] = self._worker_id
            if self._fleet_id:
                rec["fleet"] = self._fleet_id
            # batched-prove attribution: which slot of which batch this
            # request rode, so trace_report can split a batch's prove
            # latency across its requests (a batch=4 multi-column prove
            # is ONE service/prove span covering four terminal records)
            if batch_index is not None:
                rec["batch_index"] = batch_index
            if batch_n is not None:
                rec["batch_n"] = batch_n
            # the scheduler's INTENDED batch size when this request was
            # assembled (off arm: the static cap): batch_n alone reads
            # the same for "low load" and "controller chose small"
            if req.batch_target is not None:
                rec["batch_size_target"] = req.batch_target
            # request waterfall: absolute arrival/claim timestamps, the
            # queue-wait they bound, and this sweep's lifecycle spans.
            # queue_wait_s is anchored to the req-file mtime, so across
            # defer→re-prove cycles (and worker takeovers) it is the
            # CUMULATIVE wait since the request entered the spool, not
            # this attempt's slice.
            if req.t_submit:
                rec["t_submit"] = round(req.t_submit, 6)
            if req.t_claim:
                rec["t_claim"] = round(req.t_claim, 6)
                if req.t_submit:
                    rec["queue_wait_s"] = round(max(0.0, req.t_claim - req.t_submit), 6)
            if req.spans:
                rec["spans"] = req.spans
            if extra:
                rec.update(extra)
            if req.error:
                rec["error"] = req.error[:500]
            # flight recorder: HBM watermark at terminal time.  NOTE
            # peak_bytes_in_use is the PROCESS-lifetime high-water mark
            # (PJRT exposes no per-interval peak/reset), so the first
            # record whose peak jumps names the request class that
            # raised the ceiling; in_use is the live point sample.
            # Absent on stats-less backends (XLA:CPU).
            mem = sample_device_memory("service/request")
            if mem is not None:
                rec["hbm_peak_bytes"] = mem["peak_bytes_in_use"]
                rec["hbm_bytes_in_use"] = mem["bytes_in_use"]
            self._sink(spool).write(rec)
        except Exception:  # noqa: BLE001 — observation must never fail a prove
            pass
        if state in TERMINAL_STATES:
            REGISTRY.counter("zkp2p_service_requests_total", {"state": state}).inc()
            # SLO accounting: full-life latency (spool arrival ->
            # terminal) into the rolling-window tracker; only `done`
            # counts as good (docs/OBSERVABILITY.md §SLO).  The anchor
            # falls back to claim time for requests with no readable
            # arrival mtime (torn uploads).
            # observe() only here — O(1).  The zkp2p_slo_* gauges are
            # refreshed where they are READ (the /metrics scrape and the
            # time-series sampler both snapshot): a per-terminal
            # publish_slo() would sort the whole rolling window (tens of
            # thousands of samples at saturation) on every request.
            try:
                from ..utils.slo import default_tracker

                anchor = req.t_submit or req.t_claim
                if anchor:
                    default_tracker().observe(time.time() - anchor, ok=(state == "done"))
            except Exception:  # noqa: BLE001 — observation only
                pass
            # perf sentry: this request's spans vs the ledger-derived
            # stage budgets (utils.perfledger) — overruns are counted
            # per stage and surfaced through the fleet heartbeat; spans
            # also pool into the exit-time ledger stamp
            try:
                self._perf_check(req)
            except Exception:  # noqa: BLE001 — observation only
                pass
        else:
            # non-terminal sweep outcome (deferred): its own counter —
            # requests_total stays one-inc-per-TERMINAL-transition
            REGISTRY.counter("zkp2p_service_deferred_total").inc()

    def _perf_check(self, req: Request) -> None:
        """Check one terminal request's lifecycle spans against the
        ledger-derived stage budgets (utils.perfledger.BudgetBook —
        dict lookups only on this path; the book is loaded once).  An
        over-budget span incs zkp2p_stage_budget_overruns_total{stage};
        cumulative counts ride the fleet heartbeat as the `perf` block.
        With the gate off the book is empty and this is a no-op beyond
        the span pooling guard."""
        from ..utils.perfledger import BudgetBook

        book = self._perf_book
        if book is None:
            book = self._perf_book = BudgetBook.load(self.circuit)
            REGISTRY.gauge("zkp2p_perf_budget_stages").set(float(len(book)))
        if not req.spans:
            return
        overruns = checked = 0
        with self._perf_lock:
            for sp in req.spans:
                name, ms = sp.get("name"), sp.get("ms")
                if not name or ms is None:
                    continue
                # pool every span for the exit-time ledger stamp (a
                # fresh host builds its first budgets from live sweeps)
                self._perf_agg.setdefault(name, []).append(float(ms))
                verdict = book.over(name, ms)
                if verdict is None:
                    continue  # no budget for this stage: never counts
                checked += 1
                if verdict:
                    overruns += 1
                    REGISTRY.counter(
                        "zkp2p_stage_budget_overruns_total", {"stage": name}
                    ).inc()
                    # overrun-triggered flame capture (utils.flameprof):
                    # gated by ZKP2P_FLAME, one capture at a time,
                    # cooldown-limited — the sentry's "why" half.  The
                    # capture cross-links the budget's ledger head
                    # digest so `zkp2p-tpu perf` can walk DRIFT ->
                    # capture file.
                    try:
                        from ..utils.flameprof import controller as _flame

                        _flame().trigger(
                            self.circuit, name,
                            entry_digest=book.head_digest(name),
                            budget_ms=book.budget_ms(name),
                            over_ms=float(ms),
                        )
                    except Exception:  # noqa: BLE001 — observation only
                        pass
            if self._perf_hb is None:
                self._perf_hb = {"overruns": 0, "checked": 0, "budgets": len(book)}
            self._perf_hb["overruns"] += overruns
            self._perf_hb["checked"] += checked

    def _perf_stamp(self) -> None:
        """Exit-time ledger stamp: one entry aggregating this run's
        terminal-request span costs (source=service), gated inside
        perfledger.record by ZKP2P_PERF_LEDGER.  Sampling at run
        granularity — not per request — is what keeps the ledger's
        steady-state overhead under the documented <1%."""
        from ..utils.perfledger import record as perf_record, stage_stats

        with self._perf_lock:
            agg, self._perf_agg = self._perf_agg, {}
        stages = {
            name: stats
            for name, samples in agg.items()
            for stats in [stage_stats(samples)]
            if stats is not None
        }
        if stages:
            perf_record("service", self.circuit, stages, run_id=run_id())

    def _record_deferred(
        self,
        spool: str,
        req: Request,
        reason: object,
        knobs: Dict,
        batch_index: Optional[int] = None,
        batch_n: Optional[int] = None,
    ) -> None:
        """Record a NON-terminal sweep outcome: the claim was released
        for a later sweep to retry (transient witness/emit failure,
        error-artifact write failure).  One `state="deferred"` line per
        attempt — with that attempt's spans and the cumulative
        queue_wait_s — so the request's full history survives
        defer→re-prove cycles: the eventual terminal record alone would
        erase every earlier attempt from the timeline."""
        self._emit_record(
            spool, req, "deferred", knobs,
            batch_index=batch_index, batch_n=batch_n,
            deferred_reason=str(reason)[:200],
        )

    # ------------------------------------------------------------- claims
    #
    # Crash/restart and multi-worker semantics (the service-level mirror
    # of the reference's claim-with-expiry escrow pattern,
    # `Ramp.sol:144` + `clawback`): a worker that dies mid-prove leaves
    # a .claim file but no terminal output; any later sweep — same
    # worker restarted or a peer — takes the request over once the claim
    # is stale.  Terminal outputs (.proof/.error) always win over
    # claims, so a request is never reprocessed after completion.

    def _try_claim(self, base_path: str) -> bool:
        # Terminal outputs are re-checked at CLAIM time, not just at scan
        # time: a peer may have completed this request (proof emitted,
        # claim released) between our scan and our dequeue — re-claiming
        # it would duplicate the prove and double-count `done`.  A
        # microscopic emit-between-check-and-claim window remains
        # (at-least-once, never wrong: terminal writes are atomic and any
        # duplicate proof still verifies).
        if os.path.exists(base_path + ".proof.json") or os.path.exists(base_path + ".error.json"):
            return False
        claim = base_path + ".claim"
        try:
            fault_point("claim")
            fd = os.open(claim, os.O_CREAT | os.O_EXCL | os.O_WRONLY)
        except FileExistsError:
            try:
                age = time.time() - os.path.getmtime(claim)
            except OSError:
                return False  # vanished: owner just completed it
            if age < self.stale_claim_s:
                return False
            # Stale claim: STEAL it by renaming it aside — rename is
            # atomic and the kernel picks exactly ONE winner (every
            # other taker's rename of the same source gets ENOENT and
            # backs off; a replace-in-place scheme would let two takers
            # each read back their own replace and both "win").  The
            # winner then re-creates the claim O_EXCL with ITS pid/ts —
            # the old refresh-mtime takeover left the dead worker's
            # identity in the file, so `cat *.claim` lied about who
            # owns in-flight work.
            stale_aside = f"{claim}.stale.{os.getpid()}"
            try:
                # last-moment re-check: if the claim was refreshed or
                # rewritten since our stat (owner alive after all, or a
                # faster taker already won), it is not ours to steal
                if time.time() - os.path.getmtime(claim) < self.stale_claim_s:
                    return False
                os.rename(claim, stale_aside)
            except OSError:
                # the kernel picked another taker (or the owner just
                # completed): a steal ATTEMPTED and lost — counted, so
                # production can watch takeover contention (PR 7 built
                # the mechanism; this is the meter on it)
                REGISTRY.counter("zkp2p_service_takeovers_total", {"result": "lost"}).inc()
                return False
            REGISTRY.counter("zkp2p_service_takeovers_total", {"result": "won"}).inc()
            try:
                os.unlink(stale_aside)
            except OSError:
                pass
            try:
                fd = os.open(claim, os.O_CREAT | os.O_EXCL | os.O_WRONLY)
            except OSError:
                # an opportunistic claimer slipped into the freed slot
                # first — still exactly one owner, just not us
                return False
            try:
                with os.fdopen(fd, "w") as f:
                    f.write(json.dumps({"pid": os.getpid(), "ts": time.time(), "takeover": True}))
            except OSError:
                pass  # ownership = existence + mtime; identity is debug info
            # The old owner may have COMPLETED inside the stale-check →
            # steal window (it never re-checks its stolen claim;
            # terminal write, then its release unlinks OUR claim).
            # Terminal outputs always win: back off instead of
            # re-proving finished work and emitting a duplicate
            # terminal record.  (The pre-rewrite utime-based takeover
            # failed closed here with ENOENT; this re-check keeps that
            # behavior.)
            if os.path.exists(base_path + ".proof.json") or os.path.exists(base_path + ".error.json"):
                self._release_claim(base_path)
                return False
            return True
        except (OSError, FaultInjected):
            # claim-write failure (full disk, injected fault): the
            # request is simply not ours this sweep — a later sweep
            # retries; a claim failure must never kill the whole scan
            return False
        try:
            with os.fdopen(fd, "w") as f:
                f.write(json.dumps({"pid": os.getpid(), "ts": time.time()}))
        except OSError:
            # ownership = the file's existence + mtime; the identity
            # payload is best-effort debugging info
            pass
        # same completed-while-we-raced re-check as the steal path: a
        # peer may have emitted + released between our top-of-function
        # artifact check and the O_EXCL create landing on the freed slot
        if os.path.exists(base_path + ".proof.json") or os.path.exists(base_path + ".error.json"):
            self._release_claim(base_path)
            return False
        return True

    @staticmethod
    def _release_claim(base_path: str) -> None:
        try:
            os.unlink(base_path + ".claim")
        except OSError:
            pass

    # ---------------------------------------------------------- deadlines

    def _deadline_of(self, req: Request) -> Optional[float]:
        """Absolute wall-clock deadline for a request, or None.  The
        payload's `deadline_s` wins over the service default; both are
        seconds since the request file's mtime (the spool arrival clock,
        stable across worker crashes).  A malformed payload deadline
        degrades to the service default rather than killing the request
        (the witness builder will judge the payload)."""
        d = None
        if isinstance(req.payload, dict):
            d = req.payload.get("deadline_s")
        try:
            d = float(d) if d is not None else None
        except (TypeError, ValueError):
            d = None
        if d is None:
            d = self._deadline_default
        if not d or d <= 0 or not req.t_submit:
            return None
        return req.t_submit + d

    # ------------------------------------------------------ terminal emit

    def _terminal_error(
        self,
        spool: str,
        req: Request,
        state: str,
        exc: BaseException,
        knobs: Dict,
        stats: Dict[str, int],
        batch_index: Optional[int] = None,
        batch_n: Optional[int] = None,
    ) -> bool:
        """Terminal a request into an error state: atomic .error.json
        artifact, claim release, request record, counter.  Returns False
        when the artifact itself cannot be written (disk full): the
        request is left NON-terminal (claim released) for a later sweep
        rather than half-terminal."""
        req.error = f"{state}: {exc}"
        try:
            self._emit_error(req, state, exc)
        except Exception:  # noqa: BLE001 — the error artifact failed to write
            self._release_claim(req.path)
            req.deferred = True
            # best-effort deferred record (the sink may sit on the same
            # full disk — _emit_record swallows its own failures)
            self._record_deferred(
                spool, req, f"error-artifact write failed for {state}", knobs,
                batch_index=batch_index, batch_n=batch_n,
            )
            return False
        self._emit_record(spool, req, state, knobs, batch_index=batch_index, batch_n=batch_n)
        req.done = state
        stats[state] += 1
        return True

    # ------------------------------------------------- resilient proving
    #
    # The retry -> bisect -> degrade ladder (docs/ROBUSTNESS.md).  All
    # of it runs on the consumer thread under the batch's heartbeat, so
    # claim age stays bounded however long the rescue takes.

    def _prove_verified(
        self, batch: List[Request], attempt: int = 0, rung: Optional[str] = None,
    ) -> list:
        """One prover call over `batch` + the sample verify.  Raises on
        ANY failure — including a prover that returns the wrong number
        of proofs, which a bare zip() would silently truncate.
        `attempt`/`rung` label this call's lifecycle span so retries,
        bisection halves, and degradation rungs all show as child spans
        on the request waterfall (failed attempts included — the span
        closes on the way out of the exception)."""
        from ..prover.groth16_tpu import prove_tpu_batch
        from ..snark.groth16 import verify

        span_attrs: Dict = {"n": len(batch)}
        if attempt:
            span_attrs["attempt"] = attempt
        if rung:
            span_attrs["rung"] = rung
        with _lifespan(batch, "prove", **span_attrs):
            fault_point("prove")
            with trace("service/prove", n=len(batch), request_ids=[r.rid for r in batch]):
                prove = self.prover_fn or prove_tpu_batch
                proofs = prove(self.dpk, [r.witness for r in batch])
        proofs = list(proofs) if proofs is not None else []
        if len(proofs) != len(batch):
            raise RuntimeError(
                f"prover returned {len(proofs)} proofs for a batch of {len(batch)}"
            )
        with _lifespan(batch, "verify"):
            fault_point("verify")
            with trace("service/verify"):
                sample_pub = self.public_fn(batch[0].witness)
                if not verify(self.vk, proofs[0], sample_pub):
                    raise RuntimeError("sample proof failed verification")
        return proofs

    def _prove_with_retries(self, batch: List[Request]) -> list:
        """Bounded transient-failure retries with exponential backoff.
        Permanent failures (bad witness, count mismatch, verify fail)
        raise immediately — retrying them would only burn deadline."""
        attempt = 0
        while True:
            try:
                return self._prove_verified(batch, attempt=attempt)
            except Exception as e:  # noqa: BLE001 — classified below
                if attempt >= self._retries or not _is_transient(e):
                    raise
                attempt += 1
                REGISTRY.counter("zkp2p_service_retries_total").inc()
                delay = min(self._retry_backoff_s * (2 ** (attempt - 1)), 30.0)
                if delay > 0:
                    # backoff is part of the request's latency story:
                    # span it so the waterfall shows waiting, not a gap
                    with _lifespan(batch, "retry_backoff", attempt=attempt):
                        time.sleep(delay)

    def _degraded_prove(self, batch: List[Request], cause: BaseException):
        """Last resort before error-failed-to-prove: walk the
        degradation ladder, one attempt per rung, each with one more
        fast path gated off via the (fresh-read) knob env.  Returns
        (proofs, rung) on the first success; re-raises the final rung's
        failure.  Only provers that actually READ the knobs get the
        ladder (prover fns marked `reads_msm_knobs` — native_prove sets
        it): for any other prover every rung would re-run the IDENTICAL
        prove, wasting full proves and misattributing a flaky success
        to the rung."""
        prove = self.prover_fn
        if prove is None or not getattr(prove, "reads_msm_knobs", False):
            raise cause
        last: BaseException = cause
        for rung, overlay in _DEGRADATION_LADDER:
            saved = {k: os.environ.get(k) for k in overlay}
            os.environ.update(overlay)
            try:
                proofs = self._prove_verified(batch, rung=rung)
                REGISTRY.counter("zkp2p_service_degraded_total", {"rung": rung}).inc()
                return proofs, rung
            except Exception as e:  # noqa: BLE001 — try the next rung
                last = e
            finally:
                for k, v in saved.items():
                    if v is None:
                        os.environ.pop(k, None)
                    else:
                        os.environ[k] = v
        raise last

    def _prove_isolating(
        self,
        spool: str,
        batch: List[Request],
        knobs: Dict,
        stats: Dict[str, int],
        batch_n: int,
    ) -> None:
        """Prove `batch`, terminal-ing EVERY member exactly once: on
        failure the batch is bisected and the halves re-proven (a
        poisoned request costs each batchmate at most log2(S) extra
        proves), singles walk the degradation ladder before accepting
        error-failed-to-prove."""
        try:
            proofs = self._prove_with_retries(batch)
        except Exception as e:  # noqa: BLE001 — isolate below
            if len(batch) == 1:
                req = batch[0]
                try:
                    proofs, rung = self._degraded_prove(batch, e)
                    req.degraded_rung = rung
                except Exception as e2:  # noqa: BLE001 — truly failed
                    self._terminal_error(
                        spool, req, "error-failed-to-prove", e2, knobs, stats,
                        batch_index=req.batch_index, batch_n=batch_n,
                    )
                    return
            else:
                del e
                REGISTRY.counter("zkp2p_service_bisections_total").inc()
                mid = (len(batch) + 1) // 2
                self._prove_isolating(spool, batch[:mid], knobs, stats, batch_n)
                self._prove_isolating(spool, batch[mid:], knobs, stats, batch_n)
                return
        self._emit_done_batch(spool, batch, proofs, knobs, stats, batch_n)

    def _emit_done_batch(
        self,
        spool: str,
        batch: List[Request],
        proofs: list,
        knobs: Dict,
        stats: Dict[str, int],
        batch_n: int,
    ) -> None:
        from ..formats.proof_json import proof_to_json, public_to_json

        for req, proof in zip(batch, proofs):
            set_context(request_id=req.rid)
            try:
                try:
                    with _lifespan(req, "emit"):
                        fault_point("emit")
                        with trace("service/emit"):
                            # public first, proof last: the sweep treats
                            # .proof.json as the done marker, so a crash
                            # between the two atomic writes leaves a
                            # retryable request, never a proof without its
                            # public signals
                            dump(public_to_json(self.public_fn(req.witness)), req.path + ".public.json")
                            dump(proof_to_json(proof), req.path + ".proof.json")
                except Exception as e:  # noqa: BLE001 — emit failure is per-request
                    REGISTRY.counter("zkp2p_service_emit_failures_total").inc()
                    if _is_transient(e):
                        # disk full / injected ENOSPC: the proof is
                        # valid but unrecorded — and writing .error.json
                        # would fail on the same full disk — so the
                        # request stays NON-terminal: claim released, a
                        # later sweep re-proves it (at-least-once).  Its
                        # batchmates continue below.  The attempt still
                        # leaves a deferred record, so the waterfall
                        # keeps the prove this sweep paid for.
                        req.deferred = True
                        self._release_claim(req.path)
                        self._record_deferred(
                            spool, req, f"transient emit failure: {e}", knobs,
                            batch_index=req.batch_index, batch_n=batch_n,
                        )
                    else:
                        # deterministic emit-time failure (public_fn
                        # compute error): deferring would livelock the
                        # spool re-proving it forever — terminal it,
                        # exactly one record
                        self._terminal_error(
                            spool, req, "error-failed-to-prove", e, knobs, stats,
                            batch_index=req.batch_index, batch_n=batch_n,
                        )
                    continue
            finally:
                set_context(request_id=None)
            self._release_claim(req.path)
            extra = {"degraded_rung": req.degraded_rung} if req.degraded_rung else {}
            self._emit_record(
                spool, req, "done", knobs,
                batch_index=req.batch_index, batch_n=batch_n, **extra,
            )
            req.done = "done"
            stats["done"] += 1

    # --------------------------------------------------------- scheduler

    def _sched_sweep(self, spool: str, pending: List[Request], knobs: Dict, stats: Dict[str, int]) -> List[List[Request]]:
        """Adaptive-arm sweep planning (pipeline.sched): update the
        arrival EWMA, shed by expected deadline miss (+ admission cap by
        least slack), partition the survivors into lane-sorted batches.
        Applies the shed verdicts (claim -> error-shed terminal, counted
        per verdict) and publishes the decision telemetry: the
        zkp2p_sched_batch_size gauge, zkp2p_sched_decisions_total{kind}
        counters, one {"type": "sched"} line in the service sink, and
        the heartbeat `sched` block fleet /status renders."""
        from .sched import SchedRequest

        ctl = self._sched_controller()
        now = time.time()
        by_rid: Dict[str, Request] = {r.rid: r for r in pending}
        sreqs = [
            SchedRequest(
                rid=r.rid, t_submit=r.t_submit, deadline=self._deadline_of(r),
                interactive=(r.priority == "interactive"),
            )
            for r in pending
        ]
        peers = self._live_peers()
        peer_tiers = self._live_peer_tiers()
        plan = ctl.plan(
            now, sreqs, cap=max(1, self.batch_size),
            spool_cap=self._spool_cap or 0,
            # never shed while draining — same rule as the static arm
            allow_shed=not self._drain.is_set(),
            # fleet peers share this queue: predictions must not model
            # the whole backlog as served by this worker alone
            parallelism=peers,
            # heterogeneous routing: live peers' advertised tiers — a
            # native worker defers bulk to a live sharded peer (and a
            # sharded worker defers interactive to a native one).
            # Deferred requests stay UNCLAIMED in the spool for the
            # peer; they are never shed by this worker.
            peer_tiers=peer_tiers,
        )
        backlog = len(pending)
        for sr, reason in plan.shed:
            r = by_rid[sr.rid]
            if not self._try_claim(r.path):
                continue  # a peer is on it — not ours to shed
            r.t_claim = time.time()
            # counter only on a SUCCESSFUL terminal (a failed error-
            # artifact write defers the request — same rule as the
            # static cap shed)
            if self._terminal_error(
                spool, r, "error-shed",
                RuntimeError(f"sched: {reason} (backlog {backlog})"),
                knobs, stats,
            ):
                REGISTRY.counter("zkp2p_service_shed_total").inc()
                REGISTRY.counter("zkp2p_sched_decisions_total", {"kind": "shed"}).inc()
        REGISTRY.gauge("zkp2p_sched_batch_size").set(plan.batch_target)
        if plan.batches:
            REGISTRY.counter("zkp2p_sched_decisions_total", {"kind": "batch"}).inc(len(plan.batches))
        if plan.lanes.get("interactive"):
            REGISTRY.counter("zkp2p_sched_decisions_total", {"kind": "lane"}).inc()
        if plan.deferred:
            # lane handoff to a tier peer: the requests stay unclaimed
            # in the spool — count the DECISION (per sweep, per lane),
            # not the requests, so the counter reads "how often routing
            # engaged", aggregatable against the sched sink lines
            REGISTRY.counter("zkp2p_sched_decisions_total", {"kind": "defer"}).inc(len(plan.deferred))
        if plan.tier_fallback:
            # a sharded peer vanished while bulk work was pending: this
            # native worker resumes the bulk lane — the counted,
            # alertable "tier degraded to native" event
            REGISTRY.counter("zkp2p_sched_decisions_total", {"kind": "tier_fallback"}).inc()
        if self._sampler is not None:
            self._sampler.batch_target_last = plan.batch_target
        self._sched_hb = {
            "mode": "adaptive",
            "batch_target": plan.batch_target,
            "interactive_target": plan.interactive_target,
            "lane_interactive": plan.lanes.get("interactive", 0),
            "lane_bulk": plan.lanes.get("bulk", 0),
            "rate_hz": plan.rate_hz,
            "peers": peers,
            "tier": plan.tier,
        }
        if plan.deferred:
            self._sched_hb["deferred"] = dict(plan.deferred)
        if pending:
            # one decision line per sweep with queue activity: every
            # sizing/shed choice is auditable offline, next to the
            # request records it shaped
            try:
                rec: Dict = {
                    "type": "sched", "ts": round(now, 3),
                    "run_id": run_id(), "pid": os.getpid(),
                    "backlog": backlog,
                    "rate_hz": plan.rate_hz,
                    "oldest_wait_s": plan.oldest_wait_s,
                    "batch_target": plan.batch_target,
                    "batch_reason": plan.batch_reason,
                    "interactive_target": plan.interactive_target,
                    "lanes": plan.lanes,
                    "batches": len(plan.batches),
                    "shed": len(plan.shed),
                    "peers": peers,
                    "tier": plan.tier,
                }
                if peer_tiers:
                    rec["peer_tiers"] = peer_tiers
                if plan.deferred:
                    rec["deferred"] = dict(plan.deferred)
                if plan.tier_fallback:
                    rec["tier_fallback"] = True
                if self._worker_id:
                    rec["worker"] = self._worker_id
                if self._fleet_id:
                    rec["fleet"] = self._fleet_id
                self._sink(spool).write(rec)
            except Exception:  # noqa: BLE001 — observation must never stop a sweep
                pass
        return [[by_rid[sr.rid] for sr in b] for b in plan.batches]

    # ------------------------------------------------------------ one pass

    def process_dir(self, spool: str) -> Dict[str, int]:
        """One spool sweep; returns counters. Files: <name>.req.json in,
        <name>.proof.json / <name>.error.json out."""
        self._resolve_policy()
        stats = {s: 0 for s in TERMINAL_STATES}
        # draining before the sweep even starts: claim nothing, scan
        # nothing — the spool belongs to the peers now
        if self._drain.is_set():
            return stats
        # knob manifest stamped on every request record (the acceptance
        # contract: a record is attributable without joining against a
        # separate manifest line) — resolved once per process, not per
        # sweep: an idle 1 s poll loop must not re-read /proc/cpuinfo
        # and re-parse the config every tick
        if self._knobs is None:
            self._knobs = run_manifest()["knobs"]
        knobs = self._knobs
        # scheduler gate (pipeline.sched): fresh-read per sweep AND
        # record_arm'd, so adaptive-vs-off A/Bs are digest-
        # distinguishable and one process can flip arms between sweeps.
        # "off" keeps every decision below byte-for-byte the static
        # path (fixed batch_size slicing, newest-first cap shed).
        from .sched import sched_mode

        adaptive = sched_mode() == "adaptive"
        pending: List[Request] = []
        for fn in sorted(os.listdir(spool)):
            if ".claim.stale." in fn:
                # scavenge steal-aside litter: a taker SIGKILLed between
                # its rename and its unlink leaves this behind, and no
                # other path ever matches the name
                p = os.path.join(spool, fn)
                try:
                    if time.time() - os.path.getmtime(p) > self.stale_claim_s:
                        os.unlink(p)
                except OSError:
                    pass
                continue
            if not fn.endswith(".req.json"):
                continue
            base = fn[: -len(".req.json")]
            if os.path.exists(os.path.join(spool, base + ".proof.json")) or os.path.exists(
                os.path.join(spool, base + ".error.json")
            ):
                self._release_claim(os.path.join(spool, base))
                continue
            # a FRESH claim = a peer is on it right now: not claimable
            # this sweep, and counting it as backlog would let the
            # admission cap shed viable requests off an inflated number
            # (stale claims pass through — they are takeover candidates)
            try:
                if time.time() - os.path.getmtime(os.path.join(spool, base + ".claim")) < self.stale_claim_s:
                    continue
            except OSError:
                pass  # no claim: free for the taking
            fpath = os.path.join(spool, fn)
            try:
                with open(fpath) as f:
                    payload = json.load(f)
            except ValueError as e:
                # torn/malformed .req.json (half-written upload,
                # truncated copy): terminal it as error-bad-input and
                # KEEP SWEEPING — one corrupt file must not sink the
                # sweep and every batchmate behind it.  A YOUNG torn
                # file gets the benefit of the doubt first: a
                # non-atomic uploader (scp, cp) may still be writing
                # it, and a permanent terminal on a request that was
                # about to become valid is unrecoverable.
                try:
                    if time.time() - os.path.getmtime(fpath) < TORN_REQ_GRACE_S:
                        continue  # may still be mid-write: next sweep judges it
                except OSError:
                    continue  # vanished: nothing to judge
                req = Request(path=os.path.join(spool, base), payload={}, rid=base)
                if self._try_claim(req.path):
                    req.t_claim = time.time()
                    self._terminal_error(spool, req, "error-bad-input", e, knobs, stats)
                continue
            except OSError:
                continue  # vanished/unreadable this sweep: retry next sweep
            try:
                t_submit = os.path.getmtime(fpath)
            except OSError:
                t_submit = time.time()
            # priority lane: explicit payload value wins, anything
            # unrecognized falls to the configured default (bulk) — a
            # typo'd priority must not mint a third lane
            prio = payload.get("priority") if isinstance(payload, dict) else None
            if prio not in ("interactive", "bulk"):
                prio = self._priority_default
            pending.append(
                Request(
                    path=os.path.join(spool, base), payload=payload, rid=base,
                    t_submit=t_submit, priority=prio,
                )
            )

        # Admission control.  Adaptive arm: the controller plans the
        # whole sweep — expected-deadline-miss shedding (shed exactly
        # what the amortization model predicts cannot finish, never
        # what still can), lane-sorted batch partition, SLO-sized
        # batches (pipeline.sched; docs/SCHEDULING.md).  Static arm:
        # a backlog beyond the cap is SHED newest-first (the oldest
        # are closest to their deadlines and already aged in the
        # spool), each with a visible error-shed terminal + counter,
        # instead of silently aging until every deadline in the queue
        # is dead on arrival.
        # (never shed while draining: this worker is leaving — terminal-
        # erroring backlog a surviving peer could serve would turn a
        # routine restart into dropped requests)
        batch_plan: Optional[List[List[Request]]] = None
        if adaptive:
            batch_plan = self._sched_sweep(spool, pending, knobs, stats)
        elif self._spool_cap and len(pending) > self._spool_cap and not self._drain.is_set():
            backlog = len(pending)
            pending.sort(key=lambda r: (r.t_submit, r.rid))
            keep, shed = pending[: self._spool_cap], pending[self._spool_cap:]
            for r in shed:
                if not self._try_claim(r.path):
                    continue  # a peer is on it — not ours to shed
                r.t_claim = time.time()
                # counter only on a SUCCESSFUL terminal: a failed
                # error-artifact write defers the request, and the next
                # sweep would shed-count it again
                if self._terminal_error(
                    spool, r, "error-shed",
                    RuntimeError(f"spool backlog {backlog} over admission cap {self._spool_cap}"),
                    knobs, stats,
                ):
                    REGISTRY.counter("zkp2p_service_shed_total").inc()
            pending = sorted(keep, key=lambda r: r.rid)

        if not adaptive:
            # static-arm telemetry: the target IS the cap — recorded so
            # the time-series and fleet `sched` view stay comparable
            # across arms (fill < target reads as low load here)
            if self._sampler is not None:
                self._sampler.batch_target_last = self.batch_size
            self._sched_hb = {"mode": "off", "batch_target": self.batch_size}

        # Pipeline overlap (SURVEY.md §2.7 "witness ∥ prove"): witness
        # generation is host CPU, proving is device compute — a producer
        # thread builds upcoming batches while the device proves the
        # current one.  The queue holds at most `prefetch` ready batches
        # (so up to prefetch+1 batches of witnesses may be live; size the
        # knob with host memory in mind).  Mirrors the reference's
        # two-stage shell pipeline (2_gen_wtns.sh -> 5_gen_proof.sh),
        # overlapped instead of sequential.
        ready_q: "queue.Queue[Optional[List[Request]]]" = queue.Queue(maxsize=self.prefetch)
        producer_error: List[BaseException] = []

        # Sweep-level claim heartbeat: refreshes EVERY claim this sweep
        # holds — including batches sitting in ready_q behind a slow
        # rescue (retries + bisection + ladder can far exceed
        # stale_claim_s) — so claim age stays bounded by the refresh
        # interval, not by queue wait + rescue time.  A per-batch
        # heartbeat would leave queued batches' claims aging toward peer
        # takeover and duplicate terminal records.  Terminal'd/deferred
        # requests drop out via their done/deferred flags: their claims
        # are already released, and utime-ing a path a peer has since
        # re-claimed would delay that peer's legitimate takeover window.
        hb_reqs: List[Request] = []
        hb_lock = threading.Lock()
        stop_hb = threading.Event()

        def _sweep_heartbeat():
            while True:
                with hb_lock:
                    reqs = [r for r in hb_reqs if r.done is None and not r.deferred]
                for r in reqs:
                    try:
                        os.utime(r.path + ".claim", None)
                    except OSError:
                        pass
                if stop_hb.wait(max(self.stale_claim_s / 3.0, 0.05)):
                    return

        def scalar_witness(req: Request) -> bool:
            set_context(request_id=req.rid)
            try:
                with trace("service/witness"), _lifespan(req, "witness"):
                    fault_point("witness")
                    req.witness = self.witness_fn(req.payload)
                    self.cs.check_witness(req.witness)
                return True
            except Exception as e:  # noqa: BLE001 — recorded, not silenced
                if _is_transient(e):
                    # injected fault / allocation pressure: NOT the
                    # payload's fault — release the claim for a later
                    # sweep instead of terminal-ing a good request
                    REGISTRY.counter("zkp2p_service_retries_total").inc()
                    self._release_claim(req.path)
                    req.deferred = True
                    self._record_deferred(spool, req, f"transient witness failure: {e}", knobs)
                    return False
                self._terminal_error(spool, req, "error-bad-input", e, knobs, stats)
                return False
            finally:
                set_context(request_id=None)

        def batched_witness(cand: List[Request]) -> List[Request]:
            """Vectorized tier: per-request input derivation (errors stay
            per request), ONE witness_batch evaluation, sample Az∘Bz=Cz
            check (the prove step verifies a sample proof anyway); any
            batch-level failure falls back to the scalar path."""
            batch: List[Request] = []
            inputs = []
            for req in cand:
                try:
                    set_context(request_id=req.rid)
                    with trace("service/inputs"), _lifespan(req, "inputs"):
                        fault_point("witness")
                        inputs.append(self.inputs_fn(req.payload))
                    batch.append(req)
                except Exception as e:  # noqa: BLE001
                    if _is_transient(e):
                        REGISTRY.counter("zkp2p_service_retries_total").inc()
                        self._release_claim(req.path)
                        req.deferred = True
                        self._record_deferred(spool, req, f"transient inputs failure: {e}", knobs)
                    else:
                        self._terminal_error(spool, req, "error-bad-input", e, knobs, stats)
                finally:
                    set_context(request_id=None)
            if not batch:
                return []
            try:
                with trace("service/witness_batch", n=len(batch)), \
                        _lifespan(batch, "witness_batch", n=len(batch)):
                    ws = self.cs.witness_batch(inputs)
                # EVERY witness gets the Az∘Bz=Cz self-check, exactly like
                # the scalar tier — only checking a sample would let an
                # unsatisfying witness at index > 0 ship an invalid proof
                # as done (the consumer pairing-verifies one sample too).
                for req, w in zip(batch, ws):
                    self.cs.check_witness(w)
                    req.witness = w
                return batch
            except Exception:  # noqa: BLE001 — batch tier is an optimization
                return [r for r in batch if scalar_witness(r)]

        def produce():
            try:
                # adaptive: the controller's lane-sorted partition;
                # static: fixed batch_size slices of the scan order —
                # the exact pre-scheduler behavior
                if batch_plan is not None:
                    slices = batch_plan
                else:
                    slices = [
                        pending[i : i + self.batch_size]
                        for i in range(0, len(pending), self.batch_size)
                    ]
                for chunk in slices:
                    # Drain gate: once the flag is up, claim NOTHING
                    # more.  Checked per batch, before any claim — the
                    # batches already claimed (proving now, or queued in
                    # ready_q) finish to terminal under the heartbeat;
                    # everything unclaimed stays free for peers, so a
                    # fleet restart loses zero requests and duplicates
                    # zero proofs (docs/ROBUSTNESS.md §fleet).
                    if self._drain.is_set():
                        break
                    # the INTENDED size for this batch: the static cap,
                    # or the controller's planned chunk (records carry
                    # it as batch_size_target)
                    target = len(chunk) if batch_plan is not None else self.batch_size
                    # Claim at DEQUEUE, not at scan: a long sweep must
                    # not hold scan-time claims that go stale while
                    # earlier batches prove (peer takeover would then
                    # duplicate in-progress work).
                    cand = []
                    for r in chunk:
                        if not self._try_claim(r.path):
                            continue
                        r.t_claim = time.time()
                        r.batch_target = target
                        with hb_lock:
                            hb_reqs.append(r)  # heartbeat from claim to terminal
                        # deadline gate #1, at claim: a request that
                        # arrived already-expired (or aged out in the
                        # spool) terminals before any witness work
                        dl = self._deadline_of(r)
                        if dl is not None and r.t_claim > dl:
                            if self._terminal_error(
                                spool, r, "error-deadline-exceeded",
                                RuntimeError(
                                    f"deadline exceeded at claim "
                                    f"({r.t_claim - r.t_submit:.3f}s since submit)"
                                ),
                                knobs, stats,
                            ):
                                REGISTRY.counter("zkp2p_service_deadline_total").inc()
                            continue
                        cand.append(r)
                    if self.inputs_fn is not None:
                        batch = batched_witness(cand)
                    else:
                        batch = [r for r in cand if scalar_witness(r)]
                    if batch:
                        ready_q.put(batch)
            except BaseException as e:  # noqa: BLE001 — re-raised by the consumer
                producer_error.append(e)
            finally:
                # The sentinel MUST go out even if this thread dies (e.g.
                # _emit_error hitting a full disk) — otherwise the
                # consumer blocks on ready_q.get() forever.
                ready_q.put(None)

        hb = threading.Thread(target=_sweep_heartbeat, daemon=True)
        hb.start()
        producer = threading.Thread(target=produce, daemon=True)
        producer.start()
        try:
            self._consume(spool, ready_q, knobs, stats)
        finally:
            stop_hb.set()
            hb.join()
        producer.join()
        if producer_error:
            # Requests after the failure point got no witness, no proof
            # and no record this sweep — the claim-file discipline means
            # a later sweep (or another worker) picks them up.
            raise producer_error[0]
        # flame sweep boundary: an overrun-triggered capture spans the
        # next flame_capture_n FULL sweeps after its trigger; when this
        # tick completes one, the pointer rides the heartbeat perf
        # block so `zkp2p-tpu top` can name the capture file
        try:
            from ..utils.flameprof import controller as _flame

            if _flame().sweep_tick() is not None:
                ptr = _flame().pointer()
                with self._perf_lock:
                    if self._perf_hb is None:
                        self._perf_hb = {"overruns": 0, "checked": 0, "budgets": 0}
                    self._perf_hb["capture"] = ptr
        except Exception:  # noqa: BLE001 — observation must never fail a sweep
            pass
        return stats

    def _consume(self, spool, ready_q, knobs, stats) -> None:
        """Drain ready batches: deadline-gate, then prove with the full
        rescue ladder, terminal-ing every request exactly once.  Claims
        stay fresh via the caller's sweep-level heartbeat."""
        while True:
            batch = ready_q.get()
            if batch is None:
                break
            # deadline gate #2, at batch assembly: queue wait behind a
            # slow batch may have burned the remaining budget — check
            # again immediately before committing prove compute
            live: List[Request] = []
            for req in batch:
                dl = self._deadline_of(req)
                if dl is not None and time.time() > dl:
                    if self._terminal_error(
                        spool, req, "error-deadline-exceeded",
                        RuntimeError(
                            f"deadline exceeded at batch assembly "
                            f"({time.time() - req.t_submit:.3f}s since submit)"
                        ),
                        knobs, stats,
                    ):
                        REGISTRY.counter("zkp2p_service_deadline_total").inc()
                else:
                    live.append(req)
            if not live:
                continue
            for bi, req in enumerate(live):
                req.batch_index = bi
            # batch-fill distribution: live requests per prover call —
            # fill vs batch_size is the amortization signal the dynamic
            # batch scheduler (ROADMAP item 2) will size columns from
            REGISTRY.histogram(
                "zkp2p_service_batch_fill", buckets=BATCH_FILL_BUCKETS
            ).observe(len(live))
            if self._sampler is not None:
                self._sampler.batch_fill_last = len(live)
            t_batch0 = time.perf_counter()
            try:
                self._prove_isolating(spool, live, knobs, stats, batch_n=len(live))
                # online amortization calibration (adaptive arm): feed
                # the batch's ACTUAL wall cost back into the controller
                # — the static curve can be arbitrarily wrong for this
                # circuit/host, and until the first observation lands
                # the controller sheds only already-expired requests
                if self._sched_ctl is not None:
                    self._sched_ctl.observe_batch(len(live), time.perf_counter() - t_batch0)
            except Exception as e:  # noqa: BLE001 — safety net
                # _prove_isolating terminals every request itself; an
                # exception escaping it is a bug in the rescue path —
                # requests still open (and not deliberately deferred)
                # get the honest terminal instead of silently hanging
                for req in live:
                    if req.done is None and not req.deferred:
                        self._terminal_error(
                            spool, req, "error-failed-to-prove", e, knobs, stats,
                            batch_index=req.batch_index, batch_n=len(live),
                        )

    @classmethod
    def _emit_error(cls, req: Request, state: str, exc: BaseException) -> None:
        # atomic (temp+rename) like every other terminal artifact: a crash
        # or racing peer mid-write must never leave a torn .error.json that
        # the sweep's existence check treats as final
        # format_exception(exc), not format_exc(): shed/deadline
        # terminals pass a CONSTRUCTED exception that was never raised —
        # format_exc() there would stamp "NoneType: None" (or whatever
        # unrelated exception happens to be in flight) into the artifact
        trace_s = "".join(traceback.format_exception(type(exc), exc, exc.__traceback__, limit=3))
        dump(
            {"state": state, "error": str(exc), "trace": trace_s, "ts": time.time()},
            req.path + ".error.json",
        )
        cls._release_claim(req.path)

    # ------------------------------------------------------------- daemon

    @classmethod
    def for_venmo(cls, cs, lay, params, dpk, vk, keys=None, **kw) -> "ProvingService":
        """Service wired for the flagship circuit: request payloads are
        either {"eml_path": ...} (real DKIM email, keys resolved from the
        known-keys registry) or the synthetic-demo shape {"raw_id",
        "amount", "order_id", "claim_id"} (hermetic tests)."""
        from ..inputs.email import email_from_eml, generate_inputs, make_test_key, make_venmo_email

        demo_key = make_test_key(1)

        def inputs_fn(payload: Dict) -> tuple:
            order_id = int(payload.get("order_id", 1))
            claim_id = int(payload.get("claim_id", 0))
            if "eml_path" in payload:
                with open(payload["eml_path"], "rb") as f:
                    email = email_from_eml(f.read(), keys)  # unknown keys raise
                modulus = email.modulus
            else:
                email = make_venmo_email(
                    demo_key, raw_id=str(payload["raw_id"]), amount=str(payload["amount"])
                )
                modulus = demo_key.n
            inputs = generate_inputs(email, modulus, order_id, claim_id, params, lay)
            return inputs.public_signals, inputs.seed

        def witness_fn(payload: Dict) -> list:
            pubs, seed = inputs_fn(payload)
            return cs.witness(pubs, seed)

        def public_fn(witness: list) -> list:
            return list(witness[1 : cs.num_public + 1])

        kw.setdefault("inputs_fn", inputs_fn)
        return cls(cs, dpk, vk, witness_fn, public_fn, **kw)

    def run(
        self,
        spool: str,
        poll_s: float = 1.0,
        max_sweeps: Optional[int] = None,
        max_seconds: Optional[float] = None,
        exit_when_spool_terminal: bool = False,
    ) -> str:
        """Sweep `spool` until drained / exhausted; returns WHY the loop
        ended — "drained" (request_drain / SIGTERM: in-flight work
        finished, claims all released, sinks flushed), "terminal"
        (exit_when_spool_terminal and every request reached a terminal
        state — chaos/fleet workers), "sweeps" (max_sweeps), or
        "timeout" (max_seconds) — so callers can map a clean drain to a
        clean exit code."""
        # Prometheus exposition (ZKP2P_METRICS_PORT, default off) — the
        # scrape sees stage histograms, request-state counters, and a
        # scrape-time native counter refresh.
        maybe_start_metrics_server()
        # Preflight (execution audit): arm every gate, warn LOUDLY when
        # an expected arm failed to arm (pallas requested on a CPU
        # backend, bucket-h without signed digits...) — the round-5
        # silent-disarm class of failure must announce itself before the
        # first request is claimed, not after a burned tunnel window.
        try:
            import sys

            rep = preflight(
                probe=False, workload=False,
                log=lambda m: print(f"[service] {m}", file=sys.stderr, flush=True),
            )
            print(
                f"[service] preflight: backend={rep['backend']} "
                f"execution_digest={rep['execution_digest']}",
                flush=True,
            )
        except Exception:  # noqa: BLE001 — observation must never stop the service
            pass
        # service observability arms + time-series sampler: the SLO
        # objective and sampler interval are digest-visible gates (a
        # sampler-off A/B differs from sampler-on only on these), and
        # the sampler appends zkp2p_timeseries lines to the same sink
        # the request records ride.
        from ..utils.config import load_config
        from ..utils.flameprof import flame_arm
        from ..utils.perfledger import perf_arm
        from ..utils.slo import slo_arm, timeseries_arm

        slo_arm()
        timeseries_arm()
        # perf-ledger gate: the stage-budget sentry (utils.perfledger)
        # — armed here so a ledger-on service run never shares a digest
        # with the ledger-off oracle arm
        perf_arm()
        # flame-sampler gate: overrun-triggered captures ride the perf
        # sentry (utils.flameprof) — armed here so a sampler-on run
        # never shares a digest with the zero-overhead off arm
        flame_arm()
        # fleet membership gate: "worker" when the supervisor stamped an
        # identity into our env, else "off" — a fleet member and a solo
        # service are digest-distinguishable code paths (the ONE
        # resolver preflight also calls; a divergent inline copy could
        # split run()'s digest from doctor's)
        from .fleet import fleet_member_arm

        self._resolve_policy()
        fleet_member_arm()
        fleet_dir = load_config().fleet_dir or None
        self._sampler = TimeseriesSampler(load_config().ts_sample_s, self.stale_claim_s)

        def _flush():
            rid, pid = run_id(), os.getpid()
            spans = [
                {"type": "stage", "run_id": rid, "pid": pid, **r} for r in drain_trace()
            ]
            try:
                self._sink(spool).write_many(spans)
            except Exception:  # noqa: BLE001 — observation only
                pass
            publish_native_stats()

        # first heartbeat BEFORE the first sweep (the supervisor's
        # watchdog needs a liveness baseline while the worker is still
        # inside a long first sweep) plus a BACKGROUND heartbeat thread:
        # a single sweep can legitimately run minutes (cold precomp
        # build; flock losers block for the winner's whole build), and
        # a sweep-cadence heartbeat alone would read as a hang — the
        # watchdog would SIGKILL a healthy cold start mid-build forever
        hb_stop = None
        if fleet_dir:
            try:
                from .fleet import start_heartbeat_thread, worker_tick

                worker_tick(self, fleet_dir)
                hb_stop = start_heartbeat_thread(self, fleet_dir)
            except Exception:  # noqa: BLE001
                pass
        deadline = (time.time() + max_seconds) if max_seconds else None
        sweeps = 0
        why = "sweeps"
        while max_sweeps is None or sweeps < max_sweeps:
            if deadline is not None and time.time() > deadline:
                why = "timeout"
                break
            stats = self.process_dir(spool)
            if any(stats.values()):
                print(f"[service] {stats}", flush=True)
                # Per-sweep observability flush: buffered stage spans go
                # to the rotating sink (stamped with run_id/pid so
                # concurrent workers stay separable) and the native C
                # counter block is re-published for the next scrape.
                # The trace ring is DRAINED, which with the bounded
                # buffer closes the unbounded-growth leak the run() loop
                # had.
                _flush()
            # time-series tick rides the sweep cadence (interval-gated
            # inside; idle sweeps still sample, so a quiet queue is a
            # recorded fact, not a gap in the series)
            self._sampler.maybe_sample(spool, self._sink(spool))
            # fleet tick: heartbeat out (liveness for the supervisor's
            # watchdog + the bound metrics port for scrape discovery),
            # governor ctl in (soft RSS degrade)
            if fleet_dir:
                try:
                    from .fleet import worker_tick

                    worker_tick(self, fleet_dir)
                except Exception:  # noqa: BLE001 — fleet plumbing must not stop sweeps
                    pass
            if self._drain.is_set():
                why = "drained"
                break
            if exit_when_spool_terminal and spool_terminal(spool):
                why = "terminal"
                break
            sweeps += 1
            # interruptible sleep: a SIGTERM mid-poll exits promptly
            # instead of burning up to poll_s — by this point the sweep
            # above already finished every claim it held
            if self._drain.wait(poll_s):
                why = "drained"
                break
        # exit flush: whatever the reason, buffered spans and native
        # stats land in the sink before the process goes away (the
        # drain contract: in-flight work is not just proven but
        # RECORDED), and the fleet heartbeat says "draining" so the
        # supervisor sees a deliberate exit, not a hang
        _flush()
        # perf-ledger stamp: this run's aggregated span costs become
        # one `source=service` ledger entry (gate-checked inside) —
        # the live-sweep sample the next run's budgets are derived from
        try:
            self._perf_stamp()
        except Exception:  # noqa: BLE001 — observation only
            pass
        if hb_stop is not None:
            hb_stop.set()
        if fleet_dir:
            try:
                from .fleet import worker_tick

                worker_tick(self, fleet_dir, state=why)
            except Exception:  # noqa: BLE001
                pass
        print(f"[service] exiting ({why})", flush=True)
        return why
