"""The fleet observability plane: supervisor-hosted metrics federation.

PR 10 left observability per-process: each worker binds its own
ephemeral `/metrics` port, each holds a private SLO window, and
status.json knows about processes, not service health.  Nothing
answered the deployment's question — "is the FLEET meeting its SLO,
and which worker is why not?" — without a human joining N ephemeral
scrapes by hand.  This module is the missing aggregation layer
(ZKProphet's thesis applied at fleet scope: attribution first), and
the measurement substrate ROADMAP items 2 (adaptive scheduler) and 3
(multi-host federation) consume: fleet arrival rate, backlog, burn
rate, per-worker skew, all on ONE stable endpoint.

Topology:

  worker (N of them)                    supervisor (this module)
    /snapshot  ── registry snapshot ──►  scrape loop (background
    heartbeat  ── SLO window (fallback)  thread, ZKP2P_FLEET_SCRAPE_S)
                                           │ merge (rules below)
                                           ▼
                              fleet registry + merged SLO + alerts
                                           │
    ZKP2P_FLEET_METRICS_PORT serves  /metrics  /status  /healthz

Aggregation rules (the whole point — a family must merge the way its
semantics demand, not one-size-fits-all):

  counters    SUMMED across workers (labels preserved): fleet
              requests_total is the sum of worker requests_total.
              NOTE: the sum covers each worker's CURRENT incarnation —
              a restarted worker's counters restart at zero, exactly
              like a restarted Prometheus target.
  gauges      LABELLED per worker (`worker="w0"`), never summed or
              maxed: N workers sweeping one spool each report the same
              backlog, and their last-batch-fill gauges are skew
              signals only attribution preserves.
  histograms  BUCKET-MERGED via the fixed-layout merge_state path;
              a bucket-layout mismatch is REFUSED (that family is
              skipped and counted in zkp2p_fleet_merge_refusals_total)
              rather than silently mis-binned.

The merged fleet registry is rebuilt FROM SCRATCH every scrape cycle —
folding cumulative worker counters into a persistent registry would
double-count every cycle.  Scrape failures are counted per worker and
never fatal (the worker may be mid-restart; its heartbeat SLO window
is the fallback).  `/status` fails CLOSED (503) until every live
worker has armed its gates — the PR-8 single-worker discipline applied
fleet-wide: a load balancer must not trust a fleet whose members
nobody has preflighted.
"""

from __future__ import annotations

import json
import threading
import time
import urllib.request
from typing import Callable, Dict, List, Optional, Tuple

from ..utils.metrics import Registry


def merge_worker_metrics(
    fleet_reg: Registry,
    snapshot: List[Dict],
    worker: Optional[str],
    refused: Optional[Callable[[str], None]] = None,
) -> None:
    """Fold one worker's registry snapshot into the fleet registry
    under the per-family aggregation rules (module docstring).
    `worker=None` merges WITHOUT relabelling gauges — the supervisor's
    own instruments are already fleet-scoped.  The fleet registry must
    be FRESH each cycle — counters here are cumulative, and re-merging
    them into yesterday's sums fabricates throughput."""
    for rec in snapshot:
        try:
            kind = rec["kind"]
            if kind == "counter":
                fleet_reg.counter(rec["name"], rec["labels"]).merge_state(rec)
            elif kind == "gauge":
                labels = dict(rec["labels"])
                if worker is not None:
                    labels["worker"] = worker
                fleet_reg.gauge(rec["name"], labels).merge_state(rec)
            elif kind == "histogram":
                fleet_reg.histogram(
                    rec["name"], rec["labels"], buckets=tuple(rec["buckets"])
                ).merge_state(rec)
        except ValueError:
            # bucket-layout mismatch: REFUSE the family (merging
            # mismatched layouts would bin samples into the wrong
            # latency ranges — worse than a counted gap)
            if refused:
                refused(rec.get("name", "?"))
        except Exception:  # noqa: BLE001 — one torn record, not the cycle
            if refused:
                refused(rec.get("name", "?"))


class FleetPlane:
    """Supervisor-side aggregation + exposition.  Owns a background
    scrape thread (never the supervisor's control loop: a wedged worker
    socket must not delay the watchdog) and a stable HTTP endpoint.

    The plane reads the supervisor via a narrow surface: `slots` (for
    liveness + restart counts), `_hb`/`_hb_age_s` (heartbeats), `spool`
    and `status()` — and never mutates it."""

    def __init__(
        self,
        supervisor,
        port: Optional[int] = None,
        scrape_s: Optional[float] = None,
        addr: Optional[str] = None,
        clock=time.time,
        log: Optional[Callable[[str], None]] = None,
    ):
        from ..utils.alerts import AlertEngine, TrendTracker, fleet_rules
        from ..utils.config import load_config
        from ..utils.metrics import REGISTRY

        cfg = load_config()
        self.sup = supervisor
        self.port = port if port is not None else cfg.fleet_metrics_port
        self.scrape_s = scrape_s if scrape_s is not None else cfg.fleet_scrape_s
        self.addr = addr or cfg.metrics_addr or "127.0.0.1"
        self.fast_window_s = cfg.slo_fast_window_s
        self._clock = clock
        self._log = log or supervisor.log
        self._registry = REGISTRY  # the supervisor process's own instruments
        self.engine = AlertEngine(fleet_rules(cfg), registry=REGISTRY, log=self._log, clock=clock)
        self._trend = TrendTracker(keep_s=max(10 * self.scrape_s, 4 * cfg.alert_for_s, 60.0))
        self._restart_trend = TrendTracker(keep_s=max(cfg.breaker_window_s, 60.0))
        # stage-budget overruns (perf sentry): the perf_regression rule
        # fires on the DELTA inside its hysteresis window, so the trend
        # keeps at least that much history
        self._overrun_trend = TrendTracker(keep_s=max(10 * self.scrape_s, 4 * cfg.alert_for_s, 60.0))
        self._restarts_window_s = cfg.breaker_window_s
        self._alert_for_s = cfg.alert_for_s
        self._lock = threading.Lock()
        # pre-first-scrape view: an EMPTY registry, not the raw process
        # REGISTRY — the supervisor process may host other instrumented
        # work (an in-process service in tests/tools), and serving it
        # unfiltered for the first scrape interval would briefly present
        # non-worker counters as fleet counters
        self._view: Dict = {
            "registry": Registry(),
            "ready": False,
            "reason": "no scrape cycle has completed",
            "slo": None,
            "workers_scraped": {},
            "ts": None,
        }
        self._alert_log: List[Dict] = []  # every fire/clear transition this run
        self.scrapes = 0
        self._stop = threading.Event()
        self._srv = None
        self._thread: Optional[threading.Thread] = None
        self.bound_port: Optional[int] = None

    # ----------------------------------------------------------- scrape

    def _fetch_snapshot(self, port: int) -> Optional[Dict]:
        # workers bind ZKP2P_METRICS_ADDR (inherited from this process's
        # env): scrape the same address — loopback only when the bind is
        # loopback/wildcard, else the configured interface (a worker
        # bound to 10.0.0.5 alone is unreachable via 127.0.0.1)
        addr = "127.0.0.1" if self.addr in ("", "0.0.0.0", "127.0.0.1") else self.addr
        try:
            with urllib.request.urlopen(
                f"http://{addr}:{port}/snapshot", timeout=2.0
            ) as resp:
                return json.loads(resp.read())
        except Exception:  # noqa: BLE001 — counted by the caller
            return None

    def scrape_once(self, now: Optional[float] = None) -> Dict:
        """One federation cycle: scrape every live worker, merge, score
        the fleet SLO, evaluate alerts, publish the new view.  Returns
        the view (tests drive this synchronously)."""
        from ..utils.metrics import REGISTRY
        from ..utils.slo import merge_window_states, publish_fleet_slo

        t = self._clock() if now is None else now
        slo_states: List[Dict] = []
        workers_scraped: Dict[str, Dict] = {}
        snapshots: List[Tuple[str, List[Dict]]] = []
        live = unarmed = unreachable = 0
        degraded = 0
        hb_gap: Optional[float] = None
        # perf sentry: overruns summed over workers that REPORT budgets
        # — a worker with an empty budget book contributes nothing, and
        # zero reporting workers keeps the signal None (alert HOLDs; a
        # fresh host's empty ledger must not page)
        perf_workers = perf_overruns = 0
        # list(): the supervisor's autoscaler inserts slots mid-run,
        # and iterating the live dict from this (scrape) thread would
        # RuntimeError exactly at scale events — when the merged
        # signals matter most
        for slot in list(self.sup.slots.values()):
            alive = slot.proc is not None and slot.proc.poll() is None
            if not alive or slot.state not in ("up", "starting", "draining", "retiring"):
                continue
            live += 1
            hb = self.sup._hb(slot) or {}
            if hb.get("degraded"):
                degraded += 1
            perf_hb = hb.get("perf")
            if perf_hb and perf_hb.get("budgets"):
                perf_workers += 1
                try:
                    perf_overruns += int(perf_hb.get("overruns") or 0)
                except (TypeError, ValueError):
                    pass
            age = self.sup._hb_age_s(slot)
            if age is not None:
                hb_gap = age if hb_gap is None else max(hb_gap, age)
            port = hb.get("port")
            snap = self._fetch_snapshot(port) if port else None
            if snap is None:
                unreachable += 1
                # the failure counter ticks only for ATTEMPTED scrapes:
                # a worker that has not published a port yet (cold
                # imports before the first heartbeat) is expected
                # startup, not a scrape-health regression
                if port:
                    REGISTRY.counter(
                        "zkp2p_fleet_scrape_failures_total", {"worker": slot.wid}
                    ).inc()
                # heartbeat fallback: the SLO window still merges, so a
                # worker mid-restart does not punch a hole in fleet
                # attainment — but it cannot vouch for armed gates.
                # The serialized ages are relative to the heartbeat's
                # WRITE time: shift by the heartbeat's own age, or a
                # wedged worker's frozen samples would sit inside the
                # fast burn window forever.
                win = hb.get("slo_window")
                if win:
                    if age:
                        win = dict(win)
                        win["samples"] = [
                            [a + age, lat, good] for a, lat, good in win.get("samples") or []
                        ]
                    slo_states.append(win)
                # scraped-vs-armed stay separate fields: "scrape is
                # failing" and "gates not armed" are opposite
                # remediations and must be tellable apart per worker
                workers_scraped[slot.wid] = {"scraped": False, "armed": None, "port": port}
                continue
            if not snap.get("armed"):
                unarmed += 1
            if snap.get("slo_window"):
                slo_states.append(snap["slo_window"])
            snapshots.append((slot.wid, snap.get("metrics") or []))
            workers_scraped[slot.wid] = {
                "scraped": True, "armed": bool(snap.get("armed")),
                "port": port, "pid": snap.get("pid"),
            }

        # supervisor's own spool scan: the backlog signal must not
        # depend on any worker being scrapable
        from .service import scan_spool

        scan = scan_spool(self.sup.spool, t, self.scrape_s, 300.0)
        REGISTRY.gauge("zkp2p_fleet_backlog").set(scan["backlog"])
        self._trend.update(t, scan["backlog"])

        merged_slo = merge_window_states(slo_states, fast_window_s=self.fast_window_s)
        publish_fleet_slo(merged_slo, registry=REGISTRY)

        # alert signals out of the merged view + supervisor state
        total_restarts = sum(s.restarts for s in list(self.sup.slots.values()))
        self._restart_trend.update(t, total_restarts)
        restarts_recent = self._restart_trend.delta(self._restarts_window_s, t)
        budget_overruns: Optional[int] = perf_overruns if perf_workers else None
        overruns_recent: Optional[float] = None
        if budget_overruns is not None:
            self._overrun_trend.update(t, budget_overruns)
            overruns_recent = self._overrun_trend.delta(self._alert_for_s, t)
        signals = {
            "burn_fast": merged_slo["burn_fast"],
            "burn_slow": merged_slo["burn_slow"],
            "slo_n": merged_slo["n"],
            "backlog": scan["backlog"],
            "backlog_growing": self._trend.growing(self._alert_for_s, t),
            "restarts_recent": restarts_recent,
            "parked": sum(1 for s in list(self.sup.slots.values()) if s.state == "parked"),
            "degraded": degraded,
            "hb_gap_s": hb_gap,
            "budget_overruns": budget_overruns,
            "overruns_recent": overruns_recent,
        }
        for tr in self.engine.evaluate(signals, now=t):
            self._alert_log.append(tr)

        # build the merged fleet registry FRESH (counters are cumulative)
        fleet_reg = Registry()

        def refused(name: str) -> None:
            REGISTRY.counter("zkp2p_fleet_merge_refusals_total", {"family": name}).inc()

        # supervisor-process instruments first (restart/park/governor
        # counters, the just-published zkp2p_fleet_slo_* values);
        # worker=None = no relabelling — they are already fleet-scoped.
        # ONLY the zkp2p_fleet_* families: the supervisor process may
        # host other instrumented work (an in-process service in tests
        # or tools, its own trace histograms), and folding that into
        # the fleet view would break the federation invariant that
        # fleet service counters EQUAL the per-worker sums.
        sup_snap = [m for m in REGISTRY.snapshot() if m["name"].startswith("zkp2p_fleet_")]
        merge_worker_metrics(fleet_reg, sup_snap, worker=None, refused=refused)
        for wid, snap in snapshots:
            merge_worker_metrics(fleet_reg, snap, worker=wid, refused=refused)
        self.scrapes += 1
        REGISTRY.counter("zkp2p_fleet_scrapes_total").inc()

        ready = live > 0 and unreachable == 0 and unarmed == 0
        reason = None
        if not ready:
            if live == 0:
                reason = "no live workers"
            elif unreachable:
                reason = f"{unreachable}/{live} live worker(s) unreachable (no armed snapshot)"
            else:
                reason = f"{unarmed}/{live} live worker(s) have not armed their gates (preflight)"
        view = {
            "registry": fleet_reg,
            "ready": ready,
            "reason": reason,
            "slo": merged_slo,
            "signals": signals,
            "workers_scraped": workers_scraped,
            "ts": round(t, 3),
        }
        with self._lock:
            self._view = view
        return view

    # ------------------------------------------------------------ status

    def status_payload(self) -> Dict:
        """The fleet `/status` body (also folded into status.json by
        the supervisor): supervisor worker table + merged SLO + alerts
        + scrape health.  `ok` gates the HTTP code: False → 503."""
        with self._lock:
            view = dict(self._view)
        body = self.sup.status()
        body["ok"] = bool(view.get("ready"))
        if not body["ok"]:
            body["reason"] = view.get("reason") or "fleet plane not ready"
        body["slo"] = view.get("slo")
        body["alerts"] = self.engine.active()
        body["alerts_state"] = self.engine.state()
        body["signals"] = view.get("signals")
        body["scrape"] = {
            "cycles": self.scrapes,
            "interval_s": self.scrape_s,
            "last_ts": view.get("ts"),
            "workers": view.get("workers_scraped"),
        }
        if self.bound_port is not None:
            body["metrics_port"] = self.bound_port
        return body

    def alert_log(self) -> List[Dict]:
        return list(self._alert_log)

    def last_signals(self) -> Optional[Dict]:
        """The newest scrape cycle's alert/autoscale signal map (None
        before the first completed cycle) — the supervisor's autoscaler
        consumes this instead of re-deriving its own view."""
        with self._lock:
            return self._view.get("signals")

    # --------------------------------------------------------- lifecycle

    def start(self) -> Optional[int]:
        """Bind the endpoint (port 0/auto = ephemeral, recorded in
        `bound_port` + status.json) and start the scrape thread.
        Returns the bound port, or None when binding failed (counted
        behavior mirrors maybe_start_metrics_server: the fleet still
        runs; exposition degrades loudly)."""
        from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

        plane = self

        class Handler(BaseHTTPRequestHandler):
            def _send(self, code: int, body: bytes, ctype: str) -> None:
                self.send_response(code)
                self.send_header("Content-Type", ctype)
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def do_GET(self):  # noqa: N802 — stdlib API
                path = self.path.split("?", 1)[0].rstrip("/")
                if path in ("", "/metrics"):
                    with plane._lock:
                        reg = plane._view["registry"]
                    self._send(200, reg.to_prometheus().encode(), "text/plain; version=0.0.4")
                elif path == "/status":
                    try:
                        body = plane.status_payload()
                        code = 200 if body.get("ok") else 503
                    except Exception as e:  # noqa: BLE001 — degraded, not dead
                        body, code = {"ok": False, "reason": f"status error: {e}"}, 500
                    self._send(code, (json.dumps(body) + "\n").encode(), "application/json")
                elif path == "/healthz":
                    self._send(200, b'{"ok": true}\n', "application/json")
                else:
                    self.send_response(404)
                    self.end_headers()

            def log_message(self, *_a):  # scrapes must not spam stderr
                pass

        try:
            self._srv = ThreadingHTTPServer((self.addr, int(self.port or 0)), Handler)
        except OSError as e:
            self._log(f"fleet metrics endpoint on :{self.port} unavailable ({e}); plane exposition off")
            self._srv = None
        else:
            self.bound_port = int(self._srv.server_address[1])
            threading.Thread(
                target=self._srv.serve_forever, daemon=True, name="zkp2p-fleet-metrics"
            ).start()
            self._log(f"fleet observability plane on :{self.bound_port} (/metrics /status /healthz)")

        def loop():
            while not self._stop.wait(self.scrape_s):
                try:
                    self.scrape_once()
                except Exception as e:  # noqa: BLE001 — the plane must outlive a bad cycle
                    self._log(f"fleet scrape cycle failed: {e}")

        self._thread = threading.Thread(target=loop, daemon=True, name="zkp2p-fleet-scrape")
        self._thread.start()
        return self.bound_port

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=2 * self.scrape_s + 5)
        if self._srv is not None:
            self._srv.shutdown()
            self._srv.server_close()
            self._srv = None


# ---------------------------------------------------------------------------
# Shared client-side helpers: every consumer of the fleet /status
# contract (cli `top`, loadgen's readiness gate + teardown snapshot,
# chaos's plane assertions) goes through these two, so a change to the
# contract (payload shape, what a 503 carries) lands in ONE place.


def http_status_json(url: str, timeout: float = 3.0) -> Optional[Dict]:
    """GET `url` as JSON.  An HTTP error response whose body parses as
    JSON is RETURNED, not raised — the fleet /status 503 body IS the
    status (ok=False + reason).  Transport failures return None."""
    import urllib.error

    try:
        with urllib.request.urlopen(url, timeout=timeout) as resp:
            return json.loads(resp.read())
    except urllib.error.HTTPError as e:
        try:
            return json.loads(e.read())
        except ValueError:
            return None
    except (OSError, ValueError):
        return None


def discover_fleet_port(fleet_dir: str) -> Optional[int]:
    """The plane's bound port out of `<fleet_dir>/status.json`
    (`metrics_port` — written by the supervisor every tick once the
    plane is up).  None while the file or field does not exist yet."""
    import os

    try:
        with open(os.path.join(fleet_dir, "status.json")) as f:
            port = json.load(f).get("metrics_port")
        return int(port) if port else None
    except (OSError, ValueError, TypeError):
        return None


# ---------------------------------------------------------------------------
# `zkp2p-tpu top`: render one fleet /status payload as a terminal frame
# (the CLI loops fetch→render; rendering lives here so tests can pin the
# format without a live endpoint).


def render_top(body: Dict) -> str:
    """One text frame of the live fleet view: health, merged SLO,
    active alerts, per-worker table, queue signals."""
    lines: List[str] = []
    ok = body.get("ok")
    lines.append(
        f"fleet {body.get('fleet_id', '?')}  "
        f"{'READY' if ok else 'NOT READY'}"
        + (f" ({body.get('reason')})" if not ok and body.get("reason") else "")
        + ("  DRAINING" if body.get("draining") else "")
    )
    slo = body.get("slo")
    if slo:
        lines.append(
            f"slo: attainment {slo['attainment']:.4f}  "
            f"burn fast/slow {slo['burn_fast']:g}/{slo['burn_slow']:g}  "
            f"p95 {slo['p95_s']:.3f}s"
            + (f" (objective {slo['objective_p95_s']:g}s)" if slo.get("objective_p95_s") else "")
            + f"  n={slo['n']} across {slo.get('workers', 0)} window(s)"
        )
    sig = body.get("signals") or {}
    if sig:
        lines.append(
            f"queue: backlog {sig.get('backlog')}  "
            f"restarts(win) {sig.get('restarts_recent')}  "
            f"parked {sig.get('parked')}  degraded {sig.get('degraded')}"
        )
    # scheduler block: per-worker batch targets + lane depths (worker
    # heartbeats) and the supervisor's autoscale state
    sched = body.get("sched") or {}
    wsched = {
        wid: w["sched"] for wid, w in (body.get("workers") or {}).items() if w.get("sched")
    }
    if wsched:
        lines.append("sched: " + "  ".join(
            f"{wid}[{s.get('mode', '?')}] tgt={s.get('batch_target')}"
            + (
                f" lanes i{s.get('lane_interactive', 0)}/b{s.get('lane_bulk', 0)}"
                if s.get("mode") == "adaptive" else ""
            )
            for wid, s in sorted(wsched.items())
        ))
    if sched.get("autoscale"):
        last = sched.get("last_scale")
        lines.append(
            f"autoscale: {sched.get('workers_live')} live in "
            f"[{sched.get('workers_min')}..{sched.get('workers_max')}]  "
            f"events {sched.get('scale_events', 0)}"
            + (
                f"  last {last['direction']} ({last.get('reason')}) -> {last.get('workers')} @ {last.get('ts')}"
                if last else "  last none"
            )
        )
    alerts = body.get("alerts") or []
    if alerts:
        for a in alerts:
            lines.append(f"ALERT {a['rule']}: {a.get('detail', '')} (since {a.get('since')})")
    else:
        lines.append("alerts: none firing")
    workers = body.get("workers") or {}
    if workers:
        # flame column only when some worker's perf block carries a
        # capture pointer (utils.flameprof via the heartbeat): a fresh
        # fleet with no captures renders the PR-18 table unchanged
        flame_col = any(
            (w.get("perf") or {}).get("capture") for w in workers.values()
        )
        lines.append(f"{'worker':<8} {'state':<9} {'pid':>7} {'port':>6} "
                     f"{'restarts':>8} {'rss_mb':>8} {'hb_age':>7} {'degr':>5} {'overrun':>8}"
                     + ("  flame" if flame_col else ""))
        for wid in sorted(workers):
            w = workers[wid]
            rss = w.get("rss_mb")
            age = w.get("hb_age_s")
            # perf-sentry column: stage-budget overruns this worker has
            # counted ("-" = no budget book loaded — fresh ledger, not
            # a clean bill of health)
            perf = w.get("perf") or {}
            over = perf.get("overruns") if perf.get("budgets") else None
            cap = perf.get("capture") or {}
            lines.append(
                f"{wid:<8} {w.get('state', '?'):<9} {str(w.get('pid') or '-'):>7} "
                f"{str(w.get('port') or '-'):>6} {w.get('restarts', 0):>8} "
                f"{(f'{rss:.0f}' if isinstance(rss, (int, float)) else '-'):>8} "
                f"{(f'{age:.1f}' if isinstance(age, (int, float)) else '-'):>7} "
                f"{('y' if w.get('degraded') else '-'):>5} "
                f"{(str(over) if isinstance(over, (int, float)) else '-'):>8}"
                + (f"  {cap.get('file', '-')}" if flame_col else "")
            )
    scrape = body.get("scrape") or {}
    if scrape:
        lines.append(
            f"scrape: {scrape.get('cycles', 0)} cycle(s) @ {scrape.get('interval_s')}s"
            f"  last {scrape.get('last_ts')}"
        )
    return "\n".join(lines)
