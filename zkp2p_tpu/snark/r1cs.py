"""R1CS constraint-system builder — the framework's circuit frontend.

This replaces the circom language layer of the reference (circuit/*.circom,
zk-email-verify-circuits/*.circom).  Where the reference writes

    template P2POnrampVerify(...) { signal input ...; component ... }

our circuits are built programmatically: gadgets (zkp2p_tpu.gadgets) allocate
wires, emit rank-1 constraints  <A,w> * <B,w> = <C,w>, and register witness
computation hooks.  Witness generation therefore lives *with* the circuit
definition (as circom's generated WASM/C++ witness calculators do for the
reference, dizkus-scripts/2_gen_wtns.sh).  Measured at the full-size
flagship circuit (4.9M wires) the hook program runs in ~14 s on one core
— vs the reference's 60 s compiled witness generator on 48 cores
(docs/SCALE.md) — because hook values are small ints and the loop is
allocation-free.

Wire layout follows the Groth16/snarkjs convention: wire 0 is the constant
``1``, wires 1..n_pub are public, the rest private.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Callable, Dict, List, Optional, Sequence, Union

from ..field.bn254 import R


Coeffs = Dict[int, int]  # wire index -> Fr coefficient


class Witness(list):
    """A witness vector (Fr ints) that also carries ``u64``: the prover's
    standard-form (n, 4) little-endian u64 serialization, emitted at build
    time so the per-prove ``witness_convert`` stage collapses to an array
    hand-off (docs/NEXT.md lever 3, gated by ``ZKP2P_WITNESS_U64``)."""

    u64 = None


_WITNESS_ROW_CLS = None


def _witness_row_cls():
    """Object-dtype ndarray subclass used for batch witness rows, lazy so
    the frontend keeps importing without numpy."""
    global _WITNESS_ROW_CLS
    if _WITNESS_ROW_CLS is None:
        import numpy as np

        class WitnessRow(np.ndarray):
            """Batch witness column carrying the build-time ``u64``
            standard-form serialization (see :class:`Witness`)."""

            u64 = None

            def __array_finalize__(self, obj):
                u = getattr(obj, "u64", None)
                # Propagate only through same-shape views; a slice or
                # reduction must not inherit a stale serialization.
                self.u64 = (
                    u
                    if u is not None and getattr(obj, "shape", None) == self.shape
                    else None
                )

        _WITNESS_ROW_CLS = WitnessRow
    return _WITNESS_ROW_CLS


def _std_u64(vals, out=None):
    """Serialize reduced Fr values to the prover's standard form: (n, 4)
    uint64 little-endian limb rows.  Bulk numpy assign covers the sub-2^64
    common case (>99% of wires at the bench shape); a chunk that overflows
    falls back to exact 32-byte serialization — mirroring
    ``native_prove._witness_std_u64`` so builder-emitted and prove-time
    serializations are byte-identical."""
    import numpy as np

    n = len(vals)
    arr = np.zeros((n, 4), dtype=np.uint64) if out is None else out
    col = arr[:, 0]
    CH = 8192
    for lo in range(0, n, CH):
        hi = min(n, lo + CH)
        try:
            col[lo:hi] = vals[lo:hi]
        except (OverflowError, TypeError, ValueError):
            arr[lo:hi] = np.frombuffer(
                b"".join((int(v) % R).to_bytes(32, "little") for v in vals[lo:hi]),
                dtype="<u8",
            ).reshape(hi - lo, 4)
    return arr


class LC:
    """Linear combination of wires over Fr."""

    __slots__ = ("terms",)

    def __init__(self, terms: Optional[Coeffs] = None):
        self.terms: Coeffs = dict(terms) if terms else {}

    @classmethod
    def const(cls, c: int) -> "LC":
        c %= R
        return cls({0: c} if c else {})

    @classmethod
    def of(cls, wire: int, coeff: int = 1) -> "LC":
        coeff %= R
        return cls({wire: coeff} if coeff else {})

    def __add__(self, other: "LCLike") -> "LC":
        other = as_lc(other)
        out = dict(self.terms)
        for w, c in other.terms.items():
            nc = (out.get(w, 0) + c) % R
            if nc:
                out[w] = nc
            else:
                out.pop(w, None)
        return LC(out)

    def __sub__(self, other: "LCLike") -> "LC":
        return self + (as_lc(other) * (R - 1))

    def __mul__(self, scalar: int) -> "LC":
        scalar %= R
        if scalar == 0:
            return LC()
        return LC({w: (c * scalar) % R for w, c in self.terms.items()})

    __rmul__ = __mul__

    def __neg__(self) -> "LC":
        return self * (R - 1)

    def eval(self, assignment: Sequence[int]) -> int:
        return sum(c * assignment[w] for w, c in self.terms.items()) % R

    def is_const(self) -> bool:
        return all(w == 0 for w in self.terms)

    def __repr__(self):
        return f"LC({self.terms})"


LCLike = Union["LC", int]


def as_lc(x: LCLike) -> LC:
    if isinstance(x, LC):
        return x
    return LC.const(x)


@dataclass
class Constraint:
    a: Coeffs
    b: Coeffs
    c: Coeffs
    tag: str = ""


@dataclass
class ComputeHook:
    """Witness computation step: outs <- fn(*wire values of ins)."""

    outs: List[int]
    fn: Callable[..., Union[int, Sequence[int]]]
    ins: List[int]


@dataclass
class BlockHook:
    """Coarse witness step: a whole gadget block's wires from one numpy
    program.  vfn maps an (n_ins, K) int64 matrix to an (n_outs, K)
    integer matrix — vectorized over the batch axis K AND whatever
    internal structure the block has (time steps, rounds, lanes), which
    is what `witness_batch` needs to amortize numpy dispatch (per-hook
    object columns pay ~µs per op; a block pays it once per thousands of
    wires).  The scalar `witness` path runs the same vfn with K=1, so
    there is exactly ONE witness implementation per block — no
    scalar/vector drift.

    Contract (int64=True, the default): every input and output value fits
    int64 (bits, bytes, u32 words, bounded sums — the SHA/DFA/packing
    domains).  A violating value raises OverflowError at the numpy
    boundary, loudly.  int64=False hands vfn the raw OBJECT matrix
    (Python ints — exact field arithmetic; for blocks like one-hot lane
    inverses that need full-width values)."""

    outs: List[int]
    vfn: Callable
    ins: List[int]
    int64: bool = True


class ConstraintSystem:
    """Mutable R1CS under construction + witness program."""

    def __init__(self, name: str = "circuit"):
        self.name = name
        self.num_wires = 1  # wire 0 == 1
        self.num_public = 0  # not counting wire 0
        self.constraints: List[Constraint] = []
        self.hooks: List[ComputeHook] = []
        self._public_frozen = False
        self.labels: Dict[int, str] = {0: "one"}
        # Static value-width bounds (bits), PROVEN by constraints for any
        # satisfying witness (booleanity, num2bits recomposition, ...).
        # The prover's width-classed MSM drops the provably-zero scalar
        # digit planes of narrow wires — ~90% of venmo wires are bits
        # (SHA/DFA), so this is the structured-scalar analog of
        # rapidsnark's bit-concentrated-digit fast path.  Absent = 254.
        self.wire_width: Dict[int, int] = {0: 1}
        # Demand-side width metadata (snark.analysis bool/width rule):
        # gadgets whose soundness ASSUMES an input bound — comparators,
        # boolean gates, packers — record (wire, bits, site) here and the
        # static auditor checks every demand against a constraint-backed
        # wire_width bound.  An unbounded comparator input is the classic
        # circom forgery (e.g. LessThan on an unconstrained signal).
        self.width_demands: List[tuple] = []
        # Prover-seeded input wires (witness() private_inputs keys),
        # declared by the circuit builder via mark_input: the soundness
        # analysis treats them — with wire 0 and the publics — as the
        # "given" wires every other wire must be determined from.
        self.input_wires: set = set()
        # Audit waivers: (rule, label-glob) -> written soundness argument.
        # Declared INLINE at the gadget/model site that creates the waived
        # structure (the PR-13 discipline: every exception greppable,
        # justified where it lives).  An empty argument raises.
        self.audit_waivers: Dict[tuple, str] = {}

    # ---------------------------------------------------------- allocation

    def new_public(self, label: str = "") -> int:
        if self._public_frozen:
            raise RuntimeError("public inputs must be allocated before private wires")
        idx = self.num_wires
        self.num_wires += 1
        self.num_public += 1
        if label:
            self.labels[idx] = label
        return idx

    def new_wire(self, label: str = "") -> int:
        self._public_frozen = True
        idx = self.num_wires
        self.num_wires += 1
        if label:
            self.labels[idx] = label
        return idx

    def new_wires(self, n: int, label: str = "") -> List[int]:
        return [self.new_wire(f"{label}[{i}]" if label else "") for i in range(n)]

    # ---------------------------------------------------------- constraints

    def enforce(self, a: LCLike, b: LCLike, c: LCLike, tag: str = "") -> None:
        """<a,w> * <b,w> = <c,w>."""
        self.constraints.append(
            Constraint(as_lc(a).terms, as_lc(b).terms, as_lc(c).terms, tag)
        )

    def enforce_eq(self, a: LCLike, b: LCLike, tag: str = "") -> None:
        """<a,w> = <b,w>  encoded as  (a-b) * 1 = 0."""
        self.enforce(as_lc(a) - as_lc(b), LC.const(1), LC(), tag)

    def enforce_zero(self, a: LCLike, tag: str = "") -> None:
        self.enforce(as_lc(a), LC.const(1), LC(), tag)

    def enforce_bool(self, w: int, tag: str = "") -> None:
        """w * (w - 1) = 0."""
        self.enforce(LC.of(w), LC.of(w) - 1, LC(), tag or "bool")
        self.set_width(w, 1)

    def set_width(self, w: int, bits: int) -> None:
        """Record a constraint-backed value-width bound for wire `w`.

        ONLY call where a constraint actually enforces value < 2^bits for
        every satisfying witness — the width-classed MSM silently drops
        the digit planes above the bound (a wrong tag would emit a proof
        that fails verification, never a wrong-but-verifying one, since
        pi stays on the curve but differs from the honest proof)."""
        cur = self.wire_width.get(w, 254)
        if bits < cur:
            self.wire_width[w] = bits

    def require_width(self, w: int, bits: int, site: str) -> None:
        """Record that a gadget's soundness ASSUMES wire `w` < 2^bits
        (bits=1: boolean).  Checked statically by snark.analysis: every
        demand must be dominated by a constraint-backed set_width /
        enforce_bool / num2bits bound, or the audit reports bool-width."""
        self.width_demands.append((w, bits, site))

    def mark_input(self, wires) -> None:
        """Declare prover-seeded input wires (the witness()
        private_inputs keys).  The soundness auditor propagates
        determinism from wire 0 + publics + these; the hook-coverage
        rule exempts them from needing a ComputeHook."""
        if isinstance(wires, int):
            wires = [wires]
        self.input_wires.update(wires)

    def waive(self, rule: str, label_glob: str, why: str) -> None:
        """Waive an audit rule for wires whose label matches `label_glob`
        (constraint rules match the tag instead).  `why` is a REQUIRED
        written soundness argument — it lands verbatim in the audit
        report, and an empty one is refused loudly."""
        if not why or not why.strip():
            raise ValueError(
                f"audit waiver for ({rule}, {label_glob}) needs a written "
                "soundness argument — an unjustified waiver is a review failure"
            )
        self.audit_waivers[(rule, label_glob)] = why

    # ---------------------------------------------------------- witness gen

    def compute(self, outs, fn, ins) -> None:
        """Register a witness hook.  fn receives int values of `ins` and
        returns the value(s) for `outs` (single int or sequence)."""
        outs = [outs] if isinstance(outs, int) else list(outs)
        ins = [ins] if isinstance(ins, int) else list(ins)
        self.hooks.append(ComputeHook(outs, fn, ins))

    def compute_block(self, outs, vfn, ins, int64: bool = True) -> None:
        """Register a BlockHook: all of `outs` from one numpy program
        over `ins` (see BlockHook for the vfn contract)."""
        self.hooks.append(BlockHook(list(outs), vfn, list(ins), int64))

    def wire_desc(self, i: int) -> str:
        """Human description of a wire: index, label, and allocation site
        (the gadget family = the auditor's label class, so witness-time
        errors and static audit findings name wires the same way)."""
        label = self.labels.get(i)
        if not label:
            return f"wire {i} (unlabelled)"
        from .analysis import label_class

        cls = label_class(label)
        site = f", allocated by '{cls}'" if cls != label else ""
        return f"wire {i} ('{label}'{site})"

    def witness(self, public_inputs: Sequence[int], private_inputs: Dict[int, int] | None = None) -> List[int]:
        """Run the witness program.  `public_inputs` fills wires 1..n_pub;
        `private_inputs` optionally pre-seeds private wires (for inputs that
        are not computed from anything, e.g. the email bytes)."""
        if len(public_inputs) != self.num_public:
            raise ValueError(
                f"expected {self.num_public} public inputs, got {len(public_inputs)}"
            )
        w: List[Optional[int]] = [None] * self.num_wires
        w[0] = 1
        for i, v in enumerate(public_inputs):
            w[1 + i] = v % R
        if private_inputs:
            for idx, v in private_inputs.items():
                w[idx] = v % R
        for hook in self.hooks:
            if isinstance(hook, BlockHook):
                import numpy as np

                mat = np.empty(
                    (len(hook.ins), 1), dtype=np.int64 if hook.int64 else object
                )
                for j, i in enumerate(hook.ins):
                    if w[i] is None:
                        raise RuntimeError(
                            f"witness block reads unassigned {self.wire_desc(i)}"
                        )
                    mat[j, 0] = w[i]
                res = np.asarray(hook.vfn(mat))
                if res.shape[0] != len(hook.outs):
                    raise RuntimeError(
                        f"block produced {res.shape[0]} rows for {len(hook.outs)} outs"
                    )
                for o, v in zip(hook.outs, res[:, 0]):
                    w[o] = int(v) % R
                continue
            args = []
            for i in hook.ins:
                if w[i] is None:
                    raise RuntimeError(
                        f"witness hook reads unassigned {self.wire_desc(i)}"
                    )
                args.append(w[i])
            vals = hook.fn(*args)
            if isinstance(vals, int):
                vals = [vals]
            if len(vals) != len(hook.outs):
                raise RuntimeError(
                    f"hook produced {len(vals)} values for {len(hook.outs)} outs"
                )
            for o, v in zip(hook.outs, vals):
                w[o] = v % R
        missing = [i for i, v in enumerate(w) if v is None]
        if missing:
            raise RuntimeError(
                f"{len(missing)} unassigned wires (no hook or input seed "
                "assigns them; `zkp2p-tpu lint --circuits` reports this "
                "statically as hook-coverage), first: "
                + "; ".join(self.wire_desc(i) for i in missing[:5])
            )
        out = Witness(w)
        out.u64 = _std_u64(out)
        return out

    def witness_batch(
        self, inputs: Sequence[tuple], stats: Optional[Dict[str, int]] = None
    ) -> List[Sequence[int]]:
        """Vectorized witness generation: run the hook program ONCE over K
        independent inputs ([(public_inputs, private_inputs), ...]).

        Each wire holds a K-element numpy OBJECT column (Python ints inside
        a C loop), so every elementwise hook — xor/and/sum/product chains,
        the whole SHA-256 / DFA-scan / packing tier — evaluates with exact
        bigint semantics at C dispatch cost, amortising the interpreter's
        per-hook overhead across the batch.  Hooks whose lambdas are not
        array-safe (data-dependent branches: modular inverses, equality
        selects) are detected by the throw and replayed per-element — the
        scalar `witness` path stays the oracle, and the two are bit-exact
        by construction (differentially tested in tests/test_witness_batch).

        This is the batch tier of SURVEY §2.2's witness generator (the
        reference compiles witness gen to C++/WASM, dizkus-scripts/
        1_compile.sh; our batch=K service shape needs K witnesses per
        prove round).  `stats`, when given, receives vectorized/fallback
        hook counts."""
        import numpy as np

        K = len(inputs)
        if K == 0:
            return []

        # Two parallel (n_wires, K) matrices back the wires: W64 (int64)
        # holds everything int64-typed blocks produce and consume — the
        # common case, zero conversions between blocks — and W (object,
        # exact Python ints) holds field-width values from object blocks
        # and per-wire hooks.  Rows migrate lazily in either direction
        # (has64/hasobj), the final extraction is one merged
        # transpose+tolist.  (A single object matrix spent ~30% of the
        # batch wall time converting at every int64-block boundary.)
        W = np.empty((self.num_wires, K), dtype=object)
        W64 = np.empty((self.num_wires, K), dtype=np.int64)
        assigned = np.zeros(self.num_wires, dtype=bool)
        hasobj = np.zeros(self.num_wires, dtype=bool)
        has64 = np.zeros(self.num_wires, dtype=bool)

        def to64(idx: np.ndarray) -> None:
            """Materialize int64 rows for `idx` (loud OverflowError if a
            value exceeds the BlockHook int64 contract)."""
            need = idx[~has64[idx]]
            if need.shape[0]:
                W64[need] = W[need].astype(np.int64)
                has64[need] = True

        def toobj(idx: np.ndarray) -> None:
            need = idx[~hasobj[idx]]
            if need.shape[0]:
                W[need] = W64[need].astype(object)
                hasobj[need] = True

        W[0] = 1
        assigned[0] = hasobj[0] = True
        for k, (pubs, _) in enumerate(inputs):
            if len(pubs) != self.num_public:
                raise ValueError(
                    f"input {k}: expected {self.num_public} public inputs, got {len(pubs)}"
                )
        for i in range(self.num_public):
            W[1 + i] = [inputs[k][0][i] % R for k in range(K)]
            assigned[1 + i] = hasobj[1 + i] = True
        seeded = set()
        for _, priv in inputs:
            seeded.update((priv or {}).keys())
        for idx in seeded:
            vals = []
            for k, (_, priv) in enumerate(inputs):
                if priv is None or idx not in priv:
                    raise ValueError(
                        f"wire {idx} ({self.labels.get(idx)}) seeded in some batch "
                        f"inputs but not input {k} — batch inputs must share a seed shape"
                    )
                vals.append(priv[idx] % R)
            W[idx] = vals
            assigned[idx] = hasobj[idx] = True

        def check_assigned(ins_idx, kind):
            if not assigned[ins_idx].all():
                bad = int(ins_idx[~assigned[ins_idx]][0])
                raise RuntimeError(
                    f"witness {kind} reads unassigned {self.wire_desc(bad)}"
                )

        # The hook program is static per circuit: index arrays are cached
        # on the hooks, and the assigned-order checks run only until one
        # full pass has validated the program (then every later batch
        # skips them — they were ~10% of the loop's time).
        validated = getattr(self, "_hooks_validated", False)
        n_vec = n_fb = n_block = 0
        for hook in self.hooks:
            if isinstance(hook, BlockHook):
                ins_idx = getattr(hook, "_ins_idx", None)
                if ins_idx is None:
                    ins_idx = hook._ins_idx = np.asarray(hook.ins, dtype=np.intp)
                    hook._outs_idx = np.asarray(hook.outs, dtype=np.intp)
                if not validated:
                    check_assigned(ins_idx, "block")
                if hook.int64:
                    to64(ins_idx)
                    mat = W64[ins_idx]
                else:
                    toobj(ins_idx)
                    mat = W[ins_idx]
                res = hook.vfn(mat)
                if not validated and res.shape != (len(hook.outs), K):
                    raise RuntimeError(
                        f"block produced shape {res.shape}, expected {(len(hook.outs), K)}"
                    )
                outs_idx = hook._outs_idx
                if res.dtype == object:
                    W[outs_idx] = res
                    hasobj[outs_idx] = True
                    has64[outs_idx] = False
                else:
                    W64[outs_idx] = res
                    has64[outs_idx] = True
                    hasobj[outs_idx] = False
                assigned[outs_idx] = True
                n_block += 1
                continue
            ins_idx = getattr(hook, "_ins_idx", None)
            if ins_idx is None:
                ins_idx = hook._ins_idx = np.asarray(hook.ins, dtype=np.intp)
            if not validated:
                check_assigned(ins_idx, "hook")
            toobj(ins_idx)
            args = [W[i] for i in hook.ins]
            try:
                vals = hook.fn(*args)
                if isinstance(vals, np.ndarray) or not isinstance(vals, (list, tuple)):
                    vals = [vals]
                if len(vals) != len(hook.outs):
                    raise RuntimeError("arity")
                for o, v in zip(hook.outs, vals):
                    if isinstance(v, np.ndarray) and v.shape == (K,):
                        W[o] = v % R
                    elif isinstance(v, int):  # batch-constant hook
                        W[o] = v % R
                    else:
                        raise TypeError("non-columnar hook result")
                    assigned[o] = hasobj[o] = True
                    has64[o] = False
                n_vec += 1
            except Exception:
                # Array-unsafe lambda: replay per element (exact scalar
                # semantics; mirrors witness()'s inner loop).
                for k in range(K):
                    a = [int(c[k]) for c in args]
                    vs = hook.fn(*a)
                    if isinstance(vs, int):
                        vs = [vs]
                    if len(vs) != len(hook.outs):
                        raise RuntimeError(
                            f"hook produced {len(vs)} values for {len(hook.outs)} outs"
                        )
                    for o, v in zip(hook.outs, vs):
                        W[o, k] = v % R
                for o in hook.outs:
                    assigned[o] = hasobj[o] = True
                    has64[o] = False
                n_fb += 1

        if not assigned.all():
            missing = np.flatnonzero(~assigned)
            raise RuntimeError(
                f"{len(missing)} unassigned wires (no hook or input seed "
                "assigns them; `zkp2p-tpu lint --circuits` reports this "
                "statically as hook-coverage), first: "
                + "; ".join(self.wire_desc(int(i)) for i in missing[:5])
            )
        if stats is not None:
            stats["vectorized_hooks"] = n_vec
            stats["fallback_hooks"] = n_fb
            stats["block_hooks"] = n_block
        toobj(np.flatnonzero(~hasobj))  # one merged materialization
        self._hooks_validated = True
        # Standard-form u64 serialization at the builder (docs/NEXT.md
        # lever 3), vectorized while the wires are still row-major per
        # wire: int64-backed rows are canonical and non-negative in the
        # common case and bulk-cast; object rows bulk-cast per chunk with
        # the same exact fallback as _std_u64.
        U = np.zeros((self.num_wires, K, 4), dtype=np.uint64)
        i64 = np.flatnonzero(has64)
        slow_rows = np.flatnonzero(~has64)
        if i64.size:
            neg = (W64[i64] < 0).any(axis=1)
            ok = i64[~neg]
            U[ok, :, 0] = W64[ok].astype(np.uint64)
            if neg.any():
                slow_rows = np.concatenate([slow_rows, i64[neg]])
        CH = 8192
        for lo in range(0, slow_rows.size, CH):
            idx = slow_rows[lo : lo + CH]
            try:
                U[idx, :, 0] = W[idx].astype(np.uint64)
            except (OverflowError, TypeError, ValueError):
                for i in idx:
                    try:
                        U[i, :, 0] = W[i].astype(np.uint64)
                    except (OverflowError, TypeError, ValueError):
                        U[i] = np.frombuffer(
                            b"".join(
                                (int(v) % R).to_bytes(32, "little") for v in W[i]
                            ),
                            dtype="<u8",
                        ).reshape(K, 4)
        # One contiguous transpose copy (per-row strided gathers cost ~4x
        # more), then row views: W/W64 and the flag arrays are released;
        # what stays referenced is exactly the K witness vectors.  (A
        # caller keeping ONE witness long-term keeps its K-batch block —
        # copy the row if that matters.)
        Wt = np.ascontiguousarray(W.T)
        row_cls = _witness_row_cls()
        out: List[Sequence[int]] = []
        for k in range(K):
            row = Wt[k].view(row_cls)
            row.u64 = np.ascontiguousarray(U[:, k])
            out.append(row)
        return out

    # ---------------------------------------------------------- checking

    def check_witness(self, w: Sequence[int]) -> None:
        """Assert every constraint is satisfied (the Az*Bz=Cz self-check —
        the ZK analog of the reference's `circom --inspect` lint, see
        SURVEY.md §5 race-detection), plus every wire_width tag (a wrong
        width tag would make the classed MSM drop nonzero digit planes —
        failing only at pairing verification; this localises it)."""
        for idx, con in enumerate(self.constraints):
            a = sum(c * w[i] for i, c in con.a.items()) % R
            b = sum(c * w[i] for i, c in con.b.items()) % R
            c_ = sum(c * w[i] for i, c in con.c.items()) % R
            if a * b % R != c_:
                raise AssertionError(
                    f"constraint {idx} ({con.tag}) unsatisfied: {a}*{b} != {c_}"
                )
        self.check_widths(w)

    def check_widths(self, w: Sequence[int]) -> None:
        """Assert every constraint-backed width bound actually holds for
        this witness (prover.groth16_tpu width classing relies on it).
        Values reduce mod R first, matching the constraint loop — an
        unreduced-but-equivalent witness must not be rejected."""
        for i, bits in self.wire_width.items():
            v = w[i] % R
            if v >= (1 << bits):
                raise AssertionError(
                    f"wire {i} ({self.labels.get(i, '?')}): value {v} exceeds "
                    f"its tagged width bound of {bits} bits"
                )

    # ---------------------------------------------------------- stats

    @property
    def num_constraints(self) -> int:
        return len(self.constraints)

    def stats(self) -> Dict[str, int]:
        """Constraint-count profile — mirror of `snarkjs r1cs info`
        (circuit/scripts/circuit_stats.sh:2)."""
        by_tag: Dict[str, int] = {}
        for c in self.constraints:
            key = c.tag.split("/")[0] if c.tag else "untagged"
            by_tag[key] = by_tag.get(key, 0) + 1
        return {
            "wires": self.num_wires,
            "public": self.num_public,
            "constraints": self.num_constraints,
            **{f"tag:{k}": v for k, v in sorted(by_tag.items())},
        }
