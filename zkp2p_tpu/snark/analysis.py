"""Static R1CS soundness auditor — the registry admission gate.

The product's security claim is an iff: "a proof exists ⟺ a real
DKIM-signed payment exists".  The programmatic frontend (snark.r1cs
gadget composition, replacing circom) can silently break the ⟸
direction with an under-constrained wire, and NO runtime test catches
it: witnesses built by the circuit's own hooks always satisfy the
circuit's own constraints.  This module analyzes the built
``ConstraintSystem`` itself, with the PR-13 lint discipline (every rule
proven able to fire, zero unwaived findings on shipped circuits, every
waiver carrying a written soundness argument).

Rules (docs/STATIC_ANALYSIS.md carries the full table with scars):

  unconstrained-wire    a wire appearing in no constraint — the prover
                        may substitute ANY value (worst when a
                        ComputeHook assigns it: the hook hides the hole
                        from every witness test).
  determinism           Picus-lite uniqueness fixpoint: propagate
                        "uniquely determined" from wire 0 + publics +
                        declared inputs through constraints with one
                        linearly-occurring unknown, IsZero-style case
                        pairs, boolean power-of-two decompositions, and
                        small linear-system rank closure (the
                        BigMultNoCarry Vandermonde pattern).  Wires
                        never reached are attacker-choosable.
  bool-width            every gadget width DEMAND (require_width: AND
                        gate operands, mux selectors, LessThan inputs,
                        packer bytes) must be dominated by a recorded
                        wire_width bound — the unbounded-comparator
                        forgery class.  The rule closes the MISSING-
                        annotation hole; wire_width itself is trusted
                        metadata under set_width's contract ("only call
                        where a constraint enforces it"), and a LYING
                        bound already fails closed at proof time (the
                        width-classed MSM emits a proof that fails
                        pairing verification, never a forged one).
  dead-constraint       0 = 0 and constant-only rows: wasted prover
                        work (QAP rows, MSM length), and a never-
                        satisfiable constant row is a broken circuit.
  duplicate-constraint  byte-identical rows (modulo a*b swap).
  hook-coverage         every constrained non-input wire assigned by
                        exactly one witness hook — the witness()-time
                        "unassigned wire" crash, caught statically.
  public-layout         n_public vs the declared on-chain signal layout
                        (and, where a VerifyingKey is at hand, the
                        exported verifier's IC length) — the
                        docs/EVM_PARITY.md loop, registry-wide.

The determinism pass is deliberately *sound but incomplete*: it only
ever marks a wire determined when every satisfying witness provably
agrees on it, so a "determined" verdict is trustworthy and an
"undetermined" one is a finding to fix or waive — exactly Picus's
one-sided contract (PAPERS.md; Picus = the circom ecosystem's
determinism checker).  It scales by working on flat numpy incidence
arrays with a frontier worklist, so the 4.9M-wire flagship audits in a
CI-tolerable budget; reports are cached under .bench_cache keyed by a
structural circuit digest and surfaced in run_manifest.
"""

from __future__ import annotations

import fnmatch
import hashlib
import json
import os
import re
import time
from array import array
from dataclasses import asdict, dataclass
from typing import Dict, List, Optional, Tuple

from ..field.bn254 import R

AUDIT_VERSION = 2  # v2: hook-coverage also flags hook-assigned publics

RULES = (
    "unconstrained-wire",
    "determinism",
    "bool-width",
    "dead-constraint",
    "duplicate-constraint",
    "hook-coverage",
    "public-layout",
)

_NUM_RE = re.compile(r"\d+")


def label_class(label: str) -> str:
    """Collapse indices out of a wire label: 'rsa.sq3.qb.2.b[7]' ->
    'rsa.sq#.qb.#.b[#]'.  Findings aggregate by class (a 4.9M-wire
    circuit must report families, not four million lines), and witness
    errors reuse it as the allocation-site name."""
    return _NUM_RE.sub("#", label) if label else "?"


@dataclass
class CircuitFinding:
    rule: str
    where: str  # label class (wire rules) / tag class (constraint rules)
    count: int
    example: str  # one concrete wire or constraint, fully indexed
    msg: str

    def __str__(self) -> str:
        n = f" x{self.count}" if self.count > 1 else ""
        return f"[{self.rule}] {self.where}{n}: {self.msg} (e.g. {self.example})"


class CircuitAuditError(RuntimeError):
    """Raised by the admission gate when a circuit has unwaived findings."""


# ---------------------------------------------------------------------------
# determinism engine

_A_CNZ, _B_CNZ, _C_CNZ = 1, 2, 4


def _pow2_exp(v: int) -> Optional[int]:
    """Exponent k if v == ±2^k mod R (canonical residue), else None."""
    if v and v & (v - 1) == 0:
        return v.bit_length() - 1
    n = R - v
    if n and n & (n - 1) == 0:
        return n.bit_length() - 1
    return None


class _Extraction:
    """One pass over the constraints: flat incidence arrays for the
    fixpoint + everything the cheap rules need."""

    def __init__(self, cs, sources):
        import numpy as np

        n_con = len(cs.constraints)
        inc_con = array("q")
        inc_wire = array("q")
        inc_mask = array("b")
        n_unk = array("l")
        flags = array("b")
        self.pow2lin = set()
        self.bool_wires = set()
        self.dead: List[Tuple[int, str]] = []
        self.dup: List[Tuple[int, int]] = []
        zero_forms: Dict[bytes, List[int]] = {}
        inv_forms: Dict[bytes, List[int]] = {}
        seen: Dict[bytes, int] = {}
        constrained = np.zeros(cs.num_wires, dtype=bool)
        constrained[0] = True
        blake = hashlib.blake2b

        def side_bytes(d) -> bytes:
            buf = bytearray()
            for w in sorted(d):
                v = d[w] % R
                if v:
                    buf += w.to_bytes(8, "little") + v.to_bytes(32, "little")
            return bytes(buf)

        for idx, con in enumerate(cs.constraints):
            a, b, c = con.a, con.b, con.c
            masks: Dict[int, int] = {}
            for d, m in ((a, 1), (b, 2), (c, 4)):
                for w, v in d.items():
                    if w and v % R:
                        masks[w] = masks.get(w, 0) | m
            for w in masks:
                constrained[w] = True
            aw = [w for w, m in masks.items() if m & 1]
            bw = [w for w, m in masks.items() if m & 2]
            cw = [w for w, m in masks.items() if m & 4]
            av, bv, cv = a.get(0, 0) % R, b.get(0, 0) % R, c.get(0, 0) % R
            fl = 0
            if not aw and av:
                fl |= _A_CNZ
            if not bw and bv:
                fl |= _B_CNZ
            if not cw and cv:
                fl |= _C_CNZ
            # ---- dead / duplicate
            if not cw and not cv and ((not aw and not av) or (not bw and not bv)):
                self.dead.append((idx, "0 = 0 (one product side identically zero)"))
            elif not aw and not bw and not cw:
                if av * bv % R == cv:
                    self.dead.append((idx, "constant identity (no wires)"))
                else:
                    self.dead.append(
                        (idx, "constant constraint that is NEVER satisfiable")
                    )
            sa, sb, sc = side_bytes(a), side_bytes(b), side_bytes(c)
            key = blake(min(sa, sb) + b"\x00" + max(sa, sb) + b"\x00" + sc,
                        digest_size=16).digest()
            first = seen.setdefault(key, idx)
            if first != idx:
                self.dup.append((idx, first))
            # ---- booleanity pattern: w*(w-1) = 0
            if (
                aw
                and len(masks) == 1
                and aw == bw
                and not cw
                and not cv
                and not av
                and a.get(aw[0], 0) % R == 1
                and b.get(aw[0], 0) % R == 1
                and bv == R - 1
            ):
                self.bool_wires.add(aw[0])
            # ---- IsZero case pair (lemma A): L*out = 0  +  L*inv = 1 - out.
            # Case analysis makes `out` unique once L's wires are known
            # (L=0 forces out=1 via the inv row; L!=0 forces out=0 via the
            # zero row) — the one circomlib shape the linear rules miss.
            if len(bw) == 1 and b.get(bw[0], 0) % R == 1 and not bv:
                wb = bw[0]
                if not cw and not cv:
                    zero_forms.setdefault(sa, []).append(wb)
            if len(bw) == 1 and len(cw) == 1 and cv == 1 and c.get(cw[0], 0) % R == R - 1:
                inv_forms.setdefault(sa, []).append(cw[0])
            # ---- boolean power-of-two decomposition candidates (lemma B)
            if not bw and bv and not cw and aw:
                ok = True
                for w in aw:
                    if _pow2_exp(a[w] % R) is None:
                        ok = False
                        break
                if ok:
                    self.pow2lin.add(idx)
            # ---- incidence
            nk = 0
            for w, m in masks.items():
                inc_con.append(idx)
                inc_wire.append(w)
                inc_mask.append(m)
                if not sources[w]:
                    nk += 1
            n_unk.append(nk)
            flags.append(fl)

        # lemma A synthetic edges: target determined once all L wires are
        syn_rows: List[Tuple[int, List[int]]] = []
        for sa, outs in zero_forms.items():
            invs = inv_forms.get(sa)
            if not invs:
                continue
            for w_o in set(outs) & set(invs):
                # recover L's wires from the serialized side
                srcs = [
                    int.from_bytes(sa[i : i + 8], "little")
                    for i in range(0, len(sa), 40)
                ]
                srcs = [w for w in srcs if w]
                if w_o in srcs:
                    continue
                syn_rows.append((w_o, srcs))
        self.n_real = n_con
        for j, (w_o, srcs) in enumerate(syn_rows):
            idx = n_con + j
            nk = 0 if sources[w_o] else 1
            for w in srcs:
                inc_con.append(idx)
                inc_wire.append(w)
                inc_mask.append(1)
                if not sources[w]:
                    nk += 1
            inc_con.append(idx)
            inc_wire.append(w_o)
            inc_mask.append(4)
            n_unk.append(nk)
            flags.append(0)

        self.inc_con = np.frombuffer(inc_con, dtype=np.int64)
        self.inc_wire = np.frombuffer(inc_wire, dtype=np.int64)
        self.inc_mask = np.frombuffer(inc_mask, dtype=np.int8)
        self.n_unk = np.array(n_unk, dtype=np.int64)
        self.flags = np.array(flags, dtype=np.int8)
        self.constrained = constrained


def _determinism(cs, exc: "_Extraction", sources) -> "np.ndarray":
    """The fixpoint: returns the boolean `determined` array."""
    import numpy as np

    determined = sources.copy()
    inc_con, inc_wire, inc_mask = exc.inc_con, exc.inc_wire, exc.inc_mask
    n_unk, flags = exc.n_unk, exc.flags
    n_total = n_unk.shape[0]

    order_w = np.argsort(inc_wire, kind="stable")
    ws = inc_wire[order_w]
    w_start = np.searchsorted(ws, np.arange(cs.num_wires))
    w_end = np.searchsorted(ws, np.arange(cs.num_wires), side="right")
    order_c = np.argsort(inc_con, kind="stable")
    csort = inc_con[order_c]
    c_start = np.searchsorted(csort, np.arange(n_total))
    c_end = np.searchsorted(csort, np.arange(n_total), side="right")

    newly: List[int] = []

    def try_determine(con: int) -> None:
        w = -1
        m = 0
        for r in order_c[c_start[con] : c_end[con]]:
            wr = inc_wire[r]
            if not determined[wr]:
                w, m = int(wr), int(inc_mask[r])
                break
        if w < 0:
            return
        f = flags[con]
        if m == 4:
            ok = True  # linear in C (also the lemma-A synthetic target)
        elif m == 1:
            ok = bool(f & (_B_CNZ | _C_CNZ))
        elif m == 2:
            ok = bool(f & (_A_CNZ | _C_CNZ))
        else:
            ok = False  # occurs quadratically (e.g. booleanity) — no
        if ok:
            determined[w] = True
            newly.append(w)

    bool_wires = exc.bool_wires

    def try_pow2(con: int) -> None:
        a = cs.constraints[con].a
        unk = [(w, v % R) for w, v in a.items() if w and v % R and not determined[w]]
        if not unk:
            return
        exps = set()
        for w, v in unk:
            if w not in bool_wires:
                return
            e = _pow2_exp(v)
            if e is None or e > 252 or e in exps:
                return
            exps.add(e)
        # distinct ±2^k coefficients over boolean unknowns: any two
        # assignments differ at the highest differing bit, so the linear
        # form is injective — all unknowns uniquely determined.
        for w, _ in unk:
            determined[w] = True
            newly.append(w)

    def gather_rows(front: "np.ndarray") -> "np.ndarray":
        s = w_start[front]
        ln = w_end[front] - s
        tot = int(ln.sum())
        if not tot:
            return np.empty(0, dtype=np.int64)
        offs = np.cumsum(ln) - ln
        pos = np.arange(tot)
        within = pos - np.repeat(offs, ln)
        return order_w[np.repeat(s, ln) + within]

    def rank_closure(c_side_only: bool) -> None:
        """Lemma C: residual linear systems (the BigMultNoCarry
        Vandermonde shape).  Row scaling by a determined-nonzero factor
        never changes rank, so b-side values need not be known.  The
        c_side_only pass runs first: product-output systems (a,b fully
        determined) cluster tightly (one Vandermonde block per bigmul),
        while the general pass lets ripple-carry rows union everything
        into one oversized — skipped — cluster."""
        stalled = np.flatnonzero(n_unk[: exc.n_real] > 0)
        eqs: List[Dict[int, int]] = []
        for con in stalled:
            rec = cs.constraints[int(con)]
            a, b, c = rec.a, rec.b, rec.c
            ua = [w for w, v in a.items() if w and v % R and not determined[w]]
            ub = [w for w, v in b.items() if w and v % R and not determined[w]]
            uc = [w for w, v in c.items() if w and v % R and not determined[w]]
            f = flags[con]
            if uc and not ua and not ub:
                eqs.append({w: c[w] % R for w in uc})
            elif c_side_only:
                continue
            elif ua and not ub and not uc and (f & (_B_CNZ | _C_CNZ)):
                eqs.append({w: a[w] % R for w in ua})
            elif ub and not ua and not uc and (f & (_A_CNZ | _C_CNZ)):
                eqs.append({w: b[w] % R for w in ub})
        if not eqs:
            return
        parent: Dict[int, int] = {}

        def find(x: int) -> int:
            r = x
            while parent.get(r, r) != r:
                r = parent[r]
            while parent.get(x, x) != x:
                parent[x], x = r, parent[x]
            return r

        for eq in eqs:
            it = iter(eq)
            first = find(next(it))
            for w in it:
                parent[find(w)] = first
        clusters: Dict[int, List[Dict[int, int]]] = {}
        wires_of: Dict[int, set] = {}
        for eq in eqs:
            root = find(next(iter(eq)))
            clusters.setdefault(root, []).append(eq)
            wires_of.setdefault(root, set()).update(eq)
        for root, rows in clusters.items():
            wires = wires_of[root]
            if len(wires) > 96 or len(rows) < len(wires):
                continue
            # sparse forward elimination mod R.  Every pivot row is
            # stored under its minimum wire, so reducing a row at its
            # smallest pivot-overlapping wire only introduces larger
            # wires — the row's smallest overlap strictly increases and
            # the loop terminates.  Each surviving row is nonzero after
            # reduction by ALL current pivots, hence independent of
            # them: len(pivots) == column count proves full rank.
            pivots: Dict[int, Dict[int, int]] = {}
            for eq in rows:
                row = dict(eq)
                while row:
                    common = [w for w in row if w in pivots]
                    if not common:
                        break
                    w = min(common)
                    piv = pivots[w]
                    factor = row[w] * pow(piv[w], R - 2, R) % R
                    for pw, pv in piv.items():
                        nv = (row.get(pw, 0) - factor * pv) % R
                        if nv:
                            row[pw] = nv
                        else:
                            row.pop(pw, None)
                if row:
                    pivots[min(row)] = row
                if len(pivots) == len(wires):
                    break
            if len(pivots) == len(wires):  # full column rank: unique solve
                for w in wires:
                    if not determined[w]:
                        determined[w] = True
                        newly.append(w)

    # round 0: everything already single-unknown or decomposition-ready
    for con in np.flatnonzero(n_unk == 1):
        try_determine(int(con))
    for con in sorted(exc.pow2lin):
        if n_unk[con] > 0:
            try_pow2(con)
    frontier = np.array(sorted(set(newly)), dtype=np.int64)
    newly = []
    pow2lin = exc.pow2lin
    while True:
        while frontier.size:
            rows = gather_rows(frontier)
            cons = inc_con[rows]
            np.subtract.at(n_unk, cons, 1)
            uniq = np.unique(cons)
            for con in uniq[n_unk[uniq] == 1]:
                try_determine(int(con))
            for con in uniq:
                ci = int(con)
                if ci in pow2lin and n_unk[ci] > 0:
                    try_pow2(ci)
            frontier = np.array(sorted(set(newly)), dtype=np.int64)
            newly = []
        rank_closure(c_side_only=True)
        if not newly:
            rank_closure(c_side_only=False)
        if not newly:
            break
        frontier = np.array(sorted(set(newly)), dtype=np.int64)
        newly = []
    return determined


# ---------------------------------------------------------------------------
# digest + cache

def circuit_digest(cs) -> str:
    """Structural digest of a built circuit: constraints, public count,
    width bounds + demands, declared inputs, hook wiring, and the waiver
    table (a waiver edit must invalidate cached reports).  16 hex."""
    h = hashlib.sha256()
    h.update(f"v{AUDIT_VERSION}|{cs.num_wires}|{cs.num_public}|".encode())
    for con in cs.constraints:
        for d in (con.a, con.b, con.c):
            for w in sorted(d):
                v = d[w] % R
                if v:
                    h.update(w.to_bytes(8, "little"))
                    h.update(v.to_bytes(32, "little"))
            h.update(b"\xfe")
        # the tag IS audit-relevant structure: dead/duplicate waivers
        # match on it, so a tag edit must invalidate cached verdicts
        h.update(con.tag.encode())
        h.update(b"\xff")
    # labels likewise: waiver globs and finding attribution key on them,
    # so a label-only rename must rebuild (a stale cached "clean" would
    # otherwise ADMIT a circuit whose waivers no longer match)
    for w in sorted(cs.labels):
        h.update(f"L{w}:{cs.labels[w]};".encode())
    for w in sorted(cs.wire_width):
        h.update(f"W{w}:{cs.wire_width[w]};".encode())
    for w, bits, site in cs.width_demands:
        h.update(f"D{w}:{bits}:{site};".encode())
    for w in sorted(cs.input_wires):
        h.update(f"I{w};".encode())
    for hook in cs.hooks:
        h.update(type(hook).__name__.encode())
        h.update(array("q", hook.outs).tobytes())
        h.update(b"<")
        h.update(array("q", hook.ins).tobytes())
    for (rule, glob), why in sorted(cs.audit_waivers.items()):
        h.update(f"X{rule}|{glob}|{why};".encode())
    return h.hexdigest()[:16]


def _cache_dir() -> str:
    root = os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
    return os.path.join(root, ".bench_cache")


def _cache_path(name: str, digest: str, cache_dir: Optional[str]) -> str:
    safe = re.sub(r"[^A-Za-z0-9_.-]", "_", name)
    return os.path.join(cache_dir or _cache_dir(), f"circuit_audit_{safe}_{digest}.json")


# ---------------------------------------------------------------------------
# the audit

def analyze(cs, declared_n_public: Optional[int] = None, vk=None) -> Tuple[
    List[Tuple[str, str, str, str]], Dict[str, int]
]:
    """Run every rule; returns (raw findings, stats).  Raw findings are
    (rule, match_text, example_desc, family_msg) per wire/constraint —
    waiver resolution and aggregation happen in audit_circuit."""
    import numpy as np

    sources = np.zeros(cs.num_wires, dtype=bool)
    sources[0] = True
    sources[1 : 1 + cs.num_public] = True
    for w in cs.input_wires:
        sources[w] = True

    exc = _Extraction(cs, sources)
    raw: List[Tuple[str, str, str]] = []
    labels = cs.labels

    def wdesc(w: int) -> str:
        return f"wire {w} '{labels.get(w, '')}'"

    # unconstrained-wire (wire 0 and an untouched tail would both be
    # allocator bugs; every allocated wire must appear somewhere)
    hooked = np.zeros(cs.num_wires, dtype=np.int32)
    for hook in cs.hooks:
        for o in hook.outs:
            hooked[o] += 1
    unconstrained = np.flatnonzero(~exc.constrained)
    for w in unconstrained:
        w = int(w)
        kind = (
            "assigned by a witness hook"
            if hooked[w]
            else ("a public signal" if w <= cs.num_public else
                  ("a declared input" if w in cs.input_wires else "never assigned"))
        )
        raw.append((
            "unconstrained-wire",
            labels.get(w, ""),
            f"{wdesc(w)} ({kind})",
            "appears in no constraint — the prover may substitute any value",
        ))

    # determinism
    determined = _determinism(cs, exc, sources)
    undet = np.flatnonzero(~determined & exc.constrained)
    for w in undet:
        w = int(w)
        raw.append((
            "determinism",
            labels.get(w, ""),
            wdesc(w),
            "not uniquely determined by the inputs — an attacker may "
            "choose it freely among satisfying witnesses",
        ))

    # bool-width
    for w, bits, site in cs.width_demands:
        bound = cs.wire_width.get(w, 254)
        if bound > bits:
            raw.append((
                "bool-width",
                labels.get(w, ""),
                f"{wdesc(w)} demanded at site '{site}'",
                f"assumed < 2^{bits} but the strongest recorded bound is "
                f"2^{bound} — the unbounded-comparator forgery class",
            ))

    # dead / duplicate (match on the constraint TAG)
    for idx, msg in exc.dead:
        tag = cs.constraints[idx].tag
        raw.append(("dead-constraint", tag, f"constraint {idx} ({tag!r})", msg))
    for idx, first in exc.dup:
        tag = cs.constraints[idx].tag
        raw.append((
            "duplicate-constraint",
            tag,
            f"constraint {idx} ({tag!r}) == constraint {first} "
            f"({cs.constraints[first].tag!r})",
            "byte-identical constraint — wasted prover work",
        ))

    # hook-coverage
    for w in np.flatnonzero(exc.constrained):
        w = int(w)
        if w == 0:
            continue
        n = int(hooked[w])
        if w <= cs.num_public:
            # publics are seeded from public_inputs BEFORE hooks run: a
            # hook here overwrites the verifier-supplied value and every
            # proof fails pairing verification with no attribution
            if n:
                raw.append((
                    "hook-coverage",
                    labels.get(w, ""),
                    f"{wdesc(w)} (public, {n} hooks)",
                    "a public signal assigned by a witness hook — the hook "
                    "silently overwrites the verifier-supplied value",
                ))
            continue
        if w in cs.input_wires:
            if n:
                raw.append((
                    "hook-coverage",
                    labels.get(w, ""),
                    f"{wdesc(w)} (input, {n} hooks)",
                    "both a declared input and hook-assigned — the hook "
                    "silently overwrites the seed",
                ))
            continue
        if n == 0:
            raw.append((
                "hook-coverage",
                labels.get(w, ""),
                wdesc(w),
                "constrained but no hook or input seed assigns it — "
                "witness() would fail at runtime",
            ))
        elif n > 1:
            raw.append((
                "hook-coverage",
                labels.get(w, ""),
                f"{wdesc(w)} ({n} hooks)",
                "assigned by multiple hooks — later hooks silently "
                "overwrite earlier ones",
            ))

    # public-layout
    if declared_n_public is not None and cs.num_public != declared_n_public:
        raw.append((
            "public-layout",
            "n_public",
            f"built n_public = {cs.num_public}",
            f"the declared on-chain layout expects {declared_n_public} "
            "public signals (docs/EVM_PARITY.md)",
        ))
    if vk is not None:
        n_ic = len(vk.ic)
        if n_ic != cs.num_public + 1:
            raw.append((
                "public-layout",
                "vk.ic",
                f"len(vk.IC) = {n_ic}",
                f"exported verifier bakes {n_ic} IC points for "
                f"{cs.num_public} publics (IC must be n_public+1)",
            ))

    stats = {
        "n_wires": cs.num_wires,
        "n_public": cs.num_public,
        "n_constraints": len(cs.constraints),
        "n_hooks": len(cs.hooks),
        "determined": int(determined.sum()),
        "undetermined": int(undet.shape[0]),
        "width_demands": len(cs.width_demands),
    }
    return raw, stats


def _resolve_waivers(cs, raw) -> Tuple[List[CircuitFinding], List[Dict]]:
    """Split raw findings into aggregated unwaived findings and per-
    waiver usage records (pattern, why, count)."""
    pats: Dict[str, List[List]] = {}
    for (rule, glob), why in cs.audit_waivers.items():
        pats.setdefault(rule, []).append(
            [re.compile(fnmatch.translate(glob)), glob, why, 0]
        )
    agg: Dict[Tuple[str, str], CircuitFinding] = {}
    for rule, match_text, example, msg in raw:
        entries = pats.get(rule)
        hit = None
        if entries:
            for e in entries:
                if e[0].match(match_text):
                    hit = e
                    break
            if hit is not None:
                hit[3] += 1
                # move-to-front: waived families are huge and homogeneous
                if entries[0] is not hit:
                    entries.remove(hit)
                    entries.insert(0, hit)
                continue
        cls = label_class(match_text)
        key = (rule, cls)
        cur = agg.get(key)
        if cur is None:
            agg[key] = CircuitFinding(rule, cls, 1, example, msg)
        else:
            cur.count += 1
    findings = sorted(agg.values(), key=lambda f: (f.rule, f.where))
    waived = [
        {"rule": rule, "pattern": e[1], "why": e[2], "count": e[3]}
        for rule, entries in sorted(pats.items())
        for e in sorted(entries, key=lambda x: x[1])
        if e[3]
    ]
    return findings, waived


# audits performed in this process, surfaced by utils.metrics.run_manifest
# (the precomp_manifest pattern): name -> summary dict
_audit_log: Dict[str, Dict] = {}


def audit_manifest() -> Dict[str, Dict]:
    return dict(_audit_log)


def audit_circuit(
    cs,
    name: Optional[str] = None,
    declared_n_public: Optional[int] = None,
    vk=None,
    use_cache: bool = True,
    cache_dir: Optional[str] = None,
) -> Dict:
    """Audit a built circuit.  Returns the report dict (JSON-able); the
    report is cached under .bench_cache keyed by the structural circuit
    digest, so re-admitting an unchanged circuit costs one digest pass."""
    name = name or cs.name
    t0 = time.perf_counter()
    digest = circuit_digest(cs)
    path = _cache_path(name, digest, cache_dir)
    if vk is not None:
        use_cache = False  # the vk IC check is not part of the digest key
    if use_cache and os.path.exists(path):
        try:
            with open(path) as f:
                report = json.load(f)
        except (OSError, ValueError):
            report = None
        if (
            report is not None
            and report.get("digest") == digest
            and report.get("audit_version") == AUDIT_VERSION
            and report.get("declared_n_public") == declared_n_public
        ):
            report["source"] = "cache"
            _audit_log[name] = _summary(report)
            return report
    raw, stats = analyze(cs, declared_n_public=declared_n_public, vk=vk)
    findings, waived = _resolve_waivers(cs, raw)
    report = {
        "circuit": name,
        "digest": digest,
        "audit_version": AUDIT_VERSION,
        "declared_n_public": declared_n_public,
        **stats,
        "findings": [asdict(f) for f in findings],
        "unwaived": sum(f.count for f in findings),
        "waived": sum(w["count"] for w in waived),
        "waivers_used": waived,
        "audit_s": round(time.perf_counter() - t0, 3),
        "source": "fresh",
    }
    if use_cache:
        try:
            os.makedirs(os.path.dirname(path), exist_ok=True)
            tmp = f"{path}.tmp.{os.getpid()}"
            with open(tmp, "w") as f:
                json.dump(report, f, indent=1)
            os.replace(tmp, path)
        except OSError:
            pass  # cache is best-effort; the report itself is the product
    _audit_log[name] = _summary(report)
    return report


def _summary(report: Dict) -> Dict:
    return {
        "digest": report["digest"],
        "unwaived": report["unwaived"],
        "waived": report["waived"],
        "audit_s": report["audit_s"],
        "source": report["source"],
    }


def require_clean(report: Dict) -> Dict:
    """The admission gate: raise (naming the findings) unless the audit
    reports zero unwaived findings."""
    if report["unwaived"]:
        lines = "\n  ".join(
            str(CircuitFinding(**f)) for f in report["findings"][:10]
        )
        err = CircuitAuditError(
            f"circuit {report['circuit']!r} REFUSED admission: "
            f"{report['unwaived']} unwaived audit finding(s) "
            f"({len(report['findings'])} families):\n  {lines}"
        )
        err.report = report  # machine consumers (lint --json) keep the evidence
        raise err
    return report
