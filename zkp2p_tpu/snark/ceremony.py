"""Groth16 phase-2 ceremony operations: contribute / beacon / verify.

The reference's trust model rests on the phase-2 MPC its scripts drive
with snarkjs (`dizkus-scripts/3_gen_both_zkeys.sh:18-65`: two
`zkey contribute` rounds + a `zkey beacon` + `zkey verify`;
`circuit/server-scripts/generate_keys_phase2_groth16.sh:55-61`).  This
module re-builds those operations natively over our zkey format
(`formats/zkey.py`), with the BGM17 update/proof scheme snarkjs uses:

  contribute:  pick delta'; delta1 *= delta', delta2 *= delta',
               c_query[i] *= 1/delta', h_query[i] *= 1/delta'; publish a
               proof of knowledge (s·G1, delta'·s·G1, delta'·SP) where
               SP = hash-to-G2 of the running transcript challenge.
  beacon:      same update with delta' derived from a public beacon
               value by 2^iter_exp iterated hashes — verifiers re-derive
               it, so the final contribution is unriggable.
  verify:      per-contribution pairing checks (the PoK ratio test and
               deltaAfter = delta'·deltaBefore), delta1/delta2
               consistency, exact re-derivation of beacon deltas, and a
               random-linear-combination pairing check that the C and H
               queries of the final key are the initial ones scaled by
               the accumulated 1/delta' — using the identity
               e(C_i/d, d·D2) = e(C_i, D2).

Hashes are blake2b-512 (snarkjs's choice for ceremony transcripts).
Byte-level parity with snarkjs section-10 records is NOT claimed (no
snarkjs in this environment to diff against); the formats round-trip
through our own reader and the cryptographic checks are equivalent.
"""

from __future__ import annotations

import hashlib
import secrets
from dataclasses import replace
from typing import List, Optional, Tuple

from ..curve.host import (
    G1_GENERATOR,
    G2_GENERATOR,
    G1Point,
    G2Point,
    TWIST_B,
    g1_is_on_curve,
    g1_mul,
    g1_neg,
    g2_is_on_curve,
    g2_mul,
)
from ..field.bn254 import P, R
from ..field.tower import Fq2
from ..formats.zkey import Contribution, MpcParams, ZkeyData, read_zkey, write_zkey_data
from ..pairing.pairing import pairing_product_is_one

# ------------------------------------------------------------- hash-to-G2

# G2 twist cofactor: the sextic twist E'(Fp2) used for BN254 G2 has
# order h2*r with h2 = 2p - r (NOT the (p^2+1-t2)/r of E(Fp2) itself —
# that is the other twist order and leaves points outside the
# r-torsion).  Multiplying a curve point by h2 lands it in the subgroup
# the pairing is defined on; validated by the subgroup assertion in
# hash_to_g2 and the cofactor probe in tests/test_ceremony.py.
G2_COFACTOR = 2 * P - R


def _fq_sqrt(a: int) -> Optional[int]:
    """Square root in Fq (p ≡ 3 mod 4): a^((p+1)/4), validated."""
    r_ = pow(a, (P + 1) // 4, P)
    return r_ if r_ * r_ % P == a % P else None


def _fq2_sqrt(a: Fq2) -> Optional[Fq2]:
    """Square root in Fq2 = Fq[u]/(u^2+1) via the norm trick."""
    if a.c0 == 0 and a.c1 == 0:
        return Fq2(0, 0)
    norm = (a.c0 * a.c0 + a.c1 * a.c1) % P
    alpha = _fq_sqrt(norm)
    if alpha is None:
        return None
    inv2 = pow(2, P - 2, P)
    lam = (a.c0 + alpha) * inv2 % P
    x0 = _fq_sqrt(lam)
    if x0 is None:
        lam = (a.c0 - alpha) * inv2 % P
        x0 = _fq_sqrt(lam)
        if x0 is None:
            return None
    x1 = a.c1 * inv2 % P * pow(x0, P - 2, P) % P
    cand = Fq2(x0, x1)
    return cand if cand * cand == a else None


def hash_to_g2(seed: bytes) -> G2Point:
    """Deterministic try-and-increment map to the r-torsion of the twist
    (the SP point of the BGM17 proof of knowledge)."""
    ctr = 0
    while True:
        h = hashlib.blake2b(seed + ctr.to_bytes(4, "little"), digest_size=64).digest()
        x = Fq2(int.from_bytes(h[:32], "little") % P, int.from_bytes(h[32:], "little") % P)
        y2 = x * x * x + TWIST_B
        y = _fq2_sqrt(y2)
        ctr += 1
        if y is None:
            continue
        pt = (x, y)
        assert g2_is_on_curve(pt)
        pt = g2_mul(pt, G2_COFACTOR)
        if pt is not None:  # cofactor clearing can hit infinity; retry
            assert g2_mul(pt, R) is None, "cofactor clearing left the r-torsion"
            return pt


# ------------------------------------------------------------- transcript


def _challenge(mpc: MpcParams, upto: int) -> bytes:
    """The challenge a contributor at position `upto` signs into its SP
    point: circuit hash chained through every prior transcript."""
    h = hashlib.blake2b(digest_size=64)
    h.update(mpc.cs_hash)
    for c in mpc.contributions[:upto]:
        h.update(c.transcript)
    return h.digest()


def _g1_raw(pt: G1Point) -> bytes:
    if pt is None:
        return b"\x00" * 64
    return pt[0].to_bytes(32, "little") + pt[1].to_bytes(32, "little")


def _g2_raw(pt: G2Point) -> bytes:
    if pt is None:
        return b"\x00" * 128
    x, y = pt
    return b"".join(v.to_bytes(32, "little") for v in (x.c0, x.c1, y.c0, y.c1))


def _scale_points(points, k: int):
    """k * P_i for a shared k: native batch (NAF once + batched affine
    normalization, csrc g1_scale_batch) when available — the op runs
    over every C and H query point, ~1.5M for the flagship key — else
    the Python Jacobian path."""
    from ..native.lib import g1_scale_batch

    res = g1_scale_batch(list(points), k)
    if res is not None:
        return res
    return [None if p is None else g1_mul(p, k) for p in points]


def _msm_points(points, scalars):
    """Random-combination MSM for verify_chain: native Pippenger when
    available, Python fallback otherwise."""
    from ..curve.host import g1_add
    from ..native.lib import g1_msm

    res = g1_msm(list(points), list(scalars))
    if res is not False:
        return res
    acc = None
    for p, s in zip(points, scalars):
        acc = g1_add(acc, g1_mul(p, s))
    return acc


def _scale_queries(z: ZkeyData, delta_prime: int) -> ZkeyData:
    """Apply a contribution's delta' to the key material."""
    dinv = pow(delta_prime, R - 2, R)
    return replace(
        z,
        delta_1=g1_mul(z.delta_1, delta_prime),
        delta_2=g2_mul(z.delta_2, delta_prime),
        c_query=_scale_points(z.c_query, dinv),  # None holes pass through
        h_query=_scale_points(z.h_query, dinv),
    )


def _append_contribution(z: ZkeyData, delta_prime: int, kind: int, name: str,
                         beacon_hash: bytes = b"", beacon_iter_exp: int = 0) -> ZkeyData:
    mpc = z.mpc or MpcParams(cs_hash=b"\x00" * 64, contributions=[])
    challenge = _challenge(mpc, len(mpc.contributions))
    sp = hash_to_g2(challenge)
    s = 1 + secrets.randbelow(R - 1)
    g1_s = g1_mul(G1_GENERATOR, s)
    g1_sx = g1_mul(G1_GENERATOR, s * delta_prime % R)
    g2_spx = g2_mul(sp, delta_prime)
    z2 = _scale_queries(z, delta_prime)
    transcript = hashlib.blake2b(
        challenge + _g1_raw(z2.delta_1) + _g1_raw(g1_s) + _g1_raw(g1_sx) + _g2_raw(g2_spx),
        digest_size=64,
    ).digest()
    contrib = Contribution(
        delta_after=z2.delta_1,
        pok_g1_s=g1_s,
        pok_g1_sx=g1_sx,
        pok_g2_spx=g2_spx,
        transcript=transcript,
        kind=kind,
        name=name,
        beacon_hash=beacon_hash,
        beacon_iter_exp=beacon_iter_exp,
    )
    return replace(z2, mpc=MpcParams(mpc.cs_hash, mpc.contributions + [contrib]))


# ------------------------------------------------------------ public ops


def circuit_hash(z: ZkeyData) -> bytes:
    """64-byte digest binding the phase-2 transcript to the circuit: the
    non-delta key material (everything a contribution must not touch)."""
    h = hashlib.blake2b(digest_size=64)
    h.update(_g1_raw(z.alpha_1) + _g1_raw(z.beta_1) + _g2_raw(z.beta_2) + _g2_raw(z.gamma_2))
    for pt in z.ic + z.a_query + z.b1_query:
        h.update(_g1_raw(pt))
    for pt2 in z.b2_query:
        h.update(_g2_raw(pt2))
    for m, row, wire, value in z.coeffs:
        h.update(m.to_bytes(1, "little") + row.to_bytes(4, "little") + wire.to_bytes(4, "little") + value.to_bytes(32, "little"))
    return h.digest()


def contribute(zkey_in: str, zkey_out: str, entropy: bytes, name: str = "") -> ZkeyData:
    """`snarkjs zkey contribute` equivalent: one interactive phase-2
    contribution with delta' drawn from caller entropy + fresh CSPRNG."""
    z = read_zkey(zkey_in)
    if z.mpc is None or z.mpc.cs_hash == b"\x00" * 64:
        z = replace(z, mpc=MpcParams(cs_hash=circuit_hash(z), contributions=(z.mpc.contributions if z.mpc else [])))
    seed = hashlib.blake2b(entropy + secrets.token_bytes(32), digest_size=64).digest()
    delta_prime = 1 + int.from_bytes(seed, "little") % (R - 1)
    z2 = _append_contribution(z, delta_prime, kind=0, name=name)
    write_zkey_data(zkey_out, z2)
    return z2


# Beacon iteration ceiling: snarkjs caps numIterationsExp at 63; anything
# past ~32 is already months of hashing, and verify_chain re-derives the
# chain from FILE-CONTROLLED bytes — an uncapped exponent is a DoS knob.
MAX_BEACON_ITER_EXP = 32


def beacon_delta(beacon_hash: bytes, iter_exp: int) -> int:
    """The deterministic beacon delta': 2^iter_exp iterated blake2b over
    the public beacon value, reduced into Fr* (re-derived by verifiers)."""
    if not 0 <= iter_exp <= MAX_BEACON_ITER_EXP:
        raise ValueError(f"beacon iter_exp {iter_exp} outside [0, {MAX_BEACON_ITER_EXP}]")
    h = beacon_hash
    for _ in range(1 << iter_exp):
        h = hashlib.blake2b(h, digest_size=64).digest()
    return 1 + int.from_bytes(h, "little") % (R - 1)


def beacon(zkey_in: str, zkey_out: str, beacon_hash: bytes, iter_exp: int = 10,
           name: str = "final beacon") -> ZkeyData:
    """`snarkjs zkey beacon` equivalent: the closing contribution whose
    delta' anyone can re-derive from the public beacon value."""
    z = read_zkey(zkey_in)
    if z.mpc is None or z.mpc.cs_hash == b"\x00" * 64:
        z = replace(z, mpc=MpcParams(cs_hash=circuit_hash(z), contributions=(z.mpc.contributions if z.mpc else [])))
    # normalize to the 64-byte stored form FIRST: verifiers re-derive
    # delta' from the stored bytes, so derivation must use them too
    beacon_hash = beacon_hash.ljust(64, b"\x00")[:64]
    delta_prime = beacon_delta(beacon_hash, iter_exp)
    z2 = _append_contribution(z, delta_prime, kind=1, name=name,
                              beacon_hash=beacon_hash, beacon_iter_exp=iter_exp)
    write_zkey_data(zkey_out, z2)
    return z2


def verify_chain(zkey_initial: str, zkey_final: str) -> Tuple[bool, List[str]]:
    """`snarkjs zkey verify` equivalent against a trusted initial key
    (the post-setup, zero-contribution zkey).  Returns (ok, log)."""
    zi = read_zkey(zkey_initial)
    zf = read_zkey(zkey_final)
    log: List[str] = []

    def fail(msg: str) -> Tuple[bool, List[str]]:
        log.append(f"FAIL: {msg}")
        return False, log

    # 1. the contribution-invariant material must be untouched
    if circuit_hash(zi) != circuit_hash(zf):
        return fail("circuit material (alpha/beta/gamma/IC/A/B/coeffs) differs")
    mpc = zf.mpc
    if mpc is None:
        return fail("final zkey has no MPC section")
    if mpc.cs_hash != circuit_hash(zi):
        return fail("cs_hash does not bind to the initial circuit")
    log.append(f"circuit hash bound; {len(mpc.contributions)} contribution(s)")

    # point validation BEFORE any pairing work: off-curve or
    # out-of-subgroup points make the Miller loop a value an attacker
    # can search over (invalid-curve / small-subgroup attacks on the
    # PoK checks).  G1 has cofactor 1 so on-curve == in-subgroup; G2
    # needs the explicit r-torsion check.
    def g1_ok(pt) -> bool:
        return pt is not None and g1_is_on_curve(pt)

    def g2_ok(pt) -> bool:
        return pt is not None and g2_is_on_curve(pt) and g2_mul(pt, R) is None

    for i, c in enumerate(mpc.contributions):
        if not (g1_ok(c.delta_after) and g1_ok(c.pok_g1_s) and g1_ok(c.pok_g1_sx)):
            return fail(f"contribution {i}: G1 point off-curve/infinity")
        if not g2_ok(c.pok_g2_spx):
            return fail(f"contribution {i}: g2_spx off-curve or outside the r-torsion")
        if c.kind == 1 and not 0 <= c.beacon_iter_exp <= MAX_BEACON_ITER_EXP:
            return fail(f"contribution {i}: beacon iter_exp {c.beacon_iter_exp} over cap")
    if not (g1_ok(zf.delta_1) and g2_ok(zf.delta_2)):
        return fail("final delta off-curve or outside the subgroup")

    # 2. walk the delta chain with the PoK pairing checks
    delta_before = zi.delta_1
    for i, c in enumerate(mpc.contributions):
        challenge = _challenge(mpc, i)
        sp = hash_to_g2(challenge)
        # PoK ratio: e(g1_sx, SP) == e(g1_s, g2_spx)  (same delta' on both)
        if not pairing_product_is_one([(c.pok_g1_sx, sp), (g1_neg(c.pok_g1_s), c.pok_g2_spx)]):
            return fail(f"contribution {i}: proof of knowledge rejected")
        # delta update: e(deltaAfter, SP) == e(deltaBefore, g2_spx)
        if not pairing_product_is_one([(c.delta_after, sp), (g1_neg(delta_before), c.pok_g2_spx)]):
            return fail(f"contribution {i}: deltaAfter != delta'*deltaBefore")
        expect_transcript = hashlib.blake2b(
            challenge + _g1_raw(c.delta_after) + _g1_raw(c.pok_g1_s) + _g1_raw(c.pok_g1_sx) + _g2_raw(c.pok_g2_spx),
            digest_size=64,
        ).digest()
        if expect_transcript != c.transcript:
            return fail(f"contribution {i}: transcript hash mismatch")
        if c.kind == 1:
            dp = beacon_delta(c.beacon_hash, c.beacon_iter_exp)
            if g1_mul(delta_before, dp) != c.delta_after:
                return fail(f"contribution {i}: beacon delta does not re-derive")
            log.append(f"contribution {i}: beacon re-derived (iter_exp={c.beacon_iter_exp})")
        else:
            log.append(f"contribution {i}: PoK + delta link verified")
        delta_before = c.delta_after

    if delta_before != zf.delta_1:
        return fail("final delta1 is not the chain head")
    # delta1 (G1) and delta2 (G2) must carry the same scalar:
    # e(delta1, G2) == e(G1, delta2)
    if not pairing_product_is_one([(zf.delta_1, G2_GENERATOR), (g1_neg(G1_GENERATOR), zf.delta_2)]):
        return fail("final delta1/delta2 inconsistent")
    log.append("delta chain closed; delta1/delta2 consistent")

    # 3. query scaling: for random rho, e(sum rho_i C_i^f, delta2^f) must
    # equal e(sum rho_i C_i^0, delta2^0) — the delta' factors cancel.
    def combo(points_f, points_i, tag: str) -> bool:
        # a length mismatch is itself a forgery vector (circuit_hash does
        # not bind domain_size): zip() must never silently truncate
        if len(points_f) != len(points_i):
            return False
        pts_f, pts_i, rhos = [], [], []
        for a, b in zip(points_f, points_i):
            if a is None and b is None:
                continue
            if (a is None) != (b is None):
                return False
            if not g1_ok(a):
                return False
            pts_f.append(a)
            pts_i.append(b)
            rhos.append(secrets.randbelow(1 << 127))
        if not pts_f:
            log.append(f"{tag} query empty on both sides")
            return True
        pf = _msm_points(pts_f, rhos)
        pi_ = _msm_points(pts_i, rhos)
        if (pf is None) != (pi_ is None):
            return False  # one-sided infinity: scalings cannot match
        if pf is None:
            return True  # both infinity under the same rhos
        ok = pairing_product_is_one([(pf, zf.delta_2), (g1_neg(pi_), zi.delta_2)])
        if ok:
            log.append(f"{tag} query scaling verified (randomized)")
        return ok

    if not combo(zf.c_query, zi.c_query, "C"):
        return fail("C query not a consistent delta-scaling of the initial key")
    if not combo(zf.h_query, zi.h_query, "H"):
        return fail("H query not a consistent delta-scaling of the initial key")
    return True, log
