"""Host-side radix-2 NTT over Fr (Python ints).

Oracle + setup-time twin of the TPU NTT kernel (zkp2p_tpu.ops.ntt).  In the
reference this work hides inside snarkjs's `groth16 setup` / `groth16 prove`
(polynomial evaluation for the QAP H polynomial).
"""

from __future__ import annotations

from typing import List

from ..field.bn254 import R, fr_domain_root


def bit_reverse_permute(a: List[int]) -> List[int]:
    n = len(a)
    logn = n.bit_length() - 1
    out = list(a)
    for i in range(n):
        j = int(bin(i)[2:].zfill(logn)[::-1], 2)
        if i < j:
            out[i], out[j] = out[j], out[i]
    return out


def ntt(coeffs: List[int], inverse: bool = False) -> List[int]:
    """In-order DIT NTT.  coeffs -> evaluations over the 2^k domain
    (or back, when inverse=True)."""
    n = len(coeffs)
    assert n & (n - 1) == 0, "size must be a power of two"
    logn = n.bit_length() - 1
    w = fr_domain_root(logn)
    if inverse:
        w = pow(w, R - 2, R)
    a = bit_reverse_permute(coeffs)
    size = 2
    while size <= n:
        wn = pow(w, n // size, R)
        half = size // 2
        for start in range(0, n, size):
            tw = 1
            for j in range(half):
                lo = a[start + j]
                hi = a[start + j + half] * tw % R
                a[start + j] = (lo + hi) % R
                a[start + j + half] = (lo - hi) % R
                tw = tw * wn % R
        size *= 2
    if inverse:
        ninv = pow(n, R - 2, R)
        a = [x * ninv % R for x in a]
    return a


def intt(evals: List[int]) -> List[int]:
    return ntt(evals, inverse=True)


def coset_shift(coeffs: List[int], g: int) -> List[int]:
    """coeffs of f(X) -> coeffs of f(gX)."""
    out = []
    power = 1
    for c in coeffs:
        out.append(c * power % R)
        power = power * g % R
    return out


def evaluate_poly(coeffs: List[int], x: int) -> int:
    acc = 0
    for c in reversed(coeffs):
        acc = (acc * x + c) % R
    return acc
