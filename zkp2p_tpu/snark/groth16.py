"""Groth16 over BN254: setup, host reference prover, verifier.

Pipeline-parity targets in the reference:
  - setup   ~ `snarkjs groth16 setup` + contribute/beacon
              (circuit/scripts/generate_keys_phase2_groth16.sh:11-28,
               dizkus-scripts/3_gen_both_zkeys.sh) — we generate keys
              directly from a seed (a "development ceremony"); the key
              *material* (QAP evaluations at tau) is identical in shape.
  - prove   ~ `snarkjs groth16 prove` / rapidsnark
              (dizkus-scripts/5_gen_proof.sh, 6_gen_proof_rapidsnark.sh).
              The host prover here is the slow reference oracle; the TPU
              prover (zkp2p_tpu.prover) must emit byte-identical proofs
              given the same (witness, r, s).
  - verify  ~ `snarkjs groth16 verify` (5_gen_proof.sh:15-22) and
              contracts/Verifier.sol:340-380 on-chain — same equation:
              e(A,B) = e(alpha,beta) e(vk_x,gamma) e(C,delta).

Public-input wires get dedicated binding rows in the QAP (a_row = x_i,
b_row = 0, c_row = 0) so their A-polynomials are linearly independent —
standard Groth16 hygiene against public-input malleability.
"""

from __future__ import annotations

import hashlib
import secrets
from dataclasses import dataclass
from typing import Dict, List, Optional, Sequence, Tuple

from ..curve.host import (
    G1Point,
    G2Point,
    G1_GENERATOR,
    G2_GENERATOR,
    g1_add,
    g1_gen_mul,
    g1_gen_mul_batch,
    g1_is_on_curve,
    g1_mul,
    g1_msm,
    g1_neg,
    g2_add,
    g2_gen_mul,
    g2_is_on_curve,
    g2_msm,
    g2_mul,
)
from ..field.bn254 import R, fr_domain_root, fr_inv
from ..pairing.pairing import pairing_product_is_one
from .fft_host import coset_shift, intt, ntt
from .r1cs import ConstraintSystem

def coset_gen(log_m: int) -> int:
    """Coset generator for the H-polynomial evaluation domain — the
    snarkjs/rapidsnark convention: AB-C is evaluated on the ODD points of
    the doubled domain (shift = w_{2m}, `groth16_prove`'s batchApplyKey
    with inc = Fr.w[power+1]), so Z(g·w^j) = w_{2m}^m - 1 = -2, a
    constant.  Adopting the identical convention makes imported snarkjs
    `.zkey` section-9 points (formats.zkey) work with no translation."""
    return fr_domain_root(log_m + 1)


def _batch_inv(xs: List[int]) -> List[int]:
    """Montgomery trick: n inverses for 3n muls + one exponentiation."""
    n = len(xs)
    prefix = [1] * (n + 1)
    for i, x in enumerate(xs):
        prefix[i + 1] = prefix[i] * x % R
    inv_all = fr_inv(prefix[n])
    out = [0] * n
    for i in range(n - 1, -1, -1):
        out[i] = prefix[i] * inv_all % R
        inv_all = inv_all * xs[i] % R
    return out


@dataclass
class ProvingKey:
    n_public: int
    domain_size: int
    alpha_1: G1Point
    beta_1: G1Point
    beta_2: G2Point
    delta_1: G1Point
    delta_2: G2Point
    a_query: List[G1Point]  # [A_i(tau)]1 per wire
    b1_query: List[G1Point]  # [B_i(tau)]1 per wire
    b2_query: List[G2Point]  # [B_i(tau)]2 per wire
    c_query: List[Optional[G1Point]]  # [(beta A_i + alpha B_i + C_i)/delta]1, None for public wires
    # Coset-Lagrange H basis (snarkjs zkey section 9 shape), one point per
    # domain element j: [L'_j(tau) * Z(tau) / (delta * Z(g))]1 where L'_j is
    # the Lagrange basis on the coset g*H.  The prover MSMs the raw coset
    # evaluations d_j = (A*B - C)(g w^j) against these — no division by Z,
    # no final iNTT (d_j = H(g w^j) * Z(g), and the Z(g) is folded in here).
    h_query: List[G1Point]


@dataclass
class VerifyingKey:
    n_public: int
    alpha_1: G1Point
    beta_2: G2Point
    gamma_2: G2Point
    delta_2: G2Point
    ic: List[G1Point]  # [(beta A_i + alpha B_i + C_i)/gamma]1 for wires 0..n_public


@dataclass
class Proof:
    a: G1Point
    b: G2Point
    c: G1Point


def _seeded_scalars(seed: str, n: int) -> List[int]:
    out = []
    counter = 0
    while len(out) < n:
        h = hashlib.sha256(f"{seed}:{counter}".encode()).digest()
        v = int.from_bytes(h + hashlib.sha256(h).digest(), "big") % R
        counter += 1
        if v != 0:
            out.append(v)
    return out


def qap_rows(cs: ConstraintSystem) -> List[Tuple[Dict[int, int], Dict[int, int], Dict[int, int]]]:
    """R1CS rows + public-input binding rows (wires 0..n_public)."""
    rows = [(c.a, c.b, c.c) for c in cs.constraints]
    for i in range(cs.num_public + 1):
        rows.append(({i: 1}, {}, {}))
    return rows


def domain_size_for(cs: ConstraintSystem) -> int:
    n = cs.num_constraints + cs.num_public + 1
    m = 1
    while m < n:
        m *= 2
    return m


def setup(cs: ConstraintSystem, seed: str = "zkp2p-tpu-dev") -> Tuple[ProvingKey, VerifyingKey]:
    """Deterministic development setup (tau, alpha, beta, gamma, delta from
    seed).  For production, phase-2 ceremony import comes via
    zkp2p_tpu.formats.zkey (read_zkey -> device_pk_from_zkey) instead."""
    tau, alpha, beta, gamma, delta = _seeded_scalars(seed, 5)
    rows = qap_rows(cs)
    m = domain_size_for(cs)
    n_wires = cs.num_wires

    # Lagrange basis at tau over the 2^k domain:
    #   L_j(tau) = (tau^m - 1) * w^j / (m * (tau - w^j))
    w = fr_domain_root(m.bit_length() - 1)
    z_tau = (pow(tau, m, R) - 1) % R
    minv = fr_inv(m)
    lag = []
    wj = 1
    for _ in range(m):
        lag.append(z_tau * wj % R * minv % R * fr_inv((tau - wj) % R) % R)
        wj = wj * w % R

    a_tau = [0] * n_wires
    b_tau = [0] * n_wires
    c_tau = [0] * n_wires
    for j, (ra, rb, rc) in enumerate(rows):
        lj = lag[j]
        for wi, coeff in ra.items():
            a_tau[wi] = (a_tau[wi] + coeff * lj) % R
        for wi, coeff in rb.items():
            b_tau[wi] = (b_tau[wi] + coeff * lj) % R
        for wi, coeff in rc.items():
            c_tau[wi] = (c_tau[wi] + coeff * lj) % R

    g1, g2 = G1_GENERATOR, G2_GENERATOR
    delta_inv = fr_inv(delta)
    gamma_inv = fr_inv(gamma)

    # fixed-base batches: native C++ when built (csrc/), Python windowed
    # tables otherwise — setup is one g1 mul per wire per query
    a_query = g1_gen_mul_batch(a_tau)
    b1_query = g1_gen_mul_batch(b_tau)
    b2_query = [g2_gen_mul(v) for v in b_tau]

    vals = [(beta * a_tau[i] + alpha * b_tau[i] + c_tau[i]) % R for i in range(n_wires)]
    scaled = [
        v * (gamma_inv if i <= cs.num_public else delta_inv) % R
        for i, v in enumerate(vals)
    ]
    pts = g1_gen_mul_batch(scaled)
    c_query: List[Optional[G1Point]] = [
        None if i <= cs.num_public else pts[i] for i in range(n_wires)
    ]
    ic: List[G1Point] = pts[: cs.num_public + 1]

    # Coset-Lagrange H points: L'_j(tau) = L_j(tau/g) with L_j the standard
    # Lagrange basis on H, so
    #   hcl_j = ((tau')^m - 1) * w^j / (m (tau' - w^j)) * Z(tau)/(delta Z(g))
    # with tau' = tau/g.  One batched inversion for the m denominators.
    g = coset_gen(m.bit_length() - 1)
    tau_p = tau * fr_inv(g) % R
    z_tau_p = (pow(tau_p, m, R) - 1) % R
    z_coset = (pow(g, m, R) - 1) % R  # == -2 by the odd-interleave choice
    scale = z_tau_p * minv % R * z_tau % R * fr_inv(delta * z_coset % R) % R
    wjs = []
    wj = 1
    for _ in range(m):
        wjs.append(wj)
        wj = wj * w % R
    denom_inv = _batch_inv([(tau_p - wj) % R for wj in wjs])
    h_scalars = [scale * wj % R * di % R for wj, di in zip(wjs, denom_inv)]
    h_query = g1_gen_mul_batch(h_scalars)

    pk = ProvingKey(
        n_public=cs.num_public,
        domain_size=m,
        alpha_1=g1_gen_mul(alpha),
        beta_1=g1_gen_mul(beta),
        beta_2=g2_gen_mul(beta),
        delta_1=g1_gen_mul(delta),
        delta_2=g2_gen_mul(delta),
        a_query=a_query,
        b1_query=b1_query,
        b2_query=b2_query,
        c_query=c_query,
        h_query=h_query,
    )
    vk = VerifyingKey(
        n_public=cs.num_public,
        alpha_1=pk.alpha_1,
        beta_2=pk.beta_2,
        gamma_2=g2_gen_mul(gamma),
        delta_2=pk.delta_2,
        ic=ic,
    )
    return pk, vk


def coset_quotient_evals(cs: ConstraintSystem, witness: Sequence[int]) -> List[int]:
    """d_j = (A·B - C)(g·w^j): the raw coset evaluations the prover MSMs
    against the coset-Lagrange h_query (snarkjs `groth16 prove` dataflow).

    Lagrange-basis row dot-products -> iNTT -> coset NTT -> pointwise
    a*b - c.  No division: Z is constant on the coset and folded into the
    h_query points at setup.  C evaluations on the original domain equal
    A∘B pointwise for a satisfying witness (every binding row has B = 0),
    so only the A and B matrices are ever evaluated — exactly why the
    snarkjs .zkey coefficient section stores just those two.
    This exact dataflow is what zkp2p_tpu.prover runs as batched TPU NTTs.
    """
    rows = qap_rows(cs)
    m = domain_size_for(cs)
    a_ev = [0] * m
    b_ev = [0] * m
    for j, (ra, rb, _rc) in enumerate(rows):
        a_ev[j] = sum(coeff * witness[wi] for wi, coeff in ra.items()) % R
        b_ev[j] = sum(coeff * witness[wi] for wi, coeff in rb.items()) % R
    c_ev = [a * b % R for a, b in zip(a_ev, b_ev)]
    a_c = intt(a_ev)
    b_c = intt(b_ev)
    c_c = intt(c_ev)
    g = coset_gen(m.bit_length() - 1)
    a_cos = ntt(coset_shift(a_c, g))
    b_cos = ntt(coset_shift(b_c, g))
    c_cos = ntt(coset_shift(c_c, g))
    return [(a * b - c) % R for a, b, c in zip(a_cos, b_cos, c_cos)]


def prove_host(
    pk: ProvingKey,
    cs: ConstraintSystem,
    witness: Sequence[int],
    r: Optional[int] = None,
    s: Optional[int] = None,
) -> Proof:
    """Reference prover (host ints).  Deliberately structured exactly like
    the TPU prover so the two can be diffed step by step."""
    if r is None:
        r = 1 + secrets.randbelow(R - 1)
    if s is None:
        s = 1 + secrets.randbelow(R - 1)
    h = coset_quotient_evals(cs, witness)

    a_acc = g1_msm(pk.a_query, witness)
    pi_a = g1_add(g1_add(pk.alpha_1, a_acc), g1_mul(pk.delta_1, r))

    b2_acc = g2_msm(pk.b2_query, witness)
    pi_b = g2_add(g2_add(pk.beta_2, b2_acc), g2_mul(pk.delta_2, s))

    b1_acc = g1_msm(pk.b1_query, witness)
    pi_b1 = g1_add(g1_add(pk.beta_1, b1_acc), g1_mul(pk.delta_1, s))

    priv = [(pt, wv) for pt, wv in zip(pk.c_query, witness) if pt is not None]
    c_acc = g1_msm([p for p, _ in priv], [v for _, v in priv])
    h_acc = g1_msm(pk.h_query, h)
    pi_c = g1_add(c_acc, h_acc)
    pi_c = g1_add(pi_c, g1_mul(pi_a, s))
    pi_c = g1_add(pi_c, g1_mul(pi_b1, r))
    pi_c = g1_add(pi_c, g1_neg(g1_mul(pk.delta_1, r * s % R)))

    return Proof(a=pi_a, b=pi_b, c=pi_c)


def verify(vk: VerifyingKey, proof: Proof, public_inputs: Sequence[int]) -> bool:
    """e(A,B) == e(alpha,beta) * e(vk_x,gamma) * e(C,delta) — the equation
    contracts/Verifier.sol:340-359 checks via pairingProd4."""
    if len(public_inputs) != vk.n_public:
        return False
    # Point validation before any pairing work — mirrors what the EVM
    # ecPairing precompile enforces (off-curve or non-subgroup points make
    # the whole call revert).  G1 has cofactor 1, so on-curve == in-subgroup;
    # G2's twist has a large cofactor, so proof.b also needs an order check
    # (the small-subgroup forgery gap).
    if not (g1_is_on_curve(proof.a) and g1_is_on_curve(proof.c)):
        return False
    if not g2_is_on_curve(proof.b):
        return False
    if proof.b is not None and g2_mul(proof.b, R) is not None:
        return False
    vk_x = vk.ic[0]
    for i, x in enumerate(public_inputs):
        vk_x = g1_add(vk_x, g1_mul(vk.ic[i + 1], x % R))
    return pairing_product_is_one(
        [
            (g1_neg(proof.a), proof.b),
            (vk.alpha_1, vk.beta_2),
            (vk_x, vk.gamma_2),
            (proof.c, vk.delta_2),
        ]
    )
