#!/usr/bin/env python
"""Hardware A/B microbench: XLA vs Pallas curve kernels inside the MSM.

Round-4 follow-up to docs/ROOFLINE.md: the fused Montgomery mul measured
136.5 M muls/s (7.9x XLA) on the chip; this script measures what that
buys at the POINT and MSM level, which is what the prover actually runs
(SURVEY.md §3.1 hot loop 2 — the reference's rapidsnark MSMs).

Selects the implementation via the existing env flags (read at import
time, so each arm runs in its own process).  The defaults are "auto"
(= pallas on TPU), so the XLA arm must PIN BOTH flags:

  ZKP2P_CURVE_KERNEL=xla ZKP2P_FIELD_MUL=xla python tools/msm_hwbench.py \
      [--n 131072] [--window 4] [--lanes ...]

Prints per-stage rates: batched add_mixed (the MSM inner op), and a full
G1 msm_windowed at the requested size.

`--native` benches the C++ Pippenger tier (csrc zkp2p_native) instead of
the JAX path — the arm the tunnel-down bench actually runs.  The
batch-affine bucket knob is A/B-able there:

  python tools/msm_hwbench.py --native --n 524288 --glv --batch-affine
  python tools/msm_hwbench.py --native --n 524288 --glv --no-batch-affine

`--columns S` (native arm) benches the cross-proof multi-column kernel —
one base sweep filling S independent bucket sets, batch-affine inversion
rounds shared across columns — against S sequential MSMs, min-of-reps,
with a result-hash parity echo:

  python tools/msm_hwbench.py --native --n 131072 --columns 4 [--glv]

`--precomp` (native arm) benches the fixed-base precomputed-table tier
(csrc g1_msm_pippenger_fixed / _fixed_multi with --columns) against the
variable-base oracle arm (--glv picks which), building the level tables
in-process first; `--table-depth` sets the level count (q derives as
ceil(W/depth)); parity hash echoed like the --columns convention:

  python tools/msm_hwbench.py --native --n 524288 --precomp --glv
  python tools/msm_hwbench.py --native --n 524288 --precomp --table-depth 4
  python tools/msm_hwbench.py --native --n 131072 --precomp --columns 4

Each arm runs in its own process anyway (import-time constants on the
JAX side; one clean env per arm on the native side).
"""

import argparse
import json
import os
import sys
import time

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, ROOT)

# --json accumulator: every bench arm appends one record (arm, shape,
# min-of-reps seconds, parity hash where the arm has an oracle), and
# main() emits ONE JSON document after all text output.  The text lines
# above it stay byte-stable — existing docs/scripts scrape them; the
# tune pass (zkp2p_tpu.pipeline.tune) consumes the records.
_RESULTS = []


def _rec(**kw):
    _RESULTS.append(kw)


def _native_bench(args):
    """The C++ Pippenger arm: random full-width scalars over a tiled
    base set, min-of-reps wall time (this box is ±30% noisy), result
    x-coordinate echoed so A/B arms can be cross-checked for parity."""
    import ctypes
    import random

    import numpy as np

    from zkp2p_tpu.field.bn254 import GLV_MAX_BITS, R
    from zkp2p_tpu.curve.host import G1_GENERATOR, g1_mul
    from zkp2p_tpu.native.lib import _pack_affine, _scalars_to_u64
    from zkp2p_tpu.prover.native_prove import (
        _glv_consts,
        _lib,
        _p,
        _pick_window,
        _pick_window_glv,
    )
    from zkp2p_tpu.utils.config import load_config

    lib = _lib()
    assert lib is not None, "native library unavailable"
    load_config()  # resolve + validate env the same way the prover does
    from zkp2p_tpu.prover.native_prove import _n_threads

    # the PROVER's thread resolution (env else core count), so the bench
    # measures the arm the tunnel-down bench actually runs; pin
    # ZKP2P_NATIVE_THREADS=1 for single-worker microbenches
    threads = _n_threads()
    if args.window is not None and args.window <= 0:
        args.window = None  # 0 = auto, same as omitting the flag
    ba = bool(lib.zkp2p_batch_affine_enabled())
    print(
        f"native arm: n={args.n} ifma={'on' if lib.zkp2p_ifma_available() else 'off'} "
        f"threads={threads} glv={'on' if args.glv else 'off'} "
        f"batch_affine={'on' if ba else 'off'}",
        flush=True,
    )
    rng = np.random.default_rng(7)
    host_pts = [g1_mul(G1_GENERATOR, int(k)) for k in rng.integers(1, 1 << 30, 64)]
    n = args.n
    bases = _pack_affine(host_pts)
    bm64 = np.zeros_like(bases)
    u64p = ctypes.POINTER(ctypes.c_uint64)
    lib.fp_to_mont.argtypes = [u64p, u64p, ctypes.c_int]
    lib.fp_to_mont(_p(bases), _p(bm64), 2 * 64)
    bm = np.ascontiguousarray(np.tile(bm64, ((n + 63) // 64, 1))[:n])
    py_rng = random.Random(11)
    sc = np.ascontiguousarray(_scalars_to_u64([py_rng.randrange(R) for _ in range(n)]))
    out = np.zeros(8, dtype=np.uint64)
    reps = args.reps
    if args.precomp:
        _native_precomp_bench(args, lib, bm, sc, threads)
        return
    if args.columns > 1:
        _native_multi_bench(args, lib, bm, threads)
        return
    if args.glv:
        c = args.window if args.window is not None else _pick_window_glv(n, threads=threads)
        phi = np.zeros_like(bm)
        lib.g1_glv_phi_bases(_p(bm), n, _p(_glv_consts()), _p(phi))
        b2 = np.ascontiguousarray(np.concatenate([bm, phi]))

        def run():
            lib.g1_msm_pippenger_glv_mt(
                _p(b2), _p(sc), n, n, c, threads, _p(_glv_consts()), GLV_MAX_BITS, _p(out)
            )
    else:
        c = args.window if args.window is not None else _pick_window(n, threads=threads)

        def run():
            lib.g1_msm_pippenger_mt(_p(bm), _p(sc), n, c, threads, _p(out))

    times = []
    for _ in range(reps):
        t0 = time.perf_counter()
        run()
        times.append(time.perf_counter() - t0)
    best = min(times)
    x = int.from_bytes(out[:4].tobytes(), "little")
    print(
        f"native msm: n={n} c={c} reps={reps} min={best*1e3:.0f} ms "
        f"(all: {' '.join(f'{t*1e3:.0f}' for t in times)}) -> {n/best/1e6:.3f} M pts/s "
        f"result_x={x % (1 << 64):#x}",
        flush=True,
    )
    import hashlib

    _rec(
        arm="native_msm", tag="glv" if args.glv else "plain", n=n, c=c,
        threads=threads, reps=reps, min_s=best, times_s=times,
        result_hash=hashlib.sha256(out.tobytes()).hexdigest()[:16],
    )


def _native_apply_prof_bench(args):
    """--apply-prof arm: isolated fill/apply/suffix/bailfill attribution
    for the MSM apply-interleave lever, riding the csrc `g_prof_*`
    counters (ZKP2P_MSM_PROF is latched ON in main() BEFORE the native
    lib loads).  Interleaved same-process A/B — ZKP2P_MSM_INTERLEAVE=1
    vs =0 alternate every rep (the C side fresh-reads the env per call),
    min-of-reps per arm, counters drained before each rep so every
    split belongs to exactly one call — with the usual result-hash
    parity echo.  NOTE the fill window ENCLOSES the apply window
    (sched = fill - apply), so the columns do not sum to the wall."""
    import ctypes
    import hashlib
    import random

    import numpy as np

    from zkp2p_tpu.field.bn254 import GLV_MAX_BITS, R
    from zkp2p_tpu.curve.host import G1_GENERATOR, g1_mul
    from zkp2p_tpu.native.lib import _pack_affine, _scalars_to_u64
    from zkp2p_tpu.prover.native_prove import (
        _glv_consts,
        _lib,
        _n_threads,
        _p,
        _pick_window,
        _pick_window_glv,
    )

    lib = _lib()
    assert lib is not None, "native library unavailable"
    lib.zkp2p_msm_prof_dump.argtypes = [ctypes.POINTER(ctypes.c_longlong)]
    threads = _n_threads()
    n = args.n
    rng = np.random.default_rng(7)
    host_pts = [g1_mul(G1_GENERATOR, int(k)) for k in rng.integers(1, 1 << 30, 64)]
    bases = _pack_affine(host_pts)
    bm64 = np.zeros_like(bases)
    u64p = ctypes.POINTER(ctypes.c_uint64)
    lib.fp_to_mont.argtypes = [u64p, u64p, ctypes.c_int]
    lib.fp_to_mont(_p(bases), _p(bm64), 2 * 64)
    bm = np.ascontiguousarray(np.tile(bm64, ((n + 63) // 64, 1))[:n])
    py_rng = random.Random(11)
    sc = np.ascontiguousarray(_scalars_to_u64([py_rng.randrange(R) for _ in range(n)]))
    out = np.zeros(8, dtype=np.uint64)
    if args.glv:
        c = args.window if args.window is not None else _pick_window_glv(n, threads=threads)
        phi = np.zeros_like(bm)
        lib.g1_glv_phi_bases(_p(bm), n, _p(_glv_consts()), _p(phi))
        b2 = np.ascontiguousarray(np.concatenate([bm, phi]))

        def run():
            lib.g1_msm_pippenger_glv_mt(
                _p(b2), _p(sc), n, n, c, threads, _p(_glv_consts()), GLV_MAX_BITS, _p(out)
            )
    else:
        c = args.window if args.window is not None else _pick_window(n, threads=threads)

        def run():
            lib.g1_msm_pippenger_mt(_p(bm), _p(sc), n, c, threads, _p(out))

    def drain():
        buf = (ctypes.c_longlong * 4)()
        lib.zkp2p_msm_prof_dump(buf)
        return [int(v) for v in buf]

    print(
        f"apply-prof: n={n} c={c} threads={threads} "
        f"glv={'on' if args.glv else 'off'} reps={args.reps} "
        "(interleaved ZKP2P_MSM_INTERLEAVE=1/0 per rep)",
        flush=True,
    )
    best = {}  # arm -> (wall_s, [fill, apply, suffix, bailfill] ns)
    hashes = {}
    for rep in range(args.reps):
        for arm in ("1", "0"):
            os.environ["ZKP2P_MSM_INTERLEAVE"] = arm
            drain()
            t0 = time.perf_counter()
            run()
            wall = time.perf_counter() - t0
            split = drain()
            if arm not in best or wall < best[arm][0]:
                best[arm] = (wall, split)
            hashes.setdefault(arm, hashlib.sha256(out.tobytes()).hexdigest()[:16])
    os.environ.pop("ZKP2P_MSM_INTERLEAVE", None)
    names = ("fill", "apply", "suffix", "bailfill")
    for arm in ("0", "1"):
        wall, split = best[arm]
        cols = " ".join(f"{nm}={v / 1e6:.1f}ms" for nm, v in zip(names, split))
        print(
            f"  interleave={arm}: wall={wall * 1e3:.1f}ms {cols} "
            f"(sched={ (split[0] - split[1]) / 1e6:.1f}ms) "
            f"result_hash={hashes[arm]}",
            flush=True,
        )
    w1, s1 = best["1"]
    w0, s0 = best["0"]
    parity = hashes["1"] == hashes["0"]
    print(
        f"  speedup: wall {w0 / w1:.3f}x  apply "
        f"{(s0[1] / s1[1]) if s1[1] else float('nan'):.3f}x  "
        f"parity={'OK' if parity else 'MISMATCH'}",
        flush=True,
    )
    assert parity, "apply-prof arms disagree on the MSM result"
    _rec(
        arm="native_apply_prof", tag="glv" if args.glv else "plain", n=n, c=c,
        threads=threads, reps=args.reps,
        interleave_on={"wall_s": w1, **{nm + "_ns": v for nm, v in zip(names, s1)}},
        interleave_off={"wall_s": w0, **{nm + "_ns": v for nm, v in zip(names, s0)}},
        result_hash=hashes["1"],
    )


def _native_precomp_bench(args, lib, bm, sc, threads):
    """--precomp arm: fixed-base precomputed-table drivers vs the
    variable-base oracle (GLV when --glv, plain otherwise) — tables
    built in-process at the prover's fixed-tier window, min-of-reps per
    arm, speedup ratio, and a result-hash parity echo matching the
    --columns convention.  --table-depth sets the level count (the
    ZKP2P_MSM_PRECOMP_DEPTH dial); --columns S runs the _fixed_multi
    driver against S sequential oracle MSMs."""
    import hashlib
    import random

    import numpy as np

    from zkp2p_tpu.field.bn254 import GLV_MAX_BITS, R
    from zkp2p_tpu.native.lib import _scalars_to_u64
    from zkp2p_tpu.prover.native_prove import (
        _glv_consts,
        _p,
        _pick_window,
        _pick_window_glv,
    )
    from zkp2p_tpu.prover.precomp import _resolve_geometry

    n, S, reps = bm.shape[0], max(1, args.columns), args.reps
    # the prover's own geometry resolver (uncapped budget: the bench
    # measures the requested depth, the prover's RAM guard is its own
    # concern) — so the tool can never drift from what the prover runs.
    # No argtype declarations here: the `lib` handle comes from
    # native_prove._lib(), which already configures the precomp ABI.
    cf, q, levels = _resolve_geometry(n, args.table_depth, 1 << 62)
    t0 = time.perf_counter()
    table = np.zeros((levels * n, 8), dtype=np.uint64)
    lib.g1_precomp_build(_p(bm), n, cf, q, levels, threads, _p(table))
    t_build = time.perf_counter() - t0
    table52 = np.zeros((levels * n, 10), dtype=np.uint64)
    p52 = _p(table52) if lib.g1_precomp_to52(_p(table), levels * n, _p(table52)) else None
    print(
        f"precomp tables: c={cf} q={q} levels={levels} "
        f"({table.nbytes + (table52.nbytes if p52 else 0):,} B resident) "
        f"built in {t_build:.1f}s",
        flush=True,
    )

    py_rng = random.Random(13)
    if S > 1:
        cols = [[py_rng.randrange(R) for _ in range(n)] for _ in range(S)]
        scm = np.ascontiguousarray(np.stack([_scalars_to_u64(col) for col in cols]))
    else:
        scm = np.ascontiguousarray(sc.reshape(1, n, 4))
    out_fixed = np.zeros((S, 8), dtype=np.uint64)
    out_ref = np.zeros((S, 8), dtype=np.uint64)

    def run_fixed():
        if S > 1:
            lib.g1_msm_pippenger_fixed_multi(
                _p(table), p52, _p(scm), n, n, S, levels, cf, q, threads, _p(out_fixed)
            )
        else:
            lib.g1_msm_pippenger_fixed(
                _p(table), p52, _p(scm), n, n, levels, cf, q, threads, _p(out_fixed)
            )

    if args.glv:
        c_ref = args.window if args.window is not None else _pick_window_glv(n, threads=threads)
        phi = np.zeros_like(bm)
        lib.g1_glv_phi_bases(_p(bm), n, _p(_glv_consts()), _p(phi))
        b2 = np.ascontiguousarray(np.concatenate([bm, phi]))

        def run_ref():
            for s in range(S):
                col = np.ascontiguousarray(scm[s])
                lib.g1_msm_pippenger_glv_mt(
                    _p(b2), _p(col), n, n, c_ref, threads, _p(_glv_consts()),
                    GLV_MAX_BITS, _p(out_ref[s]),
                )
    else:
        c_ref = args.window if args.window is not None else _pick_window(n, threads=threads)

        def run_ref():
            for s in range(S):
                col = np.ascontiguousarray(scm[s])
                lib.g1_msm_pippenger_mt(_p(bm), _p(col), n, c_ref, threads, _p(out_ref[s]))

    t_fixed, t_ref = [], []
    for _ in range(reps):
        t0 = time.perf_counter()
        run_fixed()
        t_fixed.append(time.perf_counter() - t0)
        t0 = time.perf_counter()
        run_ref()
        t_ref.append(time.perf_counter() - t0)
    bf, br = min(t_fixed), min(t_ref)
    parity = "OK" if np.array_equal(out_fixed, out_ref) else "MISMATCH"
    h = hashlib.sha256(out_fixed.tobytes()).hexdigest()[:16]
    tag = "glv" if args.glv else "plain"
    print(
        f"native msm precomp[vs {tag}]: n={n} S={S} c={cf} q={q} L={levels} reps={reps} "
        f"fixed min={bf*1e3:.0f} ms vs oracle(c={c_ref}) min={br*1e3:.0f} ms "
        f"-> {br/bf:.2f}x ({S*n/bf/1e6:.3f} M col-pts/s) "
        f"parity={parity} result_hash={h}",
        flush=True,
    )
    _rec(
        arm="native_msm_precomp", tag=tag, n=n, S=S, c=cf, q=q, levels=levels,
        threads=threads, reps=reps, build_s=t_build, min_s=bf,
        oracle_min_s=br, oracle_c=c_ref, parity=parity, result_hash=h,
    )
    assert parity == "OK", "precomp result diverged from the variable-base oracle"


def _native_multi_bench(args, lib, bm, threads):
    """--columns S sweep: the multi-column kernel (one base sweep, S
    scalar columns) vs S sequential single-column MSMs — min-of-reps
    wall per arm, speedup ratio, and a result-hash parity check (the
    sequential driver is the byte oracle)."""
    import ctypes
    import hashlib
    import random

    import numpy as np

    from zkp2p_tpu.field.bn254 import GLV_MAX_BITS, R
    from zkp2p_tpu.native.lib import _scalars_to_u64
    from zkp2p_tpu.prover.native_prove import (
        _glv_consts,
        _p,
        _pick_window,
        _pick_window_glv,
    )

    u64p = ctypes.POINTER(ctypes.c_uint64)
    n, S, reps = bm.shape[0], args.columns, args.reps
    py_rng = random.Random(13)
    cols = [[py_rng.randrange(R) for _ in range(n)] for _ in range(S)]
    sc = np.ascontiguousarray(np.stack([_scalars_to_u64(col) for col in cols]))
    out_multi = np.zeros((S, 8), dtype=np.uint64)
    out_seq = np.zeros((S, 8), dtype=np.uint64)
    if args.glv:
        c = args.window if args.window is not None else _pick_window_glv(n, threads=threads)
        phi = np.zeros_like(bm)
        lib.g1_glv_phi_bases.argtypes = [u64p, ctypes.c_long, u64p, u64p]
        lib.g1_glv_phi_bases(_p(bm), n, _p(_glv_consts()), _p(phi))
        b2 = np.ascontiguousarray(np.concatenate([bm, phi]))
        lib.g1_msm_pippenger_glv_multi.argtypes = [
            u64p, u64p, ctypes.c_long, ctypes.c_long, ctypes.c_int, ctypes.c_int,
            ctypes.c_int, u64p, ctypes.c_int, u64p,
        ]

        def run_multi():
            lib.g1_msm_pippenger_glv_multi(
                _p(b2), _p(sc), n, n, S, c, threads, _p(_glv_consts()),
                GLV_MAX_BITS, _p(out_multi),
            )

        def run_seq():
            for s in range(S):
                col = np.ascontiguousarray(sc[s])
                lib.g1_msm_pippenger_glv_mt(
                    _p(b2), _p(col), n, n, c, threads, _p(_glv_consts()),
                    GLV_MAX_BITS, _p(out_seq[s]),
                )
    else:
        c = args.window if args.window is not None else _pick_window(n, threads=threads)
        lib.g1_msm_pippenger_multi.argtypes = [
            u64p, u64p, ctypes.c_long, ctypes.c_int, ctypes.c_int, ctypes.c_int, u64p,
        ]

        def run_multi():
            lib.g1_msm_pippenger_multi(_p(bm), _p(sc), n, S, c, threads, _p(out_multi))

        def run_seq():
            for s in range(S):
                col = np.ascontiguousarray(sc[s])
                lib.g1_msm_pippenger_mt(_p(bm), _p(col), n, c, threads, _p(out_seq[s]))

    t_multi, t_seq = [], []
    for _ in range(reps):
        t0 = time.perf_counter()
        run_multi()
        t_multi.append(time.perf_counter() - t0)
        t0 = time.perf_counter()
        run_seq()
        t_seq.append(time.perf_counter() - t0)
    bm_multi, bm_seq = min(t_multi), min(t_seq)
    parity = "OK" if np.array_equal(out_multi, out_seq) else "MISMATCH"
    h = hashlib.sha256(out_multi.tobytes()).hexdigest()[:16]
    tag = "glv" if args.glv else "plain"
    print(
        f"native msm multi[{tag}]: n={n} S={S} c={c} reps={reps} "
        f"multi min={bm_multi*1e3:.0f} ms vs {S}x sequential min={bm_seq*1e3:.0f} ms "
        f"-> {bm_seq/bm_multi:.2f}x ({S*n/bm_multi/1e6:.3f} M col-pts/s) "
        f"parity={parity} result_hash={h}",
        flush=True,
    )
    _rec(
        arm="native_msm_multi", tag=tag, n=n, S=S, c=c, threads=threads,
        reps=reps, min_s=bm_multi, seq_min_s=bm_seq, parity=parity,
        result_hash=h,
    )
    assert parity == "OK", "multi-column result diverged from the sequential oracle"


def _ladder_bench(args):
    """--ladder: the non-MSM floor in isolation (docs/TUNING.md
    §non-MSM) — the segmented matvec vs the scatter `fr_matvec` oracle,
    and the H ladder with the pool-parallel fused NTT pipeline vs the
    3-wide unfused arm.  Interleaved same-process A/B (both knobs are
    fresh-read in csrc), min-of-reps, parity asserted on output bytes.

      python tools/msm_hwbench.py --ladder --n 524288 [--reps 5]
    """
    import ctypes

    import numpy as np

    from zkp2p_tpu.field.bn254 import fr_domain_root
    from zkp2p_tpu.prover import matvec_plan
    from zkp2p_tpu.prover.native_prove import _lib, _n_threads, _p
    from zkp2p_tpu.snark.groth16 import coset_gen

    lib = _lib()
    assert lib is not None, "native library unavailable"
    u64p = ctypes.POINTER(ctypes.c_uint64)
    u32p = ctypes.POINTER(ctypes.c_uint32)
    i64p = ctypes.POINTER(ctypes.c_longlong)
    threads = _n_threads()
    m = args.n
    log_m = m.bit_length() - 1
    assert 1 << log_m == m, "--ladder needs a power-of-two --n (the NTT domain)"
    print(
        f"ladder arm: m=2^{log_m} threads={threads} "
        f"ifma={'on' if lib.zkp2p_ifma_available() else 'off'} reps={args.reps}",
        flush=True,
    )
    g = np.random.default_rng(17)

    def rand_fr(n):
        a = g.integers(0, 1 << 64, size=(n, 4), dtype=np.uint64)
        a[:, 3] &= np.uint64((1 << 60) - 1)  # < 2^252 < r
        return np.ascontiguousarray(a)

    def mont(std):
        out = np.zeros_like(std)
        lib.fr_to_mont_batch(_p(std), _p(out), std.shape[0])
        return out

    # ---- matvec: venmo-like density (~4 nnz/row), random wires/rows
    nnz = 4 * m
    coeff = mont(rand_fr(nnz))
    wire = g.integers(0, m, size=nnz, dtype=np.uint32)
    row = g.integers(0, m, size=nnz, dtype=np.uint32)
    w_mont = mont(rand_fr(m))
    cp, wp, _perm, seg_starts, seg_rows = matvec_plan._build(coeff, wire, row)
    c52 = matvec_plan._pack52(lib, cp)
    outs = {}
    times = {"oracle": [], "seg": []}
    for _ in range(args.reps):
        for arm in ("oracle", "seg"):  # interleaved
            out = np.zeros((m, 4), dtype=np.uint64)
            t0 = time.perf_counter()
            if arm == "oracle":
                lib.fr_matvec(
                    _p(coeff), wire.ctypes.data_as(u32p), row.ctypes.data_as(u32p),
                    nnz, _p(w_mont), m, _p(out),
                )
            else:
                lib.fr_matvec_seg(
                    _p(c52) if c52 is not None else None, _p(cp),
                    wp.ctypes.data_as(u32p), seg_starts.ctypes.data_as(i64p),
                    seg_rows.ctypes.data_as(u32p), seg_rows.shape[0],
                    _p(w_mont), m, threads, _p(out),
                )
            times[arm].append(time.perf_counter() - t0)
            outs[arm] = out
    assert np.array_equal(outs["oracle"], outs["seg"]), "segmented matvec diverged"
    mo, ms = min(times["oracle"]), min(times["seg"])
    print(
        f"matvec nnz={nnz}: oracle min={mo*1e3:.1f} ms seg min={ms*1e3:.1f} ms "
        f"-> {mo/ms:.2f}x parity=OK",
        flush=True,
    )
    _rec(
        arm="ladder_matvec", m=m, nnz=nnz, threads=threads, reps=args.reps,
        min_s=ms, oracle_min_s=mo, parity="OK",
    )

    # ---- H ladder: pool-fused arm vs the 3-wide unfused arm
    wroot = np.ascontiguousarray(
        np.frombuffer(int(fr_domain_root(log_m)).to_bytes(32, "little"), dtype="<u8")
    )
    gcos = np.ascontiguousarray(
        np.frombuffer(int(coset_gen(log_m)).to_bytes(32, "little"), dtype="<u8")
    )
    base = mont(rand_fr(3 * m)).reshape(3, m, 4)
    lt = {"pool": [], "unfused": []}
    louts = {}
    for _ in range(args.reps):
        for arm, knob in (("pool", "1"), ("unfused", "0")):
            os.environ["ZKP2P_NTT_POOL"] = knob
            abc = [np.ascontiguousarray(base[i].copy()) for i in range(3)]
            d = np.zeros((m, 4), dtype=np.uint64)
            t0 = time.perf_counter()
            lib.fr_h_ladder(
                _p(abc[0]), _p(abc[1]), _p(abc[2]), m, _p(wroot), _p(gcos), _p(d)
            )
            lt[arm].append(time.perf_counter() - t0)
            louts[arm] = d
    os.environ.pop("ZKP2P_NTT_POOL", None)
    assert np.array_equal(louts["pool"], louts["unfused"]), "pooled ladder diverged"
    lp, lu = min(lt["pool"]), min(lt["unfused"])
    print(
        f"h_ladder m=2^{log_m}: unfused min={lu*1e3:.0f} ms pool-fused min={lp*1e3:.0f} ms "
        f"-> {lu/lp:.2f}x parity=OK",
        flush=True,
    )
    _rec(
        arm="ladder_h", m=m, threads=threads, reps=args.reps,
        min_s=lp, unfused_min_s=lu, parity="OK",
    )


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--n", type=int, default=1 << 17)
    ap.add_argument(
        "--window", type=int, default=None,
        help="digit/window width; default: 4 on the JAX path, the prover's "
        "_pick_window choice on --native (an explicit value always wins)",
    )
    ap.add_argument("--lanes", type=int, default=0, help="0 = default_lanes(n)")
    ap.add_argument("--adds", type=int, default=1 << 20, help="batch size for the raw add bench")
    ap.add_argument("--skip-msm", action="store_true")
    ap.add_argument("--skip-adds", action="store_true")
    ap.add_argument("--signed", action="store_true", help="signed digit recoding (half-size table)")
    ap.add_argument(
        "--native", action="store_true",
        help="bench the native C++ Pippenger tier (csrc) instead of the JAX path; "
        "omit --window (or pass 0) for the prover's _pick_window choice",
    )
    ap.add_argument("--reps", type=int, default=5, help="native arm: min-of-reps (noisy box)")
    ap.add_argument(
        "--ladder", action="store_true",
        help="bench the NON-MSM floor in isolation: segmented matvec vs the "
        "scatter oracle + the pool-fused H ladder vs the 3-wide unfused arm, "
        "interleaved same-process A/B at domain size --n (power of two)",
    )
    ap.add_argument(
        "--columns", type=int, default=1,
        help="native arm: S > 1 benches the multi-column kernel (one base sweep, "
        "S scalar columns) against S sequential MSMs, with a parity hash",
    )
    glv_grp = ap.add_mutually_exclusive_group()
    glv_grp.add_argument(
        "--glv", action="store_true",
        help="GLV endomorphism arm: half the signed digit planes over the "
        "endomorphism-doubled [P, phi(P)] base axis (implies --signed)",
    )
    glv_grp.add_argument(
        "--no-glv", action="store_true",
        help="explicit non-GLV arm (the default; named so A/B run logs are self-labelling)",
    )
    pc_grp = ap.add_mutually_exclusive_group()
    pc_grp.add_argument(
        "--precomp", action="store_true",
        help="native arm: fixed-base precomputed-table tier (tables built "
        "in-process) vs the variable-base oracle, with a parity hash",
    )
    pc_grp.add_argument(
        "--no-precomp", action="store_true",
        help="explicit variable-base arm (the default; named so A/B run logs "
        "are self-labelling)",
    )
    ap.add_argument(
        "--table-depth", type=int, default=8,
        help="--precomp: table levels per family (the ZKP2P_MSM_PRECOMP_DEPTH "
        "dial; q = ceil(W/depth) hot-loop windows remain)",
    )
    ba_grp = ap.add_mutually_exclusive_group()
    ba_grp.add_argument(
        "--batch-affine", action="store_true",
        help="native tier: batch-affine Pippenger buckets (one shared Montgomery "
        "inversion per chunk of bucket adds) — the default arm",
    )
    ba_grp.add_argument(
        "--no-batch-affine", action="store_true",
        help="native tier: plain mixed-Jacobian bucket fill (the A/B baseline)",
    )
    ap.add_argument(
        "--apply-prof", action="store_true",
        help="native arm: isolated fill/apply/suffix/bailfill split via the "
        "csrc g_prof_* counters (ZKP2P_MSM_PROF latched before lib load), "
        "interleaved ZKP2P_MSM_INTERLEAVE=1/0 A/B with a parity hash — the "
        "measurable surface for the apply-interleave lever (docs/TUNING.md)",
    )
    ap.add_argument(
        "--json", action="store_true",
        help="after all text output, emit ONE JSON document of structured "
        "per-arm records (arm, shape, min-of-reps seconds, parity hash) — "
        "the machine-readable surface the tune pass consumes; the text "
        "lines above it are unchanged",
    )
    args = ap.parse_args()
    if args.glv:
        args.signed = True
    # The knob rides the env so the C runtime (and any child) sees it;
    # set BEFORE the native lib is loaded/called.
    if args.batch_affine:
        os.environ["ZKP2P_MSM_BATCH_AFFINE"] = "1"
    elif args.no_batch_affine:
        os.environ["ZKP2P_MSM_BATCH_AFFINE"] = "0"
    if args.apply_prof:
        # the C prof gate is latched at first use — arm it before ANY
        # native call so every counter add is live for the whole run
        os.environ["ZKP2P_MSM_PROF"] = "1"

    try:
        _dispatch(args)
    finally:
        if args.json:
            print(json.dumps({"schema": 1, "records": _RESULTS}, sort_keys=True), flush=True)


def _dispatch(args):
    if args.ladder:
        _ladder_bench(args)
        return
    if args.apply_prof:
        if args.window is not None and args.window <= 0:
            args.window = None
        _native_apply_prof_bench(args)
        return
    if args.native:
        _native_bench(args)
        return
    if args.window is None:
        args.window = 4

    import jax
    import jax.numpy as jnp
    import numpy as np

    from zkp2p_tpu.utils.jaxcfg import enable_cache

    enable_cache()
    dev = jax.devices()[0]
    # Print the RESOLVED implementations (the "auto" default resolves by
    # backend), not the raw env — a bare run on TPU measures pallas.
    from zkp2p_tpu.curve.jcurve import G1J
    from zkp2p_tpu.field.jfield import field_mul_impl

    curve_impl = "pallas" if G1J._pallas() else "xla"
    from zkp2p_tpu.utils.config import load_config

    print(
        f"device={dev} curve={curve_impl} fieldmul={field_mul_impl()} "
        f"glv={'on' if args.glv else 'off'} "
        f"batch_affine={'on' if load_config().msm_batch_affine else 'off'} (native tier knob)",
        flush=True,
    )

    from zkp2p_tpu.curve.host import G1_GENERATOR, g1_mul
    from zkp2p_tpu.curve.jcurve import g1_to_affine_arrays
    from zkp2p_tpu.ops.msm import (
        default_lanes,
        digit_planes_from_limbs,
        msm_windowed,
        msm_windowed_signed,
        signed_digit_planes_from_limbs,
    )

    curve = G1J
    rng = np.random.default_rng(7)

    # random-ish affine bases: k*G for 64 distinct k, tiled to n
    host_pts = [g1_mul(G1_GENERATOR, int(k)) for k in rng.integers(1, 1 << 30, 64)]
    ax_np, ay_np = (np.asarray(c) for c in g1_to_affine_arrays(host_pts))
    n = args.n
    reps = (n + 63) // 64
    bx = jnp.asarray(np.tile(ax_np, (reps, 1))[:n])
    by = jnp.asarray(np.tile(ay_np, (reps, 1))[:n])
    bases = (bx, by)

    # ---- raw batched add_mixed rate (the MSM inner op) ----
    if not args.skip_adds:
        B = args.adds
        reps_b = (B + 63) // 64
        px = jnp.asarray(np.tile(ax_np, (reps_b, 1))[:B])
        py = jnp.asarray(np.tile(ay_np, (reps_b, 1))[:B])
        P = curve.from_affine((px, py))
        qx = jnp.roll(px, 1, axis=0)
        qy = jnp.roll(py, 1, axis=0)

        addm = jax.jit(lambda p, a: curve.add_mixed(p, a))
        out = addm(P, (qx, qy))
        jax.block_until_ready(out)
        t0 = time.perf_counter()
        iters = 4
        for _ in range(iters):
            out = addm(P, (qx, qy))
        jax.block_until_ready(out)
        dt = (time.perf_counter() - t0) / iters
        print(f"add_mixed: B={B} {dt*1e3:.1f} ms -> {B/dt/1e6:.2f} M adds/s", flush=True)
        _rec(arm="jax_add_mixed", n=B, min_s=dt, reps=iters)

    if args.skip_msm:
        return

    # ---- full windowed MSM ----
    limbs_np = rng.integers(0, 1 << 16, size=(n, 16), dtype=np.uint32)
    limbs_np[:, 15] &= 0x3FFF  # < 2^254, like Fr scalars (signed recoding bound)
    lanes = args.lanes or default_lanes(2 * n if args.glv else n)
    tag = f"n={n} lanes={lanes} w={args.window}"
    if args.glv:
        from zkp2p_tpu.ops.msm import glv_extend_bases, glv_signed_planes_from_limbs

        gb = glv_extend_bases(bases)
        mags, negs = glv_signed_planes_from_limbs(jnp.asarray(limbs_np), args.window)
        f = jax.jit(lambda b, m, s: msm_windowed_signed(curve, b, m, s, lanes=lanes, window=args.window))
        fargs = (gb, mags, negs)
        tag += f" glv({mags.shape[0]} planes x 2n bases)"
    elif args.signed:
        mags, negs = signed_digit_planes_from_limbs(jnp.asarray(limbs_np), args.window)
        f = jax.jit(lambda b, m, s: msm_windowed_signed(curve, b, m, s, lanes=lanes, window=args.window))
        fargs = (bases, mags, negs)
        tag += " signed"
    else:
        planes = digit_planes_from_limbs(jnp.asarray(limbs_np), window=args.window)
        f = jax.jit(lambda b, p: msm_windowed(curve, b, p, lanes=lanes, window=args.window))
        fargs = (bases, planes)
    t0 = time.perf_counter()
    r = f(*fargs)
    jax.block_until_ready(r)
    compile_and_first = time.perf_counter() - t0
    print(f"msm first (incl compile): {compile_and_first:.1f}s", flush=True)
    t0 = time.perf_counter()
    r = f(*fargs)
    jax.block_until_ready(r)
    dt = time.perf_counter() - t0
    print(f"msm_windowed: {tag} {dt:.2f} s -> {n/dt/1e6:.3f} M pts/s", flush=True)
    _rec(
        arm="jax_msm_windowed", n=n, window=args.window, min_s=dt, reps=1,
        compile_s=compile_and_first,
    )


if __name__ == "__main__":
    main()
