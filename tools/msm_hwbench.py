#!/usr/bin/env python
"""Hardware A/B microbench: XLA vs Pallas curve kernels inside the MSM.

Round-4 follow-up to docs/ROOFLINE.md: the fused Montgomery mul measured
136.5 M muls/s (7.9x XLA) on the chip; this script measures what that
buys at the POINT and MSM level, which is what the prover actually runs
(SURVEY.md §3.1 hot loop 2 — the reference's rapidsnark MSMs).

Selects the implementation via the existing env flags (read at import
time, so each arm runs in its own process).  The defaults are "auto"
(= pallas on TPU), so the XLA arm must PIN BOTH flags:

  ZKP2P_CURVE_KERNEL=xla ZKP2P_FIELD_MUL=xla python tools/msm_hwbench.py \
      [--n 131072] [--window 4] [--lanes ...]

Prints per-stage rates: batched add_mixed (the MSM inner op), and a full
G1 msm_windowed at the requested size.
"""

import argparse
import os
import sys
import time

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, ROOT)


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--n", type=int, default=1 << 17)
    ap.add_argument("--window", type=int, default=4)
    ap.add_argument("--lanes", type=int, default=0, help="0 = default_lanes(n)")
    ap.add_argument("--adds", type=int, default=1 << 20, help="batch size for the raw add bench")
    ap.add_argument("--skip-msm", action="store_true")
    ap.add_argument("--skip-adds", action="store_true")
    ap.add_argument("--signed", action="store_true", help="signed digit recoding (half-size table)")
    glv_grp = ap.add_mutually_exclusive_group()
    glv_grp.add_argument(
        "--glv", action="store_true",
        help="GLV endomorphism arm: half the signed digit planes over the "
        "endomorphism-doubled [P, phi(P)] base axis (implies --signed)",
    )
    glv_grp.add_argument(
        "--no-glv", action="store_true",
        help="explicit non-GLV arm (the default; named so A/B run logs are self-labelling)",
    )
    args = ap.parse_args()
    if args.glv:
        args.signed = True

    import jax
    import jax.numpy as jnp
    import numpy as np

    from zkp2p_tpu.utils.jaxcfg import enable_cache

    enable_cache()
    dev = jax.devices()[0]
    # Print the RESOLVED implementations (the "auto" default resolves by
    # backend), not the raw env — a bare run on TPU measures pallas.
    from zkp2p_tpu.curve.jcurve import G1J
    from zkp2p_tpu.field.jfield import field_mul_impl

    curve_impl = "pallas" if G1J._pallas() else "xla"
    print(
        f"device={dev} curve={curve_impl} fieldmul={field_mul_impl()} "
        f"glv={'on' if args.glv else 'off'}",
        flush=True,
    )

    from zkp2p_tpu.curve.host import G1_GENERATOR, g1_mul
    from zkp2p_tpu.curve.jcurve import g1_to_affine_arrays
    from zkp2p_tpu.ops.msm import (
        default_lanes,
        digit_planes_from_limbs,
        msm_windowed,
        msm_windowed_signed,
        signed_digit_planes_from_limbs,
    )

    curve = G1J
    rng = np.random.default_rng(7)

    # random-ish affine bases: k*G for 64 distinct k, tiled to n
    host_pts = [g1_mul(G1_GENERATOR, int(k)) for k in rng.integers(1, 1 << 30, 64)]
    ax_np, ay_np = (np.asarray(c) for c in g1_to_affine_arrays(host_pts))
    n = args.n
    reps = (n + 63) // 64
    bx = jnp.asarray(np.tile(ax_np, (reps, 1))[:n])
    by = jnp.asarray(np.tile(ay_np, (reps, 1))[:n])
    bases = (bx, by)

    # ---- raw batched add_mixed rate (the MSM inner op) ----
    if not args.skip_adds:
        B = args.adds
        reps_b = (B + 63) // 64
        px = jnp.asarray(np.tile(ax_np, (reps_b, 1))[:B])
        py = jnp.asarray(np.tile(ay_np, (reps_b, 1))[:B])
        P = curve.from_affine((px, py))
        qx = jnp.roll(px, 1, axis=0)
        qy = jnp.roll(py, 1, axis=0)

        addm = jax.jit(lambda p, a: curve.add_mixed(p, a))
        out = addm(P, (qx, qy))
        jax.block_until_ready(out)
        t0 = time.time()
        iters = 4
        for _ in range(iters):
            out = addm(P, (qx, qy))
        jax.block_until_ready(out)
        dt = (time.time() - t0) / iters
        print(f"add_mixed: B={B} {dt*1e3:.1f} ms -> {B/dt/1e6:.2f} M adds/s", flush=True)

    if args.skip_msm:
        return

    # ---- full windowed MSM ----
    limbs_np = rng.integers(0, 1 << 16, size=(n, 16), dtype=np.uint32)
    limbs_np[:, 15] &= 0x3FFF  # < 2^254, like Fr scalars (signed recoding bound)
    lanes = args.lanes or default_lanes(2 * n if args.glv else n)
    tag = f"n={n} lanes={lanes} w={args.window}"
    if args.glv:
        from zkp2p_tpu.ops.msm import glv_extend_bases, glv_signed_planes_from_limbs

        gb = glv_extend_bases(bases)
        mags, negs = glv_signed_planes_from_limbs(jnp.asarray(limbs_np), args.window)
        f = jax.jit(lambda b, m, s: msm_windowed_signed(curve, b, m, s, lanes=lanes, window=args.window))
        fargs = (gb, mags, negs)
        tag += f" glv({mags.shape[0]} planes x 2n bases)"
    elif args.signed:
        mags, negs = signed_digit_planes_from_limbs(jnp.asarray(limbs_np), args.window)
        f = jax.jit(lambda b, m, s: msm_windowed_signed(curve, b, m, s, lanes=lanes, window=args.window))
        fargs = (bases, mags, negs)
        tag += " signed"
    else:
        planes = digit_planes_from_limbs(jnp.asarray(limbs_np), window=args.window)
        f = jax.jit(lambda b, p: msm_windowed(curve, b, p, lanes=lanes, window=args.window))
        fargs = (bases, planes)
    t0 = time.time()
    r = f(*fargs)
    jax.block_until_ready(r)
    compile_and_first = time.time() - t0
    print(f"msm first (incl compile): {compile_and_first:.1f}s", flush=True)
    t0 = time.time()
    r = f(*fargs)
    jax.block_until_ready(r)
    dt = time.time() - t0
    print(f"msm_windowed: {tag} {dt:.2f} s -> {n/dt/1e6:.3f} M pts/s", flush=True)


if __name__ == "__main__":
    main()
