#!/usr/bin/env python
"""On-chip correctness + A/B timing for the batch-affine MSM tier.

Run during a tunnel window BEFORE arming ZKP2P_MSM_AFFINE by default:
Mosaic lowering has twice accepted interpret-mode semantics it could not
run on real hardware (scatter-add, u32 reductions — see ops/pallas_curve
docstring), so the affine tier's fused-pow inversion kernel and its
select-heavy add dataflow must be diffed ON THE CHIP against the
Jacobian path before any default flips.

Phases:
  1. correctness: msm_windowed_affine vs msm_windowed_signed, n=4096,
     w=4 and w=8 — host-compared point equality.
  2. timing: both paths at n=2^17 (the bench-shape chunk regime),
     steady-state over 3 runs.
"""

import sys
import time

import numpy as np


def main():
    import jax
    import jax.numpy as jnp

    from zkp2p_tpu.utils.jaxcfg import enable_cache

    enable_cache()
    print("devices:", jax.devices(), flush=True)

    import random

    from zkp2p_tpu.curve.host import G1_GENERATOR, g1_mul
    from zkp2p_tpu.curve.jcurve import G1J, g1_jac_to_host, g1_to_affine_arrays
    from zkp2p_tpu.field.bn254 import R
    from zkp2p_tpu.field.jfield import FR
    from zkp2p_tpu.ops import msm as jmsm
    from zkp2p_tpu.ops.msm_affine import msm_windowed_affine

    rng = random.Random(9)

    def limbs(scalars):
        return jnp.asarray(np.stack([FR.to_std_host(s) for s in scalars]))

    # -------------------------------------------------- 1. correctness
    n = 4096
    base_pts = [g1_mul(G1_GENERATOR, rng.randrange(1, R)) for _ in range(64)]
    pts = [base_pts[i % 64] for i in range(n)]  # repeats force doubling lanes
    pts[5] = None
    scalars = [rng.randrange(R) for _ in range(n)]
    scalars[9] = 0
    bases = g1_to_affine_arrays(pts)
    for w in (4, 8):
        mags, negs = jmsm.signed_digit_planes_from_limbs(limbs(scalars), w)
        t0 = time.perf_counter()
        got = g1_jac_to_host(
            jax.jit(lambda b, m, s, w=w: msm_windowed_affine(G1J, b, m, s, lanes=512, window=w))(
                bases, mags, negs
            )
        )[0]
        want = g1_jac_to_host(
            jax.jit(lambda b, m, s, w=w: jmsm.msm_windowed_signed(G1J, b, m, s, lanes=512, window=w))(
                bases, mags, negs
            )
        )[0]
        ok = got == want
        print(f"correctness w={w}: {'OK' if ok else 'MISMATCH'} ({time.perf_counter()-t0:.1f}s incl compile)", flush=True)
        if not ok:
            print("AFFINE TIER MISCOMPARES ON HARDWARE — do not arm", flush=True)
            return 1

    # ------------------------------------- 1b. vmapped (the prover path)
    # The batched prover runs jit(vmap(msm)) — a different Mosaic
    # lowering combination (fused-pow inside a scan under vmap) that the
    # unbatched phase cannot vouch for.
    Bv = 2
    sc_b = [[rng.randrange(R) for _ in range(4096)] for _ in range(Bv)]
    mags_b, negs_b = zip(*(jmsm.signed_digit_planes_from_limbs(limbs(s), 8) for s in sc_b))
    mags_b, negs_b = jnp.stack(mags_b), jnp.stack(negs_b)
    vfn = jax.jit(
        jax.vmap(
            lambda m, s: msm_windowed_affine(G1J, bases, m, s, lanes=512, window=8)
        )
    )
    vref = jax.jit(
        jax.vmap(
            lambda m, s: jmsm.msm_windowed_signed(G1J, bases, m, s, lanes=512, window=8)
        )
    )
    got_b = g1_jac_to_host(vfn(mags_b, negs_b))
    want_b = g1_jac_to_host(vref(mags_b, negs_b))
    ok = got_b == want_b
    print(f"correctness vmap B={Bv}: {'OK' if ok else 'MISMATCH'}", flush=True)
    if not ok:
        print("AFFINE TIER MISCOMPARES UNDER VMAP — do not arm", flush=True)
        return 1

    # -------------------------------------------------- 2. timing A/B
    n = 1 << 17
    pts = [base_pts[i % 64] for i in range(n)]
    scalars = [rng.randrange(R) for _ in range(n)]
    bases = g1_to_affine_arrays(pts)
    w = 8
    mags, negs = jmsm.signed_digit_planes_from_limbs(limbs(scalars), w)
    aff = jax.jit(lambda b, m, s: msm_windowed_affine(G1J, b, m, s, lanes=4096, window=w))
    jac = jax.jit(lambda b, m, s: jmsm.msm_windowed_signed(G1J, b, m, s, lanes=4096, window=w))
    for name, fn in (("jacobian", jac), ("affine", aff)):
        t0 = time.perf_counter()
        r = fn(bases, mags, negs)
        jax.block_until_ready(r)
        compile_s = time.perf_counter() - t0
        ts = []
        for _ in range(3):
            t0 = time.perf_counter()
            jax.block_until_ready(fn(bases, mags, negs))
            ts.append(time.perf_counter() - t0)
        best = min(ts)
        print(
            f"{name}: first={compile_s:.1f}s steady={best:.3f}s -> {n/best/1e6:.3f} M pts/s",
            flush=True,
        )

    # ------------------------- 3. bucket MSM (lever 2): correctness + A/B
    from zkp2p_tpu.ops.msm_bucket import msm_bucket_affine

    nb = 4096
    pts_b = [base_pts[i % 64] for i in range(nb)]
    pts_b[3] = None
    sc_b = [rng.randrange(R) for _ in range(nb)]
    sc_b[7] = 0
    bases_b = g1_to_affine_arrays(pts_b)
    mags8, negs8 = jmsm.signed_digit_planes_from_limbs(limbs(sc_b), 8)
    t0 = time.perf_counter()
    got = g1_jac_to_host(
        jax.jit(lambda b, m, s: msm_bucket_affine(G1J, b, m, s, window=8))(bases_b, mags8, negs8)
    )[0]
    want = g1_jac_to_host(
        jax.jit(lambda b, m, s: jmsm.msm_windowed_signed(G1J, b, m, s, lanes=512, window=8))(
            bases_b, mags8, negs8
        )
    )[0]
    ok = got == want
    print(f"bucket correctness w=8: {'OK' if ok else 'MISMATCH'} ({time.perf_counter()-t0:.1f}s incl compile)", flush=True)
    if not ok:
        print("BUCKET TIER MISCOMPARES ON HARDWARE — do not arm", flush=True)
        return 1

    mags16, negs16 = jmsm.signed_digit_planes_from_limbs(limbs(scalars), 16)
    bkt = jax.jit(lambda b, m, s: msm_bucket_affine(G1J, b, m, s, window=16))
    t0 = time.perf_counter()
    jax.block_until_ready(bkt(bases, mags16, negs16))
    compile_s = time.perf_counter() - t0
    ts = []
    for _ in range(3):
        t0 = time.perf_counter()
        jax.block_until_ready(bkt(bases, mags16, negs16))
        ts.append(time.perf_counter() - t0)
    best = min(ts)
    print(
        f"bucket w=16: first={compile_s:.1f}s steady={best:.3f}s -> {n/best/1e6:.3f} M pts/s",
        flush=True,
    )
    return 0


if __name__ == "__main__":
    sys.exit(main())
