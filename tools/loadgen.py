"""QPS-under-SLO load generator + capacity model for the proving service.

BENCH records "proofs/s min-of-reps" — the number a *benchmark* buys.
A deployment buys a different number: the max arrival rate this host
sustains while holding a latency objective (ROADMAP item 2).  This tool
measures it: an **open-loop Poisson** arrival process (arrivals do NOT
wait for completions — the honest model of independent users; a closed
loop self-throttles and hides saturation) writes spool requests at a
target rate, ramps the rate stepwise, and scores each step against the
p95 objective with the same SLO math the service exposes on /status
(utils.slo).  Output: a capacity JSON naming max sustainable QPS for
this host shape.

    python tools/loadgen.py --spool /tmp/lg --rates 0.5,1,2 --step-s 20 \
        --objective-s 30 --circuit toy --out capacity.json

  --circuit toy    hermetic 2-constraint circuit (the chaos-harness
                   world) — a stub-speed prover for smokes; --prove-s
                   adds artificial per-request service time (scaled by
                   batch fill, in-process and --fleet alike) so
                   saturation is reachable in a 2-second test.
  --circuit venmo  the bench-shape 499k-constraint flagship: one
                   synthetic signed email's witness is built once and
                   replayed per request (witnessing is not what this
                   tool measures), every request is a REAL native
                   prove.  Uses the .bench_cache key like bench.py.

By default the tool runs the service in-process (a worker thread
sweeping the spool with the multi-column native batch prover, preflight
armed, metrics/status endpoint on when ZKP2P_METRICS_PORT is set, the
time-series sampler ticking).  --no-service drives an externally
running worker instead: this tool only writes requests and scores the
terminal artifacts.

Request latency is measured from artifact mtimes (req-file mtime →
terminal-file mtime) — the same spool arrival clock the service's
deadlines and queue_wait_s use, so loadgen numbers and service records
agree.  A request still unterminal when the drain window closes counts
as a MISS with latency = cutoff (an unfinished request is not evidence
the SLO held).

The capacity JSON is also wired into bench.py as the `service` arm
(BENCH_SERVICE_S), so trajectory records gain `service_qps_under_slo`.
"""

from __future__ import annotations

import argparse
import json
import os
import random
import sys
import threading
import time
import traceback
from typing import Callable, Dict, List, Optional, Tuple

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if REPO not in sys.path:
    sys.path.insert(0, REPO)

TERMINAL_SUFFIXES = (".proof.json", ".error.json")


# ------------------------------------------------------------ worlds


def _toy_world():
    """The deterministic 2-constraint chaos-harness circuit — ONE
    source of truth (tools/chaos.py `_build_world`); proves in
    milliseconds, so a smoke can reach saturation with --prove-s
    instead of minutes of real MSM."""
    import importlib.util

    spec = importlib.util.spec_from_file_location(
        "zkp2p_chaos", os.path.join(os.path.dirname(os.path.abspath(__file__)), "chaos.py"))
    chaos = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(chaos)
    cs, dpk, vk, witness_fn = chaos._build_world()

    def payload_fn(rng):
        return {"x": rng.randrange(2, 50), "y": rng.randrange(2, 50)}

    return cs, dpk, vk, witness_fn, (lambda w: [w[1]]), payload_fn, "toy"


def _venmo_world():
    """Bench-shape venmo (499k constraints) with the .bench_cache key:
    ONE synthetic signed email's witness, replayed per request — every
    prove is real; the capacity number measures the PROVING service,
    not the email parser."""
    import bench  # repo-root module; shares the key cache with bench runs

    cs, lay, make_input = bench._build_venmo()
    dpk, vk = bench.build_keys(cs)
    inputs = make_input(0)
    w = cs.witness(inputs.public_signals, inputs.seed)

    def witness_fn(_payload):
        return w

    def public_fn(wit):
        return list(wit[1 : cs.num_public + 1])

    def payload_fn(rng):
        return {"i": rng.randrange(1 << 30)}

    return cs, dpk, vk, witness_fn, public_fn, payload_fn, "venmo"


# ------------------------------------------------------------ capacity


def _write_request(spool: str, rid: str, payload: Dict) -> str:
    """Atomic request drop (tmp + rename): the service's torn-file grace
    window is for sloppy uploaders; the loadgen should not need it."""
    path = os.path.join(spool, rid + ".req.json")
    tmp = path + ".tmp"
    with open(tmp, "w") as f:
        json.dump(payload, f)
    os.replace(tmp, path)
    return path


def parse_trace(spec: str) -> List[Tuple[float, float]]:
    """Parse a piecewise arrival trace "rate x duration" segment list:
    "0.2x30,4x20,0.2x30" = 0.2 QPS for 30 s, a 4 QPS spike for 20 s,
    0.2 QPS for 30 s.  The low->spike->drain shape is THE scheduler
    A/B instrument (docs/SCHEDULING.md): a flat ramp never shows the
    batch-size controller moving.  Malformed specs raise ValueError
    BEFORE the multi-minute run."""
    segments: List[Tuple[float, float]] = []
    for part in spec.split(","):
        part = part.strip()
        if not part:
            continue
        try:
            rate_s, dur_s = part.lower().split("x")
            rate, dur = float(rate_s), float(dur_s)
        except ValueError:
            raise ValueError(f"bad --trace segment {part!r} (want 'RATExSECONDS,...')") from None
        if rate <= 0 or dur <= 0:
            raise ValueError(f"bad --trace segment {part!r}: rate and duration must be > 0")
        segments.append((rate, dur))
    if not segments:
        raise ValueError(f"--trace {spec!r} has no segments")
    return segments


def run_capacity(
    svc,
    spool: str,
    rates: List[float],
    step_s: float,
    objective_s: float,
    target: float = 0.95,
    payload_fn: Optional[Callable] = None,
    seed: int = 7,
    drain_s: Optional[float] = None,
    poll_s: float = 0.05,
    run_service: bool = True,
    circuit: str = "?",
    prove_sleep_s: float = 0.0,
    batch_overhead_s: float = 0.0,
    fleet_workers: int = 0,
    segments: Optional[List[Tuple[float, float]]] = None,
    log: Callable[[str], None] = lambda m: print(m, file=sys.stderr, flush=True),
) -> Dict:
    """Drive the ramp and score it; returns the capacity report dict.

    svc: a ProvingService (swept in-process when run_service) — pass
    None with run_service=False to only generate load for an external
    worker.  prove_sleep_s / batch_overhead_s: artificial service time
    added around the prover (per request scaled by batch fill + a
    per-batch fixed cost) — the same model the --fleet toy workers
    apply, so in-process and fleet capacity numbers share one
    service-time definition (smoke-scale saturation).  segments:
    explicit (rate, duration) pairs (--trace); None = one segment of
    step_s per entry in rates."""
    from zkp2p_tpu.pipeline.sched import normalize_sched as _normalize_sched
    from zkp2p_tpu.pipeline.service import TimeseriesSampler
    from zkp2p_tpu.utils.audit import execution_digest
    from zkp2p_tpu.utils.config import load_config
    from zkp2p_tpu.utils.metrics import REGISTRY, host_facts, run_id
    from zkp2p_tpu.utils.slo import SloTracker

    os.makedirs(spool, exist_ok=True)
    # Per-run rid prefix: a reused spool still holds prior runs'
    # terminal artifacts, and a colliding rid would score the OLD proof
    # as an instant completion (attainment 1.0 at every rate — a
    # fabricated capacity number).  Unique rids make stale artifacts
    # inert; scoring below looks up this run's rids only.
    run_tok = f"{os.getpid() & 0xFFFF:04x}{int(time.time() * 1000) & 0xFFFF:04x}"
    stale = [f for f in os.listdir(spool) if f.endswith(".req.json")]
    if stale:
        log(f"[loadgen] note: spool holds {len(stale)} pre-existing request(s); "
            f"this run's rids carry prefix lg{run_tok} and are scored alone")
    # The scoring objective IS this run's SLO: write it through to the
    # typed config so the in-process service's tracker, the
    # zkp2p_slo_* gauges behind /status, and the service_slo digest
    # arm all agree with the capacity math (runs at different
    # objectives stay digest-distinguishable).  Restored (and re-armed)
    # on the way out so a host process (bench's service arm) does not
    # inherit a tool-injected "env" objective in its knob manifest.
    # Scoring-only mode (run_service=False) drives an external process
    # — nothing here to reconcile.
    if not 0.0 < target < 1.0:
        raise ValueError(f"SLO target must be in (0,1), got {target}")
    from zkp2p_tpu.utils import slo as slo_mod

    saved_env: Dict[str, Optional[str]] = {}
    if run_service:
        for k, v in (("ZKP2P_SLO_P95_S", f"{objective_s:g}"),
                     ("ZKP2P_SLO_TARGET", f"{target:g}")):
            saved_env[k] = os.environ.get(k)
            os.environ[k] = v
        slo_mod._reset()
        slo_mod.slo_arm()
    try:
        rng = random.Random(seed)
        if payload_fn is None:
            payload_fn = lambda r: {"x": r.randrange(2, 50), "y": r.randrange(2, 50)}  # noqa: E731

        if (prove_sleep_s > 0 or batch_overhead_s > 0) and svc is not None and svc.prover_fn is not None:
            # fleet.slowed_prover is THE shared artificial-service-time
            # model (per request scaled by fill + per-batch overhead) —
            # the chaos/fleet toy workers wrap with the same helper, so
            # the in-process and --fleet capacity numbers stay
            # comparable by construction
            from zkp2p_tpu.pipeline.fleet import slowed_prover

            svc.prover_fn = slowed_prover(svc.prover_fn, prove_sleep_s, batch_overhead_s)

        stop = threading.Event()
        worker_errors: List[str] = []

        def worker():
            cfg = load_config()
            sampler = TimeseriesSampler(cfg.ts_sample_s, svc.stale_claim_s)
            svc._sampler = sampler
            while not stop.is_set():
                try:
                    svc.process_dir(spool)
                    sampler.maybe_sample(spool, svc._sink(spool))
                except Exception:  # noqa: BLE001 — the ramp must finish and report
                    worker_errors.append(traceback.format_exc())
                stop.wait(poll_s)

        th = None
        if run_service:
            th = threading.Thread(target=worker, daemon=True, name="loadgen-service")
            th.start()

        # ---- ramp: open-loop Poisson arrivals per segment (a --trace
        # spec, or one step_s segment per --rates entry)
        if segments is None:
            segments = [(r, step_s) for r in rates]
        steps_reqs: List[List[str]] = []
        t_ramp0 = time.time()
        for si, (rate, seg_s) in enumerate(segments):
            reqs: List[str] = []
            t_end = time.time() + seg_s
            t_next = time.time()
            while t_next < t_end:
                delay = t_next - time.time()
                if delay > 0:
                    time.sleep(delay)
                rid = f"lg{run_tok}s{si:02d}r{len(reqs):05d}"
                _write_request(spool, rid, payload_fn(rng))
                reqs.append(rid)
                t_next += rng.expovariate(rate)
            steps_reqs.append(reqs)
            log(f"[loadgen] step {si}: target {rate:g} QPS -> {len(reqs)} requests in {seg_s:g}s")

        # ---- drain: give in-flight work a bounded window to terminal
        if drain_s is None:
            drain_s = max(2 * max(s for _r, s in segments), 10.0)
        t_cutoff = time.time() + drain_s
        while time.time() < t_cutoff:
            open_reqs = [
                rid for reqs in steps_reqs for rid in reqs
                if not any(os.path.exists(os.path.join(spool, rid + s)) for s in TERMINAL_SUFFIXES)
            ]
            if not open_reqs:
                break
            time.sleep(min(0.2, poll_s * 4))
        if run_service:
            stop.set()
            th.join(timeout=30.0)

        # ---- score each step with the /status SLO math (window unbounded:
        # a ramp step is its own window)
        now = time.time()
        steps_out: List[Dict] = []
        for si, ((rate, seg_s), reqs) in enumerate(zip(segments, steps_reqs)):
            tracker = SloTracker(objective_s=objective_s, target=target, window_s=0.0)
            done = errors = unfinished = 0
            for rid in reqs:
                base = os.path.join(spool, rid)
                try:
                    t_sub = os.path.getmtime(base + ".req.json")
                except OSError:
                    t_sub = now
                if os.path.exists(base + ".proof.json"):
                    done += 1
                    tracker.observe(os.path.getmtime(base + ".proof.json") - t_sub, ok=True)
                elif os.path.exists(base + ".error.json"):
                    errors += 1
                    tracker.observe(os.path.getmtime(base + ".error.json") - t_sub, ok=False)
                else:
                    # never finished: a miss at the cutoff, not a free pass
                    unfinished += 1
                    tracker.observe(max(0.0, now - t_sub), ok=False)
            snap = tracker.snapshot()
            ok = bool(reqs) and snap["attainment"] >= target
            steps_out.append({
                "qps_target": rate,
                "offered": len(reqs),
                "done": done,
                "errors": errors,
                "unfinished": unfinished,
                # served-under-SLO: done AND inside the objective — THE
                # scheduler-A/B comparison count (a late `done` is not
                # a served request to an SLO)
                "served_under_slo": snap["good"],
                "duration_s": round(seg_s, 3),
                "completed_qps": round(done / seg_s, 4) if seg_s > 0 else 0.0,
                "p50_s": snap["p50_s"],
                "p95_s": snap["p95_s"],
                "max_s": snap["max_s"],
                "attainment": snap["attainment"],
                "burn_rate": snap["burn_rate"],
                "ok": ok,
            })
            log(
                f"[loadgen] step {si}: {rate:g} QPS offered={len(reqs)} done={done} "
                f"under_slo={snap['good']} p95={snap['p95_s']:.2f}s "
                f"attainment={snap['attainment']:.3f} {'OK' if ok else 'MISS'}"
            )

        passing = [s["qps_target"] for s in steps_out if s["ok"]]
        report = {
            "type": "capacity",
            "ts": round(t_ramp0, 3),
            "run_id": run_id(),
            "pid": os.getpid(),
            "host": host_facts(),
            "execution_digest": execution_digest(),
            "circuit": circuit,
            "arrivals": "open-loop poisson",
            "seed": seed,
            "objective_p95_s": objective_s,
            "target": target,
            "step_s": step_s,
            "trace": ",".join(f"{r:g}x{s:g}" for r, s in segments),
            # the scheduler arm that served this run (capacity numbers
            # at different arms are not comparable without it; ONE
            # normalization rule, owned by pipeline.sched)
            "sched": _normalize_sched(load_config().sched),
            "drain_s": round(drain_s, 3),
            "steps": steps_out,
            # THE number: the highest offered rate whose step held the
            # objective.  0.0 = no step held it (rates all above capacity —
            # re-run lower), reported honestly rather than extrapolated.
            "max_sustainable_qps": max(passing) if passing else 0.0,
            # whole-run served-under-SLO count: the scheduler A/B's
            # scalar (per-segment splits live in `steps`)
            "served_under_slo": sum(s["served_under_slo"] for s in steps_out),
        }
        if fleet_workers:
            # the serving side was an N-worker fleet (external processes
            # under the `zkp2p-tpu fleet` supervisor), not the
            # in-process service — capacity numbers at different N are
            # not comparable without this field
            report["fleet_workers"] = fleet_workers
        if worker_errors:
            report["worker_errors"] = worker_errors[:3]
        # service-observability counters snapshot for the record
        fills = [
            m for m in REGISTRY.snapshot()
            if m["name"] == "zkp2p_service_batch_fill" and m["kind"] == "histogram"
        ]
        if fills and fills[0]["count"]:
            report["mean_batch_fill"] = round(fills[0]["sum"] / fills[0]["count"], 3)
        return report
    finally:
        if run_service:
            for k, v in saved_env.items():
                if v is None:
                    os.environ.pop(k, None)
                else:
                    os.environ[k] = v
            slo_mod._reset()
            slo_mod.slo_arm()


# ------------------------------------------------------------ CLI


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.split("\n", 1)[0])
    ap.add_argument("--spool", required=True, help="spool directory (created if absent)")
    ap.add_argument("--rates", default="0.5,1,2",
                    help="comma-separated target QPS per ramp step")
    ap.add_argument("--step-s", type=float, default=20.0, help="seconds per ramp step")
    ap.add_argument("--trace", default="",
                    help="piecewise arrival trace 'RATExSECONDS,...' (e.g. "
                         "'0.2x30,4x20,0.2x30' = low->spike->drain; overrides "
                         "--rates/--step-s; scored per segment)")
    ap.add_argument("--objective-s", type=float, default=None,
                    help="p95 latency objective in s (default: ZKP2P_SLO_P95_S, else 30)")
    ap.add_argument("--target", type=float, default=None,
                    help="attainment target fraction (default: ZKP2P_SLO_TARGET)")
    ap.add_argument("--circuit", choices=["toy", "venmo"], default="toy")
    ap.add_argument("--batch", type=int, default=4, help="service batch size")
    ap.add_argument("--seed", type=int, default=7)
    ap.add_argument("--prove-s", type=float, default=0.0,
                    help="artificial PER-REQUEST prove time, scaled by batch fill "
                         "(smoke-scale saturation; same model in-process and --fleet)")
    ap.add_argument("--batch-overhead-s", type=float, default=0.0,
                    help="artificial PER-BATCH fixed prove cost (models the "
                         "amortization curve's setup term; same model in-process "
                         "and --fleet)")
    ap.add_argument("--sched", choices=["off", "adaptive"], default=None,
                    help="scheduler arm for the serving side (writes ZKP2P_SCHED; "
                         "default: inherit the environment)")
    ap.add_argument("--fleet-min", type=int, default=None,
                    help="with --fleet: autoscale floor (--workers-min)")
    ap.add_argument("--fleet-max", type=int, default=None,
                    help="with --fleet: autoscale ceiling (--workers-max; the "
                         "autoscale demo arm)")
    ap.add_argument("--drain-s", type=float, default=None,
                    help="max wait for in-flight work after the ramp (default 2*step)")
    ap.add_argument("--no-service", action="store_true",
                    help="only generate load; an external worker sweeps the spool")
    ap.add_argument("--fleet", type=int, default=0,
                    help="serve the ramp with N toy workers under the `zkp2p-tpu fleet` "
                         "supervisor (subprocesses) instead of the in-process service — "
                         "the fleet-scaling arm of the capacity model (toy circuit only)")
    ap.add_argument("--out", default="", help="also write the capacity JSON to this path")
    args = ap.parse_args(argv)

    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    os.environ.pop("PALLAS_AXON_POOL_IPS", None)  # never dial the TPU tunnel

    from zkp2p_tpu.pipeline.service import ProvingService
    from zkp2p_tpu.prover.native_prove import prove_native_batch
    from zkp2p_tpu.utils.audit import preflight
    from zkp2p_tpu.utils.config import load_config
    from zkp2p_tpu.utils.metrics import maybe_start_metrics_server

    # the scheduler arm rides the env (fresh-read per sweep): the flag
    # covers the in-process service AND the --fleet workers (inherited)
    if args.sched is not None:
        os.environ["ZKP2P_SCHED"] = args.sched

    cfg = load_config()
    objective_s = args.objective_s if args.objective_s is not None else (cfg.slo_p95_s or 30.0)
    target = args.target if args.target is not None else cfg.slo_target
    segments = None
    if args.trace:
        try:
            segments = parse_trace(args.trace)
        except ValueError as e:
            print(f"[loadgen] {e}", file=sys.stderr)
            return 2
        rates = [r for r, _s in segments]
    else:
        rates = [float(r) for r in args.rates.split(",") if r.strip()]
    if not rates or any(r <= 0 for r in rates):
        print(f"[loadgen] bad --rates {args.rates!r}: need positive QPS values", file=sys.stderr)
        return 2
    # fail BEFORE the multi-minute ramp, not at scoring time
    if not 0.0 < target < 1.0:
        print(f"[loadgen] bad --target {target!r}: need a fraction in (0,1)", file=sys.stderr)
        return 2

    if args.fleet and args.circuit != "toy":
        print("[loadgen] --fleet serves the toy circuit only (each worker is a "
              "fresh process; venmo workers would each rebuild the 499k key)", file=sys.stderr)
        return 2

    svc = None
    payload_fn = None
    circuit = args.circuit
    fleet_proc = None
    if args.fleet:
        # N subprocess workers under the fleet supervisor sweep the
        # spool; this process only generates + scores (the external-
        # worker mode of run_capacity).  Workers linger past spool-
        # terminal — the ramp writes continuously — and drain on the
        # supervisor's SIGTERM at the end.
        import signal as _signal
        import subprocess

        os.makedirs(args.spool, exist_ok=True)
        # per-RUN fleet dir: a reused spool's previous .fleet would
        # satisfy the readiness gate below with STALE heartbeats before
        # the supervisor even starts, billing N cold starts as queue
        # latency — the exact artifact the gate exists to prevent
        fleet_dir = os.path.join(args.spool, f".fleet-{os.getpid():x}{int(time.time()) & 0xFFFF:04x}")
        worker_argv = [
            sys.executable,
            os.path.join(os.path.dirname(os.path.abspath(__file__)), "chaos.py"),
            "--worker", "--linger",
            "--spool", args.spool,
            "--batch", str(args.batch),
            "--prove-s", str(args.prove_s),
            "--batch-overhead-s", str(args.batch_overhead_s),
            "--max-seconds", "100000",
            "--poll-s", "0.05",
        ]
        env = dict(os.environ)
        env.pop("PALLAS_AXON_POOL_IPS", None)
        env["JAX_PLATFORMS"] = "cpu"
        # the fleet observability plane rides the run (auto port, bound
        # port in status.json): it IS the readiness gate below, and its
        # merged SLO + fired alerts land in the capacity JSON.  Parse-
        # checked, not setdefault: an explicitly EMPTY (or junk) value
        # in the caller's environment also means plane-off, and a
        # plane-less fleet can never pass the /status gate.
        from zkp2p_tpu.utils.config import _opt_port

        if _opt_port(env.get("ZKP2P_FLEET_METRICS_PORT") or "") is None:
            env["ZKP2P_FLEET_METRICS_PORT"] = "auto"
        # the scoring objective is the WORKERS' objective too — the
        # merged fleet window recorded at teardown must judge "good"
        # by the same bound the capacity math scores against (the
        # in-process arm writes the same env through run_capacity)
        env["ZKP2P_SLO_P95_S"] = f"{objective_s:g}"
        env["ZKP2P_SLO_TARGET"] = f"{target:g}"
        fleet_argv = [
            sys.executable, "-m", "zkp2p_tpu", "fleet",
            "--spool", args.spool,
            "--workers", str(args.fleet),
            "--fleet-dir", fleet_dir,
            "--worker-cmd", json.dumps(worker_argv),
        ]
        if args.fleet_min is not None:
            fleet_argv += ["--workers-min", str(args.fleet_min)]
        if args.fleet_max is not None:
            # the autoscale demo arm: workers grow on the spike, drain
            # back down after it (pipeline.sched.AutoscalePolicy)
            fleet_argv += ["--workers-max", str(args.fleet_max)]
        fleet_proc = subprocess.Popen(fleet_argv, env=env, cwd=REPO)
        # readiness gate: score only once the FLEET /status answers 200
        # — i.e. every live worker is up, scrapable, AND has armed its
        # gates (preflight).  Stronger than the old N-heartbeat-files
        # check: a stale .hb can't fake readiness, an unarmed worker
        # can't hide, and step 0 never pays N cold python/jax imports
        # billed as queue latency.
        from zkp2p_tpu.pipeline.fleet_obs import discover_fleet_port, http_status_json

        deadline = time.time() + 120.0
        fleet_status_url = None
        last_reason = "status.json has no metrics_port yet"
        while time.time() < deadline:
            if fleet_proc.poll() is not None:
                print("[loadgen] fleet supervisor died before the ramp", file=sys.stderr)
                return 2
            if fleet_status_url is None:
                port = discover_fleet_port(fleet_dir)
                if port:
                    fleet_status_url = f"http://127.0.0.1:{port}/status"
            if fleet_status_url is not None:
                st = http_status_json(fleet_status_url)
                if st and st.get("ok"):
                    break
                if st:
                    last_reason = st.get("reason", "not ready")
            time.sleep(0.1)
        else:
            fleet_proc.kill()
            print(f"[loadgen] fleet never became ready ({last_reason})", file=sys.stderr)
            return 2
        print(
            f"[loadgen] fleet ready: /status 200 ({args.fleet} armed workers)",
            file=sys.stderr,
        )
    elif not args.no_service:
        world = _toy_world() if args.circuit == "toy" else _venmo_world()
        cs, dpk, vk, witness_fn, public_fn, payload_fn, circuit = world
        svc = ProvingService(
            cs, dpk, vk, witness_fn, public_fn=public_fn,
            batch_size=args.batch, prover_fn=prove_native_batch,
        )
        # arm the gates (also opens /status — it fails closed until a
        # preflight has run) and the exposition endpoint when configured
        preflight(probe=False, workload=False,
                  log=lambda m: print(f"[loadgen] {m}", file=sys.stderr, flush=True))
        maybe_start_metrics_server()

    try:
        report = run_capacity(
            svc, args.spool, rates, args.step_s, objective_s, target=target,
            payload_fn=payload_fn, seed=args.seed, drain_s=args.drain_s,
            run_service=not args.no_service and not args.fleet, circuit=circuit,
            prove_sleep_s=args.prove_s, batch_overhead_s=args.batch_overhead_s,
            fleet_workers=args.fleet, segments=segments,
        )
        if args.fleet and fleet_status_url:
            # the serving fleet's own read of the run, BEFORE teardown:
            # merged SLO (sample count = sum of worker windows) and
            # every alert that fired — a capacity number whose run
            # tripped restart_storm or slo_burn is not a capacity number
            fs = http_status_json(fleet_status_url, timeout=5)
            if fs:
                report["fleet_slo"] = fs.get("slo")
                # autoscale record: band, live count, every scale event
                # this run took (the demo's acceptance surface)
                report["fleet_sched"] = fs.get("sched")
                report["fleet_alerts"] = {
                    "active": fs.get("alerts", []),
                    "fired": {
                        rule: st.get("fired_count", 0)
                        for rule, st in (fs.get("alerts_state") or {}).items()
                        if st.get("fired_count")
                    },
                }
    finally:
        if fleet_proc is not None and fleet_proc.poll() is None:
            # graceful fleet teardown: SIGTERM fans drain out to the
            # workers; the supervisor escalates stragglers itself
            fleet_proc.send_signal(_signal.SIGTERM)
            try:
                fleet_proc.wait(timeout=60)
            except subprocess.TimeoutExpired:
                fleet_proc.kill()
    print(json.dumps(report, indent=1))
    if args.out:
        with open(args.out, "w") as f:
            json.dump(report, f, indent=1)
    print(
        f"[loadgen] max sustainable QPS at p95<={objective_s:g}s "
        f"(target {target:g}): {report['max_sustainable_qps']:g}",
        file=sys.stderr,
    )
    return 0


if __name__ == "__main__":
    sys.exit(main())
