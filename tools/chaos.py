"""Chaos harness for the proving service (docs/ROBUSTNESS.md §chaos).

Spawns N worker subprocesses sweeping ONE spool of requests, SIGKILLs
some of them provably MID-PROVE (the victim is chosen by reading the
pid out of a live `.claim` file — a worker that demonstrably owns
in-flight work), injects faults via ZKP2P_FAULTS across the service's
sites, waits for the survivors to drain the spool, then asserts the
global invariant the service claims to provide:

  1. every request reached EXACTLY ONE terminal state
     (.proof.json xor .error.json — never both, never neither);
  2. every emitted proof pairing-verifies against its public signals,
     and the public signals match the request payload;
  3. no request_id has duplicate terminal records in the metrics sink.

Exit 0 = invariant holds; 1 = violated (details in the JSON report on
stdout).  The circuit is the 2-constraint toy from the service tests —
chaos exercises the SERVING layer's failure machinery, not the prover's
arithmetic (the byte-parity suites own that).

    python tools/chaos.py --workers 2 --kills 1 --requests 6 \
        --faults "seed=7,witness:hang=0.2,prove:raise:p=0.2,emit:enospc:once,claim:raise:p=0.05"

A worker process is this same file with --worker (it builds the
deterministic toy world, then sweeps until the spool is fully terminal
or --max-seconds expires).  `make chaos-smoke` runs the tier-1 shape.
"""

from __future__ import annotations

import argparse
import json
import os
import signal
import subprocess
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if REPO not in sys.path:
    sys.path.insert(0, REPO)

TERMINAL_SUFFIXES = (".proof.json", ".error.json")


# ----------------------------------------------------------- toy world


def _build_world():
    """The deterministic 2-constraint circuit (out = (x*y)^2) every
    worker and the checker rebuild identically (setup seed pins the
    keys, so a proof emitted by any worker verifies under the checker's
    vk)."""
    from zkp2p_tpu.field.bn254 import R
    from zkp2p_tpu.prover.groth16_tpu import device_pk
    from zkp2p_tpu.snark.groth16 import setup
    from zkp2p_tpu.snark.r1cs import LC, ConstraintSystem

    cs = ConstraintSystem("chaos")
    out = cs.new_public("out")
    x = cs.new_wire("x")
    y = cs.new_wire("y")
    z = cs.new_wire("z")
    cs.enforce(LC.of(x), LC.of(y), LC.of(z), "mul")
    cs.enforce(LC.of(z), LC.of(z), LC.of(out), "sq")
    cs.compute(z, lambda a, b: a * b % R, [x, y])
    pk, vk = setup(cs, seed="chaos")
    dpk = device_pk(pk, cs)

    def witness_fn(payload):
        xv, yv = int(payload["x"]), int(payload["y"])
        return cs.witness([pow(xv * yv, 2, R)], {x: xv, y: yv})

    return cs, dpk, vk, witness_fn


# -------------------------------------------------------------- worker


def worker_main(args) -> int:
    from zkp2p_tpu.pipeline.fleet import install_drain_handlers, slowed_prover
    from zkp2p_tpu.pipeline.service import ProvingService
    from zkp2p_tpu.prover.native_prove import prove_native_batch

    cs, dpk, vk, witness_fn = _build_world()
    # artificial PER-REQUEST service time (loadgen --fleet smokes: the
    # toy prove is µs — saturation and mid-prove kill windows need
    # batches that HOLD claims for a while); fleet.slowed_prover is THE
    # shared model, so fleet and in-process capacity stay comparable
    prover_fn = slowed_prover(prove_native_batch, args.prove_s, args.batch_overhead_s)
    svc = ProvingService(
        cs, dpk, vk, witness_fn, public_fn=lambda w: [w[1]],
        batch_size=args.batch,
        prover_fn=prover_fn,
        stale_claim_s=args.stale_claim_s,
        retry_backoff_s=0.05,
    )
    # fleet semantics ride the service run loop: SIGTERM/SIGINT drain
    # (stop claiming, finish in-flight, flush, exit 0), heartbeats +
    # governor ctl via ZKP2P_FLEET_DIR when a supervisor spawned us
    install_drain_handlers(svc)
    print(f"[chaos-worker {os.getpid()}] up, sweeping {args.spool}", flush=True)
    why = svc.run(
        args.spool, poll_s=args.poll_s,
        max_seconds=args.max_seconds,
        # --linger: keep sweeping an empty/terminal spool (loadgen fleet
        # workers outlive the ramp); default chaos workers exit once
        # every request is terminal
        exit_when_spool_terminal=not args.linger,
    )
    print(f"[chaos-worker {os.getpid()}] exiting ({why})", flush=True)
    return 0 if why in ("drained", "terminal") else 2


# ----------------------------------------------------------- invariant


def check_invariants(spool: str, vk=None) -> dict:
    """The global invariant (docs/ROBUSTNESS.md): returns a report dict
    with `violations` (empty = invariant holds).  Standalone-callable on
    any spool a chaos (or production) run left behind."""
    from zkp2p_tpu.field.bn254 import R
    from zkp2p_tpu.formats.proof_json import load, proof_from_json
    from zkp2p_tpu.snark.groth16 import verify

    if vk is None:
        _, _, vk, _ = _build_world()
    violations = []
    states = {}
    verified = 0
    rids = []
    for fn in sorted(os.listdir(spool)):
        if not fn.endswith(".req.json"):
            continue
        rid = fn[: -len(".req.json")]
        rids.append(rid)
        base = os.path.join(spool, rid)
        has_proof = os.path.exists(base + ".proof.json")
        has_error = os.path.exists(base + ".error.json")
        if has_proof and has_error:
            violations.append(f"{rid}: BOTH proof and error artifacts")
        elif not has_proof and not has_error:
            violations.append(f"{rid}: NO terminal state")
        states[rid] = "done" if has_proof else ("error" if has_error else "open")
        if has_proof:
            try:
                proof = proof_from_json(load(base + ".proof.json"))
                pub = [int(v) for v in load(base + ".public.json")]
                with open(base + ".req.json") as f:
                    payload = json.load(f)
                want = [pow(int(payload["x"]) * int(payload["y"]), 2, R)]
                if pub != want:
                    violations.append(f"{rid}: public signals {pub} != payload-derived {want}")
                elif not verify(vk, proof, pub):
                    violations.append(f"{rid}: proof FAILED pairing verification")
                else:
                    verified += 1
            except Exception as e:  # noqa: BLE001 — torn artifact = violation
                violations.append(f"{rid}: unreadable proof artifacts ({e})")

    # terminal records: at most one per rid across every worker's sink
    # writes (the sink is shared, O_APPEND, line-atomic).  Missing
    # records are legal (sink faults, SIGKILL between artifact and
    # record) — duplicates are not.
    rec_counts: dict = {}
    sink = spool.rstrip("/") + ".metrics.jsonl"
    for path in [sink] + [f"{sink}.{i}" for i in range(1, 4)]:
        if not os.path.exists(path):
            continue
        with open(path) as f:
            for line in f:
                try:
                    rec = json.loads(line)
                except ValueError:
                    violations.append(f"{os.path.basename(path)}: torn sink line")
                    continue
                if rec.get("type") == "request" and rec.get("state") != "deferred":
                    # TERMINAL records only: deferred attempt lines
                    # (state="deferred", one per retried sweep — the
                    # request-waterfall history) are expected repeats,
                    # not duplicate terminals
                    rec_counts[rec["request_id"]] = rec_counts.get(rec["request_id"], 0) + 1
    for rid, n in sorted(rec_counts.items()):
        if n > 1:
            violations.append(f"{rid}: {n} terminal records (duplicate)")

    counts: dict = {}
    for s in states.values():
        counts[s] = counts.get(s, 0) + 1
    return {
        "requests": len(rids),
        "states": counts,
        "proofs_verified": verified,
        "terminal_records": sum(rec_counts.values()),
        "violations": violations,
    }


# -------------------------------------------------------------- parent


def _live_claim_pids(spool: str) -> list:
    pids = []
    for fn in os.listdir(spool):
        if fn.endswith(".claim"):
            try:
                with open(os.path.join(spool, fn)) as f:
                    pid = json.load(f).get("pid")
                if pid:
                    pids.append(int(pid))
            except (OSError, ValueError):
                continue
    return pids


def run_chaos(args) -> dict:
    import random

    os.makedirs(args.spool, exist_ok=True)
    rng = random.Random(args.seed)
    for i in range(args.requests):
        with open(os.path.join(args.spool, f"q{i:03d}.req.json"), "w") as f:
            json.dump({"x": rng.randrange(2, 50), "y": rng.randrange(2, 50)}, f)

    env = dict(os.environ)
    env.pop("PALLAS_AXON_POOL_IPS", None)  # never dial the TPU tunnel
    env["JAX_PLATFORMS"] = "cpu"
    env["ZKP2P_FAULTS"] = args.faults
    env.pop("ZKP2P_METRICS_SINK", None)  # per-spool sink = the shared record file
    cmd = [
        sys.executable, os.path.abspath(__file__), "--worker",
        "--spool", args.spool,
        "--batch", str(args.batch),
        "--stale-claim-s", str(args.stale_claim_s),
        "--max-seconds", str(args.max_seconds),
        "--poll-s", str(args.poll_s),
    ]
    workers = [subprocess.Popen(cmd, env=env, cwd=REPO) for _ in range(args.workers)]
    print(f"[chaos] {args.workers} workers up: {[w.pid for w in workers]}", flush=True)

    # Kill phase: a victim must provably be MID-PROVE — we take the pid
    # from a live .claim file.  Never kill the last standing worker (the
    # invariant needs a survivor to drain the spool).
    killed = []
    deadline = time.time() + args.max_seconds
    while len(killed) < args.kills and time.time() < deadline:
        alive = [w for w in workers if w.poll() is None and w.pid not in killed]
        if len(alive) <= 1:
            break
        candidates = [p for p in _live_claim_pids(args.spool)
                      if p in {w.pid for w in alive}]
        if candidates:
            victim = candidates[0]
            os.kill(victim, signal.SIGKILL)
            killed.append(victim)
            print(f"[chaos] SIGKILL {victim} (owned a live claim)", flush=True)
        else:
            time.sleep(0.02)

    # Drain phase: wait for survivors to finish the spool.
    rc = {}
    for w in workers:
        if w.pid in killed:
            w.wait()
            continue
        remaining = max(1.0, deadline + 15.0 - time.time())
        try:
            rc[w.pid] = w.wait(timeout=remaining)
        except subprocess.TimeoutExpired:
            w.kill()
            rc[w.pid] = "timeout"

    report = check_invariants(args.spool)
    report.update({
        "workers": args.workers,
        "kills": len(killed),
        "killed_pids": killed,
        "worker_rc": rc,
        "faults": args.faults,
    })
    if args.kills and not killed:
        report["violations"].append(
            f"harness: no mid-prove SIGKILL landed (wanted {args.kills})"
        )
    return report


# --------------------------------------------------------------- fleet


def _fleet_pids(fleet_dir: str) -> dict:
    """worker id -> pid, from the supervisor's status.json (written per
    tick, so pids are visible the moment workers spawn — heartbeats
    only land once a worker finishes its first sweep) with the
    heartbeat files as fallback."""
    pids = {}
    try:
        with open(os.path.join(fleet_dir, "status.json")) as f:
            status = json.load(f)
        for wid, w in status.get("workers", {}).items():
            if w.get("pid"):
                pids[wid] = int(w["pid"])
    except (OSError, ValueError):
        pass
    try:
        names = os.listdir(fleet_dir)
    except OSError:
        return pids
    for fn in names:
        if not fn.endswith(".hb"):
            continue
        try:
            with open(os.path.join(fleet_dir, fn)) as f:
                hb = json.load(f)
            if hb.get("pid"):
                pids.setdefault(fn[:-3], int(hb["pid"]))
        except (OSError, ValueError):
            continue
    return pids


def _live_claims(spool: str) -> list:
    """[(rid, owner_pid)] for every live .claim file."""
    out = []
    for fn in os.listdir(spool):
        if fn.endswith(".claim"):
            try:
                with open(os.path.join(spool, fn)) as f:
                    pid = json.load(f).get("pid")
                if pid:
                    out.append((fn[: -len(".claim")], int(pid)))
            except (OSError, ValueError):
                continue
    return out


def _http_json(url: str, timeout: float = 3.0):
    # the ONE fleet-status client (fleet_obs): a 503 body is still the
    # status JSON
    from zkp2p_tpu.pipeline.fleet_obs import http_status_json

    return http_status_json(url, timeout=timeout)


def _http_text(url: str, timeout: float = 3.0):
    import urllib.request

    try:
        with urllib.request.urlopen(url, timeout=timeout) as resp:
            return resp.read().decode()
    except (OSError, ValueError):
        return None


def _prom_counters(text: str, name: str) -> dict:
    """{label-string: value} for one counter family out of Prometheus
    exposition text (the fleet /metrics side of the parity check)."""
    out = {}
    for line in (text or "").splitlines():
        if not line.startswith(name):
            continue
        rest = line[len(name):]
        if rest.startswith("{"):
            labels, _, val = rest[1:].partition("} ")
        elif rest.startswith(" "):
            labels, val = "", rest[1:]
        else:
            continue
        try:
            out[labels] = out.get(labels, 0.0) + float(val)
        except ValueError:
            pass
    return out


def check_plane(args, env) -> dict:
    """The fleet-observability-plane assertions (ISSUE-12 satellite),
    run as two self-contained mini-fleets after the main chaos phases:

      A. FEDERATION PARITY under fault: a lingering 2-worker fleet
         serves a small spool to terminal (faults still armed); once
         quiesced, the fleet /metrics `zkp2p_service_requests_total`
         counters must EQUAL the sum of the live workers' /snapshot
         counters — the merge invents nothing and loses nothing.
      B. RESTART STORM: a crash-looping worker under breaker_k=2 must
         get PARKED, and the plane's restart_storm alert must FIRE
         (status.json alert state + zkp2p_fleet_alerts_total).
    """
    report = {"violations": []}
    env = dict(env)
    env["ZKP2P_FLEET_SCRAPE_S"] = "0.5"
    env["ZKP2P_FLEET_METRICS_PORT"] = "auto"

    # ---- A: counter federation parity
    spool = args.spool.rstrip("/") + "_plane"
    os.makedirs(spool, exist_ok=True)
    for i in range(6):
        with open(os.path.join(spool, f"p{i:03d}.req.json"), "w") as f:
            json.dump({"x": 3 + i, "y": 5 + i}, f)
    fleet_dir = os.path.join(spool, ".fleet")
    worker_argv = [
        sys.executable, os.path.abspath(__file__), "--worker", "--linger",
        "--spool", spool, "--batch", "2", "--poll-s", "0.05",
        "--max-seconds", "90", "--prove-s", "0.1",
    ]
    sup = subprocess.Popen(
        [sys.executable, "-m", "zkp2p_tpu", "fleet",
         "--spool", spool, "--workers", "2", "--fleet-dir", fleet_dir,
         "--fleet-metrics-port", "auto", "--restart-backoff-s", "0.2",
         "--max-seconds", "90", "--worker-cmd", json.dumps(worker_argv)],
        env=env, cwd=REPO,
    )
    try:
        from zkp2p_tpu.pipeline.fleet_obs import discover_fleet_port

        deadline = time.time() + 60
        port = None
        while time.time() < deadline and port is None:
            port = discover_fleet_port(fleet_dir)
            time.sleep(0.1)
        status = None
        while time.time() < deadline:
            status = _http_json(f"http://127.0.0.1:{port}/status") if port else None
            if status and status.get("ok"):
                break
            time.sleep(0.2)
        if not (status and status.get("ok")):
            report["violations"].append("plane: fleet /status never reached 200")
            return report
        # serve to terminal, then let the scrape loop catch up.  A
        # quiesce TIMEOUT is its own violation and ends the check: a
        # counter comparison against a still-moving fleet would report
        # a misleading federation-parity failure for what is really a
        # slow-host harness problem.
        from zkp2p_tpu.pipeline.service import spool_terminal

        while time.time() < deadline and not spool_terminal(spool):
            time.sleep(0.2)
        if not spool_terminal(spool):
            report["violations"].append(
                "plane: harness spool never quiesced inside the deadline "
                "(parity not comparable; not a federation failure)"
            )
            return report
        time.sleep(2.0)  # >= 2 scrape intervals: counters quiesced AND federated
        status = _http_json(f"http://127.0.0.1:{port}/status")
        fleet_text = _http_text(f"http://127.0.0.1:{port}/metrics")
        fleet_counts = _prom_counters(fleet_text, "zkp2p_service_requests_total")
        worker_sum: dict = {}
        scraped = 0
        for wid, w in (status.get("workers") or {}).items():
            if w.get("state") not in ("up", "starting", "draining") or not w.get("port"):
                continue
            snap = _http_json(f"http://127.0.0.1:{w['port']}/snapshot")
            if snap is None:
                report["violations"].append(f"plane: worker {wid} /snapshot unreachable")
                continue
            scraped += 1
            for m in snap.get("metrics") or []:
                if m["name"] == "zkp2p_service_requests_total" and m["kind"] == "counter":
                    key = ",".join(f'{k}="{v}"' for k, v in sorted(m["labels"].items()))
                    worker_sum[key] = worker_sum.get(key, 0.0) + m["value"]
        report["parity"] = {
            "fleet": fleet_counts, "worker_sum": worker_sum, "workers_scraped": scraped,
        }
        if scraped < 2:
            report["violations"].append(f"plane: only {scraped} worker snapshots scraped")
        if fleet_counts != worker_sum:
            report["violations"].append(
                f"plane: fleet /metrics request counters {fleet_counts} != "
                f"per-worker sums {worker_sum}"
            )
        n_done = sum(v for k, v in worker_sum.items() if 'state="done"' in k)
        n_proofs = len([f for f in os.listdir(spool) if f.endswith(".proof.json")])
        if n_done != n_proofs:
            report["violations"].append(
                f"plane: summed done counter {n_done} != {n_proofs} proof artifacts"
            )
    finally:
        if sup.poll() is None:
            sup.send_signal(signal.SIGTERM)
            try:
                sup.wait(timeout=60)
            except subprocess.TimeoutExpired:
                sup.kill()

    # ---- B: breaker park -> restart_storm alert
    spool_b = args.spool.rstrip("/") + "_storm"
    os.makedirs(spool_b, exist_ok=True)
    fleet_dir_b = os.path.join(spool_b, ".fleet")
    sup_b = subprocess.run(
        [sys.executable, "-m", "zkp2p_tpu", "fleet",
         "--spool", spool_b, "--workers", "1", "--fleet-dir", fleet_dir_b,
         "--fleet-metrics-port", "auto", "--breaker-k", "2",
         "--breaker-window-s", "60", "--restart-backoff-s", "0.05",
         "--max-seconds", "45",
         "--worker-cmd", json.dumps([sys.executable, "-c", "import sys; sys.exit(1)"])],
        env=env, cwd=REPO, capture_output=True, text=True, timeout=120,
    )
    storm = {"supervisor_rc": sup_b.returncode}
    try:
        with open(os.path.join(fleet_dir_b, "status.json")) as f:
            st = json.load(f)
        storm["alerts_state"] = st.get("alerts_state")
        fired = ((st.get("alerts_state") or {}).get("restart_storm") or {}).get("fired_count", 0)
        if sup_b.returncode != 4:
            report["violations"].append(
                f"plane: storm fleet exited rc={sup_b.returncode} (want 4 = all parked)"
            )
        if not fired:
            report["violations"].append(
                "plane: breaker parked the worker but restart_storm never fired"
            )
    except (OSError, ValueError) as e:
        report["violations"].append(f"plane: storm status.json unreadable ({e})")
    report["restart_storm"] = storm
    return report


def run_fleet_chaos(args) -> dict:
    """Fleet-scale chaos (the ISSUE-10 acceptance shape): a SUPERVISED
    fleet of N workers on one spool, faults armed in every worker, then

      1. SIGKILL a worker that provably owns in-flight work (the
         supervisor must restart it with backoff, not flap);
      2. SIGTERM-drain another claim-owning worker (its in-flight
         requests must terminal `done` — drain finishes what it owns —
         and the supervisor must count the clean exit, not restart it);
      3. SIGKILL the supervisor itself mid-run, then start a
         replacement on the same spool (the supervisor holds no request
         state: orphaned workers keep sweeping, the new supervisor's
         workers join them, claims arbitrate).

    Then the PR-7 global invariant is asserted over the spool, plus the
    drain contract: every request the drained worker held at SIGTERM
    time has a .proof.json (terminal `done`, not deferred/stolen)."""
    import random

    os.makedirs(args.spool, exist_ok=True)
    rng = random.Random(args.seed)
    for i in range(args.requests):
        with open(os.path.join(args.spool, f"q{i:03d}.req.json"), "w") as f:
            json.dump({"x": rng.randrange(2, 50), "y": rng.randrange(2, 50)}, f)

    env = dict(os.environ)
    env.pop("PALLAS_AXON_POOL_IPS", None)  # never dial the TPU tunnel
    env["JAX_PLATFORMS"] = "cpu"
    env["ZKP2P_FAULTS"] = args.faults
    env.pop("ZKP2P_METRICS_SINK", None)  # per-spool sink = the shared record file
    env.setdefault("ZKP2P_METRICS_PORT", "auto")  # N workers: ephemeral ports
    # the observability plane rides the chaos run: the supervisor
    # federates /metrics + /status while workers are being killed —
    # the plane must tolerate exactly this.  Parse-checked: an empty
    # inherited ZKP2P_FLEET_METRICS_PORT means plane-off and would
    # silently skip every plane assertion.
    from zkp2p_tpu.utils.config import _opt_port

    if _opt_port(env.get("ZKP2P_FLEET_METRICS_PORT") or "") is None:
        env["ZKP2P_FLEET_METRICS_PORT"] = "auto"
    env.setdefault("ZKP2P_FLEET_SCRAPE_S", "0.5")
    worker_argv = [
        sys.executable, os.path.abspath(__file__), "--worker",
        "--spool", args.spool,
        "--batch", str(args.batch),
        "--stale-claim-s", str(args.stale_claim_s),
        "--max-seconds", str(args.max_seconds),
        "--poll-s", str(args.poll_s),
        "--prove-s", str(args.prove_s),
        "--batch-overhead-s", str(args.batch_overhead_s),
    ]

    def sup_cmd(fleet_dir: str) -> list:
        return [
            sys.executable, "-m", "zkp2p_tpu", "fleet",
            "--spool", args.spool,
            "--workers", str(args.fleet),
            "--fleet-dir", fleet_dir,
            "--drain-timeout-s", str(max(4 * args.prove_s, 15.0)),
            "--restart-backoff-s", "0.2",
            "--liveness-s", "60",
            "--max-seconds", str(args.max_seconds + 30.0),
            "--worker-cmd", json.dumps(worker_argv),
        ]

    fleet_dir = os.path.join(args.spool, ".fleet1")
    sup = subprocess.Popen(sup_cmd(fleet_dir), env=env, cwd=REPO)
    print(f"[chaos] fleet supervisor up (pid {sup.pid}, {args.fleet} workers)", flush=True)
    deadline = time.time() + args.max_seconds

    def kill_claim_owner(sig, exclude: set) -> tuple:
        """Deliver `sig` to a fleet worker that owns >=1 live claim;
        returns (pid, [the rids it held]).  The pid comes from
        status.json/claim files, which can lag reality (a worker that
        crashed on an injected fault leaves claims behind, and the
        supervisor keeps its last pid visible through the backoff
        window) — a pid that is gone by the time the signal lands is
        excluded and the hunt continues, never a harness crash."""
        excl = set(exclude)
        while time.time() < deadline:
            pids = set(_fleet_pids(fleet_dir).values()) - excl
            claims = _live_claims(args.spool)
            for rid, pid in claims:
                if pid in pids:
                    held = sorted(r for r, p in claims if p == pid)
                    try:
                        os.kill(pid, sig)
                    except (ProcessLookupError, PermissionError):
                        excl.add(pid)  # died between discovery and signal
                        continue
                    return pid, held
            time.sleep(0.02)
        return None, []

    # phase 1: SIGKILL a worker that provably owns in-flight work
    killed_pid, _ = kill_claim_owner(signal.SIGKILL, set())
    if killed_pid is not None:
        print(f"[chaos] SIGKILL worker {killed_pid} (owned a live claim)", flush=True)

    # phase 2: SIGTERM-drain a DIFFERENT claim-owning worker; remember
    # exactly what it held — the drain contract is judged on those rids
    drained_pid, drained_claims = kill_claim_owner(
        signal.SIGTERM, {killed_pid} if killed_pid else set()
    )
    if drained_pid is not None:
        print(
            f"[chaos] SIGTERM worker {drained_pid} (drains {len(drained_claims)} "
            f"held claim(s): {drained_claims})", flush=True,
        )

    # phase 3: kill the supervisor mid-run, start a replacement
    supervisor_rcs = []
    if args.supervisor_kill and sup.poll() is None:
        sup.send_signal(signal.SIGKILL)
        supervisor_rcs.append(sup.wait())
        print("[chaos] SIGKILL supervisor; starting replacement", flush=True)
        fleet_dir2 = os.path.join(args.spool, ".fleet2")
        sup = subprocess.Popen(sup_cmd(fleet_dir2), env=env, cwd=REPO)

    try:
        supervisor_rcs.append(sup.wait(timeout=args.max_seconds + 60.0))
    except subprocess.TimeoutExpired:
        sup.kill()
        supervisor_rcs.append("timeout")

    report = check_invariants(args.spool)
    report.update({
        "fleet": args.fleet,
        "killed_worker": killed_pid,
        "drained_worker": drained_pid,
        "drained_claims": drained_claims,
        "supervisor_rcs": supervisor_rcs,
        "faults": args.faults,
    })
    if killed_pid is None:
        report["violations"].append("harness: no mid-prove worker SIGKILL landed")
    if drained_pid is None:
        report["violations"].append("harness: no claim-owning worker was SIGTERM-drained")
    # the drain contract: everything the drained worker held at SIGTERM
    # time finished as `done` — not error, not stolen-and-deferred
    for rid in drained_claims:
        if not os.path.exists(os.path.join(args.spool, rid + ".proof.json")):
            report["violations"].append(
                f"{rid}: held by the drained worker but did not terminal done"
            )
    if supervisor_rcs and supervisor_rcs[-1] != 0:
        report["violations"].append(
            f"harness: final supervisor exited rc={supervisor_rcs[-1]} (want 0 = clean)"
        )
    # fleet-plane assertions (federation parity + restart-storm alert)
    # as their own mini-fleets — the main run's workers exit the moment
    # the spool goes terminal, too racy a target for a counter-equality
    # check that needs a quiesced, still-scrapable fleet
    plane = check_plane(args, env)
    report["plane"] = {k: v for k, v in plane.items() if k != "violations"}
    report["violations"].extend(plane["violations"])
    return report


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.split("\n", 1)[0])
    ap.add_argument("--worker", action="store_true", help=argparse.SUPPRESS)
    ap.add_argument("--spool", default="/tmp/zkp2p_chaos_spool")
    ap.add_argument("--workers", type=int, default=2)
    ap.add_argument("--kills", type=int, default=1)
    ap.add_argument("--requests", type=int, default=6)
    ap.add_argument("--batch", type=int, default=2)
    ap.add_argument("--seed", type=int, default=7)
    ap.add_argument("--stale-claim-s", type=float, default=3.0,
                    help="claim staleness for takeover; heartbeats keep live claims fresh")
    ap.add_argument("--max-seconds", type=float, default=90.0)
    ap.add_argument("--poll-s", type=float, default=0.05)
    ap.add_argument("--prove-s", type=float, default=0.0,
                    help="artificial PER-REQUEST prove time, scaled by batch fill "
                         "(fleet kill windows / loadgen saturation)")
    ap.add_argument("--batch-overhead-s", type=float, default=0.0,
                    help="artificial PER-BATCH fixed prove cost (the amortization "
                         "curve's setup term; scheduler A/Bs need a curve to sit on)")
    ap.add_argument("--linger", action="store_true",
                    help="worker: keep sweeping after the spool goes terminal (loadgen fleet workers)")
    ap.add_argument("--fleet", type=int, default=0,
                    help="fleet-scale chaos: run N workers under the `zkp2p-tpu fleet` "
                         "supervisor (SIGKILL a worker, SIGTERM-drain a worker, kill + "
                         "restart the supervisor) instead of bare Popen workers")
    ap.add_argument("--supervisor-kill", action="store_true", default=None,
                    help="fleet mode: SIGKILL the supervisor mid-run and start a "
                         "replacement (default on in fleet mode; --no-supervisor-kill disables)")
    ap.add_argument("--no-supervisor-kill", dest="supervisor_kill", action="store_false")
    ap.add_argument(
        "--faults",
        default="seed=7,witness:hang=0.2,prove:raise:p=0.2,emit:enospc:once,claim:raise:p=0.05",
        help="ZKP2P_FAULTS spec exported to every worker (>=3 sites for the acceptance shape)",
    )
    ap.add_argument("--report", default="",
                    help="also write the JSON report to this path (stdout is shared "
                         "with the workers' logs, so machine consumers read the file)")
    args = ap.parse_args(argv)
    if args.worker:
        return worker_main(args)
    if args.supervisor_kill is None:
        args.supervisor_kill = bool(args.fleet)
    report = run_fleet_chaos(args) if args.fleet else run_chaos(args)
    print(json.dumps(report, indent=1, default=str))
    if args.report:
        with open(args.report, "w") as f:
            json.dump(report, f, indent=1, default=str)
    if report["violations"]:
        print(f"[chaos] INVARIANT VIOLATED: {report['violations']}", file=sys.stderr)
        return 1
    kills = report.get("kills", 1 if report.get("killed_worker") else 0)
    print(f"[chaos] invariant holds: {report['requests']} requests, "
          f"{report['proofs_verified']} proofs verified, {kills} kills", flush=True)
    return 0


if __name__ == "__main__":
    sys.exit(main())
