"""Chaos harness for the proving service (docs/ROBUSTNESS.md §chaos).

Spawns N worker subprocesses sweeping ONE spool of requests, SIGKILLs
some of them provably MID-PROVE (the victim is chosen by reading the
pid out of a live `.claim` file — a worker that demonstrably owns
in-flight work), injects faults via ZKP2P_FAULTS across the service's
sites, waits for the survivors to drain the spool, then asserts the
global invariant the service claims to provide:

  1. every request reached EXACTLY ONE terminal state
     (.proof.json xor .error.json — never both, never neither);
  2. every emitted proof pairing-verifies against its public signals,
     and the public signals match the request payload;
  3. no request_id has duplicate terminal records in the metrics sink.

Exit 0 = invariant holds; 1 = violated (details in the JSON report on
stdout).  The circuit is the 2-constraint toy from the service tests —
chaos exercises the SERVING layer's failure machinery, not the prover's
arithmetic (the byte-parity suites own that).

    python tools/chaos.py --workers 2 --kills 1 --requests 6 \
        --faults "seed=7,witness:hang=0.2,prove:raise:p=0.2,emit:enospc:once,claim:raise:p=0.05"

A worker process is this same file with --worker (it builds the
deterministic toy world, then sweeps until the spool is fully terminal
or --max-seconds expires).  `make chaos-smoke` runs the tier-1 shape.
"""

from __future__ import annotations

import argparse
import json
import os
import signal
import subprocess
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if REPO not in sys.path:
    sys.path.insert(0, REPO)

TERMINAL_SUFFIXES = (".proof.json", ".error.json")


# ----------------------------------------------------------- toy world


def _build_world():
    """The deterministic 2-constraint circuit (out = (x*y)^2) every
    worker and the checker rebuild identically (setup seed pins the
    keys, so a proof emitted by any worker verifies under the checker's
    vk)."""
    from zkp2p_tpu.field.bn254 import R
    from zkp2p_tpu.prover.groth16_tpu import device_pk
    from zkp2p_tpu.snark.groth16 import setup
    from zkp2p_tpu.snark.r1cs import LC, ConstraintSystem

    cs = ConstraintSystem("chaos")
    out = cs.new_public("out")
    x = cs.new_wire("x")
    y = cs.new_wire("y")
    z = cs.new_wire("z")
    cs.enforce(LC.of(x), LC.of(y), LC.of(z), "mul")
    cs.enforce(LC.of(z), LC.of(z), LC.of(out), "sq")
    cs.compute(z, lambda a, b: a * b % R, [x, y])
    pk, vk = setup(cs, seed="chaos")
    dpk = device_pk(pk, cs)

    def witness_fn(payload):
        xv, yv = int(payload["x"]), int(payload["y"])
        return cs.witness([pow(xv * yv, 2, R)], {x: xv, y: yv})

    return cs, dpk, vk, witness_fn


def _spool_terminal(spool: str) -> bool:
    for fn in os.listdir(spool):
        if not fn.endswith(".req.json"):
            continue
        base = os.path.join(spool, fn[: -len(".req.json")])
        if not any(os.path.exists(base + s) for s in TERMINAL_SUFFIXES):
            return False
    return True


# -------------------------------------------------------------- worker


def worker_main(args) -> int:
    from zkp2p_tpu.pipeline.service import ProvingService
    from zkp2p_tpu.prover.native_prove import prove_native_batch

    cs, dpk, vk, witness_fn = _build_world()
    svc = ProvingService(
        cs, dpk, vk, witness_fn, public_fn=lambda w: [w[1]],
        batch_size=args.batch,
        prover_fn=prove_native_batch,
        stale_claim_s=args.stale_claim_s,
        retry_backoff_s=0.05,
    )
    print(f"[chaos-worker {os.getpid()}] up, sweeping {args.spool}", flush=True)
    deadline = time.time() + args.max_seconds
    while time.time() < deadline:
        stats = svc.process_dir(args.spool)
        if any(stats.values()):
            print(f"[chaos-worker {os.getpid()}] {stats}", flush=True)
        if _spool_terminal(args.spool):
            print(f"[chaos-worker {os.getpid()}] spool terminal, exiting", flush=True)
            return 0
        time.sleep(args.poll_s)
    print(f"[chaos-worker {os.getpid()}] max-seconds expired", flush=True)
    return 2


# ----------------------------------------------------------- invariant


def check_invariants(spool: str, vk=None) -> dict:
    """The global invariant (docs/ROBUSTNESS.md): returns a report dict
    with `violations` (empty = invariant holds).  Standalone-callable on
    any spool a chaos (or production) run left behind."""
    from zkp2p_tpu.field.bn254 import R
    from zkp2p_tpu.formats.proof_json import load, proof_from_json
    from zkp2p_tpu.snark.groth16 import verify

    if vk is None:
        _, _, vk, _ = _build_world()
    violations = []
    states = {}
    verified = 0
    rids = []
    for fn in sorted(os.listdir(spool)):
        if not fn.endswith(".req.json"):
            continue
        rid = fn[: -len(".req.json")]
        rids.append(rid)
        base = os.path.join(spool, rid)
        has_proof = os.path.exists(base + ".proof.json")
        has_error = os.path.exists(base + ".error.json")
        if has_proof and has_error:
            violations.append(f"{rid}: BOTH proof and error artifacts")
        elif not has_proof and not has_error:
            violations.append(f"{rid}: NO terminal state")
        states[rid] = "done" if has_proof else ("error" if has_error else "open")
        if has_proof:
            try:
                proof = proof_from_json(load(base + ".proof.json"))
                pub = [int(v) for v in load(base + ".public.json")]
                with open(base + ".req.json") as f:
                    payload = json.load(f)
                want = [pow(int(payload["x"]) * int(payload["y"]), 2, R)]
                if pub != want:
                    violations.append(f"{rid}: public signals {pub} != payload-derived {want}")
                elif not verify(vk, proof, pub):
                    violations.append(f"{rid}: proof FAILED pairing verification")
                else:
                    verified += 1
            except Exception as e:  # noqa: BLE001 — torn artifact = violation
                violations.append(f"{rid}: unreadable proof artifacts ({e})")

    # terminal records: at most one per rid across every worker's sink
    # writes (the sink is shared, O_APPEND, line-atomic).  Missing
    # records are legal (sink faults, SIGKILL between artifact and
    # record) — duplicates are not.
    rec_counts: dict = {}
    sink = spool.rstrip("/") + ".metrics.jsonl"
    for path in [sink] + [f"{sink}.{i}" for i in range(1, 4)]:
        if not os.path.exists(path):
            continue
        with open(path) as f:
            for line in f:
                try:
                    rec = json.loads(line)
                except ValueError:
                    violations.append(f"{os.path.basename(path)}: torn sink line")
                    continue
                if rec.get("type") == "request" and rec.get("state") != "deferred":
                    # TERMINAL records only: deferred attempt lines
                    # (state="deferred", one per retried sweep — the
                    # request-waterfall history) are expected repeats,
                    # not duplicate terminals
                    rec_counts[rec["request_id"]] = rec_counts.get(rec["request_id"], 0) + 1
    for rid, n in sorted(rec_counts.items()):
        if n > 1:
            violations.append(f"{rid}: {n} terminal records (duplicate)")

    counts: dict = {}
    for s in states.values():
        counts[s] = counts.get(s, 0) + 1
    return {
        "requests": len(rids),
        "states": counts,
        "proofs_verified": verified,
        "terminal_records": sum(rec_counts.values()),
        "violations": violations,
    }


# -------------------------------------------------------------- parent


def _live_claim_pids(spool: str) -> list:
    pids = []
    for fn in os.listdir(spool):
        if fn.endswith(".claim"):
            try:
                with open(os.path.join(spool, fn)) as f:
                    pid = json.load(f).get("pid")
                if pid:
                    pids.append(int(pid))
            except (OSError, ValueError):
                continue
    return pids


def run_chaos(args) -> dict:
    import random

    os.makedirs(args.spool, exist_ok=True)
    rng = random.Random(args.seed)
    for i in range(args.requests):
        with open(os.path.join(args.spool, f"q{i:03d}.req.json"), "w") as f:
            json.dump({"x": rng.randrange(2, 50), "y": rng.randrange(2, 50)}, f)

    env = dict(os.environ)
    env.pop("PALLAS_AXON_POOL_IPS", None)  # never dial the TPU tunnel
    env["JAX_PLATFORMS"] = "cpu"
    env["ZKP2P_FAULTS"] = args.faults
    env.pop("ZKP2P_METRICS_SINK", None)  # per-spool sink = the shared record file
    cmd = [
        sys.executable, os.path.abspath(__file__), "--worker",
        "--spool", args.spool,
        "--batch", str(args.batch),
        "--stale-claim-s", str(args.stale_claim_s),
        "--max-seconds", str(args.max_seconds),
        "--poll-s", str(args.poll_s),
    ]
    workers = [subprocess.Popen(cmd, env=env, cwd=REPO) for _ in range(args.workers)]
    print(f"[chaos] {args.workers} workers up: {[w.pid for w in workers]}", flush=True)

    # Kill phase: a victim must provably be MID-PROVE — we take the pid
    # from a live .claim file.  Never kill the last standing worker (the
    # invariant needs a survivor to drain the spool).
    killed = []
    deadline = time.time() + args.max_seconds
    while len(killed) < args.kills and time.time() < deadline:
        alive = [w for w in workers if w.poll() is None and w.pid not in killed]
        if len(alive) <= 1:
            break
        candidates = [p for p in _live_claim_pids(args.spool)
                      if p in {w.pid for w in alive}]
        if candidates:
            victim = candidates[0]
            os.kill(victim, signal.SIGKILL)
            killed.append(victim)
            print(f"[chaos] SIGKILL {victim} (owned a live claim)", flush=True)
        else:
            time.sleep(0.02)

    # Drain phase: wait for survivors to finish the spool.
    rc = {}
    for w in workers:
        if w.pid in killed:
            w.wait()
            continue
        remaining = max(1.0, deadline + 15.0 - time.time())
        try:
            rc[w.pid] = w.wait(timeout=remaining)
        except subprocess.TimeoutExpired:
            w.kill()
            rc[w.pid] = "timeout"

    report = check_invariants(args.spool)
    report.update({
        "workers": args.workers,
        "kills": len(killed),
        "killed_pids": killed,
        "worker_rc": rc,
        "faults": args.faults,
    })
    if args.kills and not killed:
        report["violations"].append(
            f"harness: no mid-prove SIGKILL landed (wanted {args.kills})"
        )
    return report


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.split("\n", 1)[0])
    ap.add_argument("--worker", action="store_true", help=argparse.SUPPRESS)
    ap.add_argument("--spool", default="/tmp/zkp2p_chaos_spool")
    ap.add_argument("--workers", type=int, default=2)
    ap.add_argument("--kills", type=int, default=1)
    ap.add_argument("--requests", type=int, default=6)
    ap.add_argument("--batch", type=int, default=2)
    ap.add_argument("--seed", type=int, default=7)
    ap.add_argument("--stale-claim-s", type=float, default=3.0,
                    help="claim staleness for takeover; heartbeats keep live claims fresh")
    ap.add_argument("--max-seconds", type=float, default=90.0)
    ap.add_argument("--poll-s", type=float, default=0.05)
    ap.add_argument(
        "--faults",
        default="seed=7,witness:hang=0.2,prove:raise:p=0.2,emit:enospc:once,claim:raise:p=0.05",
        help="ZKP2P_FAULTS spec exported to every worker (>=3 sites for the acceptance shape)",
    )
    ap.add_argument("--report", default="",
                    help="also write the JSON report to this path (stdout is shared "
                         "with the workers' logs, so machine consumers read the file)")
    args = ap.parse_args(argv)
    if args.worker:
        return worker_main(args)
    report = run_chaos(args)
    print(json.dumps(report, indent=1, default=str))
    if args.report:
        with open(args.report, "w") as f:
            json.dump(report, f, indent=1, default=str)
    if report["violations"]:
        print(f"[chaos] INVARIANT VIOLATED: {report['violations']}", file=sys.stderr)
        return 1
    print(f"[chaos] invariant holds: {report['requests']} requests, "
          f"{report['proofs_verified']} proofs verified, {report['kills']} kills", flush=True)
    return 0


if __name__ == "__main__":
    sys.exit(main())
