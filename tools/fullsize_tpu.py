#!/usr/bin/env python
"""Prove the FULL-SIZE flagship (P2POnrampVerify 1024/6400: 4.94 M
constraints, domain 2^23) ON THE REAL TPU CHIP — VERDICT r4 next #4.

Loads the device key + witness that tools/prove_fullsize_native.py
cached under .bench_cache/ (run it first on CPU; ~15 min setup), pushes
the key to HBM, jits `prove_tpu` at batch=1, and writes a per-stage
trace to docs/fullsize_proof/timing_tpu.json with the proof pairing-
verified against the same vkey the native run used.

HBM budget note (v5e, 15.75 G usable): the key is ~4-5 GB resident
(a/b1/b2/c/h bases + QAP coeff rows), NTT scratch at 2^23 is ~0.5 GB per
live array.  The XLA field-mul path would materialise an (nnz, 16, 16)
partial-product tensor (~11 GB at full-size nnz) in the matvec — this
tool therefore requires the fused Pallas field path (utils.jaxcfg.on_tpu
routing), which keeps the Montgomery chain in VMEM.  Run only after
tools/pallas_hw_diff.py is green on this chip; FULLSIZE_ALLOW_XLA=1
overrides the guard for A/B forensics.
"""

import json
import os
import sys
import time

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, ROOT)
CACHE = os.path.join(ROOT, ".bench_cache")
OUT = os.path.join(ROOT, "docs", "fullsize_proof")

T0 = time.time()


def log(msg):
    print(f"[fullsize-tpu +{time.time() - T0:7.1f}s] {msg}", flush=True)


def main():
    from zkp2p_tpu.utils.jaxcfg import enable_cache, on_tpu

    enable_cache()
    import jax

    devs = jax.devices()
    log(f"devices: {devs}")
    if not on_tpu():
        log("not on a TPU — this tool measures the chip; aborting")
        return 2
    from zkp2p_tpu.field.jfield import field_mul_impl

    if field_mul_impl() != "pallas" and not os.environ.get("FULLSIZE_ALLOW_XLA"):
        log(
            "pallas field path not engaged (would OOM the XLA matvec at "
            "full-size nnz); set FULLSIZE_ALLOW_XLA=1 to force"
        )
        return 2

    import numpy as np

    from zkp2p_tpu.prover.keycache import load_dpk
    from zkp2p_tpu.prover.groth16_tpu import prove_tpu
    from zkp2p_tpu.snark.groth16 import verify
    from zkp2p_tpu.utils.trace import dump_trace, trace

    key_path = os.path.join(CACHE, "venmo_1024_6400.npz")
    wit_path = os.path.join(CACHE, "venmo_witness_1024_6400.npz")
    for p in (key_path, wit_path):
        if not os.path.exists(p):
            log(f"missing {p} — run tools/prove_fullsize_native.py (CPU) first")
            return 2

    timing = {}
    t = time.perf_counter()
    log("loading device key (npz -> host arrays)")
    dpk, vk = load_dpk(key_path)
    timing["load_key_s"] = round(time.perf_counter() - t, 1)

    t = time.perf_counter()
    z = np.load(wit_path)
    # (n, 4) u64 standard-form limbs — witness_to_device's vectorized
    # fast path consumes this directly (no Python bigint loop).
    w = z["witness"].astype(np.uint64)
    pubs = [
        sum(int(limb) << (64 * i) for i, limb in enumerate(row)) for row in z["pubs"]
    ]
    timing["load_witness_s"] = round(time.perf_counter() - t, 1)
    log(f"witness loaded ({w.shape[0]} wires) in {timing['load_witness_s']}s")

    # Deterministic (r, s) so the proof is byte-comparable to the native
    # run's committed artifact (same contract as prove_native there).
    t = time.perf_counter()
    log("prove_tpu (first call: key transfer + compile + prove) ...")
    with trace("fullsize_tpu_first"):
        proof = prove_tpu(dpk, w, r=123456789, s=987654321)
    timing["first_prove_incl_compile_s"] = round(time.perf_counter() - t, 1)
    log(f"first prove (incl compile/transfer): {timing['first_prove_incl_compile_s']}s")

    t = time.perf_counter()
    assert verify(vk, proof, pubs), "full-size TPU proof failed pairing verification"
    timing["verify_s"] = round(time.perf_counter() - t, 1)
    log("pairing verified")

    t = time.perf_counter()
    with trace("fullsize_tpu_steady"):
        proof2 = prove_tpu(dpk, w, r=123456789, s=987654321)
    timing["steady_prove_s"] = round(time.perf_counter() - t, 1)
    assert proof2 == proof, "determinism: same (witness, r, s) must re-emit the same proof"
    log(f"steady-state prove: {timing['steady_prove_s']}s")

    timing["constraints"] = 4939112
    timing["device"] = str(devs[0])
    timing["field_mul"] = field_mul_impl()
    os.makedirs(OUT, exist_ok=True)
    with open(os.path.join(OUT, "timing_tpu.json"), "w") as f:
        json.dump(timing, f, indent=1)
    dump_trace()
    log(f"done: {json.dumps(timing)}")
    return 0


if __name__ == "__main__":
    sys.exit(main())
