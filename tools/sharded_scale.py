"""Sharded prove at representative scale (VERDICT r4 next #7).

The green driver dryrun proves sharded-dataflow bit-exactness on a
319-constraint demo; this closes the scale gap: `prove_tpu_sharded` on
the 8-virtual-device CPU mesh over a >=27k-constraint circuit (two
SHA-256 blocks — the venmo circuit's dominant gadget family), diffed
byte-for-byte against the native prover (itself oracle-pinned to
`prove_host`) and pairing-verified.  Output log is committed under
docs/logs/ as the round's evidence.

Run: JAX_PLATFORMS=cpu python tools/sharded_scale.py  (the script
re-asserts the platform itself; ~10-20 min compile-dominated COLD —
warm runs load every executable from the persistent .jax_cache
(ZKP2P_JAX_CACHE_DIR / <repo>/.jax_cache) in seconds, and the log
carries a per-stage cache HIT/MISS line so the split is auditable).
"""

import hashlib
import os
import re
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

os.environ["JAX_PLATFORMS"] = "cpu"
os.environ.pop("PALLAS_AXON_POOL_IPS", None)
flags = os.environ.get("XLA_FLAGS", "")
flags = re.sub(r"--xla_force_host_platform_device_count=\d+", "", flags)
os.environ["XLA_FLAGS"] = (flags + " --xla_force_host_platform_device_count=8").strip()

T0 = time.time()


def stage(msg: str) -> None:
    print(f"[sharded-scale +{time.time() - T0:7.1f}s] {msg}", flush=True)


def main() -> None:
    from zkp2p_tpu.utils.jaxcfg import cache_dir, enable_cache

    # persistent cache with a zero compile-time floor: every executable
    # of this run round-trips, so the NEXT session's run is warm (the
    # per-session 10-20 min compile stall was the whole wall clock) —
    # `make warm-cache` / ZKP2P_JAX_CACHE_DIR share the same root
    enable_cache(min_compile_s=0.0)
    import jax
    import numpy as np

    from zkp2p_tpu.utils.audit import install_compile_listener
    from zkp2p_tpu.utils.metrics import REGISTRY

    install_compile_listener()
    cdir = cache_dir()

    def _cache_entries() -> int:
        n = 0
        for _root, _dirs, fns in os.walk(cdir):
            n += sum(1 for fn in fns if fn.endswith("-cache"))
        return n

    def _compiles() -> float:
        return sum(
            m.get("value", 0.0)
            for m in REGISTRY.snapshot()
            if m["name"] == "zkp2p_compile_events_total"
        )

    _cache_state = {"entries": _cache_entries(), "compiles": _compiles()}
    stage(f"persistent cache at {cdir}: {_cache_state['entries']} entries")

    def cache_report(label: str) -> None:
        # per-stage hit/miss accounting: a fresh XLA compile that left a
        # new cache entry = MISS (now warmed); a compile-free stage with
        # executables dispatched = HIT (loaded from cache)
        entries, compiles = _cache_entries(), _compiles()
        de = entries - _cache_state["entries"]
        dc = compiles - _cache_state["compiles"]
        _cache_state.update(entries=entries, compiles=compiles)
        verdict = "MISS (cold compile, cached for next run)" if dc else "HIT (warm)"
        stage(f"cache[{label}]: {verdict} — {dc:.0f} compiles, {de:+d} entries")

    jax.config.update("jax_platforms", "cpu")
    from jax.sharding import Mesh

    from zkp2p_tpu.prover.groth16_tpu import device_pk, prove_tpu_sharded
    from zkp2p_tpu.prover.native_prove import prove_native
    from zkp2p_tpu.snark.groth16 import setup, verify

    devs = jax.devices()
    assert len(devs) >= 8 and devs[0].platform == "cpu", devs
    mesh = Mesh(np.array(devs[:8]).reshape(8), ("shard",))
    stage(f"8-device virtual mesh up ({devs[0].platform})")

    # two-block fixed SHA-256 over 128 padded bytes: the flagship's
    # dominant gadget at a domain (2^16) 128x the dryrun's
    msg = b"zkp2p sharded-scale witness " + bytes(range(64))

    def sha_pad(m: bytes, max_len: int) -> bytes:
        # MD padding to max_len bytes (shaHash.ts sha256Pad semantics)
        length = len(m) * 8
        padded = bytearray(m) + b"\x80"
        while (len(padded) + 8) % 64:
            padded.append(0)
        padded += length.to_bytes(8, "big")
        assert len(padded) <= max_len and max_len % 64 == 0
        return bytes(padded) + b"\x00" * (max_len - len(padded))

    padded = sha_pad(msg, 128)
    # the registry's sha2b shape (ONE definition; its audit gate covers
    # this run's circuit too — zkp2p-tpu lint --circuits)
    from zkp2p_tpu.models.registry import build_sha2b

    cs, out = build_sha2b()
    wires = sorted(cs.input_wires)
    seed = {wr: padded[i] for i, wr in enumerate(wires)}
    stage(f"circuit: {cs.num_constraints} constraints, {cs.num_wires} wires")
    assert cs.num_constraints >= 27_000, "scale target not met"

    w = cs.witness([], seed)
    cs.check_witness(w)
    digest_bits = [w[b] for b in out]
    # circuit emits 8 words x 32 LSB-first bits of the big-endian words
    want_bits = []
    digest = hashlib.sha256(msg).digest()
    for wi in range(8):
        word = int.from_bytes(digest[4 * wi : 4 * wi + 4], "big")
        want_bits.extend((word >> i) & 1 for i in range(32))
    assert digest_bits == want_bits, "SHA circuit output mismatch vs hashlib"
    stage("witness checked; circuit digest == hashlib")

    pk, vk = setup(cs, seed="sharded-scale")
    dpk = device_pk(pk, cs)
    stage("setup + device key")

    r, s = 123456789, 987654321
    oracle = prove_native(dpk, w, r=r, s=s)  # byte-pinned to prove_host
    stage("native oracle proof done")

    def traced_stage(msg: str) -> None:
        # compile deltas attribute to the stage that just FINISHED (the
        # one the progress message names)
        cache_report(msg.split()[0])
        stage(msg)

    t0 = time.perf_counter()
    proof = prove_tpu_sharded(dpk, w, mesh, r=r, s=s, unified=True, progress=traced_stage)
    stage(f"prove_tpu_sharded done in {time.perf_counter() - t0:.1f}s (incl. compile)")
    cache_report("assemble")
    assert proof == oracle, "sharded proof != native/host oracle proof"
    assert verify(vk, proof, [])
    # Observability flush, wired the way bench.py's native tier is: the
    # per-stage records (sharded/h_evals, sharded/msm_*) go to the
    # configured JSONL sink (stderr when unset) with run_id/pid and the
    # knob/gate manifest, so MULTICHIP runs are aggregatable and
    # `trace_report --diff RID_A RID_B` works across dryrun rounds.
    from zkp2p_tpu.utils.config import load_config
    from zkp2p_tpu.utils.metrics import run_id
    from zkp2p_tpu.utils.trace import dump_trace

    sink = load_config().metrics_sink
    dump_trace(sink or None)
    if sink:
        stage(f"stage trace appended to {sink} (run_id {run_id()})")
    stage(
        f"SHARDED == ORACLE and pairing-verified at {cs.num_constraints} constraints "
        f"on the 8-device mesh — scale evidence recorded (run_id {run_id()})"
    )


if __name__ == "__main__":
    sys.exit(main())
