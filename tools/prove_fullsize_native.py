#!/usr/bin/env python
"""Prove the FULL-SIZE flagship circuit P2POnrampVerify(1024, 6400, 121, 17)
with the native C++ runtime, end to end, on one CPU core.

The analog of the reference's one real full-scale proof (its rapidsnark
run: 6.62M constraints in 9.2 s on 48 cores, zkp-mooc-hackathon-
submission.md:89-101; its pinned proof vector: test/ramp.test.js:193).
Artifacts land in docs/fullsize_proof/ (proof.json, public.json,
timing.json) and the witness + device key are cached under .bench_cache/
so reruns skip the expensive builds.

Run:  JAX_PLATFORMS=cpu python tools/prove_fullsize_native.py
"""

import json
import os
import sys
import time

os.environ.setdefault("JAX_PLATFORMS", "cpu")
ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, ROOT)
CACHE = os.path.join(ROOT, ".bench_cache")
OUT = os.path.join(ROOT, "docs", "fullsize_proof")

import jax  # noqa: E402
import numpy as np  # noqa: E402

# The axon plugin force-selects its platform over JAX_PLATFORMS and a
# wedged tunnel HANGS backend init — pin CPU through the config API
# (the same guard bench.py and tests/conftest.py apply).
jax.config.update("jax_platforms", "cpu")


def log(msg):
    print(f"[fullsize +{time.time() - T0:7.1f}s] {msg}", flush=True)


T0 = time.time()


def main():
    from zkp2p_tpu.field.bn254 import R
    from zkp2p_tpu.formats.proof_json import proof_to_json, public_to_json
    from zkp2p_tpu.inputs.email import generate_inputs, make_test_key, make_venmo_email
    from zkp2p_tpu.models.venmo import VenmoParams, build_venmo_circuit
    from zkp2p_tpu.prover.keycache import (
        KeyCacheSchemaError,
        circuit_digest,
        load_dpk,
        save_dpk,
    )
    from zkp2p_tpu.prover.native_prove import prove_native
    from zkp2p_tpu.snark.groth16 import domain_size_for, verify

    os.makedirs(OUT, exist_ok=True)
    timing = {}

    params = VenmoParams()  # full size: 1024 header / 6400 body
    wit_path = os.path.join(CACHE, "venmo_witness_1024_6400.npz")
    key_path = os.path.join(CACHE, "venmo_1024_6400.npz")

    t = time.perf_counter()
    log("building full-size circuit (expect ~7 min) ...")
    cs, lay = build_venmo_circuit(params)
    timing["build_circuit_s"] = round(time.perf_counter() - t, 1)
    log(f"constraints={cs.num_constraints} wires={cs.num_wires} domain={domain_size_for(cs)}")

    wit_digest = circuit_digest(cs)
    if os.path.exists(wit_path):
        log("loading cached witness")
        z = np.load(wit_path)
        cached_digest = bytes(z["digest"]).decode() if "digest" in z else "<none>"
        if int(z["n_wires"][0]) == cs.num_wires and cached_digest == wit_digest:
            # hoist the arrays OUT of the npz handle: indexing an NpzFile
            # decompresses the whole member per access
            wit_arr, pubs_arr = z["witness"], z["pubs"]
            wbuf = wit_arr.tobytes()
            w = [int.from_bytes(wbuf[i * 32 : (i + 1) * 32], "little") for i in range(cs.num_wires)]
            pbuf = pubs_arr.tobytes()
            pubs = [int.from_bytes(pbuf[i * 32 : (i + 1) * 32], "little") for i in range(pubs_arr.shape[0])]
        else:
            log("cached witness is for a different circuit; regenerating")
            w = None
    else:
        w = None
    if w is None:
        t = time.perf_counter()
        key = make_test_key(1)
        email = make_venmo_email(key, raw_id="1234567891234567891", amount="42", body_filler=40)
        inputs = generate_inputs(email, key.n, order_id=1, claim_id=1, params=params, layout=lay)
        w = cs.witness(inputs.public_signals, inputs.seed)
        pubs = inputs.public_signals
        timing["witness_s"] = round(time.perf_counter() - t, 1)
        log(f"witness generated in {timing['witness_s']}s; checking")
        t = time.perf_counter()
        cs.check_witness(w)
        timing["check_witness_s"] = round(time.perf_counter() - t, 1)
        from zkp2p_tpu.native.lib import _scalars_to_u64

        np.savez(
            wit_path,
            witness=_scalars_to_u64([x % R for x in w]),
            pubs=_scalars_to_u64([x % R for x in pubs]),
            n_wires=np.array([cs.num_wires], dtype=np.int64),
            digest=np.frombuffer(wit_digest.encode(), dtype=np.uint8),
        )
        log("witness cached")

    digest = wit_digest  # same circuit, one digest pass
    n_wires_expect, domain_expect = cs.num_wires, domain_size_for(cs)
    n_constraints = cs.num_constraints
    dpk = vk = None
    if os.path.exists(key_path):
        try:
            t = time.perf_counter()
            dpk, vk = load_dpk(key_path, digest=digest)
            timing["load_key_s"] = round(time.perf_counter() - t, 1)
            if dpk.n_wires != n_wires_expect or (1 << dpk.log_m) != domain_expect:
                log("cached key does not match the rebuilt circuit; re-running setup")
                dpk = vk = None
        except KeyCacheSchemaError as exc:
            log(f"stale key cache: {exc}")
    if dpk is not None:
        # Release the ~8 GB circuit object (wire labels, hook closures)
        # before the prove: holding it costs ~25% prove throughput in
        # cache/memory pressure on this host.
        import gc

        cs = lay = None
        gc.collect()
    if dpk is None:
        t = time.perf_counter()
        log("full-size device setup (native fixed-base batches; expect ~15 min) ...")
        from zkp2p_tpu.prover.setup_device import setup_device

        dpk, vk = setup_device(cs, seed="bench")
        timing["setup_s"] = round(time.perf_counter() - t, 1)
        log(f"setup took {timing['setup_s']}s; caching")
        save_dpk(key_path, dpk, vk, digest=digest)

    t = time.perf_counter()
    log("native prove ...")
    proof = prove_native(dpk, w, r=123456789, s=987654321)
    timing["prove_native_s"] = round(time.perf_counter() - t, 1)
    log(f"native prove took {timing['prove_native_s']}s; verifying")

    t = time.perf_counter()
    assert verify(vk, proof, pubs), "full-size proof failed pairing verification"
    timing["verify_s"] = round(time.perf_counter() - t, 1)
    timing["constraints"] = n_constraints
    timing["wires"] = n_wires_expect
    timing["reference_rapidsnark_s_48core"] = 9.2
    timing["host"] = "1 CPU core"

    with open(os.path.join(OUT, "proof.json"), "w") as f:
        json.dump(proof_to_json(proof), f, indent=1)
    with open(os.path.join(OUT, "public.json"), "w") as f:
        json.dump(public_to_json(pubs), f, indent=1)
    with open(os.path.join(OUT, "timing.json"), "w") as f:
        json.dump(timing, f, indent=1)
    log(f"DONE: verified full-size proof written to {OUT}")
    log(json.dumps(timing))


if __name__ == "__main__":
    main()
