#!/usr/bin/env python
"""Compiled-kernel differential on REAL hardware: the fused Pallas
point kernels (G1 + G2, every special-case lane) vs the XLA jcurve
formulas, compiled for the chip.

The interpret-mode tests (tests/test_pallas_curve.py) pin the MATH;
this pins the MOSAIC LOWERING — the layer that has already produced two
behaviours interpret mode accepted and the chip rejected (scatter-add,
u32 reductions).  Run whenever the kernels change, before trusting a
bench number.
"""

import os
import sys
import time

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, ROOT)

import numpy as np  # noqa: E402


def main():
    import jax
    import jax.numpy as jnp

    from zkp2p_tpu.utils.jaxcfg import enable_cache

    enable_cache()
    # Compiled on a real chip (the point of the tool); interpret mode
    # off-TPU so the tool itself stays smoke-testable on CPU.
    from zkp2p_tpu.utils.jaxcfg import on_tpu

    interp = not on_tpu()
    t0 = time.perf_counter()

    def log(m):
        print(f"[{time.perf_counter()-t0:6.1f}s] {m}", flush=True)

    from zkp2p_tpu.curve.host import G1_GENERATOR, G2_GENERATOR, g1_mul, g2_mul
    from zkp2p_tpu.curve.jcurve import G1J, G2J, g1_to_affine_arrays, g2_to_affine_arrays
    from zkp2p_tpu.field.jfield import FQ, FQ2
    from zkp2p_tpu.ops import pallas_curve as pc

    rng = np.random.default_rng(11)

    def check(name, got, want):
        ok = all(bool(jnp.array_equal(x, y)) for x, y in zip(got, want))
        log(f"{name} {'OK' if ok else 'MISMATCH'}")
        assert ok, name

    # Lanes: [0]=inf+Q, [1]=P+P, [2]=P+(-P), [3]=P+inf, [5:]=generic
    pts = [g1_mul(G1_GENERATOR, int(k)) for k in rng.integers(1, 2**60, 16)]
    aff = g1_to_affine_arrays([None] + pts[:7])
    aff_q = g1_to_affine_arrays(pts[7:15])
    P = G1J.from_affine(aff)
    Q = G1J.from_affine(aff_q)
    lane = jnp.arange(8)

    def force(dst, src, i):
        return tuple(jnp.where((lane == i)[:, None], s, d) for s, d in zip(src, dst))

    Q = force(Q, P, 1)
    Q = force(Q, G1J.neg(P), 2)
    Q = force(Q, G1J.infinity((8,)), 3)
    # add_mixed needs its special cases in the AFFINE operand: lane 1 =
    # same point (doubling fallthrough), lane 2 = negated (-> infinity),
    # lane 3 = (0, 0) sentinel (affine infinity)
    aff_m = list(aff_q)
    aff_m[0] = jnp.where((lane == 1)[:, None], aff[0], aff_m[0])
    aff_m[1] = jnp.where((lane == 1)[:, None], aff[1], aff_m[1])
    aff_m[0] = jnp.where((lane == 2)[:, None], aff[0], aff_m[0])
    aff_m[1] = jnp.where((lane == 2)[:, None], FQ.neg(aff[1]), aff_m[1])
    aff_m = tuple(jnp.where((lane == 3)[:, None], jnp.zeros_like(c), c) for c in aff_m)
    log("g1 cases built")
    check("g1_add", pc.g1_add(FQ, P, Q, interp), G1J.add(P, Q))
    check("g1_add_mixed", pc.g1_add_mixed(FQ, P, aff_m, interp), G1J.add_mixed(P, aff_m))
    check("g1_double", pc.g1_double(FQ, P, interp), G1J.double(P))

    g2pts = [g2_mul(G2_GENERATOR, int(k)) for k in rng.integers(1, 2**60, 16)]
    aff2 = g2_to_affine_arrays([None] + g2pts[:7])
    aff2q = g2_to_affine_arrays(g2pts[7:15])
    P2 = G2J.from_affine(aff2)
    Q2 = G2J.from_affine(aff2q)

    def force2(dst, src, i):
        return tuple(jnp.where((lane == i)[:, None, None], s, d) for s, d in zip(src, dst))

    Q2 = force2(Q2, P2, 1)
    Q2 = force2(Q2, G2J.neg(P2), 2)
    Q2 = force2(Q2, G2J.infinity((8,)), 3)
    aff2_m = list(aff2q)
    m1 = (lane == 1)[:, None, None]
    m2c = (lane == 2)[:, None, None]
    aff2_m[0] = jnp.where(m1, aff2[0], aff2_m[0])
    aff2_m[1] = jnp.where(m1, aff2[1], aff2_m[1])
    aff2_m[0] = jnp.where(m2c, aff2[0], aff2_m[0])
    aff2_m[1] = jnp.where(m2c, FQ2.neg(aff2[1]), aff2_m[1])
    aff2_m = tuple(jnp.where((lane == 3)[:, None, None], jnp.zeros_like(c), c) for c in aff2_m)
    log("g2 cases built")
    check("g2_add", pc.g2_add(FQ2, P2, Q2, interp), G2J.add(P2, Q2))
    check("g2_add_mixed", pc.g2_add_mixed(FQ2, P2, aff2_m, interp), G2J.add_mixed(P2, aff2_m))
    check("g2_double", pc.g2_double(FQ2, P2, interp), G2J.double(P2))

    # Mont mul kernel vs the host bignum oracle on canonical residues
    from zkp2p_tpu.field.bn254 import P as PMOD
    from zkp2p_tpu.field.jfield import MONT_R, int_to_limbs, limbs_to_int
    from zkp2p_tpu.ops.pallas_mont import mont_mul

    B = 1024
    ints_a = [int.from_bytes(rng.bytes(32), "little") % PMOD for _ in range(B)]
    ints_b = [int.from_bytes(rng.bytes(32), "little") % PMOD for _ in range(B)]
    a = jnp.asarray(np.stack([int_to_limbs(x) for x in ints_a]))
    b = jnp.asarray(np.stack([int_to_limbs(x) for x in ints_b]))
    ga = np.asarray(mont_mul(FQ, a, b, interp))
    rinv = pow(MONT_R, -1, PMOD)
    for i in range(32):
        expect = (ints_a[i] * ints_b[i] * rinv) % PMOD
        assert limbs_to_int(ga[i]) == expect, i
    log("mont_mul OK (vs host oracle)")
    log("ALL HARDWARE DIFFS OK")


if __name__ == "__main__":
    main()
