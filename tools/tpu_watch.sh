#!/bin/bash
# Probe the TPU tunnel every ~8 min; fire the session script on the
# first healthy probe of each window.  Run in the background for the
# whole round: windows have been ~30 min and unannounced.
cd "$(dirname "$0")/.."
LOG=docs/logs/tpu_watch_r5.log
while true; do
  if python -c "from zkp2p_tpu.utils.jaxcfg import tpu_probe_ok; import sys; sys.exit(0 if tpu_probe_ok() else 1)" 2>/dev/null; then
    echo "$(date +%H:%M:%S) tunnel UP -> firing session" >> "$LOG"
    tools/tpu_session2.sh || { rc=$?; echo "$(date +%H:%M:%S) session skipped/failed rc=$rc" >> "$LOG"; }
    echo "$(date +%H:%M:%S) session done" >> "$LOG"
  else
    echo "$(date +%H:%M:%S) tunnel down" >> "$LOG"
  fi
  sleep 480
done
