#!/usr/bin/env python
"""Aggregate zkp2p observability JSONL sinks into per-stage tables.

Input: one or more JSONL files produced by utils.trace.dump_trace, the
ProvingService sink, or bench.py with ZKP2P_METRICS_SINK set.  Lines:

  {"type": "manifest", "run_id": ..., "host": {...}, "knobs": {...}}
  {"stage": "native/msm_a", "ms": 812.3, "run_id": ..., "pid": ...}
  {"type": "request", "request_id": ..., "state": "done", "ms": ...}

Modes:
  default      per-stage n / p50 / p95 / max / total table (+ request
               state summary when request records are present)
  --tree       stage-path tree (indented by "/" nesting) with the same
               percentiles per node
  --runs       list the run_ids found (with knob arms + execution
               digest) and exit
  --run RID    restrict aggregation to one run_id
  --diff A B   A/B: two files OR (with one file) two run_ids — per-stage
               p50 delta table, replacing eyeballed min-of-5 comparisons
  --json       machine output: {"stages", "requests", "runs",
               "timeseries"} with the per-stage aggregates,
               request-state aggregates, sampler-line summary, and each
               run's knobs + gate arms + execution digest — so CI can
               gate on digests/latencies instead of scraping text
               tables.  Honors --run; with --diff, emits {"a","b"} of
               per-stage aggregates instead.
  --chrome-trace OUT
               export the request records' lifecycle spans as Chrome
               trace-event JSON (one pid per worker process, one tid
               per request, queue-wait vs witness/prove/emit slices,
               FLOW arrows stitching a deferred/taken-over request's
               attempts across worker process rows) — load OUT in
               https://ui.perfetto.dev.  Honors --run.
  --fleet-dir DIR
               cross-worker mode: discover every sink a fleet run left
               behind (the shared spool sink + rotation backups, plus
               any per-worker ZKP2P_METRICS_SINK files dropped inside
               DIR) instead of naming files by hand — `--fleet-dir
               <spool>/.fleet --chrome-trace out.json` renders the
               whole fleet, one process row per worker.
  --request RID
               single-request forensics: a text timeline of RID's
               journey — arrival, every claim with its owning worker
               and queue-wait, defer/takeover hops, spans per attempt,
               terminal state.  The "which worker did what, when" view
               chasing one stuck request needs.

Exact percentiles from the raw records (the registry's histograms are
bucket-resolution; this reads the records themselves).
"""

from __future__ import annotations

import argparse
import glob as _glob
import json
import os
import sys
from typing import Dict, List, Optional, Tuple


def load_records(
    paths: List[str],
) -> Tuple[List[dict], List[dict], List[dict], List[dict]]:
    """(stage_records, request_records, manifests, timeseries) from
    JSONL files, rotation backups included if named explicitly.
    Unparseable lines are counted, not fatal (a torn tail from a
    crashed worker must not hide the rest of the file)."""
    stages: List[dict] = []
    requests: List[dict] = []
    manifests: List[dict] = []
    timeseries: List[dict] = []
    bad = 0
    for path in paths:
        with open(path) as f:
            for line in f:
                line = line.strip()
                if not line:
                    continue
                try:
                    rec = json.loads(line)
                except ValueError:
                    bad += 1
                    continue
                t = rec.get("type")
                if t == "manifest":
                    manifests.append(rec)
                elif t == "request":
                    requests.append(rec)
                elif t == "timeseries":
                    timeseries.append(rec)
                elif "stage" in rec and "ms" in rec:
                    stages.append(rec)
    if bad:
        print(f"[trace_report] skipped {bad} unparseable line(s)", file=sys.stderr)
    return stages, requests, manifests, timeseries


def _pct(sorted_vals: List[float], q: float) -> float:
    """Nearest-rank percentile on a pre-sorted list."""
    if not sorted_vals:
        return 0.0
    k = max(0, min(len(sorted_vals) - 1, int(round(q * (len(sorted_vals) - 1)))))
    return sorted_vals[k]


def aggregate(stages: List[dict], run: Optional[str] = None) -> Dict[str, dict]:
    """stage path -> {n, p50, p95, max, total_ms}."""
    by_stage: Dict[str, List[float]] = {}
    for rec in stages:
        if run and rec.get("run_id") != run:
            continue
        by_stage.setdefault(rec["stage"], []).append(float(rec["ms"]))
    out: Dict[str, dict] = {}
    for stage, vals in by_stage.items():
        vals.sort()
        out[stage] = {
            "n": len(vals),
            "p50": _pct(vals, 0.50),
            "p95": _pct(vals, 0.95),
            "max": vals[-1],
            "total_ms": sum(vals),
        }
    return out


def _fmt_ms(v: float) -> str:
    if v >= 10000:
        return f"{v / 1000:.1f}s"
    return f"{v:.1f}"


def render_table(agg: Dict[str, dict]) -> str:
    rows = sorted(agg.items(), key=lambda kv: -kv[1]["total_ms"])
    w = max([len("stage")] + [len(s) for s, _ in rows]) if rows else 5
    lines = [f"{'stage':<{w}}  {'n':>6}  {'p50':>9}  {'p95':>9}  {'max':>9}  {'total':>9}"]
    lines.append("-" * len(lines[0]))
    for stage, a in rows:
        lines.append(
            f"{stage:<{w}}  {a['n']:>6}  {_fmt_ms(a['p50']):>9}  {_fmt_ms(a['p95']):>9}  "
            f"{_fmt_ms(a['max']):>9}  {_fmt_ms(a['total_ms']):>9}"
        )
    return "\n".join(lines)


def render_tree(agg: Dict[str, dict]) -> str:
    """Stage-path tree: each node indented by its '/' depth, children
    under their parent, siblings ordered by total time."""
    children: Dict[str, List[str]] = {"": []}
    for stage in agg:
        parts = stage.split("/")
        for d in range(len(parts)):
            node = "/".join(parts[: d + 1])
            parent = "/".join(parts[:d])
            children.setdefault(parent, [])
            children.setdefault(node, [])
            if node not in children[parent]:
                children[parent].append(node)

    lines: List[str] = []
    w = max([len("stage") + 2] + [len(s) + 2 * s.count("/") for s in agg]) if agg else 5
    lines.append(f"{'stage':<{w}}  {'n':>6}  {'p50':>9}  {'p95':>9}  {'total':>9}")
    lines.append("-" * len(lines[0]))

    def total(node: str) -> float:
        a = agg.get(node)
        if a:
            return a["total_ms"]
        return sum(total(c) for c in children.get(node, []))

    def walk(node: str, depth: int) -> None:
        if node:
            a = agg.get(node)
            label = "  " * (depth - 1) + node.split("/")[-1]
            if a:
                lines.append(
                    f"{label:<{w}}  {a['n']:>6}  {_fmt_ms(a['p50']):>9}  "
                    f"{_fmt_ms(a['p95']):>9}  {_fmt_ms(a['total_ms']):>9}"
                )
            else:
                lines.append(f"{label:<{w}}  {'-':>6}  {'-':>9}  {'-':>9}  {_fmt_ms(total(node)):>9}")
        for c in sorted(children.get(node, []), key=lambda n: -total(n)):
            walk(c, depth + 1)

    walk("", 0)
    return "\n".join(lines)


def render_requests(requests: List[dict], run: Optional[str] = None) -> str:
    agg = _aggregate_requests(requests, run=run)
    if not agg:
        return ""
    lines = ["request states:"]
    for state, a in sorted(agg.items()):
        if state.startswith("_"):
            continue
        lines.append(
            f"  {state:<24} n={a['n']:<6} p50={_fmt_ms(a['p50'])} "
            f"p95={_fmt_ms(a['p95'])} max={_fmt_ms(a['max'])}"
        )
    b = agg.get("_batched")
    if b:
        # batched-prove attribution (records carrying batch_index/batch_n):
        # mean fill names the latency-vs-batch-fill tradeoff the service
        # batch_size knob sets; the amortized p50 divides each request's
        # claim->terminal ms by its batch width — the per-proof share of
        # a multi-column batch prove that one request's `ms` conflates.
        lines.append(
            f"  batched proves:          n={b['n']:<6} mean_fill={b['mean_fill']:.2f} "
            f"p50_amortized={_fmt_ms(b['p50_amortized'])}"
        )
    return "\n".join(lines)


def render_diff(agg_a: Dict[str, dict], agg_b: Dict[str, dict], label_a: str, label_b: str) -> str:
    """Per-stage p50 A-vs-B — the knob-arm comparison the bench notes
    used to eyeball from two min-of-5 logs."""
    stages = sorted(
        set(agg_a) | set(agg_b),
        key=lambda s: -(agg_a.get(s, {}).get("total_ms", 0) + agg_b.get(s, {}).get("total_ms", 0)),
    )
    w = max([len("stage")] + [len(s) for s in stages]) if stages else 5
    head = (
        f"{'stage':<{w}}  {'n(A)':>5} {'n(B)':>5}  {'p50 A':>9}  {'p50 B':>9}  {'delta':>8}"
    )
    lines = [f"A = {label_a}", f"B = {label_b}", head, "-" * len(head)]
    for s in stages:
        a, b = agg_a.get(s), agg_b.get(s)
        pa = a["p50"] if a else None
        pb = b["p50"] if b else None
        if pa is not None and pb is not None and pa > 0:
            delta = f"{(pb - pa) / pa * 100:+.1f}%"
        else:
            delta = "-"
        lines.append(
            f"{s:<{w}}  {a['n'] if a else 0:>5} {b['n'] if b else 0:>5}  "
            f"{_fmt_ms(pa) if pa is not None else '-':>9}  "
            f"{_fmt_ms(pb) if pb is not None else '-':>9}  {delta:>8}"
        )
    return "\n".join(lines)


def digest_callout(runs_detail: List[dict], run_a: str, run_b: str) -> List[str]:
    """The interleaved-A/B sanity line every bench note used to write
    by hand: do the two runs share an execution digest (apples to
    apples), and if not, WHICH gate arms differ — a perf delta between
    digest-divergent runs is a code-path change, not a regression."""
    by = {r["run_id"]: r for r in runs_detail}
    a, b = by.get(run_a, {}), by.get(run_b, {})
    da, db = a.get("execution_digest"), b.get("execution_digest")
    if not da or not db:
        missing = [r for r, d in ((run_a, da), (run_b, db)) if not d]
        return [f"digest callout unavailable: no manifest digest for {', '.join(missing)}"]
    if da == db:
        return [f"digests MATCH ({da}) — same code paths, the delta is a real perf delta"]
    lines = [f"digests DIFFER: A={da}  B={db} — the runs took different code paths"]
    ga, gb = a.get("gates") or {}, b.get("gates") or {}
    diffs = [
        f"{g}={ga.get(g, '?')}->{gb.get(g, '?')}"
        for g in sorted(set(ga) | set(gb))
        if ga.get(g) != gb.get(g)
    ]
    if diffs:
        lines.append("  differing arms: " + "  ".join(diffs))
    return lines


def chrome_trace(requests: List[dict], run: Optional[str] = None) -> dict:
    """Chrome trace-event JSON (loads in Perfetto / chrome://tracing)
    from the service's request records: **one pid per worker process,
    one tid per request**, so the UI shows each request as its own
    waterfall row under its worker.

    Per record: a synthesized `queue_wait` slice (req-file mtime →
    claim — the spool wait the `queue_wait_s` field sums), one complete
    ("X") slice per lifecycle span (witness / prove attempts / rungs /
    verify / emit, `spans` on the record), and an instant marker at the
    terminal/deferred transition.  Deferred attempt records share their
    request's tid, so a defer→re-prove cycle reads as one row with two
    prove slices.

    Cross-attempt FLOW events: a request with more than one record
    (defer→re-prove, takeover after a SIGKILL) gets a flow arrow from
    each attempt's last slice to the next attempt's first slice — the
    ph "s"/"f" pair Perfetto draws as an arrow BETWEEN process rows.
    Before this, a defer whose re-prove landed on another worker
    rendered as two unrelated rows with nothing saying they were the
    same request's journey.  Timestamps are µs relative to the
    earliest event (Chrome's `ts` unit), emitted sorted so they are
    monotonic."""
    recs = [
        r for r in requests
        if r.get("request_id") and (not run or r.get("run_id") == run)
    ]
    events: List[dict] = []
    tids: Dict[tuple, int] = {}  # (pid, request_id) -> tid
    next_tid: Dict[int, int] = {}  # per-pid tid allocator

    def tid_for(pid: int, rid: str) -> int:
        key = (pid, rid)
        if key not in tids:
            next_tid[pid] = next_tid.get(pid, 0) + 1
            tids[key] = next_tid[pid]
            events.append({
                "ph": "M", "name": "thread_name", "pid": pid, "tid": tids[key],
                "args": {"name": rid},
            })
        return tids[key]

    seen_pids = set()
    for r in recs:
        pid = int(r.get("pid") or 0)
        if pid not in seen_pids:
            seen_pids.add(pid)
            # fleet attribution: records stamped with a worker id (and
            # fleet id) name the row by WORKER — pids recycle across
            # supervisor restarts, worker ids don't, so "w1 pid 123" and
            # "w1 pid 456" read as one worker's two incarnations
            wname = r.get("worker")
            fname = r.get("fleet")
            label = (
                f"zkp2p {wname}" + (f"@{fname}" if fname else "") + f" (pid {pid})"
                if wname else f"zkp2p worker {pid}"
            )
            events.append({
                "ph": "M", "name": "process_name", "pid": pid,
                "args": {"name": label},
            })
        tid = tid_for(pid, r["request_id"])
        t_submit, t_claim = r.get("t_submit"), r.get("t_claim")
        if t_submit and t_claim and t_claim >= t_submit:
            events.append({
                "ph": "X", "name": "queue_wait", "cat": "request",
                "pid": pid, "tid": tid,
                "ts": t_submit * 1e6, "dur": (t_claim - t_submit) * 1e6,
                "args": {"queue_wait_s": r.get("queue_wait_s")},
            })
        for s in r.get("spans") or []:
            args = {k: v for k, v in s.items() if k not in ("name", "t0", "ms")}
            events.append({
                "ph": "X", "name": s["name"], "cat": "request",
                "pid": pid, "tid": tid,
                "ts": float(s["t0"]) * 1e6, "dur": float(s["ms"]) * 1e3,
                "args": args,
            })
        if r.get("ts"):
            events.append({
                "ph": "i", "s": "t", "name": r.get("state", "?"), "cat": "request",
                "pid": pid, "tid": tid, "ts": float(r["ts"]) * 1e6,
                "args": {k: r[k] for k in ("batch_index", "batch_n", "degraded_rung",
                                           "deferred_reason") if r.get(k) is not None},
            })

    # ---- flow events: stitch a request's attempts across process rows.
    # Each record is one ATTEMPT; consecutive attempts get an arrow
    # from the earlier attempt's last slice to the later attempt's
    # first slice.  The "s"/"f" anchors must land INSIDE a slice on
    # their row for importers to bind them, so the ts is nudged one µs
    # off the slice edge.
    def _anchor_slices(r: dict) -> Tuple[Optional[dict], Optional[dict]]:
        """(first, last) anchorable slices of one record: lifecycle
        spans preferred; the synthesized queue_wait slice as the
        fallback for span-less records (a claim-then-shed terminal)."""
        spans = [s for s in (r.get("spans") or []) if s.get("ms", 0) > 0]
        if spans:
            first = min(spans, key=lambda s: float(s["t0"]))
            last = max(spans, key=lambda s: float(s["t0"]) + float(s["ms"]) / 1e3)
            return first, last
        t_submit, t_claim = r.get("t_submit"), r.get("t_claim")
        if t_submit and t_claim and t_claim > t_submit:
            qw = {"t0": t_submit, "ms": (t_claim - t_submit) * 1e3}
            return qw, qw
        return None, None

    by_rid: Dict[str, List[dict]] = {}
    for r in recs:
        by_rid.setdefault(r["request_id"], []).append(r)
    flow_id = 0
    for rid, attempts in sorted(by_rid.items()):
        if len(attempts) < 2:
            continue
        attempts.sort(key=lambda r: float(r.get("ts") or 0.0))
        for prev, cur in zip(attempts, attempts[1:]):
            _, prev_last = _anchor_slices(prev)
            cur_first, _ = _anchor_slices(cur)
            if prev_last is None or cur_first is None:
                continue
            flow_id += 1
            prev_pid, cur_pid = int(prev.get("pid") or 0), int(cur.get("pid") or 0)
            start_ts = float(prev_last["t0"]) * 1e6 + max(0.0, float(prev_last["ms"]) * 1e3 - 1.0)
            finish_ts = float(cur_first["t0"]) * 1e6 + min(1.0, float(cur_first["ms"]) * 1e3 / 2)
            hop = "takeover" if cur_pid != prev_pid else "re-prove"
            common = {"cat": "flow", "name": f"{rid} {hop}", "id": flow_id}
            events.append({
                "ph": "s", **common, "pid": prev_pid,
                "tid": tid_for(prev_pid, rid), "ts": start_ts,
            })
            events.append({
                "ph": "f", "bp": "e", **common, "pid": cur_pid,
                "tid": tid_for(cur_pid, rid), "ts": max(finish_ts, start_ts + 1.0),
            })
    # normalize to the earliest event and sort: Perfetto wants sane
    # (small, monotonic-sortable) µs timestamps, not epoch µs
    slices = [e for e in events if "ts" in e]
    if slices:
        t0 = min(e["ts"] for e in slices)
        for e in slices:
            e["ts"] = round(e["ts"] - t0, 3)
            if "dur" in e:
                e["dur"] = round(e["dur"], 3)
    meta = [e for e in events if "ts" not in e]
    # Equal-ts slices sort LONGEST first: importers nest same-timestamp
    # complete events by assuming the enclosing slice precedes the
    # enclosed one, and a defer→re-prove request emits two queue_wait
    # slices both anchored at t_submit (shorter-first would mis-nest).
    slices.sort(key=lambda e: (e["ts"], -e.get("dur", 0)))
    return {"traceEvents": meta + slices, "displayTimeUnit": "ms"}


def load_flame_capture(path: str) -> Optional[dict]:
    """Fail-closed reader for a utils.flameprof capture file (kept
    dependency-free: this tool must run standalone).  One JSON object
    with kind/schema and a str->int stacks map, or None — a truncated
    or foreign file must never render as a flamegraph."""
    try:
        with open(path) as f:
            doc = json.load(f)
    except (OSError, ValueError):
        return None
    if not isinstance(doc, dict) or doc.get("kind") != "zkp2p_flame_capture":
        return None
    if doc.get("schema") != 1:
        return None
    stacks = doc.get("stacks")
    if not isinstance(stacks, dict) or not all(
        isinstance(k, str) and isinstance(v, int) and v >= 0
        for k, v in stacks.items()
    ):
        return None
    return doc


def render_flame(cap: dict) -> str:
    """Collapsed-stack text (the flamegraph.pl wire format), heaviest
    stack first — pipe straight into flamegraph.pl."""
    rows = sorted((cap.get("stacks") or {}).items(), key=lambda kv: (-kv[1], kv[0]))
    return "\n".join(f"{k} {v}" for k, v in rows)


def flame_events(cap: dict, pid: int = 990001) -> List[dict]:
    """Chrome trace events for one flame capture: the collapsed stacks
    folded into a trie and rendered as nested X slices under a
    dedicated flame pid — one synthetic millisecond of track time per
    sample, so slice WIDTH is sample share (a flamegraph on its side
    in Perfetto).  Merges beside the request waterfalls: the flame pid
    is its own process row, its timeline synthetic by construction."""
    stacks = cap.get("stacks") or {}
    root: Dict[str, dict] = {}
    for stack, count in stacks.items():
        frames = [fr for fr in stack.split(";") if fr]
        level = root
        for fr in frames:
            node = level.setdefault(fr, {"count": 0, "children": {}})
            node["count"] += count
            level = node["children"]
    label = (
        f"flame {cap.get('circuit', '?')}/{cap.get('stage', '?')} "
        f"@{cap.get('hz', '?')}Hz ({cap.get('trigger', '?')})"
    )
    events: List[dict] = [
        {"ph": "M", "name": "process_name", "pid": pid, "args": {"name": label}},
        {"ph": "M", "name": "thread_name", "pid": pid, "tid": 1,
         "args": {"name": f"{cap.get('samples', 0)} samples"}},
    ]
    ms = 1000.0  # µs per sample of synthetic track time

    def walk(level: Dict[str, dict], t0: float) -> None:
        offset = t0
        for name in sorted(level):
            node = level[name]
            # parent appended before children: importers nest equal-ts
            # complete events by emission order
            events.append({
                "ph": "X", "name": name, "cat": "flame", "pid": pid, "tid": 1,
                "ts": round(offset, 3), "dur": round(node["count"] * ms, 3),
                "args": {"samples": node["count"]},
            })
            walk(node["children"], offset)
            offset += node["count"] * ms

    walk(root, 0.0)
    return events


def fleet_sinks(fleet_dir: str) -> List[str]:
    """Discover every JSONL sink a fleet run left behind, from its
    fleet dir (default `<spool>/.fleet`): the shared per-spool sink
    `<spool>.metrics.jsonl` with its rotation backups, plus any
    `*.jsonl` dropped inside the fleet dir itself (a per-worker
    ZKP2P_METRICS_SINK override pointed there).  The spool path comes
    from status.json when present (the supervisor records it), else
    from the directory layout."""
    spool = None
    try:
        with open(os.path.join(fleet_dir, "status.json")) as f:
            spool = json.load(f).get("spool")
    except (OSError, ValueError):
        pass
    if not spool:
        spool = os.path.dirname(os.path.abspath(fleet_dir))
    base = spool.rstrip("/") + ".metrics.jsonl"
    paths = [p for p in [base] + [f"{base}.{i}" for i in range(1, 10)] if os.path.exists(p)]
    paths += sorted(
        p for p in _glob.glob(os.path.join(fleet_dir, "*.jsonl")) if os.path.isfile(p)
    )
    return paths


def request_timeline(requests: List[dict], rid: str) -> str:
    """Single-request forensics: every attempt (record) for `rid` in
    time order — owning worker, claim offset, queue-wait for THAT hop,
    span breakdown, outcome — with takeover hops called out where the
    owner changed between attempts.  Offsets are relative to the spool
    arrival (t_submit), the clock every worker shares."""
    recs = sorted(
        (r for r in requests if r.get("request_id") == rid),
        key=lambda r: float(r.get("ts") or 0.0),
    )
    if not recs:
        return f"(no records for request {rid!r})"
    t0 = min(
        [float(r["t_submit"]) for r in recs if r.get("t_submit")]
        or [float(r.get("t_claim") or r.get("ts") or 0.0) for r in recs]
    )

    def owner(r: dict) -> str:
        w = r.get("worker")
        return f"{w} (pid {r.get('pid')})" if w else f"pid {r.get('pid')}"

    lines = [f"request {rid} — {len(recs)} attempt(s)"]
    lines.append("  +0.000s  arrival (spool mtime)")
    prev_owner = None
    for i, r in enumerate(recs, 1):
        hop = ""
        if prev_owner is not None and owner(r) != prev_owner:
            hop = "  TAKEOVER"
        prev_owner = owner(r)
        t_claim = r.get("t_claim")
        claim_s = f"+{float(t_claim) - t0:.3f}s" if t_claim else "?"
        qw = r.get("queue_wait_s")
        qw_s = f"  queue_wait {float(qw):.3f}s" if qw is not None else ""
        spans = r.get("spans") or []
        span_s = ", ".join(f"{s['name']} {float(s['ms']):.0f}ms" for s in spans)
        state = r.get("state", "?")
        outcome = state
        if state == "deferred" and r.get("deferred_reason"):
            outcome += f" ({r['deferred_reason']})"
        if r.get("degraded_rung"):
            outcome += f" [rescued: {r['degraded_rung']}]"
        ts = r.get("ts")
        end_s = f" at +{float(ts) - t0:.3f}s" if ts else ""
        lines.append(
            f"  attempt {i}  {owner(r)}{hop}  claim {claim_s}{qw_s}"
            + (f"\n             {span_s}" if span_s else "")
            + f"\n             -> {outcome}{end_s}"
        )
    return "\n".join(lines)


def _aggregate_timeseries(timeseries: List[dict], run: Optional[str] = None) -> dict:
    """Compact summary of the sampler lines: sample count, time covered,
    and min/mean/max of the queue-state signals — enough for the text
    report to say "backlog peaked at N while arrivals ran at X Hz"
    (full-resolution analysis reads the raw lines)."""
    recs = [r for r in timeseries if not run or r.get("run_id") == run]
    if not recs:
        return {}

    def series(key):
        vals = [float(r[key]) for r in recs if r.get(key) is not None]
        if not vals:
            return None
        return {
            "min": min(vals),
            "mean": round(sum(vals) / len(vals), 4),
            "max": max(vals),
        }

    out = {"n": len(recs)}
    ts = [float(r["ts"]) for r in recs if r.get("ts")]
    if len(ts) >= 2:
        out["span_s"] = round(max(ts) - min(ts), 3)
    for key in ("arrival_rate_hz", "backlog", "claimable", "in_flight", "batch_fill_last"):
        s = series(key)
        if s is not None:
            out[key] = s
    return out


def render_timeseries(agg: dict) -> str:
    if not agg:
        return ""
    parts = [f"timeseries: {agg['n']} samples"]
    if "span_s" in agg:
        parts.append(f"over {agg['span_s']:.0f}s")
    for key, label in (
        ("arrival_rate_hz", "arrivals/s"), ("backlog", "backlog"),
        ("in_flight", "in_flight"), ("batch_fill_last", "batch_fill"),
    ):
        if key in agg:
            a = agg[key]
            parts.append(f"{label} mean={a['mean']:g} max={a['max']:g}")
    return "  ".join(parts)


def _aggregate_requests(requests: List[dict], run: Optional[str] = None) -> Dict[str, dict]:
    """state -> {n, p50, p95, max} over request terminal records; plus a
    `_batched` pseudo-state over records carrying batch_index/batch_n
    (mean batch fill + amortized-per-proof latency p50)."""
    by_state: Dict[str, List[float]] = {}
    batched: List[dict] = []
    for rec in requests:
        if run and rec.get("run_id") != run:
            continue
        by_state.setdefault(rec.get("state", "?"), []).append(float(rec.get("ms") or 0.0))
        if rec.get("batch_n"):
            batched.append(rec)
    out: Dict[str, dict] = {}
    for state, vals in by_state.items():
        vals.sort()
        out[state] = {
            "n": len(vals),
            "p50": _pct(vals, 0.50),
            "p95": _pct(vals, 0.95),
            "max": vals[-1] if vals else 0.0,
        }
    if batched:
        amortized = sorted(
            float(r.get("ms") or 0.0) / max(1, int(r["batch_n"])) for r in batched
        )
        # mean fill counts each BATCH once (its index-0 record), not each
        # request — averaging batch_n over per-request records would weight
        # every batch by its own width and inflate the mean toward full
        # batches (a 4-batch plus a 1-batch is fill 2.5, not 3.4)
        heads = [int(r["batch_n"]) for r in batched if int(r.get("batch_index", 0)) == 0]
        out["_batched"] = {
            "n": len(batched),
            "mean_fill": (sum(heads) / len(heads)) if heads else float(batched[0]["batch_n"]),
            "p50_amortized": _pct(amortized, 0.50),
        }
    return out


def _runs_detail(
    stages: List[dict], requests: List[dict], manifests: List[dict],
    run: Optional[str] = None,
) -> List[dict]:
    """One entry per run_id (restricted to `run` when given): record
    count, knobs, gate arms, execution digest (from the newest manifest
    carrying one — a process stamps a manifest per dump, and the latest
    reflects its final arm map)."""
    counts: Dict[str, int] = {}
    for rec in stages:
        rid = rec.get("run_id", "?")
        counts[rid] = counts.get(rid, 0) + 1
    for rec in requests:
        # request records count too: a service run whose stage spans
        # were dropped/drained before a dump still HAS data
        rid = rec.get("run_id", "?")
        counts[rid] = counts.get(rid, 0) + 1
    if run:
        counts = {rid: n for rid, n in counts.items() if rid == run}
    man_by_run: Dict[str, dict] = {}
    for m in manifests:  # later manifests win (file order = append order)
        man_by_run[m.get("run_id")] = m
    out = []
    for rid, n in sorted(counts.items()):
        m = man_by_run.get(rid, {})
        out.append(
            {
                "run_id": rid,
                "records": n,
                "knobs": m.get("knobs", {}),
                "gates": m.get("gates", {}),
                "execution_digest": m.get("execution_digest"),
                "tpu_probe": m.get("tpu_probe"),
                # fixed-base table accounting (family geometry + resident
                # bytes + built-vs-cache provenance) — so a cold start's
                # precomp_build cost in the stage table is attributable
                # to the tables it produced
                "precomp": m.get("precomp"),
            }
        )
    return out


def _runs_summary(runs: List[dict]) -> str:
    """Text render of _runs_detail — ONE aggregation behind both views,
    so the text and --json listings can never disagree about which runs
    exist or what their digests are."""
    lines = []
    for r in runs:
        k = r["knobs"]
        arms = " ".join(
            f"{name}={k[name]}"
            for name in ("msm_glv", "msm_batch_affine", "msm_overlap", "msm_precomp")
            if name in k
        )
        if r["execution_digest"]:
            arms = f"digest={r['execution_digest']}  {arms}"
        pm = r.get("precomp")
        if pm:
            built = sum(1 for f in pm.get("families", {}).values() if f.get("source") == "built")
            arms += (
                f"  precomp_tables={len(pm.get('families', {}))}"
                f" ({pm.get('total_bytes', 0) / 1e6:.0f} MB, {built} built)"
            )
        lines.append(f"{r['run_id']}: {r['records']} records  {arms}")
    return "\n".join(lines) or "(no run_ids found)"


def main(argv: Optional[List[str]] = None) -> int:
    ap = argparse.ArgumentParser(description=__doc__, formatter_class=argparse.RawDescriptionHelpFormatter)
    ap.add_argument("files", nargs="*", help="JSONL sink file(s)")
    ap.add_argument("--tree", action="store_true", help="stage-path tree view")
    ap.add_argument("--runs", action="store_true", help="list run_ids and exit")
    ap.add_argument("--run", help="restrict to one run_id")
    ap.add_argument(
        "--diff", nargs=2, metavar=("A", "B"),
        help="two run_ids (single input) or ignored-with-two-files A/B p50 diff",
    )
    ap.add_argument(
        "--compare", nargs=2, metavar=("RUN_A", "RUN_B"),
        help="two run_ids: per-stage p50 diff table WITH the execution-digest "
             "callout (match = real perf delta; differ = names the diverging arms)",
    )
    ap.add_argument("--json", action="store_true", help="machine output (stages/requests/runs + digests)")
    ap.add_argument(
        "--chrome-trace", metavar="OUT",
        help="write the request waterfalls as Chrome trace-event JSON (Perfetto-loadable)",
    )
    ap.add_argument(
        "--fleet-dir", metavar="DIR",
        help="discover a fleet run's sinks from its fleet dir (<spool>/.fleet) "
             "instead of naming files — composes with every other mode",
    )
    ap.add_argument(
        "--request", metavar="RID",
        help="single-request timeline: arrival -> claims -> takeovers -> terminal, "
             "with owning worker and queue-wait per hop",
    )
    ap.add_argument(
        "--flame", metavar="CAPTURE",
        help="flame capture JSON (utils.flameprof): print its collapsed stacks; "
             "with --chrome-trace, render/merge a flame track pid into the trace",
    )
    args = ap.parse_args(argv)
    if args.fleet_dir:
        found = fleet_sinks(args.fleet_dir)
        if not found and not args.files:
            print(f"[trace_report] no sinks found for fleet dir {args.fleet_dir}", file=sys.stderr)
            return 1
        args.files = list(args.files) + [p for p in found if p not in args.files]
    flame_cap = None
    if args.flame:
        flame_cap = load_flame_capture(args.flame)
        if flame_cap is None:
            print(
                f"[trace_report] refusing {args.flame}: not a valid "
                "zkp2p_flame_capture (truncated, foreign, or schema drift)",
                file=sys.stderr,
            )
            return 1
    if not args.files:
        if flame_cap is not None:
            # flame-only mode: no sink needed — collapsed text, or a
            # standalone flame-track trace with --chrome-trace
            if args.chrome_trace:
                ev = flame_events(flame_cap)
                with open(args.chrome_trace, "w") as f:
                    json.dump({"traceEvents": ev, "displayTimeUnit": "ms"}, f)
                n = sum(1 for e in ev if e.get("ph") == "X")
                print(
                    f"[trace_report] wrote {n} flame slice(s) to "
                    f"{args.chrome_trace} (load in https://ui.perfetto.dev)",
                    file=sys.stderr,
                )
            else:
                print(render_flame(flame_cap))
            return 0
        ap.error("need sink file(s), --fleet-dir, or --flame")

    if args.diff and len(args.files) == 2:
        # file-vs-file diff: --diff labels the columns
        sa, _, _, _ = load_records([args.files[0]])
        sb, _, _, _ = load_records([args.files[1]])
        if args.json:
            print(json.dumps({"a": aggregate(sa), "b": aggregate(sb)}))
        else:
            print(render_diff(aggregate(sa), aggregate(sb), args.diff[0], args.diff[1]))
        return 0

    stages, requests, manifests, timeseries = load_records(args.files)
    if args.request:
        reqs = [r for r in requests if not args.run or r.get("run_id") == args.run]
        print(request_timeline(reqs, args.request))
        return 0
    if args.chrome_trace:
        trace = chrome_trace(requests, run=args.run)
        if flame_cap is not None:
            # the flame track rides its own pid beside the request
            # waterfalls (appended AFTER the sort: parent-before-child
            # emission order is what nests the equal-ts slices)
            trace["traceEvents"].extend(flame_events(flame_cap))
        n_slices = sum(1 for e in trace["traceEvents"] if e.get("ph") == "X")
        n_flows = sum(1 for e in trace["traceEvents"] if e.get("ph") == "s")
        with open(args.chrome_trace, "w") as f:
            json.dump(trace, f)
        print(
            f"[trace_report] wrote {n_slices} spans + {n_flows} cross-attempt flow(s) across "
            f"{len({e['pid'] for e in trace['traceEvents']})} worker pid(s) to "
            f"{args.chrome_trace} (load in https://ui.perfetto.dev)",
            file=sys.stderr,
        )
        if not n_slices:
            print("[trace_report] no request spans found (pre-PR-8 sink?)", file=sys.stderr)
        return 0
    if args.runs:
        runs = _runs_detail(stages, requests, manifests, run=args.run)
        if args.json:
            print(json.dumps({"runs": runs}))
        else:
            print(_runs_summary(runs))
        return 0
    if args.compare:
        run_a, run_b = args.compare
        agg_a = aggregate(stages, run=run_a)
        agg_b = aggregate(stages, run=run_b)
        if not agg_a or not agg_b:
            print(f"no records for run_id {run_a if not agg_a else run_b}", file=sys.stderr)
            return 1
        callout = digest_callout(_runs_detail(stages, requests, manifests), run_a, run_b)
        if args.json:
            print(json.dumps({"a": agg_a, "b": agg_b, "digest_callout": callout}))
        else:
            print("\n".join(callout))
            print(render_diff(agg_a, agg_b, run_a, run_b))
        return 0
    if args.diff:
        agg_a = aggregate(stages, run=args.diff[0])
        agg_b = aggregate(stages, run=args.diff[1])
        if not agg_a or not agg_b:
            print(f"no records for run_id {args.diff[0] if not agg_a else args.diff[1]}", file=sys.stderr)
            return 1
        if args.json:
            print(json.dumps({"a": agg_a, "b": agg_b}))
        else:
            print(render_diff(agg_a, agg_b, args.diff[0], args.diff[1]))
        return 0
    agg = aggregate(stages, run=args.run)
    if args.json:
        print(
            json.dumps(
                {
                    "stages": agg,
                    "requests": _aggregate_requests(requests, run=args.run),
                    "runs": _runs_detail(stages, requests, manifests, run=args.run),
                    "timeseries": _aggregate_timeseries(timeseries, run=args.run),
                }
            )
        )
        return 0
    print(render_tree(agg) if args.tree else render_table(agg))
    req_view = render_requests(requests, run=args.run)
    if req_view:
        print()
        print(req_view)
    ts_view = render_timeseries(_aggregate_timeseries(timeseries, run=args.run))
    if ts_view:
        print()
        print(ts_view)
    return 0


if __name__ == "__main__":
    sys.exit(main())
