#!/usr/bin/env python
"""Phase profile of the native G1 Pippenger tier on a real prove.

Runs prove_native on the cached bench-shape key/witness with
ZKP2P_MSM_PROF=1 and prints the csrc counters after each stage:
fill (incl. apply), the batched 8-wide apply alone, and the serial
suffix reduction — the measurement behind any suffix-vectorization
decision (no perf(1) on the driver box; see zkp2p_msm_prof_dump).

Run: JAX_PLATFORMS=cpu python tools/msm_native_prof.py
"""

import ctypes
import os
import sys
import time

os.environ.setdefault("JAX_PLATFORMS", "cpu")
os.environ["ZKP2P_MSM_PROF"] = "1"
os.environ.pop("PALLAS_AXON_POOL_IPS", None)
ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, ROOT)

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")
import numpy as np  # noqa: E402


def main():
    from zkp2p_tpu.inputs.email import generate_inputs, make_test_key, make_venmo_email
    from zkp2p_tpu.models.venmo import VenmoParams, build_venmo_circuit
    from zkp2p_tpu.native.lib import get_lib
    from zkp2p_tpu.prover.keycache import load_dpk
    from zkp2p_tpu.prover.native_prove import prove_native
    from zkp2p_tpu.snark.groth16 import verify

    lib = get_lib()
    assert lib is not None, "native library unavailable"
    from zkp2p_tpu.utils.config import load_config

    cfg = load_config()
    print(
        f"native msm mode: glv={'on' if cfg.msm_glv else 'off'} "
        f"batch_affine={'on' if cfg.msm_batch_affine else 'off'}",
        flush=True,
    )
    nthreads = cfg.native_threads
    if nthreads and nthreads > 1:
        print(
            f"WARNING: ZKP2P_NATIVE_THREADS={nthreads} — fill counters sum "
            "across workers; phase ratios are only valid single-threaded",
            flush=True,
        )
    dump = lib.zkp2p_msm_prof_dump
    dump.argtypes = [ctypes.POINTER(ctypes.c_longlong)]

    def read_prof(tag):
        buf = (ctypes.c_longlong * 4)()
        dump(buf)
        fill, apply_, suffix, bailfill = (x / 1e6 for x in buf)
        sched = fill - apply_
        print(
            f"[{tag}] fill={fill:8.1f} ms (apply={apply_:8.1f}, sched={sched:8.1f})"
            f"  bailfill={bailfill:8.1f}  suffix={suffix:8.1f} ms",
            flush=True,
        )
        return fill, apply_, suffix

    params = VenmoParams(max_header_bytes=256, max_body_bytes=192)
    print("building bench-shape circuit ...", flush=True)
    cs, lay = build_venmo_circuit(params)
    key = make_test_key(1)
    email = make_venmo_email(key, raw_id="1234567891234567891"[:19], amount="30", body_filler=40)
    inputs = generate_inputs(email, key.n, order_id=1, claim_id=0, params=params, layout=lay)
    w = cs.witness(inputs.public_signals, inputs.seed)

    path = os.path.join(ROOT, ".bench_cache", "venmo_256_192.npz")
    dpk, vk = load_dpk(path)
    print("warm prove ...", flush=True)
    prove_native(dpk, w)
    read_prof("warm (discard)")
    t0 = time.perf_counter()
    proof = prove_native(dpk, w)
    total = time.perf_counter() - t0
    fill, apply_, suffix = read_prof("steady")
    assert verify(vk, proof, inputs.public_signals)
    print(f"prove total {total:.2f}s; G1 phases sum {(fill + suffix) / 1e3:.2f}s", flush=True)


if __name__ == "__main__":
    main()
