#!/bin/bash
# Orchestrated TPU hardware session: run the moment the tunnel is up.
# Each phase logs to docs/logs/tpu_session_<ts>/ and later phases run
# even if earlier ones fail (the bench self-protects via its XLA
# re-exec fallback).  Order: correctness diff -> microbench arms ->
# full venmo bench (the driver's command) -> artifacts summary.
set -u
cd "$(dirname "$0")/.."
TS=$(date +%H%M%S)
OUT=docs/logs/tpu_session_$TS
mkdir -p "$OUT"
echo "== TPU session $TS -> $OUT"

FAILS=0
phase() {
  local name=$1 tmo=$2; shift 2
  echo "-- $name (timeout ${tmo}s): $*" | tee -a "$OUT/session.log"
  timeout "$tmo" "$@" > "$OUT/$name.log" 2>&1
  local rc=$?
  echo "   rc=$rc" | tee -a "$OUT/session.log"
  tail -4 "$OUT/$name.log" | sed 's/^/   /'
  [ $rc -ne 0 ] && FAILS=$((FAILS + 1))
  return $rc
}

# 1. compiled-kernel differential vs the XLA path on chip (G1+G2, all
#    special-case lanes) — the check interpret mode cannot do.
phase diff 1500 python -u tools/pallas_hw_diff.py

# 2. the real thing FIRST (a short tunnel window must warm the bench
#    compile cache before anything else): the driver's command, with the
#    in-session TPU budget widened so cold compiles can finish.  Each
#    killed attempt still banks its completed executables in the
#    persistent cache, so back-to-back passes make monotone progress.
phase bench 900 env BENCH_TPU_BUDGET=820 python -u bench.py
phase bench_warm 900 env BENCH_TPU_BUDGET=820 python -u bench.py
phase bench_steady 900 env BENCH_TPU_BUDGET=820 python -u bench.py

# 3. microbench arms: signed w=8 (the bench config), lanes sweep
phase msm_w8 1200 python -u tools/msm_hwbench.py --n 131072 --window 8 --signed --skip-adds
phase msm_lanes8k 900 python -u tools/msm_hwbench.py --n 131072 --lanes 8192 --skip-adds
phase msm_lanes16k 900 python -u tools/msm_hwbench.py --n 131072 --lanes 16384 --skip-adds

echo "== session done ($FAILS failed phases); logs in $OUT" | tee -a "$OUT/session.log"
exit $((FAILS > 0))
