#!/bin/bash
# Tunnel-window session v3 (fired by tools/tpu_watch.sh the moment a
# probe sees the TPU up).  Reworked after the r5 first window:
#   - jax.default_backend() is "axon" under the tunnel plugin, so every
#     "auto on tpu" gate was OFF on chip; utils.jaxcfg.on_tpu() fixes
#     the routing and this session must first VALIDATE the pallas
#     kernels it arms (Mosaic has twice accepted interpret-mode
#     semantics it could not run: scatter-add, u32 reductions).
#   - the batched prove OOMs HBM above ~4 witnesses/chunk on the XLA
#     field path (18 GB at batch=16); prove_tpu_batch now sub-chunks
#     (ZKP2P_BATCH_CHUNK auto=4 on chip) so any BENCH_BATCH is safe.
# Order of business for a window of unknown length:
#   1. pallas kernel differential on chip (small shapes, fast compiles)
#      — decides whether the auto-armed kernels stay on for the benches
#      (bench.py also self-protects with its re-exec-XLA fallback).
#   2. driver bench (batch=16, sub-chunked) with budget wide enough to
#      finish remaining cold compiles in ONE window; killed attempts
#      still bank completed executables in the persistent cache.
#   3. affine/bucket A/B -> .bench_cache/armed_flags.json (driver bench
#      inherits validated arming with no human in the loop).
#   4. re-bench with winners armed; latency + batch sweep; MSM roofline.
set -u
cd "$(dirname "$0")/.."
# One session at a time: the watcher fires on every healthy probe, and a
# manual launch may already be in flight.
mkdir -p .bench_cache
exec 9> .bench_cache/session.lock
flock -n 9 || { echo "session already in flight; exiting"; exit 3; }
TS=$(date +%H%M%S)
OUT=docs/logs/tpu_session3_$TS
mkdir -p "$OUT"
phase() {
  local name=$1 tmo=$2; shift 2
  echo "-- $name ($(date +%H:%M:%S), timeout ${tmo}s): $*" >> "$OUT/session.log"
  timeout "$tmo" "$@" > "$OUT/$name.log" 2>&1
  echo "   rc=$? at $(date +%H:%M:%S)" >> "$OUT/session.log"
}

# 1. on-chip kernel differential: G1/G2 point kernels + the fused
#    Montgomery mul/pow ladder, every special-case lane, vs the XLA path.
phase diff 1500 python -u tools/pallas_hw_diff.py
PALLAS_ENV=()
if ! grep -q "ALL HARDWARE DIFFS OK" "$OUT/diff.log" 2>/dev/null; then
  # Kernels unproven on this chip -> force the portable XLA paths for
  # the benches (bench would also self-protect via re-exec, but that
  # burns a compile cycle mid-window).
  PALLAS_ENV=(ZKP2P_FIELD_MUL=xla ZKP2P_CURVE_KERNEL=xla)
  echo "   pallas diff NOT green -> benches forced to XLA paths" >> "$OUT/session.log"
fi

# 2. the driver's own command, wide budget; back-to-back passes make
#    monotone progress through the compile set.
phase bench1 1800 env BENCH_TPU_BUDGET=1700 "${PALLAS_ENV[@]}" python -u bench.py
phase bench2 1200 env BENCH_TPU_BUDGET=1100 "${PALLAS_ENV[@]}" python -u bench.py

# 3. affine/bucket hardware A/B -> armed_flags.json
phase affine 2400 env "${PALLAS_ENV[@]}" python -u tools/affine_hw_check.py
AFFINE=0; HMODE=windowed
if grep -q "correctness vmap B=2: OK" "$OUT/affine.log" 2>/dev/null; then
  JR=$(grep -oP 'jacobian:.*-> \K[0-9.]+' "$OUT/affine.log" | head -1)
  AR=$(grep -oP '^affine:.*-> \K[0-9.]+' "$OUT/affine.log" | head -1)
  BR=$(grep -oP 'bucket w=16:.*-> \K[0-9.]+' "$OUT/affine.log" | head -1)
  [ -n "$JR" ] && [ -n "$AR" ] && python -c "import sys; sys.exit(0 if float('$AR') > float('$JR') else 1)" && AFFINE=1
  if grep -q "bucket correctness w=8: OK" "$OUT/affine.log" && [ -n "$BR" ] && [ -n "$JR" ]; then
    BEST=$JR; [ "$AFFINE" = 1 ] && BEST=$AR
    python -c "import sys; sys.exit(0 if float('$BR') > float('$BEST') else 1)" && HMODE=bucket
  fi
fi
echo "   armed: ZKP2P_MSM_AFFINE=$AFFINE ZKP2P_MSM_H=$HMODE" >> "$OUT/session.log"
printf '{"ZKP2P_MSM_AFFINE": "%s", "ZKP2P_MSM_H": "%s"}' "$AFFINE" "$HMODE" > .bench_cache/armed_flags.json

# 4. re-bench with the A/B winners armed; then the north-star metrics.
phase bench3 1800 env BENCH_TPU_BUDGET=1700 "${PALLAS_ENV[@]}" python -u bench.py
# single-proof latency (batch=1): the north-star p50 metric
phase bench_lat 1200 env BENCH_TPU_BUDGET=1100 BENCH_BATCH=1 "${PALLAS_ENV[@]}" python -u bench.py
# batch sweep (BASELINE.json configs[3]): amortization curve
phase bench_b32 1500 env BENCH_TPU_BUDGET=1400 BENCH_BATCH=32 "${PALLAS_ENV[@]}" python -u bench.py
phase bench_b64 1800 env BENCH_TPU_BUDGET=1700 BENCH_BATCH=64 "${PALLAS_ENV[@]}" python -u bench.py
# 5. MSM roofline datapoint with whatever won
phase msm_w8 900 env "${PALLAS_ENV[@]}" python -u tools/msm_hwbench.py --n 131072 --window 8 --signed --skip-adds
# 6. the 4.94 M-constraint flagship ON CHIP (VERDICT r4 next #4) — needs
#    the pallas field path (XLA matvec would OOM at full-size nnz) and
#    the key cached by tools/prove_fullsize_native.py.
if [ ${#PALLAS_ENV[@]} -eq 0 ] && [ -f .bench_cache/venmo_1024_6400.npz ]; then
  phase fullsize 3600 python -u tools/fullsize_tpu.py
fi
echo "== session3 done $(date +%H:%M:%S)" >> "$OUT/session.log"
