#!/bin/bash
# Tunnel-window session v2 (fired by tools/tpu_watch.sh the moment a
# probe sees the TPU up).  Order of business for a window of unknown
# length:
#   1. bench with a budget wide enough to finish the remaining cold
#      compiles in ONE window (every killed attempt still banks its
#      completed executables in the persistent cache)
#   2. the affine/bucket hardware A/B (tools/affine_hw_check.py)
#   3. record the winning h-MSM formulation in
#      .bench_cache/armed_flags.json — the driver's own bench.py reads
#      it and inherits validated arming with no human in the loop
#   4. kernel differential + a final bench with the winner armed
set -u
cd "$(dirname "$0")/.."
TS=$(date +%H%M%S)
OUT=docs/logs/tpu_session2_$TS
mkdir -p "$OUT"
phase() {
  local name=$1 tmo=$2; shift 2
  echo "-- $name ($(date +%H:%M:%S), timeout ${tmo}s): $*" >> "$OUT/session.log"
  timeout "$tmo" "$@" > "$OUT/$name.log" 2>&1
  echo "   rc=$? at $(date +%H:%M:%S)" >> "$OUT/session.log"
}
phase bench1 1800 env BENCH_TPU_BUDGET=1700 python -u bench.py
phase bench2 900 env BENCH_TPU_BUDGET=820 python -u bench.py
phase affine 2400 python -u tools/affine_hw_check.py
AFFINE=0; HMODE=windowed
if grep -q "correctness vmap B=2: OK" "$OUT/affine.log" 2>/dev/null; then
  JR=$(grep -oP 'jacobian:.*-> \K[0-9.]+' "$OUT/affine.log" | head -1)
  AR=$(grep -oP '^affine:.*-> \K[0-9.]+' "$OUT/affine.log" | head -1)
  BR=$(grep -oP 'bucket w=16:.*-> \K[0-9.]+' "$OUT/affine.log" | head -1)
  [ -n "$JR" ] && [ -n "$AR" ] && python -c "import sys; sys.exit(0 if float('$AR') > float('$JR') else 1)" && AFFINE=1
  if grep -q "bucket correctness w=8: OK" "$OUT/affine.log" && [ -n "$BR" ] && [ -n "$JR" ]; then
    BEST=$JR; [ "$AFFINE" = 1 ] && BEST=$AR
    python -c "import sys; sys.exit(0 if float('$BR') > float('$BEST') else 1)" && HMODE=bucket
  fi
fi
echo "   armed: ZKP2P_MSM_AFFINE=$AFFINE ZKP2P_MSM_H=$HMODE" >> "$OUT/session.log"
mkdir -p .bench_cache
printf '{"ZKP2P_MSM_AFFINE": "%s", "ZKP2P_MSM_H": "%s"}' "$AFFINE" "$HMODE" > .bench_cache/armed_flags.json
phase diff 1200 python -u tools/pallas_hw_diff.py
phase bench3 1800 env BENCH_TPU_BUDGET=1700 python -u bench.py
phase msm_w8 900 python -u tools/msm_hwbench.py --n 131072 --window 8 --signed --skip-adds
# single-proof latency (batch=1): the north-star p50 metric
phase bench_lat 1200 env BENCH_TPU_BUDGET=1100 BENCH_BATCH=1 python -u bench.py
# batch sweep 32/64 (BASELINE.json configs[3]): amortization curve
phase bench_b32 1200 env BENCH_TPU_BUDGET=1100 BENCH_BATCH=32 python -u bench.py
phase bench_b64 1500 env BENCH_TPU_BUDGET=1400 BENCH_BATCH=64 python -u bench.py
echo "== session2 done $(date +%H:%M:%S)" >> "$OUT/session.log"
