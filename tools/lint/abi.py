"""Cross-language ABI drift: csrc StatSlot vs native/lib.py STATS_FIELDS.

Rules (historical risk they encode — docs/STATIC_ANALYSIS.md):

  abi-drift    the `enum StatSlot` parsed out of csrc/zkp2p_native.cpp
               must mirror native/lib.py's STATS_FIELDS tuple EXACTLY —
               same count, same order, each ST_<NAME> lowercasing to the
               Python field name.  Index i on the Python side reads
               g_stats[i] on the C side; one inserted slot silently
               shifts every counter after it (pool_wait_ns becomes
               pool_run_ns and every derived rate lies).  The runtime
               guard (zkp2p_stats_count() == len(STATS_FIELDS), pinned
               in tests/test_metrics.py) only runs when the .so builds;
               this check holds on a toolchain-less tree too.

  abi-export   the C side must export `zkp2p_stats_count` returning
               ST_COUNT and `zkp2p_stats_snapshot` looping to ST_COUNT —
               the two symbols the ctypes bridge version-skew logic
               (native/lib.py stats_snapshot) depends on.
"""

from __future__ import annotations

import ast
import re
from typing import List, Optional, Tuple

from .core import Finding, Tree, str_const

CPP = "csrc/zkp2p_native.cpp"
LIB = "zkp2p_tpu/native/lib.py"

_ENUM_RE = re.compile(r"enum\s+StatSlot\s*\{(.*?)\}\s*;", re.S)
_ENTRY_RE = re.compile(r"^\s*(ST_[A-Z0-9_]+)", re.M)


def parse_enum(text: str) -> Tuple[Optional[int], List[str]]:
    """(line of the enum, ordered ST_* names minus ST_COUNT)."""
    m = _ENUM_RE.search(text)
    if not m:
        return None, []
    line = text[: m.start()].count("\n") + 1
    entries = [e for e in _ENTRY_RE.findall(m.group(1)) if e != "ST_COUNT"]
    return line, entries


def parse_stats_fields(sf) -> Tuple[Optional[int], List[str]]:
    """(line, entries) of the STATS_FIELDS tuple from lib.py's AST."""
    if sf is None or sf.tree is None:
        return None, []
    for node in ast.walk(sf.tree):
        if isinstance(node, ast.Assign) and len(node.targets) == 1:
            t = node.targets[0]
            if isinstance(t, ast.Name) and t.id == "STATS_FIELDS" and isinstance(node.value, (ast.Tuple, ast.List)):
                fields = [s for s in (str_const(e) for e in node.value.elts) if s]
                return node.lineno, fields
    return None, []


def check(tree: Tree) -> List[Finding]:
    findings: List[Finding] = []
    cpp = tree.c_files.get(CPP)
    sf = tree.files.get(LIB)
    if cpp is None and sf is None:
        return findings  # no native layer in this tree — nothing to drift
    if cpp is None or sf is None:
        findings.append(Finding("abi-drift", CPP if cpp is None else LIB, 1,
                                "stats ABI source missing — cannot verify StatSlot mirror"))
        return findings

    enum_line, slots = parse_enum(cpp)
    py_line, fields = parse_stats_fields(sf)
    if enum_line is None:
        findings.append(Finding("abi-drift", CPP, 1, "enum StatSlot not found"))
    if py_line is None:
        findings.append(Finding("abi-drift", LIB, 1, "STATS_FIELDS tuple not found"))
    if enum_line is not None and py_line is not None:
        mirrored = [s[len("ST_"):].lower() for s in slots]
        if mirrored != list(fields):
            # name the first divergent index — that is where every later
            # counter starts lying
            n = min(len(mirrored), len(fields))
            at = next((i for i in range(n) if mirrored[i] != fields[i]), n)
            cpp_at = mirrored[at] if at < len(mirrored) else "<missing>"
            py_at = fields[at] if at < len(fields) else "<missing>"
            findings.append(Finding(
                "abi-drift", LIB, py_line,
                f"STATS_FIELDS diverges from csrc enum StatSlot at index {at}: "
                f"C says {cpp_at!r}, Python says {py_at!r} "
                f"(C has {len(mirrored)} slots, Python {len(fields)}) — every slot "
                "from there on reads the wrong counter",
            ))

    # exports the ctypes bridge's version-skew logic relies on
    if not re.search(r"zkp2p_stats_count\s*\(\s*void\s*\)\s*\{\s*return\s+ST_COUNT\s*;", cpp):
        findings.append(Finding(
            "abi-export", CPP, enum_line or 1,
            "zkp2p_stats_count export must return ST_COUNT verbatim — it is the "
            "runtime drift guard the ctypes bridge sizes its read buffer by",
        ))
    if "zkp2p_stats_snapshot" not in cpp:
        findings.append(Finding(
            "abi-export", CPP, enum_line or 1,
            "zkp2p_stats_snapshot export missing — stats_snapshot() would "
            "AttributeError instead of degrading",
        ))
    return findings
