"""Clock discipline: wall time is for timestamps, perf_counter for spans.

trace.py's rule (its spans use time.perf_counter; its manifests stamp
time.time): wall clock is CORRECT for anything compared across
processes — request arrival anchors, claim-file mtimes, deadlines — and
WRONG for measuring an in-process duration, where an NTP step or a
suspend/resume silently corrupts the reading.  Two rules:

  clock-span    a local variable assigned from time.time() whose ONLY
                use is as the subtrahend of a subtraction (the
                `t0 = time.time(); ... time.time() - t0` span idiom) is
                a wall-clock span: use time.perf_counter().  A t0 that
                is ALSO stored/passed/compared is a cross-process
                timestamp anchor and stays wall-clock by design (the
                service waterfall records both the anchor and the
                elapsed, so its wall-wall subtraction is deliberate).

  clock-mix     subtracting across the two clocks (a perf_counter
                reading minus a time.time() reading, either direction,
                direct or via locals) is meaningless in every case.
"""

from __future__ import annotations

import ast
from typing import Dict, List, Optional

from .core import Finding, Tree, call_name, functions_of, parent_map

_WALL = ("time.time",)
_PERF = ("time.perf_counter", "time.monotonic")


def _clock_of_call(node) -> Optional[str]:
    if isinstance(node, ast.Call):
        name = call_name(node)
        if name in _WALL:
            return "wall"
        if name in _PERF:
            return "perf"
    return None


def check(tree: Tree) -> List[Finding]:
    findings: List[Finding] = []
    for sf in tree.py_files():
        if sf.tree is None:
            continue
        parents = parent_map(sf.tree)
        for fn in functions_of(sf.tree):
            clock_vars: Dict[str, str] = {}  # local name -> "wall"|"perf"
            assigns: Dict[str, int] = {}
            for node in ast.walk(fn):
                if isinstance(node, ast.Assign) and len(node.targets) == 1:
                    t = node.targets[0]
                    c = _clock_of_call(node.value)
                    if isinstance(t, ast.Name) and c:
                        clock_vars[t.id] = c
                        assigns[t.id] = node.lineno

            def clock_of(expr) -> Optional[str]:
                c = _clock_of_call(expr)
                if c:
                    return c
                if isinstance(expr, ast.Name):
                    return clock_vars.get(expr.id)
                return None

            # clock-mix: any subtraction across clock families
            for node in ast.walk(fn):
                if isinstance(node, ast.BinOp) and isinstance(node.op, ast.Sub):
                    lc, rc = clock_of(node.left), clock_of(node.right)
                    if lc and rc and lc != rc:
                        findings.append(Finding(
                            "clock-mix", sf.relpath, node.lineno,
                            f"subtraction mixes {lc} and {rc} clocks — the result "
                            "is meaningless on every host",
                        ))

            # clock-span: wall-assigned locals used only as subtrahends
            for var, clock in clock_vars.items():
                if clock != "wall":
                    continue
                only_sub, used = True, False
                for node in ast.walk(fn):
                    if isinstance(node, ast.Name) and node.id == var and isinstance(node.ctx, ast.Load):
                        used = True
                        p = parents.get(node)
                        if not (isinstance(p, ast.BinOp) and isinstance(p.op, ast.Sub) and p.right is node):
                            only_sub = False
                            break
                if used and only_sub:
                    findings.append(Finding(
                        "clock-span", sf.relpath, assigns[var],
                        f"{var} = time.time() is used only to measure an in-process "
                        "span — use time.perf_counter() (trace.py clock rule; an "
                        "NTP step mid-span corrupts wall-clock durations)",
                    ))
    return findings
