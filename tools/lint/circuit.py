"""Circuit tier of zkp2p-lint: the constraint-tag source rule and the
R1CS soundness-audit runner.

Two halves, matching the two ways circuit bugs enter the tree:

  * ``check(tree)`` — pure-AST rule over the circuit-building surface
    (gadgets/, models/, regexc/): every ``enforce`` / ``enforce_eq`` /
    ``enforce_zero`` call site must pass a non-empty ``tag``.  Audit
    findings and check_witness failures are attributed BY TAG — an
    untagged constraint makes them anonymous, which is how the round-2
    bh= bug hid inside a wall of unattributed rows.
  * ``run_circuit_audit()`` — builds every registered circuit
    (zkp2p_tpu.models.registry) and runs the static soundness audit
    (zkp2p_tpu.snark.analysis): unconstrained wires, the determinism
    fixpoint, bool/width demands, dead/duplicate constraints, hook
    coverage, public-layout parity.  This half IMPORTS the package (it
    must build real circuits), so it is a separate tier from `make
    lint`: ``zkp2p-tpu lint --circuits`` / ``make circuit-audit`` —
    still jax-free (gadgets/models need only numpy), still the
    registry's admission gate.
"""

from __future__ import annotations

import ast
from typing import List, Optional

from .core import Finding, Tree, call_name

# The circuit-building surface: constraints emitted anywhere else (tests
# build throwaway fixtures) are not part of a shipped circuit.
_TAGGED_ROOTS = (
    "zkp2p_tpu/gadgets/",
    "zkp2p_tpu/models/",
    "zkp2p_tpu/regexc/",
)

# method -> 1-based positional index of the tag parameter
_TAG_POS = {"enforce": 4, "enforce_eq": 3, "enforce_zero": 2}


def check(tree: Tree) -> List[Finding]:
    out: List[Finding] = []
    for sf in tree.py_files():
        if sf.tree is None or not sf.relpath.startswith(_TAGGED_ROOTS):
            continue
        for node in ast.walk(sf.tree):
            if not isinstance(node, ast.Call):
                continue
            name = call_name(node).rsplit(".", 1)[-1]
            pos = _TAG_POS.get(name)
            if pos is None or not isinstance(node.func, ast.Attribute):
                continue
            tag = node.args[pos - 1] if len(node.args) >= pos else None
            for kw in node.keywords:
                if kw.arg == "tag":
                    tag = kw.value
            empty = tag is None or (
                isinstance(tag, ast.Constant) and tag.value in ("", None)
            )
            if empty:
                out.append(
                    Finding(
                        "constraint-tag",
                        sf.relpath,
                        node.lineno,
                        f"{name}() without a tag: audit findings and "
                        "check_witness failures on this constraint are "
                        "unattributable",
                    )
                )
    return out


def run_circuit_audit(
    names: Optional[List[str]] = None,
    include_flagship: bool = False,
    use_cache: bool = True,
    as_json: bool = False,
) -> int:
    """Audit registered circuits; print one line per circuit (or a JSON
    report list).  Exit code is a bitmask so mixed failures survive:
    bit 0 = some circuit was REFUSED, bit 1 = unknown circuit id."""
    import json
    import sys

    from zkp2p_tpu.models import registry
    from zkp2p_tpu.snark.analysis import CircuitAuditError

    ids = names or registry.circuit_ids(include_flagship=include_flagship)
    reports = []
    rc = 0
    for name in ids:
        if name not in registry.SPECS:
            # checked HERE so a KeyError from inside a circuit builder
            # is a real crash, not misreported as a bad id
            print(
                f"circuit-audit: unknown circuit {name!r}; registered: "
                f"{', '.join(sorted(registry.SPECS))}",
                file=sys.stderr,
            )
            rc |= 2
            continue
        try:
            _, rep = registry.audited(name, use_cache=use_cache)
        except CircuitAuditError as e:
            print(e, file=sys.stderr)
            rep = getattr(e, "report", None)
            if rep is not None:
                reports.append(rep)  # --json consumers get the refusal too
            rc |= 1
            continue
        reports.append(rep)
        if not as_json:
            print(
                f"circuit-audit {name}: clean — 0 unwaived / "
                f"{rep['waived']} waived findings, "
                f"{rep['n_constraints']} constraints / {rep['n_wires']} wires, "
                f"{rep['audit_s']}s ({rep['source']}, digest {rep['digest']})"
            )
    if as_json:
        print(json.dumps(reports, indent=1))
    return rc
