"""Curated pyflakes-tier baseline, with `ruff` grafted on when present.

The container this repo builds in has no ruff/pyflakes and nothing may
be installed, so the pyflakes-tier rules that have bitten (or nearly
bitten) this tree are reimplemented here over the shared AST cache, and
an installed `ruff` binary — when one exists on PATH — is run on top
with the same curated rule set (F401,F541,F632,F811,F821,E722) so a
richer environment gets the richer checker for free.  Rules:

  unused-import   a module-level import never referenced in its file.
                  Exemptions keep it zero-noise on a healthy tree:
                  `__init__.py` files (re-export surface), names listed
                  in `__all__`, imports inside try/except (availability
                  probes), `from __future__`, `# noqa` lines, and names
                  another scanned module imports FROM this module (the
                  cross-file re-export check — removing those breaks
                  the importer, which pyflakes famously cannot see).

  fstring-placeholder   an f-string with no {placeholders}: almost
                  always a forgotten interpolation (the r2 bench once
                  logged the literal text "{rate} proofs/s").

  bare-except     `except:` catches SystemExit/KeyboardInterrupt — a
                  drain-loop worker becomes unkillable.  The repo
                  standard is `except Exception:  # noqa: BLE001 + why`.

  dict-dup-key    duplicate literal keys in a dict display: the first
                  value is silently discarded.

  assert-tuple    `assert (cond, "msg")` is always true.
"""

from __future__ import annotations

import ast
import os
import shutil
import subprocess
from typing import Dict, List, Set, Tuple

from .core import Finding, Tree, str_const

RUFF_RULES = "F401,F541,F632,F811,F821,E722"


def _module_of(relpath: str) -> str:
    return relpath[:-3].replace(os.sep, ".").replace("/", ".")


def _resolve_from(relpath: str, node: ast.ImportFrom) -> str:
    """Dotted module an ImportFrom pulls from, relative imports resolved
    against the importing file's package."""
    if node.level == 0:
        return node.module or ""
    pkg = _module_of(relpath).split(".")
    # drop the filename, then (level-1) more packages
    pkg = pkg[: max(0, len(pkg) - node.level)]
    return ".".join(pkg + ([node.module] if node.module else []))


def _reexport_edges(tree: Tree) -> Set[Tuple[str, str]]:
    """(module, name) pairs some OTHER file imports — an unused import
    in `module` named `name` is a re-export, not dead code.  tests/ is
    parsed as an edge SOURCE even though it is never linted: removing an
    import a test consumes breaks the suite, which pyflakes-class tools
    famously cannot see."""
    edges: Set[Tuple[str, str]] = set()

    def add_edges(relpath: str, tree_node: ast.AST) -> None:
        for node in ast.walk(tree_node):
            if isinstance(node, ast.ImportFrom):
                mod = _resolve_from(relpath, node)
                for a in node.names:
                    edges.add((mod, a.name))
            elif isinstance(node, ast.Import):
                for a in node.names:
                    # `import pkg.sub` marks every name in pkg.sub reachable
                    edges.add((a.name, "*"))

    for sf in tree.py_files():
        if sf.tree is not None:
            add_edges(sf.relpath, sf.tree)
    tests_dir = os.path.join(tree.root, "tests")
    if os.path.isdir(tests_dir):
        for n in sorted(os.listdir(tests_dir)):
            if not n.endswith(".py"):
                continue
            try:
                with open(os.path.join(tests_dir, n), errors="ignore") as f:
                    add_edges(os.path.join("tests", n), ast.parse(f.read()))
            except SyntaxError:
                pass
    return edges


def _used_names(tree_node: ast.AST) -> Set[str]:
    used: Set[str] = set()
    for node in ast.walk(tree_node):
        if isinstance(node, ast.Name):
            used.add(node.id)
        elif isinstance(node, ast.Attribute):
            n = node
            while isinstance(n, ast.Attribute):
                n = n.value
            if isinstance(n, ast.Name):
                used.add(n.id)
    return used


def _all_list(tree_node: ast.AST) -> Set[str]:
    out: Set[str] = set()
    for node in ast.walk(tree_node):
        if isinstance(node, ast.Assign):
            for t in node.targets:
                if isinstance(t, ast.Name) and t.id == "__all__":
                    for e in ast.walk(node.value):
                        s = str_const(e)
                        if s:
                            out.add(s)
    return out


def check(tree: Tree) -> List[Finding]:
    findings: List[Finding] = []
    edges = _reexport_edges(tree)
    for sf in tree.py_files():
        if sf.tree is None:
            continue
        findings.extend(_check_file(sf, edges))
    findings.extend(_run_ruff(tree))
    return findings


def _check_file(sf, edges) -> List[Finding]:
    findings: List[Finding] = []
    mod = _module_of(sf.relpath)
    is_init = os.path.basename(sf.relpath) == "__init__.py"
    used = _used_names(sf.tree)
    exported = _all_list(sf.tree)

    # ---- unused-import (module level, outside try/except probes) ----
    if not is_init:
        probe_lines: Set[int] = set()
        for node in ast.walk(sf.tree):
            if isinstance(node, ast.Try):
                for sub in ast.walk(node):
                    if isinstance(sub, (ast.Import, ast.ImportFrom)):
                        probe_lines.add(sub.lineno)
        for node in sf.tree.body:
            if not isinstance(node, (ast.Import, ast.ImportFrom)):
                continue
            if isinstance(node, ast.ImportFrom) and node.module == "__future__":
                continue
            if node.lineno in probe_lines or "noqa" in sf.lines[node.lineno - 1]:
                continue
            for a in node.names:
                if a.name == "*":
                    continue
                name = a.asname or a.name.split(".")[0]
                if name in used or name in exported or name.startswith("_"):
                    continue
                if (mod, name) in edges or (mod, "*") in edges:
                    continue  # re-exported: another module imports it from here
                findings.append(Finding(
                    "unused-import", sf.relpath, node.lineno,
                    f"{name!r} imported but unused (and not re-exported by any "
                    "scanned module)",
                ))

    # ---- AST-shape rules ----
    # format specs (`f"{x:.0f}"`) are themselves JoinedStr nodes with no
    # FormattedValue children — collect them so the placeholder rule only
    # sees top-level f-strings
    spec_nodes = set()
    for node in ast.walk(sf.tree):
        if isinstance(node, ast.FormattedValue) and node.format_spec is not None:
            spec_nodes.add(id(node.format_spec))
    for node in ast.walk(sf.tree):
        if isinstance(node, ast.JoinedStr) and id(node) not in spec_nodes:
            if not any(isinstance(v, ast.FormattedValue) for v in node.values):
                findings.append(Finding(
                    "fstring-placeholder", sf.relpath, node.lineno,
                    "f-string without any placeholder — forgotten interpolation?",
                ))
        elif isinstance(node, ast.ExceptHandler) and node.type is None:
            findings.append(Finding(
                "bare-except", sf.relpath, node.lineno,
                "bare `except:` swallows SystemExit/KeyboardInterrupt — a "
                "drain-loop worker becomes unkillable; catch Exception",
            ))
        elif isinstance(node, ast.Dict):
            seen: Dict[object, int] = {}
            for k in node.keys:
                if isinstance(k, ast.Constant):
                    key = (type(k.value).__name__, k.value)
                    if key in seen:
                        findings.append(Finding(
                            "dict-dup-key", sf.relpath, k.lineno,
                            f"duplicate dict key {k.value!r} — the first value is "
                            "silently discarded",
                        ))
                    seen[key] = k.lineno
        elif isinstance(node, ast.Assert) and isinstance(node.test, ast.Tuple) and node.test.elts:
            findings.append(Finding(
                "assert-tuple", sf.relpath, node.lineno,
                "assert on a non-empty tuple is always true",
            ))
    return findings


def _run_ruff(tree: Tree) -> List[Finding]:
    """Graft an installed ruff on top (curated rule set, same output
    model).  Absent binary = silently skipped: the container bakes no
    linters and installing one is off the table, so the built-in rules
    above are the floor and ruff is the opportunistic ceiling."""
    ruff = shutil.which("ruff")
    if not ruff:
        return []
    targets = [os.path.join(tree.root, r) for r in ("zkp2p_tpu", "bench.py")]
    try:
        r = subprocess.run(
            [ruff, "check", "--select", RUFF_RULES, "--output-format", "concise", *targets],
            capture_output=True, text=True, timeout=120,
        )
    except Exception:  # noqa: BLE001 — opportunistic layer only
        return []
    findings = []
    for line in r.stdout.splitlines():
        # path:line:col: CODE message
        parts = line.split(":", 3)
        if len(parts) == 4 and parts[1].isdigit():
            rel = os.path.relpath(parts[0], tree.root)
            findings.append(Finding("ruff", rel, int(parts[1]), parts[3].strip()))
    return findings
