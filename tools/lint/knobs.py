"""Knob discipline: the typed config is the ONLY door to ZKP2P_* env.

Rules (historical bugs they encode — docs/STATIC_ANALYSIS.md):

  knob-registry   every `ZKP2P_*` string referenced in zkp2p_tpu/,
                  tools/, bench.py, __graft_entry__.py, or read via
                  getenv() in csrc/ must be a registered knob in
                  utils/config.py KNOBS.  The invisible-ZKP2P_SLO_P95_S
                  bug: a knob consumed by the SLO tracker that no
                  config, doctor report, or manifest knew existed.

  env-read        raw READS of ZKP2P_* via os.environ.get /
                  os.environ[...] / os.getenv outside the sanctioned
                  fresh-read sites (utils/config.py — THE resolver;
                  utils/faults.py — the fault spec's documented
                  fresh-read; utils/jaxcfg.py — ZKP2P_NO_CACHE consumed
                  before the config package may import).  Writes are
                  the TRANSPORT (apply_env contract) and stay legal
                  everywhere.  A scattered read bypasses the
                  default->armed->env resolution order and the
                  provenance record.
"""

from __future__ import annotations

import ast
import re
from typing import List

from .core import Finding, Tree, call_name, parse_config_registry, str_const

# non-knob ZKP2P_ tokens that legitimately appear in the tree
ALLOWED_EXTRA = {
    "ZKP2P_RUN_SLOW",   # test-tier gate, read only by the suite/Makefile
    "ZKP2P_RUN_XSLOW",  # ditto
    "ZKP2P_",           # prefix literals in scanners/docs
    "ZKP2P_HAVE_IFMA",  # C compile-time macro, not an env knob
    "ZKP2P_REPO",       # subprocess-test plumbing (abs repo path)
    "ZKP2P_ASAN_SO",    # sanitizer-test plumbing
    "ZKP2P_TSAN_SO",    # sanitizer-test plumbing
}

# files whose raw ZKP2P_* reads are the sanctioned fresh-read sites
SANCTIONED_READERS = {
    "zkp2p_tpu/utils/config.py",   # the resolver itself
    "zkp2p_tpu/utils/faults.py",   # ZKP2P_FAULTS fresh-read (docs/ROBUSTNESS.md)
    "zkp2p_tpu/utils/jaxcfg.py",   # ZKP2P_NO_CACHE before config may import
}

_TOKEN = re.compile(r"ZKP2P_[A-Z0-9_]*")
_GETENV_C = re.compile(r'getenv\(\s*"([A-Za-z0-9_]+)"\s*\)')


def check(tree: Tree) -> List[Finding]:
    knobs, _armable = parse_config_registry(tree)
    registered = set(knobs.values())
    findings: List[Finding] = []
    if not registered:
        findings.append(Finding(
            "knob-registry", "zkp2p_tpu/utils/config.py", 1,
            "could not parse the KNOBS registry — the linter's anchor is gone",
        ))
        return findings

    # ---- knob-registry: every ZKP2P_* token is a registered knob ----
    for sf in tree.py_files():
        if sf.relpath.endswith("utils/config.py"):
            continue  # the registry defines the names
        for i, line in enumerate(sf.lines, 1):
            for tok in _TOKEN.findall(line):
                if tok not in registered and tok not in ALLOWED_EXTRA:
                    findings.append(Finding(
                        "knob-registry", sf.relpath, i,
                        f"{tok} is not in the utils/config.py KNOBS registry "
                        "(unregistered knobs are invisible to doctor/manifest/provenance)",
                    ))
    for relpath, text in tree.c_files.items():
        for i, line in enumerate(text.splitlines(), 1):
            for m in _GETENV_C.finditer(line):
                var = m.group(1)
                if var.startswith("ZKP2P_") and var not in registered and var not in ALLOWED_EXTRA:
                    findings.append(Finding(
                        "knob-registry", relpath, i,
                        f"csrc getenv(\"{var}\") has no registered knob — the C runtime "
                        "would read config the typed registry cannot resolve or audit",
                    ))

    # ---- env-read: raw reads outside the sanctioned sites ----
    for sf in tree.py_files():
        if sf.relpath in SANCTIONED_READERS or sf.tree is None:
            continue
        for node in ast.walk(sf.tree):
            var = _read_zkp2p_var(node)
            if var is None:
                continue
            findings.append(Finding(
                "env-read", sf.relpath, node.lineno,
                f"raw os.environ read of {var} outside the sanctioned fresh-read "
                "sites — resolve through utils.config.load_config() so armed flags "
                "and provenance apply",
            ))
    return findings


def _read_zkp2p_var(node) -> str:
    """The ZKP2P_* var a node READS, or None.  Covers os.environ.get(X),
    os.getenv(X), and os.environ[X] in Load context (subscript STORES
    are apply_env-style transport and stay legal)."""
    if isinstance(node, ast.Call):
        name = call_name(node)
        if name in ("os.environ.get", "os.getenv", "environ.get", "getenv") and node.args:
            s = str_const(node.args[0])
            if s and s.startswith("ZKP2P_"):
                return s
    elif isinstance(node, ast.Subscript) and isinstance(node.ctx, ast.Load):
        base = node.value
        if isinstance(base, ast.Attribute) and base.attr == "environ":
            s = str_const(node.slice)
            if s and s.startswith("ZKP2P_"):
                return s
    return None
