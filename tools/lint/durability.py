"""Durability discipline for the spool/fleet state machine.

Rules (historical bug they encode — docs/STATIC_ANALYSIS.md):

  durable-write   in zkp2p_tpu/pipeline/*, a truncating `open(path,
                  "w"/"wb")` is only legal when the enclosing function
                  also renames the result into place (os.replace /
                  os.rename — the tmp+rename idiom `_atomic_write`
                  uses) or the path itself is a `.tmp` staging name.
                  A bare truncating write on a status/claim/heartbeat
                  path is the takeover-protocol bug waiting to happen:
                  a reader (a peer worker deciding whether to steal a
                  claim, the supervisor reading status.json) can see a
                  half-written or empty file and act on it.

  durable-open    `os.open` with O_WRONLY/O_RDWR in the same modules
                  must carry O_EXCL (the claim-file create-or-lose
                  protocol) or O_APPEND (the JSONL sink contract:
                  one atomic append per record) — a bare O_CREAT|
                  O_WRONLY silently truncates-and-races the same way.

`os.fdopen` over an already-O_EXCL fd is exempt (the fd carries the
atomicity); read-mode opens are exempt everywhere.
"""

from __future__ import annotations

import ast
from typing import List

from .core import Finding, Tree, call_name, functions_of, str_const

# the spool/fleet state-machine modules — the files whose writes have
# concurrent readers applying the takeover/heartbeat/status protocols.
# cli.py's one-shot build artifacts (verifier.sol, proof.json) have no
# concurrent reader and stay out of scope.
SCOPE = (
    "zkp2p_tpu/pipeline/service.py",
    "zkp2p_tpu/pipeline/fleet.py",
    "zkp2p_tpu/pipeline/fleet_obs.py",
)
_RENAMERS = {"os.replace", "os.rename", "replace", "rename"}


def _mode_of(call: ast.Call):
    if len(call.args) >= 2:
        return str_const(call.args[1])
    for kw in call.keywords:
        if kw.arg == "mode":
            return str_const(kw.value)
    return None


def _flag_names(expr) -> set:
    """All os.O_* attribute names in a flags expression."""
    out = set()
    for node in ast.walk(expr):
        if isinstance(node, ast.Attribute) and node.attr.startswith("O_"):
            out.add(node.attr)
    return out


def _path_is_tmp(fn: ast.AST, arg) -> bool:
    """True when the written path is visibly a .tmp staging name: a
    literal/f-string containing '.tmp', or a local Name assigned from
    one inside the same function."""
    def expr_tmp(e) -> bool:
        for node in ast.walk(e):
            s = str_const(node)
            if s and ".tmp" in s:
                return True
        return False

    if expr_tmp(arg):
        return True
    if isinstance(arg, ast.Name):
        for node in ast.walk(fn):
            if isinstance(node, ast.Assign):
                for t in node.targets:
                    if isinstance(t, ast.Name) and t.id == arg.id and expr_tmp(node.value):
                        return True
    return False


def check(tree: Tree) -> List[Finding]:
    findings: List[Finding] = []
    for sf in tree.py_files():
        if sf.relpath not in SCOPE or sf.tree is None:
            continue
        for fn in functions_of(sf.tree):
            renames = any(
                isinstance(n, ast.Call) and call_name(n) in _RENAMERS
                for n in ast.walk(fn)
            )
            for node in ast.walk(fn):
                if not isinstance(node, ast.Call):
                    continue
                name = call_name(node)
                if name == "open" and node.args:
                    mode = _mode_of(node)
                    if mode and "w" in mode and not renames and not _path_is_tmp(fn, node.args[0]):
                        findings.append(Finding(
                            "durable-write", sf.relpath, node.lineno,
                            f"truncating open(..., {mode!r}) in {fn.name}() without "
                            "tmp+rename — a concurrent reader can observe a torn "
                            "file (spool/fleet durability contract)",
                        ))
                elif name in ("os.open",) and len(node.args) >= 2:
                    flags = _flag_names(node.args[1])
                    if ("O_WRONLY" in flags or "O_RDWR" in flags) and not (
                        "O_EXCL" in flags or "O_APPEND" in flags
                    ):
                        findings.append(Finding(
                            "durable-open", sf.relpath, node.lineno,
                            f"os.open with {'|'.join(sorted(flags))} in {fn.name}() "
                            "needs O_EXCL (claim protocol) or O_APPEND (JSONL "
                            "contract) — bare write flags truncate-and-race",
                        ))
    return findings
