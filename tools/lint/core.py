"""zkp2p-lint core: finding model, source walking, waivers, the runner.

The checkers in this package encode invariants the repo already bled
for (each rule's docstring names the historical bug it fossilizes —
docs/STATIC_ANALYSIS.md carries the full table).  Design constraints:

  * **No imports of the checked code.**  Everything is AST/regex over
    source text, so `make lint` runs in seconds on a box with no
    toolchain, no jax, and no built `.so` — the ABI-drift checker in
    particular must work when the native library cannot build.
  * **Zero findings on a healthy tree.**  A rule that cries wolf gets
    deleted; anything intentionally exempt carries an inline waiver
    (`# lint: allow[<rule>] <reason>`) or a named sanction in the
    checker itself, so every exception is greppable and justified.
  * **Provably able to fail.**  tests/test_lint.py seeds one violation
    per rule and asserts the checker reports it — the same "checker
    proven able to fail" discipline the chaos harness applies to its
    invariants (docs/ROBUSTNESS.md).
"""

from __future__ import annotations

import ast
import os
import re
from dataclasses import dataclass
from typing import Dict, Iterable, List, Optional, Tuple

REPO = os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

# Python source the domain checkers (knobs/gates/metrics/durability/
# clocks) police.  tools/lint itself is excluded everywhere: the scanner
# necessarily contains the patterns it hunts.
PY_SCAN_ROOTS = ("zkp2p_tpu", "tools", "bench.py", "__graft_entry__.py")
EXCLUDE_DIRS = ("tools/lint",)


@dataclass(frozen=True)
class Finding:
    rule: str
    path: str  # repo-relative
    line: int
    msg: str

    def __str__(self) -> str:
        return f"{self.path}:{self.line}: [{self.rule}] {self.msg}"


class SourceFile:
    """One parsed source file: text, line list, AST (py only), waivers."""

    _WAIVER_RE = re.compile(r"#\s*lint:\s*allow\[([a-z0-9\-,\s]+)\]")

    def __init__(self, relpath: str, text: str):
        self.relpath = relpath
        self.text = text
        self.lines = text.splitlines()
        self.tree: Optional[ast.AST] = None
        self.parse_error: Optional[str] = None
        if relpath.endswith(".py"):
            try:
                self.tree = ast.parse(text)
            except SyntaxError as e:
                self.parse_error = f"{e.msg} (line {e.lineno})"
        # line -> set of waived rule names
        self.waivers: Dict[int, set] = {}
        for i, ln in enumerate(self.lines, 1):
            m = self._WAIVER_RE.search(ln)
            if m:
                self.waivers[i] = {r.strip() for r in m.group(1).split(",")}

    def waived(self, rule: str, line: int) -> bool:
        return rule in self.waivers.get(line, ())


class Tree:
    """The lint target: every scanned file, parsed once, shared by all
    checkers (the AST cache is what keeps the whole pass under seconds)."""

    def __init__(self, root: str = REPO, roots: Iterable[str] = PY_SCAN_ROOTS):
        self.root = root
        self.files: Dict[str, SourceFile] = {}
        for r in roots:
            path = os.path.join(root, r)
            if os.path.isfile(path):
                self._add(r)
            elif os.path.isdir(path):
                for dirpath, dirs, names in os.walk(path):
                    rel_dir = os.path.relpath(dirpath, root)
                    if any(rel_dir == e or rel_dir.startswith(e + os.sep) for e in EXCLUDE_DIRS):
                        dirs[:] = []
                        continue
                    for n in sorted(names):
                        if n.endswith(".py"):
                            self._add(os.path.join(rel_dir, n))
        # C sources are scanned by regex only (getenv sites, StatSlot)
        self.c_files: Dict[str, str] = {}
        csrc = os.path.join(root, "csrc")
        if os.path.isdir(csrc):
            for n in sorted(os.listdir(csrc)):
                if n.endswith((".cpp", ".cc", ".h")):
                    with open(os.path.join(csrc, n), errors="ignore") as f:
                        self.c_files[os.path.join("csrc", n)] = f.read()

    def _add(self, rel: str) -> None:
        with open(os.path.join(self.root, rel), errors="ignore") as f:
            self.files[rel] = SourceFile(rel, f.read())

    def py_files(self) -> List[SourceFile]:
        return list(self.files.values())


# ---------------------------------------------------------------------------
# Shared AST helpers


def call_name(node: ast.Call) -> str:
    """Dotted name of a call target ('os.environ.get', 'record_arm')."""
    parts: List[str] = []
    n = node.func
    while isinstance(n, ast.Attribute):
        parts.append(n.attr)
        n = n.value
    if isinstance(n, ast.Name):
        parts.append(n.id)
    return ".".join(reversed(parts))


def str_const(node) -> Optional[str]:
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return node.value
    return None


def functions_of(tree: ast.AST):
    """Every function/method definition (nested included)."""
    for node in ast.walk(tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            yield node


def parent_map(tree: ast.AST) -> Dict[ast.AST, ast.AST]:
    parents: Dict[ast.AST, ast.AST] = {}
    for node in ast.walk(tree):
        for child in ast.iter_child_nodes(node):
            parents[child] = node
    return parents


def parse_config_registry(tree_obj: "Tree") -> Tuple[Dict[str, str], Tuple[str, ...]]:
    """(knob attr -> env var) and the ARMABLE tuple, read from
    utils/config.py WITHOUT importing it (the linter must run on a tree
    whose imports are broken — that is exactly when it is most useful)."""
    sf = tree_obj.files.get(os.path.join("zkp2p_tpu", "utils", "config.py"))
    knobs: Dict[str, str] = {}
    armable: Tuple[str, ...] = ()
    if sf is None or sf.tree is None:
        return knobs, armable
    for node in ast.walk(sf.tree):
        if isinstance(node, ast.AnnAssign) and node.value is not None:
            t, value = node.target, node.value
            if isinstance(t, ast.Name) and t.id == "KNOBS" and isinstance(value, ast.Dict):
                for k, v in zip(value.keys, value.values):
                    attr = str_const(k)
                    if attr is None or not isinstance(v, ast.Tuple) or not v.elts:
                        continue
                    var = str_const(v.elts[0])
                    if var:
                        knobs[attr] = var
        elif isinstance(node, ast.Assign) and len(node.targets) == 1:
            t = node.targets[0]
            if isinstance(t, ast.Name) and t.id == "KNOBS" and isinstance(node.value, ast.Dict):
                for k, v in zip(node.value.keys, node.value.values):
                    attr = str_const(k)
                    if attr is None or not isinstance(v, ast.Tuple) or not v.elts:
                        continue
                    var = str_const(v.elts[0])
                    if var:
                        knobs[attr] = var
            elif isinstance(t, ast.Name) and t.id == "ARMABLE" and isinstance(node.value, ast.Tuple):
                armable = tuple(s for s in (str_const(e) for e in node.value.elts) if s)
    return knobs, armable


# ---------------------------------------------------------------------------
# Runner


def run_checkers(tree: Tree, rules: Optional[Iterable[str]] = None) -> List[Finding]:
    from . import abi, circuit, clocks, durability, gates, knobs, metric_names, pyflakes_lite

    checkers = [
        knobs.check,
        gates.check,
        abi.check,
        metric_names.check,
        durability.check,
        clocks.check,
        circuit.check,
        pyflakes_lite.check,
    ]
    findings: List[Finding] = []
    for c in checkers:
        findings.extend(c(tree))
    # a file that does not parse is itself a finding — every other
    # checker silently skipped it, and silence is the failure mode this
    # tool exists to kill
    for sf in tree.py_files():
        if sf.parse_error:
            findings.append(Finding("syntax", sf.relpath, 1, f"unparseable: {sf.parse_error}"))
    if rules:
        want = set(rules)
        findings = [f for f in findings if f.rule in want]
    # drop waived findings (inline `# lint: allow[rule] reason`) and
    # dedupe (nested functions can surface one site twice)
    out = []
    seen = set()
    for f in findings:
        sf = tree.files.get(f.path)
        if sf is not None and sf.waived(f.rule, f.line):
            continue
        key = (f.rule, f.path, f.line, f.msg)
        if key in seen:
            continue
        seen.add(key)
        out.append(f)
    return sorted(out, key=lambda f: (f.path, f.line, f.rule))
