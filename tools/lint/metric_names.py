"""Metric naming discipline: one namespace, Prometheus-conventional.

Rules (docs/STATIC_ANALYSIS.md):

  metric-name   every literal family registered via .counter/.gauge/
                .histogram must match ^zkp2p_[a-z0-9_]+$; counters must
                end `_total` (Prometheus counter convention — scrapers
                and the fleet merge both key on it), non-counters must
                NOT end `_total` (the fleet plane SUMS `_total` families
                across workers; a gauge named like a counter would be
                summed into nonsense), and no family may end in the
                exposition-reserved `_bucket`/`_sum`/`_count`/`_info`.

  metric-kind   one family name, one instrument kind.  The same name
                registered as both a counter and a gauge would merge
                under one HELP/TYPE block in the exposition and take
                different merge rules in the fleet plane.

  metric-help   every literal zkp2p_* family must carry a METRIC_HELP
                entry in utils/metrics.py (the exposition emits a HELP
                block per family — an unknown family gets boilerplate),
                and every METRIC_HELP key must still be registered
                somewhere (stale help rots into documentation of
                metrics that no longer exist).  The templated
                `zkp2p_native_<field>` gauges are exempt: their help is
                generated from the slot name at exposition time.

Dynamic names (f-strings) are checked for the zkp2p_ prefix on their
literal head and skipped otherwise — the registry cannot know the
interpolated tail statically.
"""

from __future__ import annotations

import ast
import re
from typing import Dict, List, Set, Tuple

from .core import Finding, Tree, str_const

_NAME_RE = re.compile(r"^zkp2p_[a-z0-9_]+$")
_RESERVED = ("_bucket", "_sum", "_count", "_info")
_KINDS = {"counter", "gauge", "histogram"}
METRICS_MOD = "zkp2p_tpu/utils/metrics.py"


def _registrations(tree: Tree):
    """Yield (relpath, line, kind, name_node) for every instrument call."""
    for sf in tree.py_files():
        if sf.tree is None:
            continue
        for node in ast.walk(sf.tree):
            if not isinstance(node, ast.Call) or not isinstance(node.func, ast.Attribute):
                continue
            kind = node.func.attr
            if kind not in _KINDS or not node.args:
                continue
            yield sf.relpath, node.lineno, kind, node.args[0]


def parse_metric_help(tree: Tree) -> Set[str]:
    sf = tree.files.get(METRICS_MOD)
    if sf is None or sf.tree is None:
        return set()
    for node in ast.walk(sf.tree):
        if isinstance(node, ast.Assign) and len(node.targets) == 1:
            t = node.targets[0]
            if isinstance(t, ast.Name) and t.id == "METRIC_HELP" and isinstance(node.value, ast.Dict):
                return {s for s in (str_const(k) for k in node.value.keys) if s}
    return set()


def check(tree: Tree) -> List[Finding]:
    findings: List[Finding] = []
    kinds_seen: Dict[str, Tuple[str, str, int]] = {}  # name -> (kind, path, line)
    literal_names: Set[str] = set()
    help_keys = parse_metric_help(tree)

    for relpath, line, kind, name_node in _registrations(tree):
        name = str_const(name_node)
        if name is None:
            # dynamic family: enforce the prefix on the literal head only
            if isinstance(name_node, ast.JoinedStr) and name_node.values:
                head = str_const(name_node.values[0]) or ""
                if not head.startswith("zkp2p_"):
                    findings.append(Finding(
                        "metric-name", relpath, line,
                        "dynamic metric family does not start with the zkp2p_ "
                        "namespace prefix",
                    ))
            continue
        literal_names.add(name)
        if not _NAME_RE.match(name):
            findings.append(Finding(
                "metric-name", relpath, line,
                f"family {name!r} must match ^zkp2p_[a-z0-9_]+$ (one namespace, "
                "Prometheus-safe charset)",
            ))
        if kind == "counter" and not name.endswith("_total"):
            findings.append(Finding(
                "metric-name", relpath, line,
                f"counter {name!r} must end `_total` — the fleet merge and every "
                "Prometheus rate() consumer key on the suffix",
            ))
        if kind != "counter" and name.endswith("_total"):
            findings.append(Finding(
                "metric-name", relpath, line,
                f"{kind} {name!r} must not end `_total`: the fleet plane SUMS "
                "_total families across workers",
            ))
        if any(name.endswith(s) for s in _RESERVED):
            findings.append(Finding(
                "metric-name", relpath, line,
                f"family {name!r} ends in an exposition-reserved suffix "
                f"({'/'.join(_RESERVED)}) — histogram serialization would collide",
            ))
        prev = kinds_seen.get(name)
        if prev is None:
            kinds_seen[name] = (kind, relpath, line)
        elif prev[0] != kind:
            findings.append(Finding(
                "metric-kind", relpath, line,
                f"family {name!r} registered as {kind} here but as {prev[0]} at "
                f"{prev[1]}:{prev[2]} — one family, one kind",
            ))
        if (
            help_keys
            and name not in help_keys
            and not name.startswith("zkp2p_native_")
        ):
            findings.append(Finding(
                "metric-help", relpath, line,
                f"family {name!r} has no METRIC_HELP entry in utils/metrics.py — "
                "the exposition would emit boilerplate HELP for it",
            ))

    # stale help keys (reverse direction)
    sf = tree.files.get(METRICS_MOD)
    if sf is not None and help_keys:
        for key in sorted(help_keys - literal_names):
            line = next(
                (i for i, ln in enumerate(sf.lines, 1) if f'"{key}"' in ln), 1
            )
            findings.append(Finding(
                "metric-help", METRICS_MOD, line,
                f"METRIC_HELP documents {key!r} but nothing registers it — stale "
                "help describes a metric that no longer exists",
            ))
    return findings
