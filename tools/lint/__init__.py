"""zkp2p-lint: the repo's invariants, enforced statically.

Entry points:
    python -m tools.lint          (from the repo root; what `make lint` runs)
    python -m zkp2p_tpu lint      (the CLI wrapper)

See docs/STATIC_ANALYSIS.md for the rule table — every rule encodes a
bug this repo has already shipped (or nearly shipped) once.
"""

from __future__ import annotations

import argparse
import sys
import time
from typing import List, Optional

from .core import REPO, Finding, Tree, run_checkers

__all__ = ["main", "run_lint", "Tree", "Finding", "run_checkers"]


def run_lint(root: str = REPO, rules: Optional[List[str]] = None) -> List[Finding]:
    return run_checkers(Tree(root), rules=rules)


def main(argv: Optional[List[str]] = None) -> int:
    ap = argparse.ArgumentParser(
        prog="zkp2p-tpu lint",
        description="static invariant checks (knobs, gates, ABI, metrics, "
        "durability, clocks, pyflakes-tier) — docs/STATIC_ANALYSIS.md",
    )
    ap.add_argument("--root", default=REPO, help="tree to lint (default: this repo)")
    ap.add_argument("--rules", default="", help="comma-separated rule filter")
    ap.add_argument("--json", action="store_true", help="machine-readable findings")
    ap.add_argument(
        "--circuits", nargs="?", const="all", default=None, metavar="IDS",
        help="run the R1CS soundness audit on registered circuits instead "
        "of the source rules (comma-separated ids, default all tier-1 "
        "circuits) — the registry admission gate, docs/STATIC_ANALYSIS.md",
    )
    ap.add_argument("--flagship", action="store_true",
                    help="with --circuits: include the 4.9M-wire flagship")
    ap.add_argument("--no-cache", action="store_true",
                    help="with --circuits: ignore cached audit reports")
    args = ap.parse_args(argv)

    if args.circuits is not None:
        if args.rules or args.root != REPO:
            ap.error("--circuits is a separate tier: --rules/--root do not apply")
        from .circuit import run_circuit_audit

        names = None if args.circuits == "all" else [
            n.strip() for n in args.circuits.split(",") if n.strip()
        ]
        return run_circuit_audit(
            names=names,
            include_flagship=args.flagship,
            use_cache=not args.no_cache,
            as_json=args.json,
        )

    t0 = time.perf_counter()
    rules = [r.strip() for r in args.rules.split(",") if r.strip()] or None
    tree = Tree(args.root)
    findings = run_checkers(tree, rules=rules)
    dt = time.perf_counter() - t0
    if args.json:
        import json

        print(json.dumps([f.__dict__ for f in findings], indent=1))
    else:
        for f in findings:
            print(f)
        status = "clean" if not findings else f"{len(findings)} finding(s)"
        print(f"zkp2p-lint: {status} across {len(tree.files)} files in {dt:.2f}s", file=sys.stderr)
    return 1 if findings else 0
