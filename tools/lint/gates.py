"""Gate discipline: a branch on an armable knob must record its arm.

Rule (historical bug it encodes — docs/STATIC_ANALYSIS.md):

  gate-arm   any function in zkp2p_tpu/ that references an ARMABLE
             config attribute (cfg.msm_glv, load_config().ntt_pool, ...)
             must also call audit.record_arm — otherwise a knob flip
             changes the executed code path while the execution digest
             stays identical.  That is the round-2 silent-disarm bug
             class: `default_backend() == "tpu"` gates quietly armed
             "off" for three rounds with nothing in any artifact to
             show it.  Two digest-equal runs must be PROVABLY the same
             code path, so every armable consultation records itself
             (directly, or by being resolved inside a *_arm/_use_*
             resolver that does).

Module-level snapshot constants (`MSM_GLV = _CFG.msm_glv` in
groth16_tpu) are exempt: their jit-time consumers resolve through
record_arm-bearing resolver functions, and the constant assignment
itself takes no branch.
"""

from __future__ import annotations

import ast
from typing import List

from .core import Finding, Tree, call_name, functions_of, parse_config_registry

_RECORDERS = ("record_arm", "_record_arm")


def check(tree: Tree) -> List[Finding]:
    _knobs, armable = parse_config_registry(tree)
    armable_set = set(armable)
    findings: List[Finding] = []
    if not armable_set:
        return findings
    for sf in tree.py_files():
        if not sf.relpath.startswith("zkp2p_tpu/") or sf.tree is None:
            continue
        if sf.relpath.endswith(("utils/config.py", "utils/audit.py")):
            # config defines the knobs; audit's doctor COMPARES config
            # to recorded arms (mis-arm warnings) without taking a path
            continue
        for fn in functions_of(sf.tree):
            refs = []
            records = False
            for node in ast.walk(fn):
                if isinstance(node, ast.Attribute) and node.attr in armable_set and isinstance(node.ctx, ast.Load):
                    refs.append(node)
                elif isinstance(node, ast.Call) and call_name(node).split(".")[-1] in _RECORDERS:
                    records = True
            if refs and not records:
                for r in refs:
                    findings.append(Finding(
                        "gate-arm", sf.relpath, r.lineno,
                        f"function {fn.name}() branches on armable knob .{r.attr} "
                        "without a record_arm call — the arm is invisible to the "
                        "execution digest (round-2 silent-disarm class)",
                    ))
    return findings
