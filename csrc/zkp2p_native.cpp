// Native BN254 host library: Montgomery field arithmetic + G1/G2 fixed-base.
//
// The runtime role rapidsnark's x86-asm field library plays in the
// reference (SURVEY.md §2.2): the host-side hot loops — trusted-setup
// query-point generation, witness-side bignum math — run here instead of
// Python bigints (~400x).  The TPU compute path stays JAX/XLA; this is
// the CPU runtime around it.  Exposed as extern "C" for ctypes
// (zkp2p_tpu.native.lib); every entry point is batch-oriented.
//
// Field elements: 4 x 64-bit little-endian limbs, Montgomery form with
// R = 2^256.  unsigned __int128 provides the 64x64->128 multiply.

#include <algorithm>
#include <atomic>
#include <condition_variable>
#include <cstdint>
#include <array>
#include <deque>
#include <functional>
#include <map>
#include <memory>
#include <mutex>
#include <utility>
#include <cassert>
#include <cstdlib>
#include <cstring>
#include <thread>
#include <unistd.h>
#include <vector>

// ---------------------------------------------------------------------------
// Env-gated MSM phase profile (ZKP2P_MSM_PROF=1, a registered debug knob):
// per-process accumulated wall ns for the G1 Pippenger phases of the 52-bit
// tier, printed to stderr by zkp2p_msm_prof_dump() (and readable any time via
// the exported counters) so the fill/schedule/reduction balance can be read
// off a real prove instead of modeled (no perf(1) on the driver box).
#include <chrono>
#include <cstdio>
static std::atomic<long long> g_prof_fill_ns(0), g_prof_apply_ns(0),
    g_prof_suffix_ns(0), g_prof_bailfill_ns(0);
static bool msm_prof_enabled() {
  static int v = -1;
  if (v < 0) {
    const char *e = getenv("ZKP2P_MSM_PROF");
    v = (e && e[0] == '1') ? 1 : 0;
  }
  return v == 1;
}
static inline long long prof_now_ns() {
  return std::chrono::duration_cast<std::chrono::nanoseconds>(
             std::chrono::steady_clock::now().time_since_epoch())
      .count();
}

// ---------------------------------------------------------------------------
// Always-on runtime stats (zkp2p_stats_snapshot / zkp2p_stats_reset): a
// lock-free block of relaxed atomics the Python side reads as one array.
// Unlike the ZKP2P_MSM_PROF counters above (env-gated, stderr-oriented)
// these are ON in every build and every run — the cost budget is one or
// two clock reads per CHUNK/WINDOW/CALL, never per point (the rare
// doubling/cancellation lanes are tallied locally per window and flushed
// with one atomic add), so the measured overhead on the MSM path stays
// under the 2% instrumentation budget.
//
// Slot order is the ABI the ctypes bridge mirrors (native/lib.py
// STATS_FIELDS) — append only, never reorder.
enum StatSlot {
  ST_MSM_G1_CALLS = 0,        // plain G1 Pippenger driver entries
  ST_MSM_G2_CALLS,            // G2 driver entries
  ST_MSM_GLV_CALLS,           // GLV G1 driver entries
  ST_MSM_BATCH_AFFINE_CALLS,  // driver entries with the batch-affine arm on
  ST_MSM_POINTS,              // scalar/point pairs handed to the drivers
  ST_MSM_WALL_NS,             // total wall ns inside the MSM drivers
  ST_MSM_FILL_NS,             // batch-affine bucket fill (incl. apply)
  ST_MSM_APPLY_NS,            // batched affine apply alone
  ST_MSM_SUFFIX_NS,           // window suffix reductions (serial + vector)
  ST_MSM_BAILFILL_NS,         // conflict-bail Jacobian refill
  ST_MSM_WINDOW_LAST,         // window size c of the most recent MSM (gauge)
  ST_MSM_DBL_LANES,           // batch-round P+P doubling lane hits
  ST_MSM_CANCEL_LANES,        // batch-round P+(-P) cancellation hits
  ST_MSM_DEFER_HITS,          // same-chunk bucket conflicts deferred a pass
  ST_POOL_JOBS,               // parallel regions run through the WorkPool
  ST_POOL_TASKS,              // region indices executed by workers
  ST_POOL_WAIT_NS,            // enqueue -> FIRST task claim, summed per job
  ST_POOL_RUN_NS,             // task fn execution ns, summed per task
  ST_POOL_DEPTH_PEAK,         // max queued-region depth observed (gauge)
  ST_POOL_WORKERS,            // current worker-thread count (gauge)
  ST_MSM_MULTI_CALLS,         // multi-column G1 driver entries (plain + GLV)
  ST_MSM_MULTI_COLS,          // scalar columns summed over multi calls
  ST_MSM_MULTI_COLS_LAST,     // S of the most recent multi call (gauge)
  ST_MSM_MULTI_PREP_NS,       // per-column classify/ones/digit prep, summed
  ST_MSM_FIXED_CALLS,         // fixed-base precomputed-table driver entries
  ST_MSM_FIXED_PREP_NS,       // fixed-tier digit recode/scatter, summed
  ST_PRECOMP_BUILD_NS,        // g1_precomp_build wall ns, summed
  ST_PRECOMP_TABLE_BYTES,     // mont256 table bytes built this process, summed
  ST_MATVEC_NS,               // wall ns inside fr_matvec + fr_matvec_seg
  ST_MATVEC_SEG_CALLS,        // segmented-plan matvec driver entries
  ST_NTT_STAGE_NS,            // wall ns inside the vectorized NTT stage pipeline
  ST_MSM_INFLIGHT,            // MSM driver entries currently executing (gauge)
  ST_COUNT
};
static std::atomic<long long> g_stats[ST_COUNT];
static inline void stat_add(int slot, long long v) {
  g_stats[slot].fetch_add(v, std::memory_order_relaxed);
}
static inline void stat_set(int slot, long long v) {
  g_stats[slot].store(v, std::memory_order_relaxed);
}
static inline void stat_max(int slot, long long v) {
  long long cur = g_stats[slot].load(std::memory_order_relaxed);
  while (v > cur &&
         !g_stats[slot].compare_exchange_weak(cur, v, std::memory_order_relaxed)) {
  }
}
// Scoped in-flight gauge: +1 on driver entry, -1 on EVERY exit path
// (RAII covers early returns).  An external sampler reading the stats
// block mid-call can tell "an MSM is executing right now" apart from
// "the wall counters moved between my two reads".
struct InflightStat {
  int slot;
  explicit InflightStat(int s) : slot(s) { stat_add(slot, 1); }
  ~InflightStat() { stat_add(slot, -1); }
};

extern "C" {
int zkp2p_stats_count(void) { return ST_COUNT; }
void zkp2p_stats_snapshot(long long *out) {
  for (int i = 0; i < ST_COUNT; ++i) out[i] = g_stats[i].load(std::memory_order_relaxed);
}
void zkp2p_stats_reset(void) {
  for (int i = 0; i < ST_COUNT; ++i) g_stats[i].store(0, std::memory_order_relaxed);
}
}  // extern "C"

// Batch-affine Pippenger bucket accumulation (ZKP2P_MSM_BATCH_AFFINE,
// default ON; off only on a leading '0', the ZKP2P_NATIVE_IFMA rule).
// Gates the affine-bucket fill tiers of the G1/G2 MSMs — off routes
// every window through the plain mixed-Jacobian fill, which is the
// honest A/B arm for what the shared-inversion affine adds buy.
// Deliberately NOT cached: re-read once per MSM (and per G2 window), so
// a single process can diff both arms (tests monkeypatch the env).
static bool batch_affine_enabled() {
  const char *e = getenv("ZKP2P_MSM_BATCH_AFFINE");
  return !(e && e[0] == '0');
}

// Apply-chain interleave (ZKP2P_MSM_INTERLEAVE, default ON; same '0'
// rule): two levers under one knob, both attacking the chunk apply's
// stalls.  (1) The batched-affine chunk apply splits its blocks into
// TWO independent prefix/suffix chains issued through one register
// schedule (mont52_mul8x2), so the second chain's muls fill the IFMA
// latency bubbles of the first.  (2) The gather/schedule loops issue
// software prefetches down the already-known (bucket, point) index
// streams — the apply's phase profile shows the random-index Aff52
// gathers (DRAM-latency, hardware-prefetch-blind) cost more than the
// mul chains themselves.  Off = the original schedule — the byte-parity
// A/B arm (outputs are canonically folded either way and prefetch never
// changes an architectural value, so neither lever can change a proof
// byte).  Fresh-read per chunk-apply call, like the batch-affine gate
// above.
static bool msm_interleave_enabled() {
  const char *e = getenv("ZKP2P_MSM_INTERLEAVE");
  return !(e && e[0] == '0');
}

// Radix-8 NTT stage fusion (ZKP2P_NTT_RADIX8, default OFF — set '1'
// to arm): the vectorized SoA stage pipeline fuses THREE radix-2
// stages per load/store pass (12 muls / 8 elements — the same mul
// count as the radix-4 arrangement, one memory pass instead of 1.5).
// Measured slightly SLOWER (0.95x at 2^19) on the 1-core IFMA box —
// the extra live registers spill and the muls are throughput-bound, so
// the saved memory pass does not pay there; the knob stays for wider
// hosts.  Off = the radix-4 stage-pair fusion — the byte-parity A/B
// arm (identical butterflies in a different pass grouping).
// Fresh-read per transform.
static bool ntt_radix8_enabled() {
  const char *e = getenv("ZKP2P_NTT_RADIX8");
  return e && e[0] == '1';
}

typedef unsigned __int128 u128;
typedef uint64_t u64;

// ---------------------------------------------------------------------------
// Persistent worker pool.  Every parallel region in this library (the
// Pippenger window sums, the 3-way h_ladder split) used to spawn-and-join
// its own std::thread vector per CALL — ~5 MSMs + 1 ladder per prove, each
// paying thread creation latency and a cold stack/TLB.  The pool spawns
// workers once (lazily, or via zkp2p_pool_init) and keeps them parked on a
// condition variable between regions.  Concurrency semantics are unchanged:
// ZKP2P_NATIVE_THREADS still bounds how many indices run at once (the pool
// grows to the largest n_threads any caller has asked for, never shrinks
// below it), and n_threads <= 1 keeps the exact serial caller-thread path.
//
// The pool is MPMC-safe: multiple Python threads may each submit a region
// (the prover's stage task-graph overlaps independent MSMs), and workers
// drain region index spaces FIFO.  Each region carries a WIDTH cap — at
// most `width` workers join its index space, so a caller's n_threads
// request bounds ITS region even when the pool has grown wider for some
// other caller.  pool_run() must not be called from a pool worker (no
// region in this library nests).
// Set inside worker_loop for the thread's lifetime: parallel regions
// must never be SUBMITTED from a pool worker (run() blocks the caller,
// and a worker blocked on a nested region is a deadlock waiting for the
// pool to shrink).  Helpers that can be reached both from Python threads
// and from pool workers (the NTT stage splitter under the knob-off
// 3-wide ladder) consult this and degrade to the inline serial path.
static thread_local bool g_pool_worker = false;

struct PoolJob {
  std::function<void(long)> fn;
  long n = 0;
  int width = 1;           // max workers on this job (caller's n_threads)
  int active = 0;          // workers currently on it (guarded by pool mu_)
  long long enqueue_ns = 0;  // stats: task wait = claim time - this
  std::atomic<long> next{0};
  std::atomic<long> done{0};
  std::mutex mu;
  std::condition_variable cv;
};

class WorkPool {
 public:
  ~WorkPool() { shutdown(); }

  // Grow to at least n workers (never shrinks: a one-off wide caller
  // leaves capacity parked, which is the point of persistence).
  void ensure(int n) {
    std::lock_guard<std::mutex> life(lifecycle_mu_);
    ensure_inner(n);
  }

  int size() {
    std::lock_guard<std::mutex> lk(mu_);
    return (int)workers_.size();
  }

  // Run fn(0..n-1) on at most `width` workers; blocks until every index
  // completed.  The caller thread does NOT execute indices itself —
  // n_threads keeps its historical meaning (worker count), and a
  // blocked caller is what lets overlapped submissions share one
  // bounded worker set.  lifecycle_mu_ brackets the ensure+enqueue pair
  // so a concurrent shutdown() either drains this job with the old
  // workers or sees it after respawn — never in between (a job enqueued
  // onto a pool mid-join would wait forever).
  void run(long n, std::function<void(long)> fn, int width) {
    if (n <= 0) return;
    auto job = std::make_shared<PoolJob>();
    job->fn = std::move(fn);
    job->n = n;
    job->width = width > 0 ? width : 1;
    job->enqueue_ns = prof_now_ns();
    stat_add(ST_POOL_JOBS, 1);
    {
      std::lock_guard<std::mutex> life(lifecycle_mu_);
      ensure_inner(1);  // a job on an empty pool would wait forever
      std::lock_guard<std::mutex> lk(mu_);
      jobs_.push_back(job);
      stat_max(ST_POOL_DEPTH_PEAK, (long long)jobs_.size());
    }
    cv_.notify_all();
    std::unique_lock<std::mutex> lk(job->mu);
    job->cv.wait(lk, [&] { return job->done.load() >= job->n; });
  }

  // Join all workers (draining queued jobs first).  The pool respawns
  // lazily on the next run()/ensure(), so shutdown is safe mid-process
  // (tests cycle it; services can drop the threads while idle).
  // lifecycle_mu_ serializes against ensure()/run(), closing the race
  // where a worker spawned during the join would exit immediately yet
  // linger in workers_, leaving later jobs waiting on a dead pool.
  void shutdown() {
    std::lock_guard<std::mutex> life(lifecycle_mu_);
    {
      std::lock_guard<std::mutex> lk(mu_);
      stop_ = true;
    }
    cv_.notify_all();
    std::vector<std::thread> ws;
    {
      std::lock_guard<std::mutex> lk(mu_);
      ws.swap(workers_);
    }
    for (auto &t : ws) t.join();
    std::lock_guard<std::mutex> lk(mu_);
    stop_ = false;
  }

 private:
  void ensure_inner(int n) {
    std::lock_guard<std::mutex> lk(mu_);
    while ((int)workers_.size() < n) workers_.emplace_back([this] { worker_loop(); });
    stat_set(ST_POOL_WORKERS, (long long)workers_.size());
  }

  // Under mu_: drop jobs whose index space is fully handed out (their
  // in-flight indices finish on the workers that claimed them; run()
  // waits on the done counter, not queue presence) and return the first
  // job with free indices AND head-room under its width cap.
  std::shared_ptr<PoolJob> runnable_locked() {
    for (auto it = jobs_.begin(); it != jobs_.end();) {
      if ((*it)->next.load() >= (*it)->n) {
        it = jobs_.erase(it);
        continue;
      }
      if ((*it)->active < (*it)->width) return *it;
      ++it;
    }
    return nullptr;
  }

  void worker_loop() {
    g_pool_worker = true;
    for (;;) {
      std::shared_ptr<PoolJob> job;
      {
        std::unique_lock<std::mutex> lk(mu_);
        cv_.wait(lk, [&] { return stop_ || runnable_locked() != nullptr; });
        job = runnable_locked();
        if (!job) return;  // stop_ set and nothing left to join
        ++job->active;
      }
      long i;
      while ((i = job->next.fetch_add(1)) < job->n) {
        long long t0 = prof_now_ns();
        // queueing latency per JOB: enqueue -> first task claim (index 0
        // is the chronologically first fetch_add).  Summing it per TASK
        // would count predecessors' run time as "wait" and fabricate
        // contention on an idle pool.
        if (i == 0) stat_add(ST_POOL_WAIT_NS, t0 - job->enqueue_ns);
        job->fn(i);
        stat_add(ST_POOL_RUN_NS, prof_now_ns() - t0);
        stat_add(ST_POOL_TASKS, 1);
        if (job->done.fetch_add(1) + 1 == job->n) {
          std::lock_guard<std::mutex> jlk(job->mu);
          job->cv.notify_all();
        }
      }
      std::lock_guard<std::mutex> lk(mu_);
      --job->active;  // width slot back (job is exhausted, not re-joined)
    }
  }

  std::mutex mu_;
  std::mutex lifecycle_mu_;  // serializes shutdown vs ensure/enqueue
  std::condition_variable cv_;
  std::vector<std::thread> workers_;
  std::deque<std::shared_ptr<PoolJob>> jobs_;
  bool stop_ = false;
};

static WorkPool &work_pool() {
  static WorkPool pool;  // joined by the static destructor at exit
  return pool;
}

// Split [0, n) into contiguous ranges across the pool and run
// fn(lo, hi) on each, blocking until all complete.  Falls back to one
// inline fn(0, n) when the caller pinned a single thread, the range is
// below `grain` (per-chunk minimum — tiny jobs cost more in pool
// handoff than they save), or the caller IS a pool worker (regions
// never nest — see g_pool_worker).  Used by the NTT stage splitter and
// the segmented matvec, where every range is independent by
// construction.
static void pool_parallel_ranges(long n, long grain, int n_threads,
                                 const std::function<void(long, long)> &fn) {
  if (n <= 0) return;
  long max_chunks = grain > 0 ? (n + grain - 1) / grain : n;
  if (n_threads <= 1 || g_pool_worker || max_chunks <= 1) {
    fn(0, n);
    return;
  }
  // a few chunks per worker smooths uneven ranges without drowning the
  // queue in micro-tasks
  long nchunk = (long)n_threads * 4;
  if (nchunk > max_chunks) nchunk = max_chunks;
  long per = (n + nchunk - 1) / nchunk;
  work_pool().ensure(n_threads);
  work_pool().run(
      nchunk,
      [&](long ci) {
        long lo = ci * per;
        long hi = lo + per < n ? lo + per : n;
        if (lo < hi) fn(lo, hi);
      },
      n_threads);
}

// Pool-parallel NTT stage splitting (ZKP2P_NTT_POOL, default ON; off
// only on a leading '0', the ZKP2P_NATIVE_IFMA rule).  Gates both the
// per-stage butterfly-block fan-out inside the vectorized NTT and the
// fused-ladder pipeline in fr_h_ladder; off restores the 3-wide
// whole-transform split — the honest A/B arm.  Fresh-read per call so
// one process can diff both arms (tests monkeypatch the env).
static bool ntt_pool_enabled() {
  const char *e = getenv("ZKP2P_NTT_POOL");
  return !(e && e[0] == '0');
}

// The env-resolved default worker count (ZKP2P_NATIVE_THREADS, else the
// core count) — the same rule fr_h_ladder applied per call before.
static int pool_default_threads() {
  const char *tenv = getenv("ZKP2P_NATIVE_THREADS");
  int nt = tenv ? atoi(tenv) : (int)std::thread::hardware_concurrency();
  return nt > 0 ? nt : 1;
}

extern "C" {
// Explicit lifecycle (optional — every parallel entry point lazily
// ensures capacity): init pre-spawns n workers (n <= 0 resolves
// ZKP2P_NATIVE_THREADS / core count), shutdown joins them all.
void zkp2p_pool_init(int n_threads) {
  work_pool().ensure(n_threads > 0 ? n_threads : pool_default_threads());
}
void zkp2p_pool_shutdown(void) { work_pool().shutdown(); }
int zkp2p_pool_size(void) { return work_pool().size(); }
}  // extern "C"

// BN254 base field p and scalar field r moduli (little-endian limbs).
static const u64 P[4] = {0x3c208c16d87cfd47ULL, 0x97816a916871ca8dULL,
                         0xb85045b68181585dULL, 0x30644e72e131a029ULL};
static const u64 PINV = 0x87d20782e4866389ULL;  // -p^-1 mod 2^64
// R^2 mod p
static const u64 R2P[4] = {0xf32cfc5b538afa89ULL, 0xb5e71911d44501fbULL,
                           0x47ab1eff0a417ff6ULL, 0x06d89f71cab8351fULL};

struct Fp {
  u64 v[4];
};

static inline bool geq(const u64 a[4], const u64 b[4]) {
  for (int i = 3; i >= 0; --i) {
    if (a[i] != b[i]) return a[i] > b[i];
  }
  return true;
}

static inline void sub_nored(u64 out[4], const u64 a[4], const u64 b[4]) {
  u128 borrow = 0;
  for (int i = 0; i < 4; ++i) {
    u128 d = (u128)a[i] - b[i] - borrow;
    out[i] = (u64)d;
    borrow = (d >> 64) & 1;
  }
}

static inline void add_mod(u64 out[4], const u64 a[4], const u64 b[4]) {
  u64 t[5] = {0, 0, 0, 0, 0};
  u128 carry = 0;
  for (int i = 0; i < 4; ++i) {
    u128 s = (u128)a[i] + b[i] + carry;
    t[i] = (u64)s;
    carry = s >> 64;
  }
  t[4] = (u64)carry;
  if (t[4] || geq(t, P)) {
    sub_nored(out, t, P);
  } else {
    memcpy(out, t, 32);
  }
}

static inline void sub_mod(u64 out[4], const u64 a[4], const u64 b[4]) {
  if (geq(a, b)) {
    sub_nored(out, a, b);
  } else {
    u64 t[4];
    sub_nored(t, b, a);
    sub_nored(out, P, t);
  }
}

// CIOS Montgomery multiplication.
static void mont_mul(u64 out[4], const u64 a[4], const u64 b[4]) {
  u64 t[6] = {0, 0, 0, 0, 0, 0};
  for (int i = 0; i < 4; ++i) {
    u128 carry = 0;
    for (int j = 0; j < 4; ++j) {
      u128 s = (u128)t[j] + (u128)a[i] * b[j] + carry;
      t[j] = (u64)s;
      carry = s >> 64;
    }
    u128 s = (u128)t[4] + carry;
    t[4] = (u64)s;
    t[5] = (u64)(s >> 64);

    u64 m = t[0] * PINV;
    carry = ((u128)t[0] + (u128)m * P[0]) >> 64;
    for (int j = 1; j < 4; ++j) {
      u128 s2 = (u128)t[j] + (u128)m * P[j] + carry;
      t[j - 1] = (u64)s2;
      carry = s2 >> 64;
    }
    u128 s3 = (u128)t[4] + carry;
    t[3] = (u64)s3;
    t[4] = t[5] + (u64)(s3 >> 64);
  }
  if (t[4] || geq(t, P)) {
    sub_nored(out, t, P);
  } else {
    memcpy(out, t, 32);
  }
}

static inline void mont_sqr(u64 out[4], const u64 a[4]) { mont_mul(out, a, a); }

static const u64 ZERO[4] = {0, 0, 0, 0};

struct G1Jac {
  u64 X[4], Y[4], Z[4];
};
struct G1Aff {
  u64 x[4], y[4];  // Montgomery; (0,0) = infinity
};

static inline bool is_zero4(const u64 a[4]) {
  return !(a[0] | a[1] | a[2] | a[3]);
}

static void jac_double(G1Jac &r, const G1Jac &p) {
  if (is_zero4(p.Z)) {
    r = p;
    return;
  }
  u64 A[4], B[4], C[4], D[4], E[4], F[4], t[4], t2[4];
  mont_sqr(A, p.X);
  mont_sqr(B, p.Y);
  mont_sqr(C, B);
  add_mod(t, p.X, B);
  mont_sqr(t, t);
  sub_mod(t, t, A);
  sub_mod(t, t, C);
  add_mod(D, t, t);
  add_mod(E, A, A);
  add_mod(E, E, A);
  mont_sqr(F, E);
  // X3 = F - 2D
  add_mod(t, D, D);
  sub_mod(r.X, F, t);
  // Y3 = E(D - X3) - 8C
  sub_mod(t, D, r.X);
  mont_mul(t, E, t);
  add_mod(t2, C, C);
  add_mod(t2, t2, t2);
  add_mod(t2, t2, t2);
  u64 y3[4];
  sub_mod(y3, t, t2);
  // Z3 = 2 Y Z
  mont_mul(t, p.Y, p.Z);
  add_mod(r.Z, t, t);
  memcpy(r.Y, y3, 32);
}

// r = p + (x2, y2) affine (Montgomery), standard madd-2007-bl shape.
static void jac_add_mixed(G1Jac &r, const G1Jac &p, const u64 x2[4], const u64 y2[4]) {
  if (is_zero4(x2) && is_zero4(y2)) {
    r = p;
    return;
  }
  if (is_zero4(p.Z)) {
    memcpy(r.X, x2, 32);
    memcpy(r.Y, y2, 32);
    // Z = 1 in Montgomery form = R mod p
    static const u64 ONE_M[4] = {0xd35d438dc58f0d9dULL, 0x0a78eb28f5c70b3dULL,
                                 0x666ea36f7879462cULL, 0x0e0a77c19a07df2fULL};
    memcpy(r.Z, ONE_M, 32);
    return;
  }
  u64 Z1Z1[4], U2[4], S2[4], H[4], HH[4], HHH[4], V[4], Rr[4], t[4];
  mont_sqr(Z1Z1, p.Z);
  mont_mul(U2, x2, Z1Z1);
  mont_mul(t, y2, p.Z);
  mont_mul(S2, t, Z1Z1);
  sub_mod(H, U2, p.X);
  sub_mod(Rr, S2, p.Y);
  if (is_zero4(H)) {
    if (is_zero4(Rr)) {
      jac_double(r, p);
      return;
    }
    memset(&r, 0, sizeof(r));  // infinity
    return;
  }
  mont_sqr(HH, H);
  mont_mul(HHH, H, HH);
  mont_mul(V, p.X, HH);
  // X3 = Rr^2 - HHH - 2V
  mont_sqr(t, Rr);
  sub_mod(t, t, HHH);
  u64 v2[4];
  add_mod(v2, V, V);
  sub_mod(r.X, t, v2);
  // Y3 = Rr (V - X3) - Y1 HHH
  sub_mod(t, V, r.X);
  mont_mul(t, Rr, t);
  u64 t2[4];
  mont_mul(t2, p.Y, HHH);
  sub_mod(r.Y, t, t2);
  // Z3 = Z1 H
  u64 z3[4];
  mont_mul(z3, p.Z, H);
  memcpy(r.Z, z3, 32);
}

// Full Jacobian + Jacobian G1 add (defined with the Pippenger MSM below;
// also the accumulate step of the fixed-base batches).
static void g1_add_jac(G1Jac &acc, const G1Jac &e);

// Fermat inverse via exponentiation (p - 2); only used once per output.
static void mont_inv(u64 out[4], const u64 a[4]) {
  // exponent p-2, big-endian bit scan
  u64 e[4];
  u64 two[4] = {2, 0, 0, 0};
  sub_nored(e, P, two);
  // out = 1 (Montgomery)
  static const u64 ONE_M[4] = {0xd35d438dc58f0d9dULL, 0x0a78eb28f5c70b3dULL,
                               0x666ea36f7879462cULL, 0x0e0a77c19a07df2fULL};
  u64 acc[4];
  memcpy(acc, ONE_M, 32);
  for (int i = 255; i >= 0; --i) {
    mont_sqr(acc, acc);
    if ((e[i / 64] >> (i % 64)) & 1) mont_mul(acc, acc, a);
  }
  memcpy(out, acc, 32);
}

extern "C" {

// Dump + reset the ZKP2P_MSM_PROF counters (ns): fill total (incl. apply),
// batched apply alone, suffix reduction.  No-op zeros when profiling is off.
// Counters are summed across worker threads — on an n_threads > 1 run the
// fill total overstates wall contribution by up to the thread count, so
// phase RATIOS are only comparable single-threaded (the driver box).
void zkp2p_msm_prof_dump(long long out4[4]) {
  out4[0] = g_prof_fill_ns.exchange(0);
  out4[1] = g_prof_apply_ns.exchange(0);
  out4[2] = g_prof_suffix_ns.exchange(0);
  out4[3] = g_prof_bailfill_ns.exchange(0);
}

// std -> Montgomery and back (batch), for the Python bridge.
void fp_to_mont(const u64 *in, u64 *out, int n) {
  for (int i = 0; i < n; ++i) mont_mul(out + 4 * i, in + 4 * i, R2P);
}
void fp_from_mont(const u64 *in, u64 *out, int n) {
  static const u64 ONE[4] = {1, 0, 0, 0};
  for (int i = 0; i < n; ++i) mont_mul(out + 4 * i, in + 4 * i, ONE);
}

// Fixed-base batch scalar-mul over G1.
//   base: affine (x, y) standard form; scalars: 4-limb standard form;
//   out: n affine points, standard form, (0,0) for infinity.
// Window-8 table built per call (n is large in setup, so amortised).
void g1_fixed_base_batch(const u64 *base_xy, const u64 *scalars, int n, u64 *out_xy) {
  // Build table[32][256] affine-in-Jacobian: keep Jacobian to skip inversions.
  // Heap per call: ctypes releases the GIL, so a function-local static
  // would be shared (and corrupted) by concurrent callers (r3 advisor).
  G1Jac(*table)[256] = new G1Jac[32][256];
  u64 bx[4], by[4];
  fp_to_mont(base_xy, bx, 1);
  fp_to_mont(base_xy + 4, by, 1);

  G1Jac wbase;
  memcpy(wbase.X, bx, 32);
  memcpy(wbase.Y, by, 32);
  static const u64 ONE_M[4] = {0xd35d438dc58f0d9dULL, 0x0a78eb28f5c70b3dULL,
                               0x666ea36f7879462cULL, 0x0e0a77c19a07df2fULL};
  memcpy(wbase.Z, ONE_M, 32);

  for (int w = 0; w < 32; ++w) {
    memset(&table[w][0], 0, sizeof(G1Jac));
    // normalize wbase to affine for mixed adds: one inversion per window
    u64 zi[4], zi2[4], zi3[4], ax[4], ay[4];
    mont_inv(zi, wbase.Z);
    mont_sqr(zi2, zi);
    mont_mul(zi3, zi2, zi);
    mont_mul(ax, wbase.X, zi2);
    mont_mul(ay, wbase.Y, zi3);
    for (int d = 1; d < 256; ++d) {
      jac_add_mixed(table[w][d], table[w][d - 1], ax, ay);
    }
    for (int k = 0; k < 8; ++k) jac_double(wbase, wbase);
  }

  for (int i = 0; i < n; ++i) {
    const u64 *s = scalars + 4 * i;
    G1Jac acc;
    memset(&acc, 0, sizeof(acc));
    for (int w = 0; w < 32; ++w) {
      int d = (int)((s[w / 8] >> ((w % 8) * 8)) & 0xff);
      if (!d) continue;
      g1_add_jac(acc, table[w][d]);
    }
    u64 *o = out_xy + 8 * i;
    if (is_zero4(acc.Z)) {
      memset(o, 0, 64);
      continue;
    }
    u64 zi[4], zi2[4], zi3[4], mx[4], my[4];
    mont_inv(zi, acc.Z);
    mont_sqr(zi2, zi);
    mont_mul(zi3, zi2, zi);
    mont_mul(mx, acc.X, zi2);
    mont_mul(my, acc.Y, zi3);
    fp_from_mont(mx, o, 1);
    fp_from_mont(my, o + 4, 1);
  }
  delete[] table;
}

// Self-test hook: c = a*b mod p (standard form in/out).
void fp_mul_std(const u64 *a, const u64 *b, u64 *c) {
  u64 am[4], bm[4], cm[4];
  fp_to_mont(a, am, 1);
  fp_to_mont(b, bm, 1);
  mont_mul(cm, am, bm);
  fp_from_mont(cm, c, 1);
}

}  // extern "C"

// ---------------------------------------------------------------- Fq2 / G2
//
// Fq2 = Fq[u]/(u^2 + 1); G2 is the twist curve over Fq2.  Needed for the
// b2_query of trusted setup (one G2 fixed-base mul per wire — at venmo
// scale that is millions of muls, unreachable for Python bigints).

struct Fp2 {
  u64 c0[4], c1[4];
};

static inline void fp2_add(Fp2 &r, const Fp2 &a, const Fp2 &b) {
  add_mod(r.c0, a.c0, b.c0);
  add_mod(r.c1, a.c1, b.c1);
}
static inline void fp2_sub(Fp2 &r, const Fp2 &a, const Fp2 &b) {
  sub_mod(r.c0, a.c0, b.c0);
  sub_mod(r.c1, a.c1, b.c1);
}
static void fp2_mul(Fp2 &r, const Fp2 &a, const Fp2 &b) {
  // Karatsuba: v0 = a0 b0, v1 = a1 b1; c0 = v0 - v1; c1 = (a0+a1)(b0+b1) - v0 - v1
  u64 v0[4], v1[4], s[4], t[4], u[4];
  mont_mul(v0, a.c0, b.c0);
  mont_mul(v1, a.c1, b.c1);
  add_mod(s, a.c0, a.c1);
  add_mod(t, b.c0, b.c1);
  mont_mul(u, s, t);
  sub_mod(r.c0, v0, v1);
  sub_mod(u, u, v0);
  sub_mod(r.c1, u, v1);
}
static inline void fp2_sqr(Fp2 &r, const Fp2 &a) { fp2_mul(r, a, a); }
static inline bool fp2_is_zero(const Fp2 &a) {
  return is_zero4(a.c0) && is_zero4(a.c1);
}

struct G2Jac {
  Fp2 X, Y, Z;
};

static void g2_double(G2Jac &r, const G2Jac &p) {
  if (fp2_is_zero(p.Z)) {
    r = p;
    return;
  }
  Fp2 A, B, C, D, E, F, t, t2;
  fp2_sqr(A, p.X);
  fp2_sqr(B, p.Y);
  fp2_sqr(C, B);
  fp2_add(t, p.X, B);
  fp2_sqr(t, t);
  fp2_sub(t, t, A);
  fp2_sub(t, t, C);
  fp2_add(D, t, t);
  fp2_add(E, A, A);
  fp2_add(E, E, A);
  fp2_sqr(F, E);
  fp2_add(t, D, D);
  fp2_sub(r.X, F, t);
  fp2_sub(t, D, r.X);
  fp2_mul(t, E, t);
  fp2_add(t2, C, C);
  fp2_add(t2, t2, t2);
  fp2_add(t2, t2, t2);
  Fp2 y3;
  fp2_sub(y3, t, t2);
  fp2_mul(t, p.Y, p.Z);
  fp2_add(r.Z, t, t);
  r.Y = y3;
}

static const u64 ONE_MONT[4] = {0xd35d438dc58f0d9dULL, 0x0a78eb28f5c70b3dULL,
                                0x666ea36f7879462cULL, 0x0e0a77c19a07df2fULL};

static void g2_add_mixed(G2Jac &r, const G2Jac &p, const Fp2 &x2, const Fp2 &y2) {
  if (fp2_is_zero(x2) && fp2_is_zero(y2)) {
    r = p;
    return;
  }
  if (fp2_is_zero(p.Z)) {
    r.X = x2;
    r.Y = y2;
    memcpy(r.Z.c0, ONE_MONT, 32);
    memset(r.Z.c1, 0, 32);
    return;
  }
  Fp2 Z1Z1, U2, S2, H, HH, HHH, V, Rr, t, t2;
  fp2_sqr(Z1Z1, p.Z);
  fp2_mul(U2, x2, Z1Z1);
  fp2_mul(t, y2, p.Z);
  fp2_mul(S2, t, Z1Z1);
  fp2_sub(H, U2, p.X);
  fp2_sub(Rr, S2, p.Y);
  if (fp2_is_zero(H)) {
    if (fp2_is_zero(Rr)) {
      g2_double(r, p);
      return;
    }
    memset(&r, 0, sizeof(r));
    return;
  }
  fp2_sqr(HH, H);
  fp2_mul(HHH, H, HH);
  fp2_mul(V, p.X, HH);
  fp2_sqr(t, Rr);
  fp2_sub(t, t, HHH);
  Fp2 v2;
  fp2_add(v2, V, V);
  fp2_sub(r.X, t, v2);
  fp2_sub(t, V, r.X);
  fp2_mul(t, Rr, t);
  fp2_mul(t2, p.Y, HHH);
  fp2_sub(r.Y, t, t2);
  Fp2 z3;
  fp2_mul(z3, p.Z, H);
  r.Z = z3;
}

static void g2_add(G2Jac &acc, const G2Jac &e) {
  if (fp2_is_zero(e.Z)) return;
  if (fp2_is_zero(acc.Z)) {
    acc = e;
    return;
  }
  Fp2 Z1Z1, Z2Z2, U1, U2, S1, S2, H, Rr, t;
  fp2_sqr(Z1Z1, acc.Z);
  fp2_sqr(Z2Z2, e.Z);
  fp2_mul(U1, acc.X, Z2Z2);
  fp2_mul(U2, e.X, Z1Z1);
  fp2_mul(t, acc.Y, e.Z);
  fp2_mul(S1, t, Z2Z2);
  fp2_mul(t, e.Y, acc.Z);
  fp2_mul(S2, t, Z1Z1);
  fp2_sub(H, U2, U1);
  fp2_sub(Rr, S2, S1);
  if (fp2_is_zero(H)) {
    if (fp2_is_zero(Rr)) {
      G2Jac d;
      g2_double(d, acc);
      acc = d;
      return;
    }
    memset(&acc, 0, sizeof(acc));
    return;
  }
  Fp2 HH, HHH, V, x3, y3, z3, t2, v2;
  fp2_sqr(HH, H);
  fp2_mul(HHH, H, HH);
  fp2_mul(V, U1, HH);
  fp2_sqr(t, Rr);
  fp2_sub(t, t, HHH);
  fp2_add(v2, V, V);
  fp2_sub(x3, t, v2);
  fp2_sub(t, V, x3);
  fp2_mul(t, Rr, t);
  fp2_mul(t2, S1, HHH);
  fp2_sub(y3, t, t2);
  fp2_mul(t, acc.Z, e.Z);
  fp2_mul(z3, t, H);
  acc.X = x3;
  acc.Y = y3;
  acc.Z = z3;
}

static void fp2_inv(Fp2 &r, const Fp2 &a) {
  // (a0 + a1 u)^-1 = (a0 - a1 u) / (a0^2 + a1^2)
  u64 n0[4], n1[4], d[4], di[4];
  mont_sqr(n0, a.c0);
  mont_sqr(n1, a.c1);
  add_mod(d, n0, n1);
  mont_inv(di, d);
  mont_mul(r.c0, a.c0, di);
  u64 neg[4];
  sub_mod(neg, (const u64 *)ZERO, a.c1);
  mont_mul(r.c1, neg, di);
}

extern "C" {

// G1 fixed-base batch, Montgomery-form output, batch-inverted
// normalization (one field inversion for the whole batch instead of one
// per point — the Montgomery trick).  out: n * 8 u64 (x, y) Montgomery;
// (0,0) = infinity.
void g1_fixed_base_batch_mont(const u64 *base_xy, const u64 *scalars, int n, u64 *out_xy) {
  G1Jac(*table)[256] = new G1Jac[32][256];  // heap per call: GIL-free concurrent safety
  u64 bx[4], by[4];
  fp_to_mont(base_xy, bx, 1);
  fp_to_mont(base_xy + 4, by, 1);

  G1Jac wbase;
  memcpy(wbase.X, bx, 32);
  memcpy(wbase.Y, by, 32);
  memcpy(wbase.Z, ONE_MONT, 32);
  for (int w = 0; w < 32; ++w) {
    memset(&table[w][0], 0, sizeof(G1Jac));
    u64 zi[4], zi2[4], zi3[4], ax[4], ay[4];
    mont_inv(zi, wbase.Z);
    mont_sqr(zi2, zi);
    mont_mul(zi3, zi2, zi);
    mont_mul(ax, wbase.X, zi2);
    mont_mul(ay, wbase.Y, zi3);
    for (int d = 1; d < 256; ++d) jac_add_mixed(table[w][d], table[w][d - 1], ax, ay);
    for (int k = 0; k < 8; ++k) jac_double(wbase, wbase);
  }

  G1Jac *accs = new G1Jac[n];
  for (int i = 0; i < n; ++i) {
    const u64 *s = scalars + 4 * i;
    G1Jac acc;
    memset(&acc, 0, sizeof(acc));
    for (int w = 0; w < 32; ++w) {
      int d = (int)((s[w / 8] >> ((w % 8) * 8)) & 0xff);
      if (!d) continue;
      g1_add_jac(acc, table[w][d]);
    }
    accs[i] = acc;
  }

  // Batch inversion of all Zs (Montgomery trick), skipping infinities.
  u64 *prefix = new u64[4 * (n + 1)];
  memcpy(prefix, ONE_MONT, 32);
  for (int i = 0; i < n; ++i) {
    const u64 *z = accs[i].Z;
    if (is_zero4(z)) {
      memcpy(prefix + 4 * (i + 1), prefix + 4 * i, 32);
    } else {
      mont_mul(prefix + 4 * (i + 1), prefix + 4 * i, z);
    }
  }
  u64 inv_all[4];
  mont_inv(inv_all, prefix + 4 * n);
  for (int i = n - 1; i >= 0; --i) {
    u64 *o = out_xy + 8 * i;
    if (is_zero4(accs[i].Z)) {
      memset(o, 0, 64);
      continue;
    }
    u64 zi[4], zi2[4], zi3[4];
    mont_mul(zi, prefix + 4 * i, inv_all);        // Z_i^-1
    mont_mul(inv_all, inv_all, accs[i].Z);        // strip Z_i
    mont_sqr(zi2, zi);
    mont_mul(zi3, zi2, zi);
    mont_mul(o, accs[i].X, zi2);
    mont_mul(o + 4, accs[i].Y, zi3);
  }
  delete[] prefix;
  delete[] accs;
  delete[] table;
}

// G2 fixed-base batch, Montgomery output.  base: (x.c0, x.c1, y.c0, y.c1)
// standard form (16 u64); out: n * 16 u64 Montgomery; all-zero = infinity.
void g2_fixed_base_batch_mont(const u64 *base, const u64 *scalars, int n, u64 *out) {
  G2Jac(*table)[256] = new G2Jac[32][256];  // heap per call: GIL-free concurrent safety
  Fp2 bx, by;
  fp_to_mont(base, bx.c0, 1);
  fp_to_mont(base + 4, bx.c1, 1);
  fp_to_mont(base + 8, by.c0, 1);
  fp_to_mont(base + 12, by.c1, 1);

  G2Jac wbase;
  wbase.X = bx;
  wbase.Y = by;
  memcpy(wbase.Z.c0, ONE_MONT, 32);
  memset(wbase.Z.c1, 0, 32);
  for (int w = 0; w < 32; ++w) {
    memset(&table[w][0], 0, sizeof(G2Jac));
    Fp2 zi, zi2, zi3, ax, ay;
    fp2_inv(zi, wbase.Z);
    fp2_sqr(zi2, zi);
    fp2_mul(zi3, zi2, zi);
    fp2_mul(ax, wbase.X, zi2);
    fp2_mul(ay, wbase.Y, zi3);
    for (int d = 1; d < 256; ++d) g2_add_mixed(table[w][d], table[w][d - 1], ax, ay);
    G2Jac t;
    for (int k = 0; k < 8; ++k) {
      g2_double(t, wbase);
      wbase = t;
    }
  }

  G2Jac *accs = new G2Jac[n];
  for (int i = 0; i < n; ++i) {
    const u64 *s = scalars + 4 * i;
    G2Jac acc;
    memset(&acc, 0, sizeof(acc));
    for (int w = 0; w < 32; ++w) {
      int d = (int)((s[w / 8] >> ((w % 8) * 8)) & 0xff);
      if (!d) continue;
      g2_add(acc, table[w][d]);
    }
    accs[i] = acc;
  }

  // Batch inversion in Fq2 via prefix products.
  Fp2 *prefix = new Fp2[n + 1];
  memcpy(prefix[0].c0, ONE_MONT, 32);
  memset(prefix[0].c1, 0, 32);
  for (int i = 0; i < n; ++i) {
    if (fp2_is_zero(accs[i].Z)) {
      prefix[i + 1] = prefix[i];
    } else {
      fp2_mul(prefix[i + 1], prefix[i], accs[i].Z);
    }
  }
  Fp2 inv_all;
  fp2_inv(inv_all, prefix[n]);
  for (int i = n - 1; i >= 0; --i) {
    u64 *o = out + 16 * i;
    if (fp2_is_zero(accs[i].Z)) {
      memset(o, 0, 128);
      continue;
    }
    Fp2 zi, zi2, zi3, mx, my, t;
    fp2_mul(zi, prefix[i], inv_all);
    fp2_mul(t, inv_all, accs[i].Z);
    inv_all = t;
    fp2_sqr(zi2, zi);
    fp2_mul(zi3, zi2, zi);
    fp2_mul(mx, accs[i].X, zi2);
    fp2_mul(my, accs[i].Y, zi3);
    memcpy(o, mx.c0, 32);
    memcpy(o + 4, mx.c1, 32);
    memcpy(o + 8, my.c0, 32);
    memcpy(o + 12, my.c1, 32);
  }
  delete[] prefix;
  delete[] accs;
  delete[] table;
}

}  // extern "C"

// ===================================================================
// Fr scalar field + NTT + Pippenger MSM: the native Groth16 prover
// runtime.  This is the rapidsnark-analog of the framework (the
// reference's fastest prover is native C++, dizkus-scripts/
// 6_gen_proof_rapidsnark.sh); the TPU path (prover/groth16_tpu.py) is
// the accelerator backend, this is the portable-CPU one.  Same
// dataflow as prove_tpu: sparse matvec -> iNTT/coset/NTT ladder ->
// variable-base MSMs -> (host) blind+assemble, differentially tested
// against prove_host in tests/test_native_prover.py.
// ===================================================================

// BN254 scalar field r (little-endian limbs) and Montgomery constants.
static const u64 R_MOD[4] = {0x43e1f593f0000001ULL, 0x2833e84879b97091ULL,
                             0xb85045b68181585dULL, 0x30644e72e131a029ULL};
static const u64 RINV = 0xc2e1f593efffffffULL;  // -r^-1 mod 2^64
static const u64 R2R[4] = {0x1bb8e645ae216da7ULL, 0x53fe3ab1e35c59e3ULL,
                           0x8c49833d53bb8085ULL, 0x0216d0b17f4e44a5ULL};
static const u64 ONE_R[4] = {0xac96341c4ffffffbULL, 0x36fc76959f60cd29ULL,
                             0x666ea36f7879462eULL, 0x0e0a77c19a07df2fULL};

static inline void fr_add(u64 out[4], const u64 a[4], const u64 b[4]) {
  u64 t[5];
  u128 carry = 0;
  for (int i = 0; i < 4; ++i) {
    u128 s = (u128)a[i] + b[i] + carry;
    t[i] = (u64)s;
    carry = s >> 64;
  }
  t[4] = (u64)carry;
  if (t[4] || geq(t, R_MOD)) {
    sub_nored(out, t, R_MOD);
  } else {
    memcpy(out, t, 32);
  }
}

static inline void fr_sub(u64 out[4], const u64 a[4], const u64 b[4]) {
  if (geq(a, b)) {
    sub_nored(out, a, b);
  } else {
    u64 t[4];
    sub_nored(t, b, a);
    sub_nored(out, R_MOD, t);
  }
}

// CIOS Montgomery multiplication over r (mirror of mont_mul over p).
static void fr_mul(u64 out[4], const u64 a[4], const u64 b[4]) {
  u64 t[6] = {0, 0, 0, 0, 0, 0};
  for (int i = 0; i < 4; ++i) {
    u128 carry = 0;
    for (int j = 0; j < 4; ++j) {
      u128 s = (u128)t[j] + (u128)a[i] * b[j] + carry;
      t[j] = (u64)s;
      carry = s >> 64;
    }
    u128 s = (u128)t[4] + carry;
    t[4] = (u64)s;
    t[5] = (u64)(s >> 64);

    u64 m = t[0] * RINV;
    carry = ((u128)t[0] + (u128)m * R_MOD[0]) >> 64;
    for (int j = 1; j < 4; ++j) {
      u128 s2 = (u128)t[j] + (u128)m * R_MOD[j] + carry;
      t[j - 1] = (u64)s2;
      carry = s2 >> 64;
    }
    u128 s3 = (u128)t[4] + carry;
    t[3] = (u64)s3;
    t[4] = t[5] + (u64)(s3 >> 64);
  }
  if (t[4] || geq(t, R_MOD)) {
    sub_nored(out, t, R_MOD);
  } else {
    memcpy(out, t, 32);
  }
}

// Montgomery exponentiation a^e over r (big-endian bit scan of e).
static void fr_pow(u64 out[4], const u64 a[4], const u64 e[4]) {
  u64 acc[4];
  memcpy(acc, ONE_R, 32);
  for (int i = 255; i >= 0; --i) {
    fr_mul(acc, acc, acc);
    if ((e[i / 64] >> (i % 64)) & 1) fr_mul(acc, acc, a);
  }
  memcpy(out, acc, 32);
}

static void fr_inv_mont(u64 out[4], const u64 a[4]) {
  u64 e[4];
  u64 two[4] = {2, 0, 0, 0};
  sub_nored(e, R_MOD, two);
  fr_pow(out, a, e);
}

// ----------------------------------------------- AVX-512 IFMA field core
//
// 8-wide Montgomery arithmetic in a 5x52-bit limb representation
// (R = 2^260), the layout vpmadd52luq/vpmadd52huq are built for.  This
// is the single-core SIMD answer to rapidsnark's x86-64 asm field layer
// (SURVEY.md §2.2): the driver box exposes exactly one core, so lane
// parallelism is the only parallel axis the native tier has.
//
// Domain bookkeeping ("carrier trick"): a value stored as y = x·2^256
// (the scalar tier's mont256 form) times a constant stored as c·2^260
// (mont260) under mont260 multiplication yields (y·c·2^260)·2^-260 =
// (x·c)·2^256 — i.e. data can stay in the scalar tier's Montgomery form
// through the whole vector pipeline as long as every CONSTANT table
// (twiddles, coset powers) is prepared in mont260 form.  No conversion
// passes over the data, ever.
//
// Lazy reduction: all vector values live in [0, 2p).  mont260 output is
// < p + a·b/2^260 < 2p for inputs < 2p because 4p < 2^260; add/sub
// conditionally fold by 2p.  Full reduction happens only at unpack.

#if defined(__AVX512IFMA__)
#include <immintrin.h>
#define ZKP2P_HAVE_IFMA 1

static const u64 M52 = (1ULL << 52) - 1;

// Per-field constant pack for the 52-bit core (Fr for NTT, Fq later for
// the MSM lambda lanes).
struct Ifma52Field {
  u64 p52[5];      // modulus
  u64 p2_52[5];    // 2p
  u64 comp2p[5];   // 2^260 - 2p  (complement used for the cond-subtract)
  u64 pinv52;      // -p^-1 mod 2^52
  u64 r260sq[5];   // 2^520 mod p (std -> mont260 via one mont260 mul)
  u64 c256[5];     // 2^256 mod p (mont260 -> mont256 carrier)
  u64 c264[5];     // 2^264 mod p (mont256 -> mont260 carrier)
  u64 compp[5];    // 2^260 - p (complement for the canonical fold)
};

static void limbs4_to_52(u64 out[5], const u64 a[4]) {
  out[0] = a[0] & M52;
  out[1] = ((a[0] >> 52) | (a[1] << 12)) & M52;
  out[2] = ((a[1] >> 40) | (a[2] << 24)) & M52;
  out[3] = ((a[2] >> 28) | (a[3] << 36)) & M52;
  out[4] = a[3] >> 16;
}

static void limbs52_to_4(u64 out[4], const u64 t[5]) {
  out[0] = t[0] | (t[1] << 52);
  out[1] = (t[1] >> 12) | (t[2] << 40);
  out[2] = (t[2] >> 24) | (t[3] << 28);
  out[3] = (t[3] >> 36) | (t[4] << 16);
}

// 1-lane 52-limb mont260 multiply (u128 scalar): table building only.
static void mont52_mul_scalar(u64 out[5], const u64 a[5], const u64 b[5],
                              const Ifma52Field &F) {
  u128 t[6] = {0, 0, 0, 0, 0, 0};
  for (int i = 0; i < 5; ++i) {
    u64 bi = b[i];
    for (int j = 0; j < 5; ++j) {
      u128 prod = (u128)a[j] * bi;
      t[j] += (u64)prod & M52;
      t[j + 1] += (u64)(prod >> 52);
    }
    u64 mi = ((u64)t[0] * F.pinv52) & M52;
    for (int j = 0; j < 5; ++j) {
      u128 prod = (u128)mi * F.p52[j];
      t[j] += (u64)prod & M52;
      t[j + 1] += (u64)(prod >> 52);
    }
    t[1] += (u64)(t[0] >> 52);
    for (int j = 0; j < 5; ++j) t[j] = t[j + 1];
    t[5] = 0;
  }
  u64 c = 0;
  for (int j = 0; j < 5; ++j) {
    u128 s = t[j] + c;
    out[j] = (u64)s & M52;
    c = (u64)(s >> 52);
  }
}

// Build the constant pack from 4x64 modulus + -p^-1 mod 2^64.
static void ifma52_init(Ifma52Field &F, const u64 p4[4], u64 pinv64,
                        void (*add_modp)(u64 *, const u64 *, const u64 *)) {
  limbs4_to_52(F.p52, p4);
  F.pinv52 = pinv64 & M52;
  // 2p as a raw 255-bit value (p < 2^254, so the shift cannot overflow)
  u64 two_p[4];
  u64 carry = 0;
  for (int i = 0; i < 4; ++i) {
    two_p[i] = (p4[i] << 1) | carry;
    carry = p4[i] >> 63;
  }
  limbs4_to_52(F.p2_52, two_p);
  // comp2p = 2^260 - 2p = (~2p + 1) over 5x52 limbs (mod 2^260)
  u64 c2 = 1;
  for (int j = 0; j < 5; ++j) {
    u64 s = ((~F.p2_52[j]) & M52) + c2;
    F.comp2p[j] = s & M52;
    c2 = s >> 52;
  }
  // compp = 2^260 - p (canonical fold: subtract p when >= p)
  c2 = 1;
  for (int j = 0; j < 5; ++j) {
    u64 s = ((~F.p52[j]) & M52) + c2;
    F.compp[j] = s & M52;
    c2 = s >> 52;
  }
  // 2^520 mod p by 520 reducing doublings of 1, snapshotting the
  // carrier-conversion constants 2^256 and 2^264 on the way up
  u64 x[4] = {1, 0, 0, 0};
  for (int i = 0; i < 520; ++i) {
    add_modp(x, x, x);
    if (i == 255) limbs4_to_52(F.c256, x);
    if (i == 263) limbs4_to_52(F.c264, x);
  }
  limbs4_to_52(F.r260sq, x);
}

// add thunks with the reducing signature ifma52_init expects
static void fr_add_thunk(u64 *o, const u64 *a, const u64 *b) { fr_add(o, a, b); }
static void fp_add_thunk(u64 *o, const u64 *a, const u64 *b) { add_mod(o, a, b); }

static Ifma52Field &fr52_field() {
  static Ifma52Field F;
  static bool init = false;
  static std::mutex mu;
  std::lock_guard<std::mutex> lk(mu);
  if (!init) {
    ifma52_init(F, R_MOD, RINV, fr_add_thunk);
    init = true;
  }
  return F;
}

static Ifma52Field &fq52_field() {
  static Ifma52Field F;
  static bool init = false;
  static std::mutex mu;
  std::lock_guard<std::mutex> lk(mu);
  if (!init) {
    ifma52_init(F, P, PINV, fp_add_thunk);
    init = true;
  }
  return F;
}

static bool ifma_enabled() {
  // atomic, not a plain int: the first call can come from several pool
  // workers at once (TSan caught the plain-int version racing here).
  // Both racers compute the same value, so relaxed ordering suffices —
  // the atomic only removes the UB, not any needed synchronization.
  static std::atomic<int> cached{-1};
  int v = cached.load(std::memory_order_relaxed);
  if (v < 0) {
    const char *e = getenv("ZKP2P_NATIVE_IFMA");
    bool off = e && e[0] == '0';
    v = (!off && __builtin_cpu_supports("avx512ifma")) ? 1 : 0;
    cached.store(v, std::memory_order_relaxed);
  }
  return v == 1;
}

// ---- vector kernel: out = a*b*2^-260, lanes independent, in/out < 2p.
// Accumulator headroom: each 64-bit lane absorbs <= 4 madd52 terms plus
// one sub-2^12 carry per outer iteration (5 iterations -> < 25·2^52 <
// 2^57), far under 2^64.
static inline void mont52_mul8(__m512i out[5], const __m512i a[5],
                               const __m512i b[5], const __m512i p[5],
                               const __m512i pinv) {
  const __m512i z = _mm512_setzero_si512();
  __m512i t0 = z, t1 = z, t2 = z, t3 = z, t4 = z, t5 = z;
  for (int i = 0; i < 5; ++i) {
    const __m512i bi = b[i];
    t0 = _mm512_madd52lo_epu64(t0, a[0], bi);
    t1 = _mm512_madd52lo_epu64(t1, a[1], bi);
    t2 = _mm512_madd52lo_epu64(t2, a[2], bi);
    t3 = _mm512_madd52lo_epu64(t3, a[3], bi);
    t4 = _mm512_madd52lo_epu64(t4, a[4], bi);
    t1 = _mm512_madd52hi_epu64(t1, a[0], bi);
    t2 = _mm512_madd52hi_epu64(t2, a[1], bi);
    t3 = _mm512_madd52hi_epu64(t3, a[2], bi);
    t4 = _mm512_madd52hi_epu64(t4, a[3], bi);
    t5 = _mm512_madd52hi_epu64(t5, a[4], bi);
    const __m512i mi = _mm512_madd52lo_epu64(z, t0, pinv);
    t0 = _mm512_madd52lo_epu64(t0, mi, p[0]);
    t1 = _mm512_add_epi64(t1, _mm512_srli_epi64(t0, 52));
    t1 = _mm512_madd52lo_epu64(t1, mi, p[1]);
    t2 = _mm512_madd52lo_epu64(t2, mi, p[2]);
    t3 = _mm512_madd52lo_epu64(t3, mi, p[3]);
    t4 = _mm512_madd52lo_epu64(t4, mi, p[4]);
    t1 = _mm512_madd52hi_epu64(t1, mi, p[0]);
    t2 = _mm512_madd52hi_epu64(t2, mi, p[1]);
    t3 = _mm512_madd52hi_epu64(t3, mi, p[2]);
    t4 = _mm512_madd52hi_epu64(t4, mi, p[3]);
    t5 = _mm512_madd52hi_epu64(t5, mi, p[4]);
    t0 = t1; t1 = t2; t2 = t3; t3 = t4; t4 = t5; t5 = z;
  }
  // carry-normalize to 52-bit limbs
  const __m512i m52 = _mm512_set1_epi64((long long)M52);
  __m512i c;
  out[0] = _mm512_and_si512(t0, m52);           c = _mm512_srli_epi64(t0, 52);
  t1 = _mm512_add_epi64(t1, c);
  out[1] = _mm512_and_si512(t1, m52);           c = _mm512_srli_epi64(t1, 52);
  t2 = _mm512_add_epi64(t2, c);
  out[2] = _mm512_and_si512(t2, m52);           c = _mm512_srli_epi64(t2, 52);
  t3 = _mm512_add_epi64(t3, c);
  out[3] = _mm512_and_si512(t3, m52);           c = _mm512_srli_epi64(t3, 52);
  t4 = _mm512_add_epi64(t4, c);
  out[4] = t4;  // < 2^52 (result < 2p < 2^255)
}

// Two INDEPENDENT mont52_mul8 chains issued through one instruction
// schedule.  A single chain is latency-bound: each of the 5 outer
// iterations serializes t0 -> mi -> t0 (madd52lo latency ~4 cycles on
// 1-2 IFMA ports), leaving most multiplier slots idle.  Interleaving a
// second chain with no data dependence on the first fills those slots —
// the out-of-order window sees ~2x the independent madd52 work per
// serial step.  Lane semantics are exactly two mont52_mul8 calls; the
// fusion is purely an instruction-scheduling artifact, so callers can
// regroup chains freely without changing any result bit.
static inline void mont52_mul8x2(__m512i outA[5], const __m512i aA[5],
                                 const __m512i bA[5], __m512i outB[5],
                                 const __m512i aB[5], const __m512i bB[5],
                                 const __m512i p[5], const __m512i pinv) {
  const __m512i z = _mm512_setzero_si512();
  __m512i s0 = z, s1 = z, s2 = z, s3 = z, s4 = z, s5 = z;
  __m512i u0 = z, u1 = z, u2 = z, u3 = z, u4 = z, u5 = z;
  for (int i = 0; i < 5; ++i) {
    const __m512i bi = bA[i], ci = bB[i];
    s0 = _mm512_madd52lo_epu64(s0, aA[0], bi);
    u0 = _mm512_madd52lo_epu64(u0, aB[0], ci);
    s1 = _mm512_madd52lo_epu64(s1, aA[1], bi);
    u1 = _mm512_madd52lo_epu64(u1, aB[1], ci);
    s2 = _mm512_madd52lo_epu64(s2, aA[2], bi);
    u2 = _mm512_madd52lo_epu64(u2, aB[2], ci);
    s3 = _mm512_madd52lo_epu64(s3, aA[3], bi);
    u3 = _mm512_madd52lo_epu64(u3, aB[3], ci);
    s4 = _mm512_madd52lo_epu64(s4, aA[4], bi);
    u4 = _mm512_madd52lo_epu64(u4, aB[4], ci);
    s1 = _mm512_madd52hi_epu64(s1, aA[0], bi);
    u1 = _mm512_madd52hi_epu64(u1, aB[0], ci);
    s2 = _mm512_madd52hi_epu64(s2, aA[1], bi);
    u2 = _mm512_madd52hi_epu64(u2, aB[1], ci);
    s3 = _mm512_madd52hi_epu64(s3, aA[2], bi);
    u3 = _mm512_madd52hi_epu64(u3, aB[2], ci);
    s4 = _mm512_madd52hi_epu64(s4, aA[3], bi);
    u4 = _mm512_madd52hi_epu64(u4, aB[3], ci);
    s5 = _mm512_madd52hi_epu64(s5, aA[4], bi);
    u5 = _mm512_madd52hi_epu64(u5, aB[4], ci);
    const __m512i mA = _mm512_madd52lo_epu64(z, s0, pinv);
    const __m512i mB = _mm512_madd52lo_epu64(z, u0, pinv);
    s0 = _mm512_madd52lo_epu64(s0, mA, p[0]);
    u0 = _mm512_madd52lo_epu64(u0, mB, p[0]);
    s1 = _mm512_add_epi64(s1, _mm512_srli_epi64(s0, 52));
    u1 = _mm512_add_epi64(u1, _mm512_srli_epi64(u0, 52));
    s1 = _mm512_madd52lo_epu64(s1, mA, p[1]);
    u1 = _mm512_madd52lo_epu64(u1, mB, p[1]);
    s2 = _mm512_madd52lo_epu64(s2, mA, p[2]);
    u2 = _mm512_madd52lo_epu64(u2, mB, p[2]);
    s3 = _mm512_madd52lo_epu64(s3, mA, p[3]);
    u3 = _mm512_madd52lo_epu64(u3, mB, p[3]);
    s4 = _mm512_madd52lo_epu64(s4, mA, p[4]);
    u4 = _mm512_madd52lo_epu64(u4, mB, p[4]);
    s1 = _mm512_madd52hi_epu64(s1, mA, p[0]);
    u1 = _mm512_madd52hi_epu64(u1, mB, p[0]);
    s2 = _mm512_madd52hi_epu64(s2, mA, p[1]);
    u2 = _mm512_madd52hi_epu64(u2, mB, p[1]);
    s3 = _mm512_madd52hi_epu64(s3, mA, p[2]);
    u3 = _mm512_madd52hi_epu64(u3, mB, p[2]);
    s4 = _mm512_madd52hi_epu64(s4, mA, p[3]);
    u4 = _mm512_madd52hi_epu64(u4, mB, p[3]);
    s5 = _mm512_madd52hi_epu64(s5, mA, p[4]);
    u5 = _mm512_madd52hi_epu64(u5, mB, p[4]);
    s0 = s1; s1 = s2; s2 = s3; s3 = s4; s4 = s5; s5 = z;
    u0 = u1; u1 = u2; u2 = u3; u3 = u4; u4 = u5; u5 = z;
  }
  const __m512i m52 = _mm512_set1_epi64((long long)M52);
  __m512i c;
  outA[0] = _mm512_and_si512(s0, m52);          c = _mm512_srli_epi64(s0, 52);
  s1 = _mm512_add_epi64(s1, c);
  outA[1] = _mm512_and_si512(s1, m52);          c = _mm512_srli_epi64(s1, 52);
  s2 = _mm512_add_epi64(s2, c);
  outA[2] = _mm512_and_si512(s2, m52);          c = _mm512_srli_epi64(s2, 52);
  s3 = _mm512_add_epi64(s3, c);
  outA[3] = _mm512_and_si512(s3, m52);          c = _mm512_srli_epi64(s3, 52);
  s4 = _mm512_add_epi64(s4, c);
  outA[4] = s4;
  outB[0] = _mm512_and_si512(u0, m52);          c = _mm512_srli_epi64(u0, 52);
  u1 = _mm512_add_epi64(u1, c);
  outB[1] = _mm512_and_si512(u1, m52);          c = _mm512_srli_epi64(u1, 52);
  u2 = _mm512_add_epi64(u2, c);
  outB[2] = _mm512_and_si512(u2, m52);          c = _mm512_srli_epi64(u2, 52);
  u3 = _mm512_add_epi64(u3, c);
  outB[3] = _mm512_and_si512(u3, m52);          c = _mm512_srli_epi64(u3, 52);
  u4 = _mm512_add_epi64(u4, c);
  outB[4] = u4;
}

// conditional fold by an arbitrary complement (2^260 - M): subtract M
// when v >= M.  Used with comp2p (lazy fold) and compp (canonical fold).
static inline void cond_sub_c8(__m512i v[5], const __m512i comp[5]) {
  const __m512i m52 = _mm512_set1_epi64((long long)M52);
  __m512i u[5], c = _mm512_setzero_si512();
  for (int j = 0; j < 5; ++j) {
    __m512i s = _mm512_add_epi64(_mm512_add_epi64(v[j], comp[j]), c);
    u[j] = _mm512_and_si512(s, m52);
    c = _mm512_srli_epi64(s, 52);
  }
  __mmask8 ge = _mm512_cmpneq_epu64_mask(c, _mm512_setzero_si512());
  for (int j = 0; j < 5; ++j) v[j] = _mm512_mask_blend_epi64(ge, v[j], u[j]);
}

// u' = u + t (mod lazy 2p); limbs of u,t are 52-bit normalized.
static inline void add_lazy8(__m512i out[5], const __m512i u[5],
                             const __m512i t[5], const __m512i comp2p[5]) {
  const __m512i m52 = _mm512_set1_epi64((long long)M52);
  __m512i c = _mm512_setzero_si512();
  for (int j = 0; j < 5; ++j) {
    __m512i s = _mm512_add_epi64(_mm512_add_epi64(u[j], t[j]), c);
    out[j] = _mm512_and_si512(s, m52);
    c = _mm512_srli_epi64(s, 52);
  }
  cond_sub_c8(out, comp2p);
}

// v' = u - t + 2p (mod lazy 2p).
static inline void sub_lazy8(__m512i out[5], const __m512i u[5],
                             const __m512i t[5], const __m512i p2[5],
                             const __m512i comp2p[5]) {
  const __m512i m52 = _mm512_set1_epi64((long long)M52);
  // u + 2p + (~t + 1) over 52-bit limbs, mod 2^260
  __m512i c = _mm512_set1_epi64(1);
  for (int j = 0; j < 5; ++j) {
    __m512i nt = _mm512_andnot_si512(t[j], m52);  // M52 - t[j]
    __m512i s = _mm512_add_epi64(_mm512_add_epi64(u[j], p2[j]),
                                 _mm512_add_epi64(nt, c));
    out[j] = _mm512_and_si512(s, m52);
    c = _mm512_srli_epi64(s, 52);
  }
  cond_sub_c8(out, comp2p);
}

// -------- per-stage twiddle tables (mont260, SoA planes, contiguous j)
//
// For each radix-2 stage len >= 16 the vector path wants tw[j] for
// contiguous j in 0..half-1.  Tables are cached per (m, root) like the
// scalar twiddle cache, same 8-entry cap, shared_ptr for in-flight
// safety.  Layout: stages concatenated, each stage stored as 5 planes
// of `half` u64.
struct IfmaTwiddles {
  std::shared_ptr<u64[]> buf;
  // offsets[s] = start of stage (len = 16 << s) in buf, in u64s
  std::vector<size_t> offsets;
};

static IfmaTwiddles ifma_stage_twiddles(long m, const u64 root_std[4]) {
  static std::mutex mu;
  static std::map<std::array<u64, 5>, IfmaTwiddles> cache;
  std::lock_guard<std::mutex> lk(mu);
  std::array<u64, 5> key = {(u64)m, root_std[0], root_std[1], root_std[2], root_std[3]};
  auto it = cache.find(key);
  if (it != cache.end()) return it->second;

  Ifma52Field &F = fr52_field();
  IfmaTwiddles T;
  size_t total = 0;
  for (long len = 16; len <= m; len <<= 1) total += (size_t)(len >> 1) * 5;
  T.buf = std::shared_ptr<u64[]>(new u64[total]);
  // root in mont260: pack then one mont260 mul by 2^520
  u64 root52[5], root260[5];
  limbs4_to_52(root52, root_std);
  mont52_mul_scalar(root260, root52, F.r260sq, F);
  u64 one260[5];  // 2^260 mod p = mont260(1): 1*2^520*2^-260
  u64 one52[5] = {1, 0, 0, 0, 0};
  mont52_mul_scalar(one260, one52, F.r260sq, F);
  size_t off = 0;
  for (long len = 16; len <= m; len <<= 1) {
    long half = len >> 1;
    // wlen = root^(m/len) in mont260 (square root260 down the chain)
    u64 wlen[5];
    memcpy(wlen, root260, 40);
    for (long s = m / len; s > 1; s >>= 1) mont52_mul_scalar(wlen, wlen, wlen, F);
    T.offsets.push_back(off);
    u64 cur[5];
    memcpy(cur, one260, 40);
    u64 *planes = T.buf.get() + off;
    for (long j = 0; j < half; ++j) {
      for (int k = 0; k < 5; ++k) planes[(size_t)k * half + j] = cur[k];
      mont52_mul_scalar(cur, cur, wlen, F);
    }
    off += (size_t)half * 5;
  }
  while (cache.size() >= 8) cache.erase(cache.begin());
  cache[key] = T;
  return T;
}

// -------- SoA-plane pipeline helpers (shared by fr_ntt_ifma and the
// fused H ladder).  Layout: 5 planes of m u64 (plane k at soa + k*m),
// values in the lazy [0, 2p) 52-limb domain carrying the scalar tier's
// mont256 form (see the domain comment above).  Every helper takes the
// resolved worker count and degrades to the serial inline path through
// pool_parallel_ranges (nt <= 1, tiny m, or a pool-worker caller).

// Direct index bit-reversal (byte-table compose): the parallel permute
// passes can't ride the classic incremental-j walk — each range needs
// its own j, so compute rev(i) outright.  m <= 2^31 here (domains top
// out at 2^26 for the flagship).
struct Rev8Tab {
  unsigned char t[256];
  Rev8Tab() {
    for (int i = 0; i < 256; ++i) {
      int r = 0;
      for (int b = 0; b < 8; ++b) r |= ((i >> b) & 1) << (7 - b);
      t[i] = (unsigned char)r;
    }
  }
};
static const Rev8Tab REV8;
static inline long bitrev_idx(long i, int bits) {
  unsigned v = (unsigned)i;
  unsigned r = ((unsigned)REV8.t[v & 0xff] << 24) |
               ((unsigned)REV8.t[(v >> 8) & 0xff] << 16) |
               ((unsigned)REV8.t[(v >> 16) & 0xff] << 8) |
               (unsigned)REV8.t[(v >> 24) & 0xff];
  return (long)(r >> (32 - bits));
}

// (m, 4) mont256 rows -> SoA planes, BIT-REVERSED on the way in:
// soa[:, i] = pack(data[rev(i)]) — folding the permutation into the
// pack pass (sequential writes, gathered 32-byte row reads) removes the
// standalone swap pass the serial NTT entry used to run.
static void fr_soa_pack_rev(const u64 *data, long m, u64 *soa, int nt) {
  int bits = 0;
  while ((1L << bits) < m) ++bits;
  pool_parallel_ranges(m, 1L << 13, nt, [&](long lo, long hi) {
    for (long i = lo; i < hi; ++i) {
      u64 t[5];
      limbs4_to_52(t, data + 4 * bitrev_idx(i, bits));
      for (int k = 0; k < 5; ++k) soa[(size_t)k * m + i] = t[k];
    }
  });
}

// SoA planes -> (m, 4) mont256 rows with full canonical reduction.
static void fr_soa_unpack(const u64 *soa, long m, u64 *data, int nt) {
  pool_parallel_ranges(m, 1L << 13, nt, [&](long lo, long hi) {
    for (long i = lo; i < hi; ++i) {
      u64 t[5], o[4];
      for (int k = 0; k < 5; ++k) t[k] = soa[(size_t)k * m + i];
      limbs52_to_4(o, t);
      while (geq(o, R_MOD)) sub_nored(o, o, R_MOD);
      memcpy(data + 4 * i, o, 32);
    }
  });
}

// In-place bit-reversal of the SoA planes: the fused ladder re-enters
// the forward stages without unpacking to mont256 between transforms.
// Range-parallel: pair {i, rev(i)} is swapped only by the owner of the
// SMALLER index, and no other task reads either slot during the pass,
// so ranges never conflict.
static void fr_soa_bitrev(u64 *soa, long m, int nt) {
  int bits = 0;
  while ((1L << bits) < m) ++bits;
  pool_parallel_ranges(m, 1L << 14, nt, [&](long lo, long hi) {
    for (long i = lo; i < hi; ++i) {
      long j = bitrev_idx(i, bits);
      if (i < j) {
        for (int k = 0; k < 5; ++k) {
          u64 tmp = soa[(size_t)k * m + i];
          soa[(size_t)k * m + i] = soa[(size_t)k * m + j];
          soa[(size_t)k * m + j] = tmp;
        }
      }
    }
  });
}

// Pointwise vector multiply by a mont260 SoA constant table (the fused
// ladder's coset-shift + deferred-1/m-scale pass): soa[i] *= tbl[i],
// lazy domain preserved (mont260 constants keep the data's mont256
// carrier — the standing rule of this pipeline).
static void fr_soa_mul(u64 *soa, long m, const u64 *tbl, int nt) {
  Ifma52Field &F = fr52_field();
  __m512i p[5];
  for (int k = 0; k < 5; ++k) p[k] = _mm512_set1_epi64((long long)F.p52[k]);
  const __m512i pinv = _mm512_set1_epi64((long long)F.pinv52);
  pool_parallel_ranges(m / 8, 512, nt, [&](long blo, long bhi) {
    for (long b = blo; b < bhi; ++b) {
      const long i = b * 8;
      __m512i x[5], t[5], o[5];
      for (int k = 0; k < 5; ++k) {
        x[k] = _mm512_loadu_si512(soa + (size_t)k * m + i);
        t[k] = _mm512_loadu_si512(tbl + (size_t)k * m + i);
      }
      mont52_mul8(o, x, t, p, pinv);
      for (int k = 0; k < 5; ++k) _mm512_storeu_si512(soa + (size_t)k * m + i, o[k]);
    }
  });
}

// ALL NTT stages over packed SoA planes (input bit-reversed): len 2/4/8
// in-register (permute + blended add/sub, constant twiddle vectors),
// then the radix-4-fused len>=16 loop.  Each pass's butterfly blocks
// are independent, so every pass fans out across the WorkPool
// (nt-gated) with the pool's run() barrier separating stages — the
// split that lets ONE transform use every core, where the ladder's old
// 3-wide whole-transform split stranded cores at 6 transforms / prove.
static void fr_ntt_soa_stages(u64 *soa, long m, const u64 root_std[4], int nt) {
  long long t_st = prof_now_ns();
  Ifma52Field &F = fr52_field();
  IfmaTwiddles T = ifma_stage_twiddles(m, root_std);
  __m512i p[5], p2[5], comp2p[5];
  for (int k = 0; k < 5; ++k) {
    p[k] = _mm512_set1_epi64((long long)F.p52[k]);
    p2[k] = _mm512_set1_epi64((long long)F.p2_52[k]);
    comp2p[k] = _mm512_set1_epi64((long long)F.comp2p[k]);
  }
  const __m512i pinv = _mm512_set1_epi64((long long)F.pinv52);

  // ---- stages len = 2, 4, 8 fully in-register (butterflies never
  // cross a 512-bit vector): permute u/v lanes, one constant-twiddle
  // mont mul (len 2 is mul-free: its only twiddle is 1), blended
  // add/sub.  Twiddle constant vectors repeat per vector:
  //   len 4: [1, w4] x4   len 8: [1, w8, w8^2, w8^3] x2
  {
    u64 one52v[5] = {1, 0, 0, 0, 0}, one260[5];
    mont52_mul_scalar(one260, one52v, F.r260sq, F);
    // root260 = root_std in mont260; w_len = root260^(m/len)
    u64 root52[5], root260[5];
    limbs4_to_52(root52, root_std);
    mont52_mul_scalar(root260, root52, F.r260sq, F);
    auto pow2k = [&](u64 out[5], long e_pow2) {
      // root260^(e_pow2) where e_pow2 is a power of two: squarings
      memcpy(out, root260, 40);
      for (long s = e_pow2; s > 1; s >>= 1) mont52_mul_scalar(out, out, out, F);
    };
    u64 w4[5], w8[5], w8sq[5], w8cu[5];
    pow2k(w4, m / 4);
    pow2k(w8, m / 8);
    mont52_mul_scalar(w8sq, w8, w8, F);
    mont52_mul_scalar(w8cu, w8sq, w8, F);

    const __m512i idx_even = _mm512_set_epi64(6, 6, 4, 4, 2, 2, 0, 0);
    const __m512i idx_odd = _mm512_set_epi64(7, 7, 5, 5, 3, 3, 1, 1);
    const __m512i idx_lo4 = _mm512_set_epi64(5, 4, 5, 4, 1, 0, 1, 0);
    const __m512i idx_hi4 = _mm512_set_epi64(7, 6, 7, 6, 3, 2, 3, 2);
    const __m512i idx_lo8 = _mm512_set_epi64(3, 2, 1, 0, 3, 2, 1, 0);
    const __m512i idx_hi8 = _mm512_set_epi64(7, 6, 5, 4, 7, 6, 5, 4);
    __m512i tw4[5], tw8[5];
    {
      u64 t4[5][8], t8[5][8];
      for (int k = 0; k < 5; ++k) {
        for (int l = 0; l < 8; ++l) {
          t4[k][l] = (l & 1) ? w4[k] : one260[k];
          t8[k][l] = (l & 3) == 0 ? one260[k]
                     : (l & 3) == 1 ? w8[k]
                     : (l & 3) == 2 ? w8sq[k]
                                    : w8cu[k];
        }
        tw4[k] = _mm512_loadu_si512(t4[k]);
        tw8[k] = _mm512_loadu_si512(t8[k]);
      }
    }
    pool_parallel_ranges(m / 8, 256, nt, [&](long blo, long bhi) {
    for (long blk = blo; blk < bhi; ++blk) {
      const long i = blk * 8;
      __m512i x[5];
      for (int k = 0; k < 5; ++k) x[k] = _mm512_loadu_si512(soa + (size_t)k * m + i);
      // stage len=2: pairs (0,1)(2,3)(4,5)(6,7), twiddle 1 (no mul)
      {
        __m512i u[5], v[5], s[5], d[5];
        for (int k = 0; k < 5; ++k) {
          u[k] = _mm512_permutexvar_epi64(idx_even, x[k]);
          v[k] = _mm512_permutexvar_epi64(idx_odd, x[k]);
        }
        add_lazy8(s, u, v, comp2p);
        sub_lazy8(d, u, v, p2, comp2p);
        for (int k = 0; k < 5; ++k) x[k] = _mm512_mask_blend_epi64(0xAA, s[k], d[k]);
      }
      // stage len=4: pairs (0,2)(1,3) per group of 4, twiddles [1, w4]
      {
        __m512i u[5], v[5], t[5], s[5], d[5];
        for (int k = 0; k < 5; ++k) {
          u[k] = _mm512_permutexvar_epi64(idx_lo4, x[k]);
          v[k] = _mm512_permutexvar_epi64(idx_hi4, x[k]);
        }
        mont52_mul8(t, v, tw4, p, pinv);
        add_lazy8(s, u, t, comp2p);
        sub_lazy8(d, u, t, p2, comp2p);
        for (int k = 0; k < 5; ++k) x[k] = _mm512_mask_blend_epi64(0xCC, s[k], d[k]);
      }
      // stage len=8: pairs (l, l+4), twiddles [1, w8, w8^2, w8^3]
      {
        __m512i u[5], v[5], t[5], s[5], d[5];
        for (int k = 0; k < 5; ++k) {
          u[k] = _mm512_permutexvar_epi64(idx_lo8, x[k]);
          v[k] = _mm512_permutexvar_epi64(idx_hi8, x[k]);
        }
        mont52_mul8(t, v, tw8, p, pinv);
        add_lazy8(s, u, t, comp2p);
        sub_lazy8(d, u, t, p2, comp2p);
        for (int k = 0; k < 5; ++k) x[k] = _mm512_mask_blend_epi64(0xF0, s[k], d[k]);
      }
      for (int k = 0; k < 5; ++k) _mm512_storeu_si512(soa + (size_t)k * m + i, x[k]);
    }
    });
  }
  // One radix-2 vector stage (the generic building block, and the odd
  // leading stage when the vector-stage count is odd).  The (block,
  // j-group) butterfly space is flattened so the pool splits within a
  // block too — the last stages have only a handful of blocks.
  auto radix2_stage = [&](long len, int stage) {
    const long half = len >> 1;
    const u64 *twp = T.buf.get() + T.offsets[stage];
    const long jblocks = half >> 3;
    pool_parallel_ranges((m / len) * jblocks, 256, nt, [&](long glo, long ghi) {
      for (long g = glo; g < ghi; ++g) {
        const long i0 = (g / jblocks) * len;
        const long j = (g % jblocks) * 8;
        __m512i u[5], v[5], tw[5], t[5], un[5], vn[5];
        for (int k = 0; k < 5; ++k) {
          u[k] = _mm512_loadu_si512(soa + (size_t)k * m + i0 + j);
          v[k] = _mm512_loadu_si512(soa + (size_t)k * m + i0 + j + half);
          tw[k] = _mm512_loadu_si512(twp + (size_t)k * half + j);
        }
        mont52_mul8(t, v, tw, p, pinv);
        add_lazy8(un, u, t, comp2p);
        sub_lazy8(vn, u, t, p2, comp2p);
        for (int k = 0; k < 5; ++k) {
          _mm512_storeu_si512(soa + (size_t)k * m + i0 + j, un[k]);
          _mm512_storeu_si512(soa + (size_t)k * m + i0 + j + half, vn[k]);
        }
      }
    });
  };
  // Radix-4 fusion of stage pairs (len, 2len): same 4 Montgomery muls
  // per 4 elements as two radix-2 passes, but ONE load/store pass over
  // the SoA planes instead of two — the stages are memory-bound at
  // these sizes.  Twiddles come straight from the existing per-stage
  // radix-2 tables: stage len's w^j plus stage 2len's w^j and w^{j+q}.
  auto radix4_pass = [&](long len4, int stg) {
    const long L = 2 * len4;   // fused block size
    const long q = len4 >> 1;  // quarter
    const u64 *tw1p = T.buf.get() + T.offsets[stg];      // stage len: q entries
    const u64 *tw2p = T.buf.get() + T.offsets[stg + 1];  // stage 2len: 2q entries
    const long jblocks = q >> 3;
    pool_parallel_ranges((m / L) * jblocks, 128, nt, [&](long glo, long ghi) {
      for (long g = glo; g < ghi; ++g) {
        const long i0 = (g / jblocks) * L;
        const long j = (g % jblocks) * 8;
        __m512i a[5], b[5], c[5], d[5], w1[5], w2[5], w2q[5];
        for (int k = 0; k < 5; ++k) {
          a[k] = _mm512_loadu_si512(soa + (size_t)k * m + i0 + j);
          b[k] = _mm512_loadu_si512(soa + (size_t)k * m + i0 + j + q);
          c[k] = _mm512_loadu_si512(soa + (size_t)k * m + i0 + j + 2 * q);
          d[k] = _mm512_loadu_si512(soa + (size_t)k * m + i0 + j + 3 * q);
          w1[k] = _mm512_loadu_si512(tw1p + (size_t)k * q + j);
          w2[k] = _mm512_loadu_si512(tw2p + (size_t)k * (2 * q) + j);
          w2q[k] = _mm512_loadu_si512(tw2p + (size_t)k * (2 * q) + j + q);
        }
        __m512i t1[5], t2[5], a1[5], b1[5], c1[5], d1[5];
        // stage len: (a,b) and (c,d) with twiddle w1 — independent
        // chains, one fused schedule
        mont52_mul8x2(t1, b, w1, t2, d, w1, p, pinv);
        add_lazy8(a1, a, t1, comp2p);
        sub_lazy8(b1, a, t1, p2, comp2p);
        add_lazy8(c1, c, t2, comp2p);
        sub_lazy8(d1, c, t2, p2, comp2p);
        // stage 2len: (a1,c1) with w2[j], (b1,d1) with w2[j+q]
        __m512i u1[5], u2[5], o0[5], o1[5], o2[5], o3[5];
        mont52_mul8x2(u1, c1, w2, u2, d1, w2q, p, pinv);
        add_lazy8(o0, a1, u1, comp2p);
        sub_lazy8(o2, a1, u1, p2, comp2p);
        add_lazy8(o1, b1, u2, comp2p);
        sub_lazy8(o3, b1, u2, p2, comp2p);
        for (int k = 0; k < 5; ++k) {
          _mm512_storeu_si512(soa + (size_t)k * m + i0 + j, o0[k]);
          _mm512_storeu_si512(soa + (size_t)k * m + i0 + j + q, o1[k]);
          _mm512_storeu_si512(soa + (size_t)k * m + i0 + j + 2 * q, o2[k]);
          _mm512_storeu_si512(soa + (size_t)k * m + i0 + j + 3 * q, o3[k]);
        }
      }
    });
  };
  // Radix-8 fusion of stage triples (len, 2len, 4len): 12 Montgomery
  // muls per 8 elements — the same butterfly count as three radix-2
  // passes or 1.5 radix-4 passes, but ONE load/store trip over the SoA
  // planes, and every mul paired with an independent partner through
  // mont52_mul8x2 so the serial madd52 recurrences overlap.  The fused
  // ladder at 2^19 is compute-bound on exactly those chains (NEXT.md
  // lever 2).  Twiddle indexing per element s of the 8q block
  // (q = len/2): stage len pairs (2t, 2t+1) ×w1[j]; stage 2len pairs
  // (4t+s, 4t+s+2) ×w2[j+s·q]; stage 4len pairs (s, s+4) ×w3[j+s·q].
  // The op sequence per element is exactly the radix-2 decomposition,
  // so the lazy-domain residues — and the final proof bytes — are
  // bit-identical to the radix-4 arrangement.
  auto radix8_pass = [&](long len8, int stg) {
    const long q = len8 >> 1;
    const long L8 = 8 * q;  // fused block: three stages span 4·len8
    const u64 *tw1p = T.buf.get() + T.offsets[stg];      // q entries
    const u64 *tw2p = T.buf.get() + T.offsets[stg + 1];  // 2q entries
    const u64 *tw3p = T.buf.get() + T.offsets[stg + 2];  // 4q entries
    const long jblocks = q >> 3;
    pool_parallel_ranges((m / L8) * jblocks, 64, nt, [&](long glo, long ghi) {
      for (long g = glo; g < ghi; ++g) {
        const long i0 = (g / jblocks) * L8;
        const long j = (g % jblocks) * 8;
        __m512i x0[5], x1[5], x2[5], x3[5], x4[5], x5[5], x6[5], x7[5];
        __m512i w1[5], w2a[5], w2b[5], w3a[5], w3b[5], w3c[5], w3d[5];
        for (int k = 0; k < 5; ++k) {
          const size_t o = (size_t)k * m + i0 + j;
          x0[k] = _mm512_loadu_si512(soa + o);
          x1[k] = _mm512_loadu_si512(soa + o + q);
          x2[k] = _mm512_loadu_si512(soa + o + 2 * q);
          x3[k] = _mm512_loadu_si512(soa + o + 3 * q);
          x4[k] = _mm512_loadu_si512(soa + o + 4 * q);
          x5[k] = _mm512_loadu_si512(soa + o + 5 * q);
          x6[k] = _mm512_loadu_si512(soa + o + 6 * q);
          x7[k] = _mm512_loadu_si512(soa + o + 7 * q);
          w1[k] = _mm512_loadu_si512(tw1p + (size_t)k * q + j);
          w2a[k] = _mm512_loadu_si512(tw2p + (size_t)k * (2 * q) + j);
          w2b[k] = _mm512_loadu_si512(tw2p + (size_t)k * (2 * q) + j + q);
          w3a[k] = _mm512_loadu_si512(tw3p + (size_t)k * (4 * q) + j);
          w3b[k] = _mm512_loadu_si512(tw3p + (size_t)k * (4 * q) + j + q);
          w3c[k] = _mm512_loadu_si512(tw3p + (size_t)k * (4 * q) + j + 2 * q);
          w3d[k] = _mm512_loadu_si512(tw3p + (size_t)k * (4 * q) + j + 3 * q);
        }
        __m512i tA[5], tB[5];
        // stage len: (x0,x1)(x2,x3)(x4,x5)(x6,x7), all ×w1[j]
        __m512i a0[5], a1[5], a2[5], a3[5], a4[5], a5[5], a6[5], a7[5];
        mont52_mul8x2(tA, x1, w1, tB, x3, w1, p, pinv);
        add_lazy8(a0, x0, tA, comp2p);
        sub_lazy8(a1, x0, tA, p2, comp2p);
        add_lazy8(a2, x2, tB, comp2p);
        sub_lazy8(a3, x2, tB, p2, comp2p);
        mont52_mul8x2(tA, x5, w1, tB, x7, w1, p, pinv);
        add_lazy8(a4, x4, tA, comp2p);
        sub_lazy8(a5, x4, tA, p2, comp2p);
        add_lazy8(a6, x6, tB, comp2p);
        sub_lazy8(a7, x6, tB, p2, comp2p);
        // stage 2len: (a0,a2)(a4,a6) ×w2[j], (a1,a3)(a5,a7) ×w2[j+q]
        __m512i b0[5], b1[5], b2[5], b3[5], b4[5], b5[5], b6[5], b7[5];
        mont52_mul8x2(tA, a2, w2a, tB, a3, w2b, p, pinv);
        add_lazy8(b0, a0, tA, comp2p);
        sub_lazy8(b2, a0, tA, p2, comp2p);
        add_lazy8(b1, a1, tB, comp2p);
        sub_lazy8(b3, a1, tB, p2, comp2p);
        mont52_mul8x2(tA, a6, w2a, tB, a7, w2b, p, pinv);
        add_lazy8(b4, a4, tA, comp2p);
        sub_lazy8(b6, a4, tA, p2, comp2p);
        add_lazy8(b5, a5, tB, comp2p);
        sub_lazy8(b7, a5, tB, p2, comp2p);
        // stage 4len: (b0,b4)×w3[j] (b1,b5)×w3[j+q] (b2,b6)×w3[j+2q]
        // (b3,b7)×w3[j+3q]
        __m512i o0[5], o1[5], o2[5], o3[5], o4[5], o5[5], o6[5], o7[5];
        mont52_mul8x2(tA, b4, w3a, tB, b5, w3b, p, pinv);
        add_lazy8(o0, b0, tA, comp2p);
        sub_lazy8(o4, b0, tA, p2, comp2p);
        add_lazy8(o1, b1, tB, comp2p);
        sub_lazy8(o5, b1, tB, p2, comp2p);
        mont52_mul8x2(tA, b6, w3c, tB, b7, w3d, p, pinv);
        add_lazy8(o2, b2, tA, comp2p);
        sub_lazy8(o6, b2, tA, p2, comp2p);
        add_lazy8(o3, b3, tB, comp2p);
        sub_lazy8(o7, b3, tB, p2, comp2p);
        for (int k = 0; k < 5; ++k) {
          const size_t o = (size_t)k * m + i0 + j;
          _mm512_storeu_si512(soa + o, o0[k]);
          _mm512_storeu_si512(soa + o + q, o1[k]);
          _mm512_storeu_si512(soa + o + 2 * q, o2[k]);
          _mm512_storeu_si512(soa + o + 3 * q, o3[k]);
          _mm512_storeu_si512(soa + o + 4 * q, o4[k]);
          _mm512_storeu_si512(soa + o + 5 * q, o5[k]);
          _mm512_storeu_si512(soa + o + 6 * q, o6[k]);
          _mm512_storeu_si512(soa + o + 7 * q, o7[k]);
        }
      }
    });
  };
  int n_vstages = 0;
  for (long len0 = 16; len0 <= m; len0 <<= 1) ++n_vstages;
  int stage = 0;
  long len = 16;
  if (ntt_radix8_enabled() && n_vstages >= 3) {
    // Radix-8 arm: clear the mod-3 remainder first (one radix-2 or
    // radix-4 pass), then triples all the way up.
    const int r = n_vstages % 3;
    if (r == 1) {
      radix2_stage(len, stage);
      ++stage;
      len <<= 1;
    } else if (r == 2) {
      radix4_pass(len, stage);
      stage += 2;
      len <<= 2;
    }
    for (; stage < n_vstages; len <<= 3, stage += 3) radix8_pass(len, stage);
  } else {
    if (n_vstages & 1) {
      radix2_stage(len, stage);
      ++stage;
      len <<= 1;
    }
    for (; len * 2 <= m; len <<= 2, stage += 2) radix4_pass(len, stage);
  }
  stat_add(ST_NTT_STAGE_NS, prof_now_ns() - t_st);
}

// Compat wrapper (fr_ntt_ifma's tier), NATURAL-order input: the input
// bit-reversal folds into the pack pass (fr_soa_pack_rev), so the
// standalone swap pass the serial entry used to run is gone.  The
// stage-pool gate resolves HERE: splitting engages when ZKP2P_NTT_POOL
// is on; a pool-worker caller (the knob-off 3-wide ladder runs each
// transform ON a worker) degrades to serial inside pool_parallel_ranges
// regardless, so regions never nest.
static void fr_ntt_ifma_stages(u64 *data, long m, const u64 root_std[4]) {
  int nt = ntt_pool_enabled() ? pool_default_threads() : 1;
  u64 *soa = new u64[(size_t)m * 5];
  fr_soa_pack_rev(data, m, soa, nt);
  fr_ntt_soa_stages(soa, m, root_std, nt);
  fr_soa_unpack(soa, m, data, nt);
  delete[] soa;
}

// Vectorized batch-affine chunk apply over Fq (the MSM hot loop): given
// the per-add arrays of one scheduled chunk (all Montgomery-256), run
// the whole inversion-and-apply pipeline 8 lanes at a time:
//   - lane-strided prefix products (lane l owns j ≡ l mod 8),
//   - ONE scalar field inversion for the 8 lane totals,
//   - vector suffix walk producing 1/den[j],
//   - lambda / x3 / y3 evaluation, all 8-wide mont260 with the lazy
//     [0,2p) domain, carriers converted 256<->260 at the edges.
// x3a/y3a come back fully reduced (< p) so the caller's memcmp-based
// bucket equality checks keep working.
// Caller-provided SoA scratch: 9 arrays x 5 planes x (chunk cap rounded
// to 8) u64 — hoisted out of the per-chunk hot loop by g1_window_sum.
static void g1_chunk_apply_ifma(const u64 (*x1a)[4], const u64 (*y1a)[4],
                                const u64 (*x2a)[4], const u64 (*y2a)[4],
                                const unsigned char *dbl, long m,
                                u64 (*x3a)[4], u64 (*y3a)[4], u64 *buf) {
  Ifma52Field &F = fq52_field();
  const long nblk = (m + 7) / 8, N = nblk * 8;
  // SoA scratch layout: den,num,x1,y1,x2,y2,prod,x3,y3
  u64 *d52 = buf, *n52 = buf + (size_t)5 * N, *x152 = buf + (size_t)10 * N,
      *y152 = buf + (size_t)15 * N, *x252 = buf + (size_t)20 * N,
      *y252 = buf + (size_t)25 * N, *pr52 = buf + (size_t)30 * N,
      *x352 = buf + (size_t)35 * N, *y352 = buf + (size_t)40 * N;
  u64 one52[5] = {1, 0, 0, 0, 0}, one260[5];
  mont52_mul_scalar(one260, one52, F.r260sq, F);
  auto pack_arr = [&](const u64 (*src)[4], u64 *dst, const u64 *pad) {
    for (long j = 0; j < N; ++j) {
      u64 t[5];
      if (j < m) {
        limbs4_to_52(t, src[j]);
      } else {
        memcpy(t, pad, 40);
      }
      for (int k = 0; k < 5; ++k) dst[(size_t)k * N + j] = t[k];
    }
  };
  static const u64 Z5[5] = {0, 0, 0, 0, 0};
  pack_arr(x1a, x152, Z5);
  pack_arr(y1a, y152, Z5);
  // x2/y2 pad with x1-ish zeros; den derives below and pads to the
  // Montgomery-256 ONE so padded lanes are no-ops in the product chains
  pack_arr(x2a, x252, Z5);
  pack_arr(y2a, y252, Z5);

  __m512i p[5], p2[5], comp2p[5], c264v[5], c256v[5];
  for (int k = 0; k < 5; ++k) {
    p[k] = _mm512_set1_epi64((long long)F.p52[k]);
    p2[k] = _mm512_set1_epi64((long long)F.p2_52[k]);
    comp2p[k] = _mm512_set1_epi64((long long)F.comp2p[k]);
    c264v[k] = _mm512_set1_epi64((long long)F.c264[k]);
    c256v[k] = _mm512_set1_epi64((long long)F.c256[k]);
  }
  const __m512i pinv = _mm512_set1_epi64((long long)F.pinv52);
  // carrier 256 -> 260 for the coordinate arrays, then derive num/den
  // IN VECTOR FORM: chord lanes are (y2-y1, x2-x1); the rare doubling
  // lanes (3x1^2, 2y1) blend in per-block only when flagged.
  for (long t = 0; t < nblk; ++t) {
    u64 *arrs[4] = {x152, y152, x252, y252};
    __m512i conv[4][5];
    for (int a = 0; a < 4; ++a) {
      __m512i v[5];
      for (int k = 0; k < 5; ++k)
        v[k] = _mm512_loadu_si512(arrs[a] + (size_t)k * N + t * 8);
      mont52_mul8(conv[a], v, c264v, p, pinv);
      for (int k = 0; k < 5; ++k)
        _mm512_storeu_si512(arrs[a] + (size_t)k * N + t * 8, conv[a][k]);
    }
    __m512i denv[5], numv[5];
    sub_lazy8(denv, conv[2], conv[0], p2, comp2p);  // x2 - x1
    sub_lazy8(numv, conv[3], conv[1], p2, comp2p);  // y2 - y1
    unsigned char dm = 0;
    for (int l = 0; l < 8 && t * 8 + l < m; ++l)
      if (dbl[t * 8 + l]) dm |= (unsigned char)(1u << l);
    if (dm) {
      __m512i x1sq[5], numd[5], dend[5];
      mont52_mul8(x1sq, conv[0], conv[0], p, pinv);
      add_lazy8(numd, x1sq, x1sq, comp2p);
      add_lazy8(numd, numd, x1sq, comp2p);           // 3 x1^2
      add_lazy8(dend, conv[1], conv[1], comp2p);     // 2 y1
      const __mmask8 k = (__mmask8)dm;
      for (int q = 0; q < 5; ++q) {
        denv[q] = _mm512_mask_blend_epi64(k, denv[q], dend[q]);
        numv[q] = _mm512_mask_blend_epi64(k, numv[q], numd[q]);
      }
    }
    // padded lanes: force den to the mont260 ONE (no-op in chains)
    if (t == nblk - 1 && m < N) {
      __mmask8 padk = (__mmask8)(0xFFu << (8 - (N - m)));
      for (int q = 0; q < 5; ++q)
        denv[q] = _mm512_mask_blend_epi64(
            padk, denv[q], _mm512_set1_epi64((long long)one260[q]));
    }
    for (int k2 = 0; k2 < 5; ++k2) {
      _mm512_storeu_si512(d52 + (size_t)k2 * N + t * 8, denv[k2]);
      _mm512_storeu_si512(n52 + (size_t)k2 * N + t * 8, numv[k2]);
    }
  }
  // phase A: lane-strided prefix products
  __m512i run[5];
  for (int k = 0; k < 5; ++k) run[k] = _mm512_set1_epi64((long long)one260[k]);
  for (long t = 0; t < nblk; ++t) {
    __m512i dv[5];
    for (int k = 0; k < 5; ++k) {
      _mm512_storeu_si512(pr52 + (size_t)k * N + t * 8, run[k]);
      dv[k] = _mm512_loadu_si512(d52 + (size_t)k * N + t * 8);
    }
    mont52_mul8(run, run, dv, p, pinv);
  }
  // ONE inversion for the 8 lane totals (scalar mont256)
  u64 tl8[5][8];
  for (int k = 0; k < 5; ++k) _mm512_storeu_si512(tl8[k], run[k]);
  u64 T4[8][4];
  for (int l = 0; l < 8; ++l) {
    u64 t52[5], t256[5];
    for (int k = 0; k < 5; ++k) t52[k] = tl8[k][l];
    mont52_mul_scalar(t256, t52, F.c256, F);  // carrier 260 -> 256
    limbs52_to_4(T4[l], t256);
    while (geq(T4[l], P)) sub_nored(T4[l], T4[l], P);
  }
  u64 pre[8][4], G[4], Ginv[4], suf[4], Tinv[8][4];
  memcpy(pre[0], ONE_MONT, 32);
  for (int l = 1; l < 8; ++l) mont_mul(pre[l], pre[l - 1], T4[l - 1]);
  mont_mul(G, pre[7], T4[7]);
  mont_inv(Ginv, G);
  memcpy(suf, Ginv, 32);
  for (int l = 7; l >= 0; --l) {
    mont_mul(Tinv[l], suf, pre[l]);
    mont_mul(suf, suf, T4[l]);
  }
  __m512i inv_run[5];
  {
    u64 ir8[5][8];
    for (int l = 0; l < 8; ++l) {
      u64 t52[5], t260[5];
      limbs4_to_52(t52, Tinv[l]);
      mont52_mul_scalar(t260, t52, F.c264, F);  // carrier 256 -> 260
      for (int k = 0; k < 5; ++k) ir8[k][l] = t260[k];
    }
    for (int k = 0; k < 5; ++k) inv_run[k] = _mm512_loadu_si512(ir8[k]);
  }
  // phase B: backward suffix walk + apply
  for (long t = nblk - 1; t >= 0; --t) {
    __m512i prv[5], dv[5], nv[5], x1v[5], y1v[5], x2v[5];
    for (int k = 0; k < 5; ++k) {
      prv[k] = _mm512_loadu_si512(pr52 + (size_t)k * N + t * 8);
      dv[k] = _mm512_loadu_si512(d52 + (size_t)k * N + t * 8);
      nv[k] = _mm512_loadu_si512(n52 + (size_t)k * N + t * 8);
      x1v[k] = _mm512_loadu_si512(x152 + (size_t)k * N + t * 8);
      y1v[k] = _mm512_loadu_si512(y152 + (size_t)k * N + t * 8);
      x2v[k] = _mm512_loadu_si512(x252 + (size_t)k * N + t * 8);
    }
    __m512i dinv[5], lam[5], lam2[5], x3[5], tt[5], yy[5], y3[5];
    mont52_mul8(dinv, inv_run, prv, p, pinv);
    mont52_mul8(inv_run, inv_run, dv, p, pinv);
    mont52_mul8(lam, nv, dinv, p, pinv);
    mont52_mul8(lam2, lam, lam, p, pinv);
    sub_lazy8(x3, lam2, x1v, p2, comp2p);
    sub_lazy8(x3, x3, x2v, p2, comp2p);
    sub_lazy8(tt, x1v, x3, p2, comp2p);
    mont52_mul8(yy, lam, tt, p, pinv);
    sub_lazy8(y3, yy, y1v, p2, comp2p);
    mont52_mul8(x3, x3, c256v, p, pinv);  // carrier back to 256
    mont52_mul8(y3, y3, c256v, p, pinv);
    for (int k = 0; k < 5; ++k) {
      _mm512_storeu_si512(x352 + (size_t)k * N + t * 8, x3[k]);
      _mm512_storeu_si512(y352 + (size_t)k * N + t * 8, y3[k]);
    }
  }
  // unpack, fully reduced
  for (long j = 0; j < m; ++j) {
    u64 t[5], o[4];
    for (int k = 0; k < 5; ++k) t[k] = x352[(size_t)k * N + j];
    limbs52_to_4(o, t);
    while (geq(o, P)) sub_nored(o, o, P);
    memcpy(x3a[j], o, 32);
    for (int k = 0; k < 5; ++k) t[k] = y352[(size_t)k * N + j];
    limbs52_to_4(o, t);
    while (geq(o, P)) sub_nored(o, o, P);
    memcpy(y3a[j], o, 32);
  }
}

// -------- persistent 52-limb mont260 MSM storage (G1)
//
// Bases and buckets live in 5x52-limb mont260 form for the WHOLE MSM:
// the chunk apply loses its six carrier-conversion vector muls per
// block and all per-add limb-shift packing — conversion happens once
// per MSM (bases, vectorized) and once per bucket at reduction time.
// Components are kept CANONICAL (< p) so memcmp equality (doubling /
// cancellation detection) still works.

struct Aff52 {
  u64 x[5], y[5];  // canonical mont260; all-zero = infinity/empty
};

static void fold52_canonical(u64 v[5], const Ifma52Field &F);

// y -> p - y over canonical 52-limb components (the signed-digit negation).
static inline void neg52(u64 out[5], const u64 y[5], const Ifma52Field &F) {
  bool z = true;
  for (int j = 0; j < 5 && z; ++j) z = y[j] == 0;
  if (z) {
    memset(out, 0, 40);
    return;
  }
  u64 borrow = 0;
  for (int j = 0; j < 5; ++j) {
    u64 yb = y[j] + borrow;  // <= 2^52, no overflow
    if (F.p52[j] >= yb) {
      out[j] = F.p52[j] - yb;
      borrow = 0;
    } else {
      out[j] = (F.p52[j] + (1ULL << 52)) - yb;
      borrow = 1;
    }
  }
}

// mont256 affine pairs -> canonical mont260 Aff52, 8 points per step.
static void g1_bases_to_52(const u64 *bases_xy, long n, Aff52 *out) {
  Ifma52Field &F = fq52_field();
  __m512i p[5], c264v[5], comppv[5];
  for (int k = 0; k < 5; ++k) {
    p[k] = _mm512_set1_epi64((long long)F.p52[k]);
    c264v[k] = _mm512_set1_epi64((long long)F.c264[k]);
    comppv[k] = _mm512_set1_epi64((long long)F.compp[k]);
  }
  const __m512i pinv = _mm512_set1_epi64((long long)F.pinv52);
  long i = 0;
  for (; i + 8 <= n; i += 8) {
    u64 xv[5][8], yv[5][8];
    for (int l = 0; l < 8; ++l) {
      u64 t[5];
      limbs4_to_52(t, bases_xy + 8 * (i + l));
      for (int k = 0; k < 5; ++k) xv[k][l] = t[k];
      limbs4_to_52(t, bases_xy + 8 * (i + l) + 4);
      for (int k = 0; k < 5; ++k) yv[k][l] = t[k];
    }
    __m512i X[5], Y[5];
    for (int k = 0; k < 5; ++k) {
      X[k] = _mm512_loadu_si512(xv[k]);
      Y[k] = _mm512_loadu_si512(yv[k]);
    }
    __m512i Xm[5], Ym[5];
    mont52_mul8(Xm, X, c264v, p, pinv);
    cond_sub_c8(Xm, comppv);
    mont52_mul8(Ym, Y, c264v, p, pinv);
    cond_sub_c8(Ym, comppv);
    u64 ox[5][8], oy[5][8];
    for (int k = 0; k < 5; ++k) {
      _mm512_storeu_si512(ox[k], Xm[k]);
      _mm512_storeu_si512(oy[k], Ym[k]);
    }
    for (int l = 0; l < 8; ++l) {
      for (int k = 0; k < 5; ++k) {
        out[i + l].x[k] = ox[k][l];
        out[i + l].y[k] = oy[k][l];
      }
    }
  }
  for (; i < n; ++i) {
    u64 t[5], m260[5];
    limbs4_to_52(t, bases_xy + 8 * i);
    mont52_mul_scalar(m260, t, F.c264, F);
    fold52_canonical(m260, F);
    memcpy(out[i].x, m260, 40);
    limbs4_to_52(t, bases_xy + 8 * i + 4);
    mont52_mul_scalar(m260, t, F.c264, F);
    fold52_canonical(m260, F);
    memcpy(out[i].y, m260, 40);
  }
}

// canonical fold of a < 2p 52-limb value (scalar path).
static void fold52_canonical(u64 v[5], const Ifma52Field &F) {
  bool ge = true;
  for (int j = 4; j >= 0; --j) {
    if (v[j] != F.p52[j]) {
      ge = v[j] > F.p52[j];
      break;
    }
  }
  if (!ge) return;
  u64 borrow = 0;
  for (int j = 0; j < 5; ++j) {
    u64 pb = F.p52[j] + borrow;
    if (v[j] >= pb) {
      v[j] -= pb;
      borrow = 0;
    } else {
      v[j] = (v[j] + (1ULL << 52)) - pb;
      borrow = 1;
    }
  }
}

// canonical mont260 component -> canonical mont256 u64x4.
static void limb52_to_mont256(const u64 a[5], u64 out[4], const Ifma52Field &F) {
  u64 t[5];
  mont52_mul_scalar(t, a, F.c256, F);
  limbs52_to_4(out, t);
  while (geq(out, P)) sub_nored(out, out, P);
}

// The 52-native chunk apply: same pipeline as g1_chunk_apply_ifma but
// with NO carrier conversions and NO limb-shift packing — stashes are
// already 5-limb mont260 canonical.  Outputs canonical.
// buf: 8 x 5 x roundup8(m) u64 scratch (den,num,x1,y1,x2,prod,x3,y3 —
// y2 is derived per block from b52 + the sign flag, no plane kept).
// Gathers operands by INDEX (bucket id + point id + sign) straight
// from the bucket array and the converted bases — the schedule loop
// stores three small ints per add instead of 160 bytes of coordinate
// stashes.
static void g1_chunk_apply_52(const Aff52 *bk, const Aff52 *b52,
                              const long *add_bkt, const long *add_pt,
                              const unsigned char *negf,
                              const unsigned char *dbl, long m,
                              u64 (*x3a)[5], u64 (*y3a)[5], u64 *buf) {
  Ifma52Field &F = fq52_field();
  const long nblk = (m + 7) / 8, N = nblk * 8;
  u64 *d52 = buf, *n52 = buf + (size_t)5 * N, *x152 = buf + (size_t)10 * N,
      *y152 = buf + (size_t)15 * N, *x252 = buf + (size_t)20 * N,
      *pr52 = buf + (size_t)25 * N, *x352 = buf + (size_t)30 * N,
      *y352 = buf + (size_t)35 * N;
  u64 one52[5] = {1, 0, 0, 0, 0}, one260[5];
  mont52_mul_scalar(one260, one52, F.r260sq, F);
  const bool ilv_pf = msm_interleave_enabled();
  // Prefetch distance down the schedule's index streams.  The gathered
  // Aff52s (80 bytes, two cache lines) sit at random offsets in a
  // bases/buckets working set far beyond L2 at bench shape — without
  // prefetch every add eats a demand-miss latency twice.
  const long PF = 24;
  // gather-transpose into SoA planes (x1 = bucket, x2 = incoming point)
  for (long j = 0; j < N; ++j) {
    if (j < m) {
      if (ilv_pf && j + PF < m) {
        const char *pb = (const char *)&bk[add_bkt[j + PF]];
        const char *pp = (const char *)&b52[add_pt[j + PF]];
        _mm_prefetch(pb, _MM_HINT_T0);
        _mm_prefetch(pb + 64, _MM_HINT_T0);
        _mm_prefetch(pp, _MM_HINT_T0);
        _mm_prefetch(pp + 64, _MM_HINT_T0);
      }
      const Aff52 &B1 = bk[add_bkt[j]];
      const Aff52 &P2 = b52[add_pt[j]];
      for (int k = 0; k < 5; ++k) {
        x152[(size_t)k * N + j] = B1.x[k];
        y152[(size_t)k * N + j] = B1.y[k];
        x252[(size_t)k * N + j] = P2.x[k];
      }
    } else {
      for (int k = 0; k < 5; ++k)
        x152[(size_t)k * N + j] = y152[(size_t)k * N + j] = x252[(size_t)k * N + j] = 0;
    }
  }
  // y2 goes straight into the num derivation below (no plane kept)

  __m512i p[5], p2[5], comp2p[5], comppv[5];
  for (int k = 0; k < 5; ++k) {
    p[k] = _mm512_set1_epi64((long long)F.p52[k]);
    p2[k] = _mm512_set1_epi64((long long)F.p2_52[k]);
    comp2p[k] = _mm512_set1_epi64((long long)F.comp2p[k]);
    comppv[k] = _mm512_set1_epi64((long long)F.compp[k]);
  }
  const __m512i pinv = _mm512_set1_epi64((long long)F.pinv52);
  for (long t = 0; t < nblk; ++t) {
    __m512i x1v[5], y1v[5], x2v[5], y2v[5];
    for (int k = 0; k < 5; ++k) {
      x1v[k] = _mm512_loadu_si512(x152 + (size_t)k * N + t * 8);
      y1v[k] = _mm512_loadu_si512(y152 + (size_t)k * N + t * 8);
      x2v[k] = _mm512_loadu_si512(x252 + (size_t)k * N + t * 8);
    }
    {
      u64 y2v8[5][8];
      for (int l = 0; l < 8; ++l) {
        long j = t * 8 + l;
        if (j < m) {
          if (ilv_pf && j + PF < m) {
            const char *pp = (const char *)b52[add_pt[j + PF]].y;
            _mm_prefetch(pp, _MM_HINT_T0);
            _mm_prefetch(pp + 39, _MM_HINT_T0);
          }
          u64 py[5];
          if (negf[j]) {
            neg52(py, b52[add_pt[j]].y, F);
          } else {
            memcpy(py, b52[add_pt[j]].y, 40);
          }
          for (int k = 0; k < 5; ++k) y2v8[k][l] = py[k];
        } else {
          for (int k = 0; k < 5; ++k) y2v8[k][l] = 0;
        }
      }
      for (int k = 0; k < 5; ++k) y2v[k] = _mm512_loadu_si512(y2v8[k]);
    }
    __m512i denv[5], numv[5];
    sub_lazy8(denv, x2v, x1v, p2, comp2p);
    sub_lazy8(numv, y2v, y1v, p2, comp2p);
    unsigned char dm = 0;
    for (int l = 0; l < 8 && t * 8 + l < m; ++l)
      if (dbl[t * 8 + l]) dm |= (unsigned char)(1u << l);
    if (dm) {
      __m512i x1sq[5], numd[5], dend[5];
      mont52_mul8(x1sq, x1v, x1v, p, pinv);
      add_lazy8(numd, x1sq, x1sq, comp2p);
      add_lazy8(numd, numd, x1sq, comp2p);
      add_lazy8(dend, y1v, y1v, comp2p);
      const __mmask8 kk = (__mmask8)dm;
      for (int q = 0; q < 5; ++q) {
        denv[q] = _mm512_mask_blend_epi64(kk, denv[q], dend[q]);
        numv[q] = _mm512_mask_blend_epi64(kk, numv[q], numd[q]);
      }
    }
    if (t == nblk - 1 && m < N) {
      __mmask8 padk = (__mmask8)(0xFFu << (m & 7));
      for (int q = 0; q < 5; ++q)
        denv[q] = _mm512_mask_blend_epi64(
            padk, denv[q], _mm512_set1_epi64((long long)one260[q]));
    }
    for (int k = 0; k < 5; ++k) {
      _mm512_storeu_si512(d52 + (size_t)k * N + t * 8, denv[k]);
      _mm512_storeu_si512(n52 + (size_t)k * N + t * 8, numv[k]);
    }
  }
  if (msm_interleave_enabled() && nblk >= 2) {
    // Interleaved arm (ZKP2P_MSM_INTERLEAVE): split the block range at
    // hA and drive BOTH halves' prefix/apply chains through one fused
    // schedule (mont52_mul8x2).  A single chain is latency-bound —
    // every block's prefix multiply waits on the previous block's — so
    // the second, data-independent chain fills the IFMA port bubbles.
    // The two group products meet in ONE shared 16-lane scalar
    // inversion (same mont_inv count as before).  Each group is its
    // own batch-inversion domain, so every lane still computes the
    // exact same field values; the canonical fold at the end erases
    // representative drift, keeping outputs byte-identical to the
    // single-chain arm.
    const long hA = (nblk + 1) / 2, nB = nblk - hA;
    __m512i runA[5], runB[5];
    for (int k = 0; k < 5; ++k)
      runA[k] = runB[k] = _mm512_set1_epi64((long long)one260[k]);
    for (long t = 0; t < hA; ++t) {
      const bool hasB = t < nB;
      __m512i dvA[5], dvB[5];
      for (int k = 0; k < 5; ++k) {
        _mm512_storeu_si512(pr52 + (size_t)k * N + t * 8, runA[k]);
        dvA[k] = _mm512_loadu_si512(d52 + (size_t)k * N + t * 8);
        if (hasB) {
          _mm512_storeu_si512(pr52 + (size_t)k * N + (hA + t) * 8, runB[k]);
          dvB[k] = _mm512_loadu_si512(d52 + (size_t)k * N + (hA + t) * 8);
        }
      }
      if (hasB)
        mont52_mul8x2(runA, runA, dvA, runB, runB, dvB, p, pinv);
      else
        mont52_mul8(runA, runA, dvA, p, pinv);
    }
    u64 tl16[2][5][8];
    for (int k = 0; k < 5; ++k) {
      _mm512_storeu_si512(tl16[0][k], runA[k]);
      _mm512_storeu_si512(tl16[1][k], runB[k]);
    }
    u64 T4[16][4];
    for (int l = 0; l < 16; ++l) {
      u64 t52[5];
      for (int k = 0; k < 5; ++k) t52[k] = tl16[l >> 3][k][l & 7];
      limb52_to_mont256(t52, T4[l], F);
    }
    u64 pre16[16][4], G[4], Ginv[4], suf[4], Tinv[16][4];
    memcpy(pre16[0], ONE_MONT, 32);
    for (int l = 1; l < 16; ++l) mont_mul(pre16[l], pre16[l - 1], T4[l - 1]);
    mont_mul(G, pre16[15], T4[15]);
    mont_inv(Ginv, G);
    memcpy(suf, Ginv, 32);
    for (int l = 15; l >= 0; --l) {
      mont_mul(Tinv[l], suf, pre16[l]);
      mont_mul(suf, suf, T4[l]);
    }
    __m512i inv_runA[5], inv_runB[5];
    {
      u64 ir16[2][5][8];
      for (int l = 0; l < 16; ++l) {
        u64 t52[5], t260[5];
        limbs4_to_52(t52, Tinv[l]);
        mont52_mul_scalar(t260, t52, F.c264, F);
        for (int k = 0; k < 5; ++k) ir16[l >> 3][k][l & 7] = t260[k];
      }
      for (int k = 0; k < 5; ++k) {
        inv_runA[k] = _mm512_loadu_si512(ir16[0][k]);
        inv_runB[k] = _mm512_loadu_si512(ir16[1][k]);
      }
    }
    // phase B: two interleaved backward walks (A: hA-1..0, B: nblk-1..hA)
    for (long i = 0; i < hA; ++i) {
      const long tA = hA - 1 - i, tB = nblk - 1 - i;
      const bool hasB = i < nB;
      __m512i prvA[5], dvA[5], nvA[5], x1A[5], y1A[5], x2A[5];
      __m512i prvB[5], dvB[5], nvB[5], x1B[5], y1B[5], x2B[5];
      for (int k = 0; k < 5; ++k) {
        prvA[k] = _mm512_loadu_si512(pr52 + (size_t)k * N + tA * 8);
        dvA[k] = _mm512_loadu_si512(d52 + (size_t)k * N + tA * 8);
        nvA[k] = _mm512_loadu_si512(n52 + (size_t)k * N + tA * 8);
        x1A[k] = _mm512_loadu_si512(x152 + (size_t)k * N + tA * 8);
        y1A[k] = _mm512_loadu_si512(y152 + (size_t)k * N + tA * 8);
        x2A[k] = _mm512_loadu_si512(x252 + (size_t)k * N + tA * 8);
        if (hasB) {
          prvB[k] = _mm512_loadu_si512(pr52 + (size_t)k * N + tB * 8);
          dvB[k] = _mm512_loadu_si512(d52 + (size_t)k * N + tB * 8);
          nvB[k] = _mm512_loadu_si512(n52 + (size_t)k * N + tB * 8);
          x1B[k] = _mm512_loadu_si512(x152 + (size_t)k * N + tB * 8);
          y1B[k] = _mm512_loadu_si512(y152 + (size_t)k * N + tB * 8);
          x2B[k] = _mm512_loadu_si512(x252 + (size_t)k * N + tB * 8);
        }
      }
      __m512i dinvA[5], lamA[5], lam2A[5], x3A[5], ttA[5], yyA[5], y3A[5];
      if (hasB) {
        __m512i dinvB[5], lamB[5], lam2B[5], x3B[5], ttB[5], yyB[5], y3B[5];
        mont52_mul8x2(dinvA, inv_runA, prvA, dinvB, inv_runB, prvB, p, pinv);
        mont52_mul8x2(inv_runA, inv_runA, dvA, inv_runB, inv_runB, dvB, p,
                      pinv);
        mont52_mul8x2(lamA, nvA, dinvA, lamB, nvB, dinvB, p, pinv);
        mont52_mul8x2(lam2A, lamA, lamA, lam2B, lamB, lamB, p, pinv);
        sub_lazy8(x3A, lam2A, x1A, p2, comp2p);
        sub_lazy8(x3A, x3A, x2A, p2, comp2p);
        sub_lazy8(ttA, x1A, x3A, p2, comp2p);
        sub_lazy8(x3B, lam2B, x1B, p2, comp2p);
        sub_lazy8(x3B, x3B, x2B, p2, comp2p);
        sub_lazy8(ttB, x1B, x3B, p2, comp2p);
        mont52_mul8x2(yyA, lamA, ttA, yyB, lamB, ttB, p, pinv);
        sub_lazy8(y3A, yyA, y1A, p2, comp2p);
        sub_lazy8(y3B, yyB, y1B, p2, comp2p);
        // canonical fold for the memcmp-equality contract
        cond_sub_c8(x3A, comppv);
        cond_sub_c8(y3A, comppv);
        cond_sub_c8(x3B, comppv);
        cond_sub_c8(y3B, comppv);
        for (int k = 0; k < 5; ++k) {
          _mm512_storeu_si512(x352 + (size_t)k * N + tA * 8, x3A[k]);
          _mm512_storeu_si512(y352 + (size_t)k * N + tA * 8, y3A[k]);
          _mm512_storeu_si512(x352 + (size_t)k * N + tB * 8, x3B[k]);
          _mm512_storeu_si512(y352 + (size_t)k * N + tB * 8, y3B[k]);
        }
      } else {
        mont52_mul8(dinvA, inv_runA, prvA, p, pinv);
        mont52_mul8(inv_runA, inv_runA, dvA, p, pinv);
        mont52_mul8(lamA, nvA, dinvA, p, pinv);
        mont52_mul8(lam2A, lamA, lamA, p, pinv);
        sub_lazy8(x3A, lam2A, x1A, p2, comp2p);
        sub_lazy8(x3A, x3A, x2A, p2, comp2p);
        sub_lazy8(ttA, x1A, x3A, p2, comp2p);
        mont52_mul8(yyA, lamA, ttA, p, pinv);
        sub_lazy8(y3A, yyA, y1A, p2, comp2p);
        cond_sub_c8(x3A, comppv);
        cond_sub_c8(y3A, comppv);
        for (int k = 0; k < 5; ++k) {
          _mm512_storeu_si512(x352 + (size_t)k * N + tA * 8, x3A[k]);
          _mm512_storeu_si512(y352 + (size_t)k * N + tA * 8, y3A[k]);
        }
      }
    }
  } else {
    // Single-chain arm (gate off, or a one-block chunk).
    // phase A: lane-strided prefix products
    __m512i run[5];
    for (int k = 0; k < 5; ++k)
      run[k] = _mm512_set1_epi64((long long)one260[k]);
    for (long t = 0; t < nblk; ++t) {
      __m512i dv[5];
      for (int k = 0; k < 5; ++k) {
        _mm512_storeu_si512(pr52 + (size_t)k * N + t * 8, run[k]);
        dv[k] = _mm512_loadu_si512(d52 + (size_t)k * N + t * 8);
      }
      mont52_mul8(run, run, dv, p, pinv);
    }
    u64 tl8[5][8];
    for (int k = 0; k < 5; ++k) _mm512_storeu_si512(tl8[k], run[k]);
    u64 T4[8][4];
    for (int l = 0; l < 8; ++l) {
      u64 t52[5];
      for (int k = 0; k < 5; ++k) t52[k] = tl8[k][l];
      limb52_to_mont256(t52, T4[l], F);
    }
    u64 pre8[8][4], G[4], Ginv[4], suf[4], Tinv[8][4];
    memcpy(pre8[0], ONE_MONT, 32);
    for (int l = 1; l < 8; ++l) mont_mul(pre8[l], pre8[l - 1], T4[l - 1]);
    mont_mul(G, pre8[7], T4[7]);
    mont_inv(Ginv, G);
    memcpy(suf, Ginv, 32);
    for (int l = 7; l >= 0; --l) {
      mont_mul(Tinv[l], suf, pre8[l]);
      mont_mul(suf, suf, T4[l]);
    }
    __m512i inv_run[5];
    {
      u64 ir8[5][8];
      for (int l = 0; l < 8; ++l) {
        u64 t52[5], t260[5];
        limbs4_to_52(t52, Tinv[l]);
        mont52_mul_scalar(t260, t52, F.c264, F);
        for (int k = 0; k < 5; ++k) ir8[k][l] = t260[k];
      }
      for (int k = 0; k < 5; ++k) inv_run[k] = _mm512_loadu_si512(ir8[k]);
    }
    // phase B backwards
    for (long t = nblk - 1; t >= 0; --t) {
      __m512i prv[5], dv[5], nv[5], x1v[5], y1v[5], x2v[5];
      for (int k = 0; k < 5; ++k) {
        prv[k] = _mm512_loadu_si512(pr52 + (size_t)k * N + t * 8);
        dv[k] = _mm512_loadu_si512(d52 + (size_t)k * N + t * 8);
        nv[k] = _mm512_loadu_si512(n52 + (size_t)k * N + t * 8);
        x1v[k] = _mm512_loadu_si512(x152 + (size_t)k * N + t * 8);
        y1v[k] = _mm512_loadu_si512(y152 + (size_t)k * N + t * 8);
        x2v[k] = _mm512_loadu_si512(x252 + (size_t)k * N + t * 8);
      }
      __m512i dinv[5], lam[5], lam2[5], x3[5], tt[5], yy[5], y3[5];
      mont52_mul8(dinv, inv_run, prv, p, pinv);
      mont52_mul8(inv_run, inv_run, dv, p, pinv);
      mont52_mul8(lam, nv, dinv, p, pinv);
      mont52_mul8(lam2, lam, lam, p, pinv);
      sub_lazy8(x3, lam2, x1v, p2, comp2p);
      sub_lazy8(x3, x3, x2v, p2, comp2p);
      sub_lazy8(tt, x1v, x3, p2, comp2p);
      mont52_mul8(yy, lam, tt, p, pinv);
      sub_lazy8(y3, yy, y1v, p2, comp2p);
      // canonical fold for the memcmp-equality contract
      cond_sub_c8(x3, comppv);
      cond_sub_c8(y3, comppv);
      for (int k = 0; k < 5; ++k) {
        _mm512_storeu_si512(x352 + (size_t)k * N + t * 8, x3[k]);
        _mm512_storeu_si512(y352 + (size_t)k * N + t * 8, y3[k]);
      }
    }
  }
  for (long j = 0; j < m; ++j) {
    for (int k = 0; k < 5; ++k) {
      x3a[j][k] = x352[(size_t)k * N + j];
      y3a[j][k] = y352[(size_t)k * N + j];
    }
  }
}

static inline bool aff52_is_zero(const u64 a[5]) {
  return !(a[0] | a[1] | a[2] | a[3] | a[4]);
}

// defined later in this file (shared with the non-IFMA tiers)
static void g1_window_sum_jac(const u64 *bases_xy, const int32_t *sd, long n,
                              int c, int nwin, int wi, G1Jac *out);
static inline void signed_pt_y(u64 out[4], const u64 y[4], bool negate);
static void g1_tree_sum(u64 (*xs)[4], u64 (*ys)[4], long n, G1Jac *out);
static void g1_add_jac(G1Jac &acc, const G1Jac &e);

// Tiny-digit-range windows (the TOP window at big domains has only a
// few effective bits): instead of the serial Jacobian fill — every
// point lands in one of a handful of buckets — partition points by
// digit and run each bucket through the vectorized tree sum, then do
// the standard suffix reduction over the few bucket sums.
static void g1_window_sum_small(const u64 *bases_xy, const int32_t *sd,
                                long n, int c, int nwin, int wi,
                                int bits_here, G1Jac *out) {
  const long nbuckets = (1L << bits_here) + 2;  // +carry headroom
  std::vector<std::vector<long>> members((size_t)nbuckets);
  for (long i = 0; i < n; ++i) {
    int32_t d = sd[i * nwin + wi];
    if (!d) continue;
    long b = d < 0 ? -d : d;
    if (b >= nbuckets) {  // cannot happen for a true top window; bail
      g1_window_sum_jac(bases_xy, sd, n, c, nwin, wi, out);
      return;
    }
    const u64 *x = bases_xy + 8 * i;
    if (is_zero4(x) && is_zero4(x + 4)) continue;
    members[b].push_back(i);  // sign re-read from sd at drain time
  }
  long cap = 0;
  for (auto &v : members) cap = std::max(cap, (long)v.size());
  u64 (*xs)[4] = new u64[cap > 0 ? cap : 1][4];
  u64 (*ys)[4] = new u64[cap > 0 ? cap : 1][4];
  G1Jac run, wsum;
  memset(&run, 0, sizeof(run));
  memset(&wsum, 0, sizeof(wsum));
  for (long b = nbuckets - 1; b >= 1; --b) {
    if (!members[b].empty()) {
      long k = 0;
      for (long i : members[b]) {
        const u64 *x = bases_xy + 8 * i;
        memcpy(xs[k], x, 32);
        signed_pt_y(ys[k], x + 4, sd[i * nwin + wi] < 0);
        ++k;
      }
      G1Jac bsum;
      g1_tree_sum(xs, ys, k, &bsum);
      g1_add_jac(run, bsum);
    }
    g1_add_jac(wsum, run);
  }
  delete[] xs;
  delete[] ys;
  *out = wsum;
}

// ---- 8-lane vectorized suffix reduction (one lane = one window) -----------
//
// The per-window suffix walk (run += bucket[d]; wsum += run) is serial in d
// but independent across windows, and profiles at ~27% of the G1 phase time
// of a full prove (ZKP2P_MSM_PROF / tools/msm_native_prof.py) now that the
// fill is 8-wide.  These helpers run up to 8 windows' walks in AVX-512 IFMA
// lanes: a masked Jacobian mixed add (bucket -> run) and a masked full
// Jacobian add (run -> wsum) per bucket index, in the same lazy [0,2p)
// mont260 domain as the chunk pipeline.  Exceptional lanes (doubling,
// P+(-P), infinity transitions beyond the common masks) blend out and
// re-run through the complete scalar ops — for bucket sums they cannot
// occur except adversarially, so the patch path is correctness-only.

// v == 0 (mod p) for lazy [0,2p) 52-limb values: exact 0 or exact p.
static inline __mmask8 is0_lazy8v(const __m512i v[5], const __m512i p[5]) {
  __mmask8 z = 0xFF, e = 0xFF;
  const __m512i zero = _mm512_setzero_si512();
  for (int j = 0; j < 5; ++j) {
    z &= _mm512_cmpeq_epu64_mask(v[j], zero);
    e &= _mm512_cmpeq_epu64_mask(v[j], p[j]);
  }
  return (__mmask8)(z | e);
}

struct Jac8 {
  __m512i X[5], Y[5], Z[5];
  __mmask8 inf;  // lanes at the point at infinity (coords then arbitrary)
};

static inline void v8_lane52(const __m512i V[5], int l, u64 out52[5]) {
  alignas(64) u64 b[8];
  for (int k = 0; k < 5; ++k) {
    _mm512_store_si512(b, V[k]);
    out52[k] = b[l];
  }
}

static inline void v8_set_lane52(__m512i V[5], int l, const u64 in52[5]) {
  alignas(64) u64 b[8];
  for (int k = 0; k < 5; ++k) {
    _mm512_store_si512(b, V[k]);
    b[l] = in52[k];
    V[k] = _mm512_load_si512(b);
  }
}

// One lane -> scalar G1Jac (canonical mont256 coords).
static G1Jac jac8_lane(const Jac8 &s, int l, const Ifma52Field &F) {
  G1Jac g;
  if ((s.inf >> l) & 1) {
    memset(&g, 0, sizeof(g));
    return g;
  }
  u64 c52[5];
  v8_lane52(s.X, l, c52);
  limb52_to_mont256(c52, g.X, F);
  v8_lane52(s.Y, l, c52);
  limb52_to_mont256(c52, g.Y, F);
  v8_lane52(s.Z, l, c52);
  limb52_to_mont256(c52, g.Z, F);
  return g;
}

// Scalar G1Jac -> one lane (mont256 -> mont260 carrier), inf mask updated.
static void jac8_set_lane(Jac8 &s, int l, const G1Jac &g, const Ifma52Field &F) {
  if (is_zero4(g.Z)) {
    s.inf |= (__mmask8)(1u << l);
    return;
  }
  s.inf &= (__mmask8)~(1u << l);
  u64 t52[5], t260[5];
  limbs4_to_52(t52, g.X);
  mont52_mul_scalar(t260, t52, F.c264, F);
  v8_set_lane52(s.X, l, t260);
  limbs4_to_52(t52, g.Y);
  mont52_mul_scalar(t260, t52, F.c264, F);
  v8_set_lane52(s.Y, l, t260);
  limbs4_to_52(t52, g.Z);
  mont52_mul_scalar(t260, t52, F.c264, F);
  v8_set_lane52(s.Z, l, t260);
}

// Run up to SUFFIX_MAX_LANES windows' suffix walks in lanes (8 per
// group, groups interleaved).  allbk: nwin x nbuckets canonical-mont260
// bucket arrays (all-zero = empty); wis[0..nl_total): the window index
// each lane reduces; outs[l]: that window's sum (Jacobian mont256).
// Up to MAXG groups of 8 window-lanes walk INTERLEAVED inside one
// d-loop: each group's mixed/full adds are a serial mont52_mul8
// dependency chain (~25 muls deep), so consecutive independent groups
// give the out-of-order engine real overlap that back-to-back
// single-group calls cannot.
static constexpr int SUFFIX_MAXG = 3;           // interleaved lane-groups
static constexpr int SUFFIX_MAX_LANES = 8 * SUFFIX_MAXG;  // caller batch cap

static void g1_suffix8(const Aff52 *allbk, long nbuckets, const int *wis,
                       int nl_total, G1Jac *outs) {
  constexpr int MAXG = SUFFIX_MAXG;
  Ifma52Field &F = fq52_field();
  __m512i p[5], p2[5], comp2p[5], onev[5];
  u64 one52[5] = {1, 0, 0, 0, 0}, one260[5];
  mont52_mul_scalar(one260, one52, F.r260sq, F);
  for (int k = 0; k < 5; ++k) {
    p[k] = _mm512_set1_epi64((long long)F.p52[k]);
    p2[k] = _mm512_set1_epi64((long long)F.p2_52[k]);
    comp2p[k] = _mm512_set1_epi64((long long)F.comp2p[k]);
    onev[k] = _mm512_set1_epi64((long long)one260[k]);
  }
  const __m512i pinv = _mm512_set1_epi64((long long)F.pinv52);

  const int ngroups = (nl_total + 7) / 8;
  // Hard bound, not an assert: the fixed-size stack arrays below
  // (nlg/wisg/vbaseg/rung/wsg) are MAXG-sized, and an over-long lane
  // batch must abort even in an NDEBUG build rather than smash the
  // stack.
  if (ngroups > MAXG) {
    fprintf(stderr, "g1_suffix8: %d lanes exceeds SUFFIX_MAX_LANES=%d\n",
            nl_total, SUFFIX_MAX_LANES);
    abort();
  }
  int nlg[MAXG];
  const int *wisg[MAXG];
  __m512i vbaseg[MAXG];
  __mmask8 actg[MAXG];
  alignas(64) long long lane_baseg[MAXG][8];
  for (int g = 0; g < ngroups; ++g) {
    nlg[g] = nl_total - 8 * g > 8 ? 8 : nl_total - 8 * g;
    wisg[g] = wis + 8 * g;
    for (int l = 0; l < 8; ++l) {
      int w = l < nlg[g] ? wisg[g][l] : wisg[g][0];
      lane_baseg[g][l] = (long long)((size_t)w * (size_t)nbuckets * sizeof(Aff52));
    }
    vbaseg[g] = _mm512_load_si512(lane_baseg[g]);
    actg[g] = (__mmask8)((1u << nlg[g]) - 1);
  }

  Jac8 rung[MAXG], wsg[MAXG];
  for (int g = 0; g < ngroups; ++g) {
    for (int k = 0; k < 5; ++k) {
      rung[g].X[k] = rung[g].Y[k] = rung[g].Z[k] = onev[k];
      wsg[g].X[k] = wsg[g].Y[k] = wsg[g].Z[k] = onev[k];
    }
    rung[g].inf = 0xFF;
    wsg[g].inf = 0xFF;
  }

  const char *base_ptr = (const char *)allbk;
  for (long d = nbuckets - 1; d >= 1; --d) {
   for (int gi = 0; gi < ngroups; ++gi) {
    Jac8 &run = rung[gi];
    Jac8 &ws = wsg[gi];
    const __m512i vbase = vbaseg[gi];
    const __mmask8 act_lanes = actg[gi];
    const int nl = nlg[gi];
    const int *wisl = wisg[gi];
    const long long *lane_base = lane_baseg[gi];
    // the walk is perfectly predictable but gather-driven (no hardware
    // prefetch): pull the next TWO steps' bucket lines ahead of time —
    // 8 lanes x 80 B spans two cache lines each
    if (d > 2) {
      for (int l = 0; l < 8; ++l) {
        const char *nx = base_ptr + lane_base[l] + (d - 2) * (long long)sizeof(Aff52);
        _mm_prefetch(nx, _MM_HINT_T0);
        _mm_prefetch(nx + 64, _MM_HINT_T0);
      }
    }
    const __m512i doff = _mm512_add_epi64(
        vbase, _mm512_set1_epi64((long long)d * (long long)sizeof(Aff52)));
    __m512i x2[5], y2[5];
    for (int k = 0; k < 5; ++k) {
      x2[k] = _mm512_i64gather_epi64(
          _mm512_add_epi64(doff, _mm512_set1_epi64(8LL * k)),
          (const long long *)allbk, 1);
      y2[k] = _mm512_i64gather_epi64(
          _mm512_add_epi64(doff, _mm512_set1_epi64(40 + 8LL * k)),
          (const long long *)allbk, 1);
    }
    __mmask8 xz = 0xFF, yz = 0xFF;
    {
      const __m512i zero = _mm512_setzero_si512();
      for (int k = 0; k < 5; ++k) {
        xz &= _mm512_cmpeq_epu64_mask(x2[k], zero);
        yz &= _mm512_cmpeq_epu64_mask(y2[k], zero);
      }
    }
    const __mmask8 nz = act_lanes & (__mmask8)~(xz & yz);
    if (nz) {
      const __mmask8 fresh = nz & run.inf;
      const __mmask8 addm = nz & (__mmask8)~run.inf;
      if (addm) {
        // madd-2007-bl shape, all lanes computed, exceptional ones patched
        __m512i Z1Z1[5], U2[5], S2[5], H[5], Rr[5], HH[5], HHH[5], V[5];
        __m512i t[5], t2[5], X3[5], Y3[5], Z3[5];
        mont52_mul8(Z1Z1, run.Z, run.Z, p, pinv);
        mont52_mul8(U2, x2, Z1Z1, p, pinv);
        mont52_mul8(t, y2, run.Z, p, pinv);
        mont52_mul8(S2, t, Z1Z1, p, pinv);
        sub_lazy8(H, U2, run.X, p2, comp2p);
        sub_lazy8(Rr, S2, run.Y, p2, comp2p);
        const __mmask8 exc = addm & is0_lazy8v(H, p);
        const __mmask8 ok = addm & (__mmask8)~exc;
        mont52_mul8(HH, H, H, p, pinv);
        mont52_mul8(HHH, H, HH, p, pinv);
        mont52_mul8(V, run.X, HH, p, pinv);
        mont52_mul8(t, Rr, Rr, p, pinv);
        sub_lazy8(t, t, HHH, p2, comp2p);
        add_lazy8(t2, V, V, comp2p);
        sub_lazy8(X3, t, t2, p2, comp2p);
        sub_lazy8(t, V, X3, p2, comp2p);
        mont52_mul8(t, Rr, t, p, pinv);
        mont52_mul8(t2, run.Y, HHH, p, pinv);
        sub_lazy8(Y3, t, t2, p2, comp2p);
        mont52_mul8(Z3, run.Z, H, p, pinv);
        for (int k = 0; k < 5; ++k) {
          run.X[k] = _mm512_mask_blend_epi64(ok, run.X[k], X3[k]);
          run.Y[k] = _mm512_mask_blend_epi64(ok, run.Y[k], Y3[k]);
          run.Z[k] = _mm512_mask_blend_epi64(ok, run.Z[k], Z3[k]);
        }
        if (exc) {
          for (int l = 0; l < nl; ++l) {
            if (!((exc >> l) & 1)) continue;
            G1Jac g = jac8_lane(run, l, F);
            const Aff52 &b = allbk[(size_t)wisl[l] * (size_t)nbuckets + d];
            u64 bx4[4], by4[4];
            limb52_to_mont256(b.x, bx4, F);
            limb52_to_mont256(b.y, by4, F);
            jac_add_mixed(g, g, bx4, by4);
            jac8_set_lane(run, l, g, F);
          }
        }
      }
      if (fresh) {
        for (int k = 0; k < 5; ++k) {
          run.X[k] = _mm512_mask_blend_epi64(fresh, run.X[k], x2[k]);
          run.Y[k] = _mm512_mask_blend_epi64(fresh, run.Y[k], y2[k]);
          run.Z[k] = _mm512_mask_blend_epi64(fresh, run.Z[k], onev[k]);
        }
        run.inf &= (__mmask8)~fresh;
      }
    }
    // ws += run (add-2007-bl), lanes with run finite
    const __mmask8 a2 = act_lanes & (__mmask8)~run.inf;
    if (a2) {
      const __mmask8 copy = a2 & ws.inf;
      const __mmask8 addm = a2 & (__mmask8)~ws.inf;
      if (addm) {
        __m512i Z1Z1[5], Z2Z2[5], U1[5], U2[5], S1[5], S2[5], H[5], Rr[5];
        __m512i HH[5], HHH[5], V[5], t[5], t2[5], X3[5], Y3[5], Z3[5];
        mont52_mul8(Z1Z1, ws.Z, ws.Z, p, pinv);
        mont52_mul8(Z2Z2, run.Z, run.Z, p, pinv);
        mont52_mul8(U1, ws.X, Z2Z2, p, pinv);
        mont52_mul8(U2, run.X, Z1Z1, p, pinv);
        mont52_mul8(t, ws.Y, run.Z, p, pinv);
        mont52_mul8(S1, t, Z2Z2, p, pinv);
        mont52_mul8(t, run.Y, ws.Z, p, pinv);
        mont52_mul8(S2, t, Z1Z1, p, pinv);
        sub_lazy8(H, U2, U1, p2, comp2p);
        sub_lazy8(Rr, S2, S1, p2, comp2p);
        const __mmask8 exc = addm & is0_lazy8v(H, p);
        const __mmask8 ok = addm & (__mmask8)~exc;
        mont52_mul8(HH, H, H, p, pinv);
        mont52_mul8(HHH, H, HH, p, pinv);
        mont52_mul8(V, U1, HH, p, pinv);
        mont52_mul8(t, Rr, Rr, p, pinv);
        sub_lazy8(t, t, HHH, p2, comp2p);
        add_lazy8(t2, V, V, comp2p);
        sub_lazy8(X3, t, t2, p2, comp2p);
        sub_lazy8(t, V, X3, p2, comp2p);
        mont52_mul8(t, Rr, t, p, pinv);
        mont52_mul8(t2, S1, HHH, p, pinv);
        sub_lazy8(Y3, t, t2, p2, comp2p);
        mont52_mul8(t, ws.Z, run.Z, p, pinv);
        mont52_mul8(Z3, t, H, p, pinv);
        for (int k = 0; k < 5; ++k) {
          ws.X[k] = _mm512_mask_blend_epi64(ok, ws.X[k], X3[k]);
          ws.Y[k] = _mm512_mask_blend_epi64(ok, ws.Y[k], Y3[k]);
          ws.Z[k] = _mm512_mask_blend_epi64(ok, ws.Z[k], Z3[k]);
        }
        if (exc) {
          for (int l = 0; l < nl; ++l) {
            if (!((exc >> l) & 1)) continue;
            G1Jac g = jac8_lane(ws, l, F);
            G1Jac r = jac8_lane(run, l, F);
            g1_add_jac(g, r);
            jac8_set_lane(ws, l, g, F);
          }
        }
      }
      if (copy) {
        for (int k = 0; k < 5; ++k) {
          ws.X[k] = _mm512_mask_blend_epi64(copy, ws.X[k], run.X[k]);
          ws.Y[k] = _mm512_mask_blend_epi64(copy, ws.Y[k], run.Y[k]);
          ws.Z[k] = _mm512_mask_blend_epi64(copy, ws.Z[k], run.Z[k]);
        }
        ws.inf &= (__mmask8)~copy;
      }
    }
   }
  }
  for (int g = 0; g < ngroups; ++g)
    for (int l = 0; l < nlg[g]; ++l) outs[8 * g + l] = jac8_lane(wsg[g], l, F);
}

// 52-native batch-affine window fill: buckets AND bases in mont260
// 52-limb form.  `bases_xy` (mont256) is still taken for the Jacobian
// bail tier.
// Returns true when `bk_ext` (caller-zeroed, nbuckets entries) was filled
// and the caller must reduce it (the vectorized cross-window suffix);
// false when *out was already computed via a fallback tier (small/top
// window, conflict bail) or the internal suffix (bk_ext == nullptr).
static bool g1_window_sum_52(const u64 *bases_xy, const Aff52 *b52,
                             const int32_t *sd, long n, int c, int nwin,
                             int wi, G1Jac *out, Aff52 *bk_ext = nullptr,
                             int total_bits = 254) {
  Ifma52Field &F = fq52_field();
  const long nbuckets = (1L << (c - 1)) + 1;
  const long B = 2048;
  int bits_here = total_bits - wi * c;
  if (bits_here > c) bits_here = c;
  if (bits_here < 1 || (1L << bits_here) < 4 * B) {
    // bits_here == 0 is the GLV carry-only top window (GLV_MAX_BITS
    // divisible by c, e.g. 128 at c=16): digits are +-1 recoding
    // carries, exactly the few-buckets-many-points shape the small
    // path tree-sums (its nbuckets = (1<<bits)+2 headroom covers it).
    if (bits_here >= 0 && bits_here <= 8) {
      g1_window_sum_small(bases_xy, sd, n, c, nwin, wi, bits_here, out);
    } else {
      g1_window_sum_jac(bases_xy, sd, n, c, nwin, wi, out);
    }
    return false;
  }
  Aff52 *bk = bk_ext ? bk_ext : new Aff52[nbuckets]();
  int *stamp = new int[nbuckets];
  memset(stamp, 0xff, nbuckets * sizeof(int));
  std::vector<long> cur, next;
  cur.reserve(n);
  for (long i = 0; i < n; ++i) {
    if (!sd[i * nwin + wi]) continue;
    if (aff52_is_zero(b52[i].x) && aff52_is_zero(b52[i].y)) continue;
    cur.push_back(i);
  }
  long *add_bkt = new long[B];
  long *add_pt = new long[B];
  unsigned char *negf = new unsigned char[B];
  u64 (*x3a)[5] = new u64[B][5];
  u64 (*y3a)[5] = new u64[B][5];
  unsigned char *dbl = new unsigned char[B];
  u64 *scratch = new u64[(size_t)8 * 5 * B];
  auto cleanup = [&]() {
    if (!bk_ext) delete[] bk;
    delete[] stamp;
    delete[] add_bkt;
    delete[] add_pt;
    delete[] negf;
    delete[] x3a;
    delete[] y3a;
    delete[] dbl;
    delete[] scratch;
  };
  int chunk_id = 0;
  // stats: lane hits tallied in plain locals, flushed once per window —
  // the schedule loop itself must stay free of atomics
  long long n_dbl = 0, n_cancel = 0, n_defer = 0;
  long long fl0 = prof_now_ns();
  while (!cur.empty()) {
    next.clear();
    size_t processed = 0;
    bool bail = false;
    const bool pf = msm_interleave_enabled();
    for (size_t lo = 0; lo < cur.size() && !bail; lo += B, ++chunk_id) {
      size_t hi = lo + B < cur.size() ? lo + B : cur.size();
      long m = 0;
      for (size_t k = lo; k < hi; ++k) {
        // Two-level prefetch down the schedule: pull the digit word
        // first (far), then — once it is cheap to read — the dependent
        // stamp/bucket/base lines (near).  The bucket table and the
        // bases both sit beyond L2 at bench shape and the index
        // pattern is hardware-prefetch-blind.
        if (pf) {
          if (k + 32 < hi)
            _mm_prefetch((const char *)&sd[cur[k + 32] * nwin + wi],
                         _MM_HINT_T0);
          if (k + 16 < hi) {
            const long i2 = cur[k + 16];
            const int32_t d2 = sd[i2 * nwin + wi];
            const long b2 = d2 < 0 ? -d2 : d2;
            _mm_prefetch((const char *)&stamp[b2], _MM_HINT_T0);
            const char *pb = (const char *)&bk[b2];
            _mm_prefetch(pb, _MM_HINT_T0);
            _mm_prefetch(pb + 64, _MM_HINT_T0);
            const char *pp = (const char *)&b52[i2];
            _mm_prefetch(pp, _MM_HINT_T0);
            _mm_prefetch(pp + 64, _MM_HINT_T0);
          }
        }
        long i = cur[k];
        int32_t dgt = sd[i * nwin + wi];
        long bno = dgt < 0 ? -dgt : dgt;
        if (stamp[bno] == chunk_id) {
          next.push_back(i);
          ++n_defer;
          continue;
        }
        stamp[bno] = chunk_id;
        u64 py[5];
        if (dgt < 0) {
          neg52(py, b52[i].y, F);
        } else {
          memcpy(py, b52[i].y, 40);
        }
        if (aff52_is_zero(bk[bno].x) && aff52_is_zero(bk[bno].y)) {
          memcpy(bk[bno].x, b52[i].x, 40);
          memcpy(bk[bno].y, py, 40);
          continue;
        }
        if (memcmp(bk[bno].x, b52[i].x, 40) == 0) {
          if (memcmp(bk[bno].y, py, 40) == 0) {
            dbl[m] = 1;
            ++n_dbl;
          } else {
            memset(&bk[bno], 0, sizeof(Aff52));  // P + (-P)
            ++n_cancel;
            continue;
          }
        } else {
          dbl[m] = 0;
        }
        add_bkt[m] = bno;
        add_pt[m] = i;
        negf[m] = dgt < 0 ? 1 : 0;
        ++m;
      }
      processed = hi;
      if (!m) {
        if (next.size() * 2 > processed && processed >= (size_t)B) bail = true;
        continue;
      }
      long long ap0 = prof_now_ns();
      g1_chunk_apply_52(bk, b52, add_bkt, add_pt, negf, dbl, m, x3a, y3a, scratch);
      long long ap = prof_now_ns() - ap0;
      stat_add(ST_MSM_APPLY_NS, ap);
      if (msm_prof_enabled()) g_prof_apply_ns += ap;
      for (long j = 0; j < m; ++j) {
        // write-prefetch the bucket lines ahead: the chunk's working
        // set (~B x 160 B of buckets + scratch) evicted them since the
        // gather, so every writeback otherwise eats an RFO miss
        if (pf && j + 8 < m) {
          char *wb = (char *)&bk[add_bkt[j + 8]];
          __builtin_prefetch(wb, 1);
          __builtin_prefetch(wb + 64, 1);
        }
        memcpy(bk[add_bkt[j]].x, x3a[j], 40);
        memcpy(bk[add_bkt[j]].y, y3a[j], 40);
      }
      if (next.size() * 2 > processed && processed >= (size_t)B) bail = true;
    }
    if (bail || next.size() * 4 > cur.size()) {
      long long fl = prof_now_ns() - fl0;
      stat_add(ST_MSM_FILL_NS, fl);
      if (msm_prof_enabled()) g_prof_fill_ns += fl;
      stat_add(ST_MSM_DBL_LANES, n_dbl);
      stat_add(ST_MSM_CANCEL_LANES, n_cancel);
      stat_add(ST_MSM_DEFER_HITS, n_defer);
      long long bs0 = prof_now_ns();
      G1Jac *jb = new G1Jac[nbuckets];
      memset(jb, 0, (size_t)nbuckets * sizeof(G1Jac));
      next.insert(next.end(), cur.begin() + processed, cur.end());
      for (size_t bi = 0; bi < next.size(); ++bi) {
        // prefetch the next few adds' base/bucket lines: one Jacobian
        // mixed add (~16 scalar muls) is long enough to hide the miss
        if (pf && bi + 2 < next.size()) {
          const long i3 = next[bi + 2];
          const int32_t d3 = sd[i3 * nwin + wi];
          const char *px = (const char *)(bases_xy + 8 * i3);
          _mm_prefetch(px, _MM_HINT_T0);
          _mm_prefetch((const char *)&jb[d3 < 0 ? -d3 : d3], _MM_HINT_T0);
        }
        const long i = next[bi];
        int32_t dgt = sd[i * nwin + wi];
        long bno = dgt < 0 ? -dgt : dgt;
        const u64 *x = bases_xy + 8 * i;
        u64 ys[4];
        signed_pt_y(ys, x + 4, dgt < 0);
        jac_add_mixed(jb[bno], jb[bno], x, ys);
      }
      {
        long long bf = prof_now_ns() - bs0;
        stat_add(ST_MSM_BAILFILL_NS, bf);
        if (msm_prof_enabled()) g_prof_bailfill_ns += bf;
        bs0 = prof_now_ns();
      }
      G1Jac run, wsum;
      memset(&run, 0, sizeof(run));
      memset(&wsum, 0, sizeof(wsum));
      for (long d = nbuckets - 1; d >= 1; --d) {
        g1_add_jac(run, jb[d]);
        if (!(aff52_is_zero(bk[d].x) && aff52_is_zero(bk[d].y))) {
          u64 bx[4], by[4];
          limb52_to_mont256(bk[d].x, bx, F);
          limb52_to_mont256(bk[d].y, by, F);
          jac_add_mixed(run, run, bx, by);
        }
        g1_add_jac(wsum, run);
      }
      {
        long long sf = prof_now_ns() - bs0;
        stat_add(ST_MSM_SUFFIX_NS, sf);
        if (msm_prof_enabled()) g_prof_suffix_ns += sf;
      }
      delete[] jb;
      cleanup();
      *out = wsum;
      return false;
    }
    cur.swap(next);
  }
  {
    long long fl = prof_now_ns() - fl0;  // incl. apply; sched = fill - apply
    stat_add(ST_MSM_FILL_NS, fl);
    if (msm_prof_enabled()) g_prof_fill_ns += fl;
    stat_add(ST_MSM_DBL_LANES, n_dbl);
    stat_add(ST_MSM_CANCEL_LANES, n_cancel);
    stat_add(ST_MSM_DEFER_HITS, n_defer);
  }
  if (bk_ext) {
    // caller reduces this window through the 8-lane vector suffix
    cleanup();
    return true;
  }
  long long sf0 = prof_now_ns();
  G1Jac run, wsum;
  memset(&run, 0, sizeof(run));
  memset(&wsum, 0, sizeof(wsum));
  for (long d = nbuckets - 1; d >= 1; --d) {
    if (!(aff52_is_zero(bk[d].x) && aff52_is_zero(bk[d].y))) {
      u64 bx[4], by[4];
      limb52_to_mont256(bk[d].x, bx, F);
      limb52_to_mont256(bk[d].y, by, F);
      jac_add_mixed(run, run, bx, by);
    }
    g1_add_jac(wsum, run);
  }
  {
    long long sf = prof_now_ns() - sf0;
    stat_add(ST_MSM_SUFFIX_NS, sf);
    if (msm_prof_enabled()) g_prof_suffix_ns += sf;
  }
  cleanup();
  *out = wsum;
  return false;
}

// ---- Fq2 vector helpers (u^2 = -1): componentwise lazy-domain ops on
// top of mont52_mul8.  An Fq2 value is two limb-vector sets (c0, c1).

static inline void fq2_mul8(__m512i o0[5], __m512i o1[5],
                            const __m512i a0[5], const __m512i a1[5],
                            const __m512i b0[5], const __m512i b1[5],
                            const __m512i p[5], const __m512i p2[5],
                            const __m512i comp2p[5], const __m512i pinv) {
  // Karatsuba over the tower: t0=a0b0, t1=a1b1, t2=(a0+a1)(b0+b1)
  __m512i t0[5], t1[5], t2[5], sa[5], sb[5];
  mont52_mul8(t0, a0, b0, p, pinv);
  mont52_mul8(t1, a1, b1, p, pinv);
  add_lazy8(sa, a0, a1, comp2p);
  add_lazy8(sb, b0, b1, comp2p);
  mont52_mul8(t2, sa, sb, p, pinv);
  sub_lazy8(o0, t0, t1, p2, comp2p);            // a0b0 - a1b1
  sub_lazy8(t2, t2, t0, p2, comp2p);
  sub_lazy8(o1, t2, t1, p2, comp2p);            // a0b1 + a1b0
}

static inline void fq2_sqr8(__m512i o0[5], __m512i o1[5],
                            const __m512i a0[5], const __m512i a1[5],
                            const __m512i p[5], const __m512i p2[5],
                            const __m512i comp2p[5], const __m512i pinv) {
  // (a0+a1u)^2 = (a0+a1)(a0-a1) + 2 a0 a1 u
  __m512i s[5], d[5], m[5];
  add_lazy8(s, a0, a1, comp2p);
  sub_lazy8(d, a0, a1, p2, comp2p);
  mont52_mul8(o0, s, d, p, pinv);
  mont52_mul8(m, a0, a1, p, pinv);
  add_lazy8(o1, m, m, comp2p);
}

// The G2 mirror of g1_chunk_apply_ifma: every array carries TWO Fq
// components per value ((m,8) u64 rows: c0 then c1).  Batch inversion
// rides the NORM route (1/z = conj(z)/(c0^2+c1^2)): prefix/suffix over
// Fq norms + ONE scalar Fq2 inversion per chunk — fewer vector muls
// than an Fq2 product chain.  Outputs canonical (< p) per component so
// the caller's memcmp bucket checks keep working.
static void g2_chunk_apply_ifma(const u64 (*x1a)[8], const u64 (*y1a)[8],
                                const u64 (*x2a)[8], const u64 (*y2a)[8],
                                const unsigned char *dbl, long m,
                                u64 (*x3a)[8], u64 (*y3a)[8], u64 *buf) {
  Ifma52Field &F = fq52_field();
  const long nblk = (m + 7) / 8, N = nblk * 8;
  // SoA planes per COMPONENT: x1/y1/x2/y2/den/num (2 comps each) +
  // norm-prefix (1) + x3/y3 (2 each) = 17 arrays x 5 planes x N
  u64 *x10 = buf, *x11 = buf + (size_t)5 * N;
  u64 *y10 = buf + (size_t)10 * N, *y11 = buf + (size_t)15 * N;
  u64 *x20 = buf + (size_t)20 * N, *x21 = buf + (size_t)25 * N;
  u64 *y20 = buf + (size_t)30 * N, *y21 = buf + (size_t)35 * N;
  u64 *d0 = buf + (size_t)40 * N, *d1 = buf + (size_t)45 * N;
  u64 *n0 = buf + (size_t)50 * N, *n1 = buf + (size_t)55 * N;
  u64 *pr = buf + (size_t)60 * N;
  u64 *x30 = buf + (size_t)65 * N, *x31 = buf + (size_t)70 * N;
  u64 *y30 = buf + (size_t)75 * N, *y31 = buf + (size_t)80 * N;

  u64 one52[5] = {1, 0, 0, 0, 0}, one260[5];
  mont52_mul_scalar(one260, one52, F.r260sq, F);
  auto pack_comp = [&](const u64 (*src)[8], int comp, u64 *dst) {
    for (long j = 0; j < N; ++j) {
      u64 t[5] = {0, 0, 0, 0, 0};
      if (j < m) limbs4_to_52(t, src[j] + 4 * comp);
      for (int k = 0; k < 5; ++k) dst[(size_t)k * N + j] = t[k];
    }
  };
  pack_comp(x1a, 0, x10); pack_comp(x1a, 1, x11);
  pack_comp(y1a, 0, y10); pack_comp(y1a, 1, y11);
  pack_comp(x2a, 0, x20); pack_comp(x2a, 1, x21);
  pack_comp(y2a, 0, y20); pack_comp(y2a, 1, y21);

  __m512i p[5], p2[5], comp2p[5], c264v[5], c256v[5];
  for (int k = 0; k < 5; ++k) {
    p[k] = _mm512_set1_epi64((long long)F.p52[k]);
    p2[k] = _mm512_set1_epi64((long long)F.p2_52[k]);
    comp2p[k] = _mm512_set1_epi64((long long)F.comp2p[k]);
    c264v[k] = _mm512_set1_epi64((long long)F.c264[k]);
    c256v[k] = _mm512_set1_epi64((long long)F.c256[k]);
  }
  const __m512i pinv = _mm512_set1_epi64((long long)F.pinv52);
  auto loadv = [&](const u64 *base, long off, __m512i v[5]) {
    for (int k = 0; k < 5; ++k) v[k] = _mm512_loadu_si512(base + (size_t)k * N + off);
  };
  auto storev = [&](u64 *base, long off, const __m512i v[5]) {
    for (int k = 0; k < 5; ++k) _mm512_storeu_si512(base + (size_t)k * N + off, v[k]);
  };
  // carrier 256 -> 260 + derive num/den per block
  for (long t = 0; t < nblk; ++t) {
    u64 *comps[8] = {x10, x11, y10, y11, x20, x21, y20, y21};
    __m512i cv[8][5];
    for (int a = 0; a < 8; ++a) {
      __m512i v[5];
      loadv(comps[a], t * 8, v);
      mont52_mul8(cv[a], v, c264v, p, pinv);
      storev(comps[a], t * 8, cv[a]);
    }
    __m512i dv0[5], dv1[5], nv0[5], nv1[5];
    sub_lazy8(dv0, cv[4], cv[0], p2, comp2p);  // x2 - x1 (c0)
    sub_lazy8(dv1, cv[5], cv[1], p2, comp2p);  // (c1)
    sub_lazy8(nv0, cv[6], cv[2], p2, comp2p);  // y2 - y1 (c0)
    sub_lazy8(nv1, cv[7], cv[3], p2, comp2p);
    unsigned char dm = 0;
    for (int l = 0; l < 8 && t * 8 + l < m; ++l)
      if (dbl[t * 8 + l]) dm |= (unsigned char)(1u << l);
    if (dm) {
      // doubling: num = 3 x1^2, den = 2 y1 (component-wise over Fq2)
      __m512i sq0[5], sq1[5], nd0[5], nd1[5], dd0[5], dd1[5];
      fq2_sqr8(sq0, sq1, cv[0], cv[1], p, p2, comp2p, pinv);
      add_lazy8(nd0, sq0, sq0, comp2p);
      add_lazy8(nd0, nd0, sq0, comp2p);
      add_lazy8(nd1, sq1, sq1, comp2p);
      add_lazy8(nd1, nd1, sq1, comp2p);
      add_lazy8(dd0, cv[2], cv[2], comp2p);
      add_lazy8(dd1, cv[3], cv[3], comp2p);
      const __mmask8 k = (__mmask8)dm;
      for (int q = 0; q < 5; ++q) {
        dv0[q] = _mm512_mask_blend_epi64(k, dv0[q], dd0[q]);
        dv1[q] = _mm512_mask_blend_epi64(k, dv1[q], dd1[q]);
        nv0[q] = _mm512_mask_blend_epi64(k, nv0[q], nd0[q]);
        nv1[q] = _mm512_mask_blend_epi64(k, nv1[q], nd1[q]);
      }
    }
    storev(d0, t * 8, dv0); storev(d1, t * 8, dv1);
    storev(n0, t * 8, nv0); storev(n1, t * 8, nv1);
  }
  // phase A: prefix products over the Fq NORMS (norm = d0^2 + d1^2);
  // padded lanes get norm ONE via a blend
  __m512i run[5];
  for (int k = 0; k < 5; ++k) run[k] = _mm512_set1_epi64((long long)one260[k]);
  for (long t = 0; t < nblk; ++t) {
    __m512i dv0[5], dv1[5], s0[5], s1[5], norm[5];
    loadv(d0, t * 8, dv0); loadv(d1, t * 8, dv1);
    mont52_mul8(s0, dv0, dv0, p, pinv);
    mont52_mul8(s1, dv1, dv1, p, pinv);
    add_lazy8(norm, s0, s1, comp2p);
    if (t == nblk - 1 && m < N) {
      __mmask8 padk = (__mmask8)(0xFFu << (m & 7 ? (m & 7) : 8));
      for (int q = 0; q < 5; ++q)
        norm[q] = _mm512_mask_blend_epi64(padk, norm[q], _mm512_set1_epi64((long long)one260[q]));
    }
    storev(pr, t * 8, run);  // product of norms BEFORE this block's lanes
    // interleave: we need a LANE-STRIDED chain like g1 — run *= norm
    mont52_mul8(run, run, norm, p, pinv);
    // stash the norm where den c0 plane... norms are recomputed in
    // phase B, so nothing extra to store
  }
  // one scalar Fq2-ish inversion: invert the 8 lane-total NORMS in Fq
  u64 tl8[5][8];
  for (int k = 0; k < 5; ++k) _mm512_storeu_si512(tl8[k], run[k]);
  u64 T4[8][4];
  for (int l = 0; l < 8; ++l) {
    u64 t52[5], t256[5];
    for (int k = 0; k < 5; ++k) t52[k] = tl8[k][l];
    mont52_mul_scalar(t256, t52, F.c256, F);
    limbs52_to_4(T4[l], t256);
    while (geq(T4[l], P)) sub_nored(T4[l], T4[l], P);
  }
  u64 pre8[8][4], G[4], Ginv[4], suf[4], Tinv[8][4];
  memcpy(pre8[0], ONE_MONT, 32);
  for (int l = 1; l < 8; ++l) mont_mul(pre8[l], pre8[l - 1], T4[l - 1]);
  mont_mul(G, pre8[7], T4[7]);
  mont_inv(Ginv, G);
  memcpy(suf, Ginv, 32);
  for (int l = 7; l >= 0; --l) {
    mont_mul(Tinv[l], suf, pre8[l]);
    mont_mul(suf, suf, T4[l]);
  }
  __m512i inv_run[5];
  {
    u64 ir8[5][8];
    for (int l = 0; l < 8; ++l) {
      u64 t52[5], t260[5];
      limbs4_to_52(t52, Tinv[l]);
      mont52_mul_scalar(t260, t52, F.c264, F);
      for (int k = 0; k < 5; ++k) ir8[k][l] = t260[k];
    }
    for (int k = 0; k < 5; ++k) inv_run[k] = _mm512_loadu_si512(ir8[k]);
  }
  // phase B backwards: norm_inv -> dinv = conj(den) * norm_inv -> apply
  for (long t = nblk - 1; t >= 0; --t) {
    __m512i prv[5], dv0[5], dv1[5], s0[5], s1[5], norm[5];
    loadv(pr, t * 8, prv);
    loadv(d0, t * 8, dv0); loadv(d1, t * 8, dv1);
    mont52_mul8(s0, dv0, dv0, p, pinv);
    mont52_mul8(s1, dv1, dv1, p, pinv);
    add_lazy8(norm, s0, s1, comp2p);
    if (t == nblk - 1 && m < N) {
      __mmask8 padk = (__mmask8)(0xFFu << (m & 7 ? (m & 7) : 8));
      for (int q = 0; q < 5; ++q)
        norm[q] = _mm512_mask_blend_epi64(padk, norm[q], _mm512_set1_epi64((long long)one260[q]));
    }
    __m512i ninv[5];
    mont52_mul8(ninv, inv_run, prv, p, pinv);    // 1/norm for these lanes
    mont52_mul8(inv_run, inv_run, norm, p, pinv);
    // dinv = (d0 - d1 u) * ninv
    __m512i di0[5], di1[5], zt[5];
    mont52_mul8(di0, dv0, ninv, p, pinv);
    mont52_mul8(zt, dv1, ninv, p, pinv);
    // negate: 2p - x (lazy) via sub_lazy8 from zero
    __m512i zero5[5];
    for (int k = 0; k < 5; ++k) zero5[k] = _mm512_setzero_si512();
    sub_lazy8(di1, zero5, zt, p2, comp2p);
    __m512i nv0[5], nv1[5], x1v0[5], x1v1[5], y1v0[5], y1v1[5], x2v0[5], x2v1[5];
    loadv(n0, t * 8, nv0); loadv(n1, t * 8, nv1);
    loadv(x10, t * 8, x1v0); loadv(x11, t * 8, x1v1);
    loadv(y10, t * 8, y1v0); loadv(y11, t * 8, y1v1);
    loadv(x20, t * 8, x2v0); loadv(x21, t * 8, x2v1);
    __m512i lam0[5], lam1[5], l20[5], l21[5], x3v0[5], x3v1[5], tt0[5], tt1[5], yy0[5], yy1[5], y3v0[5], y3v1[5];
    fq2_mul8(lam0, lam1, nv0, nv1, di0, di1, p, p2, comp2p, pinv);
    fq2_sqr8(l20, l21, lam0, lam1, p, p2, comp2p, pinv);
    sub_lazy8(x3v0, l20, x1v0, p2, comp2p);
    sub_lazy8(x3v1, l21, x1v1, p2, comp2p);
    sub_lazy8(x3v0, x3v0, x2v0, p2, comp2p);
    sub_lazy8(x3v1, x3v1, x2v1, p2, comp2p);
    sub_lazy8(tt0, x1v0, x3v0, p2, comp2p);
    sub_lazy8(tt1, x1v1, x3v1, p2, comp2p);
    fq2_mul8(yy0, yy1, lam0, lam1, tt0, tt1, p, p2, comp2p, pinv);
    sub_lazy8(y3v0, yy0, y1v0, p2, comp2p);
    sub_lazy8(y3v1, yy1, y1v1, p2, comp2p);
    // carrier back to 256
    mont52_mul8(x3v0, x3v0, c256v, p, pinv);
    mont52_mul8(x3v1, x3v1, c256v, p, pinv);
    mont52_mul8(y3v0, y3v0, c256v, p, pinv);
    mont52_mul8(y3v1, y3v1, c256v, p, pinv);
    storev(x30, t * 8, x3v0); storev(x31, t * 8, x3v1);
    storev(y30, t * 8, y3v0); storev(y31, t * 8, y3v1);
  }
  // unpack, fully reduced
  auto unpack_comp = [&](const u64 *src, u64 (*dst)[8], int comp) {
    for (long j = 0; j < m; ++j) {
      u64 t[5], o[4];
      for (int k = 0; k < 5; ++k) t[k] = src[(size_t)k * N + j];
      limbs52_to_4(o, t);
      while (geq(o, P)) sub_nored(o, o, P);
      memcpy(dst[j] + 4 * comp, o, 32);
    }
  };
  unpack_comp(x30, x3a, 0); unpack_comp(x31, x3a, 1);
  unpack_comp(y30, y3a, 0); unpack_comp(y31, y3a, 1);
}

// G2 pairwise tree sum (the scalar==±1 fast path, Fq2 mirror of
// g1_tree_sum).  xs/ys rows are (c0, c1) pairs = 8 u64; consumed.
static void g2_tree_sum(u64 (*xs)[8], u64 (*ys)[8], long n, G2Jac *out) {
  memset(out, 0, sizeof(G2Jac));
  if (n <= 0) return;
  auto is_inf = [](const u64 *x, const u64 *y) {
    return is_zero4(x) && is_zero4(x + 4) && is_zero4(y) && is_zero4(y + 4);
  };
  auto add_into = [&](const u64 *x, const u64 *y) {
    Fp2 xx, yy;
    memcpy(xx.c0, x, 32); memcpy(xx.c1, x + 4, 32);
    memcpy(yy.c0, y, 32); memcpy(yy.c1, y + 4, 32);
    g2_add_mixed(*out, *out, xx, yy);
  };
  if (ifma_enabled() && n >= 64) {
    const long B = 1024;
    u64 (*x1a)[8] = new u64[B][8];
    u64 (*y1a)[8] = new u64[B][8];
    u64 (*x2a)[8] = new u64[B][8];
    u64 (*y2a)[8] = new u64[B][8];
    u64 (*x3a)[8] = new u64[B][8];
    u64 (*y3a)[8] = new u64[B][8];
    unsigned char *dbl = new unsigned char[B];
    u64 *scratch = new u64[(size_t)17 * 5 * B];
    while (n > 1) {
      long w = 0, ppos = 0;
      while (ppos + 1 < n) {
        long m = 0;
        while (ppos + 1 < n && m < B) {
          u64 *x1 = xs[ppos], *y1 = ys[ppos], *x2 = xs[ppos + 1], *y2 = ys[ppos + 1];
          bool i1 = is_inf(x1, y1), i2 = is_inf(x2, y2);
          if (i1 && i2) { ppos += 2; continue; }
          if (i1 || i2) {
            memcpy(xs[w], i1 ? x2 : x1, 64);
            memcpy(ys[w], i1 ? y2 : y1, 64);
            ++w; ppos += 2; continue;
          }
          if (memcmp(x1, x2, 64) == 0) {
            if (memcmp(y1, y2, 64) == 0) {
              dbl[m] = 1;
            } else {
              ppos += 2; continue;  // P + (-P)
            }
          } else {
            dbl[m] = 0;
          }
          memcpy(x1a[m], x1, 64);
          memcpy(y1a[m], y1, 64);
          memcpy(x2a[m], x2, 64);
          memcpy(y2a[m], y2, 64);
          ++m; ppos += 2;
        }
        if (m > 0) {
          g2_chunk_apply_ifma(x1a, y1a, x2a, y2a, dbl, m, x3a, y3a, scratch);
          for (long j = 0; j < m; ++j) {
            memcpy(xs[w], x3a[j], 64);
            memcpy(ys[w], y3a[j], 64);
            ++w;
          }
        }
      }
      if (ppos < n) {
        memcpy(xs[w], xs[ppos], 64);
        memcpy(ys[w], ys[ppos], 64);
        ++w;
      }
      n = w;
    }
    delete[] x1a; delete[] y1a; delete[] x2a; delete[] y2a;
    delete[] x3a; delete[] y3a; delete[] dbl; delete[] scratch;
    if (n == 1 && !is_inf(xs[0], ys[0])) add_into(xs[0], ys[0]);
    return;
  }
  for (long i = 0; i < n; ++i) {
    if (!is_inf(xs[i], ys[i])) add_into(xs[i], ys[i]);
  }
}

#else
#define ZKP2P_HAVE_IFMA 0
static bool ifma_enabled() { return false; }
#endif  // __AVX512IFMA__

#if ZKP2P_HAVE_IFMA
// One 8-row step of the Fr batch-pass vector tier: pack 8 contiguous
// (4 u64) rows to 52-limb lanes, multiply by one or two mont260
// constant vectors (carrier bookkeeping lives in the CALLER's constant
// choice), canonical-fold, unpack.  Shared by the batch mul/convert
// passes below — each was a scalar fr_mul-per-row loop on the prove
// path (m rows each: the pointwise Cz product, the witness to-mont, the
// ladder's d from-mont), together ~3 full scalar Montgomery passes per
// proof.
static inline void fr_batch8_mul2(const u64 *a8, const __m512i *b52,
                                  const __m512i c1[5], const __m512i c2[5],
                                  const __m512i p[5], const __m512i pinv,
                                  const __m512i comppv[5], u64 *out8) {
  u64 tmp[5][8];
  for (int l = 0; l < 8; ++l) {
    u64 t[5];
    limbs4_to_52(t, a8 + 4 * l);
    for (int k = 0; k < 5; ++k) tmp[k][l] = t[k];
  }
  __m512i x[5], y[5];
  for (int k = 0; k < 5; ++k) x[k] = _mm512_loadu_si512(tmp[k]);
  if (b52 != nullptr) {
    mont52_mul8(y, x, b52, p, pinv);
  } else {
    for (int k = 0; k < 5; ++k) y[k] = x[k];
  }
  mont52_mul8(x, y, c1, p, pinv);
  if (c2 != nullptr) {
    mont52_mul8(y, x, c2, p, pinv);
  } else {
    for (int k = 0; k < 5; ++k) y[k] = x[k];
  }
  cond_sub_c8(y, comppv);  // canonical (< r): callers' memcmp contracts
  for (int k = 0; k < 5; ++k) _mm512_storeu_si512(tmp[k], y[k]);
  for (int l = 0; l < 8; ++l) {
    u64 t[5], o[4];
    for (int k = 0; k < 5; ++k) t[k] = tmp[k][l];
    limbs52_to_4(o, t);
    memcpy(out8 + 4 * l, o, 32);
  }
}

// The Fr batch-pass tier gate: vector core present AND the pool knob on
// (ZKP2P_NTT_POOL gates the whole Fr vector-batch tier — stages, fused
// ladder, and these passes — so the knob-off arm reproduces the full
// pre-tier scalar path for A/Bs).
static bool fr_batch_vector_on(long n) {
  return ifma_enabled() && ntt_pool_enabled() && n >= 256;
}
#endif  // ZKP2P_HAVE_IFMA

extern "C" {

// Batch std <-> Montgomery over r.  IFMA tier (pool-split, 8-wide):
// to-mont multiplies by 2^520 then the 2^256 carrier (in·2^260·2^-4 =
// in·2^256); from-mont is ONE mul by the plain constant 16
// (in·16·2^-260 = in·2^-256) — both exactly the scalar results,
// canonically reduced.
void fr_to_mont_batch(const u64 *in, u64 *out, long n) {
#if ZKP2P_HAVE_IFMA
  if (fr_batch_vector_on(n)) {
    Ifma52Field &F = fr52_field();
    __m512i p[5], comppv[5], c1[5], c2[5];
    for (int k = 0; k < 5; ++k) {
      p[k] = _mm512_set1_epi64((long long)F.p52[k]);
      comppv[k] = _mm512_set1_epi64((long long)F.compp[k]);
      c1[k] = _mm512_set1_epi64((long long)F.r260sq[k]);
      c2[k] = _mm512_set1_epi64((long long)F.c256[k]);
    }
    const __m512i pinv = _mm512_set1_epi64((long long)F.pinv52);
    long nblk = n / 8;
    pool_parallel_ranges(nblk, 1024, pool_default_threads(), [&](long lo, long hi) {
      for (long b = lo; b < hi; ++b)
        fr_batch8_mul2(in + 32 * b, nullptr, c1, c2, p, pinv, comppv, out + 32 * b);
    });
    for (long i = nblk * 8; i < n; ++i) fr_mul(out + 4 * i, in + 4 * i, R2R);
    return;
  }
#endif
  for (long i = 0; i < n; ++i) fr_mul(out + 4 * i, in + 4 * i, R2R);
}
void fr_from_mont_batch(const u64 *in, u64 *out, long n) {
  static const u64 ONE_STD[4] = {1, 0, 0, 0};
#if ZKP2P_HAVE_IFMA
  if (fr_batch_vector_on(n)) {
    Ifma52Field &F = fr52_field();
    __m512i p[5], comppv[5], c1[5];
    for (int k = 0; k < 5; ++k) {
      p[k] = _mm512_set1_epi64((long long)F.p52[k]);
      comppv[k] = _mm512_set1_epi64((long long)F.compp[k]);
      c1[k] = _mm512_set1_epi64(k == 0 ? 16LL : 0LL);  // 2^4: 260 -> 256
    }
    const __m512i pinv = _mm512_set1_epi64((long long)F.pinv52);
    long nblk = n / 8;
    pool_parallel_ranges(nblk, 1024, pool_default_threads(), [&](long lo, long hi) {
      for (long b = lo; b < hi; ++b)
        fr_batch8_mul2(in + 32 * b, nullptr, c1, nullptr, p, pinv, comppv, out + 32 * b);
    });
    for (long i = nblk * 8; i < n; ++i) fr_mul(out + 4 * i, in + 4 * i, ONE_STD);
    return;
  }
#endif
  for (long i = 0; i < n; ++i) fr_mul(out + 4 * i, in + 4 * i, ONE_STD);
}
// In-place x mod r for n rows of 4 u64, any x < 2^256.  The witness
// conversion hot loop (docs/NEXT.md lever 3): Python now serializes raw
// int bytes and this replaces the per-element bigint `w % R`.  Since
// 2^256 / r ~ 5.3 the loop runs at most 5 conditional subtracts, and
// the common already-reduced row exits on the first compare — the pass
// is memory-bound, so no vector tier applies (the IFMA build runs this
// same scalar loop; a transposed 8-wide compare-subtract was modeled
// and the limb shuffles alone exceed the subtract work).
void fr_reduce_batch(u64 *inout, long n) {
  for (long i = 0; i < n; ++i) {
    u64 *v = inout + 4 * i;
    while (geq(v, R_MOD)) sub_nored(v, v, R_MOD);
  }
}

// Pointwise Montgomery product (c_ev = a_ev . b_ev).  IFMA tier: two
// mul8 per 8 rows (a·b·2^-260 = ab·2^252, then the 2^264 carrier
// restores mont256) vs 8 scalar fr_muls — exactly the scalar bytes.
void fr_mul_batch(const u64 *a, const u64 *b, u64 *out, long n) {
#if ZKP2P_HAVE_IFMA
  if (fr_batch_vector_on(n)) {
    Ifma52Field &F = fr52_field();
    __m512i p[5], comppv[5], c1[5];
    for (int k = 0; k < 5; ++k) {
      p[k] = _mm512_set1_epi64((long long)F.p52[k]);
      comppv[k] = _mm512_set1_epi64((long long)F.compp[k]);
      c1[k] = _mm512_set1_epi64((long long)F.c264[k]);
    }
    const __m512i pinv = _mm512_set1_epi64((long long)F.pinv52);
    long nblk = n / 8;
    pool_parallel_ranges(nblk, 1024, pool_default_threads(), [&](long lo, long hi) {
      for (long b8 = lo; b8 < hi; ++b8) {
        u64 tmp[5][8];
        for (int l = 0; l < 8; ++l) {
          u64 t[5];
          limbs4_to_52(t, b + 32 * b8 + 4 * l);
          for (int k = 0; k < 5; ++k) tmp[k][l] = t[k];
        }
        __m512i bv[5];
        for (int k = 0; k < 5; ++k) bv[k] = _mm512_loadu_si512(tmp[k]);
        fr_batch8_mul2(a + 32 * b8, bv, c1, nullptr, p, pinv, comppv, out + 32 * b8);
      }
    });
    for (long i = nblk * 8; i < n; ++i) fr_mul(out + 4 * i, a + 4 * i, b + 4 * i);
    return;
  }
#endif
  for (long i = 0; i < n; ++i) fr_mul(out + 4 * i, a + 4 * i, b + 4 * i);
}
// Self-test hook: c = a*b mod r, standard form in/out.
void fr_mul_std(const u64 *a, const u64 *b, u64 *c) {
  u64 am[4], bm[4], cm[4];
  static const u64 ONE_STD[4] = {1, 0, 0, 0};
  fr_mul(am, a, R2R);
  fr_mul(bm, b, R2R);
  fr_mul(cm, am, bm);
  fr_mul(c, cm, ONE_STD);
}

// Sparse QAP matvec: out[row[i]] += coeff[i] * w[wire[i]] (all Montgomery).
void fr_matvec(const u64 *coeff, const unsigned *wire, const unsigned *row,
               long nnz, const u64 *w, long m, u64 *out) {
  long long wall0 = prof_now_ns();
  memset(out, 0, (size_t)m * 32);
  u64 t[4];
  for (long i = 0; i < nnz; ++i) {
    fr_mul(t, coeff + 4 * i, w + 4 * (long)wire[i]);
    u64 *o = out + 4 * (long)row[i];
    fr_add(o, o, t);
  }
  stat_add(ST_MATVEC_NS, prof_now_ns() - wall0);
}

// ---------------------------------------------------------------------------
// Segmented matvec (the presorted-plan tier; docs/TUNING.md §non-MSM).
//
// fr_matvec above is a serial read-modify-write scatter: out[row[i]] +=
// coeff[i]*w[wire[i]] in nnz order, which blocks both vectorization (at
// ~2-4 nnz per QAP row the Montgomery mul IS the stage) and threading
// (two workers may hit one output row).  The plan — built once per key
// on the Python side (prover.matvec_plan) and persisted beside the
// precomp tables — presorts the nnz by output row, turning the stage
// into nseg independent "sum one contiguous run of products" segments:
//
//   * the PRODUCTS vectorize ACROSS segment boundaries (independent by
//     definition): 8-wide 5x52 IFMA Montgomery muls over gathered wire
//     values, canonically reduced in-register;
//   * the ACCUMULATION is a scalar fr_add walk over canonical products
//     — field addition is exact, so the output bytes match the scatter
//     oracle for any order;
//   * the SEGMENT space partitions across the WorkPool with zero
//     scatter conflicts by construction (each worker owns a disjoint
//     row range of the plan).
//
// Montgomery bookkeeping: w arrives mont256; the packed plan coeffs are
// pre-multiplied by the 2^264 carrier (mont256 -> mont260), so one
// mont260 vector mul yields the mont256 product directly — the same
// constants-in-mont260 rule the NTT vector pipeline rides (see the
// 52-bit core comment block).

// Pack the plan's permuted mont256 coeffs into mont260 8-lane SoA
// blocks (block b = plan entries 8b..8b+7; 5 planes x 8 u64 each, so
// ceil(nnz/8)*40 u64 out).  Returns 1 on the IFMA tier, 0 when the
// vector core is unavailable (caller then passes coeff52 = NULL and the
// segmented driver runs its scalar product loop — still pool-parallel).
int fr_matvec_pack52(const u64 *coeff_mont, long nnz, u64 *out52) {
#if ZKP2P_HAVE_IFMA
  if (!ifma_enabled() || nnz <= 0) return ifma_enabled() && nnz == 0 ? 1 : 0;
  Ifma52Field &F = fr52_field();
  long nblk = (nnz + 7) / 8;
  // zero the pad lanes of the last block so they never carry garbage
  // into a vector register (they are multiplied but never stored)
  memset(out52 + (size_t)(nblk - 1) * 40, 0, 40 * sizeof(u64));
  pool_parallel_ranges(nnz, 1L << 14, pool_default_threads(), [&](long lo, long hi) {
    for (long i = lo; i < hi; ++i) {
      u64 t[5], t260[5];
      limbs4_to_52(t, coeff_mont + 4 * i);
      mont52_mul_scalar(t260, t, F.c264, F);  // carrier 256 -> 260
      u64 *blk = out52 + (size_t)(i / 8) * 40;
      for (int k = 0; k < 5; ++k) blk[k * 8 + (i & 7)] = t260[k];
    }
  });
  return 1;
#else
  (void)coeff_mont;
  (void)nnz;
  (void)out52;
  return 0;
#endif
}

// Segmented-plan matvec: plan entries are presorted by output row;
// segment s covers plan indices [seg_starts[s], seg_starts[s+1]) and
// sums into out[seg_rows[s]].  coeff52 is the fr_matvec_pack52 output
// (NULL = scalar product tier); coeff_mont the permuted mont256 coeffs
// (always required: scalar tier, unaligned heads/tails).  Rows not
// named by any segment stay zero, matching the oracle's memset.
void fr_matvec_seg(const u64 *coeff52, const u64 *coeff_mont,
                   const unsigned *wire, const long long *seg_starts,
                   const unsigned *seg_rows, long nseg, const u64 *w,
                   long m, int n_threads, u64 *out) {
  long long wall0 = prof_now_ns();
  stat_add(ST_MATVEC_SEG_CALLS, 1);
  memset(out, 0, (size_t)m * 32);
  if (nseg <= 0) {
    stat_add(ST_MATVEC_NS, prof_now_ns() - wall0);
    return;
  }
  const long nnz_total = seg_starts[nseg];
  // chunk boundaries in SEGMENT space, balanced by nnz: worker c owns
  // segments [bounds[c], bounds[c+1]) — disjoint output rows, so no
  // two workers ever touch one out entry.
  int nchunk = 1;
  if (n_threads > 1 && !g_pool_worker && nseg > 1) {
    long want = (long)n_threads * 4;
    if (want > nseg) want = nseg;
    long by_grain = nnz_total / 4096;  // per-chunk minimum work
    if (want > by_grain) want = by_grain;
    nchunk = want > 1 ? (int)want : 1;
  }
  std::vector<long> bounds((size_t)nchunk + 1);
  bounds[0] = 0;
  for (int ci = 1; ci < nchunk; ++ci) {
    long target = nnz_total / nchunk * ci;
    long lo = bounds[ci - 1], hi = nseg;
    while (lo < hi) {  // first segment starting at/after the nnz target
      long mid = (lo + hi) / 2;
      if (seg_starts[mid] < target) lo = mid + 1; else hi = mid;
    }
    bounds[ci] = lo;
  }
  bounds[nchunk] = nseg;

  auto run_chunk = [&](long ci) {
    long sa = bounds[ci], sb = bounds[ci + 1];
    if (sa >= sb) return;
    const long i0 = seg_starts[sa], i1 = seg_starts[sb];
    const long CHV = 2048;  // product-slice length (4 planes -> 64 KB, L2-warm)
    static thread_local std::vector<u64> scratch;
    if ((long)scratch.size() < 4 * CHV) scratch.assign(4 * CHV, 0);
    u64 *pr0 = scratch.data(), *pr1 = pr0 + CHV, *pr2 = pr1 + CHV, *pr3 = pr2 + CHV;
    long seg = sa;
    u64 acc[4] = {0, 0, 0, 0};
    for (long base = i0; base < i1; base += CHV) {
      const long hi = base + CHV < i1 ? base + CHV : i1;
      long i = base;
      auto scalar_store = [&](long j) {
        u64 t[4];
        fr_mul(t, coeff_mont + 4 * j, w + 4 * (long)wire[j]);
        pr0[j - base] = t[0];
        pr1[j - base] = t[1];
        pr2[j - base] = t[2];
        pr3[j - base] = t[3];
      };
#if ZKP2P_HAVE_IFMA
      if (coeff52 != nullptr && ifma_enabled()) {
        Ifma52Field &F = fr52_field();
        __m512i p[5], comppv[5];
        for (int k = 0; k < 5; ++k) {
          p[k] = _mm512_set1_epi64((long long)F.p52[k]);
          comppv[k] = _mm512_set1_epi64((long long)F.compp[k]);
        }
        const __m512i pinv = _mm512_set1_epi64((long long)F.pinv52);
        const __m512i m52v = _mm512_set1_epi64((long long)M52);
        long a0 = (base + 7) & ~7L;  // coeff52 blocks are GLOBAL-8-aligned
        if (a0 > hi) a0 = hi;
        for (; i < a0; ++i) scalar_store(i);
        for (; i + 8 <= hi; i += 8) {
          // gather the 8 wire rows limb-by-limb, then 4x64 -> 5x52
          // entirely in-register (the lane-wise limbs4_to_52)
          const __m512i idx = _mm512_slli_epi64(
              _mm512_cvtepu32_epi64(_mm256_loadu_si256((const __m256i *)(wire + i))), 2);
          __m512i wv[4];
          for (int k = 0; k < 4; ++k)
            wv[k] = _mm512_i64gather_epi64(
                _mm512_add_epi64(idx, _mm512_set1_epi64(k)), (const long long *)w, 8);
          __m512i w52[5];
          w52[0] = _mm512_and_si512(wv[0], m52v);
          w52[1] = _mm512_and_si512(
              _mm512_or_si512(_mm512_srli_epi64(wv[0], 52), _mm512_slli_epi64(wv[1], 12)), m52v);
          w52[2] = _mm512_and_si512(
              _mm512_or_si512(_mm512_srli_epi64(wv[1], 40), _mm512_slli_epi64(wv[2], 24)), m52v);
          w52[3] = _mm512_and_si512(
              _mm512_or_si512(_mm512_srli_epi64(wv[2], 28), _mm512_slli_epi64(wv[3], 36)), m52v);
          w52[4] = _mm512_srli_epi64(wv[3], 16);
          __m512i c52[5];
          const u64 *blk = coeff52 + (size_t)(i / 8) * 40;
          for (int k = 0; k < 5; ++k) c52[k] = _mm512_loadu_si512(blk + k * 8);
          __m512i prv[5];
          mont52_mul8(prv, w52, c52, p, pinv);  // mont256 product, [0, 2p)
          cond_sub_c8(prv, comppv);             // canonical: < r
          // lane-wise limbs52_to_4, stored to the product planes
          _mm512_storeu_si512(pr0 + (i - base),
                              _mm512_or_si512(prv[0], _mm512_slli_epi64(prv[1], 52)));
          _mm512_storeu_si512(pr1 + (i - base),
                              _mm512_or_si512(_mm512_srli_epi64(prv[1], 12),
                                              _mm512_slli_epi64(prv[2], 40)));
          _mm512_storeu_si512(pr2 + (i - base),
                              _mm512_or_si512(_mm512_srli_epi64(prv[2], 24),
                                              _mm512_slli_epi64(prv[3], 28)));
          _mm512_storeu_si512(pr3 + (i - base),
                              _mm512_or_si512(_mm512_srli_epi64(prv[3], 36),
                                              _mm512_slli_epi64(prv[4], 16)));
        }
      }
#endif
      for (; i < hi; ++i) scalar_store(i);
      // segmented accumulation over this slice; acc carries across
      // slice boundaries for segments longer than CHV
      i = base;
      while (i < hi) {
        const long send = seg_starts[seg + 1];
        const long stop = send < hi ? send : hi;
        for (; i < stop; ++i) {
          u64 t[4] = {pr0[i - base], pr1[i - base], pr2[i - base], pr3[i - base]};
          fr_add(acc, acc, t);
        }
        if (i == send) {
          memcpy(out + 4 * (long)seg_rows[seg], acc, 32);
          memset(acc, 0, 32);
          ++seg;
        }
      }
    }
  };
  if (nchunk > 1) {
    work_pool().ensure(n_threads);
    work_pool().run(nchunk, run_chunk, n_threads);
  } else {
    run_chunk(0);
  }
  stat_add(ST_MATVEC_NS, prof_now_ns() - wall0);
}

// In-place radix-2 NTT over Fr, natural order in/out, data Montgomery.
// root_std: standard-form primitive m-th root (forward: w, inverse:
// w^-1); scale_std: standard-form factor applied to every output (1 for
// forward, m^-1 for inverse).  Twiddles are a precomputed m/2 table so
// each butterfly costs one fr_mul.
// bit-reversal permutation (32-byte element swaps) — shared by the
// scalar and IFMA NTT entry points so the permutation can never diverge.
static void fr_bitrev(u64 *data, long m) {
  for (long i = 1, j = 0; i < m; ++i) {
    long bit = m >> 1;
    for (; j & bit; bit >>= 1) j ^= bit;
    j ^= bit;
    if (i < j) {
      u64 tmp[4];
      memcpy(tmp, data + 4 * i, 32);
      memcpy(data + 4 * i, data + 4 * j, 32);
      memcpy(data + 4 * j, tmp, 32);
    }
  }
}

// scale_std != 1 epilogue — shared for the same reason.
static void fr_apply_scale(u64 *data, long m, const u64 *scale_std) {
  static const u64 ONE_STD[4] = {1, 0, 0, 0};
  if (memcmp(scale_std, ONE_STD, 32) != 0) {
    u64 scale_m[4];
    fr_mul(scale_m, scale_std, R2R);
    for (long i = 0; i < m; ++i) fr_mul(data + 4 * i, data + 4 * i, scale_m);
  }
}

void fr_ntt(u64 *data, long m, const u64 *root_std, const u64 *scale_std) {
  int log_m = 0;
  while ((1L << log_m) < m) ++log_m;
  fr_bitrev(data, m);
  u64 root_m[4];
  fr_mul(root_m, root_std, R2R);
  long half_m = m / 2;
  // Twiddles depend only on (m, root): cache them across calls — the
  // ladder runs 6 NTTs per prove and the sequential m/2-mul rebuild was
  // ~5% of its time.  Guarded: ladder threads call fr_ntt concurrently.
  // Capacity-capped (each entry is 16*m bytes, ~128 MB per root at
  // m=2^23): a long-lived service proving across domain sizes must not
  // accumulate unbounded twiddle tables.  shared_ptr keeps an evicted
  // table alive for any thread still mid-butterfly on it.
  static std::mutex tw_mu;
  static std::map<std::array<u64, 5>, std::shared_ptr<u64[]>> tw_cache;
  std::shared_ptr<u64[]> tw_hold;
  {
    std::lock_guard<std::mutex> lk(tw_mu);
    std::array<u64, 5> key = {(u64)m, root_std[0], root_std[1], root_std[2], root_std[3]};
    auto it = tw_cache.find(key);
    if (it != tw_cache.end()) {
      tw_hold = it->second;
    } else {
      tw_hold = std::shared_ptr<u64[]>(new u64[(size_t)(half_m > 0 ? half_m : 1) * 4]);
      memcpy(tw_hold.get(), ONE_R, 32);
      for (long j = 1; j < half_m; ++j) fr_mul(tw_hold.get() + 4 * j, tw_hold.get() + 4 * (j - 1), root_m);
      // evict smallest-m entries first (cheapest to rebuild) until at
      // most 8 tables besides the one being inserted remain
      while (tw_cache.size() >= 8) tw_cache.erase(tw_cache.begin());
      tw_cache[key] = tw_hold;
    }
  }
  u64 *tw = tw_hold.get();
  for (long len = 2; len <= m; len <<= 1) {
    long half = len >> 1;
    long stride = m / len;
    for (long i0 = 0; i0 < m; i0 += len) {
      for (long j = 0; j < half; ++j) {
        u64 *u = data + 4 * (i0 + j);
        u64 *v = data + 4 * (i0 + j + half);
        u64 t[4];
        // j == 0 is the identity twiddle: every stage's first
        // butterfly (and ALL of stage len=2) — skipping the Montgomery
        // mul there removes ~m of the m/2·log2(m) twiddle muls
        if (j == 0) {
          memcpy(t, v, 32);
        } else {
          fr_mul(t, v, tw + 4 * (j * stride));
        }
        u64 usave[4];
        memcpy(usave, u, 32);
        fr_add(u, usave, t);
        fr_sub(v, usave, t);
      }
    }
  }
  fr_apply_scale(data, m, scale_std);
}

// 1 when the AVX-512 IFMA fast paths are compiled in, the CPU has the
// instructions, and ZKP2P_NATIVE_IFMA != 0.
int zkp2p_ifma_available(void) { return ifma_enabled() ? 1 : 0; }

// 1 when the batch-affine bucket tiers are active (ZKP2P_MSM_BATCH_AFFINE
// unset / not leading-'0').  Fresh-read, so tools can echo the live arm.
int zkp2p_batch_affine_enabled(void) { return batch_affine_enabled() ? 1 : 0; }

// 1 when the pool-parallel NTT stage splitting + fused ladder pipeline
// are active (ZKP2P_NTT_POOL unset / not leading-'0').  Fresh-read for
// the same reason.
int zkp2p_ntt_pool_enabled(void) { return ntt_pool_enabled() ? 1 : 0; }

// Host cache capacity in bytes for the tune subsystem's cache-conscious
// MSM schedule picking: level 1 = L1d, 2 = L2, 3 = L3 (LLC on most
// parts).  sysconf is the portable glibc surface over cpuid/sysfs; a
// kernel or libc that doesn't expose the level reports 0 = unknown and
// the Python side falls back to sysfs, then to documented constants.
long zkp2p_cache_size(int level) {
  long v = -1;
  switch (level) {
#ifdef _SC_LEVEL1_DCACHE_SIZE
    case 1: v = sysconf(_SC_LEVEL1_DCACHE_SIZE); break;
#endif
#ifdef _SC_LEVEL2_CACHE_SIZE
    case 2: v = sysconf(_SC_LEVEL2_CACHE_SIZE); break;
#endif
#ifdef _SC_LEVEL3_CACHE_SIZE
    case 3: v = sysconf(_SC_LEVEL3_CACHE_SIZE); break;
#endif
    default: break;
  }
  return v > 0 ? v : 0;
}

// Online logical CPU count as the runtime sees it (the same figure the
// WorkPool sizes from when ZKP2P_NATIVE_THREADS is unset); 0 = unknown.
long zkp2p_cpu_count(void) {
#ifdef _SC_NPROCESSORS_ONLN
  long v = sysconf(_SC_NPROCESSORS_ONLN);
  return v > 0 ? v : 0;
#else
  return 0;
#endif
}

// Differential-test hook for the 8-wide kernel: c[i] = a[i]*b[i] mod r,
// standard form in/out, driven through pack -> mont260 vector multiply
// -> unpack (the exact pipeline the NTT stages use).  Falls back to the
// scalar path when IFMA is unavailable so tests can always call it.
void fr52_mul_std_batch(const u64 *a, const u64 *b, u64 *c, long n) {
#if ZKP2P_HAVE_IFMA
  if (ifma_enabled()) {
    Ifma52Field &F = fr52_field();
    __m512i p[5];
    for (int k = 0; k < 5; ++k) p[k] = _mm512_set1_epi64((long long)F.p52[k]);
    const __m512i pinv = _mm512_set1_epi64((long long)F.pinv52);
    // r260sq lanes: one mont260 mul maps std a -> a·2^260 (mont260)
    __m512i rsq[5];
    for (int k = 0; k < 5; ++k) rsq[k] = _mm512_set1_epi64((long long)F.r260sq[k]);
    long i = 0;
    for (; i + 8 <= n; i += 8) {
      u64 av[5][8], bv[5][8];
      for (int l = 0; l < 8; ++l) {
        u64 t[5];
        limbs4_to_52(t, a + 4 * (i + l));
        for (int k = 0; k < 5; ++k) av[k][l] = t[k];
        limbs4_to_52(t, b + 4 * (i + l));
        for (int k = 0; k < 5; ++k) bv[k][l] = t[k];
      }
      __m512i A[5], B[5], Bm[5], C[5];
      for (int k = 0; k < 5; ++k) {
        A[k] = _mm512_loadu_si512(av[k]);
        B[k] = _mm512_loadu_si512(bv[k]);
      }
      mont52_mul8(Bm, B, rsq, p, pinv);  // b_std -> b·2^260
      mont52_mul8(C, A, Bm, p, pinv);    // (a_std)(b·2^260)·2^-260 = ab std
      u64 cv[5][8];
      for (int k = 0; k < 5; ++k) _mm512_storeu_si512(cv[k], C[k]);
      for (int l = 0; l < 8; ++l) {
        u64 t[5], o[4];
        for (int k = 0; k < 5; ++k) t[k] = cv[k][l];
        limbs52_to_4(o, t);
        while (geq(o, R_MOD)) sub_nored(o, o, R_MOD);
        memcpy(c + 4 * (i + l), o, 32);
      }
    }
    for (; i < n; ++i) fr_mul_std(a + 4 * i, b + 4 * i, c + 4 * i);
    return;
  }
#endif
  for (long i = 0; i < n; ++i) fr_mul_std(a + 4 * i, b + 4 * i, c + 4 * i);
}

// Drop-in fr_ntt with the len>=16 stages vectorized 8-wide (IFMA).
// Identical contract: data Montgomery, natural order in/out, root_std /
// scale_std standard form.
void fr_ntt_ifma(u64 *data, long m, const u64 *root_std, const u64 *scale_std) {
#if ZKP2P_HAVE_IFMA
  if (ifma_enabled() && m >= 64) {
    // ALL stages vectorized: len 2/4/8 via in-register permutes (the
    // scalar small-stage tier was ~1/3 of the NTT after radix-4), then
    // the radix-4-fused len>=16 loop — one pack/unpack for everything,
    // with the input bit-reversal folded into the pack
    fr_ntt_ifma_stages(data, m, root_std);
    fr_apply_scale(data, m, scale_std);
    return;
  }
#endif
  fr_ntt(data, m, root_std, scale_std);
}

#if ZKP2P_HAVE_IFMA
// gpow table for the FUSED ladder, in mont260 SoA planes, cached per
// (m, g): gpow[j] = (1/m)·g^j — the iNTT's deferred 1/m scale folded
// into the coset shift, applied as ONE vectorized SoA pass between the
// inverse and forward stage pipelines (fr_soa_mul).  Key-shape
// invariant, so it builds once per (domain, coset) like the twiddle
// tables and drops the old per-call sequential m-mul chain from the
// prove path (shared_ptr for in-flight safety; small cap — each entry
// is 40·m bytes).
static std::shared_ptr<u64[]> ladder_gpow260(long m, const u64 *g_std,
                                             const u64 *minv_std) {
  static std::mutex mu;
  static std::map<std::array<u64, 5>, std::shared_ptr<u64[]>> cache;
  std::lock_guard<std::mutex> lk(mu);
  std::array<u64, 5> key = {(u64)m, g_std[0], g_std[1], g_std[2], g_std[3]};
  auto it = cache.find(key);
  if (it != cache.end()) return it->second;
  Ifma52Field &F = fr52_field();
  std::shared_ptr<u64[]> buf(new u64[(size_t)m * 5]);
  u64 g52[5], g260[5], cur[5], t52[5];
  limbs4_to_52(g52, g_std);
  mont52_mul_scalar(g260, g52, F.r260sq, F);  // std -> mont260
  limbs4_to_52(t52, minv_std);
  mont52_mul_scalar(cur, t52, F.r260sq, F);   // (1/m) in mont260
  u64 *planes = buf.get();
  for (long j = 0; j < m; ++j) {
    for (int k = 0; k < 5; ++k) planes[(size_t)k * m + j] = cur[k];
    mont52_mul_scalar(cur, cur, g260, F);
  }
  while (cache.size() >= 4) cache.erase(cache.begin());
  cache[key] = buf;
  return buf;
}

// Fused-pipeline ladder (the ZKP2P_NTT_POOL arm): each transform stays
// in 52-limb SoA form across iNTT -> coset-mul -> forward NTT, so the
// unpack-to-mont256 and repack passes between the two transforms (plus
// the standalone scalar coset-mul pass) disappear — two full memory
// passes per transform — and every stage pass fans out across the
// WorkPool instead of the old 3-wide whole-transform split.  Byte
// parity with the unfused arm is exact: identical field values at every
// step, one canonical unpack at the end (tests/test_nonmsm.py pins it).
static void fr_h_ladder_fused(u64 *a, u64 *b, u64 *c, long m,
                              const u64 *w_std, const u64 *winv_std,
                              const u64 *g_std, const u64 *minv_std,
                              u64 *out_d, int nt) {
  std::shared_ptr<u64[]> gpow = ladder_gpow260(m, g_std, minv_std);
  u64 *soa = new u64[(size_t)m * 5];
  u64 *vecs[3] = {a, b, c};
  for (int v3 = 0; v3 < 3; ++v3) {
    u64 *v = vecs[v3];
    fr_soa_pack_rev(v, m, soa, nt);           // bitrev folded into the pack
    fr_ntt_soa_stages(soa, m, winv_std, nt);  // unscaled iNTT: evals -> m·coeffs
    fr_soa_mul(soa, m, gpow.get(), nt);       // fused (1/m)·g^j coset pass
    fr_soa_bitrev(soa, m, nt);                // natural -> bit-reversed for forward
    fr_ntt_soa_stages(soa, m, w_std, nt);     // coefficients -> coset evals
    fr_soa_unpack(soa, m, v, nt);             // canonical mont256 out
  }
  delete[] soa;
  // d = A·B - C on the coset, range-parallel (independent rows)
  pool_parallel_ranges(m, 1L << 13, nt, [&](long lo, long hi) {
    for (long j = lo; j < hi; ++j) {
      u64 t[4];
      fr_mul(t, a + 4 * j, b + 4 * j);
      fr_sub(out_d + 4 * j, t, c + 4 * j);
    }
  });
}
#endif  // ZKP2P_HAVE_IFMA

// The H-polynomial coset ladder (prove_tpu's h_evals, native):
// a/b/c are the domain evaluations (Montgomery, length m, clobbered);
// out_d[j] = (A.B - C)(g . w^j) Montgomery.  w_std is the primitive
// m-th root matching field.bn254.fr_domain_root(log_m); g_std the coset
// generator (snarkjs convention: w_{2m}).  Inverses computed here.
void fr_h_ladder(u64 *a, u64 *b, u64 *c, long m, const u64 *w_std,
                 const u64 *g_std, u64 *out_d) {
  // winv, minv (standard form): invert in Montgomery then strip.
  u64 wm[4], wim[4], winv_std[4], minv_std[4];
  static const u64 ONE_STD[4] = {1, 0, 0, 0};
  fr_mul(wm, w_std, R2R);
  fr_inv_mont(wim, wm);
  fr_mul(winv_std, wim, ONE_STD);
  u64 m_std[4] = {(u64)m, 0, 0, 0};
  u64 mm[4], mim[4];
  fr_mul(mm, m_std, R2R);
  fr_inv_mont(mim, mm);
  fr_mul(minv_std, mim, ONE_STD);
#if ZKP2P_HAVE_IFMA
  // the fused, stage-parallel pipeline (byte-identical; gated so the
  // knob-off arm below stays the honest A/B oracle)
  if (ifma_enabled() && ntt_pool_enabled() && m >= 64) {
    fr_h_ladder_fused(a, b, c, m, w_std, winv_std, g_std, minv_std, out_d,
                      pool_default_threads());
    return;
  }
#endif
  u64 gm[4];
  fr_mul(gm, g_std, R2R);
  // One shared table for all three ladders, with the iNTT's 1/m scale
  // FOLDED IN: gpow[j] = (1/m)·g^j in Montgomery form, so the unscaled
  // iNTT plus one coset mul replaces scale-pass + coset-pass (each
  // previously ran its own sequential m-mul power chain too).
  u64 minv_m[4];
  fr_mul(minv_m, minv_std, R2R);
  u64 *gpow = new u64[(size_t)m * 4];
  memcpy(gpow, minv_m, 32);
  for (long j = 1; j < m; ++j) fr_mul(gpow + 4 * j, gpow + 4 * (j - 1), gm);
  u64 *vecs[3] = {a, b, c};
  auto ladder_one = [&](u64 *v) {
    fr_ntt_ifma(v, m, winv_std, ONE_STD);  // unscaled iNTT: evals -> m·coeffs
    // coset shift + deferred 1/m scale in one pass: v[j] *= (1/m)·g^j
    for (long j = 0; j < m; ++j) fr_mul(v + 4 * j, v + 4 * j, gpow + 4 * j);
    fr_ntt_ifma(v, m, w_std, ONE_STD);  // forward: coefficients -> coset evals
  };
  // The three polynomial ladders are independent: run them on the
  // persistent pool when the host has cores to spare (same env-driven
  // knob as the MSM pool; spawn-per-call threads retired with it).
  int nt = pool_default_threads();
  if (nt > 1) {
    int w = nt < 3 ? nt : 3;
    work_pool().ensure(w);
    work_pool().run(3, [&](long k) { ladder_one(vecs[k]); }, w);
  } else {
    for (int k = 0; k < 3; ++k) ladder_one(vecs[k]);
  }
  delete[] gpow;
  for (long j = 0; j < m; ++j) {
    u64 t[4];
    fr_mul(t, a + 4 * j, b + 4 * j);
    fr_sub(out_d + 4 * j, t, c + 4 * j);
  }
}

}  // extern "C"

// ------------------------------------------------- Pippenger MSM (G1/G2)

// Full Jacobian + Jacobian add over G1 (mirror of g2_add).
static void g1_add_jac(G1Jac &acc, const G1Jac &e) {
  if (is_zero4(e.Z)) return;
  if (is_zero4(acc.Z)) {
    acc = e;
    return;
  }
  u64 Z1Z1[4], Z2Z2[4], U1[4], U2[4], S1[4], S2[4], H[4], Rr[4], t[4];
  mont_sqr(Z1Z1, acc.Z);
  mont_sqr(Z2Z2, e.Z);
  mont_mul(U1, acc.X, Z2Z2);
  mont_mul(U2, e.X, Z1Z1);
  mont_mul(t, acc.Y, e.Z);
  mont_mul(S1, t, Z2Z2);
  mont_mul(t, e.Y, acc.Z);
  mont_mul(S2, t, Z1Z1);
  sub_mod(H, U2, U1);
  sub_mod(Rr, S2, S1);
  if (is_zero4(H)) {
    if (is_zero4(Rr)) {
      G1Jac d;
      jac_double(d, acc);
      acc = d;
      return;
    }
    memset(&acc, 0, sizeof(acc));
    return;
  }
  u64 HH[4], HHH[4], V[4], x3[4], y3[4], z3[4], t2[4], v2[4];
  mont_sqr(HH, H);
  mont_mul(HHH, H, HH);
  mont_mul(V, U1, HH);
  mont_sqr(t, Rr);
  sub_mod(t, t, HHH);
  add_mod(v2, V, V);
  sub_mod(x3, t, v2);
  sub_mod(t, V, x3);
  mont_mul(t, Rr, t);
  mont_mul(t2, S1, HHH);
  sub_mod(y3, t, t2);
  mont_mul(t, acc.Z, e.Z);
  mont_mul(z3, t, H);
  memcpy(acc.X, x3, 32);
  memcpy(acc.Y, y3, 32);
  memcpy(acc.Z, z3, 32);
}

// c-bit digit of a 256-bit scalar starting at `bit`.
static inline unsigned digit_at(const u64 s[4], int bit, int c) {
  int limb = bit >> 6, off = bit & 63;
  u64 v = s[limb] >> off;
  if (off + c > 64 && limb < 3) v |= s[limb + 1] << (64 - off);
  return (unsigned)(v & ((1ULL << c) - 1));
}

// Signed base-2^c recoding of one scalar: digits in [-(2^(c-1)-1),
// 2^(c-1)], LSW first.  Halves the bucket count per window (a negative
// digit adds the NEGATED point: (x, p - y) is free next to a bucket
// add).  The top window absorbs the final carry whenever nwin*c >= 255
// (true for every c in the sweep range; asserted by the callers) since
// Fr scalars are < 2^254.
static void signed_digits(const u64 s[4], int c, int nwin, int32_t *out) {
  long half = 1L << (c - 1), full = 1L << c;
  long carry = 0;
  for (int wi = 0; wi < nwin; ++wi) {
    long d = (long)digit_at(s, wi * c, c) + carry;
    if (d > half) {
      out[wi] = (int32_t)(d - full);
      carry = 1;
    } else {
      out[wi] = (int32_t)d;
      carry = 0;
    }
  }
}

// y -> p - y (Montgomery), the negation used for negative digits.
static inline void neg_y(u64 out[4], const u64 y[4]) {
  if (is_zero4(y)) {
    memset(out, 0, 32);
    return;
  }
  sub_nored(out, P, y);
}

// The digit-signed y of a point: shared by every G1 fill path so the
// sign handling cannot diverge between the batch-affine, jac, and bail
// tiers.
static inline void signed_pt_y(u64 out[4], const u64 y[4], bool negate) {
  if (negate) {
    neg_y(out, y);
  } else {
    memcpy(out, y, 32);
  }
}

// One Pippenger window sum: bucket fill over all n points + suffix-sum
// reduction.  Windows are independent, which is the parallel axis (the
// same split rapidsnark's thread pool uses): each worker owns its bucket
// array, the combiner pays only nwin Horner steps of c doublings.
//
// The G1 fill uses BATCH-AFFINE bucket accumulation (the gnark/arkworks
// trick): buckets live as affine points, each bucket add is an
// affine+affine add whose one field inversion is amortized across a
// whole chunk by the Montgomery batch-inverse — ~7 muls per add instead
// of the ~12 of a mixed-Jacobian add, on the op that is ~85% of the MSM.
// Same-chunk bucket collisions are deferred to the next pass (rare:
// chunk << 2^c).

struct AffPt {
  u64 x[4], y[4];  // Montgomery; (0,0) = empty bucket
};

static inline bool aff_is_empty(const AffPt &p) {
  return is_zero4(p.x) && is_zero4(p.y);
}

// Plain mixed-Jacobian fill: the fallback for windows whose effective
// digit range is tiny (the TOP window often has only a few bits: its
// points pile into a handful of buckets and the batch-affine conflict
// queue degenerates into near-serial passes).
static void g1_window_sum_jac(const u64 *bases_xy, const int32_t *sd, long n,
                              int c, int nwin, int wi, G1Jac *out) {
  long nbuckets = (1L << (c - 1)) + 1;  // signed digits reach 2^(c-1)
  G1Jac *buckets = new G1Jac[nbuckets];
  memset(buckets, 0, (size_t)nbuckets * sizeof(G1Jac));
  for (long i = 0; i < n; ++i) {
    int32_t d = sd[i * nwin + wi];
    if (!d) continue;
    const u64 *x = bases_xy + 8 * i;
    const u64 *y = x + 4;
    if (is_zero4(x) && is_zero4(y)) continue;
    long b = d < 0 ? -d : d;
    u64 ys[4];
    signed_pt_y(ys, y, d < 0);
    jac_add_mixed(buckets[b], buckets[b], x, ys);
  }
  G1Jac run, wsum;
  memset(&run, 0, sizeof(run));
  memset(&wsum, 0, sizeof(wsum));
  for (long d = nbuckets - 1; d >= 1; --d) {
    g1_add_jac(run, buckets[d]);
    g1_add_jac(wsum, run);
  }
  delete[] buckets;
  *out = wsum;
}

static void g1_window_sum(const u64 *bases_xy, const int32_t *sd, long n,
                          int c, int nwin, int wi, G1Jac *out,
                          int total_bits = 254) {
  const long nbuckets = (1L << (c - 1)) + 1;  // signed digit magnitudes
  const long B = 2048;  // chunk size for the shared inversion
  int bits_here = total_bits - wi * c;
  if (bits_here > c) bits_here = c;
  if (bits_here < 1 || (1L << bits_here) < 4 * B) {
    g1_window_sum_jac(bases_xy, sd, n, c, nwin, wi, out);
    return;
  }
  AffPt *bk = new AffPt[nbuckets]();
  int *stamp = new int[nbuckets];
  memset(stamp, 0xff, nbuckets * sizeof(int));

  std::vector<long> cur, next;
  cur.reserve(n);
  for (long i = 0; i < n; ++i) {
    if (!sd[i * nwin + wi]) continue;
    const u64 *x = bases_xy + 8 * i;
    if (is_zero4(x) && is_zero4(x + 4)) continue;
    cur.push_back(i);
  }

  // scheduled-add scratch (per chunk)
  long *add_bkt = new long[B];
  long *add_pt = new long[B];
  u64 (*den)[4] = new u64[B][4];
  u64 (*num)[4] = new u64[B][4];   // lambda numerator
  u64 (*prod)[4] = new u64[B][4];  // batch-inverse prefix products
  // coordinate stashes (bucket state at schedule time + incoming point);
  // num/den derive from these AFTER scheduling — vectorized when IFMA
  // is up, per-j in the scalar fallback — so the schedule loop itself
  // does no field ops at all
  u64 (*x1a)[4] = new u64[B][4];
  u64 (*y1a)[4] = new u64[B][4];
  u64 (*x2a)[4] = new u64[B][4];
  u64 (*y2a)[4] = new u64[B][4];
  u64 (*x3a)[4] = new u64[B][4];
  u64 (*y3a)[4] = new u64[B][4];
  unsigned char *dbl = new unsigned char[B];
#if ZKP2P_HAVE_IFMA
  // chunk-apply SoA scratch, hoisted out of the per-chunk loop
  u64 *ifma_scratch = new u64[(size_t)9 * 5 * ((B + 7) / 8 * 8)];
#endif

  int chunk_id = 0;
  long long n_dbl = 0, n_cancel = 0, n_defer = 0;  // flushed once per window
  long long fl0 = prof_now_ns();
  while (!cur.empty()) {
    next.clear();
    size_t processed = 0;
    bool bail = false;
    for (size_t lo = 0; lo < cur.size() && !bail; lo += B, ++chunk_id) {
      size_t hi = lo + B < cur.size() ? lo + B : cur.size();
      long m = 0;
      for (size_t k = lo; k < hi; ++k) {
        long i = cur[k];
        int32_t dgt = sd[i * nwin + wi];
        long b = dgt < 0 ? -dgt : dgt;
        if (stamp[b] == chunk_id) {  // bucket already touched this chunk
          next.push_back(i);
          ++n_defer;
          continue;
        }
        stamp[b] = chunk_id;
        const u64 *px = bases_xy + 8 * i;
        u64 py[4];
        signed_pt_y(py, px + 4, dgt < 0);
        if (aff_is_empty(bk[b])) {  // install: no field ops at all
          memcpy(bk[b].x, px, 32);
          memcpy(bk[b].y, py, 32);
          continue;
        }
        if (memcmp(bk[b].x, px, 32) == 0) {
          if (memcmp(bk[b].y, py, 32) == 0) {
            dbl[m] = 1;  // doubling: lambda = 3x^2 / 2y (derived later)
            ++n_dbl;
          } else {
            // p + (-p): bucket becomes empty
            memset(&bk[b], 0, sizeof(AffPt));
            ++n_cancel;
            continue;
          }
        } else {
          dbl[m] = 0;  // chord: lambda = (y2 - y1) / (x2 - x1)
        }
        memcpy(x1a[m], bk[b].x, 32);
        memcpy(y1a[m], bk[b].y, 32);
        memcpy(x2a[m], px, 32);
        memcpy(y2a[m], py, 32);
        add_bkt[m] = b;
        add_pt[m] = i;
        ++m;
      }
      processed = hi;  // BEFORE the m==0 continue: install-only chunks
                       // are processed too (the bail tail starts here)
      if (!m) {
        if (next.size() * 2 > processed && processed >= (size_t)B) bail = true;
        continue;
      }
#if ZKP2P_HAVE_IFMA
      if (ifma_enabled() && m >= 48) {
        // 8-lane inversion + apply, one scalar inversion per chunk
        g1_chunk_apply_ifma(x1a, y1a, x2a, y2a, dbl, m, x3a, y3a, ifma_scratch);
        for (long j = 0; j < m; ++j) {
          memcpy(bk[add_bkt[j]].x, x3a[j], 32);
          memcpy(bk[add_bkt[j]].y, y3a[j], 32);
        }
      } else
#endif
      {
        // batch inversion of den[0..m): prefix products + one inversion
        // (num/den derived here from the schedule stashes)
        u64 run[4];
        memcpy(run, ONE_MONT, 32);
        for (long j = 0; j < m; ++j) {
          if (dbl[j]) {
            u64 xsq[4], t[4];
            mont_sqr(xsq, x1a[j]);
            add_mod(t, xsq, xsq);
            add_mod(num[j], t, xsq);
            add_mod(den[j], y1a[j], y1a[j]);
          } else {
            sub_mod(num[j], y2a[j], y1a[j]);
            sub_mod(den[j], x2a[j], x1a[j]);
          }
          memcpy(prod[j], run, 32);  // product of dens before j
          mont_mul(run, run, den[j]);
        }
        u64 inv_all[4];
        mont_inv(inv_all, run);
        for (long j = m - 1; j >= 0; --j) {
          u64 dinv[4];
          mont_mul(dinv, inv_all, prod[j]);      // 1/den[j]
          mont_mul(inv_all, inv_all, den[j]);    // strip den[j]
          long b = add_bkt[j];
          const u64 *px = bases_xy + 8 * add_pt[j];
          u64 lam[4], lam2[4], x3[4], y3[4], t[4];
          mont_mul(lam, num[j], dinv);
          mont_sqr(lam2, lam);
          // x3 = lam^2 - x1 - x2 ; y3 = lam (x1 - x3) - y1
          sub_mod(x3, lam2, bk[b].x);
          sub_mod(x3, x3, px);
          sub_mod(t, bk[b].x, x3);
          mont_mul(t, lam, t);
          sub_mod(y3, t, bk[b].y);
          memcpy(bk[b].x, x3, 32);
          memcpy(bk[b].y, y3, 32);
        }
      }
      // Concentrated digits (witness scalars are mostly bits: window 0
      // sees thousands of digit-1 points) defer most of every chunk —
      // batch-affine degenerates into a pass per point.  Bail to
      // mixed-Jacobian for whatever remains.
      if (next.size() * 2 > processed && processed >= (size_t)B) bail = true;
    }
    if (bail || next.size() * 4 > cur.size()) {
      // Finish all unfinished points (deferred + the unprocessed tail of
      // this pass) with plain mixed-Jacobian adds into a parallel bucket
      // array, then reduce both arrays together.
      stat_add(ST_MSM_FILL_NS, prof_now_ns() - fl0);
      stat_add(ST_MSM_DBL_LANES, n_dbl);
      stat_add(ST_MSM_CANCEL_LANES, n_cancel);
      stat_add(ST_MSM_DEFER_HITS, n_defer);
      long long bs0 = prof_now_ns();
      G1Jac *jb = new G1Jac[nbuckets];
      memset(jb, 0, (size_t)nbuckets * sizeof(G1Jac));
      next.insert(next.end(), cur.begin() + processed, cur.end());
      for (long i : next) {
        int32_t dgt = sd[i * nwin + wi];
        long b = dgt < 0 ? -dgt : dgt;
        const u64 *x = bases_xy + 8 * i;
        u64 ys[4];
        signed_pt_y(ys, x + 4, dgt < 0);
        jac_add_mixed(jb[b], jb[b], x, ys);
      }
      stat_add(ST_MSM_BAILFILL_NS, prof_now_ns() - bs0);
      bs0 = prof_now_ns();
      G1Jac run, wsum;
      memset(&run, 0, sizeof(run));
      memset(&wsum, 0, sizeof(wsum));
      for (long d = nbuckets - 1; d >= 1; --d) {
        g1_add_jac(run, jb[d]);
        if (!aff_is_empty(bk[d])) jac_add_mixed(run, run, bk[d].x, bk[d].y);
        g1_add_jac(wsum, run);
      }
      stat_add(ST_MSM_SUFFIX_NS, prof_now_ns() - bs0);
      delete[] jb;
      delete[] bk;
      delete[] stamp;
      delete[] add_bkt;
      delete[] add_pt;
      delete[] den;
      delete[] num;
      delete[] prod;
      delete[] x1a;
      delete[] y1a;
      delete[] x2a;
      delete[] y2a;
      delete[] x3a;
      delete[] y3a;
      delete[] dbl;
#if ZKP2P_HAVE_IFMA
      delete[] ifma_scratch;
#endif
      *out = wsum;
      return;
    }
    cur.swap(next);
  }

  stat_add(ST_MSM_FILL_NS, prof_now_ns() - fl0);
  stat_add(ST_MSM_DBL_LANES, n_dbl);
  stat_add(ST_MSM_CANCEL_LANES, n_cancel);
  stat_add(ST_MSM_DEFER_HITS, n_defer);
  // suffix-sum reduction over affine buckets (mixed adds into Jacobian)
  long long sf0 = prof_now_ns();
  G1Jac run, wsum;
  memset(&run, 0, sizeof(run));
  memset(&wsum, 0, sizeof(wsum));
  for (long d = nbuckets - 1; d >= 1; --d) {
    if (!aff_is_empty(bk[d])) jac_add_mixed(run, run, bk[d].x, bk[d].y);
    g1_add_jac(wsum, run);
  }
  stat_add(ST_MSM_SUFFIX_NS, prof_now_ns() - sf0);
  delete[] bk;
  delete[] stamp;
  delete[] add_bkt;
  delete[] add_pt;
  delete[] den;
  delete[] num;
  delete[] prod;
  delete[] x1a;
  delete[] y1a;
  delete[] x2a;
  delete[] y2a;
  delete[] x3a;
  delete[] y3a;
  delete[] dbl;
#if ZKP2P_HAVE_IFMA
  delete[] ifma_scratch;
#endif
  *out = wsum;
}

// Plain mixed-Jacobian G2 window fill (the non-IFMA tier and the
// vector tier's bail path).
static void g2_window_sum_jac(const u64 *bases, const int32_t *sd, long n,
                              int c, int nwin, int wi, G2Jac *out) {
  long nbuckets = (1L << (c - 1)) + 1;  // signed digit magnitudes
  G2Jac *buckets = new G2Jac[nbuckets];
  memset(buckets, 0, (size_t)nbuckets * sizeof(G2Jac));
  for (long i = 0; i < n; ++i) {
    int32_t dgt = sd[i * nwin + wi];
    if (!dgt) continue;
    long d = dgt < 0 ? -dgt : dgt;
    const u64 *b = bases + 16 * i;
    Fp2 x2, y2;
    memcpy(x2.c0, b, 32);
    memcpy(x2.c1, b + 4, 32);
    memcpy(y2.c0, b + 8, 32);
    memcpy(y2.c1, b + 12, 32);
    if (fp2_is_zero(x2) && fp2_is_zero(y2)) continue;
    if (dgt < 0) {  // -(y0 + y1 u) component-wise
      u64 t[4];
      neg_y(t, y2.c0);
      memcpy(y2.c0, t, 32);
      neg_y(t, y2.c1);
      memcpy(y2.c1, t, 32);
    }
    g2_add_mixed(buckets[d], buckets[d], x2, y2);
  }
  G2Jac run, wsum;
  memset(&run, 0, sizeof(run));
  memset(&wsum, 0, sizeof(wsum));
  for (long d = nbuckets - 1; d >= 1; --d) {
    g2_add(run, buckets[d]);
    g2_add(wsum, run);
  }
  delete[] buckets;
  *out = wsum;
}

#if ZKP2P_HAVE_IFMA
// Batch-affine G2 window fill: the Fq2 mirror of g1_window_sum's
// vector tier — affine buckets, stamp-deferred same-chunk conflicts,
// the 8-wide norm-route chunk apply, mixed-Jacobian bail for
// concentrated digit distributions.  An affine G2 add through the
// vector apply costs ~15 Fq vector muls per 8 adds vs the ~42 scalar
// Fq muls of a mixed-Jacobian G2 add.
static void g2_window_sum_affine(const u64 *bases, const int32_t *sd, long n,
                                 int c, int nwin, int wi, G2Jac *out) {
  const long nbuckets = (1L << (c - 1)) + 1;
  const long B = 1024;
  int bits_here = 254 - wi * c;
  if (bits_here > c) bits_here = c;
  if (bits_here < 1 || (1L << bits_here) < 4 * B) {
    g2_window_sum_jac(bases, sd, n, c, nwin, wi, out);
    return;
  }
  // affine buckets: rows of (x.c0 x.c1 y.c0 y.c1), all-zero = empty
  u64 (*bk)[16] = new u64[nbuckets][16]();
  int *stamp = new int[nbuckets];
  memset(stamp, 0xff, nbuckets * sizeof(int));
  std::vector<long> cur, next;
  cur.reserve(n);
  for (long i = 0; i < n; ++i) {
    if (!sd[i * nwin + wi]) continue;
    const u64 *b = bases + 16 * i;
    bool inf = true;
    for (int q = 0; q < 16 && inf; ++q) inf = b[q] == 0;
    if (!inf) cur.push_back(i);
  }
  long *add_bkt = new long[B];
  u64 (*x1a)[8] = new u64[B][8];
  u64 (*y1a)[8] = new u64[B][8];
  u64 (*x2a)[8] = new u64[B][8];
  u64 (*y2a)[8] = new u64[B][8];
  u64 (*x3a)[8] = new u64[B][8];
  u64 (*y3a)[8] = new u64[B][8];
  unsigned char *dbl = new unsigned char[B];
  u64 *scratch = new u64[(size_t)17 * 5 * B];
  auto cleanup = [&]() {
    delete[] bk; delete[] stamp; delete[] add_bkt;
    delete[] x1a; delete[] y1a; delete[] x2a; delete[] y2a;
    delete[] x3a; delete[] y3a; delete[] dbl; delete[] scratch;
  };
  int chunk_id = 0;
  while (!cur.empty()) {
    next.clear();
    size_t processed = 0;
    bool bail = false;
    const bool pf = msm_interleave_enabled();
    for (size_t lo = 0; lo < cur.size() && !bail; lo += B, ++chunk_id) {
      size_t hi = lo + B < cur.size() ? lo + B : cur.size();
      long m = 0;
      for (size_t k = lo; k < hi; ++k) {
        // Two-level prefetch down the schedule: pull the digit word
        // first (far), then — once it is cheap to read — the dependent
        // stamp/bucket/base lines (near).  The bucket table and the
        // bases both sit beyond L2 at bench shape and the index
        // pattern is hardware-prefetch-blind.
        if (pf) {
          if (k + 32 < hi)
            _mm_prefetch((const char *)&sd[cur[k + 32] * nwin + wi],
                         _MM_HINT_T0);
          if (k + 16 < hi) {
            const long i2 = cur[k + 16];
            const int32_t d2 = sd[i2 * nwin + wi];
            const long b2 = d2 < 0 ? -d2 : d2;
            _mm_prefetch((const char *)&stamp[b2], _MM_HINT_T0);
            const char *pb = (const char *)&bk[b2];
            _mm_prefetch(pb, _MM_HINT_T0);
            _mm_prefetch(pb + 64, _MM_HINT_T0);
            const char *pp = (const char *)(bases + 16 * i2);
            _mm_prefetch(pp, _MM_HINT_T0);
            _mm_prefetch(pp + 64, _MM_HINT_T0);
          }
        }
        long i = cur[k];
        int32_t dgt = sd[i * nwin + wi];
        long bno = dgt < 0 ? -dgt : dgt;
        if (stamp[bno] == chunk_id) {
          next.push_back(i);
          continue;
        }
        stamp[bno] = chunk_id;
        const u64 *b = bases + 16 * i;
        u64 px[8], py[8];
        memcpy(px, b, 64);
        if (dgt < 0) {
          neg_y(py, b + 8);
          neg_y(py + 4, b + 12);
        } else {
          memcpy(py, b + 8, 64);
        }
        bool empty = true;
        for (int q = 0; q < 16 && empty; ++q) empty = bk[bno][q] == 0;
        if (empty) {  // install
          memcpy(bk[bno], px, 64);
          memcpy(bk[bno] + 8, py, 64);
          continue;
        }
        if (memcmp(bk[bno], px, 64) == 0) {
          if (memcmp(bk[bno] + 8, py, 64) == 0) {
            dbl[m] = 1;
          } else {
            memset(bk[bno], 0, 128);  // P + (-P)
            continue;
          }
        } else {
          dbl[m] = 0;
        }
        memcpy(x1a[m], bk[bno], 64);
        memcpy(y1a[m], bk[bno] + 8, 64);
        memcpy(x2a[m], px, 64);
        memcpy(y2a[m], py, 64);
        add_bkt[m] = bno;
        ++m;
      }
      processed = hi;
      if (!m) {
        if (next.size() * 2 > processed && processed >= (size_t)B) bail = true;
        continue;
      }
      g2_chunk_apply_ifma(x1a, y1a, x2a, y2a, dbl, m, x3a, y3a, scratch);
      for (long j = 0; j < m; ++j) {
        memcpy(bk[add_bkt[j]], x3a[j], 64);
        memcpy(bk[add_bkt[j]] + 8, y3a[j], 64);
      }
      if (next.size() * 2 > processed && processed >= (size_t)B) bail = true;
    }
    if (bail || next.size() * 4 > cur.size()) {
      // finish the stragglers with mixed-Jacobian adds, then merge
      G2Jac *jb = new G2Jac[nbuckets];
      memset(jb, 0, (size_t)nbuckets * sizeof(G2Jac));
      next.insert(next.end(), cur.begin() + processed, cur.end());
      for (long i : next) {
        int32_t dgt = sd[i * nwin + wi];
        long bno = dgt < 0 ? -dgt : dgt;
        const u64 *b = bases + 16 * i;
        Fp2 x2, y2;
        memcpy(x2.c0, b, 32);
        memcpy(x2.c1, b + 4, 32);
        if (dgt < 0) {
          neg_y(y2.c0, b + 8);
          neg_y(y2.c1, b + 12);
        } else {
          memcpy(y2.c0, b + 8, 32);
          memcpy(y2.c1, b + 12, 32);
        }
        g2_add_mixed(jb[bno], jb[bno], x2, y2);
      }
      G2Jac run, wsum;
      memset(&run, 0, sizeof(run));
      memset(&wsum, 0, sizeof(wsum));
      for (long d = nbuckets - 1; d >= 1; --d) {
        g2_add(run, jb[d]);
        bool empty = true;
        for (int q = 0; q < 16 && empty; ++q) empty = bk[d][q] == 0;
        if (!empty) {
          Fp2 x2, y2;
          memcpy(x2.c0, bk[d], 32);
          memcpy(x2.c1, bk[d] + 4, 32);
          memcpy(y2.c0, bk[d] + 8, 32);
          memcpy(y2.c1, bk[d] + 12, 32);
          g2_add_mixed(run, run, x2, y2);
        }
        g2_add(wsum, run);
      }
      delete[] jb;
      cleanup();
      *out = wsum;
      return;
    }
    cur.swap(next);
  }
  G2Jac run, wsum;
  memset(&run, 0, sizeof(run));
  memset(&wsum, 0, sizeof(wsum));
  for (long d = nbuckets - 1; d >= 1; --d) {
    bool empty = true;
    for (int q = 0; q < 16 && empty; ++q) empty = bk[d][q] == 0;
    if (!empty) {
      Fp2 x2, y2;
      memcpy(x2.c0, bk[d], 32);
      memcpy(x2.c1, bk[d] + 4, 32);
      memcpy(y2.c0, bk[d] + 8, 32);
      memcpy(y2.c1, bk[d] + 12, 32);
      g2_add_mixed(run, run, x2, y2);
    }
    g2_add(wsum, run);
  }
  cleanup();
  *out = wsum;
}
#endif  // ZKP2P_HAVE_IFMA

static void g2_window_sum(const u64 *bases, const int32_t *sd, long n,
                          int c, int nwin, int wi, G2Jac *out) {
#if ZKP2P_HAVE_IFMA
  if (ifma_enabled() && batch_affine_enabled()) {
    g2_window_sum_affine(bases, sd, n, c, nwin, wi, out);
    return;
  }
#endif
  g2_window_sum_jac(bases, sd, n, c, nwin, wi, out);
}

// Run window sums 0..nwin-1 through `sum_one(wi, &out[wi])`, on worker
// Vectorized SUM of a set of affine points (the scalar==±1 fast path of
// the witness MSMs: venmo's wires are ~90% SHA/DFA bits, so Pippenger
// sees half a million scalar-1 points piling into ONE bucket and bails
// to serial Jacobian — a pairwise tree through the 8-wide batch-affine
// apply does the same sum in ~n vector adds).  `ys` carries the
// (possibly negated) y of each point; both arrays are CONSUMED as
// scratch.  Result accumulated into *out (Jacobian).
static void g1_tree_sum(u64 (*xs)[4], u64 (*ys)[4], long n, G1Jac *out) {
  memset(out, 0, sizeof(G1Jac));
  if (n <= 0) return;
#if ZKP2P_HAVE_IFMA
  if (ifma_enabled() && n >= 64) {
    const long B = 2048;
    u64 (*x1a)[4] = new u64[B][4];
    u64 (*y1a)[4] = new u64[B][4];
    u64 (*x2a)[4] = new u64[B][4];
    u64 (*y2a)[4] = new u64[B][4];
    u64 (*x3a)[4] = new u64[B][4];
    u64 (*y3a)[4] = new u64[B][4];
    unsigned char *dbl = new unsigned char[B];
    u64 *scratch = new u64[(size_t)9 * 5 * B];
    while (n > 1) {
      long w = 0;  // write cursor for the next level
      long p = 0;  // pair read cursor
      while (p + 1 < n) {
        long m = 0;
        // schedule up to B pairs
        while (p + 1 < n && m < B) {
          u64 *x1 = xs[p], *y1 = ys[p], *x2 = xs[p + 1], *y2 = ys[p + 1];
          bool inf1 = is_zero4(x1) && is_zero4(y1);
          bool inf2 = is_zero4(x2) && is_zero4(y2);
          if (inf1 && inf2) {
            p += 2;
            continue;  // drop
          }
          if (inf1 || inf2) {  // pass the finite one through
            memcpy(xs[w], inf1 ? x2 : x1, 32);
            memcpy(ys[w], inf1 ? y2 : y1, 32);
            ++w;
            p += 2;
            continue;
          }
          if (memcmp(x1, x2, 32) == 0) {
            if (memcmp(y1, y2, 32) == 0) {
              dbl[m] = 1;  // doubling lane (apply handles)
            } else {
              p += 2;  // P + (-P): drop
              continue;
            }
          } else {
            dbl[m] = 0;
          }
          memcpy(x1a[m], x1, 32);
          memcpy(y1a[m], y1, 32);
          memcpy(x2a[m], x2, 32);
          memcpy(y2a[m], y2, 32);
          ++m;
          p += 2;
        }
        if (m > 0) {
          g1_chunk_apply_ifma(x1a, y1a, x2a, y2a, dbl, m, x3a, y3a, scratch);
          for (long j = 0; j < m; ++j) {
            memcpy(xs[w], x3a[j], 32);
            memcpy(ys[w], y3a[j], 32);
            ++w;
          }
        }
      }
      if (p < n) {  // odd leftover carries to the next level
        memcpy(xs[w], xs[p], 32);
        memcpy(ys[w], ys[p], 32);
        ++w;
      }
      n = w;
    }
    delete[] x1a;
    delete[] y1a;
    delete[] x2a;
    delete[] y2a;
    delete[] x3a;
    delete[] y3a;
    delete[] dbl;
    delete[] scratch;
    if (n == 1 && !(is_zero4(xs[0]) && is_zero4(ys[0]))) {
      jac_add_mixed(*out, *out, xs[0], ys[0]);
    }
    return;
  }
#endif
  for (long i = 0; i < n; ++i) {
    if (is_zero4(xs[i]) && is_zero4(ys[i])) continue;
    jac_add_mixed(*out, *out, xs[i], ys[i]);
  }
}

// the persistent worker pool when n_threads > 1.  Shared by the G1 and
// G2 MSMs (one driver to tune, not two copies).  The pool is grown to
// n_threads once and reused across calls — no thread spawn per MSM.
template <typename P, typename F>
static void run_window_sums(int nwin, int n_threads, P *wins, F sum_one) {
  if (n_threads > 1) {
    int w = n_threads < nwin ? n_threads : nwin;
    work_pool().ensure(w);
    work_pool().run(nwin, [&](long wi) { sum_one((int)wi, &wins[wi]); }, w);
  } else {
    for (int wi = 0; wi < nwin; ++wi) sum_one(wi, &wins[wi]);
  }
}

extern "C" {

// Variable-base Pippenger MSM over G1.  bases: n x 8 u64 affine
// Montgomery ((0,0) = infinity); scalars: n x 4 u64 STANDARD form
// (< r); out_xy: 8 u64 affine STANDARD form, (0,0) = infinity.
// Window width c is caller-chosen (glue picks ~log2(n)-7, clamped).
// n_threads > 1 computes window sums on worker threads (per-thread
// bucket memory: 96 B * 2^c each).
// Partition scalar indices for the MSM drivers: 0 dropped, +-1 into
// (ones, ones_neg) for the tree-sum path, the rest into `rest`.  ONE
// helper for G1 and G2 so the classification can never diverge.
static void classify_scalars(const u64 *scalars, long n, std::vector<long> &rest,
                             std::vector<long> &ones, std::vector<unsigned char> &ones_neg) {
  static const u64 ONE_S[4] = {1, 0, 0, 0};
  u64 rm1[4];
  sub_nored(rm1, R_MOD, ONE_S);
  rest.reserve(n);
  for (long i = 0; i < n; ++i) {
    const u64 *s = scalars + 4 * i;
    if (is_zero4(s)) continue;
    if (memcmp(s, ONE_S, 32) == 0) {
      ones.push_back(i);
      ones_neg.push_back(0);
    } else if (memcmp(s, rm1, 32) == 0) {
      ones.push_back(i);
      ones_neg.push_back(1);
    } else {
      rest.push_back(i);
    }
  }
}

// Tree-sum the +-1-scalar lanes (the dominant witness-MSM case) — shared
// by the plain and GLV Pippenger drivers.
static void g1_ones_tree_sum(const u64 *bases_xy, const std::vector<long> &ones,
                             const std::vector<unsigned char> &ones_neg, G1Jac *out) {
  memset(out, 0, sizeof(G1Jac));
  if (ones.empty()) return;
  long no = (long)ones.size();
  u64 (*xs)[4] = new u64[no][4];
  u64 (*ys)[4] = new u64[no][4];
  for (long k = 0; k < no; ++k) {
    const u64 *bx = bases_xy + 8 * ones[k];
    memcpy(xs[k], bx, 32);
    signed_pt_y(ys[k], bx + 4, ones_neg[k] != 0);
    if (is_zero4(bx) && is_zero4(bx + 4)) memset(ys[k], 0, 32);  // keep holes (0,0)
  }
  g1_tree_sum(xs, ys, no, out);
  delete[] xs;
  delete[] ys;
}

// Jacobian accumulator -> standard-form affine out_xy (the shared MSM tail).
static void g1_jac_out(const G1Jac &acc, u64 *out_xy) {
  if (is_zero4(acc.Z)) {
    memset(out_xy, 0, 64);
    return;
  }
  u64 zi[4], zi2[4], zi3[4], mx[4], my[4];
  mont_inv(zi, acc.Z);
  mont_sqr(zi2, zi);
  mont_mul(zi3, zi2, zi);
  mont_mul(mx, acc.X, zi2);
  mont_mul(my, acc.Y, zi3);
  fp_from_mont(mx, out_xy, 1);
  fp_from_mont(my, out_xy + 4, 1);
}

// The window-parallel Pippenger middle shared by the plain and GLV G1
// drivers: precomputed signed digits in (nr points x nwin windows),
// window sums + Horner fold added into *acc (caller-zeroed).
// b52_ext (opaque u64 rows of 10 = Aff52) lets the fixed-base tier pass
// its PERSISTENT 52-limb table so the per-MSM mont256 -> mont260
// conversion disappears from the hot loop; nullptr keeps the per-call
// conversion the variable-base drivers have always paid.
static void g1_pippenger_core(const u64 *pb, const int32_t *sd, long nr, int c,
                              int nwin, int n_threads, G1Jac *acc_out,
                              int total_bits = 254,
                              const u64 *b52_ext = nullptr) {
  G1Jac &acc = *acc_out;
  // ZKP2P_MSM_BATCH_AFFINE=0: every window through the mixed-Jacobian
  // fill — the A/B arm measuring what affine buckets + the shared batch
  // inversion buy (both the IFMA 52-limb tier and the scalar tier are
  // batch-affine, so the gate sits above them, read once per MSM).
  const bool batch_affine = batch_affine_enabled();
  {
    G1Jac *wins = new G1Jac[nwin];
#if ZKP2P_HAVE_IFMA
    const Aff52 *b52 = nullptr;
    Aff52 *b52_own = nullptr;
    if (ifma_enabled() && batch_affine) {
      if (b52_ext) {
        b52 = (const Aff52 *)b52_ext;
      } else {
        // one mont256 -> mont260 conversion per MSM; every window's fill
        // then runs conversion-free (persistent 52-limb storage)
        b52_own = new Aff52[nr];
        g1_bases_to_52(pb, nr, b52_own);
        b52 = b52_own;
      }
    }
#endif
#if ZKP2P_HAVE_IFMA
    // Deferred windows leave their bucket arrays in allbk; the vector
    // suffix then reduces up to SUFFIX_MAX_LANES windows in one call
    // (8-lane groups, interleaved) instead of 2^(c-1) serial Jacobian
    // adds per window.
    const long nbuckets52 = (1L << (c - 1)) + 1;
    Aff52 *allbk = nullptr;
    unsigned char *defer = nullptr;
    // Defer only single-threaded: with worker threads each window's
    // serial suffix already runs CONCURRENTLY on its own worker, and a
    // post-join vector pass would serialize that tail instead.  The
    // size cap only matters for the fixed tier's wide windows (the
    // variable-base sweep range never approaches it): past it the
    // windows reduce serially rather than holding a multi-hundred-MB
    // lane block.
    if (b52 && n_threads <= 1 &&
        (size_t)nwin * (size_t)nbuckets52 * sizeof(Aff52) <= ((size_t)256 << 20)) {
      allbk = new Aff52[(size_t)nwin * (size_t)nbuckets52]();
      defer = new unsigned char[nwin]();
    }
#endif
    run_window_sums(nwin, n_threads, wins, [&](int wi, G1Jac *o) {
#if ZKP2P_HAVE_IFMA
      if (b52) {
        if (!allbk) {  // multi-threaded: internal per-worker suffix
          g1_window_sum_52(pb, b52, sd, nr, c, nwin, wi, o, nullptr, total_bits);
          return;
        }
        defer[wi] = g1_window_sum_52(pb, b52, sd, nr, c, nwin, wi, o,
                                     allbk + (size_t)wi * (size_t)nbuckets52,
                                     total_bits)
                        ? 1
                        : 0;
        return;
      }
#endif
      if (batch_affine) {
        g1_window_sum(pb, sd, nr, c, nwin, wi, o, total_bits);
      } else {
        g1_window_sum_jac(pb, sd, nr, c, nwin, wi, o);
      }
    });
#if ZKP2P_HAVE_IFMA
    if (allbk) {
      long long sf0 = prof_now_ns();
      int lanes[SUFFIX_MAX_LANES], nl = 0;
      G1Jac louts[SUFFIX_MAX_LANES];
      for (int wi = 0; wi <= nwin; ++wi) {
        if (wi < nwin && defer[wi]) lanes[nl++] = wi;
        if (nl == SUFFIX_MAX_LANES || (wi == nwin && nl > 0)) {
          g1_suffix8(allbk, nbuckets52, lanes, nl, louts);
          for (int k = 0; k < nl; ++k) wins[lanes[k]] = louts[k];
          nl = 0;
        }
      }
      {
        long long sf = prof_now_ns() - sf0;
        stat_add(ST_MSM_SUFFIX_NS, sf);
        if (msm_prof_enabled()) g_prof_suffix_ns += sf;
      }
      delete[] allbk;
      delete[] defer;
    }
#endif
#if ZKP2P_HAVE_IFMA
    delete[] b52_own;
#endif
    for (int wi = nwin - 1; wi >= 0; --wi) {
      if (wi != nwin - 1)
        for (int k = 0; k < c; ++k) jac_double(acc, acc);
      g1_add_jac(acc, wins[wi]);
    }
    delete[] wins;
  }
}

// ===================================================================
// Multi-column Pippenger: ONE sweep over a fixed base array fills S
// independent bucket sets per window (bucket id = s * nbuckets + |d|),
// so every batch-affine inversion round carries adds from ALL columns —
// the inversion batch density rises ~S x exactly where the 52-bit and
// scalar batch-affine tiers pay their per-round costs (the chunk
// schedule, the one mont_inv per chunk, the SoA gather/transpose).  The
// chunk-apply kernels (g1_chunk_apply_52, the scalar batch inversion)
// run UNCHANGED: they address buckets through add_bkt and bases through
// add_pt, and neither cares that the bucket space is S arrays long.
// The amortized wins stack: the mont256 -> mont260 base conversion runs
// once for S MSMs, every base cache line is touched once per window
// instead of S times, and partially-filled chunks still ship full
// inversion batches.
//
// A work item is (point i, column s) encoded as i*S + s, built i-outer
// so the sweep stays base-sequential; digits come from per-column digit
// arrays sds[s] (row-major over the shared compacted index space, with
// all-zero rows for scalars another tier handled).  Column outputs are
// the exact group elements of S sequential single-column MSMs — the
// final affine canonicalization makes them byte-identical, so the
// sequential driver stays the parity oracle.

// Work-item encoding for the multi fills: (point i, column s) packed as
// (i << sbits) | s — shift/mask decode, never a runtime division (the
// schedule loop visits tens of millions of entries per MSM and S is not
// a compile-time constant).
static inline int multi_sbits(int S) {
  int sb = 0;
  while ((1 << sb) < S) ++sb;
  return sb;
}

// Run fn(0..njobs-1) on the pool (width-capped) or inline — the multi
// drivers' job runner (a job may span several output slots, unlike
// run_window_sums' one-window-one-slot contract).
static void run_indexed_jobs(long njobs, int n_threads,
                             const std::function<void(long)> &fn) {
  if (n_threads > 1 && njobs > 1) {
    int w = (long)n_threads < njobs ? n_threads : (int)njobs;
    work_pool().ensure(w);
    work_pool().run(njobs, fn, w);
  } else {
    for (long j = 0; j < njobs; ++j) fn(j);
  }
}

#if ZKP2P_HAVE_IFMA
// 52-native multi-column window fill: the S-column mirror of
// g1_window_sum_52.  bk_ext (caller-zeroed, S*nbuckets entries) defers
// the suffix to the caller's 8-lane vector pass (lane id = wi*S + s);
// returns true when it was filled, false when *outs was computed via a
// fallback tier or the internal per-column suffix.
static bool g1_window_sum_52_multi(const u64 *bases_xy, const Aff52 *b52,
                                   const int32_t *const *sds, int S, long n,
                                   int c, int nwin, int wi, G1Jac *outs,
                                   Aff52 *bk_ext, int total_bits) {
  Ifma52Field &F = fq52_field();
  const long nbuckets = (1L << (c - 1)) + 1;
  // Chunk size matches the single-column fill.  (Scaling it to 2048*S —
  // per-column conflict parity, S x fewer inversion rounds — was tried
  // and measured the whole batch ~12% SLOWER: the apply's SoA scratch
  // grows with B and evicts the bucket lines the schedule loop just
  // touched, costing a second miss per add at writeback.)
  const long B = 2048;
  int bits_here = total_bits - wi * c;
  if (bits_here > c) bits_here = c;
  if (bits_here < 1 || (1L << bits_here) < 4 * B) {
    // small/top windows: per column through the same tiers the
    // single-column driver takes (arm parity with the oracle path)
    for (int s = 0; s < S; ++s) {
      if (bits_here >= 0 && bits_here <= 8) {
        g1_window_sum_small(bases_xy, sds[s], n, c, nwin, wi, bits_here, &outs[s]);
      } else {
        g1_window_sum_jac(bases_xy, sds[s], n, c, nwin, wi, &outs[s]);
      }
    }
    return false;
  }
  const int sbits = multi_sbits(S);
  const long smask = (1L << sbits) - 1;
  Aff52 *bk = bk_ext ? bk_ext : new Aff52[(size_t)S * nbuckets]();
  int *stamp = new int[(size_t)S * nbuckets];
  memset(stamp, 0xff, (size_t)S * nbuckets * sizeof(int));
  std::vector<long> cur, next;
  cur.reserve((size_t)n * S);
  // i-outer entry order: all S columns of one point are adjacent, so
  // each base line is loaded once per window for the whole batch.  (A
  // point-block x column tiling was tried for bucket locality — it
  // kept each run inside one column's bucket set but quadrupled the
  // same-bucket defers back to the sequential rate and measured
  // net-slower; the prefetch below is the cheaper answer to the S-wide
  // bucket block's misses.)
  for (long i = 0; i < n; ++i) {
    if (aff52_is_zero(b52[i].x) && aff52_is_zero(b52[i].y)) continue;
    for (int s = 0; s < S; ++s)
      if (sds[s][i * nwin + wi]) cur.push_back((i << sbits) | s);
  }
  long *add_bkt = new long[B];
  long *add_pt = new long[B];
  unsigned char *negf = new unsigned char[B];
  u64 (*x3a)[5] = new u64[B][5];
  u64 (*y3a)[5] = new u64[B][5];
  unsigned char *dbl = new unsigned char[B];
  u64 *scratch = new u64[(size_t)8 * 5 * B];
  auto cleanup = [&]() {
    if (!bk_ext) delete[] bk;
    delete[] stamp;
    delete[] add_bkt;
    delete[] add_pt;
    delete[] negf;
    delete[] x3a;
    delete[] y3a;
    delete[] dbl;
    delete[] scratch;
  };
  int chunk_id = 0;
  long long n_dbl = 0, n_cancel = 0, n_defer = 0;
  long long fl0 = prof_now_ns();
  while (!cur.empty()) {
    next.clear();
    size_t processed = 0;
    bool bail = false;
    for (size_t lo = 0; lo < cur.size() && !bail; lo += B, ++chunk_id) {
      size_t hi = lo + B < cur.size() ? lo + B : cur.size();
      long m = 0;
      for (size_t k = lo; k < hi; ++k) {
        // prefetch the bucket line + stamp a few entries ahead: the
        // S-wide bucket block (S x nbuckets x 80 B) outgrows L2, and a
        // demand-missed bucket read stalls the whole schedule walk —
        // this is where the first multi profile lost its S x win
        if (k + 16 < hi) {
          long e2 = cur[k + 16];
          long i2 = e2 >> sbits;
          int s2 = (int)(e2 & smask);
          int32_t d2 = sds[s2][i2 * nwin + wi];
          long pb2 = (long)s2 * nbuckets + (d2 < 0 ? -d2 : d2);
          __builtin_prefetch(&stamp[pb2]);
          __builtin_prefetch(&bk[pb2]);
          __builtin_prefetch((const char *)&bk[pb2] + 64);
        }
        long e = cur[k];
        long i = e >> sbits;
        int s = (int)(e & smask);
        int32_t dgt = sds[s][i * nwin + wi];
        long bno = (long)s * nbuckets + (dgt < 0 ? -dgt : dgt);
        if (stamp[bno] == chunk_id) {
          next.push_back(e);
          ++n_defer;
          continue;
        }
        stamp[bno] = chunk_id;
        u64 py[5];
        if (dgt < 0) {
          neg52(py, b52[i].y, F);
        } else {
          memcpy(py, b52[i].y, 40);
        }
        if (aff52_is_zero(bk[bno].x) && aff52_is_zero(bk[bno].y)) {
          memcpy(bk[bno].x, b52[i].x, 40);
          memcpy(bk[bno].y, py, 40);
          continue;
        }
        if (memcmp(bk[bno].x, b52[i].x, 40) == 0) {
          if (memcmp(bk[bno].y, py, 40) == 0) {
            dbl[m] = 1;
            ++n_dbl;
          } else {
            memset(&bk[bno], 0, sizeof(Aff52));  // P + (-P)
            ++n_cancel;
            continue;
          }
        } else {
          dbl[m] = 0;
        }
        add_bkt[m] = bno;
        add_pt[m] = i;
        negf[m] = dgt < 0 ? 1 : 0;
        ++m;
      }
      processed = hi;
      if (!m) {
        if (next.size() * 2 > processed && processed >= (size_t)B) bail = true;
        continue;
      }
      long long ap0 = prof_now_ns();
      g1_chunk_apply_52(bk, b52, add_bkt, add_pt, negf, dbl, m, x3a, y3a, scratch);
      stat_add(ST_MSM_APPLY_NS, prof_now_ns() - ap0);
      const bool pf_wb = msm_interleave_enabled();
      for (long j = 0; j < m; ++j) {
        // write-prefetch ahead — the chunk working set evicted these
        // bucket lines since the gather (see g1_window_sum_52)
        if (pf_wb && j + 8 < m) {
          char *wb = (char *)&bk[add_bkt[j + 8]];
          __builtin_prefetch(wb, 1);
          __builtin_prefetch(wb + 64, 1);
        }
        memcpy(bk[add_bkt[j]].x, x3a[j], 40);
        memcpy(bk[add_bkt[j]].y, y3a[j], 40);
      }
      if (next.size() * 2 > processed && processed >= (size_t)B) bail = true;
    }
    if (bail || next.size() * 4 > cur.size()) {
      stat_add(ST_MSM_FILL_NS, prof_now_ns() - fl0);
      stat_add(ST_MSM_DBL_LANES, n_dbl);
      stat_add(ST_MSM_CANCEL_LANES, n_cancel);
      stat_add(ST_MSM_DEFER_HITS, n_defer);
      long long bs0 = prof_now_ns();
      G1Jac *jb = new G1Jac[(size_t)S * nbuckets];
      memset(jb, 0, (size_t)S * nbuckets * sizeof(G1Jac));
      next.insert(next.end(), cur.begin() + processed, cur.end());
      for (long e : next) {
        long i = e >> sbits;
        int s = (int)(e & smask);
        int32_t dgt = sds[s][i * nwin + wi];
        long bno = (long)s * nbuckets + (dgt < 0 ? -dgt : dgt);
        const u64 *x = bases_xy + 8 * i;
        u64 ys[4];
        signed_pt_y(ys, x + 4, dgt < 0);
        jac_add_mixed(jb[bno], jb[bno], x, ys);
      }
      stat_add(ST_MSM_BAILFILL_NS, prof_now_ns() - bs0);
      bs0 = prof_now_ns();
      for (int s = 0; s < S; ++s) {
        G1Jac run, wsum;
        memset(&run, 0, sizeof(run));
        memset(&wsum, 0, sizeof(wsum));
        for (long d = nbuckets - 1; d >= 1; --d) {
          g1_add_jac(run, jb[(long)s * nbuckets + d]);
          const Aff52 &bd = bk[(long)s * nbuckets + d];
          if (!(aff52_is_zero(bd.x) && aff52_is_zero(bd.y))) {
            u64 bx[4], by[4];
            limb52_to_mont256(bd.x, bx, F);
            limb52_to_mont256(bd.y, by, F);
            jac_add_mixed(run, run, bx, by);
          }
          g1_add_jac(wsum, run);
        }
        outs[s] = wsum;
      }
      stat_add(ST_MSM_SUFFIX_NS, prof_now_ns() - bs0);
      delete[] jb;
      cleanup();
      return false;
    }
    cur.swap(next);
  }
  stat_add(ST_MSM_FILL_NS, prof_now_ns() - fl0);
  stat_add(ST_MSM_DBL_LANES, n_dbl);
  stat_add(ST_MSM_CANCEL_LANES, n_cancel);
  stat_add(ST_MSM_DEFER_HITS, n_defer);
  if (bk_ext) {
    cleanup();
    return true;  // caller reduces the S lanes through the vector suffix
  }
  long long sf0 = prof_now_ns();
  for (int s = 0; s < S; ++s) {
    G1Jac run, wsum;
    memset(&run, 0, sizeof(run));
    memset(&wsum, 0, sizeof(wsum));
    for (long d = nbuckets - 1; d >= 1; --d) {
      const Aff52 &bd = bk[(long)s * nbuckets + d];
      if (!(aff52_is_zero(bd.x) && aff52_is_zero(bd.y))) {
        u64 bx[4], by[4];
        limb52_to_mont256(bd.x, bx, F);
        limb52_to_mont256(bd.y, by, F);
        jac_add_mixed(run, run, bx, by);
      }
      g1_add_jac(wsum, run);
    }
    outs[s] = wsum;
  }
  stat_add(ST_MSM_SUFFIX_NS, prof_now_ns() - sf0);
  cleanup();
  return false;
}
#endif  // ZKP2P_HAVE_IFMA

// Scalar-Montgomery multi-column window fill: the S-column mirror of
// g1_window_sum (the batch-affine tier on hosts without IFMA, or with
// it disabled).  Same shared-chunk batch inversion over the S-wide
// bucket space; num/den derive from the live bucket + base by index
// (each bucket is touched once per chunk, so the bucket at derive time
// IS its schedule-time state).  Internal per-column suffix.
static void g1_window_sum_multi(const u64 *bases_xy, const int32_t *const *sds,
                                int S, long n, int c, int nwin, int wi,
                                G1Jac *outs, int total_bits) {
  const long nbuckets = (1L << (c - 1)) + 1;
  const long B = 2048;  // single-column chunk — see the 52-bit multi fill
  int bits_here = total_bits - wi * c;
  if (bits_here > c) bits_here = c;
  if (bits_here < 1 || (1L << bits_here) < 4 * B) {
    for (int s = 0; s < S; ++s)
      g1_window_sum_jac(bases_xy, sds[s], n, c, nwin, wi, &outs[s]);
    return;
  }
  const int sbits = multi_sbits(S);
  const long smask = (1L << sbits) - 1;
  AffPt *bk = new AffPt[(size_t)S * nbuckets]();
  int *stamp = new int[(size_t)S * nbuckets];
  memset(stamp, 0xff, (size_t)S * nbuckets * sizeof(int));
  std::vector<long> cur, next;
  cur.reserve((size_t)n * S);
  // i-outer entry order — see the 52-bit multi fill
  for (long i = 0; i < n; ++i) {
    const u64 *x = bases_xy + 8 * i;
    if (is_zero4(x) && is_zero4(x + 4)) continue;
    for (int s = 0; s < S; ++s)
      if (sds[s][i * nwin + wi]) cur.push_back((i << sbits) | s);
  }
  long *add_bkt = new long[B];
  long *add_pt = new long[B];
  unsigned char *negf = new unsigned char[B];
  u64 (*den)[4] = new u64[B][4];
  u64 (*num)[4] = new u64[B][4];
  u64 (*prod)[4] = new u64[B][4];
  unsigned char *dbl = new unsigned char[B];
  auto cleanup = [&]() {
    delete[] bk;
    delete[] stamp;
    delete[] add_bkt;
    delete[] add_pt;
    delete[] negf;
    delete[] den;
    delete[] num;
    delete[] prod;
    delete[] dbl;
  };
  int chunk_id = 0;
  long long n_dbl = 0, n_cancel = 0, n_defer = 0;
  long long fl0 = prof_now_ns();
  while (!cur.empty()) {
    next.clear();
    size_t processed = 0;
    bool bail = false;
    for (size_t lo = 0; lo < cur.size() && !bail; lo += B, ++chunk_id) {
      size_t hi = lo + B < cur.size() ? lo + B : cur.size();
      long m = 0;
      for (size_t k = lo; k < hi; ++k) {
        if (k + 16 < hi) {  // see the 52-bit multi fill: hide the S-wide
          long e2 = cur[k + 16];  // bucket block's L2 misses
          long i2 = e2 >> sbits;
          int s2 = (int)(e2 & smask);
          int32_t d2 = sds[s2][i2 * nwin + wi];
          long pb2 = (long)s2 * nbuckets + (d2 < 0 ? -d2 : d2);
          __builtin_prefetch(&stamp[pb2]);
          __builtin_prefetch(&bk[pb2]);
        }
        long e = cur[k];
        long i = e >> sbits;
        int s = (int)(e & smask);
        int32_t dgt = sds[s][i * nwin + wi];
        long bno = (long)s * nbuckets + (dgt < 0 ? -dgt : dgt);
        if (stamp[bno] == chunk_id) {
          next.push_back(e);
          ++n_defer;
          continue;
        }
        stamp[bno] = chunk_id;
        const u64 *px = bases_xy + 8 * i;
        u64 py[4];
        signed_pt_y(py, px + 4, dgt < 0);
        if (aff_is_empty(bk[bno])) {
          memcpy(bk[bno].x, px, 32);
          memcpy(bk[bno].y, py, 32);
          continue;
        }
        if (memcmp(bk[bno].x, px, 32) == 0) {
          if (memcmp(bk[bno].y, py, 32) == 0) {
            dbl[m] = 1;
            ++n_dbl;
          } else {
            memset(&bk[bno], 0, sizeof(AffPt));  // P + (-P)
            ++n_cancel;
            continue;
          }
        } else {
          dbl[m] = 0;
        }
        add_bkt[m] = bno;
        add_pt[m] = i;
        negf[m] = dgt < 0 ? 1 : 0;
        ++m;
      }
      processed = hi;
      if (!m) {
        if (next.size() * 2 > processed && processed >= (size_t)B) bail = true;
        continue;
      }
      // shared batch inversion across ALL columns' adds in this chunk
      u64 run[4];
      memcpy(run, ONE_MONT, 32);
      for (long j = 0; j < m; ++j) {
        long b = add_bkt[j];
        const u64 *px = bases_xy + 8 * add_pt[j];
        if (dbl[j]) {
          u64 xsq[4], t[4];
          mont_sqr(xsq, bk[b].x);
          add_mod(t, xsq, xsq);
          add_mod(num[j], t, xsq);
          add_mod(den[j], bk[b].y, bk[b].y);
        } else {
          u64 py[4];
          signed_pt_y(py, px + 4, negf[j] != 0);
          sub_mod(num[j], py, bk[b].y);
          sub_mod(den[j], px, bk[b].x);
        }
        memcpy(prod[j], run, 32);
        mont_mul(run, run, den[j]);
      }
      u64 inv_all[4];
      mont_inv(inv_all, run);
      for (long j = m - 1; j >= 0; --j) {
        u64 dinv[4];
        mont_mul(dinv, inv_all, prod[j]);
        mont_mul(inv_all, inv_all, den[j]);
        long b = add_bkt[j];
        const u64 *px = bases_xy + 8 * add_pt[j];
        u64 lam[4], lam2[4], x3[4], y3[4], t[4];
        mont_mul(lam, num[j], dinv);
        mont_sqr(lam2, lam);
        sub_mod(x3, lam2, bk[b].x);
        sub_mod(x3, x3, px);
        sub_mod(t, bk[b].x, x3);
        mont_mul(t, lam, t);
        sub_mod(y3, t, bk[b].y);
        memcpy(bk[b].x, x3, 32);
        memcpy(bk[b].y, y3, 32);
      }
      if (next.size() * 2 > processed && processed >= (size_t)B) bail = true;
    }
    if (bail || next.size() * 4 > cur.size()) {
      stat_add(ST_MSM_FILL_NS, prof_now_ns() - fl0);
      stat_add(ST_MSM_DBL_LANES, n_dbl);
      stat_add(ST_MSM_CANCEL_LANES, n_cancel);
      stat_add(ST_MSM_DEFER_HITS, n_defer);
      long long bs0 = prof_now_ns();
      G1Jac *jb = new G1Jac[(size_t)S * nbuckets];
      memset(jb, 0, (size_t)S * nbuckets * sizeof(G1Jac));
      next.insert(next.end(), cur.begin() + processed, cur.end());
      for (long e : next) {
        long i = e >> sbits;
        int s = (int)(e & smask);
        int32_t dgt = sds[s][i * nwin + wi];
        long bno = (long)s * nbuckets + (dgt < 0 ? -dgt : dgt);
        const u64 *x = bases_xy + 8 * i;
        u64 ys[4];
        signed_pt_y(ys, x + 4, dgt < 0);
        jac_add_mixed(jb[bno], jb[bno], x, ys);
      }
      stat_add(ST_MSM_BAILFILL_NS, prof_now_ns() - bs0);
      bs0 = prof_now_ns();
      for (int s = 0; s < S; ++s) {
        G1Jac run, wsum;
        memset(&run, 0, sizeof(run));
        memset(&wsum, 0, sizeof(wsum));
        for (long d = nbuckets - 1; d >= 1; --d) {
          g1_add_jac(run, jb[(long)s * nbuckets + d]);
          const AffPt &bd = bk[(long)s * nbuckets + d];
          if (!aff_is_empty(bd)) jac_add_mixed(run, run, bd.x, bd.y);
          g1_add_jac(wsum, run);
        }
        outs[s] = wsum;
      }
      stat_add(ST_MSM_SUFFIX_NS, prof_now_ns() - bs0);
      delete[] jb;
      cleanup();
      return;
    }
    cur.swap(next);
  }
  stat_add(ST_MSM_FILL_NS, prof_now_ns() - fl0);
  stat_add(ST_MSM_DBL_LANES, n_dbl);
  stat_add(ST_MSM_CANCEL_LANES, n_cancel);
  stat_add(ST_MSM_DEFER_HITS, n_defer);
  long long sf0 = prof_now_ns();
  for (int s = 0; s < S; ++s) {
    G1Jac run, wsum;
    memset(&run, 0, sizeof(run));
    memset(&wsum, 0, sizeof(wsum));
    for (long d = nbuckets - 1; d >= 1; --d) {
      const AffPt &bd = bk[(long)s * nbuckets + d];
      if (!aff_is_empty(bd)) jac_add_mixed(run, run, bd.x, bd.y);
      g1_add_jac(wsum, run);
    }
    outs[s] = wsum;
  }
  stat_add(ST_MSM_SUFFIX_NS, prof_now_ns() - sf0);
  cleanup();
}

// The multi-column Pippenger middle: window sums filled S columns at a
// time (batch-affine tiers — the shared-inversion win) or per (window,
// column) (the Jacobian A/B arm, which has no rounds to share and so
// takes the wider parallel axis), Horner-folded per column into
// accs[0..S) (caller-zeroed).
static void g1_pippenger_core_multi(const u64 *pb, const int32_t *const *sds,
                                    int S, long nr, int c, int nwin,
                                    int n_threads, G1Jac *accs,
                                    int total_bits = 254,
                                    const u64 *b52_ext = nullptr) {
  const bool batch_affine = batch_affine_enabled();
  G1Jac *wins = new G1Jac[(size_t)nwin * S];
  if (!batch_affine) {
    run_indexed_jobs((long)nwin * S, n_threads, [&](long j) {
      int wi = (int)(j / S), s = (int)(j % S);
      g1_window_sum_jac(pb, sds[s], nr, c, nwin, wi, &wins[(size_t)wi * S + s]);
    });
  } else {
#if ZKP2P_HAVE_IFMA
    if (ifma_enabled()) {
      // the fixed tier's persistent table, else ONE conversion for S columns
      Aff52 *b52_own = nullptr;
      const Aff52 *b52 = (const Aff52 *)b52_ext;
      if (!b52) {
        b52_own = new Aff52[nr];
        g1_bases_to_52(pb, nr, b52_own);
        b52 = b52_own;
      }
      const long nbuckets52 = (1L << (c - 1)) + 1;
      Aff52 *allbk = nullptr;
      unsigned char *defer = nullptr;
      // Deferred vector suffix single-threaded only, like the
      // single-column core.  (Engaging it at n_threads > 1 was tried —
      // a lone multi call DID win, the post-join vector pass beating
      // two workers' serial walks — but in the real prove several
      // concurrent multi calls each hold an nwin x S x nbuckets x 80 B
      // lane block, ~300 MB of extra fill-write/suffix-read traffic
      // that thrashed what per-window local bucket arrays keep
      // cache-resident, and the whole batch measured ~15% slower.)
      // Memory cap: S multiplies the single-column block.
      if (n_threads <= 1 &&
          (size_t)nwin * S * (size_t)nbuckets52 * sizeof(Aff52) <=
              ((size_t)160 << 20)) {
        allbk = new Aff52[(size_t)nwin * S * (size_t)nbuckets52]();
        defer = new unsigned char[nwin]();
      }
      run_indexed_jobs(nwin, n_threads, [&](long wi) {
        if (!allbk) {
          g1_window_sum_52_multi(pb, b52, sds, S, nr, c, nwin, (int)wi,
                                 &wins[(size_t)wi * S], nullptr, total_bits);
          return;
        }
        defer[wi] =
            g1_window_sum_52_multi(
                pb, b52, sds, S, nr, c, nwin, (int)wi, &wins[(size_t)wi * S],
                allbk + (size_t)wi * S * (size_t)nbuckets52, total_bits)
                ? 1
                : 0;
      });
      if (allbk) {
        // one vector suffix over ALL deferred (window, column) lanes:
        // lane id wi*S + s indexes allbk exactly like a window id
        // indexes the single-column block, so g1_suffix8 runs unchanged
        // — and S columns mean fuller 8-lane groups than nwin alone.
        long long sf0 = prof_now_ns();
        int lanes[SUFFIX_MAX_LANES], nl = 0;
        G1Jac louts[SUFFIX_MAX_LANES];
        const long nlanes = (long)nwin * S;
        for (long ln = 0; ln <= nlanes; ++ln) {
          if (ln < nlanes && defer[ln / S]) lanes[nl++] = (int)ln;
          if (nl == SUFFIX_MAX_LANES || (ln == nlanes && nl > 0)) {
            g1_suffix8(allbk, nbuckets52, lanes, nl, louts);
            for (int k = 0; k < nl; ++k) wins[lanes[k]] = louts[k];
            nl = 0;
          }
        }
        stat_add(ST_MSM_SUFFIX_NS, prof_now_ns() - sf0);
        delete[] allbk;
        delete[] defer;
      }
      delete[] b52_own;
    } else
#endif
    {
      run_indexed_jobs(nwin, n_threads, [&](long wi) {
        g1_window_sum_multi(pb, sds, S, nr, c, nwin, (int)wi,
                            &wins[(size_t)wi * S], total_bits);
      });
    }
  }
  for (int s = 0; s < S; ++s) {
    G1Jac &acc = accs[s];
    for (int wi = nwin - 1; wi >= 0; --wi) {
      if (wi != nwin - 1)
        for (int k = 0; k < c; ++k) jac_double(acc, acc);
      g1_add_jac(acc, wins[(size_t)wi * S + s]);
    }
  }
  delete[] wins;
}

void g1_msm_pippenger_mt(const u64 *bases_xy, const u64 *scalars, long n,
                         int c, int n_threads, u64 *out_xy) {
  long long t0 = prof_now_ns();
  InflightStat _ifs(ST_MSM_INFLIGHT);
  stat_add(ST_MSM_G1_CALLS, 1);
  stat_add(ST_MSM_POINTS, n);
  stat_set(ST_MSM_WINDOW_LAST, c);
  if (batch_affine_enabled()) stat_add(ST_MSM_BATCH_AFFINE_CALLS, 1);
  // Scalar classification: 0 (contributes nothing), +-1 (the dominant
  // case for witness MSMs — bit wires — whose Pippenger digits all pile
  // into ONE bucket and force the serial bail path) go through the
  // vectorized tree sum; everything else rides Pippenger.
  std::vector<long> rest, ones;
  std::vector<unsigned char> ones_neg;
  classify_scalars(scalars, n, rest, ones, ones_neg);
  G1Jac ones_acc;
  g1_ones_tree_sum(bases_xy, ones, ones_neg, &ones_acc);

  G1Jac acc;
  memset(&acc, 0, sizeof(acc));
  long nr = (long)rest.size();
  if (nr > 0) {
    // compact the Pippenger inputs unless nothing was stripped
    const u64 *pb = bases_xy;
    const u64 *ps = scalars;
    u64 *cb = nullptr, *csc = nullptr;
    if (nr != n) {
      cb = new u64[(size_t)nr * 8];
      csc = new u64[(size_t)nr * 4];
      for (long k = 0; k < nr; ++k) {
        memcpy(cb + 8 * k, bases_xy + 8 * rest[k], 64);
        memcpy(csc + 4 * k, scalars + 4 * rest[k], 32);
      }
      pb = cb;
      ps = csc;
    }
    int nwin = (254 + c - 1) / c;
    // signed recoding needs the top window to absorb the carry (Fr < 2^254)
    while ((long)nwin * c < 255) ++nwin;
    int32_t *sd = new int32_t[(size_t)nr * nwin];
    for (long i = 0; i < nr; ++i) signed_digits(ps + 4 * i, c, nwin, sd + (size_t)i * nwin);
    g1_pippenger_core(pb, sd, nr, c, nwin, n_threads, &acc);
    delete[] sd;
    delete[] cb;
    delete[] csc;
  }
  g1_add_jac(acc, ones_acc);
  g1_jac_out(acc, out_xy);
  stat_add(ST_MSM_WALL_NS, prof_now_ns() - t0);
}

void g1_msm_pippenger(const u64 *bases_xy, const u64 *scalars, long n,
                      int c, u64 *out_xy) {
  g1_msm_pippenger_mt(bases_xy, scalars, n, c, 1, out_xy);
}

// ---------------------------------------------------------------------------
// GLV endomorphism MSM.  phi(x, y) = (beta*x, y) acts as multiplication
// by lambda (a cube root of unity in Fr), so each 254-bit scalar splits
// into two ~128-bit half-scalars k = k1 + k2*lambda and the n-point MSM
// runs as 2n points over HALF the windows.  All constants (beta in
// Montgomery form, the Barrett mus, the lattice-term magnitudes and
// subtract flags) are DERIVED in Python (field.bn254) and passed in as
// one u64 buffer — nothing curve-specific is hardcoded here, and the
// three implementations (host oracle, JAX limb kernel, this) are
// diffed integer-for-integer by the tests.
//
// glv_consts layout (u64 words):
//   [0..3]   beta (Montgomery)
//   [4..7]   mu1 = floor(|m1| * 2^256 / r)
//   [8..11]  mu2 = floor(|m2| * 2^256 / r)
//   [12..19] |a1|, |a2|   (k1 term magnitudes)
//   [20..27] |b1|, |b2|   (k2 term magnitudes)
//   [28]     flags: bit j   = subtract k1 term j
//                   bit 2+j = subtract k2 term j

static void mul256_full(const u64 a[4], const u64 b[4], u64 out[8]) {
  u64 t[8] = {0, 0, 0, 0, 0, 0, 0, 0};
  for (int i = 0; i < 4; ++i) {
    u128 carry = 0;
    for (int j = 0; j < 4; ++j) {
      u128 cur = (u128)a[i] * b[j] + t[i + j] + (u64)carry;
      t[i + j] = (u64)cur;
      carry = cur >> 64;
    }
    t[i + 4] = (u64)carry;
  }
  memcpy(out, t, 64);
}

static inline void add256_mod(u64 a[4], const u64 b[4]) {
  u128 carry = 0;
  for (int i = 0; i < 4; ++i) {
    u128 cur = (u128)a[i] + b[i] + (u64)carry;
    a[i] = (u64)cur;
    carry = cur >> 64;
  }
}

static inline void sub256_mod(u64 a[4], const u64 b[4]) {
  u128 borrow = 0;
  for (int i = 0; i < 4; ++i) {
    u128 cur = (u128)a[i] - b[i] - (u64)borrow;
    a[i] = (u64)cur;
    borrow = (cur >> 64) & 1;
  }
}

static inline void neg256(u64 a[4]) {
  u64 z[4] = {0, 0, 0, 0};
  u64 t[4];
  memcpy(t, a, 32);
  memcpy(a, z, 32);
  sub256_mod(a, t);
}

// One scalar -> (|k1|, neg1, |k2|, neg2), mod-2^256 wraparound exactly
// like the host oracle field.bn254.glv_decompose.
static void glv_split(const u64 k[4], const u64 *gc, u64 k1[4], int *neg1,
                      u64 k2[4], int *neg2) {
  u64 p[8], c1[4], c2[4], t[8];
  mul256_full(k, gc + 4, p);
  memcpy(c1, p + 4, 32);  // floor(k * mu1 / 2^256)
  mul256_full(k, gc + 8, p);
  memcpy(c2, p + 4, 32);
  const u64 flags = gc[28];
  const u64 *cs[2] = {c1, c2};
  memcpy(k1, k, 32);
  memset(k2, 0, 32);
  for (int j = 0; j < 2; ++j) {
    mul256_full(cs[j], gc + 12 + 4 * j, t);  // lo 4 limbs = product mod 2^256
    if ((flags >> j) & 1) sub256_mod(k1, t); else add256_mod(k1, t);
    mul256_full(cs[j], gc + 20 + 4 * j, t);
    if ((flags >> (2 + j)) & 1) sub256_mod(k2, t); else add256_mod(k2, t);
  }
  *neg1 = (int)(k1[3] >> 63);
  if (*neg1) neg256(k1);
  *neg2 = (int)(k2[3] >> 63);
  if (*neg2) neg256(k2);
}

extern "C" void glv_decompose_batch(const u64 *scalars, long n, const u64 *gc,
                                    u64 *out, unsigned char *negs) {
  // out[i] = |k1_i|, out[n+i] = |k2_i| (u64x4 rows); negs likewise.
  for (long i = 0; i < n; ++i) {
    int n1, n2;
    glv_split(scalars + 4 * i, gc, out + 4 * i, &n1, out + 4 * (n + i), &n2);
    negs[i] = (unsigned char)n1;
    negs[n + i] = (unsigned char)n2;
  }
}

extern "C" void g1_glv_phi_bases(const u64 *bases_xy, long n,
                                 const u64 *beta_mont, u64 *out_xy) {
  // out[i] = phi(P_i) = (beta * x_i, y_i); (0,0) holes map to (0,0)
  // (beta * 0 = 0), so pruned-key padding survives the endomorphism.
  for (long i = 0; i < n; ++i) {
    mont_mul(out_xy + 8 * i, bases_xy + 8 * i, beta_mont);
    memcpy(out_xy + 8 * i + 4, bases_xy + 8 * i + 4, 32);
  }
}

// GLV Pippenger driver: bases2_xy is the 2*nb-point doubled base set
// [P_0..P_{nb-1}, phi(P_0)..phi(P_{nb-1})] (see g1_glv_phi_bases; the
// caller caches it per key, so the phi half sits at offset nb
// regardless of how many scalars this call brings); scalars stay the
// n (<= nb) original Fr scalars.  glv_bits bounds |k_i| (< 2^glv_bits),
// so nwin = ceil((glv_bits+1)/c) — HALF the plain entry's window count
// at the same c.
void g1_msm_pippenger_glv_mt(const u64 *bases2_xy, const u64 *scalars, long n,
                             long nb, int c, int n_threads,
                             const u64 *glv_consts, int glv_bits, u64 *out_xy) {
  long long t0 = prof_now_ns();
  InflightStat _ifs(ST_MSM_INFLIGHT);
  stat_add(ST_MSM_GLV_CALLS, 1);
  stat_add(ST_MSM_POINTS, n);
  stat_set(ST_MSM_WINDOW_LAST, c);
  if (batch_affine_enabled()) stat_add(ST_MSM_BATCH_AFFINE_CALLS, 1);
  std::vector<long> rest, ones;
  std::vector<unsigned char> ones_neg;
  classify_scalars(scalars, n, rest, ones, ones_neg);
  G1Jac ones_acc;
  g1_ones_tree_sum(bases2_xy, ones, ones_neg, &ones_acc);  // +-1: plain P_i half

  G1Jac acc;
  memset(&acc, 0, sizeof(acc));
  long nr = (long)rest.size();
  if (nr > 0) {
    int nwin = (glv_bits + c - 1) / c;
    while ((long)nwin * c < glv_bits + 1) ++nwin;  // top-window carry absorb
    // Compact only when needed (same rule as the plain driver): with
    // nothing stripped and n == nb the doubled base array already has
    // the exact [P.., phi(P)..] layout the core wants — skip the
    // 2n x 64 B allocation + copy (~67 MB per prove at the 2^19 shape).
    const bool compact = nr != n || n != nb;
    const u64 *pb = bases2_xy;
    u64 *cb = nullptr;
    if (compact) {
      cb = new u64[(size_t)2 * nr * 8];
      pb = cb;
    }
    int32_t *sd = new int32_t[(size_t)2 * nr * nwin];
    for (long k = 0; k < nr; ++k) {
      long i = rest[k];
      if (compact) {
        memcpy(cb + 8 * k, bases2_xy + 8 * i, 64);
        memcpy(cb + 8 * (nr + k), bases2_xy + 8 * (nb + i), 64);
      }
      u64 k1[4], k2[4];
      int neg1, neg2;
      glv_split(scalars + 4 * i, glv_consts, k1, &neg1, k2, &neg2);
      int32_t *d1 = sd + (size_t)k * nwin;
      int32_t *d2 = sd + (size_t)(nr + k) * nwin;
      signed_digits(k1, c, nwin, d1);
      signed_digits(k2, c, nwin, d2);
      // a negative half-scalar negates every digit (the fill then adds
      // (x, p - y) — sign handling identical to any negative digit)
      if (neg1)
        for (int w = 0; w < nwin; ++w) d1[w] = -d1[w];
      if (neg2)
        for (int w = 0; w < nwin; ++w) d2[w] = -d2[w];
    }
    g1_pippenger_core(pb, sd, 2 * nr, c, nwin, n_threads, &acc, glv_bits);
    delete[] sd;
    delete[] cb;
  }
  g1_add_jac(acc, ones_acc);
  g1_jac_out(acc, out_xy);
  stat_add(ST_MSM_WALL_NS, prof_now_ns() - t0);
}

// Multi-column variable-base Pippenger over G1: one fixed base array,
// S scalar columns, S results (see the multi-column block above the
// single-column drivers).  scalars: S consecutive column blocks of
// n x 4 u64 STANDARD form (column s at scalars + s*n*4); out_xy: S x 8
// u64 affine STANDARD-form rows, (0,0) = infinity.
void g1_msm_pippenger_multi(const u64 *bases_xy, const u64 *scalars, long n,
                            int S, int c, int n_threads, u64 *out_xy) {
  if (S <= 0) return;
  long long t0 = prof_now_ns();
  InflightStat _ifs(ST_MSM_INFLIGHT);
  stat_add(ST_MSM_MULTI_CALLS, 1);
  stat_add(ST_MSM_MULTI_COLS, S);
  stat_set(ST_MSM_MULTI_COLS_LAST, S);
  stat_add(ST_MSM_G1_CALLS, 1);  // family counter, like the GLV multi's
  stat_add(ST_MSM_POINTS, (long long)n * S);
  stat_set(ST_MSM_WINDOW_LAST, c);
  if (batch_affine_enabled()) stat_add(ST_MSM_BATCH_AFFINE_CALLS, 1);

  std::vector<std::vector<long>> rest((size_t)S), ones((size_t)S);
  std::vector<std::vector<unsigned char>> ones_neg((size_t)S);
  std::vector<G1Jac> ones_acc((size_t)S);
  // union of the columns' Pippenger index sets: ONE compacted base
  // array serves every column (a column that stripped a point keeps
  // all-zero digits at its row — the fill skips them)
  std::vector<long> remap((size_t)n, -1);
  for (int s = 0; s < S; ++s) {
    classify_scalars(scalars + (size_t)4 * n * s, n, rest[s], ones[s], ones_neg[s]);
    for (long i : rest[s]) remap[i] = 0;
  }
  std::vector<long> idx;
  for (long i = 0; i < n; ++i)
    if (remap[i] == 0) {
      remap[i] = (long)idx.size();
      idx.push_back(i);
    }
  long nr = (long)idx.size();

  const u64 *pb = bases_xy;
  u64 *cb = nullptr;
  if (nr > 0 && nr != n) {
    cb = new u64[(size_t)nr * 8];
    for (long k = 0; k < nr; ++k) memcpy(cb + 8 * k, bases_xy + 8 * idx[k], 64);
    pb = cb;
  }
  int nwin = (254 + c - 1) / c;
  while ((long)nwin * c < 255) ++nwin;
  int32_t *sd = nr > 0 ? new int32_t[(size_t)S * nr * nwin]() : nullptr;
  // per-column prep: the +-1 tree sum and digit recode are column-local
  // and independent -> pool-parallel across columns
  run_indexed_jobs(S, n_threads, [&](long s) {
    long long p0 = prof_now_ns();
    g1_ones_tree_sum(bases_xy, ones[s], ones_neg[s], &ones_acc[s]);
    const u64 *col = scalars + (size_t)4 * n * s;
    int32_t *sdc = sd ? sd + (size_t)s * nr * nwin : nullptr;
    for (long i : rest[s])
      signed_digits(col + 4 * i, c, nwin, sdc + (size_t)remap[i] * nwin);
    stat_add(ST_MSM_MULTI_PREP_NS, prof_now_ns() - p0);
  });

  std::vector<G1Jac> accs((size_t)S);
  memset(accs.data(), 0, (size_t)S * sizeof(G1Jac));
  if (nr > 0) {
    std::vector<const int32_t *> sds((size_t)S);
    for (int s = 0; s < S; ++s) sds[s] = sd + (size_t)s * nr * nwin;
    g1_pippenger_core_multi(pb, sds.data(), S, nr, c, nwin, n_threads, accs.data());
  }
  for (int s = 0; s < S; ++s) {
    g1_add_jac(accs[s], ones_acc[s]);
    g1_jac_out(accs[s], out_xy + 8 * s);
  }
  delete[] sd;
  delete[] cb;
  stat_add(ST_MSM_WALL_NS, prof_now_ns() - t0);
}

// GLV multi-column driver: the S-column mirror of
// g1_msm_pippenger_glv_mt over the cached doubled base set
// [P.., phi(P)..] (phi half at offset nb).  Each column's rest scalars
// split per glv_split into rows k (k1 half) and nr+k (k2 half) of its
// digit array; the shared core then sweeps the 2*nr-point compacted
// base array ONCE for all S columns.
void g1_msm_pippenger_glv_multi(const u64 *bases2_xy, const u64 *scalars,
                                long n, long nb, int S, int c, int n_threads,
                                const u64 *glv_consts, int glv_bits,
                                u64 *out_xy) {
  if (S <= 0) return;
  long long t0 = prof_now_ns();
  InflightStat _ifs(ST_MSM_INFLIGHT);
  stat_add(ST_MSM_MULTI_CALLS, 1);
  stat_add(ST_MSM_MULTI_COLS, S);
  stat_set(ST_MSM_MULTI_COLS_LAST, S);
  stat_add(ST_MSM_GLV_CALLS, 1);
  stat_add(ST_MSM_POINTS, (long long)n * S);
  stat_set(ST_MSM_WINDOW_LAST, c);
  if (batch_affine_enabled()) stat_add(ST_MSM_BATCH_AFFINE_CALLS, 1);

  std::vector<std::vector<long>> rest((size_t)S), ones((size_t)S);
  std::vector<std::vector<unsigned char>> ones_neg((size_t)S);
  std::vector<G1Jac> ones_acc((size_t)S);
  std::vector<long> remap((size_t)n, -1);
  for (int s = 0; s < S; ++s) {
    classify_scalars(scalars + (size_t)4 * n * s, n, rest[s], ones[s], ones_neg[s]);
    for (long i : rest[s]) remap[i] = 0;
  }
  std::vector<long> idx;
  for (long i = 0; i < n; ++i)
    if (remap[i] == 0) {
      remap[i] = (long)idx.size();
      idx.push_back(i);
    }
  long nr = (long)idx.size();

  int nwin = (glv_bits + c - 1) / c;
  while ((long)nwin * c < glv_bits + 1) ++nwin;  // top-window carry absorb
  // Compact only when needed (the single-column driver's rule): with
  // nothing stripped and n == nb the cached doubled array already has
  // the [P.., phi(P)..] layout the core wants.
  const bool compact = nr != n || n != nb;
  const u64 *pb = bases2_xy;
  u64 *cb = nullptr;
  if (nr > 0 && compact) {
    cb = new u64[(size_t)2 * nr * 8];
    for (long k = 0; k < nr; ++k) {
      memcpy(cb + 8 * k, bases2_xy + 8 * idx[k], 64);
      memcpy(cb + 8 * (nr + k), bases2_xy + 8 * (nb + idx[k]), 64);
    }
    pb = cb;
  }
  int32_t *sd = nr > 0 ? new int32_t[(size_t)S * 2 * nr * nwin]() : nullptr;
  run_indexed_jobs(S, n_threads, [&](long s) {
    long long p0 = prof_now_ns();
    g1_ones_tree_sum(bases2_xy, ones[s], ones_neg[s], &ones_acc[s]);  // +-1: plain P_i half
    const u64 *col = scalars + (size_t)4 * n * s;
    int32_t *sdc = sd ? sd + (size_t)s * 2 * nr * nwin : nullptr;
    for (long i : rest[s]) {
      long k = remap[i];
      u64 k1[4], k2[4];
      int neg1, neg2;
      glv_split(col + 4 * i, glv_consts, k1, &neg1, k2, &neg2);
      int32_t *d1 = sdc + (size_t)k * nwin;
      int32_t *d2 = sdc + (size_t)(nr + k) * nwin;
      signed_digits(k1, c, nwin, d1);
      signed_digits(k2, c, nwin, d2);
      if (neg1)
        for (int w = 0; w < nwin; ++w) d1[w] = -d1[w];
      if (neg2)
        for (int w = 0; w < nwin; ++w) d2[w] = -d2[w];
    }
    stat_add(ST_MSM_MULTI_PREP_NS, prof_now_ns() - p0);
  });

  std::vector<G1Jac> accs((size_t)S);
  memset(accs.data(), 0, (size_t)S * sizeof(G1Jac));
  if (nr > 0) {
    std::vector<const int32_t *> sds((size_t)S);
    for (int s = 0; s < S; ++s) sds[s] = sd + (size_t)s * 2 * nr * nwin;
    g1_pippenger_core_multi(pb, sds.data(), S, 2 * nr, c, nwin, n_threads,
                            accs.data(), glv_bits);
  }
  for (int s = 0; s < S; ++s) {
    g1_add_jac(accs[s], ones_acc[s]);
    g1_jac_out(accs[s], out_xy + 8 * s);
  }
  delete[] sd;
  delete[] cb;
  stat_add(ST_MSM_WALL_NS, prof_now_ns() - t0);
}

// ===================================================================
// Fixed-base precomputed-window MSM.  The proving key's G1 base arrays
// are immutable for the life of a service, yet every prove re-ran the
// GLV split, the mont256 -> mont260 conversion, and a full bucket fill
// over them.  This tier trades that per-prove work for offline tables:
//
//   table level j holds  L_j[i] = 2^(j*q*c) * P_i   (affine Montgomery),
//
// built ONCE per (key, c, q, levels) by g1_precomp_build and persisted
// by the Python side.  A 254-bit scalar recoded into W signed base-2^c
// digits (W = ceil over 255 bits) then satisfies
//
//   k*P = sum_w d_w * 2^(w*c) * P
//       = sum_{r<q} 2^(r*c) * sum_j d_{j*q+r} * L_j[P]
//
// — i.e. the whole MSM is EXACTLY a plain Pippenger run over the
// "virtual" base array of levels*n table rows with only q windows
// (virtual point j*n+i carries digit d_{j*q+r} in virtual window r).
// g1_pippenger_core runs UNCHANGED on that framing: the batch-affine
// chunk pipeline, the IFMA 52-limb tier (fed the PERSISTENT converted
// table via b52_ext — no per-MSM conversion), the vector suffix, the
// bail path and the Horner fold (c doublings between the q virtual
// windows) all apply as-is.  What the hot loop no longer contains: the
// GLV split (wide windows beat halved scalars once the doubling chain
// is free), the base conversion, and (W - q) of the W per-window
// suffix reductions.  q is the depth knob's dual: levels = ceil(W/q)
// table copies cost levels*n*64 B (plus 80 B/row for the 52-limb form)
// and buy a q-window hot loop; q >= n_threads keeps the window-level
// parallel axis as wide as the pool.

// Windows needed by the fixed tier at width c: ceil(254/c) bumped until
// W*c >= 255 so the signed top-window carry is absorbed — the same rule
// the variable-base drivers apply inline.
static int fixed_nwin(int c) {
  int W = (254 + c - 1) / c;
  while ((long)W * c < 255) ++W;
  return W;
}

// Jacobian -> affine MONTGOMERY normalization with one shared field
// inversion per call (the Montgomery trick): the table-build tail.
// Z = 0 rows write the (0,0) infinity hole.
static void g1_jac_normalize_mont_batch(const G1Jac *in, long n, u64 *out_xy) {
  u64 (*pref)[4] = new u64[n][4];
  u64 run[4];
  memcpy(run, ONE_MONT, 32);
  for (long i = 0; i < n; ++i) {
    memcpy(pref[i], run, 32);
    if (!is_zero4(in[i].Z)) mont_mul(run, run, in[i].Z);
  }
  u64 inv[4];
  mont_inv(inv, run);
  for (long i = n - 1; i >= 0; --i) {
    u64 *o = out_xy + 8 * i;
    if (is_zero4(in[i].Z)) {
      memset(o, 0, 64);
      continue;
    }
    u64 zi[4], zi2[4], zi3[4];
    mont_mul(zi, inv, pref[i]);       // 1/Z_i
    mont_mul(inv, inv, in[i].Z);      // strip Z_i from the running inverse
    mont_sqr(zi2, zi);
    mont_mul(zi3, zi2, zi);
    mont_mul(o, in[i].X, zi2);
    mont_mul(o + 4, in[i].Y, zi3);
  }
  delete[] pref;
}

// Build the level tables: out_xy holds levels consecutive (n x 8 u64)
// affine-Montgomery blocks, level 0 a verbatim copy of bases_xy.  Each
// level is the previous one doubled q*c times — a Jacobian chain per
// point with ONE batched inversion per (level, point-chunk), so the
// per-point cost is ~q*c Jacobian doublings.  Pool-parallel over point
// chunks; (0,0) infinity holes propagate as holes through every level.
void g1_precomp_build(const u64 *bases_xy, long n, int c, int q, int levels,
                      int n_threads, u64 *out_xy) {
  long long t0 = prof_now_ns();
  memcpy(out_xy, bases_xy, (size_t)n * 64);
  if (levels > 1 && n > 0) {
    const int shift = q * c;
    const long CH = 2048;
    const long njobs = (n + CH - 1) / CH;
    run_indexed_jobs(njobs, n_threads, [&](long jb) {
      long lo = jb * CH;
      long hi = lo + CH < n ? lo + CH : n;
      long cnt = hi - lo;
      G1Jac *acc = new G1Jac[cnt];
      for (long k = 0; k < cnt; ++k) {
        const u64 *b = bases_xy + 8 * (lo + k);
        if (is_zero4(b) && is_zero4(b + 4)) {
          memset(&acc[k], 0, sizeof(G1Jac));
        } else {
          memcpy(acc[k].X, b, 32);
          memcpy(acc[k].Y, b + 4, 32);
          memcpy(acc[k].Z, ONE_MONT, 32);
        }
      }
      for (int lv = 1; lv < levels; ++lv) {
        for (long k = 0; k < cnt; ++k)
          for (int b = 0; b < shift; ++b) jac_double(acc[k], acc[k]);
        g1_jac_normalize_mont_batch(acc, cnt,
                                    out_xy + ((size_t)lv * n + lo) * 8);
      }
      delete[] acc;
    });
  }
  stat_add(ST_PRECOMP_BUILD_NS, prof_now_ns() - t0);
  stat_add(ST_PRECOMP_TABLE_BYTES, (long long)levels * n * 64);
}

// Convert a built table to the persistent 52-limb form the IFMA fill
// consumes (n_total rows of 10 u64 = one Aff52 each).  Returns 0 on a
// non-IFMA build/host — the caller then passes NULL to the fixed
// drivers and the scalar tier converts nothing (it reads mont256).
int g1_precomp_to52(const u64 *table_xy, long n_total, u64 *out52) {
#if ZKP2P_HAVE_IFMA
  if (ifma_enabled()) {
    g1_bases_to_52(table_xy, n_total, (Aff52 *)out52);
    return 1;
  }
#endif
  (void)table_xy;
  (void)n_total;
  (void)out52;
  return 0;
}

// Scatter one scalar's W-digit recoding into the virtual digit matrix:
// window w = j*q + r lands at virtual point j*n + i, virtual window r.
static inline void fixed_scatter_digits(const int32_t *dg, int W, int q,
                                        long n, long i, int32_t *sd) {
  for (int w = 0; w < W; ++w) {
    long v = (long)(w / q) * n + i;
    sd[(size_t)v * q + (w % q)] = dg[w];
  }
}

// Fixed-base precomputed-table Pippenger driver.  table_xy: the
// g1_precomp_build output (levels x n x 8 u64 affine Montgomery);
// table52: its g1_precomp_to52 form or NULL; scalars: nsc (<= n) rows
// of 4 u64 STANDARD form; out_xy: 8 u64 affine STANDARD form.  The
// result is the exact group element of the variable-base drivers for
// the same (bases, scalars) — canonicalization makes it byte-identical,
// so g1_msm_pippenger_mt stays the parity oracle.
void g1_msm_pippenger_fixed(const u64 *table_xy, const u64 *table52,
                            const u64 *scalars, long nsc, long n, int levels,
                            int c, int q, int n_threads, u64 *out_xy) {
  long long t0 = prof_now_ns();
  InflightStat _ifs(ST_MSM_INFLIGHT);
  stat_add(ST_MSM_FIXED_CALLS, 1);
  stat_add(ST_MSM_G1_CALLS, 1);
  stat_add(ST_MSM_POINTS, nsc);
  stat_set(ST_MSM_WINDOW_LAST, c);
  if (batch_affine_enabled()) stat_add(ST_MSM_BATCH_AFFINE_CALLS, 1);
  const int W = fixed_nwin(c);
  if (c < 4 || W > 64) abort();       // recode buffer bound (c >= 4 always)
  if ((long)levels * q < W) abort();  // table cannot cover the digit span
  std::vector<long> rest, ones;
  std::vector<unsigned char> ones_neg;
  classify_scalars(scalars, nsc, rest, ones, ones_neg);
  G1Jac ones_acc;
  g1_ones_tree_sum(table_xy, ones, ones_neg, &ones_acc);  // +-1: level 0
  G1Jac acc;
  memset(&acc, 0, sizeof(acc));
  long nr = (long)rest.size();
  if (nr > 0) {
    const long nv = (long)levels * n;
    // zero-initialized: non-rest virtual rows keep all-zero digits and
    // the fill skips them — the table is NEVER compacted or copied
    int32_t *sd = new int32_t[(size_t)nv * q]();
    long long p0 = prof_now_ns();
    const long CH = 8192;
    run_indexed_jobs((nr + CH - 1) / CH, n_threads, [&](long jb) {
      int32_t dg[64];  // W <= ceil(255/4) < 64 for every c >= 4
      long hi = (jb + 1) * CH < nr ? (jb + 1) * CH : nr;
      for (long k = jb * CH; k < hi; ++k) {
        long i = rest[k];
        signed_digits(scalars + 4 * i, c, W, dg);
        fixed_scatter_digits(dg, W, q, n, i, sd);
      }
    });
    stat_add(ST_MSM_FIXED_PREP_NS, prof_now_ns() - p0);
    // total_bits = q*c: every virtual window carries full c-bit digits
    // (middle real windows land in every lane), so no top-window
    // narrowing applies inside the core.
    g1_pippenger_core(table_xy, sd, nv, c, q, n_threads, &acc, q * c,
                      table52);
    delete[] sd;
  }
  g1_add_jac(acc, ones_acc);
  g1_jac_out(acc, out_xy);
  stat_add(ST_MSM_WALL_NS, prof_now_ns() - t0);
}

// Multi-column fixed-base driver: S scalar columns over ONE table —
// the batch path's gather/add mirror of g1_msm_pippenger_multi.
// scalars: S consecutive column blocks of nsc x 4 u64 STANDARD form;
// out_xy: S x 8 u64 affine STANDARD-form rows.  Column outputs equal S
// sequential g1_msm_pippenger_fixed calls byte-for-byte.
void g1_msm_pippenger_fixed_multi(const u64 *table_xy, const u64 *table52,
                                  const u64 *scalars, long nsc, long n, int S,
                                  int levels, int c, int q, int n_threads,
                                  u64 *out_xy) {
  if (S <= 0) return;
  long long t0 = prof_now_ns();
  InflightStat _ifs(ST_MSM_INFLIGHT);
  stat_add(ST_MSM_FIXED_CALLS, 1);
  stat_add(ST_MSM_MULTI_CALLS, 1);
  stat_add(ST_MSM_MULTI_COLS, S);
  stat_set(ST_MSM_MULTI_COLS_LAST, S);
  stat_add(ST_MSM_G1_CALLS, 1);
  stat_add(ST_MSM_POINTS, (long long)nsc * S);
  stat_set(ST_MSM_WINDOW_LAST, c);
  if (batch_affine_enabled()) stat_add(ST_MSM_BATCH_AFFINE_CALLS, 1);
  const int W = fixed_nwin(c);
  if (c < 4 || W > 64) abort();
  if ((long)levels * q < W) abort();
  const long nv = (long)levels * n;
  std::vector<G1Jac> ones_acc((size_t)S);
  int32_t *sd = new int32_t[(size_t)S * nv * q]();
  // per-column prep (classify, +-1 tree sum, digit scatter) is
  // column-local -> pool-parallel across columns, like the multi driver
  run_indexed_jobs(S, n_threads, [&](long s) {
    long long p0 = prof_now_ns();
    const u64 *col = scalars + (size_t)4 * nsc * s;
    std::vector<long> rest, ones;
    std::vector<unsigned char> ones_neg;
    classify_scalars(col, nsc, rest, ones, ones_neg);
    g1_ones_tree_sum(table_xy, ones, ones_neg, &ones_acc[s]);
    int32_t dg[64];
    int32_t *sdc = sd + (size_t)s * nv * q;
    for (long i : rest) {
      signed_digits(col + 4 * i, c, W, dg);
      fixed_scatter_digits(dg, W, q, n, i, sdc);
    }
    stat_add(ST_MSM_FIXED_PREP_NS, prof_now_ns() - p0);
  });
  std::vector<G1Jac> accs((size_t)S);
  memset(accs.data(), 0, (size_t)S * sizeof(G1Jac));
  std::vector<const int32_t *> sds((size_t)S);
  for (int s = 0; s < S; ++s) sds[s] = sd + (size_t)s * nv * q;
  g1_pippenger_core_multi(table_xy, sds.data(), S, nv, c, q, n_threads,
                          accs.data(), q * c, table52);
  for (int s = 0; s < S; ++s) {
    g1_add_jac(accs[s], ones_acc[s]);
    g1_jac_out(accs[s], out_xy + 8 * s);
  }
  delete[] sd;
  stat_add(ST_MSM_WALL_NS, prof_now_ns() - t0);
}

// Scale n affine STANDARD-form G1 points by ONE shared standard-form Fr
// scalar: out[i] = k * P[i].  The phase-2 ceremony hot loop (every
// contribution rescales the whole C and H query by 1/delta') — NAF of
// the shared scalar computed once, Jacobian double-add per point, one
// batched inversion for the final affine normalization.  (0,0) holes
// pass through.
void g1_scale_batch(const u64 *bases_xy, long n, const u64 *scalar, u64 *out_xy) {
  // width-2 NAF (digits -1/0/1), LSB first
  int naf[260];
  int nbits = 0;
  {
    u64 s[5] = {scalar[0], scalar[1], scalar[2], scalar[3], 0};
    auto is_zero = [&]() {
      for (int i = 0; i < 5; ++i)
        if (s[i]) return false;
      return true;
    };
    auto shr1 = [&]() {
      for (int i = 0; i < 4; ++i) s[i] = (s[i] >> 1) | (s[i + 1] << 63);
      s[4] >>= 1;
    };
    while (!is_zero() && nbits < 260) {
      if (s[0] & 1) {
        if ((s[0] & 3) == 3) {
          naf[nbits] = -1;  // d = -1, s += 1
          u128 c = 1;
          for (int i = 0; i < 5 && c; ++i) {
            u128 t = (u128)s[i] + c;
            s[i] = (u64)t;
            c = t >> 64;
          }
        } else {
          naf[nbits] = 1;  // d = 1, s -= 1
          s[0] -= 1;
        }
      } else {
        naf[nbits] = 0;
      }
      shr1();
      ++nbits;
    }
  }
  G1Jac *accs = new G1Jac[n > 0 ? n : 1];
  for (long i = 0; i < n; ++i) {
    const u64 *bx = bases_xy + 8 * i;
    const u64 *by = bx + 4;
    if (is_zero4(bx) && is_zero4(by)) {
      memset(&accs[i], 0, sizeof(G1Jac));
      continue;
    }
    u64 mx[4], my[4], nmy[4];
    mont_mul(mx, bx, R2P);
    mont_mul(my, by, R2P);
    sub_nored(nmy, P, my);
    G1Jac acc;
    memset(&acc, 0, sizeof(acc));
    for (int b = nbits - 1; b >= 0; --b) {
      jac_double(acc, acc);
      if (naf[b] == 1) {
        jac_add_mixed(acc, acc, mx, my);
      } else if (naf[b] == -1) {
        jac_add_mixed(acc, acc, mx, nmy);
      }
    }
    accs[i] = acc;
  }
  // batched affine normalization: one inversion for all nonzero Zs
  u64 *pref = new u64[(size_t)(n > 0 ? n : 1) * 4];
  u64 run[4];
  memcpy(run, ONE_MONT, 32);
  for (long i = 0; i < n; ++i) {
    memcpy(pref + 4 * i, run, 32);
    if (!is_zero4(accs[i].Z)) mont_mul(run, run, accs[i].Z);
  }
  u64 inv_all[4];
  mont_inv(inv_all, run);
  for (long i = n - 1; i >= 0; --i) {
    u64 *o = out_xy + 8 * i;
    if (is_zero4(accs[i].Z)) {
      memset(o, 0, 64);
      continue;
    }
    u64 zi[4], zi2[4], zi3[4], mx[4], my[4];
    mont_mul(zi, inv_all, pref + 4 * i);
    mont_mul(inv_all, inv_all, accs[i].Z);
    mont_sqr(zi2, zi);
    mont_mul(zi3, zi2, zi);
    mont_mul(mx, accs[i].X, zi2);
    mont_mul(my, accs[i].Y, zi3);
    fp_from_mont(mx, o, 1);
    fp_from_mont(my, o + 4, 1);
  }
  delete[] pref;
  delete[] accs;
}

// Variable-base Pippenger MSM over G2.  bases: n x 16 u64 affine
// Montgomery (x.c0, x.c1, y.c0, y.c1; all-zero = infinity); scalars
// standard form; out: 16 u64 affine STANDARD form, all-zero = infinity.
void g2_msm_pippenger_mt(const u64 *bases, const u64 *scalars, long n,
                         int c, int n_threads, u64 *out) {
  long long t0 = prof_now_ns();
  InflightStat _ifs(ST_MSM_INFLIGHT);
  stat_add(ST_MSM_G2_CALLS, 1);
  stat_add(ST_MSM_POINTS, n);
  stat_set(ST_MSM_WINDOW_LAST, c);
  if (batch_affine_enabled()) stat_add(ST_MSM_BATCH_AFFINE_CALLS, 1);
  // scalar classification, as the G1 driver: 0 skipped, +-1 through the
  // vectorized Fq2 tree sum, the rest through Pippenger
  std::vector<long> rest, ones;
  std::vector<unsigned char> ones_neg;
  classify_scalars(scalars, n, rest, ones, ones_neg);
  G2Jac ones_acc;
  memset(&ones_acc, 0, sizeof(ones_acc));
#if ZKP2P_HAVE_IFMA
  if (!ones.empty()) {
    long no = (long)ones.size();
    u64 (*xs)[8] = new u64[no][8];
    u64 (*ys)[8] = new u64[no][8];
    for (long k = 0; k < no; ++k) {
      const u64 *b = bases + 16 * ones[k];
      memcpy(xs[k], b, 64);
      if (ones_neg[k]) {
        u64 t[4];
        neg_y(t, b + 8);
        memcpy(ys[k], t, 32);
        neg_y(t, b + 12);
        memcpy(ys[k] + 4, t, 32);
      } else {
        memcpy(ys[k], b + 8, 64);
      }
      if (is_zero4(b) && is_zero4(b + 4) && is_zero4(b + 8) && is_zero4(b + 12))
        memset(ys[k], 0, 64);  // keep holes fully zero
    }
    g2_tree_sum(xs, ys, no, &ones_acc);
    delete[] xs;
    delete[] ys;
    ones.clear();
  }
#endif
  // non-IFMA COMPILE only: the tree path does not exist, so ones ride
  // Pippenger as before.  (On an IFMA build with the feature disabled
  // at runtime, g2_tree_sum above already handled them via its serial
  // g2_add_mixed fallback and cleared the list — this loop is a no-op.)
  for (long i : ones) rest.push_back(i);
  if (!ones.empty()) std::sort(rest.begin(), rest.end());

  G2Jac acc;
  memset(&acc, 0, sizeof(acc));
  long nr = (long)rest.size();
  if (nr > 0) {
    const u64 *pb = bases;
    const u64 *ps = scalars;
    u64 *cb = nullptr, *csc = nullptr;
    if (nr != n) {
      cb = new u64[(size_t)nr * 16];
      csc = new u64[(size_t)nr * 4];
      for (long k = 0; k < nr; ++k) {
        memcpy(cb + 16 * k, bases + 16 * rest[k], 128);
        memcpy(csc + 4 * k, scalars + 4 * rest[k], 32);
      }
      pb = cb;
      ps = csc;
    }
    int nwin = (254 + c - 1) / c;
    while ((long)nwin * c < 255) ++nwin;
    int32_t *sd = new int32_t[(size_t)nr * nwin];
    for (long i = 0; i < nr; ++i) signed_digits(ps + 4 * i, c, nwin, sd + (size_t)i * nwin);
    G2Jac *wins = new G2Jac[nwin];
    run_window_sums(nwin, n_threads, wins, [&](int wi, G2Jac *o) {
      g2_window_sum(pb, sd, nr, c, nwin, wi, o);
    });
    delete[] sd;
    for (int wi = nwin - 1; wi >= 0; --wi) {
      if (wi != nwin - 1)
        for (int k = 0; k < c; ++k) {
          G2Jac d2;
          g2_double(d2, acc);
          acc = d2;
        }
      g2_add(acc, wins[wi]);
    }
    delete[] wins;
    delete[] cb;
    delete[] csc;
  }
  g2_add(acc, ones_acc);
  if (fp2_is_zero(acc.Z)) {
    memset(out, 0, 128);
    stat_add(ST_MSM_WALL_NS, prof_now_ns() - t0);
    return;
  }
  Fp2 zi, zi2, zi3, mx, my;
  fp2_inv(zi, acc.Z);
  fp2_sqr(zi2, zi);
  fp2_mul(zi3, zi2, zi);
  fp2_mul(mx, acc.X, zi2);
  fp2_mul(my, acc.Y, zi3);
  fp_from_mont(mx.c0, out, 1);
  fp_from_mont(mx.c1, out + 4, 1);
  fp_from_mont(my.c0, out + 8, 1);
  fp_from_mont(my.c1, out + 12, 1);
  stat_add(ST_MSM_WALL_NS, prof_now_ns() - t0);
}

void g2_msm_pippenger(const u64 *bases, const u64 *scalars, long n,
                      int c, u64 *out) {
  g2_msm_pippenger_mt(bases, scalars, n, c, 1, out);
}

}  // extern "C"
