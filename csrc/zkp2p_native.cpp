// Native BN254 host library: Montgomery field arithmetic + G1/G2 fixed-base.
//
// The runtime role rapidsnark's x86-asm field library plays in the
// reference (SURVEY.md §2.2): the host-side hot loops — trusted-setup
// query-point generation, witness-side bignum math — run here instead of
// Python bigints (~400x).  The TPU compute path stays JAX/XLA; this is
// the CPU runtime around it.  Exposed as extern "C" for ctypes
// (zkp2p_tpu.native.lib); every entry point is batch-oriented.
//
// Field elements: 4 x 64-bit little-endian limbs, Montgomery form with
// R = 2^256.  unsigned __int128 provides the 64x64->128 multiply.

#include <cstdint>
#include <cstring>

typedef unsigned __int128 u128;
typedef uint64_t u64;

// BN254 base field p and scalar field r moduli (little-endian limbs).
static const u64 P[4] = {0x3c208c16d87cfd47ULL, 0x97816a916871ca8dULL,
                         0xb85045b68181585dULL, 0x30644e72e131a029ULL};
static const u64 PINV = 0x87d20782e4866389ULL;  // -p^-1 mod 2^64
// R^2 mod p
static const u64 R2P[4] = {0xf32cfc5b538afa89ULL, 0xb5e71911d44501fbULL,
                           0x47ab1eff0a417ff6ULL, 0x06d89f71cab8351fULL};

struct Fp {
  u64 v[4];
};

static inline bool geq(const u64 a[4], const u64 b[4]) {
  for (int i = 3; i >= 0; --i) {
    if (a[i] != b[i]) return a[i] > b[i];
  }
  return true;
}

static inline void sub_nored(u64 out[4], const u64 a[4], const u64 b[4]) {
  u128 borrow = 0;
  for (int i = 0; i < 4; ++i) {
    u128 d = (u128)a[i] - b[i] - borrow;
    out[i] = (u64)d;
    borrow = (d >> 64) & 1;
  }
}

static inline void add_mod(u64 out[4], const u64 a[4], const u64 b[4]) {
  u64 t[5] = {0, 0, 0, 0, 0};
  u128 carry = 0;
  for (int i = 0; i < 4; ++i) {
    u128 s = (u128)a[i] + b[i] + carry;
    t[i] = (u64)s;
    carry = s >> 64;
  }
  t[4] = (u64)carry;
  if (t[4] || geq(t, P)) {
    sub_nored(out, t, P);
  } else {
    memcpy(out, t, 32);
  }
}

static inline void sub_mod(u64 out[4], const u64 a[4], const u64 b[4]) {
  if (geq(a, b)) {
    sub_nored(out, a, b);
  } else {
    u64 t[4];
    sub_nored(t, b, a);
    sub_nored(out, P, t);
  }
}

// CIOS Montgomery multiplication.
static void mont_mul(u64 out[4], const u64 a[4], const u64 b[4]) {
  u64 t[6] = {0, 0, 0, 0, 0, 0};
  for (int i = 0; i < 4; ++i) {
    u128 carry = 0;
    for (int j = 0; j < 4; ++j) {
      u128 s = (u128)t[j] + (u128)a[i] * b[j] + carry;
      t[j] = (u64)s;
      carry = s >> 64;
    }
    u128 s = (u128)t[4] + carry;
    t[4] = (u64)s;
    t[5] = (u64)(s >> 64);

    u64 m = t[0] * PINV;
    carry = ((u128)t[0] + (u128)m * P[0]) >> 64;
    for (int j = 1; j < 4; ++j) {
      u128 s2 = (u128)t[j] + (u128)m * P[j] + carry;
      t[j - 1] = (u64)s2;
      carry = s2 >> 64;
    }
    u128 s3 = (u128)t[4] + carry;
    t[3] = (u64)s3;
    t[4] = t[5] + (u64)(s3 >> 64);
  }
  if (t[4] || geq(t, P)) {
    sub_nored(out, t, P);
  } else {
    memcpy(out, t, 32);
  }
}

static inline void mont_sqr(u64 out[4], const u64 a[4]) { mont_mul(out, a, a); }

static const u64 ZERO[4] = {0, 0, 0, 0};

struct G1Jac {
  u64 X[4], Y[4], Z[4];
};
struct G1Aff {
  u64 x[4], y[4];  // Montgomery; (0,0) = infinity
};

static inline bool is_zero4(const u64 a[4]) {
  return !(a[0] | a[1] | a[2] | a[3]);
}

static void jac_double(G1Jac &r, const G1Jac &p) {
  if (is_zero4(p.Z)) {
    r = p;
    return;
  }
  u64 A[4], B[4], C[4], D[4], E[4], F[4], t[4], t2[4];
  mont_sqr(A, p.X);
  mont_sqr(B, p.Y);
  mont_sqr(C, B);
  add_mod(t, p.X, B);
  mont_sqr(t, t);
  sub_mod(t, t, A);
  sub_mod(t, t, C);
  add_mod(D, t, t);
  add_mod(E, A, A);
  add_mod(E, E, A);
  mont_sqr(F, E);
  // X3 = F - 2D
  add_mod(t, D, D);
  sub_mod(r.X, F, t);
  // Y3 = E(D - X3) - 8C
  sub_mod(t, D, r.X);
  mont_mul(t, E, t);
  add_mod(t2, C, C);
  add_mod(t2, t2, t2);
  add_mod(t2, t2, t2);
  u64 y3[4];
  sub_mod(y3, t, t2);
  // Z3 = 2 Y Z
  mont_mul(t, p.Y, p.Z);
  add_mod(r.Z, t, t);
  memcpy(r.Y, y3, 32);
}

// r = p + (x2, y2) affine (Montgomery), standard madd-2007-bl shape.
static void jac_add_mixed(G1Jac &r, const G1Jac &p, const u64 x2[4], const u64 y2[4]) {
  if (is_zero4(x2) && is_zero4(y2)) {
    r = p;
    return;
  }
  if (is_zero4(p.Z)) {
    memcpy(r.X, x2, 32);
    memcpy(r.Y, y2, 32);
    // Z = 1 in Montgomery form = R mod p
    static const u64 ONE_M[4] = {0xd35d438dc58f0d9dULL, 0x0a78eb28f5c70b3dULL,
                                 0x666ea36f7879462cULL, 0x0e0a77c19a07df2fULL};
    memcpy(r.Z, ONE_M, 32);
    return;
  }
  u64 Z1Z1[4], U2[4], S2[4], H[4], HH[4], HHH[4], V[4], Rr[4], t[4];
  mont_sqr(Z1Z1, p.Z);
  mont_mul(U2, x2, Z1Z1);
  mont_mul(t, y2, p.Z);
  mont_mul(S2, t, Z1Z1);
  sub_mod(H, U2, p.X);
  sub_mod(Rr, S2, p.Y);
  if (is_zero4(H)) {
    if (is_zero4(Rr)) {
      jac_double(r, p);
      return;
    }
    memset(&r, 0, sizeof(r));  // infinity
    return;
  }
  mont_sqr(HH, H);
  mont_mul(HHH, H, HH);
  mont_mul(V, p.X, HH);
  // X3 = Rr^2 - HHH - 2V
  mont_sqr(t, Rr);
  sub_mod(t, t, HHH);
  u64 v2[4];
  add_mod(v2, V, V);
  sub_mod(r.X, t, v2);
  // Y3 = Rr (V - X3) - Y1 HHH
  sub_mod(t, V, r.X);
  mont_mul(t, Rr, t);
  u64 t2[4];
  mont_mul(t2, p.Y, HHH);
  sub_mod(r.Y, t, t2);
  // Z3 = Z1 H
  u64 z3[4];
  mont_mul(z3, p.Z, H);
  memcpy(r.Z, z3, 32);
}

// Fermat inverse via exponentiation (p - 2); only used once per output.
static void mont_inv(u64 out[4], const u64 a[4]) {
  // exponent p-2, big-endian bit scan
  u64 e[4];
  u64 two[4] = {2, 0, 0, 0};
  sub_nored(e, P, two);
  // out = 1 (Montgomery)
  static const u64 ONE_M[4] = {0xd35d438dc58f0d9dULL, 0x0a78eb28f5c70b3dULL,
                               0x666ea36f7879462cULL, 0x0e0a77c19a07df2fULL};
  u64 acc[4];
  memcpy(acc, ONE_M, 32);
  for (int i = 255; i >= 0; --i) {
    mont_sqr(acc, acc);
    if ((e[i / 64] >> (i % 64)) & 1) mont_mul(acc, acc, a);
  }
  memcpy(out, acc, 32);
}

extern "C" {

// std -> Montgomery and back (batch), for the Python bridge.
void fp_to_mont(const u64 *in, u64 *out, int n) {
  for (int i = 0; i < n; ++i) mont_mul(out + 4 * i, in + 4 * i, R2P);
}
void fp_from_mont(const u64 *in, u64 *out, int n) {
  static const u64 ONE[4] = {1, 0, 0, 0};
  for (int i = 0; i < n; ++i) mont_mul(out + 4 * i, in + 4 * i, ONE);
}

// Fixed-base batch scalar-mul over G1.
//   base: affine (x, y) standard form; scalars: 4-limb standard form;
//   out: n affine points, standard form, (0,0) for infinity.
// Window-8 table built per call (n is large in setup, so amortised).
void g1_fixed_base_batch(const u64 *base_xy, const u64 *scalars, int n, u64 *out_xy) {
  // Build table[32][256] affine-in-Jacobian: keep Jacobian to skip inversions.
  static G1Jac table[32][256];  // ~0.8 MB; single-threaded use
  u64 bx[4], by[4];
  fp_to_mont(base_xy, bx, 1);
  fp_to_mont(base_xy + 4, by, 1);

  G1Jac wbase;
  memcpy(wbase.X, bx, 32);
  memcpy(wbase.Y, by, 32);
  static const u64 ONE_M[4] = {0xd35d438dc58f0d9dULL, 0x0a78eb28f5c70b3dULL,
                               0x666ea36f7879462cULL, 0x0e0a77c19a07df2fULL};
  memcpy(wbase.Z, ONE_M, 32);

  for (int w = 0; w < 32; ++w) {
    memset(&table[w][0], 0, sizeof(G1Jac));
    // normalize wbase to affine for mixed adds: one inversion per window
    u64 zi[4], zi2[4], zi3[4], ax[4], ay[4];
    mont_inv(zi, wbase.Z);
    mont_sqr(zi2, zi);
    mont_mul(zi3, zi2, zi);
    mont_mul(ax, wbase.X, zi2);
    mont_mul(ay, wbase.Y, zi3);
    for (int d = 1; d < 256; ++d) {
      jac_add_mixed(table[w][d], table[w][d - 1], ax, ay);
    }
    for (int k = 0; k < 8; ++k) jac_double(wbase, wbase);
  }

  for (int i = 0; i < n; ++i) {
    const u64 *s = scalars + 4 * i;
    G1Jac acc;
    memset(&acc, 0, sizeof(acc));
    for (int w = 0; w < 32; ++w) {
      int d = (int)((s[w / 8] >> ((w % 8) * 8)) & 0xff);
      if (!d) continue;
      const G1Jac &e = table[w][d];
      if (is_zero4(acc.Z)) {
        acc = e;
      } else {
        // general Jacobian add via mixed trick: normalise e lazily is
        // costly; use add-via-double formulas on Jacobian pair:
        // convert e to affine once would need inversion; instead use
        // full jacobian addition:
        u64 Z1Z1[4], Z2Z2[4], U1[4], U2[4], S1[4], S2[4], H[4], Rr[4];
        mont_sqr(Z1Z1, acc.Z);
        mont_sqr(Z2Z2, e.Z);
        mont_mul(U1, acc.X, Z2Z2);
        mont_mul(U2, e.X, Z1Z1);
        u64 t[4];
        mont_mul(t, acc.Y, e.Z);
        mont_mul(S1, t, Z2Z2);
        mont_mul(t, e.Y, acc.Z);
        mont_mul(S2, t, Z1Z1);
        sub_mod(H, U2, U1);
        sub_mod(Rr, S2, S1);
        if (is_zero4(H)) {
          if (is_zero4(Rr)) {
            jac_double(acc, acc);
            continue;
          }
          memset(&acc, 0, sizeof(acc));
          continue;
        }
        u64 HH[4], HHH[4], V[4];
        mont_sqr(HH, H);
        mont_mul(HHH, H, HH);
        mont_mul(V, U1, HH);
        u64 x3[4], y3[4], z3[4];
        mont_sqr(t, Rr);
        sub_mod(t, t, HHH);
        u64 v2[4];
        add_mod(v2, V, V);
        sub_mod(x3, t, v2);
        sub_mod(t, V, x3);
        mont_mul(t, Rr, t);
        u64 t2[4];
        mont_mul(t2, S1, HHH);
        sub_mod(y3, t, t2);
        mont_mul(t, acc.Z, e.Z);
        mont_mul(z3, t, H);
        memcpy(acc.X, x3, 32);
        memcpy(acc.Y, y3, 32);
        memcpy(acc.Z, z3, 32);
      }
    }
    u64 *o = out_xy + 8 * i;
    if (is_zero4(acc.Z)) {
      memset(o, 0, 64);
      continue;
    }
    u64 zi[4], zi2[4], zi3[4], mx[4], my[4];
    mont_inv(zi, acc.Z);
    mont_sqr(zi2, zi);
    mont_mul(zi3, zi2, zi);
    mont_mul(mx, acc.X, zi2);
    mont_mul(my, acc.Y, zi3);
    fp_from_mont(mx, o, 1);
    fp_from_mont(my, o + 4, 1);
  }
}

// Self-test hook: c = a*b mod p (standard form in/out).
void fp_mul_std(const u64 *a, const u64 *b, u64 *c) {
  u64 am[4], bm[4], cm[4];
  fp_to_mont(a, am, 1);
  fp_to_mont(b, bm, 1);
  mont_mul(cm, am, bm);
  fp_from_mont(cm, c, 1);
}

}  // extern "C"
