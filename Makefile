# zkp2p_tpu build/verification entry points.
#
# `make driver-rehearsal` runs the EXACT commands the round driver runs,
# under the driver's own timeout discipline, and fails loudly — the
# guard against "green locally, red in the artifact" rounds (VERDICT r3
# weakness #1/#2).  Run it before closing a round; quote its output in
# the round notes.

.PHONY: native native-asan native-tsan lint circuit-audit test test-slow metrics-smoke precomp-smoke precomp-cache chaos-smoke loadgen-smoke nonmsm-smoke prove-floor-smoke fleet-smoke fleet-obs-smoke fleet-chaos sched-smoke tune-smoke perf-smoke flame-smoke perf-gate tpu-shard-smoke warm-cache doctor driver-rehearsal rehearsal-dryrun rehearsal-bench fullsize-proof

native:
	$(MAKE) -C csrc

# Static invariant checks (tier-1 resident; docs/STATIC_ANALYSIS.md):
# knob/gate discipline, csrc StatSlot vs STATS_FIELDS ABI drift, metric
# naming, spool durability, clock rules, and the pyflakes-tier baseline
# (an installed ruff is grafted on automatically).  Pure AST — runs in
# seconds with NO native build, NO jax import; exits nonzero on any
# finding.  This is the pre-commit gate: run it before every push.
lint:
	python -m tools.lint

# Circuit soundness audit — the registry admission gate (tier-1 resident
# via tests/test_circuit_audit.py; docs/STATIC_ANALYSIS.md §circuit
# audit): build every registered circuit and run the static R1CS
# auditor — unconstrained wires, the determinism fixpoint, bool/width
# demands, dead/duplicate rows, hook coverage, public-layout parity.
# Jax-free like `make lint` (gadgets/models need only numpy); reports
# cached under .bench_cache keyed by structural circuit digest, so an
# unchanged tree re-audits in seconds.  The 4.9M-wire flagship audit
# runs under the slow tier (ZKP2P_RUN_SLOW=1 pytest
# tests/test_circuit_audit.py -k flagship).
circuit-audit:
	env -u PALLAS_AXON_POOL_IPS python -m tools.lint --circuits

# Sanitizer smoke: build the ASan+UBSan library and run the MSM parity
# check against it (tests/test_native_asan.py LD_PRELOADs libasan into a
# python subprocess — the interpreter itself is uninstrumented).  Green
# means the batch-affine fill / batch-inversion buffers ran clean.
native-asan:
	$(MAKE) -C csrc libzkp2p_native_asan.so
	env -u PALLAS_AXON_POOL_IPS ZKP2P_RUN_SLOW=1 python -m pytest tests/test_native_asan.py -q

# Race-detector smoke (slow tier; mirrors the native-asan layout): build
# the TSan-instrumented library and drive the native CONCURRENCY surface
# — the WorkPool MPMC queue from two submitter threads, the
# relaxed-atomics stats block under a concurrent reader, pool-parallel
# NTT stages, segmented matvec and the multi-column MSM at threads=2 —
# with parity asserts against the host oracle.  Suppressions:
# csrc/tsan.supp (currently empty; policy in docs/STATIC_ANALYSIS.md).
# First green run caught a real race: the ifma_enabled plain-int cache.
native-tsan:
	$(MAKE) -C csrc libzkp2p_native_tsan.so
	env -u PALLAS_AXON_POOL_IPS ZKP2P_RUN_SLOW=1 python -m pytest tests/test_native_tsan.py -q

# Observability smoke (fast; also a tier-1 resident): a tiny prove with
# the JSONL sink + Prometheus endpoint enabled must yield nonzero native
# MSM fill/suffix + pool counters, request records carrying
# run_id/request_id/knob manifest, and a trace_report table that parses.
# See docs/OBSERVABILITY.md.
metrics-smoke: native
	env -u PALLAS_AXON_POOL_IPS python -m pytest tests/test_metrics_smoke.py -q

# Fixed-base precomputed-table smoke (fast; tier-1 resident): build ->
# persist -> reload -> identical proof on a tiny key, plus stale-cache
# rejection — the cheap proof that the precomp cache layer works before
# a cold service start spends minutes building bench-shape tables.
precomp-smoke: native
	env -u PALLAS_AXON_POOL_IPS python -m pytest \
	  tests/test_msm_precomp.py -q -k "cache or stale or partial"

# Pre-build the fixed-base tables for the bench-shape venmo key into
# .bench_cache/ (same spirit as the .jax_cache pre-warm): ~50 s per G1
# family cold, a no-op warm — run it before a driver/bench window so
# the first prove loads tables instead of building them.
precomp-cache: native
	env -u PALLAS_AXON_POOL_IPS JAX_PLATFORMS=cpu python -c "\
	import bench; \
	from zkp2p_tpu.prover.precomp import precomputed_for, precomp_manifest; \
	cs, lay, make_input = bench._build_venmo(); \
	dpk, vk = bench.build_keys(cs); \
	pk = precomputed_for(dpk); \
	import json; print(json.dumps(precomp_manifest(), indent=1))"

# Chaos smoke (fast; tier-1 resident): 2 subprocess workers on one
# spool, 1 SIGKILL landed mid-prove (victim chosen by reading the pid
# out of a live .claim file), faults injected at 4 sites — then the
# global invariant is asserted: every request in exactly one terminal
# state, every proof pairing-verifies, no duplicate terminal records.
# See docs/ROBUSTNESS.md §chaos harness; ~25 s on the 2-core box.
chaos-smoke: native
	env -u PALLAS_AXON_POOL_IPS python -m pytest tests/test_chaos.py -q

# Load-generator smoke (fast; tier-1 resident): a 2-second open-loop
# Poisson burst against the stub-speed toy prover on a temp spool —
# the capacity JSON must parse with scored ramp steps, /status must
# scrape 200 mid-run, and trace_report must render the sink's request
# waterfalls (Chrome-trace export) + time-series lines.  The real
# measurement is `python tools/loadgen.py --circuit venmo` — see
# docs/OBSERVABILITY.md §loadgen; ~20 s on the 2-core box.
loadgen-smoke: native
	env -u PALLAS_AXON_POOL_IPS python -m pytest tests/test_loadgen.py -q

# Fleet smoke (tier-1 resident): the supervised-fleet machinery end to
# end — drain semantics (SIGTERM mid-batch: in-flight -> done, no new
# claims, heartbeat keeps held claims out of peer-takeover range, exit
# codes split clean drain from escalation), supervisor restart/backoff/
# circuit-breaker/governor, a 2-worker toy fleet with one SIGKILL and
# one SIGTERM drain under the PR-7 global invariant with /status
# reachable on both auto-bound metrics ports, and the flock'd
# one-cold-build-per-key contract across two processes.  The N=3
# chaos acceptance + the --fleet loadgen scaling arm are the slow tier
# (`make fleet-chaos`).  See docs/ROBUSTNESS.md §fleet; ~2 min.
fleet-smoke: native
	env -u PALLAS_AXON_POOL_IPS python -m pytest tests/test_fleet.py -q

# Fleet observability plane smoke (fast; tier-1 resident): federation
# aggregation rules (counter sum / per-worker gauge labels / histogram
# bucket-merge with mismatch refusal), merged-window SLO pinned against
# a pooled oracle, alert rules + hysteresis on synthetic time-series,
# fleet /status fail-closed, chrome-trace flow events across pids, and
# the 2-worker toy-fleet smoke: fleet /metrics + /status scrape 200,
# merged request counters equal the per-worker sums AND the proof
# artifacts, trace_report --fleet-dir renders valid JSON.  See
# docs/OBSERVABILITY.md §fleet plane; ~15 s on the 2-core box.
fleet-obs-smoke: native
	env -u PALLAS_AXON_POOL_IPS python -m pytest tests/test_fleet_obs.py -q

# Adaptive-scheduler smoke (tier-1 resident; docs/SCHEDULING.md):
# deterministic controller units (amortization model, EWMA, SLO-driven
# sizing monotone-in-load + clamped, expected-deadline-miss shed that
# never sheds a feasible request, interactive-first lanes, autoscale
# hysteresis that cannot flap on an oscillating signal), the toy-circuit
# mini-trace through the REAL service (adaptive sheds/lanes/targets vs
# the byte-for-byte static off arm, digest-distinguishable), and the
# 1->2->1 fleet autoscale demo with the PR-7 zero-lost invariant green.
# ~40 s on the 2-core box (the autoscale demo is most of it).
sched-smoke: native
	env -u PALLAS_AXON_POOL_IPS python -m pytest tests/test_sched.py -q

# Host-profile + `zkp2p-tpu tune` smoke (fast; tier-1 resident;
# docs/TUNING.md §host profiles): atomic profile round-trip, tampered /
# foreign-fingerprint rejection to the fallback arm, byte-exact
# geometry fallback parity (no profile = the hand-picked c16/q2/L8
# oracle), profile-seeded AmortModel exiting warm-up with zero observed
# batches, tuned-vs-fallback digest distinguishability, and a real
# tiny-shape budgeted sweep end to end.  ~5 s on the 1-core box.
tune-smoke: native
	env -u PALLAS_AXON_POOL_IPS python -m pytest tests/test_tune.py -q

# Perf-regression sentry smoke (fast; tier-1 resident;
# docs/OBSERVABILITY.md §perf sentry): ledger append/round-trip,
# foreign-fingerprint + tampered-entry + schema-drift refusal, budget
# derivation windows, overrun counting through a real service sweep
# with a seeded `prove:hang` slowdown (and a clean replay that stays
# quiet), alert fire/hold/clear hysteresis, gate fails-closed, and
# ledger-on/off digest distinguishability.
perf-smoke: native
	env -u PALLAS_AXON_POOL_IPS python -m pytest tests/test_perfledger.py -q

# Flame-sampler smoke (fast; tier-1 resident; docs/OBSERVABILITY.md
# §flame profiler): gate off = no thread/no captures + digest
# distinguishability, collapsed-stack folding of a hot Python loop,
# synthetic native-frame stitching from stats-block deltas, trigger/
# cooldown/capture_n controller behavior, atomic capture writes with
# fail-closed loading, the overrun->capture closed loop through a real
# service sweep, fleet `top` capture-pointer rendering, and the
# trace_report --flame track.
flame-smoke: native
	env -u PALLAS_AXON_POOL_IPS python -m pytest tests/test_flameprof.py -q

# Drift gate (CI + the pre-hardware-window check): backfill the
# committed BENCH_r*.json history into this host's ledger (idempotent)
# and replay the ledger HEAD against the committed PERF_BASELINE.json
# band.  Exit 0 = within band, 1 = DRIFT (a stage's head p50 exceeds
# median x tolerance), 2 = fail closed (no baseline / no valid ledger
# entries — a gate that cannot compare must not pass).  Rebaseline
# with `zkp2p-tpu perf --rebaseline` after an intentional perf change.
perf-gate:
	env -u PALLAS_AXON_POOL_IPS python -m zkp2p_tpu.pipeline.cli perf --backfill --gate

# Sharded-TPU-arm smoke (tier-1 resident; docs/TPU.md): the pjit
# batch-axis prover on the 8-virtual-device CPU mesh — toy-circuit
# byte parity (single + batch) vs the native-loop oracle under pinned
# (r, s), per-device bucket partial sums vs the unsharded arm, mesh-spec
# parsing + fallback arming, warm-cache round-trip with the >=10x
# second-run compile-span assertion, and heterogeneous-tier routing
# units.  Rides the persistent .jax_cache (run `make warm-cache` first
# on a cold checkout); ~1 min warm on the 1-core box.
tpu-shard-smoke: native
	env -u PALLAS_AXON_POOL_IPS python -m pytest tests/test_tpu_shard.py -q

# Pre-compile the batch prover (sharded arm included) into the
# persistent .jax_cache — the XLA analog of `make precomp-cache`: a
# cold pod-MSM shard_map executable compiles for MINUTES on a 1-core
# host, a warm one loads in milliseconds.  Run before a driver/bench
# window or a cold `make tpu-shard-smoke`.
warm-cache:
	env -u PALLAS_AXON_POOL_IPS JAX_PLATFORMS=cpu \
	  XLA_FLAGS=--xla_force_host_platform_device_count=8 \
	  python -m zkp2p_tpu --circuit toy warm-cache --shard 2x4 --batch 4

# The full fleet acceptance (slow): N=3 supervised workers, seeded
# faults, worker SIGKILL + worker SIGTERM drain + supervisor
# kill/restart, plus the `--fleet 2` loadgen arm proving >=1.8x
# single-worker throughput at the same SLO objective.
fleet-chaos: native
	env -u PALLAS_AXON_POOL_IPS ZKP2P_RUN_SLOW=1 python -m pytest \
	  tests/test_fleet.py -q -k "acceptance or loadgen_fleet"

# Non-MSM floor smoke (fast; tier-1 resident): segmented-matvec byte
# parity vs the scatter oracle across {threads}x{tier}, pool-NTT and
# fused-ladder parity vs the knob-off arms (incl. the 2^19 bench-shape
# domain), plan-cache round-trip with tamper rejection, and the
# shared-executor churn regression.  The isolated perf read is
# `python tools/msm_hwbench.py --ladder --n 524288` — see
# docs/TUNING.md §non-MSM; ~15 s on the 2-core box.
nonmsm-smoke: native
	env -u PALLAS_AXON_POOL_IPS python -m pytest tests/test_nonmsm.py -q

# Single-prove floor smoke (fast; tier-1 resident): the PR-20 floor
# arms — interleaved+prefetched MSM apply, radix-8 fused NTT stages,
# witness-u64-at-builder — byte-identical to the committed-old arms
# across {knob on/off} x {threads 1,2} x {single, batch S=3}, with the
# execution digest separating every gate combination, plus the
# builder-u64 zero-copy hand-off and the radix-8 kernel parity vs the
# scalar fr_ntt oracle.  The isolated perf read is
# `python tools/msm_hwbench.py --apply-prof --glv --n 524288` — see
# docs/TUNING.md §prove floor; ~40 s on the 1-core box.
prove-floor-smoke: native
	env -u PALLAS_AXON_POOL_IPS python -m pytest -q \
	  tests/test_nonmsm.py -k "radix8 or witness_u64 or prove_floor" && \
	env -u PALLAS_AXON_POOL_IPS python -m pytest -q \
	  tests/test_msm_multi.py -k "floor_arms"

# Execution-path preflight (docs/OBSERVABILITY.md §execution audit):
# probe the backend, arm EVERY gate through its real resolver, print
# the gate→arm table + execution digest, and warn loudly on mis-arms
# (e.g. pallas forced on a CPU host).  Run this FIRST in every tunnel
# window — it is the check that would have caught the round-2 silent
# disarm in seconds.  Machine output: `python -m zkp2p_tpu doctor --json`.
doctor:
	python -m zkp2p_tpu doctor

# env -u PALLAS_AXON_POOL_IPS: the axon sitecustomize dials the TPU relay
# at interpreter start when the var is set, and that dial BLOCKS while any
# other process (a running bench) holds the single chip — tests must never
# touch the tunnel (tests/conftest.py documents the same for subprocesses).
test:
	env -u PALLAS_AXON_POOL_IPS python -m pytest tests/ -x -q
	@echo "hint: 'make lint' (static invariants, seconds) and" \
	  "'make native-asan' / 'make native-tsan' (sanitizer tiers) are separate gates"

# THREE fresh pytest processes, unlimited stack, persistent cache OFF:
# long single-process runs segfault inside XLA:CPU on the biggest
# graphs (executable.serialize()/backend_compile stacks in
# docs/logs/slow_suite_r4b crash history; the flake concentrates in the
# G2 MSM compiles of the test_m* files, so they get their own process).
test-slow:
	bash -c 'ulimit -s unlimited; \
	  env -u PALLAS_AXON_POOL_IPS ZKP2P_RUN_SLOW=1 ZKP2P_NO_CACHE=1 python -m pytest tests/test_[a-l]*.py -q && \
	  env -u PALLAS_AXON_POOL_IPS ZKP2P_RUN_SLOW=1 ZKP2P_NO_CACHE=1 python -m pytest tests/test_m*.py -q && \
	  env -u PALLAS_AXON_POOL_IPS ZKP2P_RUN_SLOW=1 ZKP2P_NO_CACHE=1 python -m pytest tests/test_[n-z]*.py -q'

# -- driver simulation ------------------------------------------------
# The driver gives dryrun_multichip ~10 minutes on a cold 1-core host
# and runs bench.py with a similar budget.  These targets time out a
# little below that so a local pass implies a driver pass with margin.

rehearsal-dryrun:
	@echo "== dryrun_multichip(8) under timeout 600 =="
	timeout 600 python -c 'import __graft_entry__ as g; g.dryrun_multichip(8)'

rehearsal-bench:
	@echo "== bench.py under timeout 900 =="
	timeout 900 python bench.py

driver-rehearsal: rehearsal-dryrun rehearsal-bench
	@echo "driver-rehearsal: ALL GREEN"

# Full-size flagship proof with the native C++ runtime (caches under
# .bench_cache/; artifacts in docs/fullsize_proof/).
fullsize-proof:
	JAX_PLATFORMS=cpu python tools/prove_fullsize_native.py
