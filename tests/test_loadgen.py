"""loadgen-smoke (Makefile `loadgen-smoke`, tier-1 resident): a
2-second open-loop Poisson burst against the stub-speed toy prover on a
temp spool must yield a capacity JSON that parses with the full step
schema, a live /status scrape during the run, and a sink that
trace_report renders as a waterfall (Chrome-trace export + time-series
lines)."""

import json
import os
import socket
import subprocess
import sys
import time
import urllib.error
import urllib.request

import pytest

from zkp2p_tpu.native import lib as native

pytestmark = pytest.mark.skipif(native.get_lib() is None, reason="native toolchain unavailable")

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

STEP_KEYS = {
    "qps_target", "offered", "done", "errors", "unfinished", "served_under_slo",
    "duration_s", "completed_qps", "p50_s", "p95_s", "max_s", "attainment",
    "burn_rate", "ok",
}


def _free_port() -> int:
    s = socket.socket()
    s.bind(("", 0))
    port = s.getsockname()[1]
    s.close()
    return port


def test_loadgen_burst_capacity_status_and_waterfall(tmp_path):
    spool = str(tmp_path / "spool")
    cap_path = str(tmp_path / "capacity.json")
    port = _free_port()
    env = dict(os.environ)
    env.pop("PALLAS_AXON_POOL_IPS", None)
    env["JAX_PLATFORMS"] = "cpu"
    env["ZKP2P_METRICS_PORT"] = str(port)
    env["ZKP2P_TS_SAMPLE_S"] = "1"  # several sampler lines in a short run
    env.pop("ZKP2P_METRICS_SINK", None)
    env.pop("ZKP2P_FAULTS", None)
    proc = subprocess.Popen(
        [sys.executable, os.path.join(REPO, "tools", "loadgen.py"),
         "--spool", spool, "--rates", "1.5,25", "--step-s", "1.2",
         "--objective-s", "8", "--prove-s", "0.3", "--drain-s", "30",
         "--out", cap_path],
        env=env, cwd=REPO, stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True,
    )
    try:
        # /status during the run: preflight ran -> 200 with SLO payload
        status = None
        deadline = time.time() + 30
        while time.time() < deadline and proc.poll() is None:
            try:
                r = urllib.request.urlopen(f"http://127.0.0.1:{port}/status", timeout=2)
                status = json.loads(r.read())
                break
            except (urllib.error.URLError, ConnectionError, OSError):
                time.sleep(0.2)
        out, err = proc.communicate(timeout=120)
    finally:
        if proc.poll() is None:
            proc.kill()
    assert proc.returncode == 0, err
    assert status is not None and status["ok"] is True, (out, err)
    assert "slo" in status and "attainment" in status["slo"]

    # capacity JSON: full schema, scored steps, an honest max
    with open(cap_path) as f:
        cap = json.load(f)
    assert cap["type"] == "capacity" and cap["arrivals"] == "open-loop poisson"
    for key in ("run_id", "host", "execution_digest", "objective_p95_s", "target",
                "steps", "max_sustainable_qps"):
        assert key in cap, key
    assert cap["host"]["cpu_count"] >= 1
    assert len(cap["steps"]) == 2
    for s in cap["steps"]:
        assert STEP_KEYS <= set(s), s
        assert s["offered"] == s["done"] + s["errors"] + s["unfinished"]
        assert 0.0 <= s["attainment"] <= 1.0
    assert "worker_errors" not in cap, cap.get("worker_errors")
    # saturation degrades monotonically: the 25 QPS step cannot beat the
    # in-capacity step, and the reported max is one of the offered rates
    assert cap["steps"][0]["attainment"] >= cap["steps"][1]["attainment"]
    assert cap["max_sustainable_qps"] in (0.0, *[s["qps_target"] for s in cap["steps"]])
    passing = [s["qps_target"] for s in cap["steps"] if s["ok"]]
    assert cap["max_sustainable_qps"] == (max(passing) if passing else 0.0)

    # the sink renders: waterfall spans export to Chrome trace JSON and
    # the time-series lines aggregate
    sink = spool.rstrip("/") + ".metrics.jsonl"
    assert os.path.exists(sink)
    trace_out = str(tmp_path / "trace.json")
    p = subprocess.run(
        [sys.executable, os.path.join(REPO, "tools", "trace_report.py"), sink,
         "--chrome-trace", trace_out],
        capture_output=True, text=True, timeout=60,
    )
    assert p.returncode == 0, p.stderr
    with open(trace_out) as f:
        trace = json.load(f)
    xs = [e for e in trace["traceEvents"] if e.get("ph") == "X"]
    assert {e["name"] for e in xs} >= {"queue_wait", "prove"}
    ts_vals = [e["ts"] for e in xs]
    assert ts_vals == sorted(ts_vals)
    p2 = subprocess.run(
        [sys.executable, os.path.join(REPO, "tools", "trace_report.py"), sink, "--json"],
        capture_output=True, text=True, timeout=60,
    )
    assert p2.returncode == 0, p2.stderr
    rep = json.loads(p2.stdout)
    assert rep["timeseries"].get("n", 0) >= 1
    assert "done" in rep["requests"]


def test_parse_trace_segments():
    """--trace grammar: 'RATExSECONDS,...' segments; malformed specs
    fail LOUDLY before any multi-minute ramp."""
    import importlib.util

    spec = importlib.util.spec_from_file_location(
        "zkp2p_loadgen_for_trace", os.path.join(REPO, "tools", "loadgen.py"))
    lg = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(lg)
    assert lg.parse_trace("0.2x30,4x20,0.2x30") == [(0.2, 30.0), (4.0, 20.0), (0.2, 30.0)]
    assert lg.parse_trace("1X5") == [(1.0, 5.0)]  # case-insensitive x
    for bad in ("", "junk", "0x5", "1x-3", "1:5"):
        with pytest.raises(ValueError):
            lg.parse_trace(bad)
