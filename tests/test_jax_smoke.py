"""Fast JAX-path smoke checks for the default suite.

The heavy differential files (test_jfield/test_jcurve/test_ops/
test_parallel/test_prover_tpu) are ZKP2P_RUN_SLOW-gated because each
costs minutes of XLA compile on a 1-core host.  This file keeps one tiny
representative of each layer in the default run: a field mul, a curve
add, and an NTT round trip — enough to catch gross breakage (wrong
Montgomery constants, broken carry ladder, bad butterfly indexing)
within seconds on a warm cache.
"""

import numpy as np

from zkp2p_tpu.field.bn254 import P, R, fr_domain_root
from zkp2p_tpu.field.jfield import FQ, FR


def test_field_mul_smoke():
    rng = np.random.default_rng(5)
    a = int.from_bytes(rng.bytes(31), "big") % R
    b = int.from_bytes(rng.bytes(31), "big") % R
    got = FR.mul(FR.to_mont_host(a)[None], FR.to_mont_host(b)[None])
    assert FR.from_mont_host(np.asarray(got)[0]) == a * b % R


def test_curve_add_smoke():
    from zkp2p_tpu.curve.host import G1_GENERATOR, g1_add, g1_mul
    from zkp2p_tpu.curve.jcurve import G1J, g1_jac_to_host, g1_to_affine_arrays

    p1 = g1_mul(G1_GENERATOR, 7)
    p2 = g1_mul(G1_GENERATOR, 11)
    a1 = G1J.from_affine(g1_to_affine_arrays([p1]))
    a2 = G1J.from_affine(g1_to_affine_arrays([p2]))
    got = g1_jac_to_host(G1J.add(a1, a2))[0]
    assert got == g1_add(p1, p2)


def test_ntt_roundtrip_smoke():
    from zkp2p_tpu.ops.ntt import intt, ntt
    from zkp2p_tpu.snark import fft_host

    log_m = 3
    m = 1 << log_m
    rng = np.random.default_rng(6)
    vals = [int.from_bytes(rng.bytes(31), "big") % R for _ in range(m)]
    x = np.stack([FR.to_mont_host(v) for v in vals])
    got = ntt(np.asarray(x), log_m)
    want = fft_host.ntt(vals)
    assert [FR.from_mont_host(r) for r in np.asarray(got)] == want
    back = intt(got, log_m)
    assert [FR.from_mont_host(r) for r in np.asarray(back)] == vals


def test_limb_major_conv_matches_matmul_path():
    """Both _mul_wide layouts are bit-exact vs the host oracle and each
    other (CONV_LAYOUT is a pure perf knob)."""
    from zkp2p_tpu.field import jfield

    rng = np.random.default_rng(9)
    vals = [(int.from_bytes(rng.bytes(31), "big") % R, int.from_bytes(rng.bytes(31), "big") % R) for _ in range(8)]
    a = np.stack([FR.to_mont_host(x) for x, _ in vals])
    b = np.stack([FR.to_mont_host(y) for _, y in vals])
    saved = jfield.CONV_LAYOUT
    try:
        jfield.CONV_LAYOUT = "matmul"
        got_m = np.asarray(FR.mul(a, b))
        jfield.CONV_LAYOUT = "limb_major"
        got_l = np.asarray(FR.mul(a, b))
    finally:
        jfield.CONV_LAYOUT = saved
    np.testing.assert_array_equal(got_m, got_l)
    for i, (x, y) in enumerate(vals):
        assert FR.from_mont_host(got_l[i]) == x * y % R


def test_limb_major_reduce_wide_and_addsub():
    """The non-mul users of _mul_wide (Montgomery reduction, sub borrow
    chains) also agree across layouts."""
    from zkp2p_tpu.field import jfield
    from zkp2p_tpu.field.jfield import reduce_wide

    rng = np.random.default_rng(11)
    wide_vals = [int.from_bytes(rng.bytes(60), "big") for _ in range(4)]
    arr = np.stack(
        [np.array([(v >> (16 * i)) & 0xFFFF for i in range(30)], dtype=np.uint32) for v in wide_vals]
    )
    from zkp2p_tpu.field.jfield import limbs_to_int

    saved = jfield.CONV_LAYOUT
    try:
        jfield.CONV_LAYOUT = "limb_major"
        got = np.asarray(reduce_wide(FR, arr))
    finally:
        jfield.CONV_LAYOUT = saved
    for i, v in enumerate(wide_vals):
        assert limbs_to_int(got[i]) == v % R
