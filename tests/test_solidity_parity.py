"""Structural parity of the exported verifier vs the reference contract.

No EVM toolchain exists in this environment (no solc/node/hardhat, zero
egress), so the exported `verifier.sol` cannot be *executed* here; this
test pins the next-strongest property: structural identity with
`/root/reference/contracts/Verifier.sol` — the exact snarkjs export
shape `Ramp is Verifier` compiles against — plus the calldata contract
(`verifyProof(uint[2], uint[2][2], uint[2], uint[26])`, G2 limbs in the
EVM's reversed order).  See docs/EVM_PARITY.md for the full accounting.
"""

import json
import os
import re

import pytest

from zkp2p_tpu.field.tower import Fq2
from zkp2p_tpu.formats.solidity import export_verifier
from zkp2p_tpu.snark.groth16 import VerifyingKey

REF = "/root/reference/contracts/Verifier.sol"
REF_VKEY = "/root/reference/app/src/helpers/vkey.ts"


def _venmo_shaped_vk() -> VerifyingKey:
    """A 26-public VerifyingKey (the Ramp.sol uint[26] layout) with
    generator-derived points — export_verifier only reads coordinates."""
    from zkp2p_tpu.curve.host import G1_GENERATOR, G2_GENERATOR, g1_mul, g2_mul

    ic = [g1_mul(G1_GENERATOR, 3 + i) for i in range(27)]
    return VerifyingKey(
        n_public=26,
        alpha_1=g1_mul(G1_GENERATOR, 5),
        beta_2=g2_mul(G2_GENERATOR, 7),
        gamma_2=g2_mul(G2_GENERATOR, 11),
        delta_2=g2_mul(G2_GENERATOR, 13),
        ic=ic,
    )


def test_export_has_the_reference_interface():
    sol = export_verifier(_venmo_shaped_vk())
    # The exact pieces Ramp.sol and the reference deployment depend on.
    assert "function verifyProof(" in sol
    assert "uint[26] memory input" in sol
    assert "uint[2] memory a" in sol and "uint[2][2] memory b" in sol
    assert "public view returns (bool r)" in sol
    assert len(re.findall(r"vk\.IC\[\d+\] = Pairing\.G1Point", sol)) == 27
    # BN254 precompiles 6 (add), 7 (mul), 8 (pairing) via staticcall.
    for pre in (" 6,", " 7,", " 8,"):
        assert f"staticcall(sub(gas(), 2000),{pre}" in sol
    assert "21888242871839275222246405745257275088548364400416034343698204186575808495617" in sol


@pytest.mark.skipif(not os.path.exists(REF), reason="reference checkout not available")
def test_export_structurally_matches_reference_verifier():
    """Every function the reference Verifier exposes (that the onramp
    path uses) exists in our export with an identical signature, and the
    pairing-check call sequence is the same."""
    with open(REF) as f:
        ref = f.read()
    sol = export_verifier(_venmo_shaped_vk())

    def signatures(src):
        return set(re.findall(r"function\s+(\w+)\(", src))

    ours, theirs = signatures(sol), signatures(ref)
    # pairingProd2/3 and P2 are dead code in the reference (only Prod4 is
    # called by verify); everything the verify path touches must match.
    needed = {"negate", "addition", "scalar_mul", "pairing", "pairingProd4", "verifyingKey", "verify", "verifyProof"}
    assert needed <= ours
    assert needed <= theirs

    # Same pairing equation, same operand order.
    pat = re.compile(
        r"pairingProd4\(\s*Pairing\.negate\(proof\.A\),\s*proof\.B,\s*vk\.alfa1,\s*vk\.beta2,\s*vk_x,\s*vk\.gamma2,\s*proof\.C,\s*vk\.delta2", re.S
    )
    assert pat.search(sol) and pat.search(ref)

    # Identical scalar-field guard and IC accumulation loop shape.
    for frag in (
        'require(input[i] < snark_scalar_field',
        "vk_x = Pairing.addition(vk_x, Pairing.scalar_mul(vk.IC[i + 1], input[i]))",
        "vk_x = Pairing.addition(vk_x, vk.IC[0])",
    ):
        assert frag.replace(" ", "") in sol.replace(" ", "")
        assert frag.replace(" ", "") in ref.replace(" ", "")

    # Reference vkey has 27 IC points (26 publics + 1), ours likewise.
    n_ic = lambda src: len(re.findall(r"vk\.IC\[\d+\] = Pairing\.G1Point", src))
    assert n_ic(ref) == 27 == n_ic(sol)


def _verifying_key_constants(sol: str):
    """Every number snarkjs bakes into verifyingKey(), as an ordered map:
    the complete key-dependent content of the contract (all other lines
    are vkey-independent boilerplate)."""
    out = {}
    m = re.search(r"vk\.alfa1 = Pairing\.G1Point\(\s*(\d+),\s*(\d+)", sol)
    out["alfa1"] = (int(m.group(1)), int(m.group(2)))
    for name in ("beta2", "gamma2", "delta2"):
        m = re.search(
            rf"vk\.{name} = Pairing\.G2Point\(\s*\[(\d+),\s*(\d+)\],\s*\[(\d+),\s*(\d+)\]",
            sol,
        )
        out[name] = tuple(int(m.group(i)) for i in range(1, 5))
    for m in re.finditer(r"vk\.IC\[(\d+)\] = Pairing\.G1Point\(\s*(\d+),\s*(\d+)", sol):
        out[f"IC[{m.group(1)}]"] = (int(m.group(2)), int(m.group(3)))
    return out


@pytest.mark.skipif(
    not (os.path.exists(REF) and os.path.exists(REF_VKEY)),
    reason="reference checkout not available",
)
def test_reference_vkey_golden_constants():
    """Golden comparison against a REAL snarkjs export (VERDICT r3 #6):
    feed the reference's shipped verification key (app/src/helpers/vkey.ts)
    through our exporter and require every constant embedded in the
    generated contract — alfa1, beta2/gamma2/delta2 with snarkjs's
    reversed G2 limb order, and all 27 IC points — to equal the ones in
    the reference's own snarkjs-generated contracts/Verifier.sol, plus
    the exact verifyProof ABI.  (The reference file is read in place, not
    vendored: the surrounding Pairing-library boilerplate is
    vkey-independent, so the constants + ABI are the entire key-derived
    content of the export.)"""
    from zkp2p_tpu.formats.proof_json import vkey_from_json

    from zkp2p_tpu.field.bn254 import P

    with open(REF_VKEY) as f:
        ts = f.read()
    vkey_json = json.loads(ts[ts.index("{"):ts.rindex("}") + 1])
    vk = vkey_from_json(vkey_json)
    sol = export_verifier(vk)
    ours = _verifying_key_constants(sol)
    with open(REF) as f:
        theirs = _verifying_key_constants(f.read())
    # delta2 is EXCLUDED by necessity: the reference's own two artifacts
    # disagree on it — vkey.ts and contracts/Verifier.sol were exported
    # from different phase-2 contribution counts, and a contribution
    # rerandomises exactly delta (alpha/beta/gamma and the gamma-divided
    # IC are contribution-invariant, and do match below, all 51 numbers).
    ours.pop("delta2")
    want_delta = theirs.pop("delta2")
    assert ours == theirs
    # our delta2 must still be the faithful rendering of vkey.ts's delta
    # (snarkjs reversed limb order), and a valid distinct ceremony value.
    m = re.search(
        r"vk\.delta2 = Pairing\.G2Point\(\s*\[(\d+),\s*(\d+)\],\s*\[(\d+),\s*(\d+)\]", sol
    )
    dx, dy = vk.delta_2
    assert tuple(int(m.group(i)) for i in range(1, 5)) == (dx.c1, dx.c0, dy.c1, dy.c0)
    assert all(0 < v < P for v in want_delta)
    assert "uint[26] memory input" in sol and "public view returns (bool r)" in sol
