"""Execution-path audit (utils.audit): the gate-arming matrix, the
execution digest, and the flight recorder.

The gate-matrix test is the regression test the round-2 silent disarm
never had: the PJRT plugin renamed itself ("axon") and every
`default_backend() == "tpu"` gate quietly routed on-chip runs to the
XLA fallback paths for three rounds.  Here the device platform is
mocked as "tpu" / "axon" / "cpu" and every `auto` gate must resolve to
its documented arm — a plugin rename flips the "axon" row, not silence.
"""

import re

import jax
import pytest

from zkp2p_tpu.utils import audit
from zkp2p_tpu.utils.metrics import REGISTRY


def _patch_backend(monkeypatch, backend: str, device_platform: str):
    """Mock the PJRT view: default_backend() names the PLUGIN, the
    device's .platform attribute names the hardware."""
    dev = type("FakeDev", (), {"platform": device_platform})()
    monkeypatch.setattr(jax, "default_backend", lambda: backend)
    monkeypatch.setattr(jax, "devices", lambda *a, **k: [dev])


# ---------------------------------------------------------------- gates


@pytest.mark.parametrize(
    "backend,plat,expect",
    [
        ("tpu", "tpu", True),    # plugin honestly named "tpu"
        ("axon", "tpu", True),   # the round-2 rename: hardware is TPU anyway
        ("cpu", "cpu", False),   # host fallback
    ],
)
def test_on_tpu_matrix(monkeypatch, backend, plat, expect):
    from zkp2p_tpu.utils.jaxcfg import on_tpu

    _patch_backend(monkeypatch, backend, plat)
    assert on_tpu() is expect
    assert audit.gate_arms()["on_tpu"] == ("tpu" if expect else "host")


@pytest.mark.parametrize(
    "backend,plat,armed",
    [("axon", "tpu", True), ("tpu", "tpu", True), ("cpu", "cpu", False)],
)
def test_auto_gates_resolve_documented_arms(monkeypatch, backend, plat, armed):
    """Every 'auto' impl gate arms exactly when the DEVICE platform is
    a TPU — regardless of what the plugin calls itself."""
    from zkp2p_tpu.prover import groth16_tpu as g

    _patch_backend(monkeypatch, backend, plat)
    monkeypatch.setattr(g, "MSM_UNIFIED", "auto")
    monkeypatch.setattr(g, "MSM_AFFINE", "auto")
    monkeypatch.setattr(g, "MSM_H", "auto")
    monkeypatch.setattr(g, "MSM_SIGNED", True)
    monkeypatch.setattr(g, "MSM_GLV", True)
    monkeypatch.setattr(g, "BATCH_CHUNK", "auto")
    assert g._unified() is armed
    assert g._affine() is armed
    assert g._h_bucket() is armed
    assert g._glv() is True  # GLV is backend-independent (signed-gated)
    assert g._batch_chunk_size() == (4 if armed else 0)
    arms = audit.gate_arms()
    assert arms["msm_unified"] == ("on" if armed else "off")
    assert arms["msm_affine"] == ("on" if armed else "off")
    assert arms["msm_h"] == ("bucket" if armed else "windowed")
    assert arms["msm_glv"] == "on"
    assert arms["batch_chunk"] == ("4" if armed else "0")


def test_forced_arms_beat_the_backend(monkeypatch):
    """'1'/'bucket' force the arm even on a host backend (the tests-only
    configuration), and signed-off disarms bucket-h and GLV."""
    from zkp2p_tpu.prover import groth16_tpu as g

    _patch_backend(monkeypatch, "cpu", "cpu")
    monkeypatch.setattr(g, "MSM_UNIFIED", "1")
    monkeypatch.setattr(g, "MSM_AFFINE", "1")
    monkeypatch.setattr(g, "MSM_H", "bucket")
    monkeypatch.setattr(g, "MSM_SIGNED", True)
    assert g._unified() is True and g._affine() is True and g._h_bucket() is True
    # signed off: bucket-h and GLV ride the signed machinery
    monkeypatch.setattr(g, "MSM_SIGNED", False)
    monkeypatch.setattr(g, "MSM_GLV", True)
    assert g._h_bucket() is False and g._glv() is False
    assert audit.gate_arms()["msm_h"] == "windowed"
    assert audit.gate_arms()["msm_glv"] == "off"


def test_field_and_curve_gates(monkeypatch):
    from zkp2p_tpu.curve import jcurve
    from zkp2p_tpu.curve.jcurve import G1J
    from zkp2p_tpu.field import jfield

    _patch_backend(monkeypatch, "cpu", "cpu")
    monkeypatch.setattr(jfield, "FIELD_MUL_IMPL", "auto")
    monkeypatch.setattr(jcurve, "CURVE_IMPL", "auto")
    assert jfield.field_mul_impl() == "xla"
    assert G1J._pallas() is False
    assert audit.gate_arms()["field_mul"] == "xla"
    assert audit.gate_arms()["curve_kernel"] == "xla"
    # the r5 mis-arm: pallas FORCED on a host backend resolves pallas
    # (interpret mode) — visible in the arm map, flagged by preflight
    monkeypatch.setattr(jfield, "FIELD_MUL_IMPL", "pallas")
    assert jfield.field_mul_impl() == "pallas"
    assert audit.gate_arms()["field_mul"] == "pallas"
    # curve "pallas" stays OFF on a host backend (interpret mode would
    # be orders of magnitude slower; differential tests call the
    # kernels directly) — the REQUESTED-but-not-armed case
    monkeypatch.setattr(jcurve, "CURVE_IMPL", "pallas")
    assert G1J._pallas() is False
    assert audit.gate_arms()["curve_kernel"] == "xla"
    # on the (renamed-plugin) TPU both arm
    _patch_backend(monkeypatch, "axon", "tpu")
    assert G1J._pallas() is True
    monkeypatch.setattr(jfield, "FIELD_MUL_IMPL", "auto")
    assert jfield.field_mul_impl() == "pallas"


def test_native_gates(monkeypatch):
    from zkp2p_tpu.prover import native_prove as npv

    monkeypatch.setenv("ZKP2P_MSM_GLV", "1")
    monkeypatch.setenv("ZKP2P_MSM_BATCH_AFFINE", "0")
    monkeypatch.setenv("ZKP2P_MSM_MULTI", "0")
    monkeypatch.setenv("ZKP2P_MSM_PRECOMP", "0")
    assert npv._use_glv() is True
    assert npv._use_batch_affine() is False
    assert npv._use_msm_multi() is False
    assert npv._use_msm_precomp() is False
    # batch-affine off gates the IFMA tier off regardless of hardware
    assert npv._native_ifma_tier() is False
    arms = audit.gate_arms()
    assert arms["native_msm_glv"] == "on"
    assert arms["native_batch_affine"] == "off"
    assert arms["native_msm_multi"] == "off"
    assert arms["native_msm_precomp"] == "off"
    assert arms["native_tier"] == "scalar"
    # default arm: multi + precomp ON (the _not_zero rule — off only on
    # a leading '0')
    monkeypatch.delenv("ZKP2P_MSM_MULTI", raising=False)
    assert npv._use_msm_multi() is True
    assert audit.gate_arms()["native_msm_multi"] == "on"
    monkeypatch.delenv("ZKP2P_MSM_PRECOMP", raising=False)
    assert npv._use_msm_precomp() is True
    assert audit.gate_arms()["native_msm_precomp"] == "on"


# ------------------------------------------------------------- digest


def test_execution_digest_stable_and_arm_sensitive():
    d_ab = audit.execution_digest({"g1": "a", "g2": "b"})
    assert re.fullmatch(r"[0-9a-f]{16}", d_ab)
    # order-independent: the digest hashes the SORTED map
    assert audit.execution_digest({"g2": "b", "g1": "a"}) == d_ab
    # one flipped arm changes it; one added gate changes it
    assert audit.execution_digest({"g1": "c", "g2": "b"}) != d_ab
    assert audit.execution_digest({"g1": "a", "g2": "b", "g3": "x"}) != d_ab


def test_record_arm_counters_and_map():
    base = REGISTRY.counter("zkp2p_path_taken_total", {"gate": "test_gate", "arm": "x"}).value
    assert audit.record_arm("test_gate", "x") == "x"
    audit.record_arm("test_gate", "x")
    assert REGISTRY.counter("zkp2p_path_taken_total", {"gate": "test_gate", "arm": "x"}).value == base + 2
    assert audit.gate_arms()["test_gate"] == "x"
    # bools render as on/off and pass through unchanged
    assert audit.record_arm("test_gate_b", True) is True
    assert audit.gate_arms()["test_gate_b"] == "on"


def test_record_arm_survives_registry_reset():
    """REGISTRY.reset() orphans instruments; the audit counter cache is
    generation-keyed so later records land in live instruments."""
    audit.record_arm("test_gen_gate", "a")
    REGISTRY.reset()
    audit.record_arm("test_gen_gate", "a")
    assert REGISTRY.counter("zkp2p_path_taken_total", {"gate": "test_gen_gate", "arm": "a"}).value == 1


def test_run_manifest_carries_gates_and_digest():
    from zkp2p_tpu.utils.metrics import run_manifest

    audit.record_arm("test_manifest_gate", "armed")
    man = run_manifest()
    assert man["gates"]["test_manifest_gate"] == "armed"
    assert man["execution_digest"] == audit.execution_digest()


# ------------------------------------------------------ flight recorder


def test_memory_sampler_degrades_on_cpu():
    # XLA:CPU exposes no memory_stats — sampling must be a cheap no-op
    assert audit.sample_device_memory("test") is None


def test_memory_sampler_gauges(monkeypatch):
    class Dev:
        platform = "tpu"

        @staticmethod
        def memory_stats():
            return {"bytes_in_use": 100, "peak_bytes_in_use": 250, "bytes_limit": 1000}

    monkeypatch.setattr(jax, "devices", lambda *a, **k: [Dev()])
    monkeypatch.setattr(audit, "_mem_devices", None)  # re-probe with the fake
    got = audit.sample_device_memory("test_stage")
    assert got == {"device": 0, "bytes_in_use": 100, "peak_bytes_in_use": 250, "bytes_limit": 1000}
    assert REGISTRY.gauge("zkp2p_hbm_bytes_in_use", {"device": "0"}).value == 100
    assert REGISTRY.gauge("zkp2p_hbm_peak_bytes", {"device": "0"}).value == 250
    # stage peak keeps the MAX across samples
    assert REGISTRY.gauge("zkp2p_hbm_stage_peak_bytes", {"stage": "test_stage"}).value == 250

    class Smaller(Dev):
        @staticmethod
        def memory_stats():
            return {"bytes_in_use": 50, "peak_bytes_in_use": 60, "bytes_limit": 1000}

    monkeypatch.setattr(jax, "devices", lambda *a, **k: [Smaller()])
    monkeypatch.setattr(audit, "_mem_devices", None)
    audit.sample_device_memory("test_stage")
    assert REGISTRY.gauge("zkp2p_hbm_stage_peak_bytes", {"stage": "test_stage"}).value == 250


def test_compile_listener_attributes_stage():
    import jax.numpy as jnp

    from zkp2p_tpu.utils.trace import trace

    assert audit.install_compile_listener()
    assert audit.install_compile_listener()  # idempotent
    n0 = REGISTRY.counter("zkp2p_compile_events_total", {"stage": "audit_compile_test"}).value
    with trace("audit_compile_test"):
        # a fresh closure constant -> a fresh executable -> one compile
        jax.jit(lambda x: x * 7919 + 11)(jnp.arange(4)).block_until_ready()
    assert REGISTRY.counter("zkp2p_compile_events_total", {"stage": "audit_compile_test"}).value > n0
    assert REGISTRY.counter("zkp2p_compile_seconds_total", {"stage": "audit_compile_test"}).value > 0


# ------------------------------------------------------------ preflight


def test_preflight_reports_every_gate_and_is_stable():
    rep = audit.preflight(probe=False, workload=False)
    for gate in (
        "on_tpu", "field_mul", "curve_kernel", "msm_unified", "msm_affine",
        "msm_h", "msm_glv", "batch_chunk", "native_msm_glv",
        "native_batch_affine", "native_msm_multi", "native_tier",
    ):
        assert rep["gates"].get(gate), f"gate {gate} reported no arm"
    assert re.fullmatch(r"[0-9a-f]{16}", rep["execution_digest"])
    assert rep["backend"] == "cpu"
    assert rep["tpu_probe"] == {"skipped": True} or "ok" in rep["tpu_probe"]
    # a second in-process run arms the same gates to the same arms
    rep2 = audit.preflight(probe=False, workload=False)
    assert rep2["gates"] == rep["gates"]
    assert rep2["execution_digest"] == rep["execution_digest"]


def test_preflight_flags_misarmed_pallas(monkeypatch):
    from zkp2p_tpu.field import jfield

    monkeypatch.setattr(jfield, "FIELD_MUL_IMPL", "pallas")
    rep = audit.preflight(probe=False, workload=False)
    assert rep["gates"]["field_mul"] == "pallas"
    assert any("INTERPRET" in w for w in rep["warnings"]), rep["warnings"]
    # and the digest differs from the correctly-armed run
    monkeypatch.setattr(jfield, "FIELD_MUL_IMPL", "auto")
    ok = audit.preflight(probe=False, workload=False)
    assert ok["execution_digest"] != rep["execution_digest"]
    assert not any("INTERPRET" in w for w in ok["warnings"])
