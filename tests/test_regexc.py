"""regexc compiler vs Python `re` (the reference's own test strategy:
regex_to_circom/test.py:20-40 checks the Venmo regexes with plain `re`),
plus the R1CS DFA gadget on the compiled tables."""

import random
import re

import pytest

from zkp2p_tpu.gadgets import core
from zkp2p_tpu.gadgets.regex import CharClassCache, dfa_scan, match_count, reveal_bytes
from zkp2p_tpu.regexc import compiler
from zkp2p_tpu.regexc.compiler import compile_regex
from zkp2p_tpu.snark.r1cs import ConstraintSystem

rng = random.Random(11)


CASES = [
    ("hello[0-9]+world", ["hello123world", "helloworld", "hello1world", "hello12", "xhello1world"]),
    ("(to|from):", ["to:", "from:", "tofrom:", "to", "fr:"]),
    ("a(bc)*d", ["ad", "abcd", "abcbcd", "abcbd", "abc"]),
    (r"\$[0-9]+\.", ["$30.", "$5", "$.", "$123456.", "x$1."]),
    ("[a-c]?x", ["x", "ax", "cx", "dx", "aax"]),
    (compiler.VENMO_OFFRAMPER_ID, ["user_id=3D12345", "user_id=3D", "user_id=3Dab_9"]),
    (compiler.VENMO_MESSAGE, ["<p>123", "<p>", "<p>x1", "p>9", "<p>007"]),
]


@pytest.mark.parametrize("pattern,samples", CASES, ids=[c[0][:20] for c in CASES])
def test_dfa_matches_re(pattern, samples):
    dfa = compile_regex(pattern)
    gold = re.compile(pattern.replace("=3D", "=3D"))  # full-match semantics
    for s in samples:
        want = gold.fullmatch(s) is not None
        assert dfa.matches(s.encode()) == want, (pattern, s)


def test_dfa_random_fuzz():
    pattern = "(ab|cd)+e?f"
    dfa = compile_regex(pattern)
    gold = re.compile(pattern)
    alpha = "abcdef"
    for _ in range(300):
        s = "".join(rng.choice(alpha) for _ in range(rng.randrange(0, 8)))
        assert dfa.matches(s.encode()) == (gold.fullmatch(s) is not None), s


def test_dfa_minimization_small():
    # (a|b)*abb classic: minimal DFA has 4 states
    dfa = compile_regex("(a|b)*abb")
    assert dfa.n_states == 4


def test_dfa_gadget_scan_and_reveal():
    """Substring-search form (catch-all prefix) over a byte buffer, as the
    body regexes use it; checks the state matrix, count and reveal mask."""
    pattern = "[0-9]+x"
    dfa = compile_regex(pattern)
    data = b"ab12x9"
    cs = ConstraintSystem("re")
    wires = cs.new_wires(len(data), "in")
    core.assert_bytes(cs, wires)
    states = dfa_scan(cs, wires, dfa)
    cnt = match_count(cs, states, dfa.accept)
    seed = {w: b for w, b in zip(wires, data)}
    w = cs.witness([], seed)
    cs.check_witness(w)
    # host oracle: states after each byte
    host_states = dfa.run(data)
    for t, hs in enumerate(host_states):
        onehot = [w[states[t + 1][j]] for j in range(dfa.n_states)]
        if hs == compiler.DEAD:
            assert sum(onehot) == 0
        else:
            assert onehot[hs] == 1 and sum(onehot) == 1
    assert w[cnt] == sum(1 for s in host_states if s in dfa.accept)


def test_venmo_message_scan():
    """Legacy `<p>[0-9]+` message regex (venmo_message_regex.circom:8) in
    substring-search form over an HTML body snippet: the scan counts one
    match per digit consumed and the reveal mask covers the digits."""
    dfa = compiler.search_dfa(compiler.VENMO_MESSAGE)
    data = b"<html><p>4207</p>x"
    cs = ConstraintSystem("msg")
    wires = cs.new_wires(len(data), "in")
    core.assert_bytes(cs, wires)
    cache = CharClassCache(cs)
    states = dfa_scan(cs, wires, dfa, cache)
    cnt = match_count(cs, states, dfa.accept)
    rev = reveal_bytes(cs, wires, states, sorted(dfa.accept))
    w = cs.witness([], {wi: b for wi, b in zip(wires, data)})
    cs.check_witness(w)
    assert w[cnt] == 4  # accept fires after each of 4, 2, 0, 7
    assert bytes(w[r] for r in rev).replace(b"\x00", b"") == b"4207"


def test_dfa_gadget_venmo_id_reveal():
    dfa = compile_regex(compiler.VENMO_OFFRAMPER_ID)
    payload = b"user_id=3D4499" + b"\r\n"
    cs = ConstraintSystem("venmo")
    wires = cs.new_wires(len(payload), "in")
    core.assert_bytes(cs, wires)
    cache = CharClassCache(cs)
    states = dfa_scan(cs, wires, dfa, cache)
    # reveal everything matched after the fixed prefix: the digit states
    matched_states = [s for s in range(dfa.n_states) if s in dfa.accept]
    rev = reveal_bytes(cs, wires, states, matched_states)
    w = cs.witness([], {wi: b for wi, b in zip(wires, payload)})
    cs.check_witness(w)
    revealed = bytes(w[r] for r in rev)
    # the accept states cover the payload chars after "user_id=3D"
    assert revealed.rstrip(b"\x00")[-6:] == b"4499\r\n"[-6:]


def test_lookup_table_artifact(tmp_path):
    """The halo2-analog lookup artifact (`gen.py:41-51`): every row must
    be a real transition, every non-DEAD transition must appear, and the
    DFA must be reconstructible from the rows."""
    from zkp2p_tpu.regexc.compiler import DEAD, VENMO_AMOUNT, compile_regex

    dfa = compile_regex(VENMO_AMOUNT)
    rows = dfa.lookup_rows()
    assert rows, "amount DFA has transitions"
    seen = set()
    for src, dst, c in rows:
        assert int(dfa.next[src, c]) == dst
        seen.add((src, c))
    for s in range(dfa.n_states):
        for c in range(256):
            if int(dfa.next[s, c]) != DEAD:
                assert (s, c) in seen

    out = tmp_path / "lookup.txt"
    dfa.emit_lookup_table(str(out))
    lines = out.read_text().splitlines()
    accepts = [int(x) for x in lines[0].split()]
    assert set(accepts) == set(dfa.accept)
    assert len(lines) - 1 == len(rows)
