"""Wire-format tests: proof/vkey JSON, calldata flip, r1cs/wtns binaries,
Solidity verifier export."""

import os

import pytest

from zkp2p_tpu.field.bn254 import R
from zkp2p_tpu.formats import circom_bin, proof_json, solidity
from zkp2p_tpu.snark.groth16 import prove_host, setup, verify
from zkp2p_tpu.snark.r1cs import LC, ConstraintSystem


def build_toy():
    cs = ConstraintSystem("toy")
    out = cs.new_public("out")
    x = cs.new_wire("x")
    y = cs.new_wire("y")
    z = cs.new_wire("z")
    cs.enforce(LC.of(x), LC.of(y), LC.of(z), "mul")
    cs.enforce(LC.of(z), LC.of(z), LC.of(out), "sq")
    cs.compute(z, lambda a, b: a * b % R, [x, y])
    return cs, x, y


def test_proof_vkey_json_roundtrip(tmp_path):
    cs, x, y = build_toy()
    w = cs.witness([225], {x: 3, y: 5})
    pk, vk = setup(cs, seed="fmt")
    proof = prove_host(pk, cs, w)

    pj = proof_json.proof_to_json(proof)
    assert pj["protocol"] == "groth16" and pj["curve"] == "bn128"
    assert proof_json.proof_from_json(pj) == proof

    vj = proof_json.vkey_to_json(vk)
    vk2 = proof_json.vkey_from_json(vj)
    assert verify(vk2, proof, [225])

    a, b, c, signals = proof_json.proof_to_calldata(proof, [225])
    # the pi_b flip: c1 first (SubmitOrderOnRampForm.tsx:36-46)
    assert b[0][0] == proof.b[0].c1 and b[0][1] == proof.b[0].c0


def test_r1cs_wtns_roundtrip(tmp_path):
    cs, x, y = build_toy()
    w = cs.witness([225], {x: 3, y: 5})

    r1cs_path = os.path.join(tmp_path, "toy.r1cs")
    circom_bin.write_r1cs(cs, r1cs_path)
    r = circom_bin.read_r1cs(r1cs_path)
    assert r.n_wires == cs.num_wires
    assert r.n_public == cs.num_public
    assert len(r.constraints) == cs.num_constraints

    cs2 = circom_bin.r1cs_to_constraint_system(r)
    cs2.check_witness(w)  # imported constraints accept the same witness
    bad = list(w)
    bad[-1] = (bad[-1] + 1) % R
    with pytest.raises(AssertionError):
        cs2.check_witness(bad)

    wtns_path = os.path.join(tmp_path, "toy.wtns")
    circom_bin.write_wtns(w, wtns_path)
    assert circom_bin.read_wtns(wtns_path) == [v % R for v in w]


def test_imported_r1cs_proves(tmp_path):
    """Import path end-to-end: r1cs in, setup + prove + verify without the
    original witness program (the prover=tpu drop-in contract)."""
    cs, x, y = build_toy()
    w = cs.witness([225], {x: 3, y: 5})
    path = os.path.join(tmp_path, "t.r1cs")
    circom_bin.write_r1cs(cs, path)
    cs2 = circom_bin.r1cs_to_constraint_system(circom_bin.read_r1cs(path))
    pk, vk = setup(cs2, seed="imp")
    proof = prove_host(pk, cs2, w)
    assert verify(vk, proof, [225])


def test_solidity_export_contains_vkey():
    cs, x, y = build_toy()
    pk, vk = setup(cs, seed="sol")
    src = solidity.export_verifier(vk)
    assert "function verifyProof" in src
    assert f"uint[{vk.n_public}] memory input" in src
    assert str(vk.alpha_1[0]) in src
    assert str(vk.ic[1][0]) in src
    assert "pragma solidity ^0.8.12" in src
