"""Bigint + RSA gadget tests against Python bigints.

RSA end-to-end uses the real n=121/k=17 parameterisation for limb
conversion checks but a reduced-size modexp circuit for speed; a full
2048-bit verify runs once (marked) to pin the production path."""

import hashlib
import random

import pytest

from zkp2p_tpu.field.bn254 import R
from zkp2p_tpu.gadgets import bigint, core, rsa
from zkp2p_tpu.snark.r1cs import LC, ConstraintSystem

rng = random.Random(77)


def seed_limbs(cs, value, n, k, label):
    wires = bigint.alloc_limbs(cs, k, label)
    limbs = bigint.int_to_limbs_host(value, n, k)
    return wires, dict(zip(wires, limbs))


@pytest.mark.parametrize("n,k", [(8, 4), (121, 17)])
def test_limb_roundtrip(n, k):
    for _ in range(5):
        v = rng.randrange(1 << (n * k))
        assert bigint.limbs_to_int_host(bigint.int_to_limbs_host(v, n, k), n) == v


def test_big_mult_mod_small():
    n, k = 16, 4
    cs = ConstraintSystem("mulmod")
    p_val = rng.randrange(1 << (n * k - 1), 1 << (n * k))
    a_val = rng.randrange(p_val)
    b_val = rng.randrange(p_val)
    a, seed_a = seed_limbs(cs, a_val, n, k, "a")
    b, seed_b = seed_limbs(cs, b_val, n, k, "b")
    p, seed_p = seed_limbs(cs, p_val, n, k, "p")
    bigint.range_check_limbs(cs, a, n, "a")
    bigint.range_check_limbs(cs, b, n, "b")
    bigint.range_check_limbs(cs, p, n, "p")
    r_wires = bigint.big_mult_mod(cs, a, b, p, n)
    w = cs.witness([], {**seed_a, **seed_b, **seed_p})
    cs.check_witness(w)
    got = bigint.limbs_to_int_host([w[x] for x in r_wires], n)
    assert got == a_val * b_val % p_val


def test_big_mult_mod_rejects_wrong_remainder():
    n, k = 16, 3
    cs = ConstraintSystem("mulmodbad")
    p_val = (1 << 47) + 115
    a, seed_a = seed_limbs(cs, 123456789, n, k, "a")
    b, seed_b = seed_limbs(cs, 987654321, n, k, "b")
    p, seed_p = seed_limbs(cs, p_val, n, k, "p")
    r_wires = bigint.big_mult_mod(cs, a, b, p, n)
    w = cs.witness([], {**seed_a, **seed_b, **seed_p})
    # corrupt the remainder -> the carry check must fail
    w[r_wires[0]] = (w[r_wires[0]] + 1) % R
    with pytest.raises(AssertionError):
        cs.check_witness(w)


def test_big_less_than():
    n, k = 16, 3
    cases = [(5, 9, 1), (9, 5, 0), (7, 7, 0), (1 << 40, (1 << 40) + 1, 1), ((1 << 47) - 1, 1, 0)]
    cs = ConstraintSystem("biglt")
    a = bigint.alloc_limbs(cs, k, "a")
    b = bigint.alloc_limbs(cs, k, "b")
    out = bigint.big_less_than(cs, a, b, n)
    for av, bv, want in cases:
        seed = dict(zip(a, bigint.int_to_limbs_host(av, n, k)))
        seed.update(zip(b, bigint.int_to_limbs_host(bv, n, k)))
        w = cs.witness([], seed)
        cs.check_witness(w)
        assert w[out] == want, (av, bv)


def _digest_bit_values(digest: bytes):
    vals = []
    for wi in range(8):
        word = int.from_bytes(digest[4 * wi : 4 * wi + 4], "big")
        vals.extend((word >> i) & 1 for i in range(32))
    return vals


def test_pkcs1_pad_lc_value():
    """The padded-message LCs must equal the standard EMSA-PKCS1-v1_5 value."""
    n, k = 121, 17
    msg = b"attack at dawn"
    digest = hashlib.sha256(msg).digest()
    cs = ConstraintSystem("pad")
    dbits = cs.new_wires(256, "d")
    lcs = rsa.pkcs1v15_pad_limbs_lc(dbits, n, k)
    seed = dict(zip(dbits, _digest_bit_values(digest)))
    # wire in a dummy constraint so witness() runs; evaluate LCs directly
    w = cs.witness([], seed)
    em = b"\x00\x01" + b"\xff" * 202 + b"\x00" + rsa.DIGEST_INFO.to_bytes(19, "big") + digest
    em_int = int.from_bytes(em, "big")
    got = sum(lc.eval(w) << (n * i) for i, lc in enumerate(lcs))
    assert got == em_int


@pytest.mark.slow
def test_rsa_verify_2048_end_to_end():
    """Full RSAVerify65537 with a real 2048-bit key (slow: ~17 bigmuls with
    121x17 limbs; run in CI but kept last)."""
    n, k = 121, 17
    # deterministic toy 2048-bit RSA key (Fermat-filtered pseudoprimes are
    # fine here: the fixed seed makes the key reproducible, and signing
    # only needs e invertible mod phi)
    rng2 = random.Random(1)

    def rand_prime(bits):
        while True:
            c = rng2.getrandbits(bits) | (1 << (bits - 1)) | 1
            if pow(2, c - 1, c) == 1 and pow(3, c - 1, c) == 1 and pow(5, c - 1, c) == 1:
                return c

    pp = rand_prime(1024)
    qq = rand_prime(1024)
    N = pp * qq
    e = 65537
    d = pow(e, -1, (pp - 1) * (qq - 1))

    msg = b"venmo payment receipt"
    digest = hashlib.sha256(msg).digest()
    em = b"\x00\x01" + b"\xff" * 202 + b"\x00" + rsa.DIGEST_INFO.to_bytes(19, "big") + digest
    em_int = int.from_bytes(em, "big")
    sig = pow(em_int, d, N)
    assert pow(sig, e, N) == em_int

    cs = ConstraintSystem("rsa2048")
    sig_w, seed_s = seed_limbs(cs, sig, n, k, "sig")
    mod_w, seed_m = seed_limbs(cs, N, n, k, "mod")
    dbits = cs.new_wires(256, "d")
    for b in dbits:
        cs.enforce_bool(b)
    rsa.rsa_verify_65537(cs, sig_w, mod_w, dbits)
    seed = {**seed_s, **seed_m, **dict(zip(dbits, _digest_bit_values(digest)))}
    w = cs.witness([], seed)
    cs.check_witness(w)

    # wrong digest must fail
    bad = dict(seed)
    bad[dbits[0]] = 1 - bad[dbits[0]]
    w_bad = cs.witness([], bad)
    with pytest.raises(AssertionError):
        cs.check_witness(w_bad)
