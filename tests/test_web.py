"""The client web surface driven over real HTTP: post order -> claim ->
decrypt-and-verify claims (the MainPage / NewOrderForm / ClaimOrderForm /
SubmitOrderClaimsForm arc, SURVEY §2.5)."""

import json
import urllib.error
import urllib.request

import pytest

from zkp2p_tpu.client.web import OnrampApp, serve
from zkp2p_tpu.contracts.ramp import FakeUSDC, Ramp


@pytest.fixture()
def server():
    from zkp2p_tpu.contracts.deploy import VENMO_RSA_KEY_LIMBS

    usdc = FakeUSDC()

    class _NoVerify:
        """Ramp vk stand-in: /api/onramp is prover-gated and not exercised
        here (the pairing path is covered by test_contracts)."""

        n_public = 26

    ramp = Ramp(VENMO_RSA_KEY_LIMBS, usdc, max_amount=100_000_000, vk=_NoVerify())
    app = OnrampApp(ramp, usdc)
    srv = serve(app, port=0)
    port = srv.server_address[1]
    yield f"http://127.0.0.1:{port}", app
    srv.shutdown()


def _post(base, path, payload):
    req = urllib.request.Request(
        base + path, data=json.dumps(payload).encode(), headers={"content-type": "application/json"}
    )
    try:
        with urllib.request.urlopen(req) as r:
            return json.loads(r.read())
    except urllib.error.HTTPError as e:
        raise AssertionError(f"{path} -> {e.code}: {e.read().decode()}") from e


def _get(base, path):
    with urllib.request.urlopen(base + path) as r:
        return json.loads(r.read())


def test_order_claim_decrypt_flow(server):
    base, app = server

    # page renders
    with urllib.request.urlopen(base + "/") as r:
        assert b"ZKP2P" in r.read()

    # on-ramper posts an order
    out = _post(base, "/api/orders", {"address": "alice", "signature": "alice-sig", "amount": 30_000_000, "max_amount_to_pay": 31_000_000})
    oid = out["order_id"]
    orders = _get(base, "/api/orders")
    assert orders[-1]["id"] == oid and orders[-1]["status"] == "Open"

    # off-ramper claims it (ECIES-encrypted venmo id + Poseidon hash)
    out = _post(
        base,
        "/api/claims",
        {"address": "bob", "venmo_id": "1234567891234567891", "order_id": oid, "min_amount_to_pay": 30_500_000},
    )
    cid = out["claim_id"]

    # on-ramper decrypts and verifies the claim hash (Matches column) —
    # POST so the wallet secret stays out of query strings
    views = _post(base, "/api/claims-decrypted", {"address": "alice", "signature": "alice-sig", "order_id": oid})
    assert views == [
        {"claim_id": cid, "venmo_id": "1234567891234567891", "matches": True, "min_amount_to_pay": 30_500_000}
    ]

    # prover-gated endpoint reports unavailable without a bundle
    req = urllib.request.Request(
        base + "/api/onramp",
        data=json.dumps({"address": "alice", "signature": "alice-sig", "order_id": oid, "claim_id": cid}).encode(),
        headers={"content-type": "application/json"},
    )
    try:
        urllib.request.urlopen(req)
        raise AssertionError("expected 503")
    except urllib.error.HTTPError as e:
        assert e.code == 503


def test_bad_request_is_reported(server):
    base, _ = server
    req = urllib.request.Request(
        base + "/api/claims",
        data=json.dumps({"address": "bob", "venmo_id": "x", "order_id": 999, "min_amount_to_pay": 1}).encode(),
        headers={"content-type": "application/json"},
    )
    try:
        urllib.request.urlopen(req)
        raise AssertionError("expected 400")
    except urllib.error.HTTPError as e:
        assert e.code == 400
        assert "error" in json.loads(e.read())


def test_wrong_wallet_signature_is_rejected(server):
    base, _ = server
    _post(base, "/api/orders", {"address": "carol", "signature": "s3cret", "amount": 9000000, "max_amount_to_pay": 9500000})
    for payload in (
        {"address": "carol", "signature": "WRONG", "order_id": 1},
        {"address": "carol", "order_id": 1},  # missing secret
    ):
        req = urllib.request.Request(
            base + "/api/claims-decrypted",
            data=json.dumps(payload).encode(),
            headers={"content-type": "application/json"},
        )
        try:
            urllib.request.urlopen(req)
            raise AssertionError("expected 403")
        except urllib.error.HTTPError as e:
            assert e.code == 403


def test_orders_paging(server):
    """MainPage-style paging: offset/limit envelope with total."""
    base, app = server
    for i in range(5):
        _post(base, "/api/orders", {"address": f"on{i}", "signature": f"s{i}", "amount": 1000 + i, "max_amount_to_pay": 2000})
    page = _get(base, "/api/orders?offset=1&limit=2")
    assert page["total"] == 5 and page["offset"] == 1
    assert [r["amount"] for r in page["orders"]] == [1001, 1002]
    # legacy bare-list shape preserved when unpaged
    assert len(_get(base, "/api/orders")) == 5


def test_meta_registry(server):
    """Chain-glue registry: the contract constants a client binds to."""
    base, app = server
    meta = _get(base, "/api/meta")
    assert meta["ramp_address"] == app.ramp.address
    assert meta["max_amount_usdc"] == 100_000_000
    assert len(meta["venmo_rsa_limbs"]) == 17
    assert meta["msg_len"] == 26
    assert meta["prover_loaded"] is False
    assert "onRamp(" in meta["onramp_calldata"]


def test_eml_upload_and_spool(server, tmp_path):
    """Drag-and-drop equivalent: raw .eml bytes in, spooled name out,
    readable back through the guarded spool reader."""
    base, app = server
    app.eml_spool = str(tmp_path)
    raw = b"From: venmo@venmo.com\r\nSubject: test\r\n\r\nbody"
    req = urllib.request.Request(
        base + "/api/eml", data=raw, headers={"content-type": "message/rfc822"}
    )
    with urllib.request.urlopen(req) as r:
        name = json.loads(r.read())["eml_path"]
    assert name.startswith("upload-") and name.endswith(".eml")
    assert app.read_spooled_eml(name) == raw


def test_eml_upload_requires_spool(server):
    base, app = server
    req = urllib.request.Request(base + "/api/eml", data=b"x", headers={})
    try:
        urllib.request.urlopen(req)
        raise AssertionError("expected 403")
    except urllib.error.HTTPError as e:
        assert e.code == 403


def test_zkey_fetch_progress(server, tmp_path):
    """ProgressBar equivalent: background chunked-zkey pull polled via
    /api/zkey-progress until state=done with all chunks counted."""
    import time

    from zkp2p_tpu.formats.artifact_store import DirBackend, upload_chunked

    base, app = server
    blob = bytes(range(256)) * 512  # 128 KiB "zkey"
    upload_chunked(DirBackend(str(tmp_path)), "circuit.zkey", blob)
    assert _get(base, "/api/zkey-progress")["state"] == "idle"
    # the store path is SERVER config — a client cannot supply one
    app.zkey_store = str(tmp_path)
    _post(base, "/api/zkey-fetch", {})
    for _ in range(100):
        prog = _get(base, "/api/zkey-progress")
        if prog["state"] == "done":
            break
        time.sleep(0.05)
    assert prog["state"] == "done"
    assert prog["done"] == prog["total"] > 0
    assert prog["bytes"] == len(blob)


def test_zkey_fetch_requires_server_config(server):
    """A client must not be able to point the fetch at host paths."""
    base, app = server
    req = urllib.request.Request(
        base + "/api/zkey-fetch",
        data=json.dumps({"store_dir": "/etc/cron.d"}).encode(),
    )
    try:
        urllib.request.urlopen(req)
        raise AssertionError("expected 403")
    except urllib.error.HTTPError as e:
        assert e.code == 403  # no --zkey-store configured, payload ignored


def test_eml_upload_size_capped(server, tmp_path):
    base, app = server
    app.eml_spool = str(tmp_path)
    req = urllib.request.Request(base + "/api/eml", data=b"x" * 10)
    req.add_header("content-length", str(8 * 1024 * 1024 * 1024))
    try:
        urllib.request.urlopen(req)
        raise AssertionError("expected 403")
    except (urllib.error.HTTPError, ConnectionError, OSError) as e:
        if isinstance(e, urllib.error.HTTPError):
            assert e.code == 403


def test_orders_paging_negative_limit(server):
    base, app = server
    _post(base, "/api/orders", {"address": "n1", "signature": "s", "amount": 5, "max_amount_to_pay": 9})
    page = _get(base, "/api/orders?offset=0&limit=-2")
    assert page["orders"] == [] and page["total"] == 1
