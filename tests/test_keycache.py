"""Data-only .npz device-key cache round trip (prover.keycache)."""

import os

import numpy as np
import pytest

from zkp2p_tpu.field.bn254 import R
from zkp2p_tpu.native import lib as native
from zkp2p_tpu.snark.r1cs import LC, ConstraintSystem

pytestmark = pytest.mark.skipif(native.get_lib() is None, reason="native toolchain unavailable")


def test_keycache_roundtrip(tmp_path):
    from zkp2p_tpu.prover.groth16_tpu import _DPK_ARRAY_FIELDS
    from zkp2p_tpu.prover.keycache import load_dpk, save_dpk
    from zkp2p_tpu.prover.setup_device import setup_device

    cs = ConstraintSystem("kc")
    out = cs.new_public("out")
    x = cs.new_wire("x")
    y = cs.new_wire("y")
    z = cs.new_wire("z")
    cs.enforce(LC.of(x), LC.of(y), LC.of(z), "mul")
    cs.enforce(LC.of(z), LC.of(z), LC.of(out), "sq")
    cs.compute(z, lambda a, b: a * b % R, [x, y])
    dpk, vk = setup_device(cs, seed="kc")

    path = os.path.join(tmp_path, "key.npz")
    save_dpk(path, dpk, vk)
    dpk2, vk2 = load_dpk(path)

    assert (dpk2.n_public, dpk2.n_wires, dpk2.log_m) == (dpk.n_public, dpk.n_wires, dpk.log_m)
    for f in _DPK_ARRAY_FIELDS:
        a, b = getattr(dpk, f), getattr(dpk2, f)
        if isinstance(a, tuple):
            for i, (x_, y_) in enumerate(zip(a, b)):
                np.testing.assert_array_equal(np.asarray(x_), np.asarray(y_), err_msg=f"{f}[{i}]")
        else:
            np.testing.assert_array_equal(np.asarray(a), np.asarray(b), err_msg=f)
    assert (dpk2.alpha_1, dpk2.beta_1, dpk2.beta_2) == (dpk.alpha_1, dpk.beta_1, dpk.beta_2)
    assert (dpk2.delta_1, dpk2.delta_2) == (dpk.delta_1, dpk.delta_2)
    assert vk2.ic == vk.ic and vk2.gamma_2 == vk.gamma_2
