"""Request waterfalls (PR 8 tentpole): per-request lifecycle spans and
queue_wait_s on every service record (deferred sweeps included), the
Chrome-trace export (valid JSON, monotonic timestamps, one pid per
worker / one tid per request), the takeover and batch-fill meters, and
the time-series sampler line schema — tier-1 resident."""

import json
import os
import subprocess
import sys
import time

import pytest

from zkp2p_tpu.field.bn254 import R
from zkp2p_tpu.native import lib as native
from zkp2p_tpu.pipeline.service import ProvingService, TimeseriesSampler
from zkp2p_tpu.utils import faults
from zkp2p_tpu.utils.metrics import REGISTRY

pytestmark = pytest.mark.skipif(native.get_lib() is None, reason="native toolchain unavailable")

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture(scope="module")
def world():
    from zkp2p_tpu.prover.groth16_tpu import device_pk
    from zkp2p_tpu.snark.groth16 import setup
    from zkp2p_tpu.snark.r1cs import LC, ConstraintSystem

    cs = ConstraintSystem("waterfall")
    out = cs.new_public("out")
    x = cs.new_wire("x")
    y = cs.new_wire("y")
    z = cs.new_wire("z")
    cs.enforce(LC.of(x), LC.of(y), LC.of(z), "mul")
    cs.enforce(LC.of(z), LC.of(z), LC.of(out), "sq")
    cs.compute(z, lambda a, b: a * b % R, [x, y])
    pk, vk = setup(cs, seed="waterfall")
    dpk = device_pk(pk, cs)

    def witness_fn(payload):
        xv, yv = int(payload["x"]), int(payload["y"])
        return cs.witness([pow(xv * yv, 2, R)], {x: xv, y: yv})

    return cs, dpk, vk, witness_fn


def _mk(world, **kw):
    from zkp2p_tpu.prover.native_prove import prove_native_batch

    cs, dpk, vk, witness_fn = world
    kw.setdefault("batch_size", 2)
    kw.setdefault("prover_fn", prove_native_batch)
    return ProvingService(cs, dpk, vk, witness_fn, public_fn=lambda w: [w[1]], **kw)


def _write_reqs(spool, pairs, prefix="r"):
    for i, (xv, yv) in enumerate(pairs):
        with open(os.path.join(spool, f"{prefix}{i}.req.json"), "w") as f:
            json.dump({"x": xv, "y": yv}, f)


def _records(spool):
    path = str(spool).rstrip("/") + ".metrics.jsonl"
    if not os.path.exists(path):
        return []
    with open(path) as f:
        return [json.loads(ln) for ln in f if json.loads(ln).get("type") == "request"]


def _counter(name, **labels):
    return REGISTRY.counter(name, labels or None).value


# ------------------------------------------------------- record schema


def test_done_records_carry_full_waterfall(world, tmp_path, monkeypatch):
    """Every done record: t_submit/t_claim/queue_wait_s plus the
    witness -> prove -> verify -> emit span chain, with the prove span
    SHARED across the batch (one interval, every member)."""
    monkeypatch.delenv("ZKP2P_METRICS_SINK", raising=False)
    monkeypatch.delenv("ZKP2P_FAULTS", raising=False)
    faults.reset()
    spool = str(tmp_path)
    _write_reqs(spool, [(3, 5), (2, 7)])
    t_before = time.time()
    assert _mk(world).process_dir(spool)["done"] == 2
    recs = {r["request_id"]: r for r in _records(spool)}
    assert set(recs) == {"r0", "r1"}
    for r in recs.values():
        assert r["state"] == "done"
        assert r["t_submit"] <= r["t_claim"] <= time.time()
        assert r["t_submit"] <= t_before + 1.0  # mtime-anchored, not claim-time
        assert r["queue_wait_s"] == pytest.approx(r["t_claim"] - r["t_submit"], abs=1e-3)
        names = [s["name"] for s in r["spans"]]
        assert names.index("witness") < names.index("prove") < names.index("emit")
        assert "verify" in names
        for s in r["spans"]:
            assert s["ms"] >= 0 and s["t0"] >= r["t_submit"] - 1.0
    # the batch prove is ONE shared interval: same t0/ms on both members
    p0 = [s for s in recs["r0"]["spans"] if s["name"] == "prove"][0]
    p1 = [s for s in recs["r1"]["spans"] if s["name"] == "prove"][0]
    assert p0["t0"] == p1["t0"] and p0["ms"] == p1["ms"] and p0["n"] == 2


def test_retry_attempts_and_rungs_appear_as_spans(world, tmp_path, monkeypatch):
    """A transient prove fault retried once leaves attempt-0 AND
    attempt-1 prove spans (plus the backoff) on the terminal record —
    failed attempts are part of the waterfall, not invisible."""
    spool = str(tmp_path)
    _write_reqs(spool, [(3, 5)])
    monkeypatch.setenv("ZKP2P_FAULTS", "prove:raise:once")
    faults.reset()
    svc = _mk(world, retry_backoff_s=0.01)
    assert svc.process_dir(spool)["done"] == 1
    (rec,) = _records(spool)
    proves = [s for s in rec["spans"] if s["name"] == "prove"]
    assert len(proves) == 2
    assert "attempt" not in proves[0] and proves[1]["attempt"] == 1
    assert any(s["name"] == "retry_backoff" for s in rec["spans"])


def test_deferred_sweep_keeps_history(world, tmp_path, monkeypatch):
    """A transient witness failure defers: the sweep emits a
    state='deferred' record (reason + spans + queue_wait), the next
    sweep terminals — cumulative queue_wait_s grows across the cycle
    because it is anchored to the spool arrival mtime."""
    spool = str(tmp_path)
    _write_reqs(spool, [(3, 5)])
    monkeypatch.setenv("ZKP2P_FAULTS", "witness:raise:once")
    faults.reset()
    svc = _mk(world)
    d0 = _counter("zkp2p_service_deferred_total")
    assert not any(svc.process_dir(spool).values())
    assert _counter("zkp2p_service_deferred_total") - d0 == 1
    time.sleep(0.05)
    assert svc.process_dir(spool)["done"] == 1
    recs = _records(spool)
    assert [r["state"] for r in recs] == ["deferred", "done"]
    deferred, done = recs
    assert deferred["deferred_reason"].startswith("transient witness failure")
    assert any(s["name"] == "witness" for s in deferred["spans"])
    # cumulative: the terminal's queue wait includes the deferred cycle
    assert done["queue_wait_s"] > deferred["queue_wait_s"]


# ------------------------------------------------------------- meters


def test_takeover_counter_ticks_on_stale_claim_steal(world, tmp_path, monkeypatch):
    monkeypatch.delenv("ZKP2P_FAULTS", raising=False)
    faults.reset()
    spool = str(tmp_path)
    _write_reqs(spool, [(3, 5)])
    claim = os.path.join(spool, "r0.claim")
    with open(claim, "w") as f:
        f.write(json.dumps({"pid": 99999, "ts": time.time() - 3600}))
    os.utime(claim, (time.time() - 3600, time.time() - 3600))  # provably stale
    w0 = _counter("zkp2p_service_takeovers_total", result="won")
    svc = _mk(world, stale_claim_s=5.0)
    assert svc.process_dir(spool)["done"] == 1
    assert _counter("zkp2p_service_takeovers_total", result="won") - w0 == 1


def test_batch_fill_histogram_observes_live_batches(world, tmp_path, monkeypatch):
    monkeypatch.delenv("ZKP2P_FAULTS", raising=False)
    faults.reset()
    h = REGISTRY.histogram("zkp2p_service_batch_fill")
    n0, s0 = h.count, h.sum
    spool = str(tmp_path)
    _write_reqs(spool, [(3, 5), (2, 7), (4, 4)])  # batch_size=2 -> fills 2, 1
    assert _mk(world).process_dir(spool)["done"] == 3
    assert h.count - n0 == 2
    assert h.sum - s0 == 3  # 2 + 1


# ---------------------------------------------------------- timeseries


def test_timeseries_line_schema(world, tmp_path, monkeypatch):
    """Forced sampler tick: the zkp2p_timeseries line carries the queue
    state (arrivals/backlog/claimable/in_flight), rescue counters, and
    the SLO snapshot."""
    monkeypatch.delenv("ZKP2P_FAULTS", raising=False)
    faults.reset()
    spool = str(tmp_path)
    _write_reqs(spool, [(3, 5), (2, 7)])
    svc = _mk(world)
    sampler = TimeseriesSampler(interval_s=3600.0, stale_claim_s=300.0)
    rec = sampler.maybe_sample(spool, svc._sink(spool), force=True)
    assert rec is not None and rec["type"] == "timeseries"
    for key in ("ts", "run_id", "pid", "window_s", "arrivals", "arrival_rate_hz",
                "backlog", "claimable", "in_flight", "batch_fill_last", "counters", "slo"):
        assert key in rec, key
    assert rec["backlog"] == 2 and rec["claimable"] == 2 and rec["in_flight"] == 0
    assert rec["arrivals"] == 2  # both mtimes inside the first window
    assert "attainment" in rec["slo"]
    # not due again until the interval elapses
    assert sampler.maybe_sample(spool, svc._sink(spool)) is None
    # the line landed in the sink and terminal artifacts change the scan
    assert svc.process_dir(spool)["done"] == 2
    rec2 = sampler.maybe_sample(spool, svc._sink(spool), force=True)
    assert rec2["backlog"] == 0 and rec2["batch_fill_last"] == 0
    with open(str(spool).rstrip("/") + ".metrics.jsonl") as f:
        ts_lines = [json.loads(ln) for ln in f if json.loads(ln).get("type") == "timeseries"]
    assert len(ts_lines) == 2


# -------------------------------------------------------- chrome trace


def test_chrome_trace_export_loads_and_is_monotonic(world, tmp_path, monkeypatch):
    """trace_report --chrome-trace: valid JSON, X-event timestamps
    monotonic and non-negative, one pid (this process), one tid per
    request (thread_name metadata maps them), queue_wait + prove slices
    present."""
    monkeypatch.delenv("ZKP2P_FAULTS", raising=False)
    monkeypatch.delenv("ZKP2P_METRICS_SINK", raising=False)
    faults.reset()
    spool = str(tmp_path / "spool")
    os.makedirs(spool)
    _write_reqs(spool, [(3, 5), (2, 7), (4, 4)])
    assert _mk(world).process_dir(spool)["done"] == 3
    sink = spool.rstrip("/") + ".metrics.jsonl"
    out = str(tmp_path / "trace.json")
    proc = subprocess.run(
        [sys.executable, os.path.join(REPO, "tools", "trace_report.py"), sink,
         "--chrome-trace", out],
        capture_output=True, text=True, timeout=60,
    )
    assert proc.returncode == 0, proc.stderr
    with open(out) as f:
        trace = json.load(f)
    events = trace["traceEvents"]
    xs = [e for e in events if e.get("ph") == "X"]
    assert xs, events[:3]
    # monotonic, normalized timestamps
    ts = [e["ts"] for e in xs]
    assert ts == sorted(ts) and min(ts) == 0
    assert all(e["dur"] >= 0 for e in xs)
    # one pid per worker process: this test ran one worker
    assert {e["pid"] for e in xs} == {os.getpid()}
    # one tid per request, named by thread_name metadata
    names = {e["args"]["name"]: (e["pid"], e["tid"])
             for e in events if e.get("ph") == "M" and e["name"] == "thread_name"}
    assert set(names) == {"r0", "r1", "r2"}
    assert len(set(names.values())) == 3  # distinct tids
    by_name = {}
    for e in xs:
        by_name.setdefault(e["name"], set()).add((e["pid"], e["tid"]))
    # queue_wait and prove slices present; each request's own tid
    assert set(by_name) >= {"queue_wait", "witness", "prove", "verify", "emit"}
    assert by_name["queue_wait"] == set(names.values())
    # the terminal instant markers carry the state
    marks = [e for e in events if e.get("ph") == "i"]
    assert len(marks) == 3 and all(m["name"] == "done" for m in marks)
