"""TPU limb-field arithmetic vs the host bignum oracle.

The reference trusts rapidsnark's x86 asm field library; here every
vectorised op is differentially tested against Python ints
(SURVEY.md §7 hard part #1: carry correctness against a bignum oracle).
"""

import random

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from zkp2p_tpu.field.bn254 import P, R
from zkp2p_tpu.field import jfield
from zkp2p_tpu.field.jfield import (

    FQ,
    FQ2,
    FR,
    NUM_LIMBS,
    int_to_limbs,
    lazy_segment_sum_mod,
    limbs_to_int,
    reduce_wide,
)
# XLA-compile-heavy: opt-in via ZKP2P_RUN_SLOW=1 (default suite must stay
# minutes on a 1-core host; the dryrun/bench paths exercise this code too)
pytestmark = pytest.mark.slow


rng = random.Random(1234)


def rand_elems(modulus, n):
    return [rng.randrange(modulus) for _ in range(n)]


def mont_batch(field, xs):
    return jnp.asarray(np.stack([field.to_mont_host(x) for x in xs]))


@pytest.mark.parametrize("field,modulus", [(FQ, P), (FR, R)], ids=["fq", "fr"])
def test_roundtrip_limbs(field, modulus):
    xs = rand_elems(modulus, 8) + [0, 1, modulus - 1]
    for x in xs:
        assert limbs_to_int(int_to_limbs(x)) == x
        assert field.from_mont_host(field.to_mont_host(x)) == x


@pytest.mark.parametrize("field,modulus", [(FQ, P), (FR, R)], ids=["fq", "fr"])
def test_add_sub_neg_mul_batch(field, modulus):
    n = 32
    xs = rand_elems(modulus, n - 3) + [0, 1, modulus - 1]
    ys = rand_elems(modulus, n - 3) + [modulus - 1, 0, 1]
    a = mont_batch(field, xs)
    b = mont_batch(field, ys)

    out_add = jax.jit(field.add)(a, b)
    out_sub = jax.jit(field.sub)(a, b)
    out_neg = jax.jit(field.neg)(a)
    out_mul = jax.jit(field.mul)(a, b)
    out_sq = jax.jit(field.square)(a)

    for i, (x, y) in enumerate(zip(xs, ys)):
        assert field.from_mont_host(np.asarray(out_add)[i]) == (x + y) % modulus
        assert field.from_mont_host(np.asarray(out_sub)[i]) == (x - y) % modulus
        assert field.from_mont_host(np.asarray(out_neg)[i]) == (-x) % modulus
        assert field.from_mont_host(np.asarray(out_mul)[i]) == (x * y) % modulus
        assert field.from_mont_host(np.asarray(out_sq)[i]) == (x * x) % modulus


@pytest.mark.parametrize("field,modulus", [(FQ, P), (FR, R)], ids=["fq", "fr"])
def test_mont_conversions_on_device(field, modulus):
    xs = rand_elems(modulus, 6) + [0, 1]
    std = jnp.asarray(np.stack([int_to_limbs(x) for x in xs]))
    m = jax.jit(field.to_mont)(std)
    back = jax.jit(field.from_mont)(m)
    for i, x in enumerate(xs):
        assert field.from_mont_host(np.asarray(m)[i]) == x
        assert limbs_to_int(np.asarray(back)[i]) == x


def test_inv_fq():
    xs = rand_elems(P, 4) + [1, P - 1]
    a = mont_batch(FQ, xs)
    out = jax.jit(FQ.inv)(a)
    for i, x in enumerate(xs):
        assert FQ.from_mont_host(np.asarray(out)[i]) == pow(x, P - 2, P)


def test_fq2_mul_matches_host_tower():
    from zkp2p_tpu.field.tower import Fq2

    n = 8
    elems = [(rng.randrange(P), rng.randrange(P)) for _ in range(n)]
    others = [(rng.randrange(P), rng.randrange(P)) for _ in range(n)]
    a = jnp.asarray(
        np.stack([np.stack([FQ.to_mont_host(c0), FQ.to_mont_host(c1)]) for c0, c1 in elems])
    )
    b = jnp.asarray(
        np.stack([np.stack([FQ.to_mont_host(c0), FQ.to_mont_host(c1)]) for c0, c1 in others])
    )
    out = jax.jit(FQ2.mul)(a, b)
    for i in range(n):
        want = Fq2(*elems[i]) * Fq2(*others[i])
        got0 = FQ.from_mont_host(np.asarray(out)[i, 0])
        got1 = FQ.from_mont_host(np.asarray(out)[i, 1])
        assert (got0, got1) == (want.c0, want.c1)


def test_reduce_wide():
    for nlimbs in (16, 18, 24, 31):
        xs = [rng.randrange(1 << (16 * nlimbs)) for _ in range(4)]
        wide = jnp.asarray(np.stack([int_to_limbs(x, nlimbs) for x in xs]))
        out = jax.jit(lambda w: reduce_wide(FR, w))(wide)
        for i, x in enumerate(xs):
            assert limbs_to_int(np.asarray(out)[i]) == x % R


def test_lazy_segment_sum_mod():
    n, segs = 64, 5
    xs = rand_elems(R, n)
    ids = [rng.randrange(segs) for _ in range(n)]
    vals = jnp.asarray(np.stack([int_to_limbs(x) for x in xs]))
    out = jax.jit(
        lambda v, s: lazy_segment_sum_mod(FR, v, s, segs)
    )(vals, jnp.asarray(ids, dtype=jnp.int32))
    for s in range(segs):
        want = sum(x for x, i in zip(xs, ids) if i == s) % R
        assert limbs_to_int(np.asarray(out)[s]) == want
