"""Fixed-base precomputed-window MSM (csrc g1_precomp_build /
g1_msm_pippenger_fixed / _fixed_multi + prover.precomp).

The parity oracle is the VARIABLE-BASE driver (itself diffed against
the pure-python host curve in test_msm_native_edge): the fixed tier's
result must be byte-identical to g1_msm_pippenger_mt for the same
(bases, scalars) across {batch-affine on/off} x {single, multi S=4,
ragged}, zero/infinity columns included.  One level up, the proof
contract: ZKP2P_MSM_PRECOMP=1 emits the exact proof bytes of the =0 arm
across {GLV on/off} x {single prove, batch prove} — the fixed tier
bypasses GLV, so parity across the GLV arms is what pins "same group
element, same canonical bytes".

The persistence layer is covered tier-1-resident (the Makefile
`precomp-cache` smoke): build -> persist -> reload -> identical proof,
warm start skips the build (native precomp_build_ns stat unchanged),
and a corrupt or foreign cache file is rejected by the level-0
integrity check and rebuilt.
"""

import ctypes
import os
import random

import numpy as np
import pytest

from zkp2p_tpu.curve.host import G1_GENERATOR, g1_msm, g1_mul
from zkp2p_tpu.field.bn254 import P, R
from zkp2p_tpu.native import lib as native
from zkp2p_tpu.native.lib import _pack_affine, _scalars_to_u64

pytestmark = pytest.mark.skipif(native.get_lib() is None, reason="native toolchain unavailable")

rng = random.Random(29)
_u64p = ctypes.POINTER(ctypes.c_uint64)


def _p(a: np.ndarray):
    return a.ctypes.data_as(_u64p)


def _lib():
    from zkp2p_tpu.prover.native_prove import _lib as pl

    return pl()


def _mont_bases(pts) -> np.ndarray:
    lib = _lib()
    bases = _pack_affine(pts)
    bm = np.zeros_like(bases)
    lib.fp_to_mont.argtypes = [_u64p, _u64p, ctypes.c_int]
    lib.fp_to_mont(_p(bases), _p(bm), 2 * len(pts))
    return bm


def _build_tables(bm: np.ndarray, c: int, q: int, levels: int):
    lib = _lib()
    n = bm.shape[0]
    table = np.zeros((levels * n, 8), dtype=np.uint64)
    lib.g1_precomp_build(_p(bm), n, c, q, levels, 2, _p(table))
    t52 = np.zeros((levels * n, 10), dtype=np.uint64)
    p52 = _p(t52) if lib.g1_precomp_to52(_p(table), levels * n, _p(t52)) else None
    return table, t52, p52


def _fixed(table, p52, cols, n, c, q, levels, threads=1) -> np.ndarray:
    lib = _lib()
    S = len(cols)
    sc = np.zeros((S, n, 4), dtype=np.uint64)
    for s, col in enumerate(cols):
        if col:
            sc[s, : len(col)] = _scalars_to_u64(col)
    sc = np.ascontiguousarray(sc)
    out = np.zeros((S, 8), dtype=np.uint64)
    if S == 1:
        lib.g1_msm_pippenger_fixed(
            _p(table), p52, _p(sc), n, n, levels, c, q, threads, _p(out[0])
        )
    else:
        lib.g1_msm_pippenger_fixed_multi(
            _p(table), p52, _p(sc), n, n, S, levels, c, q, threads, _p(out)
        )
    return out


def _oracle(bm, cols, c=14, threads=1) -> np.ndarray:
    lib = _lib()
    n = bm.shape[0]
    out = np.zeros((len(cols), 8), dtype=np.uint64)
    for s, col in enumerate(cols):
        sc = np.zeros((n, 4), dtype=np.uint64)
        if col:
            sc[: len(col)] = _scalars_to_u64(col)
        sc = np.ascontiguousarray(sc)
        lib.g1_msm_pippenger_mt(_p(bm), _p(sc), n, c, threads, _p(out[s]))
    return out


def _bases_and_cols(n=300, S=4):
    """Infinity holes, duplicate/negated bases, zero / +-1 / full-width
    scalars, same-bucket doubling + cancellation pairs, a zero column —
    the test_msm_multi fixture shapes, reused for the fixed tier."""
    pts = [g1_mul(G1_GENERATOR, rng.randrange(1, 1 << 28)) for _ in range(n)]
    pts[3] = None
    pts[n - 1] = None
    pts[10] = pts[11]
    x, y = pts[12]
    pts[13] = (x, P - y)
    cols = []
    for _ in range(S):
        col = [rng.randrange(1 << 14, 1 << 20) for _ in range(n)]
        col[0] = 0
        col[1] = 1
        col[2] = R - 1
        col[5] = rng.randrange(R)
        col[10] = col[11]
        col[12] = col[13]
        cols.append(col)
    cols[S // 2] = [0] * n
    return pts, cols


@pytest.fixture
def both_arms(monkeypatch):
    def runner(check):
        for arm in ("1", "0"):
            monkeypatch.setenv("ZKP2P_MSM_BATCH_AFFINE", arm)
            check(arm)

    yield runner


GEOMS = ((16, 2, 8), (8, 4, 8), (6, 43, 1))  # deep, mid, degenerate L=1


def test_fixed_vs_variable_base_oracle(both_arms):
    pts, cols = _bases_and_cols()
    bm = _mont_bases(pts)
    n = bm.shape[0]

    def check(arm):
        want = _oracle(bm, cols[:1])
        for c, q, levels in GEOMS:
            table, t52, p52 = _build_tables(bm, c, q, levels)
            for threads in (1, 2):
                got = _fixed(table, p52, cols[:1], n, c, q, levels, threads)
                assert np.array_equal(got, want), (arm, c, q, levels, threads)
            # scalar-path arm of the same tables: mont256 reads, no 52-limb
            got = _fixed(table, None, cols[:1], n, c, q, levels)
            assert np.array_equal(got, want), (arm, c, q, levels, "no52")

    both_arms(check)


def test_fixed_multi_vs_sequential(both_arms):
    pts, cols = _bases_and_cols()
    bm = _mont_bases(pts)
    n = bm.shape[0]
    c, q, levels = 10, 3, 9
    table, t52, p52 = _build_tables(bm, c, q, levels)

    def check(arm):
        want = _oracle(bm, cols)
        for threads in (1, 2):
            got = _fixed(table, p52, cols, n, c, q, levels, threads)
            assert np.array_equal(got, want), (arm, threads)
        # ragged: short + empty columns zero-pad like the multi driver
        ragged = [cols[0], cols[1][: n // 3], []]
        want = _oracle(bm, ragged)
        got = _fixed(table, p52, ragged, n, c, q, levels)
        assert np.array_equal(got, want), arm

    both_arms(check)


def test_fixed_zero_and_infinity_only(both_arms):
    pts = [g1_mul(G1_GENERATOR, rng.randrange(1, 1 << 24)) for _ in range(48)]
    holes = [None] * 48
    c, q, levels = 8, 4, 8

    def check(arm):
        table, t52, p52 = _build_tables(_mont_bases(pts), c, q, levels)
        out = _fixed(table, p52, [[0] * 48], 48, c, q, levels)
        assert not out.any(), arm
        table, t52, p52 = _build_tables(_mont_bases(holes), c, q, levels)
        out = _fixed(table, p52, [[rng.randrange(R) for _ in range(48)]] * 2, 48, c, q, levels)
        assert not out.any(), arm

    both_arms(check)


def test_fixed_vs_host_oracle():
    """Ground truth: the pure-python host curve, small scalars."""
    n = 64
    pts = [g1_mul(G1_GENERATOR, rng.randrange(1, 1 << 22)) for _ in range(n)]
    pts[5] = None
    scalars = [rng.randrange(1 << 18) for _ in range(n)]
    want = g1_msm(pts, scalars)
    bm = _mont_bases(pts)
    c, q, levels = 6, 7, 7
    table, t52, p52 = _build_tables(bm, c, q, levels)
    out = _fixed(table, p52, [scalars], n, c, q, levels)
    x = int.from_bytes(out[0, :4].tobytes(), "little")
    y = int.from_bytes(out[0, 4:].tobytes(), "little")
    assert (None if x == 0 and y == 0 else (x, y)) == want


def test_fixed_stats_counters():
    from zkp2p_tpu.native.lib import stats_reset, stats_snapshot

    pts = [g1_mul(G1_GENERATOR, rng.randrange(1, 1 << 24)) for _ in range(64)]
    bm = _mont_bases(pts)
    assert stats_reset()
    table, t52, p52 = _build_tables(bm, 8, 4, 8)
    snap = stats_snapshot()
    assert snap["precomp_build_ns"] > 0
    assert snap["precomp_table_bytes"] == 8 * 64 * 64
    _fixed(table, p52, [[rng.randrange(R) for _ in range(64)]], 64, 8, 4, 8)
    snap = stats_snapshot()
    assert snap["msm_fixed_calls"] == 1
    assert snap["msm_fixed_prep_ns"] > 0


# ------------------------------------------------------------ geometry


def test_geometry_resolution_and_budget():
    from zkp2p_tpu.prover.precomp import _resolve_geometry, fixed_nwin

    for c in range(4, 22):
        W = fixed_nwin(c)
        assert W * c >= 255
        assert (W - 1) * c < 255 or (254 + c - 1) // c == W
    # unconstrained: depth 8 at the bench shape -> c=16, q=2, L=8
    assert _resolve_geometry(1 << 19, 8, 1 << 62) == (16, 2, 8)
    # depth 1 degrades to a single level (q = W)
    c, q, levels = _resolve_geometry(1 << 19, 1, 1 << 62)
    assert levels == 1 and q == fixed_nwin(c)
    # budget squeeze: shallower tables, cover bound levels*q >= W kept
    c, q, levels = _resolve_geometry(1 << 19, 8, 300 << 20)
    assert levels * q >= fixed_nwin(c)
    assert (levels << 19) * 144 <= 300 << 20
    assert levels < 8
    # impossible budget: family skipped
    assert _resolve_geometry(1 << 19, 8, 1 << 20) is None


# ------------------------------------------------- prove-level parity


def _toy_circuit():
    from zkp2p_tpu.snark.r1cs import LC, ConstraintSystem

    cs = ConstraintSystem("precomp-toy")
    out = cs.new_public("out")
    x = cs.new_wire("x")
    y = cs.new_wire("y")
    z = cs.new_wire("z")
    cs.enforce(LC.of(x), LC.of(y), LC.of(z), "mul")
    cs.enforce(LC.of(z), LC.of(z), LC.of(out), "sq")
    cs.compute(z, lambda a, b: a * b % R, [x, y])
    return cs, (out, x, y, z)


@pytest.fixture
def toy_dpk():
    from zkp2p_tpu.prover import device_pk
    from zkp2p_tpu.snark.groth16 import setup

    cs, (out, x, y, z) = _toy_circuit()
    pk, vk = setup(cs)
    return cs, (x, y), device_pk(pk, cs), vk


@pytest.fixture(autouse=True)
def _fresh_precomp(monkeypatch, tmp_path):
    """Every test gets an isolated table cache + cleared memo so proves
    here never litter (or trust) the shared .bench_cache."""
    from zkp2p_tpu.prover import precomp

    monkeypatch.setenv("ZKP2P_MSM_PRECOMP_CACHE", str(tmp_path / "precomp"))
    precomp.reset()
    yield
    precomp.reset()


def test_prove_parity_across_arms(monkeypatch, toy_dpk):
    """Precomp on == off, byte for byte, across {GLV on/off} x {single,
    batch S=3 incl. multi-column} — and the proof verifies."""
    from zkp2p_tpu.prover.native_prove import prove_native, prove_native_batch
    from zkp2p_tpu.snark.groth16 import verify

    cs, (x, y), dpk, vk = toy_dpk
    wits = [
        cs.witness([(3 * 5) ** 2 % R], {x: 3, y: 5}),
        cs.witness([(3 * 10) ** 2 % R], {x: 3, y: 10}),
        cs.witness([(7 * 11) ** 2 % R], {x: 7, y: 11}),
    ]
    rs = [rng.randrange(1, R) for _ in wits]
    ss = [rng.randrange(1, R) for _ in wits]
    for glv in ("0", "1"):
        monkeypatch.setenv("ZKP2P_MSM_GLV", glv)
        monkeypatch.setenv("ZKP2P_MSM_PRECOMP", "0")
        base = [prove_native(dpk, w, r=r, s=s) for w, r, s in zip(wits, rs, ss)]
        monkeypatch.setenv("ZKP2P_MSM_PRECOMP", "1")
        got = [prove_native(dpk, w, r=r, s=s) for w, r, s in zip(wits, rs, ss)]
        assert got == base, f"glv={glv} single"
        assert prove_native_batch(dpk, wits, rs=rs, ss=ss) == base, f"glv={glv} batch"
    assert verify(vk, base[2], [(7 * 11) ** 2 % R])


def test_partial_families_fall_through(monkeypatch, toy_dpk):
    """A families subset (h off the tables) mixes fixed + variable-base
    paths in one prove and still matches the oracle byte-for-byte."""
    from zkp2p_tpu.prover.native_prove import prove_native
    from zkp2p_tpu.prover.precomp import precomputed_for

    cs, (x, y), dpk, _vk = toy_dpk
    w = cs.witness([225], {x: 3, y: 5})
    monkeypatch.setenv("ZKP2P_MSM_PRECOMP", "0")
    want = prove_native(dpk, w, r=11, s=13)
    monkeypatch.setenv("ZKP2P_MSM_PRECOMP", "1")
    monkeypatch.setenv("ZKP2P_MSM_PRECOMP_FAMILIES", "a,c")
    assert prove_native(dpk, w, r=11, s=13) == want
    pk = precomputed_for(dpk)
    assert set(pk.families) == {"a", "c"}
    assert pk.skipped.get("h") == "config" and pk.skipped.get("b1") == "config"


# ----------------------------------------------- cache build + reload
# (the tier-1-resident smoke behind `make precomp-cache`)


def test_cache_roundtrip_and_warm_start(monkeypatch, toy_dpk, tmp_path):
    """build -> persist -> reload -> identical proof; the warm start
    runs ZERO native table builds (precomp_build_ns stat unchanged) and
    reports source=cache in the manifest."""
    from zkp2p_tpu.native.lib import stats_reset, stats_snapshot
    from zkp2p_tpu.prover import precomp
    from zkp2p_tpu.prover.native_prove import prove_native

    cs, (x, y), dpk, _vk = toy_dpk
    w = cs.witness([225], {x: 3, y: 5})
    monkeypatch.setenv("ZKP2P_MSM_PRECOMP_PERSIST_MIN", "1")
    monkeypatch.setenv("ZKP2P_MSM_PRECOMP", "1")
    cold = prove_native(dpk, w, r=5, s=7)
    man = precomp.precomp_manifest()
    assert man and all(f["source"] == "built" for f in man["families"].values())
    cache_dir = os.environ["ZKP2P_MSM_PRECOMP_CACHE"]
    # the cache dir is shared with the matvec segment plans
    # (prover.matvec_plan) — count only the precomp tables here
    files = sorted(f for f in os.listdir(cache_dir) if f.startswith("precomp_g1_") and f.endswith(".npy"))
    assert len(files) == len(man["families"])
    assert man["total_bytes"] > 0

    # warm start: drop the in-RAM memo, prove again — tables must come
    # from disk (source=cache) with no build work in the C runtime
    precomp.reset()
    assert stats_reset()
    warm = prove_native(dpk, w, r=5, s=7)
    assert warm == cold
    snap = stats_snapshot()
    assert snap["precomp_build_ns"] == 0, "warm start re-ran the table build"
    man = precomp.precomp_manifest()
    assert all(f["source"] == "cache" for f in man["families"].values())
    assert sorted(f for f in os.listdir(cache_dir) if f.startswith("precomp_g1_") and f.endswith(".npy")) == files


@pytest.mark.parametrize("level", [0, 1])
def test_stale_cache_rejected(monkeypatch, toy_dpk, level):
    """A corrupt (or foreign-key) cache file fails the integrity check
    and rebuilds instead of proving garbage — whether the flipped bit is
    in the verbatim level 0 or in a HIGHER doubled level (caught by the
    sampled host-curve chain walk); the rebuilt file replaces it and the
    proof stays byte-identical."""
    from zkp2p_tpu.prover import precomp
    from zkp2p_tpu.prover.native_prove import prove_native

    cs, (x, y), dpk, _vk = toy_dpk
    w = cs.witness([225], {x: 3, y: 5})
    monkeypatch.setenv("ZKP2P_MSM_PRECOMP_PERSIST_MIN", "1")
    monkeypatch.setenv("ZKP2P_MSM_PRECOMP", "1")
    cold = prove_native(dpk, w, r=5, s=7)
    man = precomp.precomp_manifest()
    cache_dir = os.environ["ZKP2P_MSM_PRECOMP_CACHE"]
    for name in os.listdir(cache_dir):
        if not name.startswith("precomp_g1_") or not name.endswith(".npy"):
            continue  # matvec segment plans + flock sidecars share this dir
        path = os.path.join(cache_dir, name)
        t = np.load(path)
        fam = name.split("_")[2]
        n = man["families"][fam]["n"]
        t[level * n] ^= np.uint64(0xDEAD)  # flipped bits: torn/rotted file
        with open(path, "wb") as f:
            np.save(f, t)
    precomp.reset()
    assert prove_native(dpk, w, r=5, s=7) == cold
    man = precomp.precomp_manifest()
    assert all(f["source"] == "built" for f in man["families"].values()), (
        "tampered cache was trusted"
    )


def test_key_hash_partitions_cache(monkeypatch, toy_dpk):
    """A different key resolves to different cache files — the key hash
    in the filename IS the invalidation mechanism."""
    from zkp2p_tpu.prover import device_pk, precomp
    from zkp2p_tpu.prover.native_prove import prove_native
    from zkp2p_tpu.snark.groth16 import setup

    cs, (x, y), dpk, _vk = toy_dpk
    w = cs.witness([225], {x: 3, y: 5})
    monkeypatch.setenv("ZKP2P_MSM_PRECOMP_PERSIST_MIN", "1")
    monkeypatch.setenv("ZKP2P_MSM_PRECOMP", "1")
    prove_native(dpk, w, r=5, s=7)
    cache_dir = os.environ["ZKP2P_MSM_PRECOMP_CACHE"]
    first = {f for f in os.listdir(cache_dir) if f.startswith("precomp_g1_") and f.endswith(".npy")}
    # a different setup seed = different toxic waste = different bases
    cs2, (out2, x2, y2, z2) = _toy_circuit()
    pk2, _ = setup(cs2, seed="zkp2p-tpu-dev-precomp-b")
    dpk2 = device_pk(pk2, cs2)
    prove_native(dpk2, cs2.witness([225], {x2: 3, y2: 5}), r=5, s=7)
    second = {f for f in os.listdir(cache_dir) if f.startswith("precomp_g1_") and f.endswith(".npy")}
    assert first < second and len(second) == 2 * len(first)


def test_witness_reduce_native_matches_python():
    """The native fr_reduce_batch path (docs/NEXT.md lever 3) == the
    Python `w % R` loop, including >= r values and the big-int
    fallback for negatives."""
    from zkp2p_tpu.prover.native_prove import _lib, _witness_std_u64

    lib = _lib()
    vals = [0, 1, R - 1, R, R + 12345, 2 * R + 7, (1 << 256) - 1, 5 * R - 1,
            rng.randrange(1 << 256), rng.randrange(R)]
    want = np.ascontiguousarray(_scalars_to_u64([v % R for v in vals]))
    got = _witness_std_u64(lib, vals)
    assert np.array_equal(got, want)
    # negative values take the exact python fallback
    got = _witness_std_u64(lib, [-1, -R, 7])
    want = np.ascontiguousarray(_scalars_to_u64([(-1) % R, (-R) % R, 7]))
    assert np.array_equal(got, want)
