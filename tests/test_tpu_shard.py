"""The sharded TPU arm (`make tpu-shard-smoke`; docs/TPU.md).

Tier-1 resident: pjit batch-axis prove parity on the 8-virtual-device
CPU mesh (toy circuit, byte-identical to the host oracle under pinned
(r, s)), the `tpu_shard` gate grammar + fallback arming, the
ZKP2P_TPU_* knob registry, the warm-start compile-cache round-trip
(>=10x second-run compile span, asserted via the jax.monitoring
backend_compile listener in subprocess pairs), and the heterogeneous
worker-tier routing units + the mixed-tier toy fleet A/B under the
chaos zero-lost/zero-duplicate invariant.

The parity tests dispatch REAL pod-mesh executables: cold, one
shard_map MSM compiles for minutes on a 1-core host, so they ride the
persistent .jax_cache (tests/conftest.py points every test at it) and
SKIP with a pointer at `make warm-cache` when the pod entries are
absent — the budget rule that keeps tier-1 minutes, not hours.  The
per-device bucket partial-sum check lives in the slow tier
(ZKP2P_RUN_SLOW=1) for the same reason: its diagnostic program is a
different executable than the prover's, so it can never be pre-warmed
by a production warm-cache run.
"""

import importlib.util
import json
import os
import subprocess
import sys
import time

import pytest

from zkp2p_tpu.field.bn254 import R
from zkp2p_tpu.pipeline.sched import (
    AmortModel,
    BatchController,
    DEFAULT_SHARDED_AMORT_POINTS,
    SchedRequest,
    normalize_tier,
    worker_tier_arm,
)
from zkp2p_tpu.snark.r1cs import LC, ConstraintSystem

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
CHAOS = os.path.join(REPO, "tools", "chaos.py")


# ------------------------------------------------------------ mesh grammar


def test_mesh_spec_grammar():
    from zkp2p_tpu.prover.groth16_tpu import _parse_mesh_spec

    assert _parse_mesh_spec("", 8) == (1, 8)  # auto: all devices on the shard axis
    assert _parse_mesh_spec("4", 8) == (1, 4)  # bare int = 1xN
    assert _parse_mesh_spec("2x4", 8) == (2, 4)
    assert _parse_mesh_spec(" 2X4 ", 8) == (2, 4)  # case/space tolerant
    # malformed or non-positive fails CLOSED (None -> vmap arm)
    assert _parse_mesh_spec("0x4", 8) is None
    assert _parse_mesh_spec("2x-1", 8) is None
    assert _parse_mesh_spec("ax2", 8) is None
    assert _parse_mesh_spec("2x", 8) is None


def test_shard_mesh_gate_grammar_and_digest(monkeypatch):
    from zkp2p_tpu.prover import groth16_tpu as G
    from zkp2p_tpu.utils.audit import execution_digest, gate_arms

    monkeypatch.delenv("ZKP2P_TPU_SHARD", raising=False)
    monkeypatch.delenv("ZKP2P_TPU_MESH", raising=False)
    assert G._shard_mesh() is None
    assert gate_arms()["tpu_shard"] == "off"
    d_off = execution_digest()

    # anything but the literal "on" fails closed
    monkeypatch.setenv("ZKP2P_TPU_SHARD", "yes")
    assert G._shard_mesh() is None and gate_arms()["tpu_shard"] == "off"

    # on + unsatisfiable/malformed mesh: an on-record disarm
    monkeypatch.setenv("ZKP2P_TPU_SHARD", "on")
    monkeypatch.setenv("ZKP2P_TPU_MESH", "junk")
    assert G._shard_mesh() is None and gate_arms()["tpu_shard"] == "off"
    monkeypatch.setenv("ZKP2P_TPU_MESH", "4x4")  # 16 > the 8 virtual devices
    assert G._shard_mesh() is None and gate_arms()["tpu_shard"] == "off"

    monkeypatch.setenv("ZKP2P_TPU_MESH", "2x4")
    mesh = G._shard_mesh()
    assert mesh is not None
    assert dict(mesh.shape) == {"batch": 2, "shard": 4}
    assert gate_arms()["tpu_shard"] == "2x4"
    # a sharded prove must never share a digest with the vmap arm
    assert execution_digest() != d_off
    # mesh instances are memoised per shape (the shard_map executable
    # cache keys on the instance)
    assert G._shard_mesh() is mesh

    # restore the off arm for later tests in this process
    monkeypatch.setenv("ZKP2P_TPU_SHARD", "off")
    assert G._shard_mesh() is None


# --------------------------------------------------- arm selection (stubbed)


def build_toy():
    cs = ConstraintSystem("toy")
    out = cs.new_public("out")
    x = cs.new_wire("x")
    y = cs.new_wire("y")
    z = cs.new_wire("z")
    cs.enforce(LC.of(x), LC.of(y), LC.of(z), "mul")
    cs.enforce(LC.of(z), LC.of(z), LC.of(out), "sq")
    cs.compute(z, lambda a, b: a * b % R, [x, y])
    return cs, out, x, y


def _toy_wits(cs, x, y, cases):
    wits, pubs = [], []
    for a, b in cases:
        o = pow(a * b % R, 2, R)
        wits.append(cs.witness([o], {x: a, y: b}))
        pubs.append([o])
    return wits, pubs


@pytest.fixture(scope="module")
def toy_keys():
    from zkp2p_tpu.prover import device_pk
    from zkp2p_tpu.snark.groth16 import setup

    cs, out, x, y = build_toy()
    pk, vk = setup(cs)
    return cs, pk, vk, device_pk(pk, cs), x, y


class _ArmTaken(Exception):
    def __init__(self, arm):
        self.arm = arm


def test_batch_arm_selection_and_fallback(toy_keys, monkeypatch):
    """The per-call arm decision WITHOUT paying a compile: both prove
    arms stubbed to raise, so the test observes which one prove_tpu_batch
    dispatched and which `tpu_shard` arm it recorded."""
    from zkp2p_tpu.prover import groth16_tpu as G
    from zkp2p_tpu.utils.audit import gate_arms

    cs, _pk, _vk, dpk, x, y = toy_keys
    wits, _ = _toy_wits(cs, x, y, [(3, 5), (2, 7), (10, 11), (1, 1)])

    monkeypatch.setattr(
        G, "_prove_batch_sharded", lambda *a, **k: (_ for _ in ()).throw(_ArmTaken("sharded"))
    )
    monkeypatch.setattr(
        G, "_prove_device", lambda *a, **k: (_ for _ in ()).throw(_ArmTaken("vmap"))
    )

    def arm_for(n_wits, shard, mesh_spec):
        monkeypatch.setenv("ZKP2P_TPU_SHARD", shard)
        monkeypatch.setenv("ZKP2P_TPU_MESH", mesh_spec)
        with pytest.raises(_ArmTaken) as e:
            G.prove_tpu_batch(dpk, wits[:n_wits])
        return e.value.arm, gate_arms()["tpu_shard"]

    # knob off: the vmap arm, digest-visible as "off"
    assert arm_for(4, "off", "2x4") == ("vmap", "off")
    # on + divisible batch: the sharded arm with the resolved shape
    assert arm_for(4, "on", "2x4") == ("sharded", "2x4")
    assert arm_for(3, "on", "1x4") == ("sharded", "1x4")  # B=1 divides anything
    # on + indivisible batch (3 % 2): fallback recorded, vmap dispatched
    assert arm_for(3, "on", "2x4") == ("vmap", "fallback")


# ----------------------------------------------------------- byte parity

_POD_CACHE_HINTS = ("jit_local", "jit_msm_pod", "shard_map")


def _pod_cache_ready() -> bool:
    """True when the persistent cache holds the pod-mesh executables (a
    `make warm-cache` ran on this checkout) — the gate that keeps the
    parity tests out of a COLD tier-1 run, where one shard_map MSM
    compiles for minutes on a 1-core host."""
    if os.environ.get("ZKP2P_NO_CACHE") == "1":
        return False
    from zkp2p_tpu.utils.jaxcfg import cache_dir

    try:
        names = os.listdir(cache_dir())
    except OSError:
        return False
    return any(n.startswith(_POD_CACHE_HINTS) and n.endswith("-cache") for n in names)


needs_warm_cache = pytest.mark.skipif(
    not _pod_cache_ready(),
    reason="pod-mesh executables not in the persistent cache — run `make warm-cache` "
    "(cold shard_map compiles take minutes; docs/TPU.md §warm-start)",
)


class _PinnedSecrets:
    """Deterministic stand-in for the secrets module: prove_tpu_batch
    draws r, s per proof as 1 + randbelow(R - 1) -> the pinned sequence
    1000, 1001, 1002, ... so the host oracle can replay them."""

    def __init__(self, start=1000):
        self._it = iter(range(start, start + 10_000))

    def randbelow(self, _n):
        return next(self._it) - 1


@needs_warm_cache
def test_sharded_batch_matches_host_oracle(toy_keys, monkeypatch):
    """THE acceptance: ZKP2P_TPU_SHARD=on on the 2x4 virtual pod mesh,
    batch of 4 -> every proof byte-identical to prove_host under the
    same (witness, r, s), and pairing-verified.  Covers the batch case
    AND the single case (a 1-witness call pads to the mesh batch width
    is NOT done — B=2 groups need 2+ witnesses, so single rides a
    (1x4) mesh)."""
    from zkp2p_tpu.prover import groth16_tpu as G
    from zkp2p_tpu.snark.groth16 import prove_host, verify
    from zkp2p_tpu.utils.audit import gate_arms

    cs, pk, vk, dpk, x, y = toy_keys
    cases = [(3, 5), (2, 7), (10, 11), (1, 1)]
    wits, pubs = _toy_wits(cs, x, y, cases)

    monkeypatch.setenv("ZKP2P_TPU_SHARD", "on")
    monkeypatch.setenv("ZKP2P_TPU_MESH", "2x4")
    monkeypatch.setattr(G, "secrets", _PinnedSecrets())
    proofs = G.prove_tpu_batch(dpk, wits)
    assert gate_arms()["tpu_shard"] == "2x4"
    for i, (proof, pub) in enumerate(zip(proofs, pubs)):
        r, s = 1000 + 2 * i, 1001 + 2 * i
        assert proof == prove_host(pk, cs, wits[i], r=r, s=s), f"proof {i} != oracle"
        assert verify(vk, proof, pub)


@pytest.mark.slow
@needs_warm_cache
def test_sharded_single_matches_host_oracle(toy_keys, monkeypatch):
    """Single-witness parity on a base-axis-only (1x4) mesh.

    Slow tier: ~217 s even warm-cache on the 1-core host (virtual-device
    execution), and the tier-1 sharded-parity guarantee is carried by
    test_sharded_batch_matches_host_oracle above — this adds only the
    (1x4) mesh shape.  Runs under `make test-slow`."""
    from zkp2p_tpu.prover import groth16_tpu as G
    from zkp2p_tpu.snark.groth16 import prove_host, verify
    from zkp2p_tpu.utils.audit import gate_arms

    cs, pk, vk, dpk, x, y = toy_keys
    wits, pubs = _toy_wits(cs, x, y, [(6, 7)])
    monkeypatch.setenv("ZKP2P_TPU_SHARD", "on")
    monkeypatch.setenv("ZKP2P_TPU_MESH", "1x4")
    monkeypatch.setattr(G, "secrets", _PinnedSecrets())
    (proof,) = G.prove_tpu_batch(dpk, wits)
    assert gate_arms()["tpu_shard"] == "1x4"
    assert proof == prove_host(pk, cs, wits[0], r=1000, s=1001)
    assert verify(vk, proof, pubs[0])


@pytest.mark.slow
@pytest.mark.xslow
def test_per_device_bucket_partials_match_unsharded():
    """The allreduce layout claim (docs/TPU.md): each shard-axis
    device's bucket accumulation covers ONLY its base slice, and the
    psum fold is a pure group-op combine — so per-slice host MSMs over
    the same slicing, group-added, must equal both the unsharded host
    oracle and the pod-mesh device result.  Slow tier with the rest of
    the mesh tests (XLA-compile-heavy on a 1-core host)."""
    import jax.numpy as jnp
    import numpy as np

    from zkp2p_tpu.curve.host import G1_GENERATOR, g1_add, g1_msm, g1_mul
    from zkp2p_tpu.curve.jcurve import G1J, g1_jac_to_host, g1_to_affine_arrays
    from zkp2p_tpu.field.jfield import int_to_limbs
    from zkp2p_tpu.ops import msm as jmsm
    from zkp2p_tpu.parallel.mesh import make_pod_mesh, msm_pod_batched, pad_to_multiple

    n_ici, lanes, window = 4, 2, 4
    rng = np.random.default_rng(11)
    n = 16  # a multiple of n_ici * lanes: slice boundaries == device slices
    pts = [g1_mul(G1_GENERATOR, int(k)) for k in rng.integers(1, 2**62, n)]
    batch_scalars = [[int(s) for s in rng.integers(1, 2**62, n)] for _ in range(2)]

    # per-device partial sums, host-computed over each device's base
    # slice, folded with plain group addition
    per = n // n_ici
    for row in batch_scalars:
        partials = [
            g1_msm(pts[d * per : (d + 1) * per], row[d * per : (d + 1) * per])
            for d in range(n_ici)
        ]
        folded = None
        for p in partials:
            folded = g1_add(folded, p) if folded is not None else p
        assert folded == g1_msm(pts, row)

    # the pod-mesh executable agrees with the same oracle
    mesh = make_pod_mesh(2, n_ici)
    planes = jnp.stack(
        [
            jmsm.digit_planes_from_limbs(
                jnp.asarray(np.stack([int_to_limbs(s) for s in row])), window
            )
            for row in batch_scalars
        ]
    )
    bases, _ = pad_to_multiple(g1_to_affine_arrays(pts), planes[0], n_ici * lanes)
    acc = msm_pod_batched(G1J, bases, planes, mesh, lanes=lanes, window=window)
    got = g1_jac_to_host(acc)
    for i, row in enumerate(batch_scalars):
        assert got[i] == g1_msm(pts, row), f"batch element {i}"


# ------------------------------------------------- warm-start compile cache

_PROBE = r"""
import os, sys, time
os.environ["JAX_PLATFORMS"] = "cpu"
os.environ.pop("PALLAS_AXON_POOL_IPS", None)
os.environ.pop("ZKP2P_NO_CACHE", None)
os.environ["ZKP2P_JAX_CACHE_DIR"] = sys.argv[1]
sys.path.insert(0, sys.argv[2])
from zkp2p_tpu.utils.jaxcfg import cache_dir, enable_cache
enable_cache(min_compile_s=0.0)
assert cache_dir().startswith(sys.argv[1])  # the knob steers the root
import jax, jax.numpy as jnp
comp = []
jax.monitoring.register_event_duration_secs_listener(
    lambda name, dur, **kw: comp.append(dur) if name.endswith("backend_compile_duration") else None)
def ladder(x):
    for _ in range(10):
        x = jnp.tanh(x @ x.T) + jnp.sin(x) * jnp.cos(x)
    return x.sum()
jax.jit(ladder)(jnp.ones((256, 256))).block_until_ready()
print("COMPILE_S", sum(comp), len(comp))
"""


def _probe_compile_s(cache_root: str) -> float:
    env = {k: v for k, v in os.environ.items() if k != "ZKP2P_NO_CACHE"}
    out = subprocess.run(
        [sys.executable, "-c", _PROBE, cache_root, REPO],
        capture_output=True, text=True, timeout=300, env=env, check=True,
    ).stdout
    line = [ln for ln in out.splitlines() if ln.startswith("COMPILE_S")][0]
    _tag, secs, n_events = line.split()
    assert int(n_events) > 0  # the listener saw the compile either way
    return float(secs)


def test_warm_cache_roundtrip_10x(tmp_path):
    """Cold subprocess compiles + persists into a fresh
    ZKP2P_JAX_CACHE_DIR; a second subprocess on the same root must spend
    >=10x less in backend_compile — the warm-start contract the
    warm-cache command exists to establish (measured on compile-event
    seconds, the same zkp2p_compile_seconds_total rail the service
    publishes)."""
    root = str(tmp_path / "cache")
    cold_s = _probe_compile_s(root)
    # the cold run left entries behind (round-trip evidence, not a no-op)
    entries = [
        fn for _r, _d, fns in os.walk(root) for fn in fns if fn.endswith("-cache")
    ]
    assert entries, "cold run persisted no cache entries"
    warm_s = _probe_compile_s(root)
    assert warm_s > 0.0
    assert cold_s >= 10.0 * warm_s, (
        f"warm-start speedup {cold_s / warm_s:.1f}x < 10x (cold {cold_s:.3f}s, warm {warm_s:.3f}s)"
    )


# --------------------------------------------------- heterogeneous tiers

AMORT = "1:0.9,2:1.2,4:1.8,8:3.0"


def _ctl(tier="native", objective=8.0):
    c = BatchController(AmortModel.from_spec(AMORT), objective_s=objective, tier=tier)
    c.observe_batch(1, 0.9)  # end warm-up: predictions run on the curve
    return c


def _mixed_reqs(now, n_bulk=4, n_int=2):
    reqs = [
        SchedRequest(rid=f"b{i:02d}", t_submit=now - 1.0 + i * 1e-3,
                     deadline=now + 8.0, interactive=False)
        for i in range(n_bulk)
    ]
    reqs += [
        SchedRequest(rid=f"i{i:02d}", t_submit=now - 0.5 + i * 1e-3,
                     deadline=now + 8.0, interactive=True)
        for i in range(n_int)
    ]
    return reqs


def test_normalize_tier_fails_closed():
    assert normalize_tier("sharded") == "sharded"
    for junk in ("", "native", "SHARDED", "tpu", "mesh"):
        assert normalize_tier(junk) == "native"


def test_worker_tier_arm_digest_visible(monkeypatch):
    from zkp2p_tpu.utils.audit import execution_digest

    monkeypatch.delenv("ZKP2P_WORKER_TIER", raising=False)
    assert worker_tier_arm() == "native"
    d_native = execution_digest()
    monkeypatch.setenv("ZKP2P_WORKER_TIER", "sharded")
    assert worker_tier_arm() == "sharded"
    assert execution_digest() != d_native
    monkeypatch.setenv("ZKP2P_WORKER_TIER", "native")
    worker_tier_arm()
    assert execution_digest() == d_native


def test_native_defers_bulk_to_sharded_peer():
    """Bulk-lane wide batches prefer the sharded tier: with a live
    sharded peer the native worker's plan serves ONLY interactive; the
    bulk lane stays in the spool (deferred, never shed)."""
    c = _ctl("native")
    now = 1000.0
    plan = c.plan(now, _mixed_reqs(now), cap=8, peer_tiers=["sharded"])
    assert plan.tier == "native"
    assert plan.deferred == {"bulk": 4}
    served = [r.rid for b in plan.batches for r in b]
    assert served == ["i00", "i01"]
    assert plan.shed == []  # deferred bulk is the peer's, never shed here
    assert plan.lanes.get("bulk", 0) == 0


def test_deferred_bulk_never_shed_even_when_hopeless():
    """A doomed bulk request next to a live sharded peer is DEFERRED,
    not shed: the peer's own shed walk owns its deadline."""
    c = _ctl("native")
    now = 1000.0
    reqs = [SchedRequest(rid="doomed", t_submit=now - 50.0, deadline=now - 1.0,
                         interactive=False)]
    plan = c.plan(now, reqs, cap=8, peer_tiers=["sharded"])
    assert plan.shed == [] and plan.deferred == {"bulk": 1}
    # without the peer the same request IS shed (the baseline behavior)
    c2 = _ctl("native")
    plan2 = c2.plan(now, list(reqs), cap=8, peer_tiers=[])
    assert [r.rid for r, _why in plan2.shed] == ["doomed"]


def test_sharded_defers_interactive_to_native_peer():
    """The interactive lane never waits on a sharded-tier dispatch: with
    a live native peer the sharded worker's plan serves ONLY bulk."""
    c = _ctl("sharded")
    now = 1000.0
    plan = c.plan(now, _mixed_reqs(now), cap=8, peer_tiers=["native"])
    assert plan.tier == "sharded"
    assert plan.deferred == {"interactive": 2}
    served = [r.rid for b in plan.batches for r in b]
    assert served == ["b00", "b01", "b02", "b03"]
    assert plan.lanes.get("interactive", 0) == 0


def test_solo_worker_serves_both_lanes():
    """No starvation when the fleet degrades to one tier: without a
    peer of the other tier, either tier serves everything."""
    now = 1000.0
    for tier, peers in (("native", []), ("native", ["native"]),
                        ("sharded", []), ("sharded", ["sharded"]), ("native", None)):
        c = _ctl(tier)
        plan = c.plan(now, _mixed_reqs(now), cap=8, peer_tiers=peers)
        assert plan.deferred == {}, (tier, peers)
        assert sum(len(b) for b in plan.batches) == 6, (tier, peers)


def test_tier_loss_degrades_to_native_with_counted_event():
    """A sharded peer vanishing while bulk is queued fires tier_fallback
    exactly ONCE per loss; the native worker resumes the bulk lane."""
    c = _ctl("native")
    now = 1000.0
    plan = c.plan(now, _mixed_reqs(now), cap=8, peer_tiers=["sharded"])
    assert plan.deferred == {"bulk": 4} and not plan.tier_fallback
    # peer gone, bulk queued: fallback flagged, bulk served again
    plan2 = c.plan(now + 5.0, _mixed_reqs(now + 5.0), cap=8, peer_tiers=[])
    assert plan2.tier_fallback
    assert plan2.deferred == {}
    assert sum(len(b) for b in plan2.batches) == 6
    # once per loss, not once per sweep
    plan3 = c.plan(now + 10.0, _mixed_reqs(now + 10.0), cap=8, peer_tiers=[])
    assert not plan3.tier_fallback
    # peer back then lost again during IDLE: the edge must not fire a
    # stale fallback on the next busy sweep
    c.plan(now + 15.0, [], cap=8, peer_tiers=["sharded"])
    c.plan(now + 20.0, [], cap=8, peer_tiers=[])
    plan4 = c.plan(now + 25.0, _mixed_reqs(now + 25.0), cap=8, peer_tiers=[])
    assert not plan4.tier_fallback


def test_build_controller_resolves_per_tier_amort(monkeypatch):
    """ZKP2P_WORKER_TIER=sharded + no explicit spec + no profile ->
    DEFAULT_SHARDED_AMORT_POINTS (heavy dispatch floor, hard wide-batch
    amortization); native keeps the venmo default; an explicit
    ZKP2P_SCHED_AMORT wins for either tier."""
    from zkp2p_tpu.pipeline.sched import DEFAULT_AMORT_POINTS, build_controller
    from zkp2p_tpu.utils.config import load_config

    # a REAL host profile on this box would seed the curve — isolate it
    monkeypatch.setenv("ZKP2P_PROFILE_PATH", "/nonexistent/no-profile.json")

    cfg = load_config(environ={"ZKP2P_WORKER_TIER": "sharded"})
    monkeypatch.setenv("ZKP2P_WORKER_TIER", "sharded")  # worker_tier_arm fresh-reads
    ctl = build_controller(cfg)
    assert ctl.tier == "sharded"
    for s, cost in DEFAULT_SHARDED_AMORT_POINTS.items():
        assert ctl.amort.batch_s(s) == pytest.approx(cost)
    # the sharded curve amortizes wide batches harder than native
    nat = AmortModel(DEFAULT_AMORT_POINTS)
    assert ctl.amort.per_proof_s(16) / ctl.amort.per_proof_s(1) < \
        nat.per_proof_s(16) / nat.per_proof_s(1)

    monkeypatch.setenv("ZKP2P_WORKER_TIER", "native")
    ctl_n = build_controller(load_config(environ={}))
    assert ctl_n.tier == "native"
    for s, cost in DEFAULT_AMORT_POINTS.items():
        assert ctl_n.amort.batch_s(s) == pytest.approx(cost)

    monkeypatch.setenv("ZKP2P_WORKER_TIER", "sharded")
    ctl_s = build_controller(
        load_config(environ={"ZKP2P_WORKER_TIER": "sharded", "ZKP2P_SCHED_AMORT": AMORT})
    )
    assert ctl_s.amort.batch_s(8) == pytest.approx(3.0)  # explicit spec wins


# ------------------------------------------- mixed-tier toy fleet A/B


def _chaos_mod():
    spec = importlib.util.spec_from_file_location("zkp2p_chaos_for_shard", CHAOS)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


@pytest.fixture(scope="module")
def toy_world():
    from zkp2p_tpu.native.lib import get_lib

    if get_lib() is None:
        pytest.skip("native toolchain unavailable")
    return _chaos_mod()._build_world()


def _toy_service(world, **kw):
    from zkp2p_tpu.pipeline.service import ProvingService
    from zkp2p_tpu.prover.native_prove import prove_native_batch

    cs, dpk, vk, witness_fn = world
    kw.setdefault("batch_size", 8)
    kw.setdefault("prover_fn", prove_native_batch)
    return ProvingService(cs, dpk, vk, witness_fn, public_fn=lambda w: [w[1]], **kw)


def _drop(spool, rid, payload):
    os.makedirs(spool, exist_ok=True)
    with open(os.path.join(spool, rid + ".req.json"), "w") as f:
        json.dump(payload, f)


def _fake_peer_hb(fleet_dir, wid, tier):
    os.makedirs(fleet_dir, exist_ok=True)
    with open(os.path.join(fleet_dir, wid + ".hb"), "w") as f:
        json.dump({"pid": 0, "ts": round(time.time(), 3), "worker": wid,
                   "state": "up", "tier": tier}, f)


def test_mixed_tier_fleet_routes_bulk_to_sharded(toy_world, tmp_path, monkeypatch):
    """The mixed-tier A/B on one spool: a native worker with a live
    sharded peer proves ONLY the interactive lane (bulk deferred, sched
    line + heartbeat say so); the sharded worker then proves the bulk
    lane; the chaos checker holds — every request exactly one terminal,
    zero lost, zero duplicated."""
    monkeypatch.setenv("ZKP2P_SCHED", "adaptive")
    monkeypatch.setenv("ZKP2P_SCHED_AMORT", "1:0.05,8:0.1")
    monkeypatch.setenv("ZKP2P_DEADLINE_S", "30")
    spool = str(tmp_path / "spool")
    fleet_dir = str(tmp_path / "fleet")
    monkeypatch.setenv("ZKP2P_FLEET_DIR", fleet_dir)
    for i in range(4):
        _drop(spool, f"b{i}", {"x": 3 + i, "y": 4})
    for i in range(2):
        _drop(spool, f"i{i}", {"x": 5 + i, "y": 6, "priority": "interactive"})

    # --- the native worker, with a live sharded peer advertised
    monkeypatch.setenv("ZKP2P_WORKER_TIER", "native")
    monkeypatch.setenv("ZKP2P_WORKER_ID", "w-native")
    _fake_peer_hb(fleet_dir, "w-sharded", "sharded")
    svc_n = _toy_service(toy_world)
    stats_n = svc_n.process_dir(spool)
    assert stats_n["done"] == 2  # the interactive pair only
    assert svc_n._sched_hb["tier"] == "native"
    assert svc_n._sched_hb["deferred"] == {"bulk": 4}
    # the bulk lane is still OPEN in the spool — no terminal artifact,
    # no claim (deferral is claim-free) — while interactive is proved
    names = set(os.listdir(spool))
    for i in range(4):
        assert f"b{i}.proof.json" not in names and f"b{i}.error.json" not in names
        assert f"b{i}.claim" not in names
    for i in range(2):
        assert f"i{i}.proof.json" in names

    # --- the sharded worker sweeps next (native peer still fresh)
    monkeypatch.setenv("ZKP2P_WORKER_TIER", "sharded")
    monkeypatch.setenv("ZKP2P_WORKER_ID", "w-sharded")
    _fake_peer_hb(fleet_dir, "w-native", "native")
    svc_s = _toy_service(toy_world)
    stats_s = svc_s.process_dir(spool)
    assert stats_s["done"] == 4  # the whole bulk lane
    assert svc_s._sched_hb["tier"] == "sharded"

    # --- global invariant: zero lost, zero duplicated, all verified
    chaos = _chaos_mod()
    report = chaos.check_invariants(spool, vk=toy_world[2])
    assert report["violations"] == [], report
    assert report["proofs_verified"] == 6 and report["states"] == {"done": 6}

    # the decision telemetry: one sched line per worker, defer recorded
    with open(spool + ".metrics.jsonl") as f:
        recs = [json.loads(line) for line in f]
    sched_lines = [r for r in recs if r.get("type") == "sched"]
    by_tier = {ln["tier"]: ln for ln in sched_lines}
    assert by_tier["native"]["deferred"] == {"bulk": 4}
    assert by_tier["native"]["peer_tiers"] == ["sharded"]
    assert "deferred" not in by_tier["sharded"]
    # bulk records attribute to the sharded worker, interactive to native
    reqs = {r["request_id"]: r for r in recs if r.get("type") == "request"}
    assert all(reqs[f"b{i}"]["worker"] == "w-sharded" for i in range(4))
    assert all(reqs[f"i{i}"]["worker"] == "w-native" for i in range(2))
