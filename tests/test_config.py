"""The typed prover config (SURVEY.md §5: one config, env as override).

Pins the resolution order (default -> armed flags -> env), provenance
labeling, the armable-knob whitelist, and — via a source scan — that
every ZKP2P_* variable read anywhere in the tree is registered in the
config's knob table (no knob may bypass the single source of truth)."""

import json
import os
import re

from zkp2p_tpu.utils.config import ARMABLE, KNOBS, ProverConfig, load_config

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def test_defaults():
    cfg = load_config(environ={})
    assert cfg.msm_window == 4
    assert cfg.msm_signed is True
    assert cfg.msm_h == "windowed"
    assert cfg.native_ifma is True
    # the native batch-affine bucket tier is the committed-on arm; its
    # parser follows the C runtime's leading-'0' rule like native_ifma
    assert cfg.msm_batch_affine is True
    assert load_config(environ={"ZKP2P_MSM_BATCH_AFFINE": "true"}).msm_batch_affine is True
    assert load_config(environ={"ZKP2P_MSM_BATCH_AFFINE": "0"}).msm_batch_affine is False
    assert all(v == "default" for v in cfg.provenance.values())


def test_env_overrides_every_knob():
    env = {
        "ZKP2P_MSM_WINDOW": "8",
        "ZKP2P_MSM_SIGNED": "0",
        "ZKP2P_MSM_UNIFIED": "1",
        "ZKP2P_MSM_AFFINE": "1",
        "ZKP2P_MSM_H": "bucket",
        "ZKP2P_MSM_GLV": "1",
        "ZKP2P_MSM_OVERLAP": "0",
        "ZKP2P_MSM_BATCH_AFFINE": "0",
        "ZKP2P_MSM_MULTI": "0",
        "ZKP2P_MSM_PRECOMP": "0",
        "ZKP2P_MSM_PRECOMP_DEPTH": "4",
        "ZKP2P_MSM_PRECOMP_MAX_MB": "512",
        "ZKP2P_MSM_PRECOMP_CACHE": "/tmp/precomp_cache",
        "ZKP2P_MSM_PRECOMP_PERSIST_MIN": "1024",
        "ZKP2P_MSM_PRECOMP_FAMILIES": "a,h",
        "ZKP2P_MATVEC_SEG": "0",
        "ZKP2P_NTT_POOL": "0",
        "ZKP2P_MSM_INTERLEAVE": "0",
        "ZKP2P_NTT_RADIX8": "1",
        "ZKP2P_WITNESS_U64": "0",
        "ZKP2P_BATCH_CHUNK": "8",
        "ZKP2P_FIELD_CONV": "limb_major",
        "ZKP2P_FIELD_MUL": "pallas",
        "ZKP2P_CURVE_KERNEL": "xla",
        "ZKP2P_NATIVE_IFMA": "0",
        "ZKP2P_NATIVE_THREADS": "7",
        "ZKP2P_NO_CACHE": "1",
        "ZKP2P_MSM_PROF": "1",
        "ZKP2P_METRICS_PORT": "9464",
        "ZKP2P_METRICS_ADDR": "0.0.0.0",
        "ZKP2P_METRICS_SINK": "/tmp/sink.jsonl",
        "ZKP2P_TRACE_MAX": "1024",
        "ZKP2P_FAULTS": "prove:raise:p=0.5,emit:enospc:once",
        "ZKP2P_DEADLINE_S": "30",
        "ZKP2P_SPOOL_CAP": "256",
        "ZKP2P_PROVE_RETRIES": "5",
        "ZKP2P_RETRY_BACKOFF_S": "0.5",
        "ZKP2P_SLO_P95_S": "12",
        "ZKP2P_SLO_TARGET": "0.99",
        "ZKP2P_SLO_WINDOW_S": "60",
        "ZKP2P_TS_SAMPLE_S": "2.5",
        "ZKP2P_WORKER_ID": "w3",
        "ZKP2P_FLEET_ID": "fleet-abc",
        "ZKP2P_FLEET_DIR": "/tmp/fleetdir",
        "ZKP2P_FLEET_WORKERS": "4",
        "ZKP2P_DRAIN_TIMEOUT_S": "7.5",
        "ZKP2P_RSS_SOFT_MB": "2048",
        "ZKP2P_RSS_HARD_MB": "4096",
        "ZKP2P_BREAKER_K": "3",
        "ZKP2P_BREAKER_WINDOW_S": "45",
        "ZKP2P_RESTART_BACKOFF_S": "0.1",
        "ZKP2P_FLEET_METRICS_PORT": "9470",
        "ZKP2P_FLEET_SCRAPE_S": "1.5",
        "ZKP2P_SLO_FAST_WINDOW_S": "90",
        "ZKP2P_ALERT_BURN_RATE": "4",
        "ZKP2P_ALERT_RESTARTS": "5",
        "ZKP2P_ALERT_FOR_S": "7",
        "ZKP2P_ALERT_CLEAR_S": "20",
        "ZKP2P_ALERT_HB_GAP_S": "8",
        "ZKP2P_SCHED": "adaptive",
        "ZKP2P_SCHED_TARGET_FILL": "0.7",
        "ZKP2P_SCHED_AMORT": "1:0.9,8:3.0",
        "ZKP2P_SCHED_PRIORITY_DEFAULT": "interactive",
        "ZKP2P_WORKERS_MIN": "1",
        "ZKP2P_WORKERS_MAX": "6",
        "ZKP2P_SCALE_UP_S": "12",
        "ZKP2P_SCALE_DOWN_S": "45",
        "ZKP2P_PROFILE": "0",
        "ZKP2P_PROFILE_PATH": "/tmp/prof.json",
        "ZKP2P_TUNE_BUDGET_S": "45",
        "ZKP2P_TUNE_ARMS": "geometry,columns",
        "ZKP2P_TPU_SHARD": "on",
        "ZKP2P_TPU_MESH": "2x4",
        "ZKP2P_JAX_CACHE_DIR": "/tmp/jaxcache",
        "ZKP2P_WORKER_TIER": "sharded",
        "ZKP2P_PERF_LEDGER": "0",
        "ZKP2P_PERF_TOLERANCE": "2.25",
        "ZKP2P_PERF_WINDOW": "12",
        "ZKP2P_FLAME": "1",
        "ZKP2P_FLAME_HZ": "31",
        "ZKP2P_FLAME_CAPTURE_N": "3",
        "ZKP2P_FLAME_COOLDOWN_S": "15",
    }
    cfg = load_config(environ=env)
    assert cfg.msm_window == 8 and cfg.msm_signed is False
    assert cfg.msm_unified == "1" and cfg.msm_affine == "1" and cfg.msm_h == "bucket"
    assert cfg.msm_glv is True
    assert cfg.msm_overlap is False
    assert cfg.msm_batch_affine is False
    assert cfg.msm_multi is False
    assert cfg.msm_precomp is False and cfg.precomp_depth == 4
    assert cfg.precomp_max_mb == 512 and cfg.precomp_cache == "/tmp/precomp_cache"
    assert cfg.precomp_persist_min == 1024 and cfg.precomp_families == "a,h"
    assert cfg.matvec_seg is False and cfg.ntt_pool is False
    assert cfg.msm_interleave is False and cfg.ntt_radix8 is True
    assert cfg.witness_u64 is False
    assert cfg.batch_chunk == "8"
    assert cfg.field_conv == "limb_major" and cfg.field_mul == "pallas" and cfg.curve_kernel == "xla"
    assert cfg.native_ifma is False and cfg.native_threads == 7 and cfg.no_cache is True
    assert cfg.metrics_port == 9464 and cfg.metrics_sink == "/tmp/sink.jsonl" and cfg.trace_max == 1024
    assert cfg.metrics_addr == "0.0.0.0"
    assert cfg.faults == "prove:raise:p=0.5,emit:enospc:once"
    assert cfg.deadline_s == 30.0 and cfg.spool_cap == 256
    assert cfg.prove_retries == 5 and cfg.retry_backoff_s == 0.5
    assert cfg.slo_p95_s == 12.0 and cfg.slo_target == 0.99
    assert cfg.slo_window_s == 60.0 and cfg.ts_sample_s == 2.5
    assert cfg.worker_id == "w3" and cfg.fleet_id == "fleet-abc"
    assert cfg.fleet_dir == "/tmp/fleetdir" and cfg.fleet_workers == 4
    assert cfg.drain_timeout_s == 7.5
    assert cfg.rss_soft_mb == 2048 and cfg.rss_hard_mb == 4096
    assert cfg.breaker_k == 3 and cfg.breaker_window_s == 45.0
    assert cfg.restart_backoff_s == 0.1
    assert cfg.fleet_metrics_port == 9470 and cfg.fleet_scrape_s == 1.5
    assert cfg.slo_fast_window_s == 90.0
    assert cfg.alert_burn_rate == 4.0 and cfg.alert_restarts == 5
    assert cfg.alert_for_s == 7.0 and cfg.alert_clear_s == 20.0
    assert cfg.alert_hb_gap_s == 8.0
    assert cfg.sched == "adaptive" and cfg.sched_target_fill == 0.7
    assert cfg.sched_amort == "1:0.9,8:3.0"
    assert cfg.sched_priority_default == "interactive"
    assert cfg.workers_min == 1 and cfg.workers_max == 6
    assert cfg.scale_up_s == 12.0 and cfg.scale_down_s == 45.0
    assert cfg.profile is False and cfg.profile_path == "/tmp/prof.json"
    assert cfg.tune_budget_s == 45.0 and cfg.tune_arms == "geometry,columns"
    assert cfg.tpu_shard == "on" and cfg.tpu_mesh == "2x4"
    assert cfg.jax_cache_dir == "/tmp/jaxcache"
    assert cfg.worker_tier == "sharded"
    assert cfg.perf_ledger is False and cfg.perf_tolerance == 2.25
    assert cfg.perf_window == 12
    assert cfg.flame is True and cfg.flame_hz == 31.0
    assert cfg.flame_capture_n == 3 and cfg.flame_cooldown_s == 15.0
    assert all(v == "env" for v in cfg.provenance.values())


def test_reader_matched_parsers():
    """Parsers must reproduce the semantics of the actual readers: the
    C runtime disables IFMA only on a leading '0' ('true' stays ON),
    and an empty thread count is shell-style unset, not 1 thread."""
    cfg = load_config(environ={"ZKP2P_NATIVE_IFMA": "true"})
    assert cfg.native_ifma is True
    assert load_config(environ={"ZKP2P_NATIVE_IFMA": "0"}).native_ifma is False
    assert load_config(environ={"ZKP2P_NATIVE_THREADS": ""}).native_threads is None
    assert load_config(environ={"ZKP2P_NATIVE_THREADS": "junk"}).native_threads == 1
    # metrics port fails CLOSED (no listener) on anything non-portlike;
    # "auto"/"0" mean EPHEMERAL (bind port 0, record the bound port) so
    # N fleet workers on one host never collide on a fixed port
    assert load_config(environ={"ZKP2P_METRICS_PORT": "0"}).metrics_port == 0
    assert load_config(environ={"ZKP2P_METRICS_PORT": "auto"}).metrics_port == 0
    assert load_config(environ={"ZKP2P_METRICS_PORT": "junk"}).metrics_port is None
    assert load_config(environ={"ZKP2P_METRICS_PORT": "9464"}).metrics_port == 9464
    assert load_config(environ={"ZKP2P_METRICS_PORT": "99999"}).metrics_port is None
    # fleet plane port follows the metrics-port grammar exactly:
    # auto/0 = ephemeral, junk fails CLOSED (plane off), range-checked
    assert load_config(environ={"ZKP2P_FLEET_METRICS_PORT": "auto"}).fleet_metrics_port == 0
    assert load_config(environ={"ZKP2P_FLEET_METRICS_PORT": "0"}).fleet_metrics_port == 0
    assert load_config(environ={"ZKP2P_FLEET_METRICS_PORT": "junk"}).fleet_metrics_port is None
    assert load_config(environ={"ZKP2P_FLEET_METRICS_PORT": "9470"}).fleet_metrics_port == 9470
    assert load_config(environ={}).fleet_metrics_port is None  # default: plane off
    # alert thresholds: malformed keeps the committed default, negative
    # seconds clamp to 0 (fire/clear immediately, never a time machine)
    assert load_config(environ={"ZKP2P_ALERT_BURN_RATE": "junk"}).alert_burn_rate == 2.0
    assert load_config(environ={"ZKP2P_ALERT_RESTARTS": "0"}).alert_restarts == 1
    assert load_config(environ={"ZKP2P_ALERT_FOR_S": "-3"}).alert_for_s == 0.0
    assert load_config(environ={"ZKP2P_FLEET_SCRAPE_S": "junk"}).fleet_scrape_s == 2.0
    # host-profile gate follows the C runtime's not-zero rule (off only
    # on a leading '0'); the tune budget is a seconds knob (0 =
    # unbudgeted, malformed keeps the committed default)
    assert load_config(environ={"ZKP2P_PROFILE": "0"}).profile is False
    assert load_config(environ={"ZKP2P_PROFILE": "true"}).profile is True
    assert load_config(environ={}).profile is True  # default: profiles load
    assert load_config(environ={"ZKP2P_TUNE_BUDGET_S": "0"}).tune_budget_s == 0.0
    assert load_config(environ={"ZKP2P_TUNE_BUDGET_S": "junk"}).tune_budget_s == 120.0
    assert load_config(environ={"ZKP2P_TUNE_BUDGET_S": "-5"}).tune_budget_s == 0.0
    # fleet knobs: breaker/backoff clamp like their service siblings
    assert load_config(environ={"ZKP2P_FLEET_WORKERS": "0"}).fleet_workers == 1
    assert load_config(environ={"ZKP2P_FLEET_WORKERS": "junk"}).fleet_workers == 2
    assert load_config(environ={"ZKP2P_DRAIN_TIMEOUT_S": "-1"}).drain_timeout_s == 0.0
    assert load_config(environ={"ZKP2P_RSS_SOFT_MB": "junk"}).rss_soft_mb == 0
    assert load_config(environ={"ZKP2P_BREAKER_K": "0"}).breaker_k == 1
    assert load_config(environ={"ZKP2P_RESTART_BACKOFF_S": "junk"}).restart_backoff_s == 0.5
    # trace ring bound keeps the committed default on malformed input
    assert load_config(environ={"ZKP2P_TRACE_MAX": "junk"}).trace_max == 65536
    # fault-tolerance seconds/count knobs: 0 is meaningful (disabled /
    # unlimited / no retries), negatives clamp, malformed keeps defaults
    assert load_config(environ={"ZKP2P_DEADLINE_S": "0"}).deadline_s == 0.0
    assert load_config(environ={"ZKP2P_DEADLINE_S": "-3"}).deadline_s == 0.0
    assert load_config(environ={"ZKP2P_DEADLINE_S": "junk"}).deadline_s == 0.0
    assert load_config(environ={"ZKP2P_SPOOL_CAP": "junk"}).spool_cap == 0
    assert load_config(environ={"ZKP2P_PROVE_RETRIES": "0"}).prove_retries == 0
    assert load_config(environ={"ZKP2P_PROVE_RETRIES": "junk"}).prove_retries == 2
    assert load_config(environ={"ZKP2P_RETRY_BACKOFF_S": "junk"}).retry_backoff_s == 0.25
    # SLO knobs: objective 0 = disabled; the target fraction must land
    # strictly inside (0,1) — out-of-range or malformed keeps 0.95 (a
    # target of 1.0 would divide the burn rate by zero error budget)
    assert load_config(environ={"ZKP2P_SLO_P95_S": "0"}).slo_p95_s == 0.0
    assert load_config(environ={"ZKP2P_SLO_P95_S": "junk"}).slo_p95_s == 0.0
    assert load_config(environ={"ZKP2P_SLO_TARGET": "1.0"}).slo_target == 0.95
    assert load_config(environ={"ZKP2P_SLO_TARGET": "0"}).slo_target == 0.95
    assert load_config(environ={"ZKP2P_SLO_TARGET": "junk"}).slo_target == 0.95
    assert load_config(environ={"ZKP2P_SLO_TARGET": "0.9"}).slo_target == 0.9
    assert load_config(environ={"ZKP2P_TS_SAMPLE_S": "0"}).ts_sample_s == 0.0
    assert load_config(environ={"ZKP2P_TS_SAMPLE_S": "junk"}).ts_sample_s == 10.0
    # scheduler knobs: the gate stays a raw string (sched_mode fails
    # CLOSED to "off" on anything but "adaptive"); the headroom
    # fraction follows the SLO-target grammar (strictly inside (0,1),
    # malformed keeps 0.8); autoscale bounds are nonneg ints (0 = off)
    # and the hysteresis windows clamp like their alert siblings
    assert load_config(environ={}).sched == "off"
    assert load_config(environ={"ZKP2P_SCHED": "adaptive"}).sched == "adaptive"
    assert load_config(environ={"ZKP2P_SCHED_TARGET_FILL": "junk"}).sched_target_fill == 0.8
    assert load_config(environ={"ZKP2P_SCHED_TARGET_FILL": "1.5"}).sched_target_fill == 0.8
    assert load_config(environ={"ZKP2P_SCHED_TARGET_FILL": "0.5"}).sched_target_fill == 0.5
    assert load_config(environ={"ZKP2P_WORKERS_MAX": "junk"}).workers_max == 0
    assert load_config(environ={"ZKP2P_WORKERS_MIN": "-2"}).workers_min == 0
    assert load_config(environ={"ZKP2P_SCALE_UP_S": "-1"}).scale_up_s == 0.0
    assert load_config(environ={"ZKP2P_SCALE_DOWN_S": "junk"}).scale_down_s == 30.0
    assert load_config(environ={}).sched_priority_default == "bulk"
    # perf-sentry knobs: the gate follows the not-zero rule; the
    # tolerance is a multiplier and must stay >= 1.0 (a sub-1 band
    # would flag the median itself — malformed/too-small keeps 1.5);
    # the window is a positive entry count
    assert load_config(environ={}).perf_ledger is True  # default: sentry on
    assert load_config(environ={"ZKP2P_PERF_LEDGER": "0"}).perf_ledger is False
    assert load_config(environ={"ZKP2P_PERF_LEDGER": "true"}).perf_ledger is True
    assert load_config(environ={"ZKP2P_PERF_TOLERANCE": "2.0"}).perf_tolerance == 2.0
    assert load_config(environ={"ZKP2P_PERF_TOLERANCE": "0.5"}).perf_tolerance == 1.5
    assert load_config(environ={"ZKP2P_PERF_TOLERANCE": "junk"}).perf_tolerance == 1.5
    assert load_config(environ={"ZKP2P_PERF_WINDOW": "3"}).perf_window == 3
    assert load_config(environ={"ZKP2P_PERF_WINDOW": "0"}).perf_window == 1
    assert load_config(environ={"ZKP2P_PERF_WINDOW": "junk"}).perf_window == 8
    # PR-20 floor knobs: interleave and witness-u64 follow the C
    # runtime's not-zero rule (committed ON, off only on a leading
    # '0'); radix-8 follows the C gate's leading-'1' rule — committed
    # OFF (0.95x on narrow hosts), ON only on an explicit '1'
    assert load_config(environ={}).msm_interleave is True
    assert load_config(environ={"ZKP2P_MSM_INTERLEAVE": "0"}).msm_interleave is False
    assert load_config(environ={"ZKP2P_MSM_INTERLEAVE": "true"}).msm_interleave is True
    assert load_config(environ={}).witness_u64 is True
    assert load_config(environ={"ZKP2P_WITNESS_U64": "0"}).witness_u64 is False
    assert load_config(environ={"ZKP2P_WITNESS_U64": "yes"}).witness_u64 is True
    assert load_config(environ={}).ntt_radix8 is False
    assert load_config(environ={"ZKP2P_NTT_RADIX8": "1"}).ntt_radix8 is True
    assert load_config(environ={"ZKP2P_NTT_RADIX8": "0"}).ntt_radix8 is False
    assert load_config(environ={"ZKP2P_NTT_RADIX8": "true"}).ntt_radix8 is False
    assert load_config(environ={"ZKP2P_NTT_RADIX8": ""}).ntt_radix8 is False
    # flame-sampler knobs: gate default OFF (not-zero rule), the rate
    # must stay strictly positive (a 0 Hz sampler parks forever —
    # malformed/non-positive keeps the prime 47), capture_n is a
    # positive sweep count, cooldown 0 = unlimited captures
    assert load_config(environ={}).flame is False  # default: sampler off
    assert load_config(environ={"ZKP2P_FLAME": "1"}).flame is True
    assert load_config(environ={"ZKP2P_FLAME": "0"}).flame is False
    assert load_config(environ={"ZKP2P_FLAME": "yes"}).flame is True
    assert load_config(environ={"ZKP2P_FLAME_HZ": "101"}).flame_hz == 101.0
    assert load_config(environ={"ZKP2P_FLAME_HZ": "0"}).flame_hz == 47.0
    assert load_config(environ={"ZKP2P_FLAME_HZ": "-5"}).flame_hz == 47.0
    assert load_config(environ={"ZKP2P_FLAME_HZ": "junk"}).flame_hz == 47.0
    assert load_config(environ={"ZKP2P_FLAME_CAPTURE_N": "5"}).flame_capture_n == 5
    assert load_config(environ={"ZKP2P_FLAME_CAPTURE_N": "0"}).flame_capture_n == 1
    assert load_config(environ={"ZKP2P_FLAME_CAPTURE_N": "junk"}).flame_capture_n == 2
    assert load_config(environ={"ZKP2P_FLAME_COOLDOWN_S": "0"}).flame_cooldown_s == 0.0
    assert load_config(environ={"ZKP2P_FLAME_COOLDOWN_S": "-3"}).flame_cooldown_s == 0.0
    assert load_config(environ={"ZKP2P_FLAME_COOLDOWN_S": "junk"}).flame_cooldown_s == 60.0


def test_armed_flags_whitelist_and_precedence(tmp_path):
    p = tmp_path / "armed_flags.json"
    p.write_text(json.dumps({
        "ZKP2P_MSM_AFFINE": True,
        "ZKP2P_MSM_H": "bucket",
        "ZKP2P_MSM_WINDOW": "16",   # NOT armable: must be ignored
        "ZKP2P_NATIVE_IFMA": "0",   # NOT armable: must be ignored
    }))
    msgs = []
    cfg = load_config(environ={}, armed_flags_path=str(p), log=msgs.append)
    assert cfg.msm_affine == "1" and cfg.provenance["msm_affine"] == "armed"
    assert cfg.msm_h == "bucket" and cfg.provenance["msm_h"] == "armed"
    assert cfg.msm_window == 4 and cfg.provenance["msm_window"] == "default"
    assert cfg.native_ifma is True
    assert sum("non-armable" in m for m in msgs) == 2
    # explicit env beats armed
    cfg2 = load_config(environ={"ZKP2P_MSM_H": "windowed"}, armed_flags_path=str(p))
    assert cfg2.msm_h == "windowed" and cfg2.provenance["msm_h"] == "env"


def test_corrupt_armed_flags_never_fatal(tmp_path):
    p = tmp_path / "armed_flags.json"
    p.write_text("{not json")
    cfg = load_config(environ={}, armed_flags_path=str(p))
    assert cfg == ProverConfig(provenance=cfg.provenance)


def test_apply_env_roundtrip():
    cfg = load_config(environ={"ZKP2P_MSM_H": "bucket", "ZKP2P_NATIVE_THREADS": "3"})
    env: dict = {}
    cfg.apply_env(env)
    assert env["ZKP2P_MSM_H"] == "bucket"
    assert env["ZKP2P_MSM_SIGNED"] == "1"
    assert env["ZKP2P_NATIVE_THREADS"] == "3"
    # a second load from the exported env reproduces the config
    cfg2 = load_config(environ=env)
    assert cfg2 == cfg


def test_every_zkp2p_env_read_is_registered():
    """Scan the tree for ZKP2P_* reads: each must be a registered knob
    (or an explicitly test-scoped variable), so no code path can grow a
    config knob outside the typed config again."""
    registered = {var for var, _p, _d in KNOBS.values()}
    # ONE allowlist, shared with the zkp2p-lint knob checker (which runs
    # this same scan as a tier-1 static pass) — two diverging lists
    # would let a token pass one gate and fail the other
    import sys

    sys.path.insert(0, REPO)
    from tools.lint.knobs import ALLOWED_EXTRA as allowed_extra
    found = set()
    scan_roots = ["zkp2p_tpu", "csrc", "bench.py", "__graft_entry__.py", "tools"]
    for root in scan_roots:
        path = os.path.join(REPO, root)
        files = []
        if os.path.isfile(path):
            files = [path]
        else:
            for dirpath, _dirs, names in os.walk(path):
                files += [os.path.join(dirpath, n) for n in names if n.endswith((".py", ".cpp", ".sh"))]
        for f in files:
            if f.endswith("config.py"):
                continue
            with open(f, errors="ignore") as fh:
                # digits included: ZKP2P_SLO_P95_S was the first knob
                # with one, and an [A-Z_]-only scan truncated it to an
                # unregistered-looking "ZKP2P_SLO_P"
                found |= set(re.findall(r"ZKP2P_[A-Z0-9_]*", fh.read()))
    unregistered = found - registered - allowed_extra
    assert not unregistered, f"env reads outside the typed config: {sorted(unregistered)}"
    # and the armable whitelist refers to real knobs
    assert set(ARMABLE) <= set(KNOBS)
