"""The adaptive scheduler (pipeline.sched), tier-1 (`make sched-smoke`):

  * AmortModel — interpolation/extrapolation, spec parsing, the
    strictly-increasing validation, loud malformed-spec failure;
  * BatchController — deterministic over injected clocks + synthetic
    arrival streams: EWMA arrival rate, batch size monotone in load and
    clamped to backlog/cap, small at low load, interactive-first lane
    ordering with the bounded latency-lane width, expected-deadline-miss
    shedding (hopeless shed, feasible NEVER shed), admission-cap shed by
    least slack, no shedding while draining;
  * AutoscalePolicy — hysteresis: fires only after a sustained window,
    a boundary-oscillating signal never flaps (zero decisions), bounds
    clamp, missing signals hold state, every decision resets the clock;
  * the service integration smoke — a toy-circuit mini-trace through
    the REAL service: ZKP2P_SCHED=adaptive sheds the hopeless request,
    proves the interactive lane first, stamps batch_size_target on
    records, writes {"type": "sched"} decision lines; the off arm keeps
    the static slicing; the two arms are digest-distinguishable
    (service_sched gate);
  * the fleet autoscale demo — a 1->2->1 worker fleet under a backlog
    spike: scale events in status.json + the sched block, zero lost /
    zero duplicated proofs (the PR-7 invariant via chaos
    check_invariants).
"""

import importlib.util
import json
import os
import time

import pytest

from zkp2p_tpu.pipeline.sched import (
    AmortModel,
    AutoscalePolicy,
    BatchController,
    INTERACTIVE_LANE_CAP,
    SchedRequest,
    sched_mode,
)

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
CHAOS = os.path.join(REPO, "tools", "chaos.py")


def _chaos_mod():
    spec = importlib.util.spec_from_file_location("zkp2p_chaos_for_sched", CHAOS)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


# ------------------------------------------------------------ AmortModel


def test_amort_interpolation_and_extrapolation():
    m = AmortModel({1: 0.9, 4: 1.8, 8: 3.0})
    assert m.batch_s(1) == pytest.approx(0.9)
    assert m.batch_s(4) == pytest.approx(1.8)
    assert m.batch_s(2) == pytest.approx(0.9 + (1.8 - 0.9) / 3)  # linear between points
    assert m.batch_s(8) == pytest.approx(3.0)
    # above the last point: the last segment's slope, not a flat line
    assert m.batch_s(12) == pytest.approx(3.0 + 4 * (3.0 - 1.8) / 4)
    # a single point scales proportionally in both directions
    m1 = AmortModel({4: 2.0})
    assert m1.batch_s(2) == pytest.approx(1.0)
    assert m1.batch_s(8) == pytest.approx(4.0)
    assert m1.batch_s(0) == 0.0
    # per-proof cost + the throughput argmin (tie breaks small)
    assert m.per_proof_s(8) == pytest.approx(3.0 / 8)
    assert m.best_throughput_size(8) == 8
    flat = AmortModel({1: 1.0, 2: 2.0})  # perfectly linear: no amortization
    assert flat.best_throughput_size(8) == 1


def test_amort_spec_parsing_and_validation():
    m = AmortModel.from_spec("1:0.5, 4:1.1")
    assert m.batch_s(4) == pytest.approx(1.1)
    # "" = the built-in conservative default
    d = AmortModel.from_spec("")
    assert d.batch_s(1) > 0
    with pytest.raises(ValueError):
        AmortModel.from_spec("junk")
    with pytest.raises(ValueError):
        AmortModel.from_spec("1:2,1:3")  # duplicate / non-increasing S
    with pytest.raises(ValueError):
        AmortModel({1: 2.0, 4: 1.0})  # cost must increase with S
    with pytest.raises(ValueError):
        AmortModel({})


# ------------------------------------------------------- BatchController

AMORT = "1:0.9,2:1.2,4:1.8,8:3.0"  # overhead 0.6 + 0.3/request


def _ctl(objective=8.0, fill=0.8, confirmed=True):
    c = BatchController(AmortModel.from_spec(AMORT), objective_s=objective, target_fill=fill)
    if confirmed:
        # one on-model observation (ratio 1.0) ends the warm-up: sizing
        # and predictive shedding run on the confirmed curve
        c.observe_batch(1, 0.9)
    return c


def _reqs(now, n, wait=0.5, deadline_s=8.0, interactive=False, prefix="r"):
    return [
        SchedRequest(
            rid=f"{prefix}{i:03d}", t_submit=now - wait - i * 1e-3,
            deadline=(now - wait - i * 1e-3 + deadline_s) if deadline_s else None,
            interactive=interactive,
        )
        for i in range(n)
    ]


def test_ewma_arrival_rate_deterministic():
    c = _ctl()
    now = 1000.0
    # seed: 20 arrivals inside the 10 s tau window -> 2 Hz
    subs = [now - 0.1 - i * 0.4 for i in range(20)]
    assert c.observe_arrivals(now, subs) == pytest.approx(2.0)
    # 10 more arrivals over the next 5 s pulls the EWMA toward 2.0 (same
    # instantaneous rate: stays put)
    subs2 = subs + [now + 0.25 + i * 0.5 for i in range(10)]
    r = c.observe_arrivals(now + 5.0, subs2)
    assert r == pytest.approx(2.0, abs=1e-6)
    # silence decays toward zero, never negative
    r2 = c.observe_arrivals(now + 30.0, [])
    assert 0.0 <= r2 < 0.2


def test_batch_size_monotone_in_load_and_clamped():
    c = _ctl()
    now = 50.0
    sizes = []
    # generous budgets: sizing is the pure load dial (the clamp), and
    # must be monotone — more backlog never shrinks the batch
    for n in (1, 2, 3, 5, 8, 20):
        plan = c.plan(now, _reqs(now, n, deadline_s=60.0), cap=8)
        got = plan.batch_target
        sizes.append(got)
        assert got <= min(8, n)  # clamped to cap and live backlog
    assert sizes == sorted(sizes)
    assert sizes[0] == 1 and sizes[-1] == 8
    # low load = small batch (latency), full budget would admit 8
    assert c.plan(now, _reqs(now, 2), cap=8).batch_target == 2
    # overload with tight budgets: the count-maximizing rule must HOLD
    # throughput (wide-ish batches), not collapse to tiny batches
    # chasing the oldest straggler (head-of-line inversion)
    plan = c.plan(now, _reqs(now, 20, wait=0.5), cap=8)
    assert plan.batch_target >= 4 and plan.batch_reason == "slo"


def test_batch_size_tracks_remaining_budget():
    c = _ctl()
    now = 50.0
    # fresh queue: wide (batch_s(8)=3.0 <= 0.8 * 8)
    assert c.plan(now, _reqs(now, 16, wait=0.1), cap=8).batch_target == 8
    # aged queue (objective pressure, no hard deadline): budget ~2 s ->
    # only batch_s(S) <= 0.8*2 = 1.6 fits -> S=3 (batch_s(3)=1.5)
    plan = c.plan(now, _reqs(now, 16, wait=6.0, deadline_s=0), cap=8)
    assert plan.shed == []  # objective-only work is never predictively shed
    assert plan.batch_target == 3 and plan.batch_reason == "slo"
    # no deadline and no objective: pure throughput, the cap
    c2 = _ctl(objective=0.0)
    plan2 = c2.plan(now, _reqs(now, 16, deadline_s=0), cap=8)
    assert plan2.batch_target == 8 and plan2.batch_reason == "backlog"


def test_interactive_lane_first_and_bounded():
    c = _ctl()
    now = 50.0
    bulk = _reqs(now, 6, prefix="b")
    inter = _reqs(now, 3, wait=0.1, interactive=True, prefix="i")
    plan = c.plan(now, bulk + inter, cap=8)
    assert plan.lanes == {"interactive": 3, "bulk": 6}
    # interactive batches first, never wider than the lane cap, never
    # mixed with bulk
    first = plan.batches[0]
    assert all(r.interactive for r in first)
    assert len(first) <= INTERACTIVE_LANE_CAP
    n_int_batches = sum(1 for b in plan.batches if b[0].interactive)
    assert all(all(r.interactive for r in b) for b in plan.batches[:n_int_batches])
    assert all(not r.interactive for b in plan.batches[n_int_batches:] for r in b)
    assert plan.interactive_target <= INTERACTIVE_LANE_CAP


def test_shed_by_predicted_miss_never_the_feasible():
    c = _ctl()
    c.observe_batch(1, 0.9)  # confirmed model: predictive shed engages
    now = 100.0
    fresh = _reqs(now, 8, wait=0.5)                       # easily feasible
    hopeless = _reqs(now, 3, wait=30.0, prefix="old")     # deadline long gone
    plan = c.plan(now, fresh + hopeless, cap=8)
    shed_rids = {r.rid for r, _why in plan.shed}
    assert shed_rids == {"old000", "old001", "old002"}
    kept = [r.rid for b in plan.batches for r in b]
    assert sorted(kept) == sorted(r.rid for r in fresh)
    # every verdict names the prediction
    assert all("deadline" in why for _r, why in plan.shed)
    # with NOTHING hopeless, nothing is shed — a feasible request is
    # never shed outside the admission cap (16 requests fit the 8 s
    # deadline as two 8-wide batches: 6.0 s optimistic)
    assert c.plan(now + 1, _reqs(now + 1, 16, wait=0.2), cap=8).shed == []


def test_shed_walk_saves_requests_behind_the_hopeless():
    """Removing a hopeless request frees its virtual slot: the walk
    must not count shed requests against the queue positions behind
    them."""
    c = _ctl()
    c.observe_batch(1, 0.9)
    now = 100.0
    # 3 expired + exactly 8 feasible: if the walk charged the expired
    # ones as positions, the tail of the feasible would be mis-shed
    expired = _reqs(now, 3, wait=20.0, prefix="old")
    feasible = _reqs(now, 8, wait=0.3)
    plan = c.plan(now, expired + feasible, cap=8)
    assert {r.rid for r, _ in plan.shed} == {r.rid for r in expired}


def test_admission_cap_sheds_by_least_slack():
    c = _ctl(objective=0.0)  # no objective: slack is inf for everyone
    now = 100.0
    reqs = _reqs(now, 10, deadline_s=0)
    plan = c.plan(now, reqs, cap=8, spool_cap=6)
    assert len(plan.shed) == 4
    kept = [r.rid for b in plan.batches for r in b]
    assert len(kept) == 6
    # all-inf slack: the LAST service positions go (the newest — the
    # static arm's newest-first cap semantics for unbounded work).
    # Service order is oldest-first, and rid index here DESCENDS with
    # age, so the oldest six (r004..r009) survive.
    assert set(kept) == {f"r{i:03d}" for i in range(4, 10)}
    assert all("cap" in why for _r, why in plan.shed)


def test_no_shedding_while_draining():
    c = _ctl()
    now = 100.0
    hopeless = _reqs(now, 3, wait=30.0, prefix="old")
    plan = c.plan(now, hopeless, cap=8, spool_cap=1, allow_shed=False)
    assert plan.shed == []
    assert sum(len(b) for b in plan.batches) == 3


# ------------------------------------------------------- AutoscalePolicy


def test_autoscale_fires_after_sustained_window_only():
    p = AutoscalePolicy(1, 3, scale_up_s=5.0, scale_down_s=10.0)
    growing = {"backlog_growing": True, "backlog": 9}
    assert p.update(0.0, 1, growing) is None
    assert p.update(4.9, 1, growing) is None
    d = p.update(5.0, 1, growing)
    assert d == {"direction": "up", "reason": "backlog_growth"}
    # cooldown: the clock restarted — the next step needs a FULL window
    assert p.update(5.1, 2, growing) is None
    assert p.update(10.2, 2, growing)["direction"] == "up"
    # at the ceiling: condition may persist, no decision
    assert p.update(20.0, 3, growing) is None


def test_autoscale_never_flaps_on_boundary_oscillation():
    p = AutoscalePolicy(1, 3, scale_up_s=2.0, scale_down_s=2.0)
    decisions = []
    for t in range(200):
        on = bool(t % 2)
        decisions.append(p.update(float(t), 2, {
            "backlog_growing": on, "backlog": 5 if on else 0,
        }))
    assert [d for d in decisions if d] == []


def test_autoscale_down_on_sustained_idle_and_floor():
    p = AutoscalePolicy(1, 3, scale_up_s=2.0, scale_down_s=4.0)
    idle = {"backlog_growing": False, "backlog": 0}
    assert p.update(0.0, 2, idle) is None
    d = p.update(4.0, 2, idle)
    assert d == {"direction": "down", "reason": "idle"}
    # at the floor: stays put forever
    p2 = AutoscalePolicy(1, 3, scale_down_s=1.0)
    assert p2.update(0.0, 1, idle) is None
    assert p2.update(50.0, 1, idle) is None


def test_autoscale_burn_condition_and_missing_signals_hold():
    p = AutoscalePolicy(1, 3, scale_up_s=2.0, scale_down_s=10.0, burn_threshold=2.0)
    burn = {"burn_fast": 3.0, "burn_slow": 2.5, "slo_n": 40, "backlog": 3}
    assert p.update(0.0, 1, burn) is None
    assert p.update(2.0, 1, burn) == {"direction": "up", "reason": "slo_burn"}
    # an empty merged window is NOT a burn (no traffic != outage)
    p2 = AutoscalePolicy(1, 3, scale_up_s=1.0)
    empty = {"burn_fast": 5.0, "burn_slow": 5.0, "slo_n": 0, "backlog": 0}
    assert p2.update(0.0, 1, empty) is None
    assert p2.update(5.0, 1, empty) is None
    # missing signals HOLD the pending clock instead of resetting it
    p3 = AutoscalePolicy(1, 3, scale_up_s=4.0, scale_down_s=10.0)
    grow = {"backlog_growing": True, "backlog": 5}
    assert p3.update(0.0, 1, grow) is None
    assert p3.update(2.0, 1, {}) is None          # no data: hold
    assert p3.update(4.0, 1, grow)["direction"] == "up"  # window spans the gap


# ------------------------------------------------ gate + service smoke


def test_sched_gate_fails_closed_and_is_digest_visible(monkeypatch):
    from zkp2p_tpu.utils.audit import execution_digest

    monkeypatch.delenv("ZKP2P_SCHED", raising=False)
    assert sched_mode() == "off"
    monkeypatch.setenv("ZKP2P_SCHED", "junk")
    assert sched_mode() == "off"  # anything unrecognized = the oracle arm
    d_off = execution_digest()
    monkeypatch.setenv("ZKP2P_SCHED", "adaptive")
    assert sched_mode() == "adaptive"
    d_on = execution_digest()
    assert d_off != d_on  # adaptive-vs-off A/Bs are digest-distinguishable
    monkeypatch.setenv("ZKP2P_SCHED", "off")
    sched_mode()
    assert execution_digest() == d_off


@pytest.fixture(scope="module")
def toy_world():
    from zkp2p_tpu.native.lib import get_lib

    if get_lib() is None:
        pytest.skip("native toolchain unavailable")
    return _chaos_mod()._build_world()


def _toy_service(world, **kw):
    from zkp2p_tpu.pipeline.service import ProvingService
    from zkp2p_tpu.prover.native_prove import prove_native_batch

    cs, dpk, vk, witness_fn = world
    kw.setdefault("batch_size", 8)
    kw.setdefault("prover_fn", prove_native_batch)
    return ProvingService(cs, dpk, vk, witness_fn, public_fn=lambda w: [w[1]], **kw)


def _drop(spool, rid, payload, age_s=0.0):
    os.makedirs(spool, exist_ok=True)
    p = os.path.join(spool, rid + ".req.json")
    with open(p, "w") as f:
        json.dump(payload, f)
    if age_s:
        t = time.time() - age_s
        os.utime(p, (t, t))
    return p


def _sink_records(spool):
    with open(spool + ".metrics.jsonl") as f:
        return [json.loads(line) for line in f]


def test_adaptive_sweep_sheds_lanes_and_stamps_targets(toy_world, tmp_path, monkeypatch):
    """The sched-smoke heart: a mini-trace through the REAL service —
    hopeless request shed by prediction, interactive proved in the
    first (small) batch, bulk behind it, batch_size_target + decision
    line recorded."""
    monkeypatch.setenv("ZKP2P_SCHED", "adaptive")
    monkeypatch.setenv("ZKP2P_SCHED_AMORT", "1:0.05,8:0.1")
    monkeypatch.setenv("ZKP2P_SLO_P95_S", "10")
    monkeypatch.setenv("ZKP2P_DEADLINE_S", "10")
    spool = str(tmp_path / "spool")
    for i in range(6):
        _drop(spool, f"b{i}", {"x": 3 + i, "y": 4})
    _drop(spool, "int0", {"x": 5, "y": 6, "priority": "interactive"})
    _drop(spool, "old0", {"x": 7, "y": 8}, age_s=100.0)  # expired long ago
    svc = _toy_service(toy_world)
    stats = svc.process_dir(spool)
    assert stats["done"] == 7 and stats["error-shed"] == 1
    recs = _sink_records(spool)
    reqs = {r["request_id"]: r for r in recs if r.get("type") == "request"}
    assert reqs["old0"]["state"] == "error-shed"
    assert "sched" in reqs["old0"]["error"]
    # interactive lane: a batch of its own, ahead of bulk
    assert reqs["int0"]["state"] == "done"
    assert reqs["int0"]["batch_n"] == 1
    assert reqs["int0"]["batch_size_target"] == 1
    # bulk rode one controller-sized batch of 6
    assert reqs["b0"]["batch_n"] == 6
    assert reqs["b0"]["batch_size_target"] == 6
    # one decision line with the plan's fields
    sched_lines = [r for r in recs if r.get("type") == "sched"]
    assert len(sched_lines) == 1
    line = sched_lines[0]
    assert line["backlog"] == 8 and line["shed"] == 1
    assert line["lanes"] == {"interactive": 1, "bulk": 6}
    assert line["batch_target"] == 6 and line["interactive_target"] == 1
    # heartbeat block for fleet /status + top
    assert svc._sched_hb["mode"] == "adaptive"
    assert svc._sched_hb["lane_interactive"] == 1


def test_off_arm_keeps_static_slicing_and_records_cap_target(toy_world, tmp_path, monkeypatch):
    monkeypatch.setenv("ZKP2P_SCHED", "off")
    monkeypatch.delenv("ZKP2P_DEADLINE_S", raising=False)
    spool = str(tmp_path / "spool")
    for i in range(5):
        _drop(spool, f"b{i}", {"x": 3 + i, "y": 4})
    # priority is IGNORED by the static arm: scan order only
    _drop(spool, "zint", {"x": 5, "y": 6, "priority": "interactive"})
    svc = _toy_service(toy_world, batch_size=4)
    stats = svc.process_dir(spool)
    assert stats["done"] == 6
    recs = _sink_records(spool)
    reqs = {r["request_id"]: r for r in recs if r.get("type") == "request"}
    # static slicing: sorted scan order, batches of 4 then 2
    assert reqs["b0"]["batch_n"] == 4 and reqs["zint"]["batch_n"] == 2
    # the target is the CAP on every record (fill < target = low load)
    assert all(r["batch_size_target"] == 4 for r in reqs.values())
    # no decision lines on the oracle arm
    assert [r for r in recs if r.get("type") == "sched"] == []
    assert svc._sched_hb == {"mode": "off", "batch_target": 4}


def test_adaptive_cap_shed_orders_by_miss_not_newest(toy_world, tmp_path, monkeypatch):
    """Under the admission cap the adaptive arm sheds the requests the
    model predicts cannot finish — the aged ones — where the static arm
    sheds newest-first."""
    monkeypatch.setenv("ZKP2P_SCHED", "adaptive")
    monkeypatch.setenv("ZKP2P_SCHED_AMORT", "1:1.0,8:2.0")
    monkeypatch.setenv("ZKP2P_DEADLINE_S", "6")
    spool = str(tmp_path / "spool")
    for i in range(4):
        _drop(spool, f"fresh{i}", {"x": 3 + i, "y": 4})
    for i in range(2):
        _drop(spool, f"aged{i}", {"x": 9, "y": 4 + i}, age_s=5.5)  # ~0.5 s budget left
    svc = _toy_service(toy_world, batch_size=4, spool_cap=3)
    stats = svc.process_dir(spool)
    recs = _sink_records(spool)
    reqs = {r["request_id"]: r for r in recs if r.get("type") == "request"}
    shed = {rid for rid, r in reqs.items() if r["state"] == "error-shed"}
    # the aged pair is hopeless (predicted completion past deadline) and
    # the cap trims ONE more by least slack — never a fresh one ahead of
    # a doomed one
    assert {"aged0", "aged1"} <= shed
    assert len(shed) == 3
    assert stats["done"] == 3


def test_timeseries_line_carries_batch_size_target(toy_world, tmp_path, monkeypatch):
    from zkp2p_tpu.pipeline.service import TimeseriesSampler

    monkeypatch.setenv("ZKP2P_SCHED", "adaptive")
    monkeypatch.setenv("ZKP2P_SCHED_AMORT", "1:0.05,8:0.1")
    spool = str(tmp_path / "spool")
    for i in range(3):
        _drop(spool, f"b{i}", {"x": 3 + i, "y": 4})
    svc = _toy_service(toy_world)
    svc._sampler = TimeseriesSampler(interval_s=1000.0)
    svc.process_dir(spool)
    rec = svc._sampler.maybe_sample(spool, svc._sink(spool), force=True)
    assert rec is not None and rec["batch_size_target"] == 3


# ---------------------------------------------------- fleet autoscale demo


def test_fleet_autoscale_grows_on_spike_and_drains_back(tmp_path, monkeypatch):
    """The acceptance demo: a 1-worker toy fleet under a backlog spike
    scales to 2 (backlog_growth sustained), drains back to 1 on idle,
    with zero lost / zero duplicated proofs and the events on record."""
    import sys as _sys

    from zkp2p_tpu.native.lib import get_lib

    if get_lib() is None:
        pytest.skip("native toolchain unavailable")
    from zkp2p_tpu.pipeline.fleet import FleetSupervisor
    from zkp2p_tpu.utils.metrics import REGISTRY

    chaos = _chaos_mod()
    spool = str(tmp_path / "spool")
    fleet_dir = str(tmp_path / "fleet")
    os.makedirs(spool, exist_ok=True)
    monkeypatch.setenv("JAX_PLATFORMS", "cpu")
    monkeypatch.delenv("PALLAS_AXON_POOL_IPS", raising=False)
    # fast trend + scrape windows so the demo fits a test budget
    monkeypatch.setenv("ZKP2P_FLEET_SCRAPE_S", "0.3")
    monkeypatch.setenv("ZKP2P_ALERT_FOR_S", "0.9")
    worker_argv = [
        _sys.executable, CHAOS, "--worker", "--linger",
        "--spool", spool, "--batch", "2", "--prove-s", "0.35",
        "--max-seconds", "120", "--poll-s", "0.05",
    ]
    sup = FleetSupervisor(
        spool, lambda wid: list(worker_argv),
        workers=1, fleet_dir=fleet_dir,
        workers_min=1, workers_max=2,
        scale_up_s=0.8, scale_down_s=2.5,
        drain_timeout_s=30.0,
        fleet_metrics_port=0,
        log=lambda m: None,
    )
    rng_reqs = []
    try:
        sup.start()
        t_end = time.time() + 60.0
        i = 0
        scaled_up = False
        # feed a spike until the supervisor scales up (or time out)
        while time.time() < t_end:
            if i < 30:
                with open(os.path.join(spool, f"s{i:03d}.req.json"), "w") as f:
                    json.dump({"x": 3 + (i % 40), "y": 5}, f)
                rng_reqs.append(f"s{i:03d}")
                i += 1
            sup.tick()
            if len(sup.slots) > 1:
                scaled_up = True
                break
            time.sleep(0.1)
        assert scaled_up, "fleet never scaled up under a growing backlog"
        up_events = [e for e in sup._scale_events if e["direction"] == "up"]
        assert up_events and up_events[0]["reason"] in ("backlog_growth", "slo_burn")
        # let the spike drain, then idle long enough for a scale-down
        t_end = time.time() + 90.0
        scaled_down = False
        while time.time() < t_end:
            sup.tick()
            live = sup._live_workers()
            if any(e["direction"] == "down" for e in sup._scale_events) and len(live) == 1:
                scaled_down = True
                break
            time.sleep(0.1)
        assert scaled_down, "fleet never drained back down on sustained idle"
        # status.json carries the sched block + events
        with open(os.path.join(fleet_dir, "status.json")) as f:
            status = json.load(f)
        assert status["sched"]["autoscale"] is True
        assert status["sched"]["scale_events"] >= 2
        assert status["sched"]["last_scale"]["direction"] == "down"
        # decisions visible in metrics
        kinds = {
            (m["labels"].get("kind")): m["value"]
            for m in REGISTRY.snapshot()
            if m["name"] == "zkp2p_sched_decisions_total"
        }
        assert kinds.get("scale_up", 0) >= 1 and kinds.get("scale_down", 0) >= 1
    finally:
        sup.drain()
        if sup.plane is not None:
            sup.plane.stop()
    # zero lost, zero duplicated: every request exactly one terminal,
    # every proof pairing-verifies (the PR-7 invariant)
    deadline = time.time() + 30.0
    from zkp2p_tpu.pipeline.service import spool_terminal

    while time.time() < deadline and not spool_terminal(spool):
        time.sleep(0.2)
    report = chaos.check_invariants(spool)
    assert report["violations"] == [], report["violations"]
    assert report["states"].get("done", 0) == len(rng_reqs)


def test_top_renders_sched_block():
    """`zkp2p-tpu top` renders per-worker batch targets + lane depths
    and the autoscale state out of the fleet /status payload."""
    from zkp2p_tpu.pipeline.fleet_obs import render_top

    body = {
        "ok": True, "fleet_id": "fdemo",
        "workers": {
            "w0": {"state": "up", "sched": {
                "mode": "adaptive", "batch_target": 4,
                "lane_interactive": 1, "lane_bulk": 7,
            }},
            "w1": {"state": "up", "sched": {"mode": "off", "batch_target": 8}},
        },
        "sched": {
            "autoscale": True, "workers_min": 1, "workers_max": 4,
            "workers_live": 2, "scale_events": 3,
            "last_scale": {"direction": "up", "reason": "backlog_growth",
                           "workers": 2, "ts": 123.0},
        },
    }
    frame = render_top(body)
    assert "w0[adaptive] tgt=4 lanes i1/b7" in frame
    assert "w1[off] tgt=8" in frame
    assert "autoscale: 2 live in [1..4]" in frame
    assert "last up (backlog_growth) -> 2" in frame
    # no sched data = no sched lines, not a crash
    assert "sched:" not in render_top({"ok": False, "workers": {}})


def test_fleet_parallelism_scales_predictions():
    """N workers pull ONE queue: with parallelism=N the shed walk and
    sizing divide positions by N — a worker must never shed (or
    undersize for) requests its peers could still serve."""
    c = _ctl()
    c.observe_batch(1, 0.9)  # confirm the model so predictive shed engages
    now = 100.0
    reqs = _reqs(now, 20, wait=0.2)
    solo = c.plan(now, reqs, cap=8)
    c2 = _ctl()
    c2.observe_batch(1, 0.9)
    fleet = c2.plan(now + 0.001, reqs, cap=8, parallelism=4)
    # solo: the tail of 20 cannot finish alone; 4 peers: everything fits
    assert len(solo.shed) >= 1
    assert fleet.shed == []
    # sizing under pressure: positions /4 relax the count constraint so
    # the chosen batch is at least as wide
    aged = _reqs(now, 16, wait=5.0)
    ca, cb = _ctl(), _ctl()
    ca.observe_batch(1, 0.9)
    cb.observe_batch(1, 0.9)
    s_solo = ca.plan(now, aged, cap=8)
    s_fleet = cb.plan(now, aged, cap=8, parallelism=4)
    assert s_fleet.batch_target >= s_solo.batch_target
    assert len(s_fleet.shed) <= len(s_solo.shed)


def test_online_calibration_and_warmup_guard():
    """The static curve can be arbitrarily wrong for this circuit/host:
    before any real batch is observed, predictive shedding trusts only
    the model-free truth (deadline already passed); after observation,
    the EWMA scale pulls predictions toward measured reality."""
    c = _ctl(confirmed=False)
    now = 100.0
    fresh = _reqs(now, 20, wait=0.2)  # tail predicted-infeasible IF the model is right
    # uncalibrated: NOT expired -> never shed, however wrong the curve
    assert c.plan(now, fresh, cap=8).shed == []
    # already-expired requests shed even uncalibrated (now >= deadline)
    expired = _reqs(now, 2, wait=30.0, prefix="old")
    assert len(c.plan(now + 0.001, expired + fresh, cap=8).shed) == 2
    # observe a batch 10x CHEAPER than the model: scale drops, the
    # 20-request tail becomes feasible and stays unshed after
    # calibration too
    c.observe_batch(4, 0.18)  # model says 1.8 s -> ratio 0.1
    assert c.calibrated and c.model_scale == pytest.approx(0.1)
    assert c.plan(now + 0.002, fresh, cap=8).shed == []
    # observe a batch 2x the model: scale climbs toward it (EWMA)
    c.observe_batch(4, 3.6)
    assert 0.1 < c.model_scale < 2.0
    # a wildly slow outlier is clamped, not adopted verbatim
    c2 = _ctl(confirmed=False)
    c2.observe_batch(1, 9999.0)
    assert c2.model_scale <= 50.0
    # warm-up SIZING acts like the static arm (take the cap), never the
    # distrusted model's per-proof argmin
    c3 = _ctl(confirmed=False)
    warm = c3.plan(now + 1.0, _reqs(now + 1.0, 12, wait=0.1), cap=8)
    assert warm.batch_target == 8 and warm.batch_reason == "warmup"
