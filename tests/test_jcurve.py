"""Vectorised G1/G2 Jacobian ops vs the host curve oracle.

Differential testing mirrors the reference's trust chain: snarkjs point ops
are checked against the EVM precompiles on-chain; here the TPU lanes are
checked against `zkp2p_tpu.curve.host` (itself pairing-tested)."""

import random

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from zkp2p_tpu.curve import host
from zkp2p_tpu.curve.host import (
    G1_GENERATOR,
    G2_GENERATOR,
    g1_add,
    g1_double,
    g1_mul,
    g1_neg,
    g2_add,
    g2_double,
    g2_mul,
    g2_neg,
)
from zkp2p_tpu.curve.jcurve import (
    G1J,
    G2J,
    g1_jac_to_host,
    g1_to_affine_arrays,
    g2_jac_to_host,
    g2_to_affine_arrays,
    scalar_bit_planes,
)
from zkp2p_tpu.field.bn254 import R

# XLA-compile-heavy: opt-in via ZKP2P_RUN_SLOW=1 (default suite must stay
# minutes on a 1-core host; the dryrun/bench paths exercise this code too)
pytestmark = pytest.mark.slow

rng = random.Random(99)


def rand_g1(n):
    return [g1_mul(G1_GENERATOR, rng.randrange(1, R)) for _ in range(n)]


def rand_g2(n):
    return [g2_mul(G2_GENERATOR, rng.randrange(1, R)) for _ in range(n)]


CASES = [
    ("g1", G1J, rand_g1, g1_to_affine_arrays, g1_jac_to_host, g1_add, g1_double, g1_mul, g1_neg),
    ("g2", G2J, rand_g2, g2_to_affine_arrays, g2_jac_to_host, g2_add, g2_double, g2_mul, g2_neg),
]


@pytest.mark.parametrize(
    "curve,to_arrays,to_host,h_add,h_double,h_mul,h_neg,mk",
    [(c[1], c[3], c[4], c[5], c[6], c[7], c[8], c[2]) for c in CASES],
    ids=[c[0] for c in CASES],
)
def test_add_double_cases(curve, to_arrays, to_host, h_add, h_double, h_mul, h_neg, mk):
    pts = mk(4)
    # Lane layout exercises every branch of the complete adder:
    # random+random, P+P (double path), P+(-P) (infinity), inf+Q, P+inf, inf+inf.
    a_pts = [pts[0], pts[1], pts[2], None, pts[3], None]
    b_pts = [pts[1], pts[1], h_neg(pts[2]), pts[0], None, None]
    a = curve.from_affine(to_arrays(a_pts))
    b = curve.from_affine(to_arrays(b_pts))

    got = to_host(jax.jit(curve.add)(a, b))
    want = [h_add(x, y) for x, y in zip(a_pts, b_pts)]
    assert got == want

    got_mixed = to_host(jax.jit(curve.add_mixed)(a, to_arrays(b_pts)))
    assert got_mixed == want

    got_dbl = to_host(jax.jit(curve.double)(a))
    assert got_dbl == [h_double(x) for x in a_pts]


@pytest.mark.parametrize(
    "curve,to_arrays,to_host,h_mul,mk",
    [(c[1], c[3], c[4], c[7], c[2]) for c in CASES],
    ids=[c[0] for c in CASES],
)
def test_scalar_mul_batch(curve, to_arrays, to_host, h_mul, mk):
    n = 4
    pts = mk(n)
    scalars = [rng.randrange(R) for _ in range(n - 2)] + [0, 1]
    p = curve.from_affine(to_arrays(pts))
    bits = scalar_bit_planes(scalars)
    got = to_host(jax.jit(curve.scalar_mul)(p, bits))
    assert got == [h_mul(pt, k) for pt, k in zip(pts, scalars)]


def test_g1_add_associativity_device_only():
    """(A+B)+C == A+(B+C) computed entirely on device."""
    pts = rand_g1(3)
    arrs = [G1J.from_affine(g1_to_affine_arrays([p])) for p in pts]
    lhs = G1J.add(G1J.add(arrs[0], arrs[1]), arrs[2])
    rhs = G1J.add(arrs[0], G1J.add(arrs[1], arrs[2]))
    assert g1_jac_to_host(lhs) == g1_jac_to_host(rhs)
