"""The fleet observability plane (pipeline.fleet_obs + utils.alerts +
the mergeable SLO windows in utils.slo), tier-1 (`make fleet-obs-smoke`):

  * federation aggregation rules — counters SUM across workers, gauges
    get per-worker labels, histograms bucket-merge, and a bucket-layout
    mismatch is REFUSED (skipped + counted), never mis-binned;
  * mergeable SLO — merged-sample percentiles pinned EXACTLY against a
    pooled oracle tracker (never averaged snapshots), fleet sample
    count = sum of worker windows, fast/slow multi-window burn split;
  * alert engine — fires only after `for_s`, one FIRE per episode under
    a flapping signal (hysteresis), clears only after `clear_s` clean,
    missing signals hold state, breaker park fires restart_storm
    immediately;
  * fleet `/status` fail-closed — 503-shaped (ok=False) while any live
    worker is unreachable or unarmed, ready only when every live worker
    has armed its gates;
  * cross-worker forensics — chrome-trace FLOW events stitch a
    defer→takeover across worker pids, `--request` renders the hop
    timeline, `--fleet-dir` discovers a fleet run's sinks;
  * the 2-worker plane smoke — real supervisor + toy workers: fleet
    /metrics + /status scrape 200, merged request counters equal the
    per-worker sums AND the proof artifacts, merged SLO n equals the
    sum of worker windows, `--fleet-dir` trace renders valid JSON.
"""

import importlib.util
import json
import os
import re
import sys
import threading
import time
import urllib.error
import urllib.request

import pytest

from zkp2p_tpu.pipeline.fleet_obs import FleetPlane, merge_worker_metrics, render_top
from zkp2p_tpu.utils.alerts import AlertEngine, TrendTracker, fleet_rules
from zkp2p_tpu.utils.config import load_config
from zkp2p_tpu.utils.metrics import Registry
from zkp2p_tpu.utils.slo import SloTracker, merge_window_states

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
CHAOS = os.path.join(REPO, "tools", "chaos.py")


def _trace_report():
    sys.path.insert(0, os.path.join(REPO, "tools"))
    try:
        import trace_report
    finally:
        sys.path.pop(0)
    return trace_report


# ------------------------------------------------- federation merge rules


def _worker_registry(done: int, backlog: float, fills) -> Registry:
    r = Registry()
    r.counter("zkp2p_service_requests_total", {"state": "done"}).inc(done)
    r.gauge("zkp2p_service_backlog").set(backlog)
    h = r.histogram("zkp2p_service_batch_fill", buckets=(1, 2, 4, 8))
    for f in fills:
        h.observe(f)
    return r


def test_merge_counter_sum_gauge_label_histogram_buckets():
    fleet = Registry()
    merge_worker_metrics(fleet, _worker_registry(3, 4, [1, 2]).snapshot(), "w0")
    merge_worker_metrics(fleet, _worker_registry(5, 7, [2, 8]).snapshot(), "w1")
    snap = {(m["name"], tuple(sorted(m["labels"].items()))): m for m in fleet.snapshot()}
    # counters SUM (labels preserved, no worker label — fleet totals)
    c = snap[("zkp2p_service_requests_total", (("state", "done"),))]
    assert c["kind"] == "counter" and c["value"] == 8
    # gauges get per-worker labels (attribution, never summed/maxed)
    g0 = snap[("zkp2p_service_backlog", (("worker", "w0"),))]
    g1 = snap[("zkp2p_service_backlog", (("worker", "w1"),))]
    assert g0["value"] == 4 and g1["value"] == 7
    # histograms bucket-merge: counts add positionally
    h = snap[("zkp2p_service_batch_fill", ())]
    assert h["count"] == 4 and h["sum"] == 13
    assert h["counts"][0] == 1 and h["counts"][1] == 2 and h["counts"][3] == 1


def test_merge_refuses_histogram_bucket_mismatch():
    fleet = Registry()
    merge_worker_metrics(fleet, _worker_registry(1, 0, [1]).snapshot(), "w0")
    bad = Registry()
    bad.histogram("zkp2p_service_batch_fill", buckets=(10, 20)).observe(15)
    bad.counter("zkp2p_service_requests_total", {"state": "done"}).inc(2)
    refused = []
    merge_worker_metrics(fleet, bad.snapshot(), "w1", refused=refused.append)
    # the mismatched family was refused, the rest of the snapshot merged
    assert refused == ["zkp2p_service_batch_fill"]
    snap = {(m["name"], tuple(sorted(m["labels"].items()))): m for m in fleet.snapshot()}
    assert snap[("zkp2p_service_requests_total", (("state", "done"),))]["value"] == 3
    assert snap[("zkp2p_service_batch_fill", ())]["count"] == 1  # w0's, untouched


def test_registry_merge_raises_on_bucket_mismatch():
    """The underlying Registry.merge path REFUSES loudly — the fleet
    layer's counted skip is built on this refusal, not instead of it."""
    a = Registry()
    a.histogram("h", buckets=(1, 2)).observe(1)
    b = Registry()
    b.histogram("h", buckets=(3, 4)).observe(3)
    with pytest.raises(ValueError, match="bucket layout mismatch"):
        a.merge(b.snapshot())


# ------------------------------------------------------- mergeable SLO


def test_merged_window_equals_pooled_oracle():
    """THE merge contract: merging N serialized windows reproduces what
    ONE tracker observing every worker's traffic would report — exact
    attainment and exact percentiles, not averaged snapshots."""
    import random

    rng = random.Random(7)
    oracle = SloTracker(objective_s=2.0, target=0.9, window_s=300.0, clock=lambda: 100.0)
    workers = [
        SloTracker(objective_s=2.0, target=0.9, window_s=300.0, clock=lambda: 100.0)
        for _ in range(3)
    ]
    for i in range(200):
        w = workers[i % 3]
        lat = rng.uniform(0.1, 4.0)
        ok = rng.random() < 0.9
        t = rng.uniform(0.0, 100.0)
        w.observe(lat, ok=ok, now=t)
        oracle.observe(lat, ok=ok, now=t)
    merged = merge_window_states([w.window_state(now=100.0) for w in workers])
    want = oracle.snapshot(now=100.0)
    assert merged["n"] == want["n"] == 200
    assert merged["good"] == want["good"]
    assert abs(merged["attainment"] - want["attainment"]) < 1e-9
    assert merged["p50_s"] == want["p50_s"]
    assert merged["p95_s"] == want["p95_s"]
    assert merged["max_s"] == want["max_s"]
    assert abs(merged["burn_slow"] - want["burn_rate"]) < 1e-6


def test_merged_is_not_an_average_of_snapshots():
    """An idle worker (empty window, vacuous attainment 1.0) must not
    dilute a drowning worker's attainment — the classic averaged-
    snapshot bug the pooled merge exists to prevent."""
    idle = SloTracker(objective_s=1.0, clock=lambda: 0.0)
    busy = SloTracker(objective_s=1.0, clock=lambda: 0.0)
    for _ in range(10):
        busy.observe(5.0, ok=True, now=0.0)  # all over objective: misses
    merged = merge_window_states(
        [idle.window_state(now=0.0), busy.window_state(now=0.0)]
    )
    assert merged["attainment"] == 0.0  # not (1.0 + 0.0) / 2
    assert merged["workers"] == 2 and merged["n"] == 10


def test_window_state_cap_keeps_true_n():
    t = SloTracker(objective_s=0.0, clock=lambda: 50.0)
    for i in range(100):
        t.observe(0.1, ok=True, now=float(i % 50))
    st = t.window_state(max_samples=30, now=50.0)
    assert st["n"] == 100 and len(st["samples"]) == 30 and st["dropped"] == 70
    merged = merge_window_states([st])
    assert merged["n"] == 100 and merged["n_merged"] == 30


def test_fast_slow_burn_split():
    """Old samples good, trailing `fast_window_s` all bad: burn_fast
    maxes out while burn_slow stays diluted — the multi-window pair."""
    t = SloTracker(objective_s=1.0, target=0.95, window_s=300.0, clock=lambda: 200.0)
    for i in range(90):
        t.observe(0.2, ok=True, now=float(i))       # ages 110..200: good
    for i in range(10):
        t.observe(5.0, ok=True, now=195.0 + i / 10)  # ages < 60: misses
    merged = merge_window_states([t.window_state(now=200.0)], fast_window_s=60.0)
    assert merged["n_fast"] == 10
    assert merged["burn_fast"] == pytest.approx((1.0 - 0.0) / 0.05)
    assert merged["burn_slow"] == pytest.approx((10 / 100) / 0.05)


# ----------------------------------------------------------- alert engine


def _engine(rules, **cfg_env):
    env = {
        "ZKP2P_ALERT_FOR_S": "5", "ZKP2P_ALERT_CLEAR_S": "10",
        "ZKP2P_ALERT_BURN_RATE": "2", "ZKP2P_ALERT_RESTARTS": "3",
        "ZKP2P_ALERT_HB_GAP_S": "15",
    }
    env.update({k: str(v) for k, v in cfg_env.items()})
    cfg = load_config(environ=env)
    reg = Registry()
    log = []
    eng = AlertEngine(rules if rules is not None else fleet_rules(cfg),
                      registry=reg, log=log.append)
    return eng, reg, log


def _alert_count(reg, rule):
    for m in reg.snapshot():
        if m["name"] == "zkp2p_fleet_alerts_total" and m["labels"].get("rule") == rule:
            return m["value"]
    return 0


def test_alert_fires_after_for_s_not_before():
    eng, reg, log = _engine(None)
    sig = {"burn_fast": 5.0, "burn_slow": 5.0, "slo_n": 100}
    assert eng.evaluate(sig, now=0.0) == []          # pending, not firing
    assert eng.active() == []
    assert eng.evaluate(sig, now=4.0) == []          # still inside for_s
    trs = eng.evaluate(sig, now=5.0)                 # held 5 s: fires
    assert [t["event"] for t in trs] == ["fired"] and trs[0]["rule"] == "slo_burn"
    assert [a["rule"] for a in eng.active()] == ["slo_burn"]
    assert _alert_count(reg, "slo_burn") == 1
    assert any("FIRED" in m for m in log)


def test_alert_hysteresis_flapping_raises_one_alert():
    """A signal crossing its threshold every tick: ONE fire, no clear —
    the stream-of-pages failure mode the hysteresis exists to stop."""
    eng, reg, _ = _engine(None)
    on = {"burn_fast": 5.0, "burn_slow": 5.0, "slo_n": 100}
    off = {"burn_fast": 0.0, "burn_slow": 0.0, "slo_n": 100}
    eng.evaluate(on, now=0.0)
    eng.evaluate(on, now=5.0)                        # fires
    assert _alert_count(reg, "slo_burn") == 1
    transitions = []
    for i in range(20):                              # flap every second
        t = 6.0 + i
        transitions += eng.evaluate(on if i % 2 else off, now=t)
    assert transitions == []                         # still the SAME episode
    assert _alert_count(reg, "slo_burn") == 1
    assert eng.active()                              # never cleared mid-flap


def test_alert_clears_only_after_clear_s_clean():
    eng, reg, log = _engine(None)
    on = {"burn_fast": 5.0, "burn_slow": 5.0, "slo_n": 100}
    off = {"burn_fast": 0.0, "burn_slow": 0.0, "slo_n": 100}
    eng.evaluate(on, now=0.0)
    eng.evaluate(on, now=5.0)
    assert eng.evaluate(off, now=6.0) == []          # clean, but < clear_s
    assert eng.active()
    trs = eng.evaluate(off, now=16.0)                # clean for 10 s: clears
    assert [t["event"] for t in trs] == ["cleared"]
    assert eng.active() == []
    # a fresh episode after the clear fires AGAIN (new counter inc)
    eng.evaluate(on, now=20.0)
    eng.evaluate(on, now=25.0)
    assert _alert_count(reg, "slo_burn") == 2
    assert eng.state()["slo_burn"]["fired_count"] == 2


def test_missing_signal_holds_state():
    eng, reg, _ = _engine(None)
    on = {"burn_fast": 5.0, "burn_slow": 5.0, "slo_n": 100}
    eng.evaluate(on, now=0.0)
    eng.evaluate(on, now=5.0)
    assert eng.active()
    # scrape gap: no burn data at all — the alert must neither clear
    # nor re-fire on absence of evidence
    for i in range(50):
        assert eng.evaluate({}, now=6.0 + i) == []
    assert eng.active() and _alert_count(reg, "slo_burn") == 1


def test_empty_slo_window_never_burns():
    eng, _, _ = _engine(None)
    # burn 20 on an EMPTY window is vacuous (no traffic != outage)
    sig = {"burn_fast": 20.0, "burn_slow": 20.0, "slo_n": 0}
    for t in range(20):
        eng.evaluate(sig, now=float(t))
    assert eng.active() == []


def test_restart_storm_fires_immediately_on_park():
    eng, reg, _ = _engine(None)
    trs = eng.evaluate({"parked": 1, "restarts_recent": 0}, now=0.0)
    assert [t["rule"] for t in trs] == ["restart_storm"]
    assert _alert_count(reg, "restart_storm") == 1
    # and on restarts over threshold without a park
    eng2, reg2, _ = _engine(None)
    assert eng2.evaluate({"parked": 0, "restarts_recent": 2}, now=0.0) == []
    assert [t["rule"] for t in eng2.evaluate({"parked": 0, "restarts_recent": 3}, now=1.0)] \
        == ["restart_storm"]


def test_heartbeat_gap_and_governor_rules():
    eng, _, _ = _engine(None)
    assert [t["rule"] for t in eng.evaluate({"hb_gap_s": 20.0}, now=0.0)] == ["heartbeat_gap"]
    eng2, _, _ = _engine(None)
    sig = {"degraded": 1}
    assert eng2.evaluate(sig, now=0.0) == []         # lingering = held for_s
    assert [t["rule"] for t in eng2.evaluate(sig, now=5.0)] == ["governor_degrade"]


def test_trend_tracker_growth_and_delta():
    tr = TrendTracker(keep_s=100.0)
    assert tr.growing(10.0, now=0.0) is None         # no history: hold
    tr.update(0.0, 2.0)
    assert tr.growing(10.0, now=0.0) is None         # span too short, value > 0
    for t in range(1, 12):
        tr.update(float(t), 2.0 + t)
    assert tr.growing(10.0, now=11.0) is True
    assert tr.delta(10.0, now=11.0) == pytest.approx(10.0)
    flat = TrendTracker(keep_s=100.0)
    for t in range(12):
        flat.update(float(t), 5.0)
    assert flat.growing(10.0, now=11.0) is False
    empty = TrendTracker(keep_s=100.0)
    empty.update(0.0, 0.0)
    assert empty.growing(10.0, now=0.0) is False     # zero is a confident no


# ------------------------------------------------- fail-closed fleet status


class _FakeProc:
    def __init__(self, alive=True):
        self._alive = alive
        self.pid = 4242

    def poll(self):
        return None if self._alive else 1


class _FakeSlot:
    def __init__(self, wid, state="up", alive=True, restarts=0):
        self.wid = wid
        self.state = state
        self.proc = _FakeProc(alive) if state not in ("parked",) else None
        self.restarts = restarts
        self.last_rc = None


class _FakeSup:
    def __init__(self, spool, slots, hbs=None):
        self.spool = spool
        self.slots = {s.wid: s for s in slots}
        self.hbs = hbs or {}
        self.log = lambda m: None

    def _hb(self, slot):
        return self.hbs.get(slot.wid)

    def _hb_age_s(self, slot):
        hb = self.hbs.get(slot.wid)
        return 0.1 if hb else None

    def status(self):
        return {"type": "fleet_status", "fleet_id": "ftest", "workers": {}, "draining": False}


def _plane(sup, monkeypatch=None, snapshots=None):
    plane = FleetPlane(sup, port=0, scrape_s=0.5, clock=time.time)
    if snapshots is not None:
        plane._fetch_snapshot = lambda port: snapshots.get(port)
    return plane


def test_status_fails_closed_until_every_live_worker_armed(tmp_path):
    spool = str(tmp_path / "spool")
    os.makedirs(spool)
    hbs = {"w0": {"port": 1001}, "w1": {"port": 1002}}
    sup = _FakeSup(spool, [_FakeSlot("w0"), _FakeSlot("w1")], hbs)
    armed = {"armed": True, "metrics": [], "slo_window": None}
    unarmed = {"armed": False, "metrics": [], "slo_window": None}

    # one worker unreachable -> NOT ready (and the failure is counted)
    plane = _plane(sup, snapshots={1001: dict(armed)})
    view = plane.scrape_once()
    assert view["ready"] is False and "unreachable" in view["reason"]
    body = plane.status_payload()
    assert body["ok"] is False and body["reason"]

    # reachable but unarmed -> NOT ready (the PR-8 fail-closed rule,
    # fleet-wide: nobody preflighted that worker's gates)
    plane = _plane(sup, snapshots={1001: dict(armed), 1002: dict(unarmed)})
    view = plane.scrape_once()
    assert view["ready"] is False and "armed" in view["reason"]

    # every live worker armed -> ready, /status would be 200
    plane = _plane(sup, snapshots={1001: dict(armed), 1002: dict(armed)})
    view = plane.scrape_once()
    assert view["ready"] is True
    assert plane.status_payload()["ok"] is True

    # no live workers at all -> fail closed again
    sup_dead = _FakeSup(spool, [_FakeSlot("w0", state="done", alive=False)])
    plane = _plane(sup_dead, snapshots={})
    view = plane.scrape_once()
    assert view["ready"] is False and view["reason"] == "no live workers"


def test_scrape_merges_heartbeat_slo_fallback(tmp_path):
    """A worker whose /snapshot scrape fails still contributes its
    heartbeat-carried SLO window — fleet attainment degrades to
    slightly-stale, not to a worker-shaped hole."""
    spool = str(tmp_path / "spool")
    os.makedirs(spool)
    t = SloTracker(objective_s=1.0, clock=time.monotonic)
    for _ in range(4):
        t.observe(0.5, ok=True)
    hbs = {"w0": {"port": 1001, "slo_window": t.window_state()}}
    sup = _FakeSup(spool, [_FakeSlot("w0")], hbs)
    plane = _plane(sup, snapshots={})  # scrape always fails
    view = plane.scrape_once()
    assert view["ready"] is False              # unreachable: NOT ready...
    assert view["slo"]["n"] == 4               # ...but the window merged


# --------------------------------------------------- forensics (synthetic)


def _two_attempt_records():
    return [
        {"type": "request", "request_id": "q1", "state": "deferred", "pid": 100,
         "worker": "w0", "ts": 1010.0, "t_submit": 1000.0, "t_claim": 1001.0,
         "queue_wait_s": 1.0, "deferred_reason": "transient emit failure",
         "spans": [{"name": "witness", "t0": 1001.0, "ms": 50.0},
                   {"name": "prove", "t0": 1002.0, "ms": 800.0}]},
        {"type": "request", "request_id": "q1", "state": "done", "pid": 200,
         "worker": "w1", "ts": 1020.0, "t_submit": 1000.0, "t_claim": 1015.0,
         "queue_wait_s": 15.0,
         "spans": [{"name": "prove", "t0": 1015.5, "ms": 700.0}]},
        {"type": "request", "request_id": "q2", "state": "done", "pid": 100,
         "worker": "w0", "ts": 1005.0, "t_submit": 1000.0, "t_claim": 1001.0,
         "queue_wait_s": 1.0, "spans": [{"name": "prove", "t0": 1001.5, "ms": 100.0}]},
    ]


def test_chrome_trace_flow_events_stitch_attempts_across_pids():
    tr = _trace_report()
    trace = tr.chrome_trace(_two_attempt_records())
    flows = [e for e in trace["traceEvents"] if e.get("ph") in ("s", "f")]
    assert len(flows) == 2                      # one hop = one s/f pair
    s, f = sorted(flows, key=lambda e: e["ph"], reverse=True)  # s then f
    assert s["ph"] == "s" and f["ph"] == "f" and f.get("bp") == "e"
    assert s["id"] == f["id"]
    assert s["pid"] == 100 and f["pid"] == 200  # across worker processes
    assert "takeover" in s["name"]
    assert f["ts"] > s["ts"] >= 0
    json.loads(json.dumps(trace))               # valid, serializable
    # single-attempt requests get no flow events
    only_q2 = tr.chrome_trace([r for r in _two_attempt_records() if r["request_id"] == "q2"])
    assert not [e for e in only_q2["traceEvents"] if e.get("ph") in ("s", "f")]


def test_request_timeline_shows_takeover_and_queue_wait():
    tr = _trace_report()
    out = tr.request_timeline(_two_attempt_records(), "q1")
    assert "2 attempt(s)" in out
    assert "TAKEOVER" in out
    assert "queue_wait 15.000s" in out
    assert "w0 (pid 100)" in out and "w1 (pid 200)" in out
    assert "deferred (transient emit failure)" in out and "-> done" in out
    assert "(no records" in tr.request_timeline([], "nope")


def test_fleet_dir_sink_discovery(tmp_path):
    tr = _trace_report()
    spool = tmp_path / "spool"
    fleet_dir = spool / ".fleet"
    os.makedirs(fleet_dir)
    sink = str(spool) + ".metrics.jsonl"
    for p in (sink, sink + ".1"):
        with open(p, "w") as f:
            f.write("")
    with open(fleet_dir / "status.json", "w") as f:
        json.dump({"spool": str(spool)}, f)
    with open(fleet_dir / "extra.jsonl", "w") as f:
        f.write("")
    found = tr.fleet_sinks(str(fleet_dir))
    assert sink in found and sink + ".1" in found
    assert str(fleet_dir / "extra.jsonl") in found
    # no status.json: falls back to the directory layout
    os.unlink(fleet_dir / "status.json")
    assert sink in tr.fleet_sinks(str(fleet_dir))


def test_render_top_frame():
    body = {
        "ok": True, "fleet_id": "f1", "draining": False,
        "slo": {"attainment": 0.97, "burn_fast": 0.5, "burn_slow": 0.2,
                "p95_s": 1.25, "objective_p95_s": 2.0, "n": 42, "workers": 2},
        "signals": {"backlog": 3, "restarts_recent": 0, "parked": 0, "degraded": 0},
        "alerts": [{"rule": "slo_burn", "detail": "burning", "since": 1.0}],
        "workers": {"w0": {"state": "up", "pid": 1, "port": 1001, "restarts": 0,
                           "rss_mb": 100.0, "hb_age_s": 0.2, "degraded": False}},
        "scrape": {"cycles": 9, "interval_s": 2.0, "last_ts": 123.0},
    }
    out = render_top(body)
    assert "READY" in out and "attainment 0.9700" in out
    assert "ALERT slo_burn" in out and "w0" in out and "9 cycle(s)" in out
    assert "NOT READY" in render_top({"ok": False, "reason": "no live workers"})


# --------------------------------------------- the 2-worker plane smoke


@pytest.mark.skipif(
    importlib.util.find_spec("numpy") is None, reason="needs the toy prover"
)
def test_fleet_obs_smoke_two_worker_plane(tmp_path, monkeypatch):
    """`make fleet-obs-smoke` acceptance: a REAL supervisor + 2 toy
    workers with the plane on an auto port — /status fails closed
    before the workers arm, then 200; fleet /metrics request counters
    equal the per-worker /snapshot sums AND the proof artifacts; merged
    SLO sample count equals the sum of worker windows; trace_report
    --fleet-dir renders valid chrome-trace JSON."""
    from zkp2p_tpu.native.lib import get_lib

    if get_lib() is None:
        pytest.skip("native toolchain unavailable")
    from zkp2p_tpu.pipeline.fleet import FleetSupervisor
    from zkp2p_tpu.pipeline.service import spool_terminal

    monkeypatch.setenv("ZKP2P_FLEET_SCRAPE_S", "0.3")
    spool = str(tmp_path / "spool")
    os.makedirs(spool)
    n_req = 6
    for i in range(n_req):
        with open(os.path.join(spool, f"q{i:03d}.req.json"), "w") as f:
            json.dump({"x": 3 + i, "y": 5 + i}, f)
    env = dict(os.environ)
    env.pop("PALLAS_AXON_POOL_IPS", None)
    env["JAX_PLATFORMS"] = "cpu"
    env.pop("ZKP2P_FAULTS", None)
    env.pop("ZKP2P_METRICS_SINK", None)
    worker_cmd = lambda wid: [  # noqa: E731
        sys.executable, CHAOS, "--worker", "--linger", "--spool", spool,
        "--batch", "2", "--prove-s", "0.1", "--max-seconds", "150", "--poll-s", "0.05",
    ]
    sup = FleetSupervisor(
        spool, worker_cmd, workers=2, worker_env=env,
        fleet_metrics_port=0, restart_backoff_s=0.1, drain_timeout_s=20.0,
        fleet_dir=str(tmp_path / "fleet"), log=lambda m: None,
    )
    out = {}
    t = threading.Thread(
        target=lambda: out.update(rc=sup.run(poll_s=0.05, max_seconds=150, install_signals=False))
    )
    t.start()
    try:
        deadline = time.time() + 120
        while time.time() < deadline and (sup.plane is None or sup.plane.bound_port is None):
            time.sleep(0.02)
        port = sup.plane.bound_port
        assert port, "plane never bound its endpoint"

        # fail-closed first: workers need seconds of imports before
        # preflight arms them — the immediate answer must be 503
        saw_503 = saw_200 = False
        status = None
        while time.time() < deadline:
            try:
                with urllib.request.urlopen(f"http://127.0.0.1:{port}/status", timeout=3) as r:
                    saw_200 = True
                    status = json.loads(r.read())
                    break
            except urllib.error.HTTPError as e:
                if e.code == 503:
                    saw_503 = True
                    body = json.loads(e.read())
                    assert body["ok"] is False and body["reason"]
            time.sleep(0.1)
        assert saw_200, "fleet /status never reached 200"
        assert saw_503, "fleet /status never failed closed before the workers armed"
        assert status["ok"] is True and status["metrics_port"] == port
        healthz = json.loads(
            urllib.request.urlopen(f"http://127.0.0.1:{port}/healthz", timeout=3).read()
        )
        assert healthz["ok"] is True

        # serve to terminal, then give the scrape loop 2 intervals
        while time.time() < deadline and not spool_terminal(spool):
            time.sleep(0.1)
        assert spool_terminal(spool), "spool never went terminal"
        time.sleep(1.0)

        status = json.loads(
            urllib.request.urlopen(f"http://127.0.0.1:{port}/status", timeout=3).read()
        )
        met = urllib.request.urlopen(f"http://127.0.0.1:{port}/metrics", timeout=3).read().decode()

        # merged counters == per-worker sums == artifacts
        fleet_done = 0.0
        for line in met.splitlines():
            m = re.match(r'zkp2p_service_requests_total\{state="done"\} (\d+(?:\.\d+)?)', line)
            if m:
                fleet_done = float(m.group(1))
        worker_done = 0.0
        slo_sum = 0
        ports = []
        for wid, w in status["workers"].items():
            if w["state"] != "up":
                continue
            ports.append(w["port"])
            snap = json.loads(
                urllib.request.urlopen(f"http://127.0.0.1:{w['port']}/snapshot", timeout=3).read()
            )
            assert snap["armed"] is True and snap["worker"] == wid
            for m in snap["metrics"]:
                if m["name"] == "zkp2p_service_requests_total" and m["labels"].get("state") == "done":
                    worker_done += m["value"]
            slo_sum += snap["slo_window"]["n"]
        assert len(ports) == 2
        assert fleet_done == worker_done == n_req
        # merged SLO sample count = sum of the worker windows
        assert status["slo"]["n"] == slo_sum == n_req
        assert status["slo"]["attainment"] == 1.0
        # per-worker labelled gauges made it to the fleet exposition
        assert re.search(r'zkp2p_slo_attainment\{worker="w[01]"\}', met)
        assert "zkp2p_fleet_slo_attainment 1" in met
        assert status["alerts"] == []
    finally:
        sup.stop()
        t.join(timeout=120)
    assert not t.is_alive()
    assert out.get("rc") == 0

    # forensics over the run the fleet just produced: --fleet-dir
    # discovers the sink, the chrome trace renders valid JSON
    tr = _trace_report()
    sinks = tr.fleet_sinks(sup.fleet_dir)
    assert sinks, "fleet sink discovery found nothing"
    out_json = str(tmp_path / "trace.json")
    rc = tr.main(["--fleet-dir", sup.fleet_dir, "--chrome-trace", out_json])
    assert rc == 0
    with open(out_json) as f:
        trace = json.load(f)
    assert sum(1 for e in trace["traceEvents"] if e.get("ph") == "X") >= n_req
    # final status.json carries the plane view (alert history included)
    with open(os.path.join(sup.fleet_dir, "status.json")) as f:
        st = json.load(f)
    assert "alerts_state" in st and "slo" in st and st["metrics_port"] == port
