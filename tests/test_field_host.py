"""Host field-layer sanity: moduli, Montgomery constants, roots of unity."""

from zkp2p_tpu.field import bn254 as f


def test_moduli_are_prime_ish():
    # Fermat witnesses (full primality is overkill here; these catch typos)
    for m in (f.P, f.R):
        assert pow(2, m - 1, m) == 1
        assert pow(3, m - 1, m) == 1


def test_montgomery_constants():
    assert (f.P * pow(f.P, -1, f.MONT_R)) % f.MONT_R == 1
    assert (f.FQ_MONT_R2 - f.MONT_R * f.MONT_R) % f.P == 0
    # n' satisfies  n * n' == -1 mod 2^256
    assert (f.P * f.FQ_NPRIME) % f.MONT_R == f.MONT_R - 1
    assert (f.R * f.FR_NPRIME) % f.MONT_R == f.MONT_R - 1


def test_mont_roundtrip():
    x = 123456789123456789123456789
    assert f.from_mont(f.to_mont(x)) == x


def test_fr_two_adicity():
    w = f.FR_ROOT_OF_UNITY
    assert pow(w, 1 << 28, f.R) == 1
    assert pow(w, 1 << 27, f.R) != 1


def test_domain_roots():
    for k in (1, 4, 10):
        w = f.fr_domain_root(k)
        assert pow(w, 1 << k, f.R) == 1
        assert pow(w, 1 << (k - 1), f.R) != 1


def test_circom_bigint_constants():
    # wire-format parity with the reference app's limb layout
    # (app/src/helpers/constants.ts:17-18)
    assert f.CIRCOM_BIGINT_N == 121
    assert f.CIRCOM_BIGINT_K == 17
    assert f.CIRCOM_BIGINT_N * f.CIRCOM_BIGINT_K >= 2048
