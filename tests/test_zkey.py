"""snarkjs .zkey format round-trip (monolithic + b..k chunks).

The environment has no node/snarkjs (zero egress), so true differential
validation against the reference toolchain is impossible here; these
tests pin the byte-level format discipline instead: Montgomery LE
encodings, section layout, coeff rows including the public binding rows,
and that a key surviving the round trip proves + verifies identically.
"""

import os

import pytest

from zkp2p_tpu.field.bn254 import R
from zkp2p_tpu.formats.zkey import CHUNK_SUFFIXES, read_zkey, split_zkey, write_zkey
from zkp2p_tpu.snark.groth16 import prove_host, qap_rows, setup, verify
from zkp2p_tpu.snark.r1cs import LC, ConstraintSystem


def _toy():
    cs = ConstraintSystem("toy")
    out = cs.new_public("out")
    x = cs.new_wire("x")
    y = cs.new_wire("y")
    z = cs.new_wire("z")
    cs.enforce(LC.of(x), LC.of(y), LC.of(z), "mul")
    cs.enforce(LC.of(z) + LC.const(2), LC.of(z), LC.of(out), "sq")
    cs.compute(z, lambda a, b: a * b % R, [x, y])
    return cs, x, y


def test_zkey_roundtrip(tmp_path):
    cs, x, y = _toy()
    pk, vk = setup(cs, seed="zkey-test")
    path = os.path.join(tmp_path, "circuit_final.zkey")
    write_zkey(path, pk, vk, qap_rows(cs))
    zk = read_zkey(path)

    assert zk.n_vars == cs.num_wires
    assert zk.n_public == 1
    assert zk.domain_size == pk.domain_size
    assert zk.alpha_1 == pk.alpha_1
    assert zk.beta_2 == pk.beta_2
    assert zk.gamma_2 == vk.gamma_2
    assert zk.ic == vk.ic
    assert zk.a_query == pk.a_query
    assert zk.b1_query == pk.b1_query
    assert zk.b2_query == pk.b2_query
    assert zk.c_query == pk.c_query
    assert zk.h_query == pk.h_query

    # coeff section reproduces the QAP rows (incl. binding rows)
    a_rows, b_rows = zk.qap_row_arrays()
    rows = qap_rows(cs)
    assert len(a_rows) == len(rows)
    for j, (a, b, _c) in enumerate(rows):
        assert a_rows[j] == {w: v % R for w, v in a.items()}
        assert b_rows[j] == {w: v % R for w, v in b.items()}

    # the imported key proves and verifies
    w = cs.witness([255], {x: 3, y: 5})
    pk2 = zk.to_proving_key()
    vk2 = zk.to_verifying_key()
    proof = prove_host(pk2, cs, w, r=11, s=13)
    assert proof == prove_host(pk, cs, w, r=11, s=13)
    assert verify(vk2, proof, [255])
    assert not verify(vk2, proof, [256])


def test_zkey_chunked(tmp_path):
    cs, x, y = _toy()
    pk, vk = setup(cs, seed="zkey-test")
    path = os.path.join(tmp_path, "circuit.zkey")
    write_zkey(path, pk, vk, qap_rows(cs))
    chunks = split_zkey(path, n_chunks=10)
    assert [c[-1] for c in chunks] == list(CHUNK_SUFFIXES)
    zk = read_zkey(chunks)
    assert zk.a_query == pk.a_query
    assert zk.h_query == pk.h_query


@pytest.mark.slow
@pytest.mark.xslow
def test_zkey_device_prove(tmp_path):
    """device_pk_from_zkey: the zkey-import path drives the TPU prover to
    the same proof as the ConstraintSystem path."""
    from zkp2p_tpu.prover.groth16_tpu import device_pk, device_pk_from_zkey, prove_tpu

    cs, x, y = _toy()
    pk, vk = setup(cs, seed="zkey-test")
    path = os.path.join(tmp_path, "circuit_final.zkey")
    write_zkey(path, pk, vk, qap_rows(cs))
    zk = read_zkey(path)
    w = cs.witness([255], {x: 3, y: 5})
    got = prove_tpu(device_pk_from_zkey(zk), w, r=21, s=22)
    want = prove_tpu(device_pk(pk, cs), w, r=21, s=22)
    assert got == want
    assert verify(vk, got, [255])
