"""snarkjs .zkey format round-trip (monolithic + b..k chunks).

The environment has no node/snarkjs (zero egress), so true differential
validation against the reference toolchain is impossible here; these
tests pin the byte-level format discipline instead: Montgomery LE
encodings, section layout, coeff rows including the public binding rows,
and that a key surviving the round trip proves + verifies identically.
"""

import os

import pytest

from zkp2p_tpu.field.bn254 import R
from zkp2p_tpu.formats.zkey import CHUNK_SUFFIXES, read_zkey, split_zkey, write_zkey
from zkp2p_tpu.snark.groth16 import prove_host, qap_rows, setup, verify
from zkp2p_tpu.snark.r1cs import LC, ConstraintSystem


def _toy():
    cs = ConstraintSystem("toy")
    out = cs.new_public("out")
    x = cs.new_wire("x")
    y = cs.new_wire("y")
    z = cs.new_wire("z")
    cs.enforce(LC.of(x), LC.of(y), LC.of(z), "mul")
    cs.enforce(LC.of(z) + LC.const(2), LC.of(z), LC.of(out), "sq")
    cs.compute(z, lambda a, b: a * b % R, [x, y])
    return cs, x, y


def test_zkey_roundtrip(tmp_path):
    cs, x, y = _toy()
    pk, vk = setup(cs, seed="zkey-test")
    path = os.path.join(tmp_path, "circuit_final.zkey")
    write_zkey(path, pk, vk, qap_rows(cs))
    zk = read_zkey(path)

    assert zk.n_vars == cs.num_wires
    assert zk.n_public == 1
    assert zk.domain_size == pk.domain_size
    assert zk.alpha_1 == pk.alpha_1
    assert zk.beta_2 == pk.beta_2
    assert zk.gamma_2 == vk.gamma_2
    assert zk.ic == vk.ic
    assert zk.a_query == pk.a_query
    assert zk.b1_query == pk.b1_query
    assert zk.b2_query == pk.b2_query
    assert zk.c_query == pk.c_query
    assert zk.h_query == pk.h_query

    # coeff section reproduces the QAP rows (incl. binding rows)
    a_rows, b_rows = zk.qap_row_arrays()
    rows = qap_rows(cs)
    assert len(a_rows) == len(rows)
    for j, (a, b, _c) in enumerate(rows):
        assert a_rows[j] == {w: v % R for w, v in a.items()}
        assert b_rows[j] == {w: v % R for w, v in b.items()}

    # the imported key proves and verifies
    w = cs.witness([255], {x: 3, y: 5})
    pk2 = zk.to_proving_key()
    vk2 = zk.to_verifying_key()
    proof = prove_host(pk2, cs, w, r=11, s=13)
    assert proof == prove_host(pk, cs, w, r=11, s=13)
    assert verify(vk2, proof, [255])
    assert not verify(vk2, proof, [256])


def test_zkey_chunked(tmp_path):
    cs, x, y = _toy()
    pk, vk = setup(cs, seed="zkey-test")
    path = os.path.join(tmp_path, "circuit.zkey")
    write_zkey(path, pk, vk, qap_rows(cs))
    chunks = split_zkey(path, n_chunks=10)
    assert [c[-1] for c in chunks] == list(CHUNK_SUFFIXES)
    zk = read_zkey(chunks)
    assert zk.a_query == pk.a_query
    assert zk.h_query == pk.h_query


@pytest.mark.slow
@pytest.mark.xslow
def test_zkey_device_prove(tmp_path):
    """device_pk_from_zkey: the zkey-import path drives the TPU prover to
    the same proof as the ConstraintSystem path."""
    from zkp2p_tpu.prover.groth16_tpu import device_pk, device_pk_from_zkey, prove_tpu

    cs, x, y = _toy()
    pk, vk = setup(cs, seed="zkey-test")
    path = os.path.join(tmp_path, "circuit_final.zkey")
    write_zkey(path, pk, vk, qap_rows(cs))
    zk = read_zkey(path)
    w = cs.witness([255], {x: 3, y: 5})
    got = prove_tpu(device_pk_from_zkey(zk), w, r=21, s=22)
    want = prove_tpu(device_pk(pk, cs), w, r=21, s=22)
    assert got == want
    assert verify(vk, got, [255])


def test_zkey_width_inference(tmp_path):
    """infer_zkey_widths recovers the bit wires (circom Num2Bits pattern
    x*(x-1)=0) from the coeff section alone, the imported key proves
    identically through the narrow-classed native path, and a witness
    violating an inferred bound is rejected instead of silently proving
    wrong (the zkey has no C matrix, so x*(x-1)=y is indistinguishable
    from a bit row at import time — VERDICT r4 weak #5)."""
    import numpy as np

    from zkp2p_tpu.gadgets.core import num2bits
    from zkp2p_tpu.prover.groth16_tpu import (
        NARROW_WIDTH,
        device_pk,
        device_pk_from_zkey,
        infer_zkey_widths,
        widths_array,
    )
    from zkp2p_tpu.prover.native_prove import prove_native

    cs = ConstraintSystem("bits")
    out = cs.new_public("out")
    x = cs.new_wire("x")
    bits = num2bits(cs, x, 8)
    cs.enforce(LC.of(x), LC.of(x), LC.of(out), "sq")
    pk, vk = setup(cs, seed="width-infer")
    path = os.path.join(tmp_path, "bits.zkey")
    write_zkey(path, pk, vk, qap_rows(cs))
    zk = read_zkey(path)

    inferred = infer_zkey_widths(zk)
    tagged = widths_array(cs)
    # every cs-tagged BIT wire is recovered as narrow from the file alone
    bit_wires = np.flatnonzero(tagged == 1)
    assert len(bit_wires) >= 8
    assert (inferred[bit_wires] == 1).all()
    # and nothing untagged-narrow got widened into the narrow class
    assert (inferred[tagged > NARROW_WIDTH] > NARROW_WIDTH).all()

    dpk_imported = device_pk_from_zkey(zk)
    assert int(dpk_imported.a_nsel.shape[0]) > 0  # the fast path engaged
    dpk_cs = device_pk(pk, cs)
    w = cs.witness([169 % R], {x: 13})
    got = prove_native(dpk_imported, w, r=31, s=37)
    want = prove_native(dpk_cs, w, r=31, s=37)
    assert got == want
    assert verify(vk, got, [169])


def test_zkey_width_inference_guard(tmp_path):
    """The ambiguous pattern: x*(x-1) = y (NOT a bit constraint) — the
    importer will class x narrow, and the prove-time guard must reject a
    witness where x is actually wide."""
    from zkp2p_tpu.prover.groth16_tpu import device_pk_from_zkey
    from zkp2p_tpu.prover.native_prove import prove_native

    cs = ConstraintSystem("trap")
    out = cs.new_public("out")
    x = cs.new_wire("x")
    y = cs.new_wire("y")
    cs.enforce(LC.of(x), LC.of(x) - 1, LC.of(y), "not-a-bit")
    cs.enforce(LC.of(y), LC.const(1), LC.of(out), "bind")
    cs.compute(y, lambda v: v * (v - 1) % R, [x])
    pk, vk = setup(cs, seed="width-trap")
    path = os.path.join(tmp_path, "trap.zkey")
    write_zkey(path, pk, vk, qap_rows(cs))
    zk = read_zkey(path)
    dpk = device_pk_from_zkey(zk)

    xv = 5000  # > 2^11: breaks the inferred narrow bound
    w = cs.witness([xv * (xv - 1) % R], {x: xv})
    with pytest.raises(ValueError, match="width bound inferred"):
        prove_native(dpk, w, r=3, s=5)
    # opting out of inference proves fine (wide class)
    dpk_wide = device_pk_from_zkey(zk, infer_widths=False)
    proof = prove_native(dpk_wide, w, r=3, s=5)
    assert verify(vk, proof, [xv * (xv - 1) % R])
