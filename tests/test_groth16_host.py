"""End-to-end Groth16 on toy circuits (host oracle path).

Mirrors the reference's prove->verify loop (dizkus-scripts/5_gen_proof.sh:
prove then immediately `snarkjs groth16 verify`)."""

import pytest

from zkp2p_tpu.field.bn254 import R
from zkp2p_tpu.snark.fft_host import evaluate_poly, intt, ntt
from zkp2p_tpu.snark.groth16 import prove_host, setup, verify
from zkp2p_tpu.snark.r1cs import LC, ConstraintSystem


def build_toy():
    """public out; private x, y:  x*y = z,  z*z = out."""
    cs = ConstraintSystem("toy")
    out = cs.new_public("out")
    x = cs.new_wire("x")
    y = cs.new_wire("y")
    z = cs.new_wire("z")
    cs.enforce(LC.of(x), LC.of(y), LC.of(z), "mul")
    cs.enforce(LC.of(z), LC.of(z), LC.of(out), "sq")
    cs.compute(z, lambda a, b: a * b % R, [x, y])
    return cs, out, x, y


def test_ntt_roundtrip():
    coeffs = [(i * 7919 + 13) % R for i in range(16)]
    assert intt(ntt(coeffs)) == coeffs


def test_ntt_is_evaluation():
    from zkp2p_tpu.field.bn254 import fr_domain_root

    coeffs = [3, 1, 4, 1, 5, 9, 2, 6]
    evals = ntt(coeffs)
    w = fr_domain_root(3)
    for j in range(8):
        assert evals[j] == evaluate_poly(coeffs, pow(w, j, R))


def test_groth16_end_to_end():
    cs, out, x, y = build_toy()
    w = cs.witness([225], {x: 3, y: 5})
    cs.check_witness(w)
    pk, vk = setup(cs)
    proof = prove_host(pk, cs, w)
    assert verify(vk, proof, [225])
    assert not verify(vk, proof, [226])


def test_groth16_rejects_bad_witness():
    cs, out, x, y = build_toy()
    w = cs.witness([225], {x: 3, y: 5})
    w[-1] = (w[-1] + 1) % R  # corrupt z
    with pytest.raises(AssertionError):
        cs.check_witness(w)


def test_proofs_are_randomized():
    cs, out, x, y = build_toy()
    w = cs.witness([225], {x: 3, y: 5})
    pk, vk = setup(cs)
    p1 = prove_host(pk, cs, w)
    p2 = prove_host(pk, cs, w)
    assert p1.a != p2.a  # fresh (r, s) per proof — zero-knowledge blinding
    assert verify(vk, p1, [225]) and verify(vk, p2, [225])


def test_verify_rejects_invalid_points():
    from zkp2p_tpu.snark.groth16 import Proof

    cs, out, x, y = build_toy()
    w = cs.witness([225], {x: 3, y: 5})
    pk, vk = setup(cs)
    proof = prove_host(pk, cs, w)
    # off-curve G1 point must be rejected before any pairing math
    assert not verify(vk, Proof(a=(12345, 67890), b=proof.b, c=proof.c), [225])
    assert not verify(vk, Proof(a=proof.a, b=proof.b, c=(1, 1)), [225])


def test_witness_missing_wire_detected():
    cs = ConstraintSystem("incomplete")
    cs.new_public("p")
    cs.new_wire("unset")
    with pytest.raises(RuntimeError):
        cs.witness([1])
