"""Differential test: the fused Pallas Montgomery-mul kernel vs the XLA
field layer and the host bigint oracle (interpret mode — no TPU needed).
"""

import random

import jax.numpy as jnp
import numpy as np
import pytest

from zkp2p_tpu.field.bn254 import P, R
from zkp2p_tpu.field.jfield import FQ, FR, limbs_to_int
from zkp2p_tpu.ops.pallas_mont import mont_mul

rng = random.Random(777)


@pytest.mark.parametrize("field,mod", [(FR, R), (FQ, P)], ids=["fr", "fq"])
def test_pallas_mont_matches_xla_and_host(field, mod):
    xs = [rng.randrange(mod) for _ in range(9)] + [0, 1, mod - 1]
    ys = [rng.randrange(mod) for _ in range(9)] + [mod - 1, 0, 1]
    a = jnp.asarray(np.stack([field.to_mont_host(x) for x in xs]))
    b = jnp.asarray(np.stack([field.to_mont_host(y) for y in ys]))
    got = mont_mul(field, a, b, interpret=True)
    want = field.mul(a, b)
    assert jnp.array_equal(got, want), "pallas kernel != XLA field layer"
    for i, (x, y) in enumerate(zip(xs, ys)):
        assert field.from_mont_host(np.asarray(got[i])) == x * y % mod


def test_pallas_mont_padding_and_batch_dims():
    # A batch size that is not a TILE multiple exercises the pad/unpad
    # boundary; 2D batch dims exercise the reshape path.
    xs = [rng.randrange(R) for _ in range(6)]
    ys = [rng.randrange(R) for _ in range(6)]
    a = jnp.asarray(np.stack([FR.to_mont_host(x) for x in xs])).reshape(2, 3, 16)
    b = jnp.asarray(np.stack([FR.to_mont_host(y) for y in ys])).reshape(2, 3, 16)
    got = mont_mul(FR, a, b, interpret=True)
    assert got.shape == (2, 3, 16)
    assert jnp.array_equal(got, FR.mul(a, b))


def test_pallas_mont_pow_inverse():
    """The fused square-and-multiply ladder (one kernel launch) vs the
    host Fermat inverse — the batched-inversion primitive of the affine
    MSM tier (ops.msm_affine)."""
    from zkp2p_tpu.ops.pallas_mont import mont_pow

    xs = [rng.randrange(1, P) for _ in range(5)] + [1, P - 1]
    a = jnp.asarray(np.stack([FQ.to_mont_host(x) for x in xs]))
    got = mont_pow(FQ, a, P - 2, interpret=True)
    for i, x in enumerate(xs):
        assert FQ.from_mont_host(np.asarray(got[i])) == pow(x, P - 2, P)


def test_pallas_mont_pow_small_exponent():
    xs = [rng.randrange(R) for _ in range(4)]
    a = jnp.asarray(np.stack([FR.to_mont_host(x) for x in xs]))
    from zkp2p_tpu.ops.pallas_mont import mont_pow

    got = mont_pow(FR, a, 5, interpret=True)
    for i, x in enumerate(xs):
        assert FR.from_mont_host(np.asarray(got[i])) == pow(x, 5, R)


def test_pallas_mont_pow_under_vmap():
    """The affine MSM tier calls inv_fused inside a scan UNDER VMAP in
    the batched prover — exercise the pallas batching rule for the pow
    kernel in interpret mode so the combination is not TPU-only."""
    import jax

    from zkp2p_tpu.ops.pallas_mont import mont_pow

    xs = [[rng.randrange(1, P) for _ in range(3)] for _ in range(2)]
    a = jnp.asarray(
        np.stack([np.stack([FQ.to_mont_host(x) for x in row]) for row in xs])
    )
    got = jax.vmap(lambda v: mont_pow(FQ, v, P - 2, True))(a)
    for i, row in enumerate(xs):
        for j, x in enumerate(row):
            assert FQ.from_mont_host(np.asarray(got[i, j])) == pow(x, P - 2, P)
