"""Service fault-tolerance layer (docs/ROBUSTNESS.md), tier-1: batch
bisection isolates a poisoned request with byte-identical batchmate
proofs and a bounded prove count, transient failures retry with backoff,
the degradation ladder rescues knob-sensitive failures, deadlines and
the spool cap terminal visibly, torn requests and short prover returns
fail loudly without sinking the sweep, and stale-claim takeover rewrites
the claim file to the new owner.

Everything here drives the REAL native prover on a 2-constraint circuit
(fast; tier-1 resident — the slow-marked test_service.py covers the
XLA batch prover).  REGISTRY counters are process-global: tests assert
deltas, never absolutes.
"""

import json
import math
import os
import time

import pytest

from zkp2p_tpu.field.bn254 import R
from zkp2p_tpu.native.lib import get_lib
from zkp2p_tpu.pipeline.service import ProvingService
from zkp2p_tpu.utils import faults
from zkp2p_tpu.utils.metrics import REGISTRY

pytestmark = pytest.mark.skipif(get_lib() is None, reason="native toolchain unavailable")


@pytest.fixture(autouse=True)
def _clean_faults(monkeypatch):
    """No ZKP2P_FAULTS leakage between tests: the plan cache is keyed by
    the raw env value, and a stale cached plan would carry spent once/n
    counters into a test that sets the same spec string."""
    monkeypatch.delenv("ZKP2P_FAULTS", raising=False)
    monkeypatch.delenv("ZKP2P_METRICS_SINK", raising=False)
    faults.reset()
    yield
    faults.reset()


@pytest.fixture(scope="module")
def world():
    from zkp2p_tpu.prover.groth16_tpu import device_pk
    from zkp2p_tpu.snark.groth16 import setup
    from zkp2p_tpu.snark.r1cs import LC, ConstraintSystem

    cs = ConstraintSystem("svc-faults")
    out = cs.new_public("out")
    x = cs.new_wire("x")
    y = cs.new_wire("y")
    z = cs.new_wire("z")
    cs.enforce(LC.of(x), LC.of(y), LC.of(z), "mul")
    cs.enforce(LC.of(z), LC.of(z), LC.of(out), "sq")
    cs.compute(z, lambda a, b: a * b % R, [x, y])
    pk, vk = setup(cs, seed="svc-faults")
    dpk = device_pk(pk, cs)

    def witness_fn(payload):
        xv, yv = int(payload["x"]), int(payload["y"])
        return cs.witness([pow(xv * yv, 2, R)], {x: xv, y: yv})

    return cs, dpk, vk, witness_fn


def _prove_batch(dpk, wits):
    """Deterministic batch prover: fixed (r, s) so the same witness
    always yields byte-identical proof JSON (the byte-parity anchor for
    the isolation tests; r/s secrecy is irrelevant in a test vector)."""
    from zkp2p_tpu.prover.native_prove import prove_native

    return [prove_native(dpk, w, r=123456789, s=987654321) for w in wits]


def _mk(world, **kw):
    cs, dpk, vk, witness_fn = world
    kw.setdefault("prover_fn", _prove_batch)
    kw.setdefault("batch_size", 2)
    kw.setdefault("retry_backoff_s", 0.0)  # tests must not sleep
    return ProvingService(cs, dpk, vk, witness_fn, public_fn=lambda w: [w[1]], **kw)


def _write_reqs(spool, pairs, prefix="r", **extra):
    for i, (xv, yv) in enumerate(pairs):
        with open(os.path.join(spool, f"{prefix}{i}.req.json"), "w") as f:
            json.dump({"x": xv, "y": yv, **extra}, f)


def _records(spool):
    path = str(spool).rstrip("/") + ".metrics.jsonl"
    if not os.path.exists(path):
        return []
    with open(path) as f:
        return [json.loads(ln) for ln in f if json.loads(ln).get("type") == "request"]


def _counter(name, **labels):
    return REGISTRY.counter(name, labels or None).value


# ------------------------------------------------------- torn requests


def test_torn_req_json_terminals_bad_input_and_sweep_continues(world, tmp_path):
    spool = str(tmp_path)
    _write_reqs(spool, [(3, 5), (2, 7)])
    torn = os.path.join(spool, "aatorn.req.json")
    with open(torn, "w") as f:
        f.write('{"x": 3, "y"')  # half-written upload; sorts FIRST
    # age it past the mid-write grace window: this one is genuinely torn
    past = time.time() - 60
    os.utime(torn, (past, past))
    stats = _mk(world).process_dir(spool)
    assert stats["done"] == 2 and stats["error-bad-input"] == 1
    with open(os.path.join(spool, "aatorn.error.json")) as f:
        err = json.load(f)
    assert err["state"] == "error-bad-input"
    assert os.path.exists(os.path.join(spool, "r0.proof.json"))
    assert os.path.exists(os.path.join(spool, "r1.proof.json"))
    # idempotent: the torn file stays terminal, nothing reprocessed
    assert not any(_mk(world).process_dir(spool).values())


def test_young_torn_req_gets_grace_then_completes(world, tmp_path):
    """A torn file YOUNGER than the grace window may still be mid-write
    by a non-atomic uploader: the sweep must leave it open (a permanent
    error-bad-input on a request about to become valid is
    unrecoverable), and process it once the write completes."""
    spool = str(tmp_path)
    torn = os.path.join(spool, "r0.req.json")
    with open(torn, "w") as f:
        f.write('{"x": 3, "y"')  # fresh mtime: inside the grace window
    svc = _mk(world)
    assert not any(svc.process_dir(spool).values())
    assert not os.path.exists(os.path.join(spool, "r0.error.json"))
    with open(torn, "w") as f:  # the upload completes
        json.dump({"x": 3, "y": 5}, f)
    assert svc.process_dir(spool)["done"] == 1


def test_permanent_oserror_in_witness_terminals_bad_input(world, tmp_path):
    """A payload naming a missing file raises FileNotFoundError out of
    the witness builder — payload pathology, NOT transient pressure.
    Deferring it would livelock the spool: re-claimed, re-failed, and
    never terminal, every sweep, forever."""
    cs, dpk, vk, _ = world

    def witness_fn(payload):
        with open(payload["eml_path"]) as f:  # ENOENT
            f.read()

    svc = ProvingService(
        cs, dpk, vk, witness_fn, public_fn=lambda w: [w[1]],
        prover_fn=_prove_batch, batch_size=2, retry_backoff_s=0.0,
    )
    spool = str(tmp_path)
    _write_reqs(spool, [(3, 5)], eml_path=os.path.join(spool, "no-such.eml"))
    stats = svc.process_dir(spool)
    assert stats["error-bad-input"] == 1
    with open(os.path.join(spool, "r0.error.json")) as f:
        assert f.read().find("error-bad-input") >= 0
    # terminal, not deferred: the next sweep finds nothing to do
    assert not any(svc.process_dir(spool).values())


# --------------------------------------------------- short prover return


def test_short_prover_return_fails_loudly_not_truncated(world, tmp_path):
    """A prover_fn returning S-1 proofs for an S batch must never
    zip-truncate (last request silently dropped, or worse, mates
    emitted under the wrong rid) — the batch fails loudly, bisection
    re-proves, and every request still terminals correctly."""
    spool = str(tmp_path)
    _write_reqs(spool, [(3, 5), (2, 7)])
    calls = []

    def short_prover(dpk, wits):
        calls.append(len(wits))
        proofs = _prove_batch(dpk, wits)
        return proofs[:-1] if len(wits) > 1 else proofs

    b0 = _counter("zkp2p_service_bisections_total")
    stats = _mk(world, prover_fn=short_prover).process_dir(spool)
    # the short return is a PERMANENT batch failure -> bisected to
    # singles, which the prover handles correctly -> both still done
    assert stats["done"] == 2 and stats["error-failed-to-prove"] == 0
    assert calls == [2, 1, 1]
    assert _counter("zkp2p_service_bisections_total") - b0 == 1
    # and each proof landed under its OWN rid (no truncation shift)
    from zkp2p_tpu.formats.proof_json import load, proof_from_json
    from zkp2p_tpu.snark.groth16 import verify

    for i, (xv, yv) in enumerate([(3, 5), (2, 7)]):
        proof = proof_from_json(load(os.path.join(spool, f"r{i}.proof.json")))
        pub = [int(v) for v in load(os.path.join(spool, f"r{i}.public.json"))]
        assert pub == [pow(xv * yv, 2, R)]
        assert verify(world[2], proof, pub)


# ------------------------------------------------------ batch isolation


def test_poisoned_batch_isolates_to_one_error(world, tmp_path):
    """The acceptance criterion: a batch of 4 with one poisoned request
    completes the other three as done, with proofs byte-identical to a
    clean run and at most 1 + log2(S) prove calls touching each mate."""
    cs, dpk, vk, witness_fn = world
    pairs = [(3, 5), (2, 7), (4, 4), (9, 2)]
    poison_pub = pow(4 * 4, 2, R)  # r2 is the poisoned request

    clean_spool = str(tmp_path / "clean")
    os.makedirs(clean_spool)
    _write_reqs(clean_spool, pairs)
    assert _mk(world, batch_size=4).process_dir(clean_spool)["done"] == 4

    calls = []

    def poisoned_prover(dpk_, wits):
        calls.append(len(wits))
        if any(w[1] == poison_pub for w in wits):
            raise ValueError("poisoned witness")  # permanent: no retry
        return _prove_batch(dpk_, wits)

    spool = str(tmp_path / "dirty")
    os.makedirs(spool)
    _write_reqs(spool, pairs)
    b0 = _counter("zkp2p_service_bisections_total")
    stats = _mk(world, batch_size=4, prover_fn=poisoned_prover).process_dir(spool)
    assert stats["done"] == 3 and stats["error-failed-to-prove"] == 1
    assert _counter("zkp2p_service_bisections_total") - b0 >= 1
    with open(os.path.join(spool, "r2.error.json")) as f:
        assert json.load(f)["state"] == "error-failed-to-prove"

    # byte-identical batchmate proofs vs the clean run
    for i in (0, 1, 3):
        with open(os.path.join(spool, f"r{i}.proof.json"), "rb") as a, open(
            os.path.join(clean_spool, f"r{i}.proof.json"), "rb"
        ) as b:
            assert a.read() == b.read(), f"r{i} proof differs from clean run"

    # prove-call bound: every SUCCESSFUL call is a mate's final prove;
    # each mate additionally rides at most log2(S) failed bisection
    # probes (the poisoned single's ladder rescue attempts are its own
    # cost, not the mates') — bound the failing calls that contain any
    # mate by S/2 * log2(S) in aggregate, i.e. <= log2(S) each
    S = 4
    good_calls = [c for c in calls if c > 0]
    assert sum(1 for c in good_calls) <= (1 + math.ceil(math.log2(S))) * S
    # the sharpest observable: mates' proofs each emitted exactly once
    recs = [r for r in _records(spool) if r["state"] == "done"]
    assert sorted(r["request_id"] for r in recs) == ["r0", "r1", "r3"]


def test_batch_of_all_poisoned_terminals_every_request(world, tmp_path):
    spool = str(tmp_path)
    _write_reqs(spool, [(3, 5), (2, 7)])

    def broken_prover(dpk_, wits):
        raise ValueError("poisoned witness")

    stats = _mk(world, prover_fn=broken_prover).process_dir(spool)
    assert stats["error-failed-to-prove"] == 2 and stats["done"] == 0
    for i in range(2):
        assert os.path.exists(os.path.join(spool, f"r{i}.error.json"))
    # exactly one terminal record each, none duplicated
    recs = _records(spool)
    assert sorted(r["request_id"] for r in recs) == ["r0", "r1"]


# ---------------------------------------------------- transient retries


def test_transient_prove_failures_retry_with_bound(world, tmp_path, monkeypatch):
    """prove:raise:n=2 exhausts exactly the first two attempts; the
    bounded retry loop (retries=2) lands the third — all done, no
    bisection, retry counter +2."""
    spool = str(tmp_path)
    _write_reqs(spool, [(3, 5), (2, 7)])
    monkeypatch.setenv("ZKP2P_FAULTS", "prove:raise:n=2")
    faults.reset()
    r0 = _counter("zkp2p_service_retries_total")
    b0 = _counter("zkp2p_service_bisections_total")
    stats = _mk(world, retries=2).process_dir(spool)
    assert stats["done"] == 2 and stats["error-failed-to-prove"] == 0
    assert _counter("zkp2p_service_retries_total") - r0 == 2
    assert _counter("zkp2p_service_bisections_total") - b0 == 0


def test_retries_exhausted_falls_through_to_bisection(world, tmp_path, monkeypatch):
    """A fault that outlives the retry budget drops into bisection and
    the singles (retried again per-half) eventually terminal — the
    ladder below the retry loop, exercised end to end."""
    spool = str(tmp_path)
    _write_reqs(spool, [(3, 5), (2, 7)])
    # fires on every prove attempt forever: retries cannot save it, and
    # every bisection half + every ladder rung fails the same way
    monkeypatch.setenv("ZKP2P_FAULTS", "prove:raise")
    faults.reset()
    stats = _mk(world, retries=1).process_dir(spool)
    assert stats["error-failed-to-prove"] == 2 and stats["done"] == 0
    recs = _records(spool)
    assert sorted(r["request_id"] for r in recs) == ["r0", "r1"]
    assert all(r["state"] == "error-failed-to-prove" for r in recs)


# -------------------------------------------------- degradation ladder


def test_degradation_ladder_rescues_and_is_recorded(world, tmp_path):
    """A prover that only works with the multi-column path off (the
    classic 'fast path is broken on this host' failure) is rescued by
    the no-multi rung; the record carries degraded_rung and the
    degraded counter ticks."""
    spool = str(tmp_path)
    _write_reqs(spool, [(3, 5)])

    def multi_broken_prover(dpk_, wits):
        if os.environ.get("ZKP2P_MSM_MULTI") != "0":
            raise ValueError("multi-column path broken")  # permanent
        return _prove_batch(dpk_, wits)

    multi_broken_prover.reads_msm_knobs = True  # the ladder gates on this
    d0 = _counter("zkp2p_service_degraded_total", rung="no-multi")
    stats = _mk(world, prover_fn=multi_broken_prover, batch_size=1).process_dir(spool)
    assert stats["done"] == 1
    assert _counter("zkp2p_service_degraded_total", rung="no-multi") - d0 == 1
    (rec,) = _records(spool)
    assert rec["state"] == "done" and rec["degraded_rung"] == "no-multi"
    # the overlay is restored: the env is not left degraded
    assert os.environ.get("ZKP2P_MSM_MULTI") != "0"


def test_ladder_skipped_for_knob_blind_prover(world, tmp_path):
    """A prover that never reads the MSM knobs (the default TPU batch
    prover, or any custom fn) must NOT get the ladder: every rung would
    re-run the identical prove — four wasted full proves — and a flaky
    success would be misattributed to the rung."""
    spool = str(tmp_path)
    _write_reqs(spool, [(3, 5)])
    calls = []

    def always_broken(dpk_, wits):
        calls.append(len(wits))
        raise ValueError("deterministic breakage")  # permanent, knob-blind

    stats = _mk(world, prover_fn=always_broken, batch_size=1).process_dir(spool)
    assert stats["error-failed-to-prove"] == 1
    assert len(calls) == 1  # no retries (permanent), NO ladder re-proves
    with open(os.path.join(spool, "r0.error.json")) as f:
        assert "deterministic breakage" in json.load(f)["error"]


def test_queued_batch_claims_stay_heartbeated(world, tmp_path):
    """Claims held by batches waiting in ready_q behind a slow prove
    must stay fresh: with only a per-batch heartbeat they age toward
    stale while queued, a peer takes them over, and both workers emit
    terminal records for the same rid — the duplicate the chaos
    invariant forbids."""
    import threading

    spool = str(tmp_path)
    _write_reqs(spool, [(3, 5), (2, 7), (4, 3)])

    def slow_prover(dpk_, wits):
        time.sleep(0.6)  # each batch outlives stale_claim_s below
        return _prove_batch(dpk_, wits)

    svc = _mk(world, prover_fn=slow_prover, batch_size=1, prefetch=3, stale_claim_s=0.4)
    t = threading.Thread(target=svc.process_dir, args=(spool,))
    t.start()
    time.sleep(0.5)  # queued batches' claims are now older than stale_claim_s
    # a peer sweeping the same spool mid-run must find nothing stale
    peer = _mk(world, batch_size=1)
    peer_stats = peer.process_dir(spool)
    t.join()
    assert not any(peer_stats.values())  # nothing was takeover-eligible
    by_rid = {}
    for rec in _records(spool):
        by_rid[rec["request_id"]] = by_rid.get(rec["request_id"], 0) + 1
    assert by_rid == {"r0": 1, "r1": 1, "r2": 1}  # exactly one terminal each


def test_spool_cap_ignores_requests_claimed_by_peers(world, tmp_path):
    """Admission control must count the CLAIMABLE backlog: requests a
    peer is actively proving are not queue pressure, and shedding off
    the inflated number permanently fails viable requests while the
    fleet has spare capacity."""
    spool = str(tmp_path)
    _write_reqs(spool, [(3, 5), (2, 7), (4, 3)])
    # a peer holds r0 right now (fresh claim)
    with open(os.path.join(spool, "r0.claim"), "w") as f:
        json.dump({"pid": 99999999, "ts": time.time()}, f)
    svc = _mk(world, spool_cap=2)
    stats = svc.process_dir(spool)
    # claimable backlog = 2 = cap: nothing shed, both proven
    assert stats["error-shed"] == 0 and stats["done"] == 2
    os.unlink(os.path.join(spool, "r0.claim"))


# ------------------------------------------------------------ deadlines


def test_deadline_exceeded_at_claim(world, tmp_path):
    spool = str(tmp_path)
    _write_reqs(spool, [(3, 5)], prefix="old", deadline_s=5)
    _write_reqs(spool, [(2, 7)], prefix="fresh", deadline_s=3600)
    # age the first request past its payload deadline (mtime is the
    # spool arrival clock)
    old = os.path.join(spool, "old0.req.json")
    past = time.time() - 60
    os.utime(old, (past, past))
    d0 = _counter("zkp2p_service_deadline_total")
    stats = _mk(world).process_dir(spool)
    assert stats["error-deadline-exceeded"] == 1 and stats["done"] == 1
    assert _counter("zkp2p_service_deadline_total") - d0 == 1
    with open(os.path.join(spool, "old0.error.json")) as f:
        assert json.load(f)["state"] == "error-deadline-exceeded"
    assert os.path.exists(os.path.join(spool, "fresh0.proof.json"))


def test_deadline_exceeded_at_batch_assembly(world, tmp_path, monkeypatch):
    """Budget burned between claim and batch assembly (here: a witness
    hang fault) trips deadline gate #2 — no prove compute is spent on a
    request that is already dead."""
    spool = str(tmp_path)
    _write_reqs(spool, [(3, 5)], deadline_s=0.6)
    monkeypatch.setenv("ZKP2P_FAULTS", "witness:hang=1.2")
    faults.reset()
    calls = []

    def counting_prover(dpk_, wits):
        calls.append(len(wits))
        return _prove_batch(dpk_, wits)

    stats = _mk(world, prover_fn=counting_prover).process_dir(spool)
    assert stats["error-deadline-exceeded"] == 1
    assert calls == []  # the prover never ran


def test_service_default_deadline_applies_when_payload_has_none(world, tmp_path):
    spool = str(tmp_path)
    _write_reqs(spool, [(3, 5)])
    req = os.path.join(spool, "r0.req.json")
    past = time.time() - 60
    os.utime(req, (past, past))
    stats = _mk(world, deadline_s=5.0).process_dir(spool)
    assert stats["error-deadline-exceeded"] == 1
    # deadline_s=0 means NO deadline: same aged request proves fine
    spool2 = str(tmp_path / "nodeadline")
    os.makedirs(spool2)
    _write_reqs(spool2, [(3, 5)])
    req2 = os.path.join(spool2, "r0.req.json")
    os.utime(req2, (past, past))
    assert _mk(world, deadline_s=0.0).process_dir(spool2)["done"] == 1


# ----------------------------------------------------- admission control


def test_spool_cap_sheds_newest_visibly(world, tmp_path):
    spool = str(tmp_path)
    _write_reqs(spool, [(3, 5), (2, 7), (4, 4), (9, 2)])
    # make arrival order unambiguous: r0 oldest ... r3 newest
    now = time.time()
    for i in range(4):
        p = os.path.join(spool, f"r{i}.req.json")
        os.utime(p, (now - 40 + 10 * i, now - 40 + 10 * i))
    s0 = _counter("zkp2p_service_shed_total")
    stats = _mk(world, spool_cap=2).process_dir(spool)
    assert stats["done"] == 2 and stats["error-shed"] == 2
    assert _counter("zkp2p_service_shed_total") - s0 == 2
    # the OLDEST two are kept (closest to their deadlines), newest shed
    assert os.path.exists(os.path.join(spool, "r0.proof.json"))
    assert os.path.exists(os.path.join(spool, "r1.proof.json"))
    for i in (2, 3):
        with open(os.path.join(spool, f"r{i}.error.json")) as f:
            err = json.load(f)
        assert err["state"] == "error-shed"
    shed = [r for r in _records(spool) if r["state"] == "error-shed"]
    assert sorted(r["request_id"] for r in shed) == ["r2", "r3"]


# ------------------------------------------------------- emit deferral


def test_injected_enospc_at_emit_defers_and_next_sweep_completes(world, tmp_path, monkeypatch):
    """emit:enospc:once — the proof is valid but cannot land; the
    request stays NON-terminal (no half-terminal artifacts, no TERMINAL
    record) and the next sweep re-proves and completes it.
    At-least-once, exactly one terminal record — plus one `deferred`
    attempt record carrying the sweep's spans, so the prove the failed
    sweep paid for stays on the waterfall (PR 8)."""
    spool = str(tmp_path)
    _write_reqs(spool, [(3, 5)])
    monkeypatch.setenv("ZKP2P_FAULTS", "emit:enospc:once")
    faults.reset()
    svc = _mk(world)
    e0 = _counter("zkp2p_service_emit_failures_total")
    d0 = _counter("zkp2p_service_deferred_total")
    stats = svc.process_dir(spool)
    assert stats["done"] == 0 and not any(stats.values())
    assert _counter("zkp2p_service_emit_failures_total") - e0 == 1
    assert _counter("zkp2p_service_deferred_total") - d0 == 1
    assert not os.path.exists(os.path.join(spool, "r0.proof.json"))
    assert not os.path.exists(os.path.join(spool, "r0.error.json"))
    assert not os.path.exists(os.path.join(spool, "r0.claim"))
    # deferred = NOT terminal, but the attempt IS recorded: state
    # "deferred", a reason, and the spans of the prove it burned
    recs = _records(spool)
    assert [r["state"] for r in recs] == ["deferred"]
    assert recs[0]["deferred_reason"].startswith("transient emit failure")
    assert recs[0]["queue_wait_s"] >= 0
    assert any(s["name"] == "prove" for s in recs[0]["spans"])
    # the fault is spent: the retry sweep lands the proof — exactly one
    # TERMINAL record, the deferred attempt line preserved before it
    stats2 = svc.process_dir(spool)
    assert stats2["done"] == 1
    recs = _records(spool)
    assert [r["state"] for r in recs] == ["deferred", "done"]
    assert all(r["request_id"] == "r0" for r in recs)


def test_transient_witness_failure_defers_not_bad_input(world, tmp_path, monkeypatch):
    """witness:raise:once is an infrastructure failure, not the
    payload's fault — the request must NOT terminal error-bad-input; it
    defers and the next sweep completes it."""
    spool = str(tmp_path)
    _write_reqs(spool, [(3, 5)])
    monkeypatch.setenv("ZKP2P_FAULTS", "witness:raise:once")
    faults.reset()
    svc = _mk(world)
    stats = svc.process_dir(spool)
    assert not any(stats.values())
    assert not os.path.exists(os.path.join(spool, "r0.error.json"))
    assert svc.process_dir(spool)["done"] == 1


# -------------------------------------------------------- claim takeover


def test_stale_claim_takeover_rewrites_owner(world, tmp_path):
    """The satellite fix: takeover must leave the claim file naming the
    CURRENT owner (pid/ts/takeover marker), not the dead worker's
    identity with a refreshed mtime."""
    spool = str(tmp_path)
    _write_reqs(spool, [(3, 5)])
    base = os.path.join(spool, "r0")
    claim = base + ".claim"
    with open(claim, "w") as f:
        json.dump({"pid": 99999999, "ts": 0.0}, f)  # dead peer's claim
    past = time.time() - 3600
    os.utime(claim, (past, past))

    svc = _mk(world, stale_claim_s=10.0)
    assert svc._try_claim(base) is True
    with open(claim) as f:
        owner = json.load(f)
    assert owner["pid"] == os.getpid() and owner.get("takeover") is True
    ProvingService._release_claim(base)


def test_takeover_backs_off_when_owner_completed_mid_race(world, tmp_path, monkeypatch):
    """The 'dead' owner was merely slow: it completes INSIDE the
    stale-check -> steal window (it never re-checks its stolen claim).
    The takeover must fail closed — re-proving finished work would emit
    a duplicate terminal record, the exact violation the chaos
    invariant asserts against — and must sweep the claim away."""
    spool = str(tmp_path)
    _write_reqs(spool, [(3, 5)])
    base = os.path.join(spool, "r0")
    claim = base + ".claim"
    with open(claim, "w") as f:
        json.dump({"pid": 99999999, "ts": 0.0}, f)
    past = time.time() - 3600
    os.utime(claim, (past, past))
    svc = _mk(world, stale_claim_s=10.0)

    real_rename = os.rename

    def racing_rename(src, dst):
        # we win the steal — and the slow owner's terminal write lands
        # right after (its own claim unlink hits OUR re-created claim)
        out = real_rename(src, dst)
        with open(base + ".proof.json", "w") as f:
            f.write("{}")
        return out

    monkeypatch.setattr(os, "rename", racing_rename)
    assert svc._try_claim(base) is False
    assert not os.path.exists(claim)


def test_fresh_claim_backs_off_when_peer_completed_mid_claim(world, tmp_path, monkeypatch):
    """A peer emits + releases between our top-of-function artifact
    check and our O_EXCL create landing on the freed slot: the fresh
    claim must back off like the steal path does, not re-prove finished
    work into a duplicate terminal record."""
    spool = str(tmp_path)
    _write_reqs(spool, [(3, 5)])
    base = os.path.join(spool, "r0")
    svc = _mk(world)

    real_open = os.open

    def racing_open(path, flags, *a, **kw):
        if isinstance(path, str) and path.endswith(".claim"):
            with open(base + ".proof.json", "w") as f:  # peer completes now
                f.write("{}")
        return real_open(path, flags, *a, **kw)

    monkeypatch.setattr(os, "open", racing_open)
    assert svc._try_claim(base) is False
    assert not os.path.exists(base + ".claim")


def test_steal_aside_litter_is_scavenged(world, tmp_path):
    """A taker SIGKILLed between its rename-aside and its unlink leaves
    <name>.claim.stale.<pid> behind; the sweep must scavenge aged ones
    (no other path ever matches the name)."""
    spool = str(tmp_path)
    litter = os.path.join(spool, "r0.claim.stale.12345")
    with open(litter, "w") as f:
        f.write("{}")
    past = time.time() - 3600
    os.utime(litter, (past, past))
    _mk(world, stale_claim_s=10.0).process_dir(spool)
    assert not os.path.exists(litter)


def test_two_takers_race_loser_backs_off(world, tmp_path, monkeypatch):
    """Two survivors racing one stale claim reach the steal at the same
    moment: rename is atomic, the kernel hands the file to exactly one,
    and the other's rename gets ENOENT and backs off.  (The earlier
    replace-in-place scheme let both takers read back their own write
    and both 'win' -> duplicate proves + duplicate terminal records.)"""
    spool = str(tmp_path)
    _write_reqs(spool, [(3, 5)])
    base = os.path.join(spool, "r0")
    claim = base + ".claim"
    with open(claim, "w") as f:
        json.dump({"pid": 99999999, "ts": 0.0}, f)
    past = time.time() - 3600
    os.utime(claim, (past, past))
    a = _mk(world, stale_claim_s=10.0)

    real_rename = os.rename

    def peer_steals_first(src, dst):
        # the peer's atomic steal lands one instant before ours
        real_rename(src, src + ".stolen-by-peer")
        return real_rename(src, dst)  # ours: source gone -> ENOENT

    monkeypatch.setattr(os, "rename", peer_steals_first)
    assert a._try_claim(base) is False  # loser backs off cleanly
    os.unlink(claim + ".stolen-by-peer")


def test_error_terminal_releases_claim_immediately(world, tmp_path):
    """An error-terminal request must not leave a live .claim behind:
    an orphan claim reads as in-flight work (the chaos harness picks
    SIGKILL victims by that signal) and outlives the service when no
    later sweep runs to scavenge it."""
    spool = str(tmp_path)
    with open(os.path.join(spool, "r0.req.json"), "w") as f:
        json.dump({"x": "not-a-number", "y": 5}, f)  # witness_fn int() fails
    stats = _mk(world).process_dir(spool)
    assert stats["error-bad-input"] == 1
    assert os.path.exists(os.path.join(spool, "r0.error.json"))
    assert not os.path.exists(os.path.join(spool, "r0.claim"))


def test_stale_claim_takeover_completes_request_exactly_once(world, tmp_path):
    """Sweep-level takeover: an aged claim with no terminal output (the
    crashed-peer signature) is taken over and the request completes with
    exactly one terminal state."""
    spool = str(tmp_path)
    _write_reqs(spool, [(3, 5)])
    claim = os.path.join(spool, "r0.claim")
    with open(claim, "w") as f:
        json.dump({"pid": 99999999, "ts": 0.0}, f)
    past = time.time() - 3600
    os.utime(claim, (past, past))

    stats = _mk(world, stale_claim_s=10.0).process_dir(spool)
    assert stats["done"] == 1
    assert os.path.exists(os.path.join(spool, "r0.proof.json"))
    assert not os.path.exists(claim)
    recs = _records(spool)
    assert [r["request_id"] for r in recs] == ["r0"] and recs[0]["state"] == "done"


def test_fresh_claim_is_not_taken_over(world, tmp_path):
    """A live peer's claim (age < stale_claim_s) blocks this worker
    entirely: no prove, no artifacts, claim content untouched."""
    spool = str(tmp_path)
    _write_reqs(spool, [(3, 5)])
    claim = os.path.join(spool, "r0.claim")
    peer = {"pid": 424242, "ts": time.time()}
    with open(claim, "w") as f:
        json.dump(peer, f)

    stats = _mk(world, stale_claim_s=300.0).process_dir(spool)
    assert not any(stats.values())
    assert not os.path.exists(os.path.join(spool, "r0.proof.json"))
    assert not os.path.exists(os.path.join(spool, "r0.error.json"))
    with open(claim) as f:
        assert json.load(f) == peer  # untouched
    os.unlink(claim)


def test_terminal_output_wins_over_stale_claim(world, tmp_path):
    """A request with a .proof.json is DONE regardless of any leftover
    claim: never reprocessed, the orphan claim is swept away."""
    spool = str(tmp_path)
    _write_reqs(spool, [(3, 5)])
    assert _mk(world).process_dir(spool)["done"] == 1
    claim = os.path.join(spool, "r0.claim")
    with open(claim, "w") as f:
        json.dump({"pid": 99999999, "ts": 0.0}, f)
    past = time.time() - 3600
    os.utime(claim, (past, past))
    proof_mtime = os.path.getmtime(os.path.join(spool, "r0.proof.json"))
    stats = _mk(world, stale_claim_s=10.0).process_dir(spool)
    assert not any(stats.values())
    assert os.path.getmtime(os.path.join(spool, "r0.proof.json")) == proof_mtime
    assert not os.path.exists(claim)
